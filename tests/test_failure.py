"""Graded failure handling: PMMG_LOWFAILURE + saved conforming mesh.

Reference contract (failed_handling, libparmmg1.c:974-1011): when the
remesh loop cannot complete (here: shard capacity exhausted after the
regrow cap), the library returns PMMG_LOWFAILURE and the caller can still
retrieve and save a CONFORMING mesh."""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.api.parmesh import ParMesh
from parmmg_tpu.core import constants as C
from parmmg_tpu.utils.fixtures import cube_mesh
import pytest

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def _staged_pm(n_devices):
    vert, tet = cube_mesh(3)
    pm = ParMesh()
    pm.set_mesh_size(len(vert), len(tet))
    pm.set_vertices(vert, np.zeros(len(vert), np.int32))
    pm.set_tetrahedra(tet + 1, np.ones(len(tet), np.int32))
    pm.info.hsiz = 0.12
    pm.info.niter = 1
    pm.info.imprim = -1
    pm.info.n_devices = n_devices
    return pm


def test_shard_overflow_degrades_to_lowfailure(monkeypatch):
    from parmmg_tpu.parallel import dist, distribute

    # force the first overflow to be terminal: with the regrow cap at -1
    # the run cannot regrow, and a 1.02x capacity multiplier guarantees
    # the refinement overflows the shards immediately
    monkeypatch.setattr(dist, "MAX_SHARD_REGROWS", -1)
    orig = distribute.split_to_shards

    def tight_split(mesh, met, part, nparts, cap_mult=3.0, **kw):
        return orig(mesh, met, part, nparts, cap_mult=1.02, **kw)

    monkeypatch.setattr(distribute, "split_to_shards", tight_split)

    pm = _staged_pm(n_devices=2)
    ret = pm.run()
    assert ret == C.PMMG_LOWFAILURE

    # the staged output is a valid conforming mesh: positive volumes
    # summing to the cube, retrievable through the normal getters
    npts, ntet = pm.get_mesh_size()[:2]
    assert ntet > 0
    from parmmg_tpu.core.mesh import tet_volumes
    from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
    m = build_adjacency(pm._out)
    assert check_adjacency(m) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-5)

    # and it round-trips through Medit output (the "saveable" half)
    import tempfile, os
    from parmmg_tpu.io.medit import MeditMesh, write_mesh, read_mesh
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "out.mesh")
        mm = MeditMesh()
        mm.vert, mm.vref = pm.get_vertices()
        mm.tetra, mm.tref = pm.get_tetrahedra()
        mm.tetra = np.asarray(mm.tetra) - 1
        write_mesh(path, mm)
        back = read_mesh(path)
        assert len(back.tetra) == ntet


def test_success_path_still_returns_success():
    pm = _staged_pm(n_devices=1)
    assert pm.run() == C.PMMG_SUCCESS


def test_shard_regrow_in_place(monkeypatch):
    """Under-provisioned shards regrow IN PLACE (no merge->resplit) and
    the run still completes: the zaldy realloc analogue."""
    from parmmg_tpu.parallel import distribute
    orig = distribute.split_to_shards

    def tight_split(mesh, met, part, nparts, cap_mult=3.0, **kw):
        return orig(mesh, met, part, nparts, cap_mult=1.05, **kw)

    monkeypatch.setattr(distribute, "split_to_shards", tight_split)
    pm = _staged_pm(n_devices=2)
    assert pm.run() == C.PMMG_SUCCESS
    from parmmg_tpu.core.mesh import tet_volumes
    from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
    m = build_adjacency(pm._out)
    assert check_adjacency(m) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-5)
