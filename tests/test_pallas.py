"""Parity of the fused Pallas kernels vs the jnp reference formulas.

Runs the kernels with ``interpret=True`` on the CPU test backend; on real
TPU the production dispatch (ops/quality.py / ops/edges.py ``use_pallas``)
routes through the compiled versions of exactly these kernels.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.ops import pallas_kernels as pk
from parmmg_tpu.ops.quality import (
    edge_length_iso, edge_length_ani, quality_from_points)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(7)


def test_edge_length_iso_parity(rng):
    n = 301                      # deliberately not a multiple of 128
    p0 = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    p1 = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    h0 = jnp.asarray(rng.uniform(0.05, 2.0, size=n), jnp.float32)
    h1 = jnp.asarray(rng.uniform(0.05, 2.0, size=n), jnp.float32)
    ref = edge_length_iso(p0, p1, h0, h1)
    got = pk.edge_length_iso_pallas(p0, p1, h0, h1, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def test_edge_length_iso_equal_sizes(rng):
    # the h0 == h1 guard branch
    n = 64
    p0 = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    p1 = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    h = jnp.full(n, 0.25, jnp.float32)
    ref = edge_length_iso(p0, p1, h, h)
    got = pk.edge_length_iso_pallas(p0, p1, h, h, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-6)


def _random_spd6(rng, n):
    """Random SPD metrics packed (m11,m12,m13,m22,m23,m33)."""
    a = rng.normal(size=(n, 3, 3))
    m = np.einsum("nij,nkj->nik", a, a) + 0.5 * np.eye(3)
    return np.stack([m[:, 0, 0], m[:, 0, 1], m[:, 0, 2],
                     m[:, 1, 1], m[:, 1, 2], m[:, 2, 2]], axis=1)


def test_edge_length_ani_parity(rng):
    n = 200
    p0 = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    p1 = jnp.asarray(rng.normal(size=(n, 3)), jnp.float32)
    m0 = jnp.asarray(_random_spd6(rng, n), jnp.float32)
    m1 = jnp.asarray(_random_spd6(rng, n), jnp.float32)
    ref = edge_length_ani(p0, p1, m0, m1)
    got = pk.edge_length_ani_pallas(p0, p1, m0, m1, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=3e-5, atol=3e-6)


def test_quality_iso_parity(rng):
    n = 173
    p = jnp.asarray(rng.normal(size=(n, 4, 3)), jnp.float32)
    ref = quality_from_points(p)
    got = pk.quality_pallas(p, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=3e-5, atol=3e-6)


def test_quality_ani_parity(rng):
    n = 96
    p = jnp.asarray(rng.normal(size=(n, 4, 3)), jnp.float32)
    m6 = jnp.asarray(_random_spd6(rng, 4 * n).reshape(n, 4, 6), jnp.float32)
    ref = quality_from_points(p, m6)
    got = pk.quality_pallas(p, jnp.mean(m6, axis=1), interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
