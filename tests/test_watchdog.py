"""Hang-proofing tier-1 (host-only): deadline watchdogs, heartbeat
leases, the crash-loop breaker, and the ``hang=S`` fault action.

Everything here is stdlib-speed — no XLA programs, no subprocesses.
The end-to-end hang drills (a wedged grouped chunk retried to parity,
a wedged polish worker killed by the subprocess timeout, a wedged pod
worker killed by the heartbeat lease and resumed bit-identically) live
in ``run_tests.sh --chaos`` / ``--multihost``; tier-1 pins the
mechanism contracts those drills compose.
"""
import importlib.util
import os
import time

import pytest

from parmmg_tpu.resilience import checkpoint as ckpt
from parmmg_tpu.resilience import faults
from parmmg_tpu.resilience import watchdog as wd
from parmmg_tpu.resilience.watchdog import (Deadline, WatchdogTimeout,
                                            beat, deadline_knob,
                                            run_with_deadline,
                                            stale_ranks)


def _counter(name):
    from parmmg_tpu.obs.metrics import REGISTRY
    return REGISTRY.counter(name).value


def _site(prefix):
    """Unique watchdog site per call: first-use grace state
    (``_FIRST_DONE``) is process-global, so tests must never share a
    site name across runs in one process."""
    return f"{prefix}.{os.urandom(4).hex()}"


# ---------------------------------------------------------------------------
# polled deadlines
# ---------------------------------------------------------------------------
def test_deadline_polled_expiry():
    before = _counter("resilience.watchdog_timeouts")
    with Deadline(0.05, site="t.polled") as dl:
        assert not dl.expired
        dl.check()                      # armed but not expired: no-op
        assert dl.remaining() is not None
        time.sleep(0.08)
        assert dl.expired
        with pytest.raises(WatchdogTimeout) as ei:
            dl.check()
        assert ei.value.site == "t.polled"
        assert ei.value.seconds == pytest.approx(0.05)
    assert isinstance(ei.value, RuntimeError)   # the ladder-shape pin
    assert _counter("resilience.watchdog_timeouts") == before + 1


def test_deadline_disarmed_level_never_expires():
    with Deadline(0, site="t.off") as dl:
        assert dl.remaining() is None
        time.sleep(0.02)
        assert not dl.expired
        dl.check()                      # disarmed: never raises


def test_deadline_nested_outer_budget_wins():
    """A tight inner deadline can never mask an exhausted outer one:
    check() reports the earliest-armed expired level."""
    with Deadline(0.05, site="t.outer"):
        with Deadline(60, site="t.inner") as inner:
            time.sleep(0.08)
            with pytest.raises(WatchdogTimeout) as ei:
                inner.check()
            assert ei.value.site == "t.outer"
    # both levels popped: a fresh check is clean
    Deadline(0, site="t.clean").check()


def test_deadline_knob_parsing(monkeypatch):
    monkeypatch.delenv("PARMMG_DEADLINE_DISPATCH_S", raising=False)
    assert deadline_knob("PARMMG_DEADLINE_DISPATCH_S") == 0.0
    monkeypatch.setenv("PARMMG_DEADLINE_DISPATCH_S", "2.5")
    assert deadline_knob("PARMMG_DEADLINE_DISPATCH_S") == 2.5
    monkeypatch.setenv("PARMMG_DEADLINE_DISPATCH_S", "junk")
    assert deadline_knob("PARMMG_DEADLINE_DISPATCH_S") == 0.0
    monkeypatch.setenv("PARMMG_DEADLINE_DISPATCH_S", "-3")
    assert deadline_knob("PARMMG_DEADLINE_DISPATCH_S") == 0.0


# ---------------------------------------------------------------------------
# monitor-thread deadlines
# ---------------------------------------------------------------------------
def test_run_with_deadline_inline_when_off():
    assert run_with_deadline(lambda: 41 + 1, 0, _site("t.inline")) == 42


def test_run_with_deadline_value_and_exception_passthrough(monkeypatch):
    monkeypatch.setenv("PARMMG_DEADLINE_GRACE_S", "0")
    assert run_with_deadline(lambda: {"v": 7}, 5,
                             _site("t.value")) == {"v": 7}

    def boom():
        raise KeyError("relayed")

    with pytest.raises(KeyError, match="relayed"):
        run_with_deadline(boom, 5, _site("t.exc"))


def test_run_with_deadline_timeout(monkeypatch):
    monkeypatch.setenv("PARMMG_DEADLINE_GRACE_S", "0")
    before = _counter("resilience.watchdog_timeouts")
    site = _site("t.hang")
    with pytest.raises(WatchdogTimeout) as ei:
        run_with_deadline(lambda: time.sleep(0.5), 0.05, site)
    assert ei.value.site == site
    # the abandoned worker rides on the exception for callers that
    # serialize on shared state (the serve daemon waits it out)
    assert ei.value.thread is not None and ei.value.thread.daemon
    assert _counter("resilience.watchdog_timeouts") == before + 1
    ei.value.thread.join(timeout=2)


def test_first_use_grace_covers_cold_call_only(monkeypatch):
    """The first guarded call at a site gets the compile grace on top
    of its deadline; completing it consumes the grace, so the second
    identically-slow call times out."""
    monkeypatch.setenv("PARMMG_DEADLINE_GRACE_S", "0.4")
    site = _site("t.grace")
    assert wd.first_use_grace(site) == pytest.approx(0.4)
    slow = lambda: (time.sleep(0.15), "done")[1]  # noqa: E731
    assert run_with_deadline(slow, 0.05, site) == "done"
    assert wd.first_use_grace(site) == 0.0
    with pytest.raises(WatchdogTimeout):
        run_with_deadline(slow, 0.05, site)


# ---------------------------------------------------------------------------
# heartbeat leases
# ---------------------------------------------------------------------------
def test_beat_noop_without_supervisor_dir(monkeypatch):
    monkeypatch.delenv("PARMMG_MH_HEARTBEAT_DIR", raising=False)
    assert beat() is None


def test_beat_and_stale_ranks(tmp_path, monkeypatch):
    d = str(tmp_path / "hb")
    monkeypatch.setenv("PARMMG_MH_HEARTBEAT_DIR", d)
    monkeypatch.setenv("PARMMG_HEARTBEAT_S", "0.05")
    monkeypatch.setattr(wd, "_HB", {"last": 0.0})
    before = _counter("resilience.heartbeats")
    p = beat(rank=3)
    assert p is not None and p.endswith("hb.3") and os.path.exists(p)
    assert _counter("resilience.heartbeats") == before + 1
    assert beat(rank=3) is None         # throttled inside the interval

    now = time.time()
    # fresh lease: not stale
    assert stale_ranks(d, 5.0, [3], now=now) == []
    # rank 1 never beat: a missing heartbeat file is NEVER stale
    # (startup + cold compile are covered by the phase timeout)
    assert stale_ranks(d, 5.0, [1, 3], now=now) == []
    # backdate rank 3 past the lease: revoked
    os.utime(p, (now - 10, now - 10))
    assert stale_ranks(d, 5.0, [1, 3], now=now) == [3]
    # lease <= 0 disables the whole mechanism
    assert stale_ranks(d, 0.0, [3], now=now) == []


# ---------------------------------------------------------------------------
# crash-loop breaker
# ---------------------------------------------------------------------------
def test_crash_loop_breaker_threshold(tmp_path, monkeypatch):
    monkeypatch.setenv("PARMMG_CKPT_DIR", str(tmp_path))
    monkeypatch.setenv("PARMMG_RESUME_MAX", "2")
    before = _counter("resilience.crash_loops")
    assert ckpt.crash_loop("t15", "fp", 1) == (1, False)
    assert ckpt.crash_loop("t15", "fp", 1) == (2, False)
    n, esc = ckpt.crash_loop("t15", "fp", 1)
    assert (n, esc) == (3, True)        # the attempt AFTER resume_max
    assert _counter("resilience.crash_loops") == before + 1
    # counts are per-(fingerprint, pass): the next pass starts fresh
    assert ckpt.crash_loop("t15", "fp", 2) == (1, False)
    assert ckpt.crash_loop("t15", "other", 1) == (1, False)
    # write=False computes the decision without persisting the bump
    # (non-zero pod ranks): the stored count stays at 3
    assert ckpt.crash_loop("t15", "fp", 1, write=False) == (4, True)
    assert ckpt.crash_loop("t15", "fp", 1, write=False) == (4, True)


def test_crash_loop_without_ckpt_dir_never_escalates(monkeypatch):
    monkeypatch.delenv("PARMMG_CKPT_DIR", raising=False)
    for _ in range(3):
        assert ckpt.crash_loop("t15", "fp", 1) == (1, False)


# ---------------------------------------------------------------------------
# hang=S fault action
# ---------------------------------------------------------------------------
@pytest.fixture
def arm(monkeypatch):
    def _arm(spec):
        monkeypatch.setenv("PARMMG_FAULT", spec)
        faults.FAULTS.reset()
    yield _arm
    faults.FAULTS.reset()


def test_hang_grammar():
    rules = faults.parse_fault_spec("polish.worker:hang=2.5;nth-2")
    r = rules["polish.worker"]
    assert r.hang == 2.5 and r.nth == 2
    with pytest.raises(ValueError, match="hang must be > 0"):
        faults.parse_fault_spec("dispatch.chunk:hang=0")
    with pytest.raises(ValueError, match="unparseable fault trigger"):
        faults.parse_fault_spec("dispatch.chunk:frob=1")


def test_faultpoint_hang_sleeps_and_returns(arm):
    arm("dispatch.chunk:hang=0.1")
    before = _counter("resilience.faults_injected")
    t0 = time.monotonic()
    faults.faultpoint("dispatch.chunk")     # the wedge: NO raise
    assert time.monotonic() - t0 >= 0.09
    assert _counter("resilience.faults_injected") == before + 1


def test_fault_trigger_hang_never_flips_condition(arm):
    arm("analysis.ks_overflow:hang=0.05")
    t0 = time.monotonic()
    assert faults.fault_trigger("analysis.ks_overflow") is False
    assert time.monotonic() - t0 >= 0.04


def test_subprocess_fault_env_propagates_hang(arm):
    arm("polish.worker:hang=1")
    assert faults.subprocess_fault_env("polish.worker") == {
        faults.FORCE_ENV: "polish.worker:hang=1"}
    arm("polish.worker")
    assert faults.subprocess_fault_env("polish.worker") == {
        faults.FORCE_ENV: "polish.worker"}


# ---------------------------------------------------------------------------
# soak schedule determinism (stdlib import — no campaign execution)
# ---------------------------------------------------------------------------
def _load_soak():
    path = os.path.join(os.path.dirname(__file__), "..", "scripts",
                        "chaos_soak.py")
    spec = importlib.util.spec_from_file_location("chaos_soak_t1", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_soak_schedule_is_pure_function_of_seed():
    soak = _load_soak()
    a = soak.build_schedule(11, 3)
    assert a == soak.build_schedule(11, 3)
    assert a != soak.build_schedule(12, 3)
    assert len(a) == 3 and [s["run"] for s in a] == [0, 1, 2]
    for s in a:
        assert s["site"] in faults.SITES
        assert s["fault"].split(":")[0] in faults.SITES
        assert s["expect"] in ("parity", "nopolish", "lowfailure",
                               "quarantine")
    # the menu spans the FULL registry — no site escapes the soak
    assert set(soak.sites_in_menu()) == set(faults.SITES)


# ---------------------------------------------------------------------------
# serve daemon wedge bit (host-only stub driver)
# ---------------------------------------------------------------------------
class _WedgePool:
    steps = 0
    quarantined = ()

    def active_tenants(self):
        return []


class _WedgedDriver:
    """service_once sleeps past the step deadline every call — the
    wedged-loop shape, no jax."""

    def __init__(self, sleep_s):
        self.pool = _WedgePool()
        self.queue = []
        self.requests = {}
        self.sleep_s = sleep_s

    def service_once(self):
        time.sleep(self.sleep_s)
        return False


def test_daemon_wedge_flips_healthz(monkeypatch):
    from parmmg_tpu.serve.client import ServeClient
    from parmmg_tpu.serve.daemon import PoolDaemon
    monkeypatch.setenv("PARMMG_DEADLINE_SERVE_S", "0.05")
    monkeypatch.setenv("PARMMG_DEADLINE_GRACE_S", "0")
    before = _counter("serve.step_timeouts")
    d = PoolDaemon(driver=_WedgedDriver(0.6), port=0,
                   idle_sleep_s=0.01).start()
    try:
        cl = ServeClient(port=d.port, timeout_s=10)
        h = None
        for _ in range(150):
            h = cl.health()             # lock-free even while wedged
            if h["wedged"]:
                break
            time.sleep(0.02)
        assert h is not None and h["wedged"] is True
        assert h["ok"] is False and h["loop_alive"] is True
        assert _counter("serve.step_timeouts") >= before + 1
    finally:
        d.shutdown()
    assert not d.alive()
