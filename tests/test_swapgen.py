"""Generalized (degree 4-6) edge swaps — ops/swapgen.py.

Reference contract: Mmg's swap pass re-triangulates the shell ring of a
bad interior edge (degree up to 7) when the worst new quality beats the
old by the swap gain; the remesher the reference invokes per group
(libparmmg1.c:737-739) relies on these to lift the min quality past
what 3-2/2-3 swaps alone reach.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.ops.swapgen import swapgen_wave
from parmmg_tpu.utils.fixtures import _orient_positive


def _spindle(n, z=2.0, radius=1.0):
    """n tets around interior edge (a,b): tall poles, tight ring — the
    classic bad-shell configuration a ring re-triangulation fixes (the
    fan's worst tet beats the shell's by >2x at z=2)."""
    a = [0.0, 0.0, z]
    b = [0.0, 0.0, -z]
    ring = [[radius * np.cos(2 * np.pi * i / n),
             radius * np.sin(2 * np.pi * i / n), 0.0] for i in range(n)]
    vert = np.array([a, b] + ring)
    tet = np.array([[0, 1, 2 + i, 2 + (i + 1) % n] for i in range(n)],
                   np.int32)
    tet = _orient_positive(vert, tet)
    m = make_mesh(vert, tet, capP=64, capT=64)
    m = build_adjacency(m)
    return m


def _run_one(n):
    m = _spindle(n)
    met = jnp.full(m.capP, 2.0)
    q0 = np.asarray(tet_quality(m, met))[np.asarray(m.tmask)]
    vol0 = np.asarray(tet_volumes(m))[np.asarray(m.tmask)].sum()
    res = swapgen_wave(m, met)
    assert int(res.nswap) == 1, f"degree-{n} swap did not trigger"
    m2 = build_adjacency(res.mesh)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
    tm2 = np.asarray(m2.tmask)
    assert tm2.sum() == 2 * (n - 2)
    vols = np.asarray(tet_volumes(m2))[tm2]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), vol0, rtol=1e-5)
    q1 = np.asarray(tet_quality(m2, met))[tm2]
    assert q1.min() > q0.min()
    return m2


def test_swap44():
    _run_one(4)


def test_swap56():
    _run_one(5)


def test_swap68():
    _run_one(6)


def test_degree3_not_touched():
    # degree-3 shells belong to the 3-2 kernel; swapgen must skip them
    m = _spindle(3)
    met = jnp.full(m.capP, 2.0)
    res = swapgen_wave(m, met)
    assert int(res.nswap) == 0


@pytest.mark.slow
def test_jitted_entry_matches_eager():
    """The governed module-level jit (ops.swapgen_wave — the cached
    entry for eager tails, compile-governor satellite) must agree with
    the traced-inline wave and land in the ledger.  slow: the one-shot
    whole-wave compile takes ~a minute on the tier-1 CPU box."""
    from parmmg_tpu.ops.swapgen import swapgen_wave_j
    from parmmg_tpu.utils.compilecache import ledger_snapshot

    m = _spindle(4)
    met = jnp.full(m.capP, 2.0)
    eager = swapgen_wave(m, met)
    jitted = swapgen_wave_j(m, met)
    assert int(jitted.nswap) == int(eager.nswap) == 1
    assert np.array_equal(np.asarray(jitted.mesh.tet),
                          np.asarray(eager.mesh.tet))
    assert np.array_equal(np.asarray(jitted.mesh.tmask),
                          np.asarray(eager.mesh.tmask))
    rec = ledger_snapshot()["ops.swapgen_wave"]
    assert rec["calls"] >= 1


def test_boundary_edge_not_touched():
    # tag the shell's vanishing faces boundary-like: a ring swap that
    # would destroy tagged faces must not trigger
    import dataclasses
    from parmmg_tpu.core import constants as C
    m = _spindle(5)
    ftag = jnp.asarray(np.asarray(m.ftag) | np.uint32(C.MG_BDY))
    m = dataclasses.replace(m, ftag=ftag)
    met = jnp.full(m.capP, 2.0)
    res = swapgen_wave(m, met)
    assert int(res.nswap) == 0


def test_cube_integration_volume_preserved():
    """On a real mesh: apply one swapgen wave after a sizing cycle and
    check conformity + volume conservation + min-quality monotonicity
    at the shell level (global min can only improve or stay)."""
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.ops.adapt import adapt_cycle
    from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric
    vert, tet = cube_mesh(3)
    m = make_mesh(vert, tet, capP=6 * len(vert), capT=6 * len(tet))
    m = analyze_mesh(m).mesh
    h = analytic_iso_metric(vert, "shock", h=0.3)
    met = jnp.zeros(m.capP, m.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, m.vert.dtype)).at[len(h):].set(1.0)
    m, met, _ = adapt_cycle(m, met, jnp.asarray(0, jnp.int32),
                            do_swap=False)
    met = jnp.asarray(met)
    q0 = np.asarray(tet_quality(m, met))[np.asarray(m.tmask)].min()
    res = swapgen_wave(m, met)
    m2 = build_adjacency(res.mesh)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m2))[np.asarray(m2.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    if int(res.nswap):
        q1 = np.asarray(tet_quality(m2, met))[np.asarray(m2.tmask)].min()
        assert q1 >= q0 - 1e-7
