"""Incremental topology engine tests (PARMMG_INCR_TOPO, ops/topo_incr).

Tier-1 (fast, host-only) coverage: the dirty-band width ladder, the
tombstone-merge against a fresh stable sort (the module's exactness
proof, fuzzed with dead tets and tombstones), the overflow fallback
(PARMMG_INCR_BAND forced below the dirty count), the nd==0 wholesale
reuse, and the Pallas prefix-sum kernel in interpret mode.  The slow
marks re-run the bit-parity claim through the full grouped pass —
polish included — knob on vs off, plus a forced-Pallas arm.
"""
import os
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import MESH_FIELDS, make_mesh
from parmmg_tpu.ops.topo_incr import (_INT32_MAX, incr_band_width,
                                      incr_build_adjacency,
                                      incr_topo_enabled,
                                      incr_unique_edges,
                                      merge_sorted_band, topo_init)
from parmmg_tpu.utils.fixtures import cube_mesh


def _cube(n=2, capmul=4):
    from parmmg_tpu.ops.analysis import analyze_mesh
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert),
                  capT=capmul * len(tet))
    return analyze_mesh(m).mesh


def _assert_mesh_equal(a, b, label=""):
    for f in MESH_FIELDS:
        av, bv = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert (av == bv).all(), f"{label}: mesh field {f} differs"


# ---- band width ladder ------------------------------------------------------

def test_incr_band_width_ladder(monkeypatch):
    from parmmg_tpu.utils.compilecache import bucket
    monkeypatch.delenv("PARMMG_INCR_BAND", raising=False)
    # the band width IS a rung of the shared geo bucket ladder — band
    # sizing can never mint a new shape family
    for capT in (64, 1024, 9216, 98304, 1 << 20):
        B = incr_band_width(capT)
        assert B == bucket(max(1, capT // 16), floor=1024, scheme="geo",
                           cap=capT)
        assert 1 <= B <= capT
    # tiny meshes: the ladder reaches capT (band == full width)
    assert incr_band_width(64) == 64
    # big meshes: strict compaction
    assert incr_band_width(1 << 20) < (1 << 20)
    # monotone in capT (no oscillating families across regrows)
    widths = [incr_band_width(c) for c in range(64, 40000, 64)]
    assert all(a <= b for a, b in zip(widths, widths[1:]))
    # the override clamps into [1, capT]
    monkeypatch.setenv("PARMMG_INCR_BAND", "7")
    assert incr_band_width(9216) == 7
    monkeypatch.setenv("PARMMG_INCR_BAND", "999999")
    assert incr_band_width(64) == 64


def test_incr_knob_defaults_off(monkeypatch):
    monkeypatch.delenv("PARMMG_INCR_TOPO", raising=False)
    assert incr_topo_enabled() is False, \
        "PARMMG_INCR_TOPO must default off (exact legacy path)"
    monkeypatch.setenv("PARMMG_INCR_TOPO", "1")
    assert incr_topo_enabled() is True
    monkeypatch.setenv("PARMMG_INCR_TOPO", "0")
    assert incr_topo_enabled() is False


# ---- tombstone merge vs fresh stable sort -----------------------------------

def _merge_case(rng, ncols, n, slots_per_tet=3):
    """One fuzz case: retained stable sort of old keys, a dirty set
    re-keyed (tombstones: dirty DEAD slots key to INT32_MAX but keep
    their real slot id), band padded with (MAX, MAX) rows."""
    ntet = n // slots_per_tet
    kmax = 50
    old = rng.integers(0, kmax, size=(n, ncols)).astype(np.int32)
    old[rng.random(n) < 0.15] = _INT32_MAX          # dead slots
    # stable sort by (key..., slot): slot ascending IS the stable tie
    order = np.lexsort(tuple(old[:, j] for j in range(ncols))[::-1]) \
        if ncols > 1 else np.argsort(old[:, 0], kind="stable")
    dirty_tets = rng.random(ntet) < 0.4
    dirty_slot = np.repeat(dirty_tets, slots_per_tet)
    new = old.copy()
    fresh = rng.integers(0, kmax, size=(n, ncols)).astype(np.int32)
    fresh[rng.random(n) < 0.3] = _INT32_MAX         # tombstones
    new[dirty_slot] = fresh[dirty_slot]
    # band: every slot of every dirty tet, padded to B
    didx = np.flatnonzero(dirty_slot).astype(np.int32)
    B = len(didx) + int(rng.integers(0, 5))
    bslot = np.full(B, _INT32_MAX, np.int32)
    bslot[: len(didx)] = didx
    bkeys = np.full((B, ncols), _INT32_MAX, np.int32)
    bkeys[: len(didx)] = new[didx]
    return old, new, order, dirty_slot, bkeys, bslot


@pytest.mark.parametrize("ncols", [1, 2])
def test_merge_sorted_band_bit_equals_stable_sort(ncols):
    rng = np.random.default_rng(1234 + ncols)
    merge = jax.jit(merge_sorted_band)
    for trial in range(25):
        n = int(rng.integers(6, 120)) // 3 * 3 or 3
        old, new, order, dmask, bkeys, bslot = _merge_case(rng, ncols, n)
        ks = [jnp.asarray(old[order, j]) for j in range(ncols)]
        sd = jnp.asarray(dmask[order])
        mk, ms = merge(ks, jnp.asarray(order.astype(np.int32)), sd,
                       [jnp.asarray(bkeys[:, j]) for j in range(ncols)],
                       jnp.asarray(bslot))
        # reference: fresh stable sort of the NEW keys
        ref = np.lexsort(tuple(new[:, j] for j in range(ncols))[::-1]) \
            if ncols > 1 else np.argsort(new[:, 0], kind="stable")
        assert (np.asarray(ms) == ref).all(), \
            f"trial {trial}: merged permutation != fresh stable sort"
        for j in range(ncols):
            assert (np.asarray(mk[j]) == new[ref, j]).all(), \
                f"trial {trial}: merged key col {j} differs"


# ---- overflow fallback + nd==0 reuse on a real mesh -------------------------

def test_incr_overflow_falls_back_exact(monkeypatch):
    """More dirty tets than the band: the lax.cond fallback must yield
    the same table a full rebuild does (exactness by construction)."""
    from parmmg_tpu.ops.edges import unique_edges
    monkeypatch.setenv("PARMMG_INCR_BAND", "2")     # force overflow
    m = _cube(2)
    on = jnp.ones((), bool)

    def derive(mesh, topo):
        et, topo = incr_unique_edges(mesh, topo, on, shell_slots=0)
        return et, topo
    jderive = jax.jit(derive)
    et0, topo = jderive(m, topo_init(m.capT))
    # dirty MANY tets (all live ones) without changing the mesh: the
    # band (width 2) overflows, the full rebuild re-derives the table
    topo_d = topo._replace(
        edirty=jnp.asarray(np.asarray(m.tmask)),
        fdirty=jnp.asarray(np.asarray(m.tmask)))
    et1, topo1 = jderive(m, topo_d)
    ref = jax.jit(partial(unique_edges, shell_slots=0))(m)
    for a, b, c in zip(jax.tree.leaves(et1), jax.tree.leaves(ref),
                       jax.tree.leaves(et0)):
        assert (np.asarray(a) == np.asarray(b)).all()
        assert (np.asarray(a) == np.asarray(c)).all()
    # the fallback refreshed the retained state: dirty cleared, ok set
    assert bool(topo1.eok) and int(np.asarray(topo1.edirty).sum()) == 0


def test_incr_nd0_reuses_retained_table():
    """A clean state (no dirty tets) must reproduce the table from the
    retained sort wholesale — and adjacency from the retained face
    sort — bit-identical to the legacy derivations."""
    from parmmg_tpu.ops.adjacency import build_adjacency
    from parmmg_tpu.ops.edges import unique_edges
    m = _cube(2)
    on = jnp.ones((), bool)
    jedge = jax.jit(lambda mm, t: incr_unique_edges(mm, t, on,
                                                    shell_slots=0))
    jadj = jax.jit(lambda mm, t: incr_build_adjacency(mm, t, on))
    et0, topo = jedge(m, topo_init(m.capT))
    m1, topo = jadj(m, topo)
    # second derivation, nothing dirty: the nd==0 reuse arm
    et1, _ = jedge(m, topo)
    m2, _ = jadj(m, topo)
    ref_et = jax.jit(partial(unique_edges, shell_slots=0))(m)
    ref_m = jax.jit(build_adjacency)(m)
    for a, b in zip(jax.tree.leaves(et1), jax.tree.leaves(ref_et)):
        assert (np.asarray(a) == np.asarray(b)).all()
    _assert_mesh_equal(m1, ref_m, "incr adjacency (first derivation)")
    _assert_mesh_equal(m2, ref_m, "incr adjacency (nd==0 reuse)")


# ---- Pallas prefix kernel ---------------------------------------------------

def test_merge_prefix_pallas_interpret_parity():
    from parmmg_tpu.ops.pallas_kernels import merge_prefix_pallas
    rng = np.random.default_rng(77)
    for n in (1, 127, 128, 1024, 1025, 6144):
        x = jnp.asarray(rng.integers(0, 3, n).astype(np.int32))
        got = merge_prefix_pallas(x, interpret=True)
        ref = jnp.cumsum(x)
        assert got.dtype == jnp.int32
        assert (np.asarray(got) == np.asarray(ref)).all(), n


# ---- slow: full grouped bit-parity, knob on vs off --------------------------

@pytest.mark.slow
def test_grouped_incr_knob_parity(monkeypatch):
    """PARMMG_INCR_TOPO on/off through the full grouped pass — waves,
    fused blocks, regrows AND the sliver polish phase — is bit-for-bit
    identical, with identical op counters."""
    from parmmg_tpu.ops.adapt import AdaptStats
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.parallel.groups import grouped_adapt
    vert, tet = cube_mesh(2)
    outs = []
    for env in ("0", "1"):
        monkeypatch.setenv("PARMMG_INCR_TOPO", env)
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.full(m.capP, 0.35, m.vert.dtype)
        st = AdaptStats()
        mo, ko = grouped_adapt(m, met, 16, niter=2, cycles=3, stats=st)
        outs.append((mo, ko, st))
    (m0, k0, s0), (m1, k1, s1) = outs
    _assert_mesh_equal(m0, m1, "incr grouped")
    assert (np.asarray(k0) == np.asarray(k1)).all()
    assert (s0.nsplit, s0.ncollapse, s0.nswap, s0.nmoved) == \
        (s1.nsplit, s1.ncollapse, s1.nswap, s1.nmoved)
    assert s0.cycles == s1.cycles
    # the knob-on run recorded its dirty-band trajectory
    assert "incr_dirty_per_cycle" in s1.sched_extra
    assert len(s1.sched_extra["incr_dirty_per_cycle"]) > 0


@pytest.mark.slow
def test_incr_forced_pallas_parity(monkeypatch):
    """PARMMG_TPU_PALLAS=1 (interpret-mode merge_prefix inside the
    band merge) leaves the incremental derivations bit-identical."""
    from parmmg_tpu.ops.adapt import adapt_cycle_impl
    m = _cube(2)
    met = jnp.full(m.capP, 0.5, m.vert.dtype)
    on = jnp.ones((), bool)
    outs = []
    for env in (None, "1"):
        if env is None:
            monkeypatch.delenv("PARMMG_TPU_PALLAS", raising=False)
        else:
            monkeypatch.setenv("PARMMG_TPU_PALLAS", env)
        # fresh trace per arm: the dispatch reads the env at trace time
        step = jax.jit(lambda mm, kk, ww, tt: adapt_cycle_impl(
            mm, kk, ww, topo=tt, incr=on))
        mm, kk, tt = m, met, topo_init(m.capT)
        for cyc in range(3):
            mm, kk, cnt, tt = step(mm, kk, jnp.asarray(cyc, jnp.int32),
                                   tt)
        outs.append((mm, kk, cnt))
    (ma, ka, ca), (mb, kb, cb) = outs
    _assert_mesh_equal(ma, mb, "incr forced-pallas")
    assert (np.asarray(ka) == np.asarray(kb)).all()
    assert (np.asarray(ca) == np.asarray(cb)).all()
