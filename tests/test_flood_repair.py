"""Flood-label contiguity/reachability repair (migrate_dev.py).

The reference repairs the displaced partition before migrating: BFS
sub-blob merge (/root/reference/src/moveinterfaces_pmmg.c:475-626) and
destination reachability (:627-720).  These tests manufacture the two
pathologies directly on flood label arrays and assert the band-scoped
repair fixes them without touching healthy labels.
"""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh, mesh_to_host
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.parallel.distribute import split_to_shards
from parmmg_tpu.parallel.migrate import rebuild_shards
from parmmg_tpu.parallel.migrate_dev import repair_flood_labels
from parmmg_tpu.utils.fixtures import cube_mesh


def _two_shards(n=4):
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.3, m.vert.dtype)
    vert_h, tet_h, _, _, _ = mesh_to_host(m)
    cent = vert_h[tet_h].mean(axis=1)
    part = (cent[:, 0] > 0.5).astype(np.int32)
    s, ms = split_to_shards(m, met, part, 2)
    s = rebuild_shards(s)
    return s


def _interface_adjacent(s, shard):
    """Bool [capT]: tets of `shard` with a vertex on the frozen
    interface (MG_PARBDY vertex)."""
    from parmmg_tpu.core.constants import MG_PARBDY
    vtag = np.asarray(s.vtag[shard])
    tet = np.asarray(s.tet[shard])
    tm = np.asarray(s.tmask[shard])
    on_ifc = (vtag & MG_PARBDY) != 0
    return tm & on_ifc[np.clip(tet, 0, len(vtag) - 1)].any(axis=1)


def test_unreachable_moving_blob_reverts():
    s = _two_shards()
    capT = s.tet.shape[1]
    tm0 = np.asarray(s.tmask[0])
    ifc = _interface_adjacent(s, 0)
    # pick an interior tet far from the interface and label it (plus
    # nothing else) as moving to shard 1 with depth 2: a moving blob
    # with no depth-1 seed — unreachable by construction
    interior = np.where(tm0 & ~ifc)[0]
    assert len(interior) > 0
    orphan = int(interior[0])
    labels = np.zeros((2, capT), np.int32)
    labels[1, :] = 1
    depth = np.zeros((2, capT), np.int32)
    labels[0, orphan] = 1
    depth[0, orphan] = 2
    lab2, nfix = repair_flood_labels(
        s, jnp.asarray(labels), jnp.asarray(depth), 2)
    lab2 = np.asarray(lab2)
    assert nfix >= 1
    assert lab2[0, orphan] == 0          # reverted to owner
    # nothing else moved
    assert (lab2[0][tm0 & (np.arange(capT) != orphan)] == 0).all()


def test_reachable_front_blob_kept():
    s = _two_shards()
    capT = s.tet.shape[1]
    tm0 = np.asarray(s.tmask[0])
    ifc = np.where(_interface_adjacent(s, 0))[0]
    assert len(ifc) > 0
    # a legitimate front tet moving with depth 1 must be left alone
    labels = np.zeros((2, capT), np.int32)
    labels[1, :] = 1
    depth = np.zeros((2, capT), np.int32)
    mover = int(ifc[0])
    labels[0, mover] = 1
    depth[0, mover] = 1
    lab2, nfix = repair_flood_labels(
        s, jnp.asarray(labels), jnp.asarray(depth), 2)
    lab2 = np.asarray(lab2)
    assert lab2[0, mover] == 1
    assert (lab2[0][tm0 & (np.arange(capT) != mover)] == 0).all()


def test_enclosed_retained_pocket_joins_surrounding_color():
    s = _two_shards()
    capT = s.tet.shape[1]
    capP = s.vert.shape[1]
    tm0 = np.asarray(s.tmask[0])
    tet0 = np.asarray(s.tet[0])
    # choose a pocket tet, then label EVERY tet sharing a vertex with it
    # as moving (depth 1) — the pocket is enclosed: its every vertex is
    # held only by itself and moving tets
    ifc = _interface_adjacent(s, 0)
    interior = np.where(tm0 & ~ifc)[0]
    pocket = int(interior[len(interior) // 2])
    pverts = set(int(v) for v in tet0[pocket])
    ring = np.array([i for i in np.where(tm0)[0] if i != pocket
                     and any(int(v) in pverts for v in tet0[i])])
    # two vertex layers: the pocket must have NO vertex shared with a
    # retained tet outside the band
    rverts = set(int(v) for i in ring for v in tet0[i])
    ring2 = np.array([i for i in np.where(tm0)[0] if i != pocket
                      and any(int(v) in rverts for v in tet0[i])])
    labels = np.zeros((2, capT), np.int32)
    labels[1, :] = 1
    depth = np.zeros((2, capT), np.int32)
    movers = np.unique(np.concatenate([ring, ring2]))
    labels[0, movers] = 1
    depth[0, movers] = 1
    assert labels[0, pocket] == 0
    lab2, nfix = repair_flood_labels(
        s, jnp.asarray(labels), jnp.asarray(depth), 2)
    lab2 = np.asarray(lab2)
    assert nfix >= 1
    assert lab2[0, pocket] == 1          # joined the surrounding color


def test_healthy_flood_untouched():
    from parmmg_tpu.parallel.migrate import flood_labels
    from parmmg_tpu.parallel.comms import build_interface_comms
    from parmmg_tpu.core.mesh import make_mesh, mesh_to_host
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import cube_mesh
    vert, tet = cube_mesh(4)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.3, m.vert.dtype)
    vert_h, tet_h, _, _, _ = mesh_to_host(m)
    cent = vert_h[tet_h].mean(axis=1)
    part = (cent[:, 0] > 0.4).astype(np.int32)   # unequal halves
    s, ms, l2g = split_to_shards(m, met, part, 2, return_l2g=True)
    s = rebuild_shards(s)
    g2l = []
    for s_ in range(2):
        mm = np.full(len(vert_h), -1, np.int64)
        mm[l2g[s_]] = np.arange(len(l2g[s_]))
        g2l.append(mm)
    comms = build_interface_comms(tet_h, part, 2, l2g, g2l)
    sizes = jnp.asarray(np.asarray(s.tmask).sum(axis=1).astype(np.int32))
    labels, depth = flood_labels(
        s, jnp.asarray(comms.node_idx), jnp.asarray(comms.nbr),
        sizes, 2, nlayers=2)
    lab2, nfix = repair_flood_labels(s, labels, depth, 2)
    # a healthy advancing front needs no repair (or at most a couple of
    # tie-cut slivers); the bulk must be untouched
    assert nfix <= 3
