"""Option-matrix tests — the reference CI exercises the remesher switches
(-optim/-noinsert/-noswap/-nomove/-nosurf/-hsiz/-hgrad/-nr, see
cmake/testing/pmmg_tests.cmake:72-150).  The reference only checks exit
codes; here each switch's CONTRACT is asserted (ops suppressed, mesh
valid, volume preserved)."""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.api import ParMesh, IParam, DParam
from parmmg_tpu.core import constants as C
from parmmg_tpu.core.mesh import tet_volumes
from parmmg_tpu.utils.fixtures import cube_mesh


def _staged(n=3, **info_kw):
    vert, tet = cube_mesh(n)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.info.niter = 1
    pm.info.imprim = -1
    for k, v in info_kw.items():
        setattr(pm.info, k, v)
    return pm


def _run_ok(pm):
    assert pm.run() == C.PMMG_SUCCESS
    vols = np.asarray(tet_volumes(pm._out))[np.asarray(pm._out.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    return pm


def test_noinsert_keeps_point_count():
    pm = _run_ok(_staged(noinsert=True, hsiz=0.2))
    st = pm.stats
    assert st.nsplit == 0 and st.ncollapse == 0
    np_out, ne_out, *_ = pm.get_mesh_size()
    assert np_out == len(cube_mesh(3)[0])      # no insertion or deletion


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_noswap_suppresses_swaps():
    pm = _run_ok(_staged(noswap=True, hsiz=0.22))
    assert pm.stats.nswap == 0
    assert pm.stats.nsplit > 0                 # sizing still ran


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_nomove_suppresses_smoothing():
    pm = _run_ok(_staged(nomove=True, hsiz=0.22))
    assert pm.stats.nmoved == 0
    assert pm.stats.nsplit > 0


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_nosurf_freezes_boundary_vertices():
    pm = _staged(nosurf=True, hsiz=0.22)
    vert0, _ = cube_mesh(3)
    _run_ok(pm)
    # every original boundary vertex must survive at its position
    # (tolerance: core mesh coords are float32)
    on_bdy = (np.isclose(vert0, 0) | np.isclose(vert0, 1)).any(axis=1)
    out_v, _ = pm.get_vertices()
    for v in vert0[on_bdy]:
        d = np.linalg.norm(out_v - v[None, :], axis=1).min()
        assert d < 1e-6, f"boundary vertex {v} moved/removed (d={d})"


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_optim_without_metric():
    pm = _run_ok(_staged(optim=True))
    assert pm.stats.cycles >= 1
