"""Surface-analysis tests: ridges, corners, normals on the unit cube.

Oracle: the cube's 12 edges are 90-degree ridges, its 8 corners have 3
incident ridge edges each (=> MG_CRN), face-interior boundary vertices are
plain MG_BDY, interior vertices untagged (Mmg setdhd/singul semantics).
"""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.utils.fixtures import cube_mesh


def _analyzed(n=3):
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    return analyze_mesh(m), vert


def test_cube_corners_and_ridges():
    res, vert = _analyzed(3)
    m = res.mesh
    vm = np.asarray(m.vmask)
    vtag = np.asarray(m.vtag)[vm]
    v = np.asarray(m.vert)[vm]

    on_face = ((v == 0) | (v == 1)).sum(axis=1)   # how many cube faces
    is_corner = on_face == 3
    is_ridge = on_face == 2
    is_face = on_face == 1
    is_int = on_face == 0

    assert ((vtag[is_corner] & C.MG_CRN) != 0).all()
    assert ((vtag[is_ridge] & C.MG_GEO) != 0).all()
    assert ((vtag[is_ridge] & C.MG_CRN) == 0).all()
    assert ((vtag[is_face] & (C.MG_GEO | C.MG_CRN)) == 0).all()
    assert ((vtag[is_face] & C.MG_BDY) != 0).all()
    assert (vtag[is_int] == 0).all()


def test_cube_ridge_edge_count():
    res, vert = _analyzed(2)
    m = res.mesh
    from parmmg_tpu.ops.edges import unique_edges
    et = unique_edges(m)
    em = np.asarray(et.emask)
    etag = np.asarray(et.etag)[em]
    ev = np.asarray(et.ev)[em]
    ridge = (etag & C.MG_GEO) != 0
    # geometric oracle: both endpoints on the same cube edge (2 shared
    # extreme coordinates)
    v = np.asarray(m.vert)
    shared = ((v[ev[:, 0]] == v[ev[:, 1]]) &
              ((v[ev[:, 0]] == 0) | (v[ev[:, 0]] == 1))).sum(axis=1)
    want = shared == 2
    assert (ridge == want).all()


def test_vertex_normals_point_outward():
    res, vert = _analyzed(2)
    vn = np.asarray(res.vnormal)
    m = res.mesh
    vm = np.asarray(m.vmask)
    v = np.asarray(m.vert)[vm]
    n = vn[vm]
    on_bdy = ((v == 0) | (v == 1)).any(axis=1)
    # unit norm on boundary, zero inside
    assert np.allclose(np.linalg.norm(n[on_bdy], axis=1), 1.0, atol=1e-5)
    assert np.allclose(n[~on_bdy], 0.0)
    # face-interior vertex normal equals the face's outward axis
    face_lo = (v[:, 0] == 0) & (v[:, 1] != 0) & (v[:, 1] != 1) \
        & (v[:, 2] != 0) & (v[:, 2] != 1)
    if face_lo.any():
        assert np.allclose(n[face_lo], [-1.0, 0, 0], atol=1e-5)


def test_open_boundary_nonmanifold():
    # a single tet layer with one face removed is still manifold; instead
    # test a configuration of two tets glued at a single edge -> that edge
    # has 4 incident boundary faces => MG_NOM
    vert = np.array([
        [0, 0, 0], [1, 0, 0],          # shared edge
        [0.5, 1, 0], [0.5, 1, 1],      # top pair (tet 1)
        [0.5, -1, 0], [0.5, -1, 1],    # bottom pair (tet 2)
    ], dtype=float)
    tet = np.array([[0, 1, 2, 3], [0, 1, 5, 4]], np.int32)
    from parmmg_tpu.utils.fixtures import _orient_positive
    tet = _orient_positive(vert, tet)
    m = make_mesh(vert, tet, capP=16, capT=16)
    res = analyze_mesh(m)
    vtag = np.asarray(res.mesh.vtag)
    assert (vtag[0] & C.MG_NOM) and (vtag[1] & C.MG_NOM)


def test_ridge_per_side_normals_cube():
    """Ridge vertices of the unit cube store the TWO adjacent face
    normals (the reference's xPoint n1/n2, analys_pmmg.c:199-1171),
    not their meaningless average."""
    import numpy as np
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.core import constants as C
    from parmmg_tpu.ops.analysis import analyze_mesh, ridge_vertex_normals
    from parmmg_tpu.utils.fixtures import cube_mesh

    vert, tet = cube_mesh(4)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    m = analyze_mesh(m).mesh
    n1, n2 = ridge_vertex_normals(m)
    n1, n2 = np.asarray(n1), np.asarray(n2)
    vtag = np.asarray(m.vtag)
    vm = np.asarray(m.vmask)
    ridge = vm & ((vtag & C.MG_GEO) != 0) & ((vtag & C.MG_CRN) == 0) & \
        ((vtag & C.MG_NOM) == 0)
    assert ridge.sum() > 0, "cube edges must carry ridge vertices"
    vh = np.asarray(m.vert)
    for i in np.where(ridge)[0]:
        # each cube-edge vertex sits on exactly two axis faces: both
        # per-side normals must be +-axis unit vectors, and different
        a, b = n1[i], n2[i]
        assert np.isclose(np.abs(a).max(), 1.0, atol=1e-5), (i, a)
        assert np.isclose(np.abs(b).max(), 1.0, atol=1e-5), (i, b)
        assert not np.allclose(a, b), (i, a, b)
        # both are outward normals of faces the vertex lies on
        for n in (a, b):
            ax = int(np.argmax(np.abs(n)))
            face_val = 1.0 if n[ax] > 0 else 0.0
            assert np.isclose(vh[i][ax], face_val, atol=1e-9), (i, n)
    # off-ridge rows are zero
    assert (n1[~ridge] == 0).all() and (n2[~ridge] == 0).all()
