"""Host-only tests for the flow-sensitive lint core (R8/R9/R10).

Same contract as tests/test_lint.py: no jax import anywhere in this
module (the interprocedural analyses are pure stdlib ``ast``), each
rule gets known-clean + known-dirty fixture pairs, the summary cache
proves content-keyed invalidation, and the real tree is gated with the
new rules on — green, with every finding reason-suppressed in source.
"""
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from parmmg_tpu import lint                                    # noqa: E402
from parmmg_tpu.lint import SourceFile, flow, gate, load_baseline  # noqa: E402


def lint_sources(srcs: dict, rules, readme_text: str = ""):
    """Run a rule subset over literal {relpath: source} fixtures."""
    files = {rel: SourceFile(rel, txt) for rel, txt in srcs.items()}
    return lint.run_lint(rules=rules, files=files,
                         readme_text=readme_text)


def keys(report):
    return sorted(v.key for v in report.violations)


def details(report):
    return sorted(v.detail for v in report.violations)


# ---------------------------------------------------------------------------
# R8 SPMD collective alignment
# ---------------------------------------------------------------------------
R8_DIRTY = '''
import jax
from jax.experimental.multihost_utils import process_allgather

def divergent_collective(x):
    if jax.process_index() == 0:
        process_allgather(x)          # only rank 0 runs it: wedge

def divergent_exit(x):
    if jax.process_index() > 0:
        return None
    return process_allgather(x)       # ranks != 0 already left

def divergence_by_data(state, save):
    save(state, write=jax.process_index() == 0)

def rank_gated_side_effect(log):
    rank = jax.process_index()
    if rank == 0:
        log("only rank zero prints")
'''

R8_CLEAN = '''
import jax
from jax.experimental.multihost_utils import process_allgather
from parmmg_tpu.parallel.multihost import mh_uniform

def agreed_then_collective(local, x):
    # passing a rank-LOCAL value to the agreement primitive is the
    # idiom itself; its RESULT is uniform, so the guard is aligned
    flags = process_allgather(local)
    if flags.max() > 0:
        return process_allgather(x)
    return None

def blessed_write(state, save, multi):
    save(state, write=mh_uniform(
        (not multi) or jax.process_index() == 0,
        "rank-0-writes: payload agreed upstream"))

def uniform_guard_collective(x, n):
    # no rank taint at all: every rank computes the same n
    if n > 3:
        return process_allgather(x)
    return None
'''


def test_r8_dirty_fixture_flags_all_four_shapes():
    rep = lint_sources({"parmmg_tpu/fx/spmd_dirty.py": R8_DIRTY},
                       rules=("R8",))
    det = details(rep)
    assert "divergent-collective:process_allgather" in det
    assert any(d.startswith("collective-after-divergent-exit:")
               for d in det)
    assert "rank-tainted-arg:save" in det
    assert "rank-gated-call:log" in det


def test_r8_clean_fixture_is_quiet():
    rep = lint_sources({"parmmg_tpu/fx/spmd_clean.py": R8_CLEAN},
                       rules=("R8",))
    assert keys(rep) == []


def test_r8_def_line_suppression_covers_decorated_function():
    src = '''
import jax
from jax.experimental.multihost_utils import process_allgather

def dec(f):
    return f

# lint: ok(R8) — fixture: the whole function is a blessed rank-scoped
# action (engine def-anchor resolution, decorated def)
@dec
def rank_zero_reporter(x):
    if jax.process_index() == 0:
        process_allgather(x)
'''
    rep = lint_sources({"parmmg_tpu/fx/spmd_supp.py": src},
                       rules=("R8",))
    assert keys(rep) == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R9 lock discipline
# ---------------------------------------------------------------------------
R9_ORDER_DIRTY = '''
import threading

class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def ab(self):
        with self._a:
            with self._b:
                return 1

    def ba(self):
        with self._b:
            with self._a:
                return 2
'''

R9_RLOCK_CLEAN = '''
import threading

class Reentrant:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            return self.inner()

    def inner(self):
        with self._lock:       # RLock re-entry is its contract
            return 1
'''

R9_SELF_DEADLOCK = R9_RLOCK_CLEAN.replace("RLock()", "Lock()")

R9_DISPATCH_DIRTY = '''
import subprocess
import threading

class Holder:
    def __init__(self):
        self._lock = threading.Lock()

    def direct(self):
        with self._lock:
            subprocess.check_call(["true"])   # wedge holds the lock

    def transitive(self):
        with self._lock:
            return spawn_helper()

def spawn_helper():
    return subprocess.check_output(["true"])
'''

R9_FIELD_DIRTY = '''
import threading

class PoolDaemon:
    def __init__(self):
        self._lock = threading.RLock()
        self.flag = False

    def _dispatch(self, op):
        self.flag = True          # handler thread, unguarded

    def _loop(self):
        while True:
            if self.flag:         # loop thread reads it
                break
'''

R9_FIELD_CLEAN = '''
import threading

class PoolDaemon:
    def __init__(self):
        self._lock = threading.RLock()
        self.flag = False

    def _dispatch(self, op):
        with self._lock:
            self.flag = True      # guarded write

    def _loop(self):
        while True:
            if self.flag:
                break
'''


def test_r9_lock_order_cycle_detected():
    rep = lint_sources({"parmmg_tpu/fx/locks_cycle.py": R9_ORDER_DIRTY},
                       rules=("R9",))
    assert any(d.startswith("lock-order:") for d in details(rep))


def test_r9_rlock_reentry_clean_plain_lock_dirty():
    clean = lint_sources(
        {"parmmg_tpu/fx/locks_rlock.py": R9_RLOCK_CLEAN}, rules=("R9",))
    assert keys(clean) == []
    dirty = lint_sources(
        {"parmmg_tpu/fx/locks_self.py": R9_SELF_DEADLOCK},
        rules=("R9",))
    assert any(d.startswith("lock-order:") for d in details(dirty))


def test_r9_dispatch_under_lock_direct_and_transitive():
    rep = lint_sources(
        {"parmmg_tpu/fx/locks_dispatch.py": R9_DISPATCH_DIRTY},
        rules=("R9",))
    det = details(rep)
    assert any(d.startswith("lock-held-dispatch:") and "check_call" in d
               for d in det)
    assert any("spawn_helper" in d for d in det)


def test_r9_unguarded_cross_thread_field():
    dirty = lint_sources(
        {"parmmg_tpu/fx/daemon_field.py": R9_FIELD_DIRTY},
        rules=("R9",))
    assert "unguarded-field:flag" in details(dirty)
    clean = lint_sources(
        {"parmmg_tpu/fx/daemon_field_ok.py": R9_FIELD_CLEAN},
        rules=("R9",))
    assert keys(clean) == []


# ---------------------------------------------------------------------------
# R10 shape-ladder escapes
# ---------------------------------------------------------------------------
R10_DIRTY = '''
import jax.numpy as jnp
import numpy as np

def raw_len(pts):
    n = len(pts)
    return jnp.zeros(n, jnp.int32)

def raw_measure(counts):
    return jnp.ones(int(counts.max()))

def raw_pad(x, counts):
    return jnp.pad(x, int(np.sum(counts)))
'''

R10_CLEAN = '''
import jax.numpy as jnp
from parmmg_tpu.utils.compilecache import bucket

def bucketed(pts):
    cap = bucket(len(pts))
    return jnp.zeros(cap, jnp.int32)

def ladder_wrapper(n):
    # its returns ride the ladder: recognized by the summary fixpoint
    return bucket(2 * n)

def via_wrapper(pts):
    return jnp.zeros(ladder_wrapper(len(pts)))

def from_existing_shape(arr):
    # an array built at a bucketed capacity carries its ladder
    return jnp.zeros(arr.shape[0])

def from_parameter(cap):
    # the caller's measurement site is where the check happens
    return jnp.zeros(cap * 6)
'''


def test_r10_dirty_fixture_flags_raw_measurements():
    rep = lint_sources({"parmmg_tpu/fx/shapes_dirty.py": R10_DIRTY},
                       rules=("R10",))
    det = details(rep)
    assert "raw-shape:zeros:len()" in det
    assert "raw-shape:ones:.max()" in det
    assert any(d.startswith("raw-shape:pad:") for d in det)


def test_r10_clean_fixture_is_quiet():
    rep = lint_sources({"parmmg_tpu/fx/shapes_clean.py": R10_CLEAN},
                       rules=("R10",))
    assert keys(rep) == []


# ---------------------------------------------------------------------------
# summary cache: content-keyed invalidation
# ---------------------------------------------------------------------------
def test_file_summary_invalidates_on_content_change():
    flow.summary_cache_clear()
    calls = []

    def compute(sf):
        calls.append(sf.rel)
        return len(sf.text)

    a1 = SourceFile("parmmg_tpu/fx/cache.py", "def a():\n    pass\n")
    assert flow.file_summary(a1, "t", compute) == len(a1.text)
    assert flow.file_summary(a1, "t", compute) == len(a1.text)
    assert len(calls) == 1                      # memoized on content

    a2 = SourceFile("parmmg_tpu/fx/cache.py", "def a():\n    return 1\n")
    assert flow.file_summary(a2, "t", compute) == len(a2.text)
    assert len(calls) == 2                      # edit -> new key

    # same content again (even via a fresh SourceFile): cached
    a3 = SourceFile("parmmg_tpu/fx/cache.py", a1.text)
    assert flow.file_summary(a3, "t", compute) == len(a1.text)
    assert len(calls) == 2


# ---------------------------------------------------------------------------
# the real tree, gated with the flow rules on
# ---------------------------------------------------------------------------
def test_repo_tree_flow_rules_green_and_jax_free():
    report = lint.run_lint(ROOT, rules=("R8", "R9", "R10"))
    baseline = load_baseline(os.path.join(ROOT, "lint_baseline.json"))
    result = gate(report, baseline)
    assert result.ok, "\n".join(
        f"{v.rule} {v.path}:{v.line} {v.message}" for v in result.new)
    # zero unsuppressed R8/R9/R10 — and every suppression is reasoned
    # (the engine already rejects reasonless ones as SUPP findings)
    assert [v for v in report.violations] == []
    for v, s in report.suppressed:
        assert s.reason.strip()
    # the R2 burn-down never grows: satellite contract is <= 12 keys
    assert len(baseline) <= 12


def test_lint_package_is_jax_free():
    # static means static — in a fresh interpreter (the test session's
    # conftest may already have imported jax) loading the whole lint
    # package, flow core included, must pull in no jax
    import subprocess
    subprocess.run(
        [sys.executable, "-c",
         "import sys; import parmmg_tpu.lint; "
         "assert 'jax' not in sys.modules"],
        cwd=ROOT, check=True, timeout=60)
