"""Option-matrix tests, part 2 (split from test_options.py: the
XLA:CPU backend on this image segfaults late in long test processes —
same reason test_curved/test_curved_dist are split)."""
import numpy as np

from parmmg_tpu.api import ParMesh
from parmmg_tpu.core import constants as C
from parmmg_tpu.utils.fixtures import cube_mesh

from test_options import _staged, _run_ok
import pytest

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def test_noridge_detection_flag():
    pm = _staged(hsiz=0.3)
    pm.info.angle_detection = False
    _run_ok(pm)
    # with -nr no MG_GEO ridge tags are produced on output feature edges
    _, _, is_ridge, _ = pm.get_edges()
    assert not is_ridge.any()


def test_local_parameters_clamp_sizes():
    """MMG3D_Set_localParameter path: vertices on surface ref 7 get the
    local [hmin,hmax] clamp; elsewhere the global size applies."""
    from parmmg_tpu.core.constants import IDIR
    vert, tet = cube_mesh(3)
    faces = []
    for t in tet:
        for f in range(4):
            tri = t[IDIR[f]]
            if (vert[tri][:, 2] == 0).all():
                faces.append(tri + 1)
    faces = np.array(faces)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet), nt=len(faces))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.set_triangles(faces, refs=np.full(len(faces), 7))
    pm.info.niter = 1
    pm.info.imprim = -1
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.4))
    pm.set_local_parameter(1, 7, 0.05, 0.15, 0.001)
    assert pm.run() == C.PMMG_SUCCESS
    # output metric near z=0 must be clamped to the local hmax
    out_v, _ = pm.get_vertices()
    met = pm.get_metric()
    near = np.isclose(out_v[:, 2], 0)
    assert near.any()
    assert met[near].max() <= 0.15 + 1e-5
    far = out_v[:, 2] > 0.7
    assert met[far].min() > 0.15


def _fem_bad_edges(mesh):
    """Interior edges whose two endpoints both lie on the boundary (the
    FEM-incompatible configuration)."""
    from parmmg_tpu.core.constants import IARE, MG_BDY
    tet = np.asarray(mesh.tet)
    tm = np.asarray(mesh.tmask)
    etag = np.asarray(mesh.etag)
    vtag = np.asarray(mesh.vtag)
    ev = np.sort(tet[:, IARE], axis=2)[tm]               # [nt,6,2]
    interior = (etag[tm] & MG_BDY) == 0
    both_bdy = ((vtag[ev[..., 0]] & MG_BDY) != 0) & \
        ((vtag[ev[..., 1]] & MG_BDY) != 0)
    bad = ev[interior & both_bdy]
    return {tuple(e) for e in bad.reshape(-1, 2)}


def test_fem_mode_removes_interior_bdy_bdy_edges():
    """Default fem mode (reference default MMG5_FEM,
    API_functions_pmmg.c:413): after the run, no interior edge connects
    two boundary points — so no element has two boundary faces or all
    four vertices on the boundary."""
    pm = _run_ok(_staged(hsiz=0.4))
    assert pm.info.fem
    assert not _fem_bad_edges(pm._out)


def test_nofem_skips_fem_splits(monkeypatch):
    """-nofem: the fem conformity pass is skipped (flag must act, not
    decorate) — counted via the fem_pass entry point."""
    import parmmg_tpu.ops.adapt as adapt_mod
    calls = {"n": 0}
    orig = adapt_mod.fem_pass

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    monkeypatch.setattr(adapt_mod, "fem_pass", counting)
    pm = _staged(hsiz=0.4)
    pm.info.fem = False
    _run_ok(pm)
    assert calls["n"] == 0
    pm2 = _staged(hsiz=0.4)
    _run_ok(pm2)
    assert calls["n"] > 0
