"""Compile governor regression tests (utils/compilecache).

The steady-state remesh/repartition loop re-runs the same programs
every iteration; the governor's job is that drifting per-iteration
sizes (interface widths, retag KF2/KN, comm-table pads) land on a
small fixed set of bucketed static shapes so the registered entry
points stop compiling fresh variants (ADVICE r3: retag_device compiled
nearly every iteration).  The ledger (jax.monitoring backend-compile
listener + registry decorator) is the measurement; these tests pin the
policy AND the end-to-end behavior on the CPU backend.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.utils.compilecache import (
    bucket, governed, ledger_diff, ledger_snapshot, ledger_violations,
    reset_ledger)


def test_bucket_policy():
    # pow2: monotone, floored, >= n, few variants over a wide range
    assert bucket(1) == 256 and bucket(256) == 256 and bucket(257) == 512
    sizes = {bucket(n) for n in range(1, 4097)}
    assert sizes == {256, 512, 1024, 2048, 4096}
    for n in (1, 100, 1000, 4097):
        assert bucket(n) >= n
    # geo: bounded overshoot (<= 1.5x + 1), still O(log) variants
    for n in (70, 500, 3000, 40000):
        b = bucket(n, floor=64, scheme="geo")
        assert n <= b <= int(1.5 * n) + 2
    assert len({bucket(n, floor=64, scheme="geo")
                for n in range(1, 5000)}) <= 12
    # cap clamps (caller must handle a capped bucket < n)
    assert bucket(5000, floor=1024, cap=3000) == 3000
    import pytest
    with pytest.raises(ValueError):
        bucket(10, scheme="fib")


def test_ledger_attribution_and_budget():
    import jax
    reset_ledger()

    @governed("test.toy", budget=1)
    @jax.jit
    def toy(x):
        return x * 2 + 1

    toy(jnp.ones(8))
    toy(jnp.ones(8))          # cache hit: no new compile
    rec = ledger_snapshot()["test.toy"]
    assert rec["calls"] == 2
    assert rec["variants"] == 1 and rec["compiles"] >= 1
    assert not any(v.startswith("test.toy") for v in ledger_violations())
    toy(jnp.ones(16))         # second shape: budget 1 exceeded
    assert ledger_snapshot()["test.toy"]["variants"] == 2
    assert any(v.startswith("test.toy") for v in ledger_violations())


def test_session_id_guard_and_multiway_run_guard():
    """Satellite guards (ADVICE r3): int32 session-id overflow check and
    the non-manifold (3+ shard) exposed-face run detector."""
    from parmmg_tpu.parallel.migrate_dev import (has_multiway_face_run,
                                                 session_ids_fit)
    assert session_ids_fit(0, 8, 4096)
    assert session_ids_fit(2 ** 31 - 8 * 4096 - 1, 8, 4096)
    assert not session_ids_fit(2 ** 31 - 8 * 4096, 8, 4096)
    assert not session_ids_fit(2 ** 31, 2, 256)
    # eq = consecutive-equality mask of lexsorted face keys
    assert not has_multiway_face_run(np.array([], bool))
    assert not has_multiway_face_run(np.array([True], bool))
    assert not has_multiway_face_run(
        np.array([True, False, True, False], bool))     # pairs only
    assert has_multiway_face_run(
        np.array([False, True, True, False], bool))     # a 3-run
    assert has_multiway_face_run(np.array([True] * 3, bool))  # a 4-run


def test_ledger_diff_flags_variant_growth():
    """The bench-side regression comparison (ledger_check.py --diff /
    bench.py vs the previous BENCH artifact): growth on a shared entry
    is flagged, new entries and equal counts are not, and the nested
    per-worker shape scale_big emits is flattened per worker."""
    old = {"a": {"variants": 1}, "b": {"variants": 2}}
    new = {"a": {"variants": 3}, "b": {"variants": 2},
           "c": {"variants": 9}}
    bad = ledger_diff(old, new)
    assert bad == ["a: 1 -> 3 compiled variants"]
    assert ledger_diff(new, new) == []
    nested_o = {"pass0": {"x": {"variants": 1}}, "host": {"x":
                                                          {"variants": 1}}}
    nested_n = {"pass0": {"x": {"variants": 2}}, "host": {"x":
                                                          {"variants": 1}}}
    assert ledger_diff(nested_o, nested_n) == \
        ["pass0/x: 1 -> 2 compiled variants"]


@pytest.fixture(scope="module")
def scenario():
    """ONE run of the shared steady-state scenario
    (utils/fixtures.steady_state_migration_scenario) feeding every test
    in this module: the ledger-budget gate AND the burned-down
    migration gates from test_migrate ride the same compiled variants,
    so tier-1 pays the SPMD compile once (the slow-tier burn-down
    contract).  merge_shards calls are counted across the run for the
    no-intermediate-merge gate."""
    from parmmg_tpu.parallel import distribute
    from parmmg_tpu.utils.fixtures import steady_state_migration_scenario

    calls = {"n": 0}
    orig = distribute.merge_shards

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    distribute.merge_shards = counting
    try:
        reset_ledger()
        out, met, part = steady_state_migration_scenario(
            niter=4, cycles=2, n_shards=2, return_all=True)
    finally:
        distribute.merge_shards = orig
    return out, met, part, calls["n"], ledger_snapshot()


def test_migration_steady_state_compiles_bounded(scenario):
    """4 migration iterations with drifting interface sizes: the retag
    and halo entry points must stay within <= 2 compiled variants (the
    bucketed shapes absorb the drift) instead of ~1 fresh compile per
    iteration."""
    out, _met, _part, _nmerge, led = scenario
    assert int(np.asarray(out.tmask).sum()) > 0

    # the scenario must actually exercise the steady-state loop
    assert led["migrate_dev.device_migrate"]["calls"] >= 3
    assert led["migrate_dev.retag_device"]["calls"] >= 1
    for entry, lim in (("migrate_dev.retag_device", 2),
                       ("migrate_dev.extend_ids_device", 2),
                       ("migrate.flood_labels", 2),
                       ("dist.interface_check", 2)):
        rec = led[entry]
        assert rec["variants"] <= lim, \
            f"{entry}: {rec['variants']} compiled variants (> {lim}) — " \
            "steady-state recompile churn regressed"
    assert ledger_violations() == []


def test_multi_iteration_no_intermediate_merge(scenario):
    """Burned down from test_migrate (slow tier): >= 2 outer iterations
    with NO full-mesh merge except the final output merge (VERDICT r1
    #5), asserted on the shared scenario run — plus the adjacency
    symmetry, manifold, volume and quality-floor gates the original
    carried.  The shrunk fixture is 2-shard; the K>1-neighbor ifc-mode
    loop keeps its coverage in the slow tier
    (test_grouped_analysis.test_grouped_refresh_taken_on_g2_driver_run
    runs 4 logical shards through the same driver)."""
    out, met, _part, nmerge, _led = scenario
    assert nmerge == 1, "outer iterations must not merge the world"
    from parmmg_tpu.core.mesh import mesh_to_host
    from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
    from parmmg_tpu.ops.quality import tet_quality
    vert_h, tet_h, _, _, _ = mesh_to_host(out)
    p = vert_h[tet_h]
    vol = np.einsum("ij,ij->i", p[:, 1] - p[:, 0],
                    np.cross(p[:, 2] - p[:, 0], p[:, 3] - p[:, 0])) / 6.0
    assert (vol > 0).all(), "inverted tets after the final merge"
    assert np.isclose(vol.sum(), 1.0, rtol=1e-4)
    faces = np.sort(np.stack([
        tet_h[:, [1, 2, 3]], tet_h[:, [0, 2, 3]],
        tet_h[:, [0, 1, 3]], tet_h[:, [0, 1, 2]]], axis=1
    ).reshape(-1, 3), axis=1)
    _, cnt = np.unique(faces, axis=0, return_counts=True)
    assert cnt.max() <= 2, "non-manifold face after migration + merge"
    out2 = build_adjacency(out)
    assert check_adjacency(out2) == {"asymmetric": 0, "face_mismatch": 0}
    q = np.asarray(tet_quality(out2, met))[np.asarray(out2.tmask)]
    assert q.min() > 0.02


def test_migration_moves_interface_band(scenario):
    """Burned down from test_migrate (slow tier): after the migration
    iterations the displaced partition labels are a valid source-shard
    assignment of every live tet (the comm echo inside the loop raises
    on an ordering violation, so reaching here also proves it held)."""
    out, _met, part, _nmerge, _led = scenario
    assert part.min() >= 0 and part.max() < 2
    assert len(part) == int(np.asarray(out.tmask).sum())
