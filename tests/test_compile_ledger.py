"""Compile governor regression tests (utils/compilecache).

The steady-state remesh/repartition loop re-runs the same programs
every iteration; the governor's job is that drifting per-iteration
sizes (interface widths, retag KF2/KN, comm-table pads) land on a
small fixed set of bucketed static shapes so the registered entry
points stop compiling fresh variants (ADVICE r3: retag_device compiled
nearly every iteration).  The ledger (jax.monitoring backend-compile
listener + registry decorator) is the measurement; these tests pin the
policy AND the end-to-end behavior on the CPU backend.
"""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.utils.compilecache import (
    bucket, governed, ledger_snapshot, ledger_violations, reset_ledger)


def test_bucket_policy():
    # pow2: monotone, floored, >= n, few variants over a wide range
    assert bucket(1) == 256 and bucket(256) == 256 and bucket(257) == 512
    sizes = {bucket(n) for n in range(1, 4097)}
    assert sizes == {256, 512, 1024, 2048, 4096}
    for n in (1, 100, 1000, 4097):
        assert bucket(n) >= n
    # geo: bounded overshoot (<= 1.5x + 1), still O(log) variants
    for n in (70, 500, 3000, 40000):
        b = bucket(n, floor=64, scheme="geo")
        assert n <= b <= int(1.5 * n) + 2
    assert len({bucket(n, floor=64, scheme="geo")
                for n in range(1, 5000)}) <= 12
    # cap clamps (caller must handle a capped bucket < n)
    assert bucket(5000, floor=1024, cap=3000) == 3000
    import pytest
    with pytest.raises(ValueError):
        bucket(10, scheme="fib")


def test_ledger_attribution_and_budget():
    import jax
    reset_ledger()

    @governed("test.toy", budget=1)
    @jax.jit
    def toy(x):
        return x * 2 + 1

    toy(jnp.ones(8))
    toy(jnp.ones(8))          # cache hit: no new compile
    rec = ledger_snapshot()["test.toy"]
    assert rec["calls"] == 2
    assert rec["variants"] == 1 and rec["compiles"] >= 1
    assert not any(v.startswith("test.toy") for v in ledger_violations())
    toy(jnp.ones(16))         # second shape: budget 1 exceeded
    assert ledger_snapshot()["test.toy"]["variants"] == 2
    assert any(v.startswith("test.toy") for v in ledger_violations())


def test_session_id_guard_and_multiway_run_guard():
    """Satellite guards (ADVICE r3): int32 session-id overflow check and
    the non-manifold (3+ shard) exposed-face run detector."""
    from parmmg_tpu.parallel.migrate_dev import (has_multiway_face_run,
                                                 session_ids_fit)
    assert session_ids_fit(0, 8, 4096)
    assert session_ids_fit(2 ** 31 - 8 * 4096 - 1, 8, 4096)
    assert not session_ids_fit(2 ** 31 - 8 * 4096, 8, 4096)
    assert not session_ids_fit(2 ** 31, 2, 256)
    # eq = consecutive-equality mask of lexsorted face keys
    assert not has_multiway_face_run(np.array([], bool))
    assert not has_multiway_face_run(np.array([True], bool))
    assert not has_multiway_face_run(
        np.array([True, False, True, False], bool))     # pairs only
    assert has_multiway_face_run(
        np.array([False, True, True, False], bool))     # a 3-run
    assert has_multiway_face_run(np.array([True] * 3, bool))  # a 4-run


def test_migration_steady_state_compiles_bounded():
    """4 migration iterations with drifting interface sizes: the retag
    and halo entry points must stay within <= 2 compiled variants (the
    bucketed shapes absorb the drift) instead of ~1 fresh compile per
    iteration."""
    from parmmg_tpu.utils.fixtures import steady_state_migration_scenario

    reset_ledger()
    out = steady_state_migration_scenario(niter=4, cycles=2, n_shards=2)
    assert int(np.asarray(out.tmask).sum()) > 0

    led = ledger_snapshot()
    # the scenario must actually exercise the steady-state loop
    assert led["migrate_dev.device_migrate"]["calls"] >= 3
    assert led["migrate_dev.retag_device"]["calls"] >= 1
    for entry, lim in (("migrate_dev.retag_device", 2),
                       ("migrate_dev.extend_ids_device", 2),
                       ("migrate.flood_labels", 2),
                       ("dist.interface_check", 2)):
        rec = led[entry]
        assert rec["variants"] <= lim, \
            f"{entry}: {rec['variants']} compiled variants (> {lim}) — " \
            "steady-state recompile churn regressed"
    assert ledger_violations() == []
