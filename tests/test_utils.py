"""Timers, memory budgeting, debug dumps."""
import numpy as np

from parmmg_tpu.utils.timers import Timers
from parmmg_tpu.utils.budget import plan_capacities
from parmmg_tpu.utils import debug
from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.utils.fixtures import cube_mesh


def test_timers_nesting():
    t = Timers()
    with t("outer"):
        with t("inner"):
            pass
    assert "outer" in t.acc and "outer/inner" in t.acc
    assert t.acc["outer"] >= t.acc["outer/inner"]
    assert "inner" in t.report()


def test_plan_capacities_budget():
    capP, capT = plan_capacities(1000, 6000, budget_mb=-1)
    assert capP == 3000 and capT == 18000
    capP2, capT2 = plan_capacities(1000, 6000, budget_mb=1)
    assert capP2 < capP and capT2 < capT
    assert capP2 >= 1000 and capT2 >= 6000   # never below content


def test_debug_dumps(tmp_path):
    vert, tet = cube_mesh(2)
    m = analyze_mesh(make_mesh(vert, tet)).mesh
    p = debug.dump_mesh(m, tmp_path / "dbg.mesh")
    assert p.exists() and p.stat().st_size > 0
    t = debug.dump_tags(m, tmp_path / "tags.txt")
    txt = t.read_text()
    assert "CRN" in txt and "BDY" in txt
    chk = debug.check_mesh_consistency(m)
    assert chk["asymmetric"] == 0
    assert chk["nonpositive_vols"] == 0
    assert chk["dangling_vertex_refs"] == 0
