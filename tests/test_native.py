"""Native (C++ meshkit) tests: parity with the numpy implementations."""
import numpy as np
import pytest

from parmmg_tpu import native
from parmmg_tpu.utils.fixtures import cube_mesh

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="g++ build unavailable")


def test_native_adjacency_matches_jax():
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.adjacency import build_adjacency

    vert, tet = cube_mesh(3)
    adja_c = native.build_adjacency(tet)
    m = build_adjacency(make_mesh(vert, tet, capP=len(vert), capT=len(tet)))
    adja_j = np.asarray(m.adja)[: len(tet)]
    assert (adja_c == adja_j).all()


def test_native_partition_balanced():
    vert, tet = cube_mesh(4)
    adja = native.build_adjacency(tet)
    seeds = np.linspace(0, len(tet) - 1, 4).astype(np.int64)
    part = native.greedy_partition(adja, 4, seeds)
    counts = np.bincount(part, minlength=4)
    assert (counts > 0).all()
    assert counts.max() / counts.mean() < 1.5


def test_native_medit_scan(tmp_path):
    from parmmg_tpu.io import medit
    vert, tet = cube_mesh(2)
    m = medit.MeditMesh()
    m.vert, m.vref = vert, np.arange(len(vert), dtype=np.int32)
    m.tetra, m.tref = tet, np.full(len(tet), 3, np.int32)
    p = tmp_path / "c.mesh"
    medit.write_mesh(p, m)
    got = native.scan_medit(p)
    assert np.allclose(got["vert"], vert)
    assert (got["vref"] == m.vref).all()
    assert (got["tet"] == tet).all()
    assert (got["tref"] == 3).all()


def test_native_components():
    vert, tet = cube_mesh(2)
    adja = native.build_adjacency(tet)
    part = np.zeros(len(tet), np.int32)
    comp = native.color_components(adja, part)
    assert (comp == 0).all()
    # split by x: two components per color
    cent = vert[tet].mean(axis=1)
    part = (cent[:, 0] > 0.5).astype(np.int32)
    comp = native.color_components(adja, part)
    assert len(np.unique(comp)) == 2


def test_native_scan_speed_sanity(tmp_path):
    """The native scanner must beat the Python tokenizer (it is the
    data-loader replacement); generous 1.5x bound to stay robust on CI."""
    from parmmg_tpu.io import medit
    vert, tet = cube_mesh(10)
    m = medit.MeditMesh()
    m.vert, m.vref = vert, np.zeros(len(vert), np.int32)
    m.tetra, m.tref = tet, np.zeros(len(tet), np.int32)
    p = tmp_path / "big.mesh"
    medit.write_mesh(p, m)
    # best-of-3 each: a single timing under concurrent CI load is noise
    t_py = min(_timed(lambda: medit.read_mesh(p)) for _ in range(3))
    t_c = min(_timed(lambda: native.scan_medit(p)) for _ in range(3))
    assert t_c < t_py * 1.5


def _timed(fn):
    import time
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0
