"""M2 tests: metric synthesis/gradation and background interpolation."""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.ops.adjacency import build_adjacency, boundary_edge_tags
from parmmg_tpu.ops.metric import (
    metric_hsiz, metric_optim, clamp_metric, gradation)
from parmmg_tpu.ops.interp import (
    locate_points, interp_p1, interp_metric_ani, LocateResult,
    interpolate_from_background)
from parmmg_tpu.ops.quality import iso_to_tensor
from parmmg_tpu.utils.fixtures import cube_mesh


def _cube(n=3, capmul=2):
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert), capT=capmul * len(tet))
    return boundary_edge_tags(build_adjacency(m))


def test_metric_optim_matches_grid():
    m = _cube(4)
    h = np.asarray(metric_optim(m))[np.asarray(m.vmask)]
    # mean incident edge length of a kuhn grid with spacing 0.25:
    # mix of 0.25, 0.25*sqrt2, 0.25*sqrt3 -> between 0.25 and 0.44
    assert (h > 0.24).all() and (h < 0.45).all()


def test_clamp_metric_iso_and_ani():
    met = jnp.array([0.01, 0.5, 10.0])
    c = clamp_metric(met, 0.1, 1.0)
    assert np.allclose(np.asarray(c), [0.1, 0.5, 1.0])
    ani = iso_to_tensor(met)
    ca = np.asarray(clamp_metric(ani, 0.1, 1.0))
    # eigenvalues must be within [1, 100]
    assert np.allclose(ca[0, [0, 3, 5]], 100.0)
    assert np.allclose(ca[2, [0, 3, 5]], 1.0)


def test_gradation_limits_growth():
    m = _cube(4)
    met = np.full(m.capP, 1.0)
    # one tiny vertex size in the middle
    vert = np.asarray(m.vert)
    mid = np.argmin(np.abs(vert - 0.5).sum(axis=1))
    met[mid] = 0.01
    g = gradation(m, jnp.asarray(met), hgrad=1.3)
    g = np.asarray(g)
    # edge-wise gradation bounds h by 0.01 + slope * (shortest edge-graph
    # path length), not straight-line distance: build the graph distance
    # oracle with Bellman-Ford over mesh edges
    from parmmg_tpu.core.mesh import tet_edge_vertices
    ev = np.asarray(tet_edge_vertices(m.tet)).reshape(-1, 2)
    ev = ev[np.repeat(np.asarray(m.tmask), 6)]
    elen = np.linalg.norm(vert[ev[:, 0]] - vert[ev[:, 1]], axis=1)
    d = np.full(m.capP, np.inf)
    d[mid] = 0.0
    for _ in range(30):
        np.minimum.at(d, ev[:, 0], d[ev[:, 1]] + elen)
        np.minimum.at(d, ev[:, 1], d[ev[:, 0]] + elen)
    vm = np.asarray(m.vmask)
    bound = 0.01 + 0.3 * d + 1e-5
    assert (g[vm] <= bound[vm] + 1e-6).all()
    assert g[mid] == 0.01


def test_locate_points_walk():
    m = _cube(3)
    rng = np.random.default_rng(1)
    pts = rng.uniform(0.05, 0.95, (50, 3)).astype(np.float32)
    loc = locate_points(m, jnp.asarray(pts), jnp.zeros(50, jnp.int32))
    assert not np.asarray(loc.failed).any()
    # verify containment: all barycoords >= -1e-3
    assert float(jnp.min(loc.bary)) > -1e-3
    tids = np.asarray(loc.tet)
    assert (np.asarray(m.tmask)[tids]).all()


def test_interp_p1_linear_exact():
    m = _cube(3)
    # a linear field is reproduced exactly by P1 interpolation
    coef = np.array([1.5, -2.0, 0.5])
    vals = np.asarray(m.vert) @ coef + 0.25
    rng = np.random.default_rng(2)
    pts = rng.uniform(0.1, 0.9, (40, 3)).astype(np.float32)
    loc = locate_points(m, jnp.asarray(pts), jnp.zeros(40, jnp.int32))
    got = np.asarray(interp_p1(jnp.asarray(vals), m.tet, loc))
    want = pts @ coef + 0.25
    assert np.allclose(got, want, atol=1e-4)


def test_interp_ani_constant_exact():
    m = _cube(2)
    t = np.tile(np.array([4.0, 0.5, 0.0, 9.0, 0.1, 1.0]), (m.capP, 1))
    pts = np.array([[0.3, 0.3, 0.3], [0.7, 0.2, 0.5]], np.float32)
    loc = locate_points(m, jnp.asarray(pts), jnp.zeros(2, jnp.int32))
    got = np.asarray(interp_metric_ani(jnp.asarray(t), m.tet, loc))
    assert np.allclose(got, t[:2], atol=1e-4)


def test_interpolate_from_background_driver():
    bg = _cube(3)
    bg_met = jnp.asarray(np.linspace(0.1, 0.5, bg.capP))
    mesh = _cube(2)
    met = jnp.full(mesh.capP, 99.0)
    met2, _, loc = interpolate_from_background(bg, bg_met, mesh, met)
    met2 = np.asarray(met2)
    vm = np.asarray(mesh.vmask)
    assert (met2[vm] < 1.0).all()          # overwritten from background
    assert not np.asarray(loc.failed)[vm].any()


def test_locate_points_bdy_sphere():
    """Surface walk localization (PMMG_locatePointBdy analogue): points on
    a sphere surface must land on a surface triangle whose plane is close,
    and a surface field (linear in xyz restricted to the surface) must
    interpolate through the TRIANGLE, not some interior tet."""
    from parmmg_tpu.ops.interp import locate_points_bdy, interp_p1_tri
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import sphere_mesh

    vert, tet = sphere_mesh(8)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    m = analyze_mesh(m).mesh
    rng = np.random.default_rng(3)
    # query points ON the analytic sphere (radius of the fixture surface
    # vertices), i.e. slightly OUTSIDE the polyhedral surface
    vb = vert[np.linalg.norm(vert, axis=1) > 0.6]
    R = float(np.linalg.norm(vb, axis=1).mean())
    d = rng.normal(size=(30, 3))
    pts = (R * d / np.linalg.norm(d, axis=1, keepdims=True)).astype(
        np.float32)
    sloc = locate_points_bdy(m, jnp.asarray(pts))
    # located triangles are real surface slots and the plane distance is
    # small (chord sagitta scale, not O(R))
    assert float(jnp.max(jnp.abs(sloc.dist))) < 0.15 * R
    coef = np.array([0.7, -1.1, 0.4])
    field = np.asarray(m.vert) @ coef
    got = np.asarray(interp_p1_tri(jnp.asarray(field), m, sloc))
    want = pts @ coef
    # the error budget is the chord sagitta of the COARSE fixture
    # (|coef| ~ 1.4 x sagitta ~ 0.08R at sphere_mesh(8)); the gate is
    # that every point interpolates from a genuinely nearby surface
    # triangle, not some far slot
    assert np.abs(got - want).max() < 1.5 * 0.15 * R


def test_interpolate_from_background_boundary_split():
    """Boundary vertices must take the surface-interpolated value."""
    import dataclasses
    from parmmg_tpu.core.constants import MG_BDY
    bg = _cube(3)
    bg_met = jnp.asarray(np.linspace(0.1, 0.5, bg.capP))
    mesh = _cube(2)
    met = jnp.full(mesh.capP, 99.0)
    met2, _, _ = interpolate_from_background(bg, bg_met, mesh, met)
    vm = np.asarray(mesh.vmask)
    assert (np.asarray(met2)[vm] < 1.0).all()
    # the split engages when vtag has MG_BDY (mesh is analyzed in prod)
    vtag = np.zeros(mesh.capP, np.uint32)
    vtag[: 4] = MG_BDY
    mesh_b = dataclasses.replace(mesh, vtag=jnp.asarray(vtag))
    met3, _, _ = interpolate_from_background(bg, bg_met, mesh_b, met)
    assert (np.asarray(met3)[vm] < 1.0).all()
