"""Hot-loop attack tests (README "Hot-loop cycle costs" section).

Tier-1 (fast) coverage: the face-pair-from-sort table against the
legacy ``adja`` pairing, the donor-band width math, the fused top-k
scoring prep (jnp reference AND interpret-mode Pallas kernels), and
the smoothing-cadence parity on a fused block.  The slow marks re-run
the bit-parity claims through the full waves per knob — including the
polish pass — exactly as the production drivers call them.
"""
import os
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import MESH_FIELDS, make_mesh
from parmmg_tpu.ops.adjacency import build_adjacency
from parmmg_tpu.utils.fixtures import cube_mesh


def _cube(n=2, capmul=4):
    from parmmg_tpu.ops.analysis import analyze_mesh
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert),
                  capT=capmul * len(tet))
    return analyze_mesh(m).mesh


def _assert_mesh_equal(a, b, label=""):
    for f in MESH_FIELDS:
        av, bv = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert (av == bv).all(), f"{label}: mesh field {f} differs"


# ---- donor-band width math (attack 2) ---------------------------------------

def test_collapse_band_width_ladder():
    from parmmg_tpu.ops.collapse import collapse_band_width
    from parmmg_tpu.utils.compilecache import bucket

    # the band width IS a rung of the shared geo bucket ladder — no new
    # shape family can come out of it
    for capT in (64, 256, 1024, 4096, 12288, 100000):
        B = collapse_band_width(capT)
        assert B == bucket(max(1, capT // 4), floor=256, scheme="geo",
                           cap=capT)
        assert B <= capT
    # tiny meshes: the ladder reaches capT and the full path is taken
    assert collapse_band_width(64) == 64
    assert collapse_band_width(256) == 256
    # big meshes: the band is a strict compaction
    assert collapse_band_width(12288) < 12288
    # monotone in capT (no oscillating shape families across regrows)
    widths = [collapse_band_width(c) for c in range(64, 20000, 64)]
    assert all(a <= b for a, b in zip(widths, widths[1:]))


# ---- fused top-k scoring prep (attack 4) ------------------------------------

def _prep_ref(c, v):
    return jnp.where(c, -v, -jnp.inf), jnp.sum(c.astype(jnp.int32))


def test_topk_prep_matches_inline(monkeypatch):
    from parmmg_tpu.ops.edges import topk_prep, topk_prep3
    rng = np.random.default_rng(7)
    c = jnp.asarray(rng.random(777) > 0.6)
    v0, v1, v2 = (jnp.asarray(rng.random(777).astype(np.float32))
                  for _ in range(3))
    for env in (None, "1"):
        if env is None:
            monkeypatch.delenv("PARMMG_TPU_PALLAS", raising=False)
        else:
            # forced mode: the off-TPU branch runs the interpret-mode
            # Pallas kernels — must still be bit-identical
            monkeypatch.setenv("PARMMG_TPU_PALLAS", env)
        neg, n = topk_prep(c, v0)
        rneg, rn = _prep_ref(c, v0)
        assert (np.asarray(neg) == np.asarray(rneg)).all(), env
        assert int(n) == int(rn)
        neg3, n3 = topk_prep3(c, v0, v1, v2)
        # exact legacy association order: min(v0, min(v1, v2))
        rneg3, rn3 = _prep_ref(c, jnp.minimum(v0, jnp.minimum(v1, v2)))
        assert (np.asarray(neg3) == np.asarray(rneg3)).all(), env
        assert int(n3) == int(rn3)


def test_score_kernels_interpret_parity():
    from parmmg_tpu.ops.pallas_kernels import (score3_count_pallas,
                                               score_count_pallas)
    rng = np.random.default_rng(11)
    for n in (1, 127, 128, 1000):
        v = jnp.asarray(rng.random(n).astype(np.float32))
        for mask in (rng.random(n) > 0.5, np.zeros(n, bool),
                     np.ones(n, bool)):
            c = jnp.asarray(mask)
            neg, cnt = score_count_pallas(c.astype(jnp.float32), v,
                                          interpret=True)
            rneg, rcnt = _prep_ref(c, v)
            assert (np.asarray(neg) == np.asarray(rneg)).all()
            assert int(cnt) == int(rcnt) == int(mask.sum())
        v1 = jnp.asarray(rng.random(n).astype(np.float32))
        v2 = jnp.asarray(rng.random(n).astype(np.float32))
        c = jnp.asarray(rng.random(n) > 0.3)
        neg3, cnt3 = score3_count_pallas(c.astype(jnp.float32), v, v1,
                                         v2, interpret=True)
        rneg3, rcnt3 = _prep_ref(c, jnp.minimum(v, jnp.minimum(v1, v2)))
        assert (np.asarray(neg3) == np.asarray(rneg3)).all()
        assert int(cnt3) == int(rcnt3)


# ---- face-pair table off the sort (attack 1) --------------------------------

def test_face_pairs_match_adja():
    from parmmg_tpu.ops.quality import quality_from_points
    from parmmg_tpu.ops.swap import (_met6, _pair_fields_adja,
                                     _pair_fields_facesort)
    for m in (_cube(2), _cube(3)):
        m = build_adjacency(m)
        met = jnp.full(m.capP, 0.8, m.vert.dtype)
        m6 = _met6(met)
        q_tet = quality_from_points(
            m.vert[m.tet], None if m6 is None else m6[m.tet])
        ref = _pair_fields_adja(m, q_tet, m.capT)
        m2, *got = _pair_fields_facesort(m, q_tet, m.capT, True)
        # the candidate set must agree EVERYWHERE; t2/f2 carry dead
        # fill on non-candidate rows (different fill per path, never
        # consumed: every downstream read in swap23_wave is gated by
        # cand — q_pair, the fan construction and all scatters)
        cand = np.asarray(ref[3])
        assert (cand == np.asarray(got[3])).all(), \
            "facesort candidate set differs from adja pairing"
        assert (np.asarray(got[0]) == np.asarray(ref[0])).all(), \
            "facesort fstar differs from adja pairing"
        for name, a, b in zip(("t2", "f2"), got[1:], ref[1:]):
            assert (np.asarray(a)[cand] == np.asarray(b)[cand]).all(), \
                f"facesort pair field {name} differs on candidate rows"
        # the MG_BDY replay off the same sort is idempotent on a mesh
        # whose tags build_adjacency already set
        _assert_mesh_equal(m2, m, "bdy-tag replay")


def test_knob_readers_default_on(monkeypatch):
    from parmmg_tpu.ops.pallas_kernels import pallas_score_enabled
    from parmmg_tpu.ops.swap import swap_facesort_enabled
    from parmmg_tpu.parallel.sched import cadence_enabled
    for name, fn in (("PARMMG_SMOOTH_CADENCE", cadence_enabled),
                     ("PARMMG_PALLAS_SCORE", pallas_score_enabled)):
        monkeypatch.delenv(name, raising=False)
        assert fn() is True, f"{name} must default on"
        monkeypatch.setenv(name, "0")
        assert fn() is False
        monkeypatch.setenv(name, "1")
        assert fn() is True
    # facesort defaults platform-aware: on iff the backend is a TPU
    # (the CPU sort costs more than the adja rebuild it replaces);
    # explicit 1/0 force either path on any backend
    monkeypatch.delenv("PARMMG_SWAP_FACESORT", raising=False)
    assert swap_facesort_enabled() is (jax.default_backend() == "tpu")
    monkeypatch.setenv("PARMMG_SWAP_FACESORT", "0")
    assert swap_facesort_enabled() is False
    monkeypatch.setenv("PARMMG_SWAP_FACESORT", "1")
    assert swap_facesort_enabled() is True
    # the sort engine knob has the same platform-aware contract
    from parmmg_tpu.ops.pallas_kernels import pallas_sort_enabled
    monkeypatch.delenv("PARMMG_PALLAS_SORT", raising=False)
    assert pallas_sort_enabled() is (jax.default_backend() == "tpu")
    monkeypatch.setenv("PARMMG_PALLAS_SORT", "0")
    assert pallas_sort_enabled() is False
    monkeypatch.setenv("PARMMG_PALLAS_SORT", "1")
    assert pallas_sort_enabled() is True


# ---- smoothing cadence (attack 3) -------------------------------------------

def test_fused_cadence_parity():
    """cadence-on vs cadence-off over a fused block is bit-identical:
    the skip only ever fires where smoothing is a proven identity."""
    from parmmg_tpu.ops.adapt import adapt_cycles_fused_impl
    m = _cube(2)
    met = jnp.full(m.capP, 0.75, m.vert.dtype)
    w0 = jnp.asarray(0, jnp.int32)

    run_off = jax.jit(partial(adapt_cycles_fused_impl, n_cycles=3))
    run_on = jax.jit(lambda mm, kk, ww, cad: adapt_cycles_fused_impl(
        mm, kk, ww, n_cycles=3, cadence=cad))
    m_off, k_off, c_off = run_off(m, met, w0)
    m_on, k_on, c_on = run_on(m, met, w0, jnp.asarray(True))
    _assert_mesh_equal(m_off, m_on, "cadence")
    assert (np.asarray(k_off) == np.asarray(k_on)).all()
    assert (np.asarray(c_off) == np.asarray(c_on)).all()
    # cadence=False through the SAME gated program is the off arm too
    m_f, k_f, c_f = run_on(m, met, w0, jnp.asarray(False))
    _assert_mesh_equal(m_off, m_f, "cadence=False scalar")
    assert (np.asarray(c_off) == np.asarray(c_f)).all()


# ---- slow per-knob wave parity ----------------------------------------------

@pytest.mark.slow
def test_facesort_knob_parity(monkeypatch):
    """PARMMG_SWAP_FACESORT on/off through the full adaptation cycle
    AND the sliver polish pass (polish-on) is bit-for-bit identical."""
    from parmmg_tpu.ops.adapt import adapt_cycle_impl, sliver_polish_impl
    m = _cube(2)
    met = jnp.full(m.capP, 0.6, m.vert.dtype)
    outs = []
    for env in ("0", "1"):
        monkeypatch.setenv("PARMMG_SWAP_FACESORT", env)
        # fresh partial per arm: a fresh trace re-reads the env knob
        cyc = jax.jit(partial(adapt_cycle_impl, do_swap=True))
        mm, kk, cc = cyc(m, met, jnp.asarray(0, jnp.int32))
        pol = jax.jit(partial(sliver_polish_impl))
        mp, cp = pol(mm, kk, jnp.asarray(100, jnp.int32))
        outs.append((mm, kk, cc, mp, cp))
    (m0, k0, c0, p0, q0), (m1, k1, c1, p1, q1) = outs
    _assert_mesh_equal(m0, m1, "facesort cycle")
    assert (np.asarray(k0) == np.asarray(k1)).all()
    assert (np.asarray(c0) == np.asarray(c1)).all()
    _assert_mesh_equal(p0, p1, "facesort polish")
    assert (np.asarray(q0) == np.asarray(q1)).all()


@pytest.mark.slow
def test_collapse_band_knob_parity(monkeypatch):
    """PARMMG_COLLAPSE_BAND on/off through collapse waves that engage
    the band (B < capT) is bit-for-bit identical."""
    from parmmg_tpu.ops.collapse import collapse_band_width, collapse_wave
    m0 = _cube(3, capmul=8)
    assert collapse_band_width(m0.capT) < m0.capT, \
        "fixture too small: the band is not engaged"
    met = jnp.full(m0.capP, 2.0)         # everything is "too short"
    states = []
    for env in ("0", "1"):
        monkeypatch.setenv("PARMMG_COLLAPSE_BAND", env)
        m = m0
        ns = []
        for _ in range(4):
            wave = jax.jit(partial(collapse_wave))
            res = wave(m, met)
            m = build_adjacency(res.mesh)
            ns.append(int(res.ncollapse))
        states.append((m, ns))
    (ma, na), (mb, nb) = states
    assert na == nb and sum(na) > 0, (na, nb)
    _assert_mesh_equal(ma, mb, "collapse band")


@pytest.mark.slow
def test_pallas_forced_wave_parity(monkeypatch):
    """PARMMG_TPU_PALLAS=1 (forced interpret-mode kernels inside
    topk_prep) leaves split/collapse/swap waves bit-identical."""
    from parmmg_tpu.ops.collapse import collapse_wave
    from parmmg_tpu.ops.split import split_wave
    from parmmg_tpu.ops.swap import swap23_wave
    m = build_adjacency(_cube(2))
    met_s = jnp.full(m.capP, 0.3, m.vert.dtype)   # split-rich
    met_c = jnp.full(m.capP, 2.0, m.vert.dtype)   # collapse-rich
    outs = []
    for env in (None, "1"):
        if env is None:
            monkeypatch.delenv("PARMMG_TPU_PALLAS", raising=False)
        else:
            monkeypatch.setenv("PARMMG_TPU_PALLAS", env)
        sp = jax.jit(partial(split_wave))(m, met_s)
        co = jax.jit(partial(collapse_wave))(m, met_c)
        sw = jax.jit(partial(swap23_wave))(m, met_s)
        outs.append((sp, co, sw))
    a, b = outs
    for name, ra, rb in zip(("split", "collapse", "swap23"), a, b):
        _assert_mesh_equal(ra.mesh, rb.mesh, f"pallas-forced {name}")


@pytest.mark.slow
def test_pallas_sort_forced_wave_parity(monkeypatch):
    """PARMMG_TPU_PALLAS=1 + PARMMG_PALLAS_SORT=1 routes every sort
    site (unique_edges, priority, face sort, band sorts) through the
    interpret-mode radix/segment kernels; a full adapt cycle must stay
    bit-identical to the jnp-reference run."""
    from parmmg_tpu.ops.adapt import adapt_cycle
    m0 = _cube(2)
    met0 = jnp.full(m0.capP, 0.5, m0.vert.dtype)
    outs = []
    for on in (False, True):
        if on:
            monkeypatch.setenv("PARMMG_TPU_PALLAS", "1")
            monkeypatch.setenv("PARMMG_PALLAS_SORT", "1")
        else:
            monkeypatch.delenv("PARMMG_TPU_PALLAS", raising=False)
            monkeypatch.setenv("PARMMG_PALLAS_SORT", "0")
        m = jax.tree.map(jnp.copy, m0)
        met = jnp.copy(met0)
        m, met, cnt = adapt_cycle(m, met, jnp.asarray(0, jnp.int32))
        outs.append((m, np.asarray(met), np.asarray(cnt)))
    (ma, ka, ca), (mb, kb, cb) = outs
    _assert_mesh_equal(ma, mb, "pallas-sort forced cycle")
    assert np.array_equal(ka, kb)
    assert np.array_equal(ca, cb)
