"""Device-resident cross-shard analysis vs the host-numpy path.

Contract: parallel/analysis_dev.py must produce exactly the tags the
host refresh (parallel/dist.refresh_shard_analysis over
analysis_par.analyze_shards) produces — ridge (MG_GEO), reference
(MG_REF), corner (MG_CRN) and non-manifold (MG_NOM) classification with
cross-interface dihedrals, plus the plain-boundary stale-bit clearing.
"""
import numpy as np
import jax
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh, mesh_to_host
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.parallel.analysis_par import extend_numbering
from parmmg_tpu.parallel.comms import build_interface_comms
from parmmg_tpu.parallel.dist import (
    make_device_mesh, refresh_shard_analysis,
    refresh_shard_analysis_device, shard_stacked)
from parmmg_tpu.parallel.distribute import split_to_shards
from parmmg_tpu.parallel.partition import morton_partition, fix_contiguity
from parmmg_tpu.utils.fixtures import cube_mesh


def _setup(n=4, nparts=4):
    import dataclasses
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    # two material refs -> MG_REF edges where the surface refs differ
    tref = 1 + (vert[tet].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    trf = np.zeros(m.capT, np.int32)
    trf[: len(tet)] = tref
    m = dataclasses.replace(m, tref=jnp.asarray(trf))
    m = analyze_mesh(m).mesh
    # per-material surface refs: boundary faces inherit their tet's ref,
    # so surface edges on the material line see differing frefs (MG_REF)
    is_b = (np.asarray(m.ftag) & C.MG_BDY) != 0
    frf = np.where(is_b, trf[:, None], np.asarray(m.fref))
    m = dataclasses.replace(m, fref=jnp.asarray(frf.astype(np.int32)))
    met = jnp.full(m.capP, 0.4, m.vert.dtype)
    vert_h, tet_h, _, _, _ = mesh_to_host(m)
    cent = vert_h[tet_h].mean(axis=1)
    part = fix_contiguity(tet_h, morton_partition(cent, nparts))
    s, ms, l2g = split_to_shards(m, met, part, nparts, return_l2g=True)
    g2l = []
    for s_ in range(nparts):
        mm = np.full(len(vert_h), -1, np.int64)
        mm[l2g[s_]] = np.arange(len(l2g[s_]))
        g2l.append(mm)
    comms = build_interface_comms(tet_h, part, nparts, l2g, g2l)
    return s, ms, comms, nparts


def test_device_analysis_matches_host():
    s, ms, comms, S = _setup()
    dmesh = make_device_mesh(S)
    stacked = shard_stacked(s, dmesh)
    capP = stacked.vert.shape[1]
    glo = extend_numbering(comms, [capP] * S)
    ang = C.ANGEDG

    host_out = refresh_shard_analysis(stacked, comms, S, ang,
                                      glo=[g.copy() for g in glo])
    dev_out = refresh_shard_analysis_device(stacked, comms, S, ang,
                                            glo, dmesh)
    assert dev_out is not None, "device path overflowed its budget"

    vm = np.asarray(stacked.vmask)
    tm = np.asarray(stacked.tmask)
    vt_h = np.asarray(host_out.vtag)
    vt_d = np.asarray(dev_out.vtag)
    et_h = np.asarray(host_out.etag)
    et_d = np.asarray(dev_out.etag)
    for sh in range(S):
        bad_v = np.where(vm[sh] & (vt_h[sh] != vt_d[sh]))[0]
        assert len(bad_v) == 0, (
            f"shard {sh}: {len(bad_v)} vtag mismatches, first "
            f"{bad_v[:5]}: host {vt_h[sh][bad_v[:5]]} "
            f"dev {vt_d[sh][bad_v[:5]]}")
        bad_e = np.where((et_h[sh] != et_d[sh]) & tm[sh][:, None])
        assert len(bad_e[0]) == 0, (
            f"shard {sh}: {len(bad_e[0])} etag mismatches, first "
            f"{[(int(a), int(b)) for a, b in zip(*[x[:5] for x in bad_e])]}"
            f": host {et_h[sh][bad_e][:5]} dev {et_d[sh][bad_e][:5]}")


def test_device_analysis_classifies_ridges_and_refs():
    """Independent of the host path: cube ridges crossing shard
    boundaries must be MG_GEO, material-boundary surface edges MG_REF,
    cube corners MG_CRN."""
    s, ms, comms, S = _setup()
    dmesh = make_device_mesh(S)
    stacked = shard_stacked(s, dmesh)
    capP = stacked.vert.shape[1]
    glo = extend_numbering(comms, [capP] * S)
    dev_out = refresh_shard_analysis_device(stacked, comms, S, C.ANGEDG,
                                            glo, dmesh)
    assert dev_out is not None
    vm = np.asarray(stacked.vmask)
    vt = np.asarray(dev_out.vtag)
    verts = np.asarray(stacked.vert)
    n_geo = n_crn = 0
    for sh in range(S):
        v = verts[sh][vm[sh]]
        t = vt[sh][vm[sh]]
        on_edge = ((np.isclose(v, 0) | np.isclose(v, 1)).sum(axis=1) >= 2)
        corner = ((np.isclose(v, 0) | np.isclose(v, 1)).sum(axis=1) == 3)
        # cube corners are corners; cube-edge (non-corner) vertices are
        # ridge points unless the material line promotes them
        n_crn += int((t[corner] & C.MG_CRN != 0).sum())
        geo_pts = on_edge & ~corner
        n_geo += int(((t[geo_pts] & (C.MG_GEO | C.MG_CRN)) != 0).sum())
        assert ((t[corner] & C.MG_CRN) != 0).all()
        assert ((t[geo_pts] & (C.MG_GEO | C.MG_CRN)) != 0).all()
    assert n_geo > 0 and n_crn > 0
    # MG_REF must exist somewhere (the material interface meets the hull)
    total_ref = sum(int(((vt[sh][vm[sh]] & C.MG_REF) != 0).sum())
                    for sh in range(S))
    assert total_ref > 0
