"""Two-level group decomposition tests (parallel/groups.py).

Reference semantics: ``-mesh-size`` bounds the per-group element count
(howManyGroups, grpsplit_pmmg.c:47,1589-1614); groups are remeshed with
their seams frozen, seams are displaced between iterations.  Gates are
quality/conformity, not exit codes.
"""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.parallel.groups import how_many_groups, grouped_adapt
from parmmg_tpu.utils.fixtures import cube_mesh
import pytest


def test_how_many_groups_clamps():
    assert how_many_groups(100, 0) == 1
    assert how_many_groups(100, 1000) == 1
    assert how_many_groups(1000, 100) == 10
    assert how_many_groups(10 ** 9, 10) == C.REMESHER_NGRPS_MAX


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_grouped_adapt_conforming():
    vert, tet = cube_mesh(3)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.3, m.vert.dtype)
    ne = len(tet)
    out, met2 = grouped_adapt(m, met, target_size=ne // 4, niter=2,
                              cycles=3)
    out = build_adjacency(out)
    assert check_adjacency(out) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    q = np.asarray(tet_quality(out, met2))[np.asarray(out.tmask)]
    assert q.min() > 0.02


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_grouped_chunked_matches_unchunked(monkeypatch):
    """Chunked group dispatch (group_chunk: the tunnel-safe bounded
    dispatch) must produce the same mesh as one lax.map over all
    groups: the per-group program is identical, chunking only changes
    how many groups one dispatch covers, and the dead pad groups are
    no-ops."""
    from parmmg_tpu.parallel.groups import grouped_adapt_pass

    vert, tet = cube_mesh(3)

    def run():
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.full(m.capP, 0.35, m.vert.dtype)
        out, met2, _ = grouped_adapt_pass(m, met, 4, cycles=2)
        return out

    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "0")
    ref = run()
    # chunk=3 on 4 groups: pads to 6 with 2 dead groups
    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "3")
    chk = run()
    tm_r, tm_c = np.asarray(ref.tmask), np.asarray(chk.tmask)
    assert tm_r.sum() == tm_c.sum()
    assert (np.asarray(ref.tet)[tm_r] == np.asarray(chk.tet)[tm_c]).all()
    vr = np.asarray(ref.vert)[np.asarray(ref.vmask)]
    vc = np.asarray(chk.vert)[np.asarray(chk.vmask)]
    assert vr.shape == vc.shape and (vr == vc).all()


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_mesh_size_engages_groups():
    """Setting IParam.meshSize below the mesh size must route the
    single-device run through the grouped path."""
    from parmmg_tpu.api import ParMesh, IParam
    from parmmg_tpu.parallel import groups as G

    called = {"n": 0}
    orig = G.grouped_adapt

    def counting(*a, **k):
        called["n"] += 1
        return orig(*a, **k)

    G.grouped_adapt = counting
    try:
        vert, tet = cube_mesh(2)
        pm = ParMesh()
        pm.set_mesh_size(np_=len(vert), ne=len(tet))
        pm.set_vertices(vert)
        pm.set_tetrahedra(tet + 1)
        pm.set_met_size(1, len(vert))
        pm.set_scalar_mets(np.full(len(vert), 0.4))
        pm.set_iparameter(IParam.niter, 1)
        pm.set_iparameter(IParam.meshSize, len(tet) // 3)
        assert pm.run() == C.PMMG_SUCCESS
    finally:
        G.grouped_adapt = orig
    assert called["n"] == 1
    v, _ = pm.get_vertices()
    t, _ = pm.get_tetrahedra()
    p = v[t - 1]
    vol = np.einsum("ti,ti->t", p[:, 1] - p[:, 0],
                    np.cross(p[:, 2] - p[:, 0], p[:, 3] - p[:, 0])) / 6
    assert (vol > 0).all()
    assert np.isclose(vol.sum(), 1.0, rtol=1e-4)
