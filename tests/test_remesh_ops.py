"""M1 tests: collapse, swap, smooth waves and the full adapt driver."""
import dataclasses

import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import (
    build_adjacency, check_adjacency, boundary_edge_tags)
from parmmg_tpu.ops.collapse import collapse_wave
from parmmg_tpu.ops.swap import swap23_wave, swap32_wave
from parmmg_tpu.ops.smooth import smooth_wave
from parmmg_tpu.ops.adapt import adapt_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.ops.edges import unique_edges, edge_lengths
from parmmg_tpu.utils.fixtures import cube_mesh
import pytest


def _cube(n=2, capmul=4):
    from parmmg_tpu.ops.analysis import analyze_mesh
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert), capT=capmul * len(tet))
    return analyze_mesh(m).mesh


def _check_valid(m, vol_target=1.0):
    m = build_adjacency(m)
    assert check_adjacency(m) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), vol_target, rtol=1e-4)
    return m


def test_collapse_coarsens():
    m = _cube(3)
    npoin0, nelem0 = m.np_counts()
    met = jnp.full(m.capP, 2.0)          # everything is "too short"
    total = 0
    for _ in range(10):
        res = collapse_wave(m, met)
        m = build_adjacency(res.mesh)
        n = int(res.ncollapse)
        total += n
        if n == 0:
            break
    assert total > 0
    npoin1, nelem1 = m.np_counts()
    assert npoin1 < npoin0
    assert nelem1 < nelem0
    _check_valid(m)


def test_collapse_keeps_corners():
    m = _cube(2)
    met = jnp.full(m.capP, 5.0)
    for _ in range(12):
        res = collapse_wave(m, met)
        m = build_adjacency(res.mesh)
        if int(res.ncollapse) == 0:
            break
    m = _check_valid(m)
    # the 8 cube corners can never be removed (they are surface extreme
    # points; interior collapse of them would pull in the boundary)
    vert = np.asarray(m.vert)[np.asarray(m.vmask)]
    for corner in [(0, 0, 0), (1, 1, 1), (0, 1, 0), (1, 0, 1)]:
        d = np.abs(vert - np.array(corner)).sum(axis=1).min()
        assert d < 1e-6, f"corner {corner} was collapsed away"


def test_smooth_improves_quality():
    m = _cube(3)
    # jitter interior points to damage quality
    rng = np.random.default_rng(0)
    vert = np.asarray(m.vert).copy()
    vm = np.asarray(m.vmask)
    interior = vm & ~(((vert == 0) | (vert == 1)).any(axis=1))
    vert[interior] += rng.uniform(-0.08, 0.08, (interior.sum(), 3))
    m = dataclasses.replace(m, vert=jnp.asarray(vert))
    met = jnp.full(m.capP, 1 / 3)
    q0 = float(jnp.min(jnp.where(m.tmask, tet_quality(m), jnp.inf)))
    moved = 0
    for w in range(6):
        res = smooth_wave(m, met, wave=w)
        m = res.mesh
        moved += int(res.nmoved)
    q1 = float(jnp.min(jnp.where(m.tmask, tet_quality(m), jnp.inf)))
    assert moved > 0
    assert q1 > q0
    _check_valid(m)


def test_swap_on_bad_config():
    # two tets sharing a face, nearly degenerate, where 2-3 swap helps:
    # thin "roof" pair
    vert = np.array([
        [0, 0, 0], [1, 0, 0], [0.5, 1, 0.05],   # shared face, nearly flat
        [0.5, 0.4, -0.6], [0.5, 0.4, 0.7],
    ])
    tet = np.array([[0, 1, 2, 4], [1, 0, 2, 3]], np.int32)
    m = make_mesh(vert, tet, capP=32, capT=32)
    m = build_adjacency(m)
    met = jnp.full(m.capP, 0.8)
    q0 = float(jnp.min(jnp.where(m.tmask, tet_quality(m), jnp.inf)))
    res = swap23_wave(m, met)
    if int(res.nswap):
        m2 = build_adjacency(res.mesh)
        assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
        vols0 = np.asarray(tet_volumes(m))[np.asarray(m.tmask)].sum()
        vols1 = np.asarray(tet_volumes(m2))[np.asarray(m2.tmask)]
        assert (vols1 > 0).all()
        assert np.isclose(vols1.sum(), vols0, rtol=1e-5)
        q1 = float(jnp.min(jnp.where(m2.tmask, tet_quality(m2), jnp.inf)))
        assert q1 > q0


def test_swap32_reduces_shell():
    # 3 tets around an interior edge (a,b), ring p,q,r
    a, b = [0.5, 0.5, -1.0], [0.5, 0.5, 1.0]
    p, q, r = [0, 0, 0], [1, 0, 0], [0.5, 1.2, 0]
    vert = np.array([a, b, p, q, r])
    # shell tets: (a,b) edge with ring pairs (p,q),(q,r),(r,p)
    tet = np.array([[0, 1, 2, 3], [0, 1, 3, 4], [0, 1, 4, 2]], np.int32)
    # fix orientation
    from parmmg_tpu.utils.fixtures import _orient_positive
    tet = _orient_positive(vert, tet)
    m = make_mesh(vert, tet, capP=32, capT=32)
    m = build_adjacency(m)
    met = jnp.full(m.capP, 1.0)
    res = swap32_wave(m, met)
    # the ring triangle is large relative to the edge: swap should trigger
    if int(res.nswap):
        m2 = build_adjacency(res.mesh)
        assert m2.np_counts()[1] == 2
        assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
        vols0 = np.asarray(tet_volumes(m))[np.asarray(m.tmask)].sum()
        vols1 = np.asarray(tet_volumes(m2))[np.asarray(m2.tmask)]
        assert (vols1 > 0).all()
        assert np.isclose(vols1.sum(), vols0, rtol=1e-5)


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_adapt_refine_and_coarsen_roundtrip():
    m = _cube(2)
    met = jnp.full(m.capP, 0.2)
    m, met, st = adapt_mesh(m, met, max_cycles=20)
    assert st.nsplit > 0
    m = _check_valid(m)
    n_ref = m.np_counts()
    # now coarsen back
    met2 = jnp.where(m.vmask, 0.9, met)
    m2, met2, st2 = adapt_mesh(m, met2, max_cycles=20)
    assert st2.ncollapse > 0
    m2 = _check_valid(m2)
    assert m2.np_counts()[0] < n_ref[0]


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_adapt_target_lengths():
    m = _cube(2)
    met = jnp.full(m.capP, 0.23)
    m, met, st = adapt_mesh(m, met, max_cycles=25)
    m = _check_valid(m)
    et = unique_edges(m)
    lens = np.asarray(edge_lengths(m, et, met))[np.asarray(et.emask)]
    # no edge above the split threshold; most edges in the good range
    assert lens.max() < C.LLONG + 1e-4
    q = np.asarray(tet_quality(m))[np.asarray(m.tmask)]
    assert q.min() > 0.1


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_sliver_polish_improves_min_quality():
    """The bad-element pass (sliver_polish) must raise the min quality of
    a converged adaptation without breaking validity or volume — the
    MMG3D_opttyp contract."""
    from parmmg_tpu.ops.adapt import sliver_polish, adapt_cycle
    m = _cube(3)
    met = jnp.full(m.capP, 0.35, jnp.float32)
    # a couple of sizing cycles leave a non-uniform state
    for c in range(3):
        m, met, _ = adapt_cycle(m, met, jnp.asarray(c, jnp.int32),
                                do_swap=(c == 2))
    q0 = np.asarray(tet_quality(m))
    tm0 = np.asarray(m.tmask)
    qmin0 = q0[tm0].min()
    for w in range(3):
        m, counts = sliver_polish(m, met, jnp.asarray(w, jnp.int32))
        if int(np.asarray(counts)[0]) == 0 and \
                int(np.asarray(counts)[1]) == 0:
            break
    m = _check_valid(m)                 # conforming + volume preserved
    q1 = np.asarray(tet_quality(m))
    tm1 = np.asarray(m.tmask)
    assert q1[tm1].min() >= qmin0 - 1e-6
