"""Host-only tests for the pod runtime (parallel/pod.py): the band
exchange plan (family keys, packing/pad semantics, fault ladder), the
pull_host hot-path meter, the glo-mirror delta-sync helpers and the
host-to-host group-handoff plan.  Everything here is numpy / host
bookkeeping — no compiled exchange runs (the 2-process collective path
is run_tests.sh --multihost; the in-process fault arms are --chaos)."""
from __future__ import annotations

import os

import numpy as np
import pytest

from parmmg_tpu.parallel import pod
from parmmg_tpu.parallel.multihost import (_note_allgather, cold_io,
                                           hot_path, in_hot_path)
from parmmg_tpu.resilience.faults import FAULTS


@pytest.fixture
def fault_env():
    """Scoped PARMMG_* overrides + fault-registry reset both ways."""
    saved = {}

    def set_env(**kv):
        for k, v in kv.items():
            saved.setdefault(k, os.environ.get(k))
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        FAULTS.reset()

    yield set_env
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    FAULTS.reset()


def counters():
    from parmmg_tpu.obs.metrics import REGISTRY
    return dict(REGISTRY.snapshot()["counters"])


# ---------------------------------------------------------------------------
# exchange plan: family keys + anti-churn bucketing
# ---------------------------------------------------------------------------
def test_exchange_key_stable_and_distinct():
    a = np.zeros((4, 8), np.int32)
    b = np.zeros((4,), np.int32)
    k1 = pod.exchange_key((a, b))
    k2 = pod.exchange_key((np.ones((4, 8), np.int32),
                           np.ones((4,), np.int32)))
    assert k1 == k2                      # values never key a family
    assert pod.exchange_key((a,)) != k1
    assert pod.exchange_key((a.astype(np.int64), b)) != k1
    assert pod.exchange_key((np.zeros((4, 16), np.int32), b)) != k1


def test_exchange_families_ride_the_comm_table_ladders():
    """Drifting interface sizes must land on ONE exchange family: the
    comm tables are bucketed by pad_comm_tables' geo/pow2 ladders, so
    the (shape, dtype) exchange keys they produce are churn-free."""
    from parmmg_tpu.parallel.comms import pad_comm_tables
    keys = set()
    for n_items in (33, 41, 57, 60):     # drifts within one geo bucket
        nl = [[[], list(range(n_items))], [list(range(n_items)), []]]
        fl = [[[], list(range(n_items))], [list(range(n_items)), []]]
        ow = [np.zeros(8, np.int32), np.zeros(8, np.int32)]
        c = pad_comm_tables(nl, fl, ow, 2)
        keys.add(pod.exchange_key((c.node_idx, c.face_idx, c.nbr)))
    assert len(keys) == 1, keys


# ---------------------------------------------------------------------------
# gather_band: degenerate exchange + fault ladder (single-process arm)
# ---------------------------------------------------------------------------
def test_gather_band_passthrough_bit_identity():
    a = np.arange(12, dtype=np.int32).reshape(4, 3)
    b = np.arange(4, dtype=np.float64)
    ga, gb = pod.gather_band(a, b, what="t")
    assert ga.tobytes() == a.tobytes() and gb.tobytes() == b.tobytes()
    # single input returns the bare array, not a 1-tuple
    g = pod.gather_band(a)
    assert isinstance(g, np.ndarray) and g.tobytes() == a.tobytes()


def test_gather_band_transient_fault_retries(fault_env):
    fault_env(PARMMG_FAULT="multihost.exchange:nth-1",
              PARMMG_RETRY_MAX="2", PARMMG_RETRY_BASE_S="0")
    a = np.arange(8, dtype=np.int32)
    c0 = counters()
    out = pod.gather_band(a, what="t")
    assert out.tobytes() == a.tobytes()
    c1 = counters()
    assert c1.get("resilience.faults_injected", 0) \
        > c0.get("resilience.faults_injected", 0)
    assert c1.get("resilience.retry", 0) > c0.get("resilience.retry", 0)


def test_gather_band_exhaustion_takes_the_metered_hatch(fault_env):
    fault_env(PARMMG_FAULT="multihost.exchange",
              PARMMG_RETRY_MAX="0", PARMMG_RETRY_BASE_S="0")
    a = np.arange(8, dtype=np.int32)
    c0 = counters()
    out = pod.gather_band(a, what="t")
    assert out.tobytes() == a.tobytes()      # bit-identical fallback
    c1 = counters()
    assert c1.get("resilience.mh_allgather", 0) \
        > c0.get("resilience.mh_allgather", 0)


def test_gather_band_key_matched_fault_only_fires_on_its_site(
        fault_env):
    fault_env(PARMMG_FAULT="multihost.exchange:key=extend",
              PARMMG_RETRY_MAX="0", PARMMG_RETRY_BASE_S="0")
    a = np.arange(4, dtype=np.int32)
    c0 = counters()
    pod.gather_band(a, what="faces")         # non-matching: clean
    assert counters().get("resilience.mh_allgather", 0) \
        == c0.get("resilience.mh_allgather", 0)
    pod.gather_band(a, what="extend")        # matching: ladder
    assert counters().get("resilience.mh_allgather", 0) \
        > c0.get("resilience.mh_allgather", 0)


# ---------------------------------------------------------------------------
# pull_host hot-path meter
# ---------------------------------------------------------------------------
def test_hot_path_nesting_and_cold_io_exemption():
    assert not in_hot_path()
    with hot_path():
        assert in_hot_path()
        with hot_path():
            assert in_hot_path()
            with cold_io():
                assert not in_hot_path()
            assert in_hot_path()
        assert in_hot_path()
    assert not in_hot_path()


def test_allgather_meter_counts_total_and_hot(fault_env):
    fault_env(PARMMG_MH_STRICT=None)
    c0 = counters()
    _note_allgather(100, "cold")
    c1 = counters()
    assert c1.get("mh.allgather_bytes", 0) \
        == c0.get("mh.allgather_bytes", 0) + 100
    assert c1.get("mh.hot_allgather_bytes", 0) \
        == c0.get("mh.hot_allgather_bytes", 0)
    with hot_path():
        _note_allgather(7, "hot")
    c2 = counters()
    assert c2.get("mh.hot_allgather_bytes", 0) \
        == c1.get("mh.hot_allgather_bytes", 0) + 7


def test_strict_knob_trips_on_hot_allgather_only(fault_env):
    fault_env(PARMMG_MH_STRICT="1")
    _note_allgather(1, "cold-ok")            # outside hot path: metered
    with hot_path():
        with pytest.raises(RuntimeError, match="PARMMG_MH_STRICT"):
            _note_allgather(1, "hot-trip")
        with cold_io():
            _note_allgather(1, "ckpt-ok")    # exempted IO section


# ---------------------------------------------------------------------------
# glo-mirror delta sync (the O(mesh)-allgather replacement)
# ---------------------------------------------------------------------------
def test_mirror_delta_sync_matches_full_mask_semantics():
    from parmmg_tpu.parallel.migrate import apply_fresh_ids, kill_glo_rows
    rng = np.random.default_rng(0)
    capP, S = 32, 3
    glo = [np.where(rng.random(capP) < 0.6,
                    np.arange(capP, dtype=np.int64) + 100 * s,
                    -1) for s in range(S)]
    ref = [g.copy() for g in glo]
    vmask = [g >= 0 for g in glo]
    # kill some live rows; reference semantics: glo[~vmask] = -1
    dead_rows = np.full((S, 8), capP, np.int32)
    dead_cnt = np.zeros(S, np.int32)
    for s in range(S):
        live = np.where(vmask[s])[0]
        kill = live[:3]
        vmask[s][kill] = False
        dead_rows[s, :3] = kill
        dead_cnt[s] = 3
        ref[s][~vmask[s]] = -1
    kill_glo_rows(glo, dead_rows, dead_cnt)
    for s in range(S):
        np.testing.assert_array_equal(glo[s], ref[s])
    # fresh-id application ignores -1 pads
    rows = np.full((S, 4), -1, np.int32)
    gids = np.full((S, 4), -1, np.int32)
    rows[0, :2] = [1, 2]
    gids[0, :2] = [9001, 9002]
    apply_fresh_ids(glo, rows, gids)
    assert glo[0][1] == 9001 and glo[0][2] == 9002
    np.testing.assert_array_equal(glo[1], ref[1])


def test_kill_glo_rows_tolerates_pads_and_out_of_range():
    from parmmg_tpu.parallel.migrate import kill_glo_rows
    glo = [np.arange(8, dtype=np.int64)]
    rows = np.array([[2, -1, 8, 99]], np.int32)   # pad / oob ignored
    kill_glo_rows(glo, rows, np.array([4], np.int32))
    assert glo[0][2] == -1
    assert (glo[0][[0, 1, 3, 4, 5, 6, 7]] >= 0).all()


# ---------------------------------------------------------------------------
# group handoff: plan + comm-table permutation
# ---------------------------------------------------------------------------
def test_plan_handoff_balances_skewed_loads():
    sizes = np.array([100, 90, 1, 1], np.int64)   # dev0 huge, dev1 idle
    perm = pod.plan_handoff(sizes, 2, max_imbalance=0.25)
    assert perm is not None
    assert sorted(perm.tolist()) == [0, 1, 2, 3]  # a true permutation
    new_loads = sizes[perm].reshape(2, 2).sum(1)
    assert new_loads.max() < sizes.reshape(2, 2).sum(1).max()


def test_plan_handoff_identity_when_balanced():
    assert pod.plan_handoff(np.array([10, 11, 10, 9]), 2) is None
    assert pod.plan_handoff(np.zeros(4, np.int64), 2) is None
    assert pod.plan_handoff(np.array([5, 5]), 1) is None   # one device
    assert pod.plan_handoff(np.array([1, 2, 3]), 2) is None  # ragged


def test_plan_handoff_deterministic_and_g_preserving():
    rng = np.random.default_rng(3)
    sizes = rng.integers(0, 1000, size=12)
    p1 = pod.plan_handoff(sizes, 4, max_imbalance=0.0)
    p2 = pod.plan_handoff(sizes, 4, max_imbalance=0.0)
    if p1 is None:
        assert p2 is None
    else:
        np.testing.assert_array_equal(p1, p2)
        assert len(p1) == 12
        # exactly G=3 rows per device, ascending within each device
        for d in range(4):
            rows = p1[3 * d: 3 * (d + 1)]
            assert (np.diff(rows) > 0).all()


def test_permute_comms_roundtrip_and_id_remap():
    from parmmg_tpu.parallel.comms import InterfaceComms
    S, K, I = 4, 2, 4
    rng = np.random.default_rng(1)
    nbr = np.full((S, K), -1, np.int32)
    for s in range(S):
        nbr[s, 0] = (s + 1) % S
    node_idx = rng.integers(-1, 6, size=(S, K, I)).astype(np.int32)
    node_cnt = rng.integers(0, I, size=(S, K)).astype(np.int32)
    face_idx = rng.integers(-1, 6, size=(S, K, I)).astype(np.int32)
    face_cnt = rng.integers(0, I, size=(S, K)).astype(np.int32)
    owner = [rng.integers(0, S, size=5).astype(np.int32)
             for _ in range(S)]
    c = InterfaceComms(nbr, node_idx, node_cnt, face_idx, face_cnt,
                       owner)
    perm = np.array([2, 3, 0, 1])
    c2 = pod.permute_comms(c, perm)
    # new row i describes old shard perm[i], ids remapped
    inv = np.empty(S, np.int64)
    inv[perm] = np.arange(S)
    for i in range(S):
        old = perm[i]
        np.testing.assert_array_equal(c2.node_idx[i], node_idx[old])
        np.testing.assert_array_equal(c2.owner[i], inv[owner[old]])
        exp = np.where(nbr[old] >= 0, inv[np.clip(nbr[old], 0, S - 1)],
                       nbr[old])
        np.testing.assert_array_equal(c2.nbr[i], exp)
    # permuting back restores the original tables
    c3 = pod.permute_comms(c2, inv)
    np.testing.assert_array_equal(c3.nbr, nbr)
    np.testing.assert_array_equal(c3.node_idx, node_idx)
    np.testing.assert_array_equal(c3.face_idx, face_idx)
    for s in range(S):
        np.testing.assert_array_equal(c3.owner[s], owner[s])


def test_handoff_knobs_declared():
    from parmmg_tpu.api import knobs
    for k in ("PARMMG_MH_HANDOFF", "PARMMG_MH_IMBALANCE",
              "PARMMG_MH_STRICT", "PARMMG_MH_CACHE_DIR",
              "PARMMG_MH_COLLECTIVES"):
        assert k in knobs.KNOBS
