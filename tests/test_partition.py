"""Partitioner tests: balance, contiguity, weights, interface movement.

The reference's partition quality is implicit in METIS; here we assert the
properties the remesh-repartition loop actually needs: balance within the
groups-ratio, contiguity, empty-part repair, and that interface
displacement actually moves old interfaces into part interiors
(moveinterfaces_pmmg.c behavior).
"""
import numpy as np

from parmmg_tpu.parallel.partition import (
    morton_partition, greedy_partition, fix_contiguity, build_dual_graph,
    metric_edge_weights, correct_empty_parts, move_interfaces,
    partition_metrics)
from parmmg_tpu.utils.fixtures import cube_mesh


def _cube(n=4):
    vert, tet = cube_mesh(n)
    cent = vert[tet].mean(axis=1)
    return vert, tet, cent


def test_morton_balanced_contiguous():
    vert, tet, cent = _cube(4)
    for nparts in (2, 4, 8):
        part = fix_contiguity(tet, morton_partition(cent, nparts))
        m = partition_metrics(tet, part, nparts)
        assert min(m["counts"]) > 0
        assert m["imbalance"] < 1.7
        # contiguity: fix_contiguity is idempotent
        part2 = fix_contiguity(tet, part)
        assert (part2 == part).all()


def test_greedy_beats_or_matches_morton_cut():
    vert, tet, cent = _cube(4)
    pm = morton_partition(cent, 4)
    pg = greedy_partition(tet, cent, 4)
    mm = partition_metrics(tet, pm, 4)
    mg = partition_metrics(tet, pg, 4)
    assert mg["edge_cut"] <= mm["edge_cut"] * 2.0   # sanity envelope
    assert min(mg["counts"]) > 0


def test_metric_edge_weights_boost():
    vert, tet, cent = _cube(3)
    met = np.full(len(vert), 0.33)          # ~unit lengths: low weight
    w1 = metric_edge_weights(tet, vert, met)
    met_bad = np.full(len(vert), 0.05)      # everything overlong
    w2 = metric_edge_weights(tet, vert, met_bad)
    assert w2["w"].mean() > w1["w"].mean()
    assert w2["w"].max() <= 1.0e6 + 1e-9
    # old-interface boost dominates
    ifc = (np.arange(10), None)
    w3 = metric_edge_weights(tet, vert, met, ifc_pairs=ifc)
    pairs_i, pairs_j = w3["pairs"]
    both = np.isin(pairs_i, ifc[0]) & np.isin(pairs_j, ifc[0])
    if both.any():
        assert (w3["w"][both] == 1.0e6).all()


def test_correct_empty_parts():
    vert, tet, cent = _cube(3)
    part = np.zeros(len(tet), np.int32)     # everything on part 0
    fixed = correct_empty_parts(part, 4, tet)
    counts = np.bincount(fixed, minlength=4)
    assert (counts > 0).all()


def test_move_interfaces_displaces_and_keeps_cover():
    vert, tet, cent = _cube(4)
    part = fix_contiguity(tet, morton_partition(cent, 4))
    ifc_before = _interface_verts(tet, part)
    moved = move_interfaces(tet, part, 4, nlayers=2)
    counts = np.bincount(moved, minlength=4)
    assert (counts > 0).all()
    ifc_after = _interface_verts(tet, moved)
    # the displaced interface must differ from the old one (old interface
    # now largely interior)
    assert len(ifc_before & ifc_after) < len(ifc_before)


def _interface_verts(tet, part):
    xadj, adj = build_dual_graph(tet)
    src = np.repeat(np.arange(len(tet)), np.diff(xadj))
    cross = part[src] != part[adj]
    out = set()
    # vertices on cut faces: shared verts of the two tets
    for a, b in zip(src[cross], adj[cross]):
        out |= set(tet[a]) & set(tet[b])
    return out
