"""Sequential last-resort repair (ops/repair.py) — unit tests.

The batched waves deadlock on tangled sliver clusters; the sequential
pass reproduces the reference remesher's one-op-at-a-time freedom
(MMG3D_opttyp cascade).  The boundary path (plain-MG_BDY vertex sliding
along a boundary edge with sequential tag routing) is the fix for the
'boundary caps' that capped distributed qmin at ~1e-5.

Fixture: squash a vertex toward a neighbor along the largest step that
keeps every incident tet positive (no inversions — repair fixes
degeneracy, not tangling), leaving a genuinely flat sliver.
"""
import dataclasses

import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core import constants as C
from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.ops.repair import repair_mesh
from parmmg_tpu.utils.fixtures import cube_mesh


def _squash(m, a, b, frac=0.9995):
    """Move vertex a toward b by the largest inversion-free step."""
    vh = np.asarray(m.vert).copy()
    tm = np.asarray(m.tmask)
    tet = np.asarray(m.tet)[tm]
    ball = tet[(tet == a).any(axis=1)]

    def minvol(p):
        vv = vh.copy()
        vv[a] = p
        q = vv[ball]
        d1 = q[:, 1] - q[:, 0]
        d2 = q[:, 2] - q[:, 0]
        d3 = q[:, 3] - q[:, 0]
        return np.einsum("ti,ti->t", d1, np.cross(d2, d3)).min()

    lo, hi = 0.0, 1.0
    for _ in range(40):
        mid = 0.5 * (lo + hi)
        p = vh[a] + mid * (vh[b] - vh[a])
        if minvol(p) > 0:
            lo = mid
        else:
            hi = mid
    vh[a] = vh[a] + frac * lo * (vh[b] - vh[a])
    return dataclasses.replace(m, vert=jnp.asarray(vh, m.vert.dtype))


def _run(m, a, b):
    m = _squash(m, a, b)
    m = build_adjacency(m)
    q0 = np.asarray(tet_quality(m))[np.asarray(m.tmask)]
    assert q0.min() < 1e-2              # genuinely degenerate
    vols0 = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols0 > 0).all()            # but NOT inverted
    vol0 = float(vols0.sum())
    m2, nfixed = repair_mesh(m, jnp.full(m.capP, 0.3, m.vert.dtype),
                             q_floor=1e-2)
    assert nfixed > 0
    q1 = np.asarray(tet_quality(m2))[np.asarray(m2.tmask)]
    assert q1.min() > 1e-2
    m2 = build_adjacency(m2)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m2))[np.asarray(m2.tmask)]
    assert (vols > 0).all()
    assert abs(vols.sum() - vol0) < 1e-3 * vol0
    return m2


def test_repair_boundary_cap():
    """A flat sliver pressed onto the domain surface (plain-MG_BDY
    vertices) must be repaired by the boundary-edge collapse with tag
    routing — the old all-untagged guard refused the whole cavity."""
    vert, tet = cube_mesh(3)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    m = analyze_mesh(m).mesh
    vtag = np.asarray(m.vtag)
    vm = np.asarray(m.vmask)
    vh = np.asarray(m.vert)
    plain = vm & (vtag == C.MG_BDY)
    face = plain & (np.abs(vh[:, 2]) < 1e-9)     # inner z=0 face verts
    ids = np.where(face)[0]
    assert len(ids) >= 2
    d = np.linalg.norm(vh[ids][:, None] - vh[ids][None], axis=-1)
    d[d == 0] = np.inf
    i, j = np.unravel_index(np.argmin(d), d.shape)
    _run(m, int(ids[i]), int(ids[j]))


def test_repair_interior_cluster():
    """Interior flat sliver: the pre-existing untagged path."""
    vert, tet = cube_mesh(3)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    m = analyze_mesh(m).mesh
    vtag = np.asarray(m.vtag)
    vm = np.asarray(m.vmask)
    interior = np.where(vm & (vtag == 0))[0]
    assert len(interior) >= 2
    _run(m, int(interior[0]), int(interior[1]))
