"""Incremental shard migration tests (parallel/migrate.py).

The reference migrates only moving groups between ranks with communicator
repair (distributegrps_pmmg.c:1631-1841); the shard-resident outer loop
(dist.distributed_adapt_multi) must do the same: between outer iterations
no whole-mesh merge happens — only the displaced interface band moves.
These tests assert exactly that (a merge-call counter), plus the usual
conformity/quality/volume gates and the comm-table ordering contract on
the migrated state.  Runs on the virtual 8-device CPU mesh
(tests/conftest.py), the analogue of the reference NP matrix
(cmake/testing/pmmg_tests.cmake:30-63).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.parallel import dist
from parmmg_tpu.parallel import distribute
from parmmg_tpu.utils.fixtures import cube_mesh

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def _setup(n=3, capmul=4):
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert),
                  capT=capmul * len(tet))
    m = analyze_mesh(m).mesh
    return m, jnp.full(m.capP, 0.3, m.vert.dtype)


def test_flood_labels_advance_into_smaller():
    """The bigger shard's color must invade the smaller across the
    interface (PMMG_get_ifcDirection priority, moveinterfaces_pmmg.c:77)."""
    from parmmg_tpu.parallel.migrate import flood_labels
    from parmmg_tpu.parallel.distribute import split_to_shards
    from parmmg_tpu.parallel.comms import build_interface_comms
    from parmmg_tpu.core.mesh import mesh_to_host

    m, met = _setup(6)
    vert_h, tet_h, _, _, _ = mesh_to_host(m)
    # equal halves: the size tie breaks toward the higher shard id, whose
    # front advances 2 tet-ball layers into shard 0 — but not all of it
    cent = vert_h[tet_h].mean(axis=1)
    part = (cent[:, 0] > 0.5).astype(np.int32)
    s, ms, l2g = split_to_shards(m, met, part, 2, return_l2g=True)
    g2l = []
    for s_ in range(2):
        mm = np.full(len(vert_h), -1, np.int64)
        mm[l2g[s_]] = np.arange(len(l2g[s_]))
        g2l.append(mm)
    comms = build_interface_comms(tet_h, part, 2, l2g, g2l)
    sizes = jnp.asarray(np.asarray(s.tmask).sum(axis=1).astype(np.int32))
    labels, depth = flood_labels(
        s, jnp.asarray(comms.node_idx), jnp.asarray(comms.nbr),
        sizes, 2, nlayers=2)
    labels, depth = np.asarray(labels), np.asarray(depth)
    tm = np.asarray(s.tmask)
    # flood depth: every flipped tet records its wave (1 or 2); kept
    # tets record 0 (consumed by enforce_ne_min's front-ordered revert)
    flipped = tm[0] & (labels[0] != 0)
    assert set(np.unique(depth[0][flipped])) <= {1, 2}
    assert (depth[0][tm[0] & ~flipped] == 0).all()
    # the big shard (1) keeps everything; the small shard (0) donates a
    # band to shard 1
    assert (labels[1][tm[1]] == 1).all()
    moved = (labels[0][tm[0]] == 1).sum()
    assert 0 < moved < tm[0].sum()


# NOTE (slow-tier burn-down): the two heaviest tests this module
# carried — test_multi_iteration_no_intermediate_merge and
# test_migration_moves_interface_band — now live in
# tests/test_compile_ledger.py at tier-1 size, asserted on the shared
# steady_state_migration_scenario fixture (one compile for the whole
# scenario family instead of a multi-minute 8-shard build here).


def test_driver_uses_shard_resident_path():
    """The API path with the default ifc-displacement mode must route
    through distributed_adapt_multi and produce a valid mesh."""
    from parmmg_tpu.api import ParMesh, IParam
    calls = {"n": 0}
    orig = distribute.merge_shards

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    distribute.merge_shards = counting
    try:
        vert, tet = cube_mesh(2)
        pm = ParMesh()
        pm.set_mesh_size(np_=len(vert), ne=len(tet))
        pm.set_vertices(vert)
        pm.set_tetrahedra(tet + 1)
        pm.set_met_size(1, len(vert))
        pm.set_scalar_mets(np.full(len(vert), 0.35))
        pm.set_iparameter(IParam.niter, 2)
        pm.info.n_devices = 4
        assert pm.run() == C.PMMG_SUCCESS
    finally:
        distribute.merge_shards = orig
    assert calls["n"] == 1
    v, _ = pm.get_vertices()
    t, _ = pm.get_tetrahedra()
    p = v[t - 1]
    vol = np.einsum("ti,ti->t", p[:, 1] - p[:, 0],
                    np.cross(p[:, 2] - p[:, 0], p[:, 3] - p[:, 0])) / 6
    assert (vol > 0).all()
    assert np.isclose(vol.sum(), 1.0, rtol=1e-4)


def test_graph_mode_one_merge_and_rebalance():
    """VERDICT r2 #7 'Done' gate: graph-balancing mode runs niter=3 with
    exactly ONE merge (the final output), labels realized through the
    band machinery (migrate.graph_repartition_labels)."""
    calls = {"n": 0}
    orig = distribute.merge_shards

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    distribute.merge_shards = counting
    try:
        m, met = _setup(3)
        out, met2, part = dist.distributed_adapt_multi(
            m, met, 4, niter=3, cycles=3, mode="graph")
    finally:
        distribute.merge_shards = orig
    assert calls["n"] == 1, "graph mode must not merge between iterations"
    out = build_adjacency(out)
    assert check_adjacency(out) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    # the repartition balances the shard loads: final part sizes within
    # a generous band of the mean
    sizes = np.bincount(part, minlength=4)
    assert sizes.min() > 0.25 * sizes.mean()
