"""Distributed (multi-device shard_map) adaptation tests on the virtual
8-device CPU mesh — the analogue of the reference's NP in {1,2,4,8} CI
matrix (cmake/testing/pmmg_tests.cmake:30-63), with quality/conformity
assertions instead of exit codes.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import make_mesh, tet_volumes, mesh_to_host
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.parallel.dist import distributed_adapt
from parmmg_tpu.parallel.partition import move_interfaces
from parmmg_tpu.utils.fixtures import cube_mesh

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def _setup(n=3, capmul=4):
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert), capT=capmul * len(tet))
    m = analyze_mesh(m).mesh
    return m, jnp.full(m.capP, 0.3, m.vert.dtype)


@pytest.mark.parametrize("ndev", [2, 8])
def test_distributed_adapt_conforming(ndev):
    # ndev=4 is covered by the iterated + API tests below; the 1-core CI
    # host makes each extra (ndev, shape) combo cost minutes of wall clock
    m, met = _setup(3)
    out, met2, part = distributed_adapt(m, met, ndev, cycles=4)
    out = build_adjacency(out)
    assert check_adjacency(out) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    assert len(part) == int(np.asarray(out.tmask).sum())
    assert part.min() >= 0 and part.max() < ndev


def test_iterated_with_interface_displacement():
    m, met = _setup(3)
    part = None
    for it in range(2):
        m, met, part = distributed_adapt(m, met, 4, cycles=3, part=part)
        m = analyze_mesh(m).mesh
        _, tet_h, _, _, _ = mesh_to_host(m)
        part = move_interfaces(tet_h, part, 4, nlayers=2)
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    q = np.asarray(tet_quality(m, met))[np.asarray(m.tmask)]
    assert q.min() > 0.05


def test_api_multidevice():
    from parmmg_tpu.api import ParMesh, IParam
    vert, tet = cube_mesh(2)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.3))
    pm.set_iparameter(IParam.niter, 2)
    pm.info.n_devices = 4
    assert pm.run() == C.PMMG_SUCCESS
    v, _ = pm.get_vertices()
    t, _ = pm.get_tetrahedra()
    p = v[t - 1]
    vol = np.einsum("ti,ti->t", p[:, 1] - p[:, 0],
                    np.cross(p[:, 2] - p[:, 0], p[:, 3] - p[:, 0])) / 6
    assert (vol > 0).all()
    assert np.isclose(vol.sum(), 1.0, rtol=1e-4)
