"""Groups x shards composition: S*G logical shards on S devices.

The reference composes rank-level and group-level decomposition freely
(each rank splits its subdomain into -mesh-size groups,
grpsplit_pmmg.c:1551-1614, remeshed in the libparmmg1.c:597-636 group
loop).  The TPU analogue (parallel/dist.py `G`): the stacked leading
axis carries S*G logical shards, G consecutive rows per device, and the
SPMD adapt block serializes each device's G groups with ``lax.map`` —
peak HBM per chip is the G resident group states plus ONE group's wave
working set (the HBM bound documented on dist_adapt_block).

Main gate: the SAME logical decomposition run with G=1 (8 logical
shards on 8 devices) and G=2 (8 logical shards on 4 devices) must land
on the SAME adapted mesh — the G axis is pure placement, every
logical-shard program is identical, so the results agree to floating
point reproducibility.  A deeper-G run holds the conformity gates.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import make_mesh, mesh_to_host
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric
from parmmg_tpu.parallel.dist import distributed_adapt_multi

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def _run(n_shards, n_devices, niter=2, n=6):
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.6 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    out, met_m, part = distributed_adapt_multi(
        mesh, met, n_shards, niter=niter, cycles=6,
        n_devices=n_devices)
    return out, met_m, part


def _check_conforming(out):
    """Every live tet positively oriented; every interior face matched
    exactly twice (the manifold-conformity gate of test_dist)."""
    vert_h, tet_h, _, _, _ = mesh_to_host(out)
    p = vert_h[tet_h]
    d1, d2, d3 = (p[:, 1] - p[:, 0], p[:, 2] - p[:, 0],
                  p[:, 3] - p[:, 0])
    vol = np.einsum("ij,ij->i", d1, np.cross(d2, d3))
    assert (vol > 0).all(), "inverted or degenerate tets after merge"
    faces = np.sort(np.stack([
        tet_h[:, [1, 2, 3]], tet_h[:, [0, 2, 3]],
        tet_h[:, [0, 1, 3]], tet_h[:, [0, 1, 2]]], axis=1
    ).reshape(-1, 3), axis=1)
    _, cnt = np.unique(faces, axis=0, return_counts=True)
    assert cnt.max() <= 2, "non-manifold face after grouped merge"
    return tet_h


def test_grouped_placement_matches_flat():
    """G is pure placement: 8 logical shards on 4 devices (G=2) adapts
    to the same mesh as 8 logical shards on 8 devices (G=1) — same
    partition, same per-shard programs, same migrations."""
    out_f, met_f, part_f = _run(n_shards=8, n_devices=8)
    out_g, met_g, part_g = _run(n_shards=8, n_devices=4)
    tm_f = np.asarray(out_f.tmask)
    tm_g = np.asarray(out_g.tmask)
    assert tm_f.sum() == tm_g.sum()
    # same live tet SET (order may differ by placement): canonicalize
    # each tet as its vertex-coordinate rows sorted WITHIN the tet,
    # then lexsort whole 12-tuples — a true row-multiset comparison
    # (sorting each column independently would destroy row association
    # and could equate different meshes)
    vf, tf, _, _, _ = mesh_to_host(out_f)
    vg, tg, _, _, _ = mesh_to_host(out_g)

    def canon(v, t):
        corners = v[t]                       # [n, 4, 3]
        order = np.lexsort((corners[:, :, 2], corners[:, :, 1],
                            corners[:, :, 0]), axis=1)
        rows = np.take_along_axis(corners, order[:, :, None],
                                  axis=1).reshape(len(t), 12)
        return rows[np.lexsort(rows.T[::-1])]

    assert np.allclose(canon(vf, tf), canon(vg, tg), atol=1e-12)
    assert (np.sort(part_f) == np.sort(part_g)).all()


def test_groups_shards_deep():
    """4 devices x G=4 (16 logical shards): conformity + the
    production quality-tail floor.  The tail mirrors the driver: up to
    8 polish waves (early break when quiet) + the sequential repair
    pass.  The floor asserted is the repair pass's own q_floor (1e-3,
    Euclidean) — the contract the production tail actually guarantees;
    a 0.01 metric-quality bar was measured flaky (a handful of interior
    slivers land in the 0.003-0.01 band on this 16-shard fixture)."""
    out, met_m, part = _run(n_shards=16, n_devices=4)
    _check_conforming(out)
    from parmmg_tpu.ops.adapt import sliver_polish
    from parmmg_tpu.ops.repair import repair_mesh
    for w in range(8):
        out, counts = sliver_polish(out, met_m,
                                    jnp.asarray(1000 + w, jnp.int32))
        pc = np.asarray(counts)
        if int(pc[0]) == 0 and int(pc[1]) == 0:
            break
    out, _ = repair_mesh(out, met_m)
    _check_conforming(out)
    q = np.asarray(tet_quality(out, met_m))[np.asarray(out.tmask)]
    assert q.min() > 1e-3
    assert np.asarray(tet_quality(out))[np.asarray(out.tmask)].min() \
        > 1e-3
    assert part.max() < 16


def test_bad_divisibility():
    with pytest.raises(ValueError):
        _run(n_shards=9, n_devices=8, niter=1)
