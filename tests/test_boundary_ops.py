"""Boundary-aware operators: 2-2 boundary edge swap (MMG5_swpbdy) and
tangential relocation of regular surface points (MMG5_movbdyregpt)."""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import (
    build_adjacency, check_adjacency)
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.swap import swap22_wave
from parmmg_tpu.ops.smooth import smooth_wave
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.utils.fixtures import cube_mesh


def _two_tet_quad():
    """Two thin tets over a planar boundary quad: swapping the surface
    diagonal (a,b) -> (p,q) fattens both."""
    vert = np.array([
        [-2.0, 0.0, 0.0],   # 0 = a
        [2.0, 0.0, 0.0],    # 1 = b
        [0.0, 0.8, 0.0],    # 2 = p
        [0.0, -0.8, 0.0],   # 3 = q
        [0.0, 0.0, 1.2],    # 4 = c (apex)
    ], np.float64)
    # T1 = {a,b,c,p}, T2 = {a,b,c,q}, both positively oriented
    tet = np.array([[0, 1, 2, 4], [0, 1, 4, 3]], np.int32)
    m = make_mesh(vert, tet, capP=16, capT=8)
    return analyze_mesh(m).mesh


def test_swap22_flips_boundary_diagonal():
    m = _two_tet_quad()
    met = jnp.full(m.capP, 1.0)
    vol0 = float(np.asarray(tet_volumes(m))[np.asarray(m.tmask)].sum())
    q0 = np.asarray(tet_quality(m))[np.asarray(m.tmask)].min()

    res = swap22_wave(m, met)
    assert int(res.nswap) == 1
    m2 = build_adjacency(res.mesh)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}

    tm = np.asarray(m2.tmask)
    tv = np.asarray(m2.tet)[tm]
    # both tets now contain the flipped diagonal (p, q) = (2, 3)
    for t in tv:
        assert 2 in t and 3 in t
    # the old diagonal (a, b) is gone
    assert not any((0 in t) and (1 in t) for t in tv)
    # volume and count conserved, quality strictly improved
    vols = np.asarray(tet_volumes(m2))[tm]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), vol0, rtol=1e-12)
    q1 = np.asarray(tet_quality(m2))[tm].min()
    assert q1 > q0

    # tag routing: new diagonal is a boundary edge, the two new surface
    # faces are tagged MG_BDY, and the interior face is untagged
    from parmmg_tpu.ops.edges import unique_edges
    et = unique_edges(m2)
    ev = np.asarray(et.ev)
    etag = np.asarray(et.etag)
    emask = np.asarray(et.emask)
    diag = emask & (ev[:, 0] == 2) & (ev[:, 1] == 3)
    assert diag.any() and (etag[diag] & C.MG_BDY).all()
    ftag = np.asarray(m2.ftag)[tm]
    nbdy_faces = int(((ftag & C.MG_BDY) != 0).sum())
    assert nbdy_faces == 6        # all faces boundary except the shared one


def test_swap22_respects_frozen_edges():
    m = _two_tet_quad()
    # freeze the swappable edge (a,b) = (0,1): tag REQ on every slot
    ev = np.array([[0, 1]])
    etag = np.asarray(m.etag).copy()
    tv = np.asarray(m.tet)
    from parmmg_tpu.core.constants import IARE
    for t in range(2):
        for e, (i, j) in enumerate(IARE):
            pair = {tv[t, i], tv[t, j]}
            if pair == {0, 1}:
                etag[t, e] |= C.MG_REQ
    import dataclasses
    m = dataclasses.replace(m, etag=jnp.asarray(etag))
    res = swap22_wave(m, jnp.full(m.capP, 1.0))
    assert int(res.nswap) == 0


def test_swap22_in_cube_keeps_surface():
    """Run swap22 waves on an adapted-ish cube: conformity + exact volume."""
    vert, tet = cube_mesh(3)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.5)
    total = 0
    for _ in range(4):
        res = swap22_wave(m, met)
        m = build_adjacency(res.mesh)
        total += int(res.nswap)
        if int(res.nswap) == 0:
            break
    assert check_adjacency(m) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-10)
    # boundary vertices all still on the unit-cube surface
    vm = np.asarray(m.vmask)
    vtag = np.asarray(m.vtag)
    bdy = vm & ((vtag & C.MG_BDY) != 0)
    vv = np.asarray(m.vert)[bdy]
    on_surf = (np.isclose(vv, 0.0, atol=1e-9) |
               np.isclose(vv, 1.0, atol=1e-9)).any(axis=1)
    assert on_surf.all()


def test_boundary_smooth_moves_surface_points_in_plane():
    """A perturbed-in-plane cube face relaxes; off-plane never happens."""
    vert, tet = cube_mesh(4)
    rng = np.random.default_rng(0)
    # perturb interior points of the z=0 face tangentially
    on_face = np.isclose(vert[:, 2], 0.0)
    inner = on_face & (vert[:, 0] > 0.01) & (vert[:, 0] < 0.99) & \
        (vert[:, 1] > 0.01) & (vert[:, 1] < 0.99)
    vert = vert.copy()
    vert[inner, :2] += rng.uniform(-0.07, 0.07, (inner.sum(), 2))
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.4)

    q0 = np.asarray(tet_quality(m))[np.asarray(m.tmask)].min()
    moved = 0
    for w in range(6):
        res = smooth_wave(m, met, wave=w)
        m = res.mesh
        moved += int(res.nmoved)
    assert moved > 0
    # every z=0-face vertex is still exactly on z=0 (tangential moves only)
    vm = np.asarray(m.vmask)
    vv = np.asarray(m.vert)
    still_face = vm[: len(vert)] & on_face
    assert np.allclose(vv[: len(vert)][still_face][:, 2], 0.0, atol=1e-7)
    m = build_adjacency(m)
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-6)
    q1 = np.asarray(tet_quality(m))[np.asarray(m.tmask)].min()
    assert q1 >= q0


def test_boundary_smooth_freezes_ridges_and_corners():
    vert, tet = cube_mesh(3)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.4)
    v0 = np.asarray(m.vert).copy()
    vtag = np.asarray(m.vtag)
    for w in range(4):
        m = smooth_wave(m, met, wave=w).mesh
    v1 = np.asarray(m.vert)
    frozen = (vtag & (C.MG_CRN | C.MG_GEO | C.MG_REQ)) != 0
    assert np.allclose(v0[frozen], v1[frozen])
