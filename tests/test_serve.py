"""Serving subsystem tests (parmmg_tpu/serve + satellites).

Tier-1 tests pin the host-side state machines only — slot-pool
admission / recycling, AdaptStats tenant isolation, the chunk
auto-tune cost model — no XLA compiles (the 870s gate is tight).  The
slow tests pin the end-to-end serving contract: tenants packed into
one [chunk, ...] dispatch retire bit-for-bit identical to their
standalone ``grouped_adapt_pass(ngroups=1)`` runs, through queueing
and slot recycling.  (The compile-family side — a warm pool adds zero
``groups.*`` ledger families vs the batch path — is gated by
``scripts/run_tests.sh --ledger`` / ledger_check.serving_gate.)
"""
import numpy as np
import pytest

from parmmg_tpu.serve.pool import SlotPool
from parmmg_tpu.utils.compilecache import bucket


# ---------------------------------------------------------------------------
# slot-pool state machine (tier-1: host bookkeeping, no compiles)
# ---------------------------------------------------------------------------
def test_pool_admits_smallest_fitting_bucket():
    p = SlotPool(slots_per_bucket=2, chunk=1)
    st, key, slot = p.admit("a", 27, 48)
    assert st == "ok" and slot == 0
    # home bucket = the split_to_shards capacity formula (geo ladder,
    # floor 64, cap_mult 3) — what makes pool slots shape-identical to
    # the standalone grouped path
    assert key[:2] == (bucket(3 * 27, floor=64, scheme="geo"),
                       bucket(3 * 48, floor=64, scheme="geo"))
    # a same-size tenant shares the bucket; a bigger one gets its own
    st2, key2, slot2 = p.admit("b", 27, 48)
    assert st2 == "ok" and key2 == key and slot2 == 1
    st3, key3, _ = p.admit("c", 64, 162)
    assert st3 == "ok" and key3 != key
    assert p.occupancy() == {f"{key[0]}x{key[1]}": (2, 2),
                             f"{key3[0]}x{key3[1]}": (1, 2)}


def test_pool_rejects_oversize():
    p = SlotPool(slots_per_bucket=2, max_capP=500, max_capT=500)
    st, caps = p.admit("big", 400, 4000)
    assert st == "oversize" and caps[1] > 500
    assert "big" not in p._where          # nothing leaked
    # a fitting tenant is still admitted
    assert p.admit("ok", 27, 48)[0] == "ok"


def test_pool_quiet_tenant_slot_recycling():
    p = SlotPool(slots_per_bucket=2)
    p.admit("a", 27, 48)
    _, key, sb = p.admit("b", 27, 48)
    # bucket full: the next request waits (driver keeps it queued)
    assert p.admit("c", 27, 48) == ("full", key)
    # quiet tenant retires -> its slot recycles to the queued tenant
    p.release("b")
    st, key2, slot = p.admit("c", 27, 48)
    assert (st, key2, slot) == ("ok", key, sb)


def test_pool_pad_slots_born_quiet():
    """Free/pad slots are never part of the active set and a pool with
    no loaded tenants dispatches nothing (step is a no-op)."""
    p = SlotPool(slots_per_bucket=4)
    p.admit("a", 27, 48)          # admitted but never loaded
    assert p.active_tenants() == []
    assert p.step() == [] and p.dispatches == 0
    s = p.slot_of("a")
    assert not s.converged and not s.loaded


# ---------------------------------------------------------------------------
# AdaptStats tenant isolation (serving satellite)
# ---------------------------------------------------------------------------
def test_adapt_stats_refuses_cross_tenant_merge():
    from parmmg_tpu.ops.adapt import AdaptStats
    a = AdaptStats(tenant="a", nsplit=3)
    b = AdaptStats(tenant="b", nsplit=5)
    with pytest.raises(ValueError, match="across tenants"):
        a += b
    assert a.nsplit == 3                  # refused merge left a intact


def test_adapt_stats_namespaces_per_tenant_keys():
    from parmmg_tpu.ops.adapt import AdaptStats
    a = AdaptStats(tenant="a")
    a.sched_extra["ops_per_block"] = [4, 0]
    a.sched_extra["grp_upload_s"] = 0.5
    b = AdaptStats(tenant="b")
    b.sched_extra["ops_per_block"] = [7]
    agg = AdaptStats()
    agg += a
    agg += b
    # trajectories and timer keys never interleave across tenants
    assert agg.sched_extra == {"tenant:a/ops_per_block": [4, 0],
                               "tenant:a/grp_upload_s": 0.5,
                               "tenant:b/ops_per_block": [7]}
    # same-tenant accumulation stays un-namespaced (sub-pass merge)
    t = AdaptStats(tenant="a")
    t += AdaptStats(tenant="a", nswap=2)
    assert t.nswap == 2 and t.sched_extra == {}


# ---------------------------------------------------------------------------
# PARMMG_GROUP_CHUNK auto-tune (ROADMAP 1b satellite)
# ---------------------------------------------------------------------------
def test_timeout_scrubs_and_recycles_slot():
    """Regression (resilience satellite): a RUNNING request expired by
    _expire_timeouts must leave its pool slot SCRUBBED (row zeroed back
    to the dead-mesh state) and back on the bucket's free list, rentable
    by the next tenant — a timed-out tenant must never strand capacity."""
    import time
    from parmmg_tpu.serve.driver import (RUNNING, TIMEOUT, ServeDriver,
                                         ServeRequest)
    pool = SlotPool(slots_per_bucket=1)
    drv = ServeDriver(pool=pool, timeout_s=0.001)
    st, key, i = pool.admit("a", 27, 48)
    assert st == "ok"
    # fake-load the slot host-side (no XLA): a dict pytree stands in
    # for the stacked Mesh, with non-zero rows to catch the scrub
    b = pool.buckets[key]
    b.stacked = {"vert": np.ones((1, 8, 3)), "tet": np.ones((1, 16, 4))}
    b.met = np.ones((1, 8))
    b.slots[i].loaded = True
    drv.requests["a"] = ServeRequest(
        tid="a", state=RUNNING, t_submit=time.perf_counter() - 10.0)
    drv._expire_timeouts()
    r = drv.requests["a"]
    assert r.state == TIMEOUT and "exceeded" in r.reason
    # slot scrubbed: row zeroed (born-quiet dead mesh for the next
    # renter), tenant gone from the rent map, slot back on the free list
    assert (b.stacked["vert"] == 0).all() and (b.met == 0).all()
    assert "a" not in pool._where
    assert b.free_slot() == i
    # ...and actually rentable by the next tenant
    assert pool.admit("b", 27, 48) == ("ok", key, i)


def test_recommend_group_chunk_tracks_decay():
    from parmmg_tpu.parallel.sched import recommend_group_chunk
    # front-loaded decay: two full blocks then a long quiet tail —
    # chunk 2 beats both chunk 1 (dispatch overhead x8) and chunk 8
    # (pads 7 dead slots per tail block)
    assert recommend_group_chunk([8, 8, 1, 1, 1, 1], 8) == 2
    # never-converging trajectory: full chunks win (0 = unchunked)
    assert recommend_group_chunk([8] * 6, 8, dispatch_overhead=8.0) == 0
    # degenerate inputs
    assert recommend_group_chunk([], 8) == 0
    assert recommend_group_chunk([0, 0], 8) == 0
    assert recommend_group_chunk([4, 4], 1) == 0


def test_group_chunk_auto_env(monkeypatch):
    from parmmg_tpu.parallel import sched
    from parmmg_tpu.parallel.groups import group_chunk
    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "auto")
    monkeypatch.setattr(sched, "_CHUNK_RECOMMENDATION", [])
    # before any grouped pass: the backend default (CPU tests: 0)
    assert group_chunk(16) == 0
    sched.note_chunk_recommendation(4)
    assert group_chunk(16) == 4
    # the unchunked convention still applies when the recommendation
    # covers every group
    assert group_chunk(4) == 0
    sched.note_chunk_recommendation(2)    # newest recommendation wins
    assert group_chunk(16) == 2
    # explicit numeric values are untouched by the auto machinery
    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "3")
    assert group_chunk(16) == 3


# ---------------------------------------------------------------------------
# end-to-end serving contracts (slow tier: group-block XLA compiles)
# ---------------------------------------------------------------------------
def _tenant(n=2, h=0.55):
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import cube_mesh
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, h, m.vert.dtype)
    return m, met


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_serve_parity_packed_dispatch():
    """Tenants PACKED into one [chunk=2, ...] dispatch (different
    metrics, same bucket) each retire bit-for-bit identical to their
    standalone grouped_adapt_pass(ngroups=1) run — slot isolation under
    packing, through queue + slot recycling (3 tenants, 2 slots)."""
    from parmmg_tpu.core.mesh import MESH_FIELDS
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.serve.driver import ServeDriver

    cycles = 3
    cases = {"ta": 0.55, "tb": 0.42, "tc": 0.55}
    refs = {}
    for tid, h in cases.items():
        m, met = _tenant(2, h)
        out, met_m, _ = grouped_adapt_pass(m, met, 1, cycles=cycles)
        refs[tid] = (out, np.asarray(met_m))

    drv = ServeDriver(slots_per_bucket=2, chunk=2, cycles=cycles)
    for tid, h in cases.items():
        m, met = _tenant(2, h)
        drv.submit(mesh=m, met=met, tenant=tid)
    rep = drv.run()
    assert rep["served"] == 3 and rep["failed"] == 0
    for tid in cases:
        mesh, met_m = drv.fetch(tid)
        ref, kref = refs[tid]
        for f in MESH_FIELDS:
            a, b = np.asarray(getattr(mesh, f)), \
                np.asarray(getattr(ref, f))
            assert (a == b).all(), f"tenant {tid} field {f} differs"
        assert (np.asarray(met_m) == kref).all(), f"{tid} metric differs"
    # different metrics did different work (isolation is not no-op)
    assert rep["tenants"]["ta"]["ops"] != rep["tenants"]["tb"]["ops"]
    # every slot recycled on retirement (3 tenants through 2 home
    # slots; a capacity promotion may add a second bucket — also empty)
    occ = drv.pool.occupancy()
    assert occ and all(used == 0 for used, _ in occ.values())
