"""Resilience subsystem tests (parmmg_tpu/resilience + satellites).

Tier-1 tests pin the host-side state machines only — fault-spec
parsing, nth/every-k/probability triggers, the retry/backoff/deadline
wrapper, ladder ordering and accounting, checkpoint save/load
atomicity, the serve quarantine bookkeeping — no XLA compiles (the
870s gate is tight; ROADMAP budget note).  The end-to-end injected
runs (worker kill mid-polish, dispatch fault mid-pass, checkpoint/
resume bit-identity) ride the slow tier here and the in-process
``run_tests.sh --chaos`` gate (scripts/chaos_check.py).
"""
import os

import numpy as np
import pytest

from parmmg_tpu.resilience.faults import (FAULTS, FaultRule,
                                          parse_fault_spec,
                                          subprocess_fault_env)
from parmmg_tpu.resilience.recover import (LADDER, RetryBudgetExhausted,
                                           ladder_step, retry_call)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("PARMMG_FAULT", raising=False)
    FAULTS.reset()
    yield
    FAULTS.reset()


# ---------------------------------------------------------------------------
# fault-spec grammar + trigger semantics
# ---------------------------------------------------------------------------
def test_fault_spec_grammar():
    r = parse_fault_spec(
        "dispatch.chunk:nth-3,polish.worker,io.checkpoint:every-2,"
        "serve.slot_step:key=t7;p=0.5;seed=9")
    assert r["dispatch.chunk"].nth == 3
    assert r["polish.worker"].nth is None \
        and r["polish.worker"].every is None
    assert r["io.checkpoint"].every == 2
    s = r["serve.slot_step"]
    assert (s.key, s.p, s.seed) == ("t7", 0.5, 9)
    # bare integer == nth
    assert parse_fault_spec("dispatch.chunk:2")["dispatch.chunk"].nth == 2


def test_fault_spec_rejects_garbage():
    with pytest.raises(ValueError, match="unknown fault site"):
        parse_fault_spec("no.such.site")
    with pytest.raises(ValueError, match="unparseable"):
        parse_fault_spec("dispatch.chunk:sometimes")
    with pytest.raises(ValueError, match="nth"):
        parse_fault_spec("dispatch.chunk:nth-0")


def test_trigger_nth_fires_exactly_once():
    r = FaultRule("dispatch.chunk", nth=3)
    assert [r.fires(None) for _ in range(6)] == \
        [False, False, True, False, False, False]


def test_trigger_every_k_is_periodic():
    r = FaultRule("dispatch.chunk", every=2)
    assert [r.fires(None) for _ in range(6)] == \
        [False, True, False, True, False, True]


def test_trigger_probability_seeded_reproducible():
    r1 = FaultRule("dispatch.chunk", p=0.5, seed=4)
    r2 = FaultRule("dispatch.chunk", p=0.5, seed=4)
    assert [r1.fires(None) for _ in range(32)] == \
        [r2.fires(None) for _ in range(32)]
    r_always = FaultRule("dispatch.chunk", p=1.0)
    assert all(r_always.fires(None) for _ in range(4))
    r0 = FaultRule("dispatch.chunk", p=0.0)
    assert not any(r0.fires(None) for _ in range(4))


def test_trigger_key_filter_gates_counting():
    # non-matching hits must not advance the counter: the poison
    # tenant's nth-1 fires on ITS first hit regardless of cohort order
    r = FaultRule("serve.slot_step", nth=1, key="t1")
    assert not r.fires("t0")
    assert r.fires("t1")
    assert not r.fires("t1")


def test_registry_reads_env_and_counts_in_parent(monkeypatch):
    monkeypatch.setenv("PARMMG_FAULT", "polish.worker:nth-1")
    FAULTS.reset()
    # the subprocess form: firing decided in the PARENT so counting
    # survives fresh worker processes; the env overlay carries it
    assert subprocess_fault_env("polish.worker") == \
        {"PARMMG_FAULT_FORCE": "polish.worker"}
    assert subprocess_fault_env("polish.worker") == {}
    # changing the knob rebuilds rules with fresh counters
    monkeypatch.setenv("PARMMG_FAULT", "polish.worker:nth-1;seed=0")
    assert subprocess_fault_env("polish.worker") != {}


def test_faultpoint_raises_real_shapes(monkeypatch):
    from parmmg_tpu.resilience.faults import faultpoint
    monkeypatch.setenv("PARMMG_FAULT", "io.checkpoint")
    FAULTS.reset()
    with pytest.raises(OSError, match="injected fault"):
        faultpoint("io.checkpoint")
    monkeypatch.setenv("PARMMG_FAULT", "dispatch.chunk")
    FAULTS.reset()
    with pytest.raises(Exception) as ei:
        faultpoint("dispatch.chunk")
    # XlaRuntimeError subclasses RuntimeError; the message carries the
    # canonical INTERNAL: status prefix either way
    assert isinstance(ei.value, RuntimeError)
    assert "INTERNAL" in str(ei.value)


def test_unarmed_faultpoint_is_free(monkeypatch):
    from parmmg_tpu.resilience.faults import fault_trigger, faultpoint
    faultpoint("dispatch.chunk")          # no env: must not raise
    assert fault_trigger("analysis.ks_overflow") is False


# ---------------------------------------------------------------------------
# retry/backoff/deadline wrapper
# ---------------------------------------------------------------------------
def test_retry_succeeds_after_transient_failures():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    assert retry_call(flaky, "t", max_retries=2, base_s=0) == "ok"
    assert len(calls) == 3


def test_retry_budget_exhaustion_chains_cause():
    def always():
        raise RuntimeError("down")

    with pytest.raises(RetryBudgetExhausted) as ei:
        retry_call(always, "t", max_retries=1, base_s=0)
    assert ei.value.site == "t"
    assert isinstance(ei.value.__cause__, RuntimeError)


def test_retry_never_retries_capacity_signals():
    calls = []

    def oom():
        calls.append(1)
        raise MemoryError("group capacity exhausted")

    with pytest.raises(MemoryError):
        retry_call(oom, "t", max_retries=3, base_s=0)
    assert len(calls) == 1                # deterministic: no re-run


def test_retry_initial_failure_consumes_attempt_zero():
    # the pipelined dispatch's inline attempt already failed: with
    # PARMMG_RETRY_MAX=0 that exhausts immediately, fn never re-runs
    calls = []
    with pytest.raises(RetryBudgetExhausted) as ei:
        retry_call(lambda: calls.append(1), "t", max_retries=0,
                   base_s=0, initial_failure=RuntimeError("first"))
    assert calls == []
    assert str(ei.value.__cause__) == "first"
    # with budget, the initial failure counts as attempt 0 and the
    # wrapper proceeds to a (successful) re-attempt
    assert retry_call(lambda: "ok", "t", max_retries=1, base_s=0,
                      initial_failure=RuntimeError("first")) == "ok"


def test_retry_deadline_stops_early():
    calls = []

    def slow_fail():
        calls.append(1)
        raise RuntimeError("down")

    with pytest.raises(RetryBudgetExhausted):
        retry_call(slow_fail, "t", max_retries=50, base_s=0.02,
                   deadline_s=0.01)
    assert len(calls) <= 3                # deadline, not the 50 budget


def test_retry_env_knobs(monkeypatch):
    from parmmg_tpu.resilience.recover import retry_env
    monkeypatch.setenv("PARMMG_RETRY_MAX", "7")
    monkeypatch.setenv("PARMMG_RETRY_BASE_S", "0.5")
    monkeypatch.setenv("PARMMG_RETRY_DEADLINE_S", "9")
    assert retry_env() == (7, 0.5, 9.0)


# ---------------------------------------------------------------------------
# escalation ladder
# ---------------------------------------------------------------------------
def test_ladder_order_is_the_documented_escalation():
    assert LADDER == ("retry", "mh_allgather", "halo_dense",
                      "host_analysis",
                      "merged_polish", "lowfailure")


def test_ladder_step_counts_and_traces():
    from parmmg_tpu.obs.metrics import REGISTRY
    from parmmg_tpu.obs.trace import TRACER
    before = REGISTRY.counter("resilience.host_analysis").value
    n0 = len(TRACER.ring)
    ladder_step("host_analysis", site="analysis.ks_overflow")
    assert REGISTRY.counter("resilience.host_analysis").value == \
        before + 1
    evs = [r for r in list(TRACER.ring)[n0:]
           if r.get("kind") == "event"
           and r.get("name") == "resilience.ladder"]
    assert evs and evs[-1]["step"] == "host_analysis"
    with pytest.raises(ValueError, match="unknown ladder step"):
        ladder_step("panic")


# ---------------------------------------------------------------------------
# pass checkpoints (host round-trip; resume bit-identity is chaos/slow)
# ---------------------------------------------------------------------------
def _tiny_mesh():
    from parmmg_tpu.core.mesh import MESH_FIELDS, Mesh
    rng = np.random.RandomState(0)
    kw = {}
    for f in MESH_FIELDS:
        if f in ("npoin", "nelem"):
            kw[f] = np.asarray(4, np.int32)
        elif f in ("vmask", "tmask"):
            kw[f] = rng.rand(6) < 0.5
        elif f == "vert":
            kw[f] = rng.rand(6, 3)
        elif f == "tet":
            kw[f] = rng.randint(0, 6, (6, 4)).astype(np.int32)
        elif f == "adja":
            kw[f] = np.full((6, 4), -1, np.int32)
        else:
            kw[f] = np.zeros((6,), np.int32) if f.startswith("v") \
                else np.zeros((6, 4), np.int32)
    return Mesh(**kw)


def test_checkpoint_roundtrip_and_latest(tmp_path, monkeypatch):
    from parmmg_tpu.resilience import checkpoint as ck
    monkeypatch.setenv("PARMMG_CKPT_DIR", str(tmp_path))
    monkeypatch.setenv("PARMMG_CKPT_EVERY", "1")
    m = _tiny_mesh()
    met = np.linspace(0, 1, 6)
    part = np.array([0, 1, 2, 0], np.int32)
    for it in (0, 1):
        assert ck.save_pass_checkpoint("t", it, m, met, part)
    # a kill mid-write leaves only .tmp partials: never resumed from
    (tmp_path / "t.pass5.npz.tmp").write_bytes(b"partial")
    path, it = ck.latest_pass_checkpoint("t")
    assert it == 1 and path.endswith("t.pass1.npz")
    m2, met2, part2, it2 = ck.load_pass_checkpoint(path)
    assert it2 == 1
    assert (np.asarray(m2.vert) == np.asarray(m.vert)).all()
    assert (met2 == met).all() and (part2 == part).all()


def test_checkpoint_cadence_and_disabled(tmp_path, monkeypatch):
    from parmmg_tpu.resilience import checkpoint as ck
    monkeypatch.delenv("PARMMG_CKPT_DIR", raising=False)
    assert ck.save_pass_checkpoint("t", 0, None, None, None) is None
    monkeypatch.setenv("PARMMG_CKPT_DIR", str(tmp_path))
    monkeypatch.setenv("PARMMG_CKPT_EVERY", "2")
    assert not ck.ckpt_due(0) and ck.ckpt_due(1) and not ck.ckpt_due(2)


def test_checkpoint_fault_is_absorbed(tmp_path, monkeypatch):
    from parmmg_tpu.obs.metrics import REGISTRY
    from parmmg_tpu.resilience import checkpoint as ck
    monkeypatch.setenv("PARMMG_CKPT_DIR", str(tmp_path))
    monkeypatch.setenv("PARMMG_CKPT_EVERY", "1")
    monkeypatch.setenv("PARMMG_FAULT", "io.checkpoint")
    FAULTS.reset()
    before = REGISTRY.counter("resilience.checkpoint_failures").value
    # the injected OSError must be swallowed: run > checkpoint
    assert ck.save_pass_checkpoint("t", 0, _tiny_mesh(),
                                   np.zeros(6), None) is None
    assert REGISTRY.counter("resilience.checkpoint_failures").value == \
        before + 1
    assert list(tmp_path.iterdir()) == []


def test_checkpoint_fingerprint_guards_stale_resume(tmp_path,
                                                    monkeypatch):
    """A reused ckpt dir must never silently resume a checkpoint from
    a DIFFERENT run: the stored input fingerprint has to match."""
    from parmmg_tpu.resilience import checkpoint as ck
    monkeypatch.setenv("PARMMG_CKPT_DIR", str(tmp_path))
    monkeypatch.setenv("PARMMG_CKPT_EVERY", "1")
    m = _tiny_mesh()
    fp_a = ck.run_fingerprint(m, np.zeros(6), 16, 2)
    fp_b = ck.run_fingerprint(m, np.ones(6), 16, 2)   # different met
    assert fp_a != fp_b
    assert ck.save_pass_checkpoint("t", 0, m, np.zeros(6), None,
                                   fingerprint=fp_a)
    assert ck.latest_pass_checkpoint("t", fingerprint=fp_a) is not None
    assert ck.latest_pass_checkpoint("t", fingerprint=fp_b) is None
    # legacy checkpoints without a stored fingerprint are also refused
    # when the caller asks for identity; accepted when it doesn't
    assert ck.save_pass_checkpoint("u", 0, m, np.zeros(6), None)
    assert ck.latest_pass_checkpoint("u", fingerprint=fp_a) is None
    assert ck.latest_pass_checkpoint("u") is not None


def test_latest_checkpoint_none_without_dir(monkeypatch):
    from parmmg_tpu.resilience import checkpoint as ck
    monkeypatch.delenv("PARMMG_CKPT_DIR", raising=False)
    assert ck.latest_pass_checkpoint("t") is None


# ---------------------------------------------------------------------------
# serve quarantine bookkeeping (pool state machine, no dispatch)
# ---------------------------------------------------------------------------
def test_slot_fault_quarantine_threshold(monkeypatch):
    from parmmg_tpu.obs.metrics import REGISTRY
    from parmmg_tpu.serve.pool import SlotPool
    p = SlotPool(slots_per_bucket=2, max_slot_retries=2)
    p.admit("a", 27, 48)
    s = p.slot_of("a")
    before = REGISTRY.counter("serve.quarantined").value
    assert p._note_slot_fault(s, RuntimeError("boom")) is False
    assert s.faults == 1 and not s.failed
    assert p._note_slot_fault(s, RuntimeError("boom")) is True
    assert "quarantined after 2" in s.failed
    assert p.quarantined == ["a"]
    assert REGISTRY.counter("serve.quarantined").value == before + 1
    # a failed slot is no longer active (the pool loop retires it)
    assert "a" not in p.active_tenants()


def test_serve_max_retries_env(monkeypatch):
    from parmmg_tpu.serve.pool import SlotPool
    monkeypatch.setenv("PARMMG_SERVE_MAX_RETRIES", "5")
    assert SlotPool(slots_per_bucket=1).max_slot_retries == 5
    # constructor arg wins; floor of 1 enforced
    assert SlotPool(slots_per_bucket=1,
                    max_slot_retries=0).max_slot_retries == 1


# ---------------------------------------------------------------------------
# slow tier: end-to-end injected-fault runs (XLA compiles)
# ---------------------------------------------------------------------------
def _grouped_case():
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import cube_mesh
    vert, tet = cube_mesh(2)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.35, m.vert.dtype)
    return m, met


def _bytes(mesh, met):
    from parmmg_tpu.core.mesh import MESH_FIELDS
    return tuple(np.asarray(getattr(mesh, f)).tobytes()
                 for f in MESH_FIELDS) + (np.asarray(met).tobytes(),)


@pytest.mark.slow
def test_dispatch_fault_mid_pass_recovers_bitwise(monkeypatch):
    """A transient chunk-dispatch fault mid-pass retries serially and
    the pass result is bit-identical to the fault-free run."""
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "2")
    monkeypatch.setenv("PARMMG_RETRY_BASE_S", "0")
    m, met = _grouped_case()
    ref = grouped_adapt_pass(m, met, 3, cycles=2)
    # fault the SECOND chunk dispatch: mid-pass, not at the boundary
    monkeypatch.setenv("PARMMG_FAULT", "dispatch.chunk:nth-2")
    FAULTS.reset()
    m2, met2 = _grouped_case()
    got = grouped_adapt_pass(m2, met2, 3, cycles=2)
    assert _bytes(ref[0], ref[1]) == _bytes(got[0], got[1])


@pytest.mark.slow
def test_polish_worker_kill_then_retry_recovers(monkeypatch):
    """Worker killed mid-polish (first invocation exits non-zero), the
    retry's fresh worker succeeds: result identical to a clean
    subprocess-polish run."""
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "2")
    monkeypatch.setenv("PARMMG_POLISH_SUBPROC", "1")
    monkeypatch.setenv("PARMMG_RETRY_BASE_S", "0")
    m, met = _grouped_case()
    ref = grouped_adapt_pass(m, met, 3, cycles=2, polish=True)
    monkeypatch.setenv("PARMMG_FAULT", "polish.worker:nth-1")
    FAULTS.reset()
    m2, met2 = _grouped_case()
    got = grouped_adapt_pass(m2, met2, 3, cycles=2, polish=True)
    assert _bytes(ref[0], ref[1]) == _bytes(got[0], got[1])


@pytest.mark.slow
def test_checkpoint_resume_bit_identity(tmp_path, monkeypatch):
    """A run resumed from the pass-0 checkpoint (the killed-run replay)
    finishes bit-identical to the uninterrupted 2-pass run."""
    from parmmg_tpu.parallel.groups import grouped_adapt
    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "2")
    monkeypatch.setenv("PARMMG_CKPT_DIR", str(tmp_path))
    m, met = _grouped_case()
    full = grouped_adapt(m, met, 16, niter=2, cycles=2, ckpt_tag="ck")
    # the kill happened mid-pass-1: its checkpoint never landed
    (tmp_path / "ck.pass1.npz").unlink()
    m2, met2 = _grouped_case()
    resumed = grouped_adapt(m2, met2, 16, niter=2, cycles=2,
                            ckpt_tag="ck", resume=True)
    assert _bytes(*full) == _bytes(*resumed)
