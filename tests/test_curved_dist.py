"""Curved-geometry DISTRIBUTED workloads: aniso boundary layer through
the 8-shard path and the {1,2,4,8}-device matrix — split from
test_curved.py so each pytest process stays short (the image's XLA:CPU
compiler intermittently segfaults late in long-lived processes; see
scripts/run_tests.sh)."""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.core import constants as C
from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.ops.adapt import adapt_mesh
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.utils.fixtures import sphere_mesh, torus_mesh

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def _bdy_euler(m):
    """Euler characteristic of the boundary surface (V - E + F)."""
    tm = np.asarray(m.tmask)
    tet = np.asarray(m.tet)[tm]
    ftag = np.asarray(m.ftag)[tm]
    tris = []
    for f in range(4):
        sel = (ftag[:, f] & C.MG_BDY) != 0
        tris.append(np.sort(tet[sel][:, C.IDIR[f]], axis=1))
    tris = np.unique(np.concatenate(tris), axis=0)
    V = len(np.unique(tris.reshape(-1)))
    edges = np.unique(np.sort(np.concatenate(
        [tris[:, [0, 1]], tris[:, [1, 2]], tris[:, [0, 2]]]), axis=1),
        axis=0)
    return V - len(edges) + len(tris)


def test_aniso_boundary_layer_distributed():
    """Anisotropic boundary-layer tensor metric through the 8-shard SPMD
    path (the reference's sphere-aniso CI case, distributed): thin
    spacing normal to the z=0 wall, isotropic elsewhere."""
    from parmmg_tpu.parallel.dist import distributed_adapt
    from parmmg_tpu.utils.fixtures import cube_mesh
    vert, tet = cube_mesh(3)
    m = make_mesh(vert, tet, capP=6 * len(vert), capT=6 * len(tet))
    m = analyze_mesh(m).mesh
    # hz shrinks toward z=0 (boundary layer), hx=hy loose
    vh = np.asarray(m.vert)
    hz = 0.08 + 0.5 * np.minimum(vh[:, 2], 1.0)
    hxy = np.full(m.capP, 0.45)
    t = np.zeros((m.capP, 6))
    t[:, 0] = 1.0 / hxy**2
    t[:, 3] = 1.0 / hxy**2
    t[:, 5] = 1.0 / np.maximum(hz, 1e-3) ** 2
    met = jnp.asarray(t)
    m2, met2, part = distributed_adapt(m, met, 8, cycles=8)
    # bad-element polish, as the production driver runs after the merge
    from parmmg_tpu.ops.adapt import sliver_polish
    for w in range(4):
        m2, counts = sliver_polish(m2, met2, jnp.asarray(1000 + w,
                                                         jnp.int32))
        pc = np.asarray(counts)
        if int(pc[0]) == 0 and int(pc[1]) == 0:
            break
    m2 = build_adjacency(m2)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m2))[np.asarray(m2.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    # quality gate: this test exercises the aniso MECHANICS through the
    # SPMD path in ONE outer pass — tets pinned at frozen interfaces are
    # only repaired by later displacement iterations (see device matrix)
    q = np.asarray(tet_quality(m2, met2))[np.asarray(m2.tmask)]
    assert q.min() > 0.002
    assert np.median(q) > 0.25
    # boundary-layer refinement actually happened: tets near z=0 are
    # much flatter (smaller z-extent) than tets near z=1
    tm = np.asarray(m2.tmask)
    tv = np.asarray(m2.tet)[tm]
    vz = np.asarray(m2.vert)[:, 2]
    zmin = vz[tv].min(axis=1)
    zext = vz[tv].max(axis=1) - zmin
    low = zext[zmin < 0.05]
    high = zext[zmin > 0.6]
    assert low.mean() < 0.75 * high.mean()


@pytest.mark.parametrize("ndev", [1, 2, 4, 8])
def test_sphere_device_matrix(ndev):
    """The reference CI matrix over rank counts, on the sphere, with
    quality gates (the reference asserts exit codes only)."""
    from parmmg_tpu.api.parmesh import ParMesh
    vert, tet = sphere_mesh(5)
    pm = ParMesh()
    pm.set_mesh_size(len(vert), len(tet))
    pm.set_vertices(vert, np.zeros(len(vert), np.int32))
    pm.set_tetrahedra(tet + 1, np.ones(len(tet), np.int32))
    pm.info.hsiz = 0.4
    # two outer iterations: with one, tets pinned at the frozen interface
    # are never remeshed — the displacement/repartition between
    # iterations exists precisely to fix them (reference default niter=3)
    pm.info.niter = 1 if ndev == 1 else 2
    pm.info.imprim = -1
    pm.info.n_devices = ndev
    assert pm.run() == C.PMMG_SUCCESS
    m = build_adjacency(pm._out)
    assert check_adjacency(m) == {"asymmetric": 0, "face_mismatch": 0}
    assert _bdy_euler(m) == 2                      # still a sphere
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    # interface-band tail: merge-weld + sequential repair lift the worst
    # tet from ~1e-8 to ~1e-5..1e-4 at niter=2; the remaining boundary
    # caps need more displacement iterations (the reference CI asserts
    # exit codes only — this gate is still stronger)
    q = np.asarray(tet_quality(m))[np.asarray(m.tmask)]
    assert q.min() > 1e-5
    assert q.mean() > 0.4
