"""Curved-geometry workloads: hausd-driven surface approximation on the
sphere, torus topology preservation, and the {1,2,4,8}-device matrix —
the reference CI shape (cmake/testing/pmmg_tests.cmake:25-150) with
quality-asserting gates instead of exit codes."""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.core import constants as C
from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.ops.adapt import adapt_mesh
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.utils.fixtures import sphere_mesh, torus_mesh


def _adapted_sphere(hausd, hsiz=0.2, n=5):
    vert, tet = sphere_mesh(n)
    m = make_mesh(vert, tet, capP=8 * len(vert), capT=8 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, hsiz)
    m, met, _ = adapt_mesh(m, met, hausd=hausd)
    return m


def _bdy_radial_dev(m):
    vm = np.asarray(m.vmask)
    vtag = np.asarray(m.vtag)
    bdy = vm & ((vtag & C.MG_BDY) != 0)
    rr = np.linalg.norm(np.asarray(m.vert)[bdy], axis=1)
    return np.abs(rr - 1.0).max()


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_sphere_hausd_keeps_surface_on_sphere():
    """With hausd, refined boundary points are lifted onto the Bezier
    surface: the radial deviation stays within a few hausd, and is far
    smaller than the chord-midpoint deviation of the hausd-off run
    (h~0.45 chords on the unit sphere sag ~h^2/8 ~ 0.025)."""
    hausd = 0.01
    m_on = _adapted_sphere(hausd)
    dev_on = _bdy_radial_dev(m_on)
    m_off = _adapted_sphere(None)
    dev_off = _bdy_radial_dev(m_off)
    assert dev_off > 0.012            # the off-run really sags
    assert dev_on <= 3.0 * hausd
    assert dev_on < 0.5 * dev_off
    m_on = build_adjacency(m_on)
    assert check_adjacency(m_on) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m_on))[np.asarray(m_on.tmask)]
    assert (vols > 0).all()
    # the lifted surface hugs the unit ball: volume within 5% of 4pi/3
    assert abs(vols.sum() - 4.1888) < 0.05 * 4.1888


def test_hausd_metric_bound_refines_curved_boundary():
    """The defsiz route: even a very coarse size request refines curved
    boundaries to sqrt(8*hausd/kappa) (unit sphere: kappa=1)."""
    from parmmg_tpu.ops.metric import hausd_metric_bound
    vert, tet = sphere_mesh(5)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 1.5)                   # "no refinement please"
    met2 = hausd_metric_bound(m, met, hausd=0.005, hmin=1e-3)
    mh = np.asarray(met2)
    vtag = np.asarray(m.vtag)
    vm = np.asarray(m.vmask)
    reg_bdy = vm & ((vtag & C.MG_BDY) != 0) & \
        ((vtag & (C.MG_GEO | C.MG_CRN)) == 0)
    target = np.sqrt(8 * 0.005 / 1.0)             # = 0.2
    assert np.median(mh[reg_bdy]) < 1.5 * target
    # interior sizes untouched
    interior = vm & ((vtag & C.MG_BDY) == 0)
    assert (mh[interior] == 1.5).all()


def _bdy_euler(m):
    """Euler characteristic of the boundary surface (V - E + F)."""
    tm = np.asarray(m.tmask)
    tet = np.asarray(m.tet)[tm]
    ftag = np.asarray(m.ftag)[tm]
    tris = []
    for f in range(4):
        sel = (ftag[:, f] & C.MG_BDY) != 0
        tris.append(np.sort(tet[sel][:, C.IDIR[f]], axis=1))
    tris = np.unique(np.concatenate(tris), axis=0)
    V = len(np.unique(tris.reshape(-1)))
    edges = np.unique(np.sort(np.concatenate(
        [tris[:, [0, 1]], tris[:, [1, 2]], tris[:, [0, 2]]]), axis=1),
        axis=0)
    return V - len(edges) + len(tris)


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_torus_adapt_preserves_topology_and_quality():
    vert, tet = torus_mesh(nu=16, nc=4)
    m = make_mesh(vert, tet, capP=5 * len(vert), capT=5 * len(tet))
    m = analyze_mesh(m).mesh
    assert _bdy_euler(m) == 0                      # genus 1
    vol0 = float(np.asarray(tet_volumes(m))[np.asarray(m.tmask)].sum())
    met = jnp.full(m.capP, 0.3)
    m, met, st = adapt_mesh(m, met, hausd=0.01)
    m = build_adjacency(m)
    assert check_adjacency(m) == {"asymmetric": 0, "face_mismatch": 0}
    assert _bdy_euler(m) == 0                      # still a torus
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert abs(vols.sum() - vol0) < 0.06 * vol0
    q = np.asarray(tet_quality(m))[np.asarray(m.tmask)]
    assert q.min() > 0.05


