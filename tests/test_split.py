"""M1 tests: edge table + batched split waves."""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh, tet_volumes, mesh_to_host
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency, \
    boundary_edge_tags
from parmmg_tpu.ops.edges import unique_edges, edge_lengths
from parmmg_tpu.ops.split import split_wave
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.utils.fixtures import cube_mesh


def _cube(n=2, capmul=8):
    from parmmg_tpu.ops.analysis import analyze_mesh
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert), capT=capmul * len(tet))
    return analyze_mesh(m).mesh


def test_unique_edges_cube():
    m = _cube(2)
    et = unique_edges(m)
    # kuhn cube n=2: vertices 27; edges: 3*n*(n+1)^2 axis + face diags
    # count unique edges by brute force
    ev = np.asarray(et.ev)[np.asarray(et.emask)]
    tets = np.asarray(m.tet)[np.asarray(m.tmask)]
    ref = set()
    for t in tets:
        for a, b in [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3)]:
            ref.add((min(t[a], t[b]), max(t[a], t[b])))
    got = set(map(tuple, ev))
    assert got == ref
    # shell sizes sum to 6 * ntet
    assert int(np.asarray(et.nshell)[np.asarray(et.emask)].sum()) == 6 * len(tets)


def test_split_wave_conforming():
    m = _cube(2)
    met = jnp.full(m.capP, 0.4)  # grid h=0.5 > 0.4*sqrt2? 0.5/0.4=1.25<1.41
    # choose met so the longest edges (body diag sqrt(3)/2=0.866) split:
    # 0.866/0.4 = 2.17 > 1.414 -> candidates
    res = split_wave(m, met)
    assert int(res.nsplit) > 0
    assert not bool(res.overflow)
    m2 = build_adjacency(res.mesh)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m2))
    tm = np.asarray(m2.tmask)
    assert (vols[tm] > 0).all()
    # volume conserved
    assert np.isclose(vols[tm].sum(), 1.0, atol=1e-5)


def test_split_until_converged():
    from parmmg_tpu.ops.adapt import grow_mesh_met
    m = _cube(2)
    met0 = jnp.full(m.capP, 0.30)
    met = met0
    total = 0
    for wave in range(16):
        res = split_wave(m, met)
        m, met = res.mesh, res.met
        ns = int(res.nsplit)
        total += ns
        if bool(res.overflow):
            # capacity exhausted mid-cascade: grow and continue (what the
            # adapt driver does; the overflow guard itself is under test in
            # test_split_overflow_guard)
            m, met = grow_mesh_met(m, met, 2 * m.capP, 2 * m.capT)
            continue
        if ns == 0:
            break
    assert ns == 0, "did not converge"
    assert total > 10
    m = build_adjacency(m)
    assert check_adjacency(m) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, atol=1e-5)
    # all edges now below the split threshold
    et = unique_edges(m)
    lens = np.asarray(edge_lengths(m, et, met))[np.asarray(et.emask)]
    assert lens.max() <= C.LLONG + 1e-5
    # no degenerate quality
    q = np.asarray(tet_quality(m))[np.asarray(m.tmask)]
    assert q.min() > 0.05


def test_split_preserves_boundary_tags():
    m = _cube(2)
    met = jnp.full(m.capP, 0.25)
    for _ in range(10):
        res = split_wave(m, met)
        m, met = res.mesh, res.met
        if int(res.nsplit) == 0:
            break
    # every vertex on the unit-cube surface must be tagged MG_BDY, interior
    # vertices must not
    vert, tet, vref, tref, vtag = mesh_to_host(m)
    on_bdy = ((np.abs(vert) < 1e-6) | (np.abs(vert - 1) < 1e-6)).any(axis=1)
    has_tag = (vtag & C.MG_BDY) != 0
    assert (has_tag == on_bdy).all()


def test_split_respects_frozen_edges():
    m = _cube(2)
    # freeze everything: tag all edges REQ
    import dataclasses
    m = dataclasses.replace(
        m, etag=jnp.where(jnp.ones_like(m.etag, dtype=bool),
                          m.etag | C.MG_REQ, m.etag))
    met = jnp.full(m.capP, 0.1)
    res = split_wave(m, met)
    assert int(res.nsplit) == 0


def test_split_overflow_guard():
    vert, tet = cube_mesh(2)
    m = make_mesh(vert, tet, capP=len(vert) + 2, capT=len(tet) + 4)
    m = boundary_edge_tags(build_adjacency(m))
    met = jnp.full(m.capP, 0.05)
    res = split_wave(m, met)
    # must not crash; at most 2 points inserted
    assert int(res.nsplit) <= 2
    m2 = build_adjacency(res.mesh)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m2))[np.asarray(m2.tmask)]
    assert (vols > 0).all()
