"""Host-only tests for the static invariant linter (parmmg_tpu/lint).

No jax import anywhere in this module — the linter's contract is that
it runs jax-free in seconds, and these tests inherit that (near-zero
tier-1 budget cost).  Each rule gets a known-clean + known-dirty
fixture pair; the engine gets suppression-grammar and baseline-gate
coverage; and the real tree is gated in-process exactly as
``run_tests.sh --lint`` does.
"""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from parmmg_tpu import lint                                    # noqa: E402
from parmmg_tpu.lint import SourceFile, gate, load_baseline    # noqa: E402


def lint_sources(srcs: dict, rules, readme_text: str = ""):
    """Run a rule subset over literal {relpath: source} fixtures."""
    files = {rel: SourceFile(rel, txt) for rel, txt in srcs.items()}
    return lint.run_lint(rules=rules, files=files,
                         readme_text=readme_text)


def keys(report):
    return sorted(v.key for v in report.violations)


# ---------------------------------------------------------------------------
# R1 jit-hygiene
# ---------------------------------------------------------------------------
R1_CLEAN = '''
import jax
from functools import lru_cache, partial

analyze = jax.jit(lambda x: x)                    # module assignment

@partial(jax.jit, static_argnames=("n",))         # module decorator
def stepper(x, n):
    return x

_CACHE = {}

def builder(key):                                 # CAPS cache store
    if key in _CACHE:
        return _CACHE[key]
    @jax.jit
    def run(x):
        return x
    _CACHE[key] = run
    return run

@lru_cache(maxsize=None)                          # lru_cache builder
def cached_builder(n):
    return jax.jit(lambda x: x + n)

def governed_builder(spec):
    from parmmg_tpu.utils.compilecache import governed
    return governed("x.y", budget=2)(jax.jit(lambda x: x))

def _make():
    return jax.jit(lambda x: x)

made_once = _make()                               # built at module level

class Steps:
    def __init__(self):
        self.fn = jax.jit(lambda x: x)            # instance cache
'''

R1_DIRTY = '''
import jax

def hot_loop(x):
    fn = jax.jit(lambda a: a + 1)                 # fresh jit per call
    return fn(x)
'''


def test_r1_accepts_every_cache_idiom():
    rep = lint_sources({"parmmg_tpu/ops/clean.py": R1_CLEAN}, ["R1"])
    assert keys(rep) == []


def test_r1_flags_per_call_jit():
    rep = lint_sources({"parmmg_tpu/ops/dirty.py": R1_DIRTY}, ["R1"])
    assert len(rep.violations) == 1
    v = rep.violations[0]
    assert v.rule == "R1" and v.scope == "hot_loop"
    assert v.detail == "jax.jit"


def test_r1_flags_shard_map_alias():
    src = ("from parmmg_tpu.utils.jaxcompat import shard_map\n"
           "def f(mesh):\n"
           "    return shard_map(lambda x: x, mesh=mesh,\n"
           "                     in_specs=None, out_specs=None)\n")
    rep = lint_sources({"parmmg_tpu/parallel/x.py": src}, ["R1"])
    assert [v.detail for v in rep.violations] == ["shard_map"]


def test_r1_module_level_decorator_not_flagged():
    src = ("import jax\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return x\n")
    rep = lint_sources({"parmmg_tpu/ops/x.py": src}, ["R1"])
    assert keys(rep) == []


# ---------------------------------------------------------------------------
# R2 host-sync reachability
# ---------------------------------------------------------------------------
R2_DIRTY = '''
import numpy as np

def grouped_adapt_pass(state):                    # root
    return helper(state)

def helper(state):                                # reachable
    return np.asarray(state)

def cold_path(state):                             # NOT reachable
    return np.asarray(state)
'''


def test_r2_reachability_flags_hot_not_cold():
    rep = lint_sources({"parmmg_tpu/parallel/x.py": R2_DIRTY}, ["R2"])
    scopes = sorted(v.scope for v in rep.violations)
    assert scopes == ["grouped_adapt_pass", "helper"] or \
        scopes == ["helper"]
    assert all(v.detail == "np.asarray" for v in rep.violations)
    assert not any(v.scope == "cold_path" for v in rep.violations)


def test_r2_def_line_suppression_exempts_function():
    src = ('import numpy as np\n'
           'def grouped_adapt_pass(s):\n'
           '    return fallback(s)\n'
           '# lint: ok(R2) — documented KS-overflow host fallback\n'
           'def fallback(s):\n'
           '    return np.asarray(s)\n')
    rep = lint_sources({"parmmg_tpu/parallel/x.py": src}, ["R2"])
    assert keys(rep) == []
    # the def-line exemption is a recorded suppression, not a silent
    # drop — the audit listing must show the (violation, reason) pair
    assert len(rep.suppressed) == 1
    v, s = rep.suppressed[0]
    assert v.rule == "R2" and "fallback" in s.reason


def test_r2_env_read_cast_not_flagged():
    src = ('import os\n'
           'def grouped_adapt_pass(s):\n'
           '    return float(os.environ.get("X", "0"))\n')
    rep = lint_sources({"parmmg_tpu/parallel/x.py": src}, ["R2"])
    assert keys(rep) == []


def test_r2_def_suppression_on_decorated_function():
    src = ('import functools\n'
           'import numpy as np\n'
           'def grouped_adapt_pass(s):\n'
           '    return fallback(s)\n'
           '# lint: ok(R2) — documented host fallback (decorated)\n'
           '@functools.wraps(print)\n'
           'def fallback(s):\n'
           '    return np.asarray(s)\n')
    rep = lint_sources({"parmmg_tpu/parallel/x.py": src}, ["R2"])
    assert keys(rep) == [] and len(rep.suppressed) == 1


def test_r1_governed_does_not_exempt_sibling_jit():
    # a governed program in the function must NOT blanket-exempt a
    # second, per-call bare jit built in the same function
    src = ('import jax\n'
           'from parmmg_tpu.utils.compilecache import governed\n'
           'def builder():\n'
           '    good = governed("x.y", budget=1)(jax.jit(lambda x: x))\n'
           '    bad = jax.jit(lambda y: y + 1)\n'
           '    return good, bad\n')
    rep = lint_sources({"parmmg_tpu/ops/x.py": src}, ["R1"])
    assert len(rep.violations) == 1
    assert rep.violations[0].line == 5


def test_r1_shard_map_wrapper_ok_when_builder_governs():
    # the dist_adapt_block idiom: bare shard_map wrap, jit governed in
    # a later statement of the same builder
    src = ('import jax\n'
           'from parmmg_tpu.utils.jaxcompat import shard_map\n'
           'from parmmg_tpu.utils.compilecache import governed\n'
           'def builder(dmesh, spec):\n'
           '    fn = shard_map(lambda x: x, mesh=dmesh,\n'
           '                   in_specs=spec, out_specs=spec)\n'
           '    return governed("d.block")(jax.jit(fn))\n')
    rep = lint_sources({"parmmg_tpu/parallel/x.py": src}, ["R1"])
    assert keys(rep) == []


# ---------------------------------------------------------------------------
# R3 obs-routing
# ---------------------------------------------------------------------------
def test_r3_flags_print_outside_obs_only():
    srcs = {
        "parmmg_tpu/ops/a.py": "def f():\n    print('x')\n",
        "parmmg_tpu/obs/b.py": "def g():\n    print('x')\n",
        "scripts/c.py": "print('artifact')\n",
    }
    rep = lint_sources(srcs, ["R3"])
    assert [v.path for v in rep.violations] == ["parmmg_tpu/ops/a.py"]


def test_r3_suppression_with_reason_is_honoured():
    src = "def f():\n    print('x')  # lint: ok(R3) — stdout contract\n"
    rep = lint_sources({"parmmg_tpu/ops/a.py": src}, ["R3"])
    assert keys(rep) == []
    assert len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# R4 knob registry
# ---------------------------------------------------------------------------
KNOBS_FIXTURE = '''
class Knob:
    def __init__(self, type, default, doc): pass

KNOBS = {
    "PARMMG_GOOD": Knob("int", "1", "a used knob"),
    "PARMMG_DEAD": Knob("int", "0", "nothing reads this"),
}
'''

R4_READS = '''
import os
a = os.environ.get("PARMMG_GOOD", "1")
b = os.environ.get("PARMMG_ROGUE", "")
'''


def test_r4_unregistered_read_dead_knob_and_readme_drift():
    rep = lint_sources(
        {"parmmg_tpu/api/knobs.py": KNOBS_FIXTURE,
         "parmmg_tpu/ops/x.py": R4_READS},
        ["R4"], readme_text="only PARMMG_GOOD and PARMMG_GHOST here")
    det = sorted((v.detail, v.path) for v in rep.violations)
    # rogue read, dead knob, dead knob missing from README, ghost in README
    assert ("PARMMG_ROGUE", "parmmg_tpu/ops/x.py") in det
    assert ("PARMMG_DEAD", "parmmg_tpu/api/knobs.py") in det
    assert ("PARMMG_GHOST", "README.md") in det
    msgs = [v.message for v in rep.violations
            if v.detail == "PARMMG_DEAD"]
    assert any("no usage" in m for m in msgs)
    assert any("missing from README" in m for m in msgs)


def test_r4_clean_when_registry_readme_and_reads_agree():
    rep = lint_sources(
        {"parmmg_tpu/api/knobs.py": KNOBS_FIXTURE.replace(
            '    "PARMMG_DEAD": Knob("int", "0", "nothing reads this"),\n',
            ""),
         "parmmg_tpu/ops/x.py":
             'import os\nv = os.environ.get("PARMMG_GOOD", "1")\n'},
        ["R4"], readme_text="`PARMMG_GOOD` does things")
    assert keys(rep) == []


def test_r4_helper_env_reader_is_scanned():
    src = ('def _env_int(name, d):\n'
           '    import os\n'
           '    return int(os.environ.get(name, str(d)) or d)\n'
           'v = _env_int("PARMMG_NOT_DECLARED", 4)\n')
    rep = lint_sources(
        {"parmmg_tpu/api/knobs.py": KNOBS_FIXTURE,
         "parmmg_tpu/serve/x.py": src},
        ["R4"], readme_text="PARMMG_GOOD PARMMG_DEAD")
    assert any(v.detail == "PARMMG_NOT_DECLARED"
               for v in rep.violations)


# ---------------------------------------------------------------------------
# R5 jaxcompat
# ---------------------------------------------------------------------------
def test_r5_flags_direct_shim_spellings():
    srcs = {
        "parmmg_tpu/parallel/bad1.py":
            "from jax.experimental.shard_map import shard_map\n",
        "parmmg_tpu/parallel/bad2.py":
            "import jax\nn = jax.lax.axis_size('shard')\n",
        "parmmg_tpu/utils/jaxcompat.py":
            "from jax.experimental.shard_map import shard_map\n",
    }
    rep = lint_sources(srcs, ["R5"])
    paths = sorted(v.path for v in rep.violations)
    assert paths == ["parmmg_tpu/parallel/bad1.py",
                     "parmmg_tpu/parallel/bad2.py"]


def test_r5_flags_plain_module_import():
    src = "import jax.experimental.shard_map as sm\n"
    rep = lint_sources({"parmmg_tpu/parallel/bad3.py": src}, ["R5"])
    assert [v.detail for v in rep.violations] == \
        ["jax.experimental.shard_map"]


def test_r5_shim_import_is_clean():
    src = "from parmmg_tpu.utils.jaxcompat import shard_map, axis_size\n"
    rep = lint_sources({"parmmg_tpu/parallel/ok.py": src}, ["R5"])
    assert keys(rep) == []


# ---------------------------------------------------------------------------
# R6 name schemes
# ---------------------------------------------------------------------------
FAULTS_FIXTURE = 'SITES = {"polish.worker": "exit", "halo.exchange": "xla"}\n'
RECOVER_FIXTURE = 'LADDER = ("retry", "halo_dense", "lowfailure")\n'


def _r6(src):
    return lint_sources(
        {"parmmg_tpu/resilience/faults.py": FAULTS_FIXTURE,
         "parmmg_tpu/resilience/recover.py": RECOVER_FIXTURE,
         "parmmg_tpu/serve/x.py": src}, ["R6"])


def test_r6_dynamic_and_malformed_names():
    rep = _r6('from parmmg_tpu.obs.metrics import REGISTRY\n'
              'def f(k):\n'
              '    REGISTRY.counter(f"serve.{k}").inc()\n'
              '    REGISTRY.gauge("Serve.BadCase").set(1)\n'
              '    REGISTRY.counter("serve.ok").inc()\n')
    det = sorted(v.detail for v in rep.violations)
    assert det == ["metric.counter:dynamic",
                   "metric.gauge:Serve.BadCase"]


def test_r6_ifexp_over_literals_is_static():
    rep = _r6('from parmmg_tpu.obs.metrics import REGISTRY\n'
              'def f(ok):\n'
              '    REGISTRY.counter("a.ok" if ok else "a.bad").inc()\n')
    assert keys(rep) == []


def test_r6_faultpoint_site_must_be_registered():
    rep = _r6('from parmmg_tpu.resilience.faults import faultpoint\n'
              'def f():\n'
              '    faultpoint("halo.exchange")\n'
              '    faultpoint("made.up_site")\n')
    assert [v.detail for v in rep.violations] == \
        ["faultpoint:made.up_site"]


def test_r6_ladder_step_must_be_registered():
    rep = _r6('from parmmg_tpu.resilience.recover import ladder_step\n'
              'def f():\n'
              '    ladder_step("halo_dense", site="halo.exchange")\n'
              '    ladder_step("wishful_step")\n')
    assert [v.detail for v in rep.violations] == \
        ["ladder_step:wishful_step"]


# ---------------------------------------------------------------------------
# suppression grammar
# ---------------------------------------------------------------------------
def test_suppression_without_reason_fails():
    # concatenation keeps this *invalid* example from matching when
    # the real-tree scan reads this test file's own source
    src = "def f():\n    print('x')  # lint: " + "ok(R3)\n"
    rep = lint_sources({"parmmg_tpu/ops/a.py": src}, ["R3"])
    # the print is NOT suppressed and the bad suppression is reported
    assert len(rep.violations) == 1
    assert len(rep.bad) == 1 and rep.bad[0].rule == "SUPP"
    res = gate(rep, {})
    assert not res.ok


def test_suppression_unknown_rule_fails():
    src = "x = 1  # lint: " + "ok(R99) — sounds official\n"
    rep = lint_sources({"parmmg_tpu/ops/a.py": src}, ["R3"])
    assert len(rep.bad) == 1
    assert "unknown rule" in rep.bad[0].message


def test_standalone_suppression_skips_continuation_comments():
    src = ("def f():\n"
           "    # lint: ok(R3) — a reason that wraps onto the\n"
           "    # next comment line before the code\n"
           "    print('x')\n")
    rep = lint_sources({"parmmg_tpu/ops/a.py": src}, ["R3"])
    assert keys(rep) == [] and len(rep.suppressed) == 1


# ---------------------------------------------------------------------------
# baseline gate semantics
# ---------------------------------------------------------------------------
def test_baseline_count_pinning_and_retirement():
    two = "def f():\n    print('a')\n    print('b')\n"
    rep = lint_sources({"parmmg_tpu/ops/a.py": two}, ["R3"])
    key = rep.violations[0].key
    assert all(v.key == key for v in rep.violations)

    # exact count: clean
    assert gate(rep, {key: 2}).ok
    # count above ceiling: the excess is new
    res = gate(rep, {key: 1})
    assert not res.ok and len(res.new) == 1
    # unknown key in the baseline shows as retired (burn-down)
    res = gate(rep, {key: 2, "R3:parmmg_tpu/ops/gone.py:f:print": 3})
    assert res.ok and res.burndown["R3"]["retired"] == 3


def test_baseline_never_applies_to_r4():
    rep = lint_sources(
        {"parmmg_tpu/api/knobs.py": KNOBS_FIXTURE,
         "parmmg_tpu/ops/x.py":
             'import os\nv = os.environ.get("PARMMG_ROGUE", "")\n'},
        ["R4"], readme_text="PARMMG_GOOD PARMMG_DEAD mentioned")
    rogue = [v for v in rep.violations if v.detail == "PARMMG_ROGUE"]
    assert rogue
    res = gate(rep, {rogue[0].key: 99})      # grandfathering ignored
    assert any(v.detail == "PARMMG_ROGUE" for v in res.new)


def test_baseline_payload_roundtrip(tmp_path):
    rep = lint_sources(
        {"parmmg_tpu/ops/a.py": "def f():\n    print('x')\n"}, ["R3"])
    payload = lint.baseline_payload(rep)
    p = tmp_path / "lint_baseline.json"
    p.write_text(json.dumps(payload))
    loaded = load_baseline(str(p))
    assert gate(rep, loaded).ok


# ---------------------------------------------------------------------------
# the real tree (the tier-1 inclusion of the gate)
# ---------------------------------------------------------------------------
def test_repo_tree_is_lint_clean():
    report = lint.run_lint(ROOT)
    result = gate(report, load_baseline(
        os.path.join(ROOT, "lint_baseline.json")))
    assert result.ok, lint.format_report(report, result)
    # every suppression in the tree carries a reason by construction;
    # R4 must be exactly clean (no baseline key can hide it)
    assert not any(k.startswith("R4:") for k in load_baseline(
        os.path.join(ROOT, "lint_baseline.json")))


def test_knob_registry_matches_readme_table():
    # the README table is generated from the registry; regenerating it
    # in-process must cover every registered knob name
    from parmmg_tpu.api import knobs
    table = knobs.knob_table_md()
    for name in knobs.registered():
        assert f"`{name}`" in table
    with open(os.path.join(ROOT, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    for name in knobs.registered():
        assert name in readme


def test_knobs_get_rejects_undeclared():
    from parmmg_tpu.api import knobs
    with pytest.raises(KeyError):
        knobs.get("PARMMG_NOT_A_KNOB")
    assert knobs.get("PARMMG_TRACE_RING") in ("4096",) or \
        knobs.get("PARMMG_TRACE_RING") == os.environ.get(
            "PARMMG_TRACE_RING")


def test_unknown_rule_id_is_a_usage_error():
    with pytest.raises(ValueError, match="unknown lint rule"):
        lint.run_lint(rules=("R99",), files={})
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "lint_check.py"),
         "--rules", "R99"],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 2
    assert "unknown lint rule" in r.stderr


def test_lint_cli_runs_jaxfree_and_green():
    # subprocess: verifies the gate end-to-end INCLUDING the linter's
    # own "never imported jax" self-check (rc 2 if it ever does)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "lint_check.py")],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "lint: OK" in r.stdout


def test_linter_itself_imports_no_jax():
    # in-process guard: importing the lint package must not drag jax in
    # (only meaningful when jax is not already loaded by earlier tests)
    r = subprocess.run(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, %r); "
         "import parmmg_tpu.lint; "
         "sys.exit(1 if 'jax' in sys.modules else 0)" % ROOT],
        capture_output=True, timeout=60)
    assert r.returncode == 0
