"""O(band) device-resident migration path (parallel/migrate_dev.py).

The reference touches only moving groups and OLDPARBDY entities between
iterations (distributegrps_pmmg.c:1631-1841, analys_pmmg.c:1571); the
band path must reproduce the full-view path's results while keeping the
host work band/interface-sized.  The full-view path (parallel/migrate.py)
is the oracle here.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import make_mesh, tet_volumes, mesh_to_host
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.parallel import dist, migrate
from parmmg_tpu.parallel.distribute import split_to_shards
from parmmg_tpu.parallel.comms import build_interface_comms
from parmmg_tpu.utils.fixtures import cube_mesh

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def _two_shards(n=2, capmul=4):
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert),
                  capT=capmul * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.5, m.vert.dtype)
    vert_h, tet_h, _, _, _ = mesh_to_host(m)
    cent = vert_h[tet_h].mean(axis=1)
    part = (cent[:, 0] > 0.5).astype(np.int32)
    s, ms, l2g = split_to_shards(m, met, part, 2, cap_mult=3.0,
                                 return_l2g=True)
    g2l = []
    for s_ in range(2):
        mm = np.full(len(vert_h), -1, np.int64)
        mm[l2g[s_]] = np.arange(len(l2g[s_]))
        g2l.append(mm)
    comms = build_interface_comms(tet_h, part, 2, l2g, g2l)
    capP = s.vert.shape[1]
    glo = [np.full(capP, -1, np.int64) for _ in range(2)]
    for s_ in range(2):
        glo[s_][: len(l2g[s_])] = l2g[s_]
    return s, ms, glo, comms, len(vert_h)


def _tet_key_sets(stacked, glo, S):
    """Per-shard frozenset of sorted global tet keys (slot-order free)."""
    tm = np.asarray(stacked.tmask)
    tet = np.asarray(stacked.tet)
    out = []
    for s in range(S):
        rows = tet[s][tm[s]]
        keys = np.sort(glo[s][rows], axis=1)
        out.append({tuple(k) for k in keys})
    return out


def test_device_migrate_matches_host_oracle():
    """Moving a hand-picked band through the device path must yield the
    same per-shard tet sets, liveness, and interface tables as the
    full-view host path."""
    from parmmg_tpu.parallel.migrate_dev import band_migrate_iteration

    # --- device path ------------------------------------------------------
    s_d, ms_d, glo_d_host, comms, nv = _two_shards()
    capT = s_d.tet.shape[1]
    tm0 = np.asarray(s_d.tmask)
    # move the first 3 live tets of shard 0 to shard 1
    mv = np.where(tm0[0])[0][:3]
    labels = np.tile(np.arange(2, dtype=np.int32)[:, None], (1, capT))
    labels[0, mv] = 1
    depth = np.zeros((2, capT), np.int32)
    glo_dev = jnp.asarray(np.stack(glo_d_host).astype(np.int32))
    shared_prev = np.unique(np.concatenate(
        [glo_d_host[s][np.unique(
            comms.node_idx[s][comms.node_idx[s] >= 0])]
         for s in range(2)]))
    glo_dev_mirror = [g.copy() for g in glo_d_host]
    res = band_migrate_iteration(
        s_d, ms_d, glo_dev, glo_dev_mirror, jnp.asarray(labels),
        jnp.asarray(depth), shared_prev, 2)
    assert res is not None, "band budgets must hold on this fixture"
    out_d, met_d, glo_dev2, comms_d, shared_now, nmoved_d, arr = res
    assert nmoved_d == 3

    # --- host oracle ------------------------------------------------------
    s_h, ms_h, glo_h, comms0, _ = _two_shards()
    views = migrate.pull_views(s_h, ms_h)
    out_h, met_h, comms_h, nmoved_h = migrate.migrate_shards(
        s_h, ms_h, views, glo_h, labels, 2)
    assert nmoved_h == 3

    # --- parity -----------------------------------------------------------
    keys_d = _tet_key_sets(out_d, glo_dev_mirror, 2)
    keys_h = _tet_key_sets(out_h, glo_h, 2)
    assert keys_d == keys_h
    # device glo copy in lockstep with its host mirror (where live)
    g2 = np.asarray(glo_dev2)
    vm = np.asarray(out_d.vmask)
    for s in range(2):
        assert (g2[s][vm[s]] == glo_dev_mirror[s][vm[s]]).all()
        assert (glo_dev_mirror[s][~vm[s]] == -1).all()
    # same interface size (by construction of the same final partition)
    nd = int((comms_d.face_idx >= 0).sum())
    nh = int((comms_h.face_idx >= 0).sum())
    assert nd == nh
    # frozen faces agree as GLOBAL key sets
    def frozen_faces(stacked, glo):
        tm = np.asarray(stacked.tmask)
        ftag = np.asarray(stacked.ftag)
        tet = np.asarray(stacked.tet)
        out = set()
        for s in range(2):
            r, c = np.where(((ftag[s] & C.MG_PARBDY) != 0)
                            & tm[s][:, None])
            tri = np.sort(glo[s][tet[s][r]][
                np.arange(len(r))[:, None], C.IDIR[c]], axis=1)
            out |= {tuple(k) for k in tri}
        return out
    assert frozen_faces(out_d, glo_dev_mirror) == \
        frozen_faces(out_h, glo_h)


def test_band_path_engages_no_full_pull():
    """The default ifc loop must run without a single full views pull
    (the O(mesh) host transfer the band path exists to remove)."""
    calls = {"n": 0}
    orig = migrate.pull_views

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    migrate.pull_views = counting
    try:
        vert, tet = cube_mesh(3)
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.full(m.capP, 0.3, m.vert.dtype)
        out, met2, part = dist.distributed_adapt_multi(
            m, met, 4, niter=2, cycles=3)
    finally:
        migrate.pull_views = orig
    assert calls["n"] == 0, \
        "band path must not pull full shard views between iterations"
    out = build_adjacency(out)
    assert check_adjacency(out) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    q = np.asarray(tet_quality(out, met2))[np.asarray(out.tmask)]
    assert q.min() > 0.02


def test_band_and_full_paths_agree_statistically():
    """Same run with the band path forced OFF: both paths must deliver a
    conforming unit cube of comparable size and quality (tie-order
    differences make bitwise equality too strict)."""
    import os
    results = {}
    for flag in ("1", "0"):
        os.environ["PARMMG_BAND_PATH"] = flag
        try:
            vert, tet = cube_mesh(2)
            m = make_mesh(vert, tet, capP=6 * len(vert),
                          capT=6 * len(tet))
            m = analyze_mesh(m).mesh
            met = jnp.full(m.capP, 0.4, m.vert.dtype)
            out, met2, part = dist.distributed_adapt_multi(
                m, met, 2, niter=2, cycles=2)
        finally:
            os.environ.pop("PARMMG_BAND_PATH", None)
        vols = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
        assert (vols > 0).all()
        assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
        results[flag] = int(np.asarray(out.tmask).sum())
    a, b = results["1"], results["0"]
    assert abs(a - b) <= 0.3 * max(a, b)


def test_graph_mode_band_path_no_full_pull():
    """Graph mode must also run without a full views pull: the cluster
    graph comes from device-compacted tables
    (migrate_dev.graph_repartition_labels_band, the metis_pmmg.c
    gather-only-the-graph role)."""
    calls = {"n": 0}
    orig = migrate.pull_views

    def counting(*a, **k):
        calls["n"] += 1
        return orig(*a, **k)

    migrate.pull_views = counting
    try:
        vert, tet = cube_mesh(3)
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.full(m.capP, 0.3, m.vert.dtype)
        out, met2, part = dist.distributed_adapt_multi(
            m, met, 4, niter=3, cycles=3, mode="graph")
    finally:
        migrate.pull_views = orig
    assert calls["n"] == 0, \
        "graph mode must not pull full shard views between iterations"
    out = build_adjacency(out)
    assert check_adjacency(out) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(out))[np.asarray(out.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    q = np.asarray(tet_quality(out, met2))[np.asarray(out.tmask)]
    assert q.min() > 0.02
