"""Communicator-layer tests: construction, oracle, numbering, exchange.

Mirrors the role of the reference's chkcomm assertions (§2.3) and the
Check_Set/Get communicator API tests; the halo-exchange test is the
device-side coordinate echo under an 8-device shard_map.
"""
import numpy as np
import pytest

from parmmg_tpu.parallel.comms import (
    build_interface_comms, global_node_numbering, check_node_comms,
    check_face_comms, halo_exchange, merge_owner_max)
from parmmg_tpu.parallel.partition import morton_partition, fix_contiguity
from parmmg_tpu.utils.fixtures import cube_mesh


def _partitioned(n=3, nparts=4):
    vert, tet = cube_mesh(n)
    cent = vert[tet].mean(axis=1)
    part = fix_contiguity(tet, morton_partition(cent, nparts))
    l2g, g2l = [], []
    for s in range(nparts):
        used = np.zeros(len(vert), bool)
        used[tet[part == s].reshape(-1)] = True
        gids = np.where(used)[0]
        m = np.full(len(vert), -1, np.int64)
        m[gids] = np.arange(len(gids))
        l2g.append(gids)
        g2l.append(m)
    return vert, tet, part, l2g, g2l


def test_comm_construction_and_oracle():
    vert, tet, part, l2g, g2l = _partitioned()
    comms = build_interface_comms(tet, part, 4, l2g, g2l)
    verts = [vert[l2g[s]] for s in range(4)]
    tets = []
    for s in range(4):
        lt = g2l[s][tet[part == s]]
        tets.append(lt.astype(np.int64))
    chk = check_node_comms(comms, verts)
    assert chk["mismatch"] == 0
    assert chk["items_checked"] > 0
    chkf = check_face_comms(comms, tets, verts)
    assert chkf["mismatch"] == 0
    assert chkf["items_checked"] > 0


def test_comm_oracle_detects_breakage():
    vert, tet, part, l2g, g2l = _partitioned()
    comms = build_interface_comms(tet, part, 4, l2g, g2l)
    verts = [vert[l2g[s]] for s in range(4)]
    # corrupt one shard's coordinates
    verts[1] = verts[1] + 0.5
    chk = check_node_comms(comms, verts)
    assert chk["mismatch"] > 0


def test_global_node_numbering():
    vert, tet, part, l2g, g2l = _partitioned()
    comms = build_interface_comms(tet, part, 4, l2g, g2l)
    glo = global_node_numbering(comms, [len(l) for l in l2g])
    # every vertex numbered, numbers agree across copies, dense coverage
    seen = {}
    for s in range(4):
        assert (glo[s] > 0).all()
        for li, g in enumerate(l2g[s]):
            if g in seen:
                assert seen[g] == glo[s][li], "copies disagree"
            else:
                seen[g] = glo[s][li]
    nums = sorted(seen.values())
    assert nums == list(range(1, len(vert) + 1))


def test_owner_is_max_shard():
    vert, tet, part, l2g, g2l = _partitioned()
    comms = build_interface_comms(tet, part, 4, l2g, g2l)
    # oracle: recompute incidence directly
    for s in range(4):
        for li, g in enumerate(l2g[s]):
            shards = [r for r in range(4) if g2l[r][g] >= 0]
            assert comms.owner[s][li] == max(shards)


def test_halo_exchange_coordinate_echo():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh as DeviceMesh, PartitionSpec as P
    from parmmg_tpu.utils.jaxcompat import shard_map

    vert, tet, part, l2g, g2l = _partitioned(n=2, nparts=4)
    comms = build_interface_comms(tet, part, 4, l2g, g2l)
    S, K, In = comms.node_idx.shape
    maxP = max(len(l) for l in l2g)
    coords = np.zeros((S, maxP, 3))
    for s in range(S):
        coords[s, : len(l2g[s])] = vert[l2g[s]]

    devs = jax.devices()[:4]
    dmesh = DeviceMesh(np.array(devs), ("shard",))

    def body(coords_s, sidx_s, nbr_s):
        c, si, nb = coords_s[0], sidx_s[0], nbr_s[0]
        recv = halo_exchange(c, si, nb)                  # [K, I, 3]
        mine = jnp.where(si >= 0, 0, -1)
        safe = jnp.clip(si, 0, c.shape[0] - 1)
        own = c[safe]
        diff = jnp.where((si >= 0)[..., None],
                         jnp.abs(recv - own), 0.0)
        return jnp.max(diff)[None]

    fn = shard_map(body, mesh=dmesh,
                   in_specs=(P("shard"), P("shard"), P("shard")),
                   out_specs=P("shard"), check_vma=False)
    out = jax.jit(fn)(jnp.asarray(coords),
                      jnp.asarray(comms.node_idx),
                      jnp.asarray(comms.nbr))
    assert float(np.max(np.asarray(out))) < 1e-12


def test_merge_owner_max():
    import jax.numpy as jnp
    vals = jnp.asarray(np.array([1.0, 5.0, 2.0, 0.0]))
    send_idx = jnp.asarray(np.array([[0, 2, -1]]))
    recv = jnp.asarray(np.array([[3.0, 1.0, 99.0]]))
    out = merge_owner_max(vals, send_idx, recv)
    assert np.allclose(np.asarray(out), [3.0, 5.0, 2.0, 0.0])


def test_multihost_single_process_degenerate():
    """Multi-host backend helpers in the NP=1 degenerate form (the
    reference CI always includes NP=1; real multi-process follows the
    jax.distributed contract, parallel/multihost.py)."""
    import jax
    from parmmg_tpu.parallel.multihost import (
        init_multihost, is_multiprocess, shard_stacked_global,
        require_single_process)
    from parmmg_tpu.parallel.dist import make_device_mesh

    assert init_multihost() is False          # no coordinator set
    assert is_multiprocess() is False
    require_single_process("test stage")      # must not raise at NP=1
    dmesh = make_device_mesh(4)
    x = {"a": np.arange(8, dtype=np.float32).reshape(4, 2)}
    y = shard_stacked_global(x, dmesh)
    assert np.allclose(np.asarray(y["a"]), x["a"])
    assert len(y["a"].sharding.device_set) == 4


def test_sort_based_builder_bit_identical_to_reference():
    """VERDICT r2 #8 'Done' gate: the sort-based construction must
    produce tables bit-identical to the dense/loop reference builder."""
    from parmmg_tpu.parallel.comms import (build_interface_comms,
                                           build_interface_comms_ref)
    vert, tet, part, l2g, g2l = _partitioned(n=4, nparts=8)
    a = build_interface_comms(tet, part, 8, l2g, g2l)
    b = build_interface_comms_ref(tet, part, 8, l2g, g2l)
    assert np.array_equal(a.nbr, b.nbr)
    assert np.array_equal(a.node_idx, b.node_idx)
    assert np.array_equal(a.node_cnt, b.node_cnt)
    assert np.array_equal(a.face_idx, b.face_idx)
    assert np.array_equal(a.face_cnt, b.face_cnt)
    for oa, ob in zip(a.owner, b.owner):
        assert np.array_equal(oa, ob)


def test_builder_handles_64_parts():
    """S=64 synthetic split: construction in seconds, echo clean."""
    import time
    from parmmg_tpu.parallel.comms import (build_interface_comms,
                                           check_node_comms)
    vert, tet, part, l2g, g2l = _partitioned(n=8, nparts=64)
    t0 = time.perf_counter()
    comms = build_interface_comms(tet, part, 64, l2g, g2l)
    dt = time.perf_counter() - t0
    assert dt < 30.0, f"construction took {dt:.1f}s"
    verts = [vert[l2g[s]] for s in range(64)]
    chk = check_node_comms(comms, verts)
    assert chk["mismatch"] == 0
    assert chk["items_checked"] > 0
