"""Parallel (cross-shard) surface analysis vs the global oracle.

The decisive invariant (reference behavior contract, analys_pmmg.c): the
distributed analysis must classify every interface vertex exactly as the
sequential analysis of the merged mesh would — ridges crossing shard
boundaries included.
"""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.parallel.comms import build_interface_comms
from parmmg_tpu.parallel.analysis_par import analyze_shards
from parmmg_tpu.parallel.partition import morton_partition, fix_contiguity
from parmmg_tpu.utils.fixtures import cube_mesh
from parmmg_tpu.core.constants import IDIR


def _shard_arrays(vert, tet, part, nparts):
    """Build per-shard host arrays + ftags with MG_BDY / MG_PARBDY set."""
    # global boundary faces: unmatched sorted triples
    n = len(tet)
    faces = np.sort(tet[:, IDIR].reshape(n * 4, 3), axis=1)
    key = (faces[:, 0].astype(np.int64) << 42) | \
          (faces[:, 1].astype(np.int64) << 21) | faces[:, 2].astype(np.int64)
    uniq, cnts = np.unique(key, return_counts=True)
    bdy_keys = set(uniq[cnts == 1].tolist())

    verts, tets, ftags, frefs, l2g, g2l = [], [], [], [], [], []
    for s in range(nparts):
        sel = part == s
        ltet_g = tet[sel]
        used = np.zeros(len(vert), bool)
        used[ltet_g.reshape(-1)] = True
        gids = np.where(used)[0]
        m = np.full(len(vert), -1, np.int64)
        m[gids] = np.arange(len(gids))
        lt = m[ltet_g]
        lv = vert[gids]
        # local ftags
        nt = len(lt)
        lf = np.sort(lt[:, IDIR].reshape(nt * 4, 3), axis=1)
        lkey = (gids[lf[:, 0]].astype(np.int64) << 42) | \
               (gids[lf[:, 1]].astype(np.int64) << 21) | \
               gids[lf[:, 2]].astype(np.int64)
        lu, lc = np.unique(lkey, return_counts=True)
        ccount = dict(zip(lu.tolist(), lc.tolist()))
        ft = np.zeros((nt, 4), np.uint32)
        for i in range(nt):
            for f in range(4):
                k = int(lkey[4 * i + f])
                if ccount[k] == 1:             # locally unmatched
                    if k in bdy_keys:
                        ft[i, f] = C.MG_BDY
                    else:
                        ft[i, f] = C.MG_BDY | C.MG_PARBDY
        verts.append(lv)
        tets.append(lt.astype(np.int64))
        ftags.append(ft)
        frefs.append(np.zeros((nt, 4), np.int32))
        l2g.append(gids)
        g2l.append(m)
    return verts, tets, ftags, frefs, l2g, g2l


def test_shard_analysis_matches_global():
    vert, tet = cube_mesh(3)
    part = fix_contiguity(tet, morton_partition(
        vert[tet].mean(axis=1), 4))
    verts, tets, ftags, frefs, l2g, g2l = _shard_arrays(vert, tet, part, 4)
    comms = build_interface_comms(tet, part, 4, l2g, g2l)
    vtag_add, special_edges, vnormal = analyze_shards(
        verts, tets, ftags, frefs, comms)

    # global oracle
    gm = make_mesh(vert, tet, capP=len(vert), capT=len(tet))
    res = analyze_mesh(gm)
    gtag = np.asarray(res.mesh.vtag)
    gn = np.asarray(res.vnormal)

    CHECK = C.MG_BDY | C.MG_GEO | C.MG_CRN
    for s in range(4):
        got = vtag_add[s] & CHECK
        want = gtag[l2g[s]] & CHECK
        bad = np.where(got != want)[0]
        assert len(bad) == 0, \
            f"shard {s}: {len(bad)} misclassified, e.g. local {bad[:5]} " \
            f"got {got[bad[:5]]} want {want[bad[:5]]}"
        # normals agree wherever defined
        nl = np.linalg.norm(vnormal[s], axis=1) > 0.5
        dots = np.einsum("ij,ij->i", vnormal[s][nl], gn[l2g[s]][nl])
        assert (dots > 0.999).all()


def test_cross_shard_ridge_detected():
    """A ridge running along the partition interface must be found even
    though its two supporting faces live in different shards."""
    vert, tet = cube_mesh(2)
    # partition by z so the vertical cube edges cross the interface
    cent = vert[tet].mean(axis=1)
    part = (cent[:, 2] > 0.5).astype(np.int32)
    verts, tets, ftags, frefs, l2g, g2l = _shard_arrays(vert, tet, part, 2)
    comms = build_interface_comms(tet, part, 2, l2g, g2l)
    vtag_add, special_edges, _ = analyze_shards(
        verts, tets, ftags, frefs, comms)
    # vertical cube edges are ridges: their midpoints at z=0.5 are ridge
    # points shared by both shards; check one, e.g. global vertex at
    # (0, 0, 0.5)
    gid = np.where(np.all(np.isclose(vert, [0, 0, 0.5]), axis=1))[0][0]
    for s in range(2):
        li = g2l[s][gid]
        if li >= 0:
            assert vtag_add[s][li] & C.MG_GEO, f"shard {s} missed ridge"
            assert not vtag_add[s][li] & C.MG_CRN
    # and the cube corners stay corners
    gidc = np.where(np.all(vert == [0, 0, 0], axis=1))[0][0]
    for s in range(2):
        li = g2l[s][gidc]
        if li >= 0:
            assert vtag_add[s][li] & C.MG_CRN
