"""Open-boundary (-opnbdy) support — the reference's
OpnBdy_peninsula/island CI class (cmake/testing/pmmg_tests.cmake:153-165):
interior input triangles become a hanging MG_OPNBDY surface that the
adaptation preserves and refines like a boundary.
"""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.api import ParMesh
from parmmg_tpu.core import constants as C
from parmmg_tpu.core.mesh import tet_volumes
from parmmg_tpu.core.constants import IDIR
from parmmg_tpu.utils.fixtures import cube_mesh
import pytest

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def _peninsula_tris(vert, tet, zplane=0.5, xmax=0.5):
    """Interior tet faces lying on z=zplane with x<=xmax: a sheet
    attached to the hull at x=0 with a free rim at x=xmax."""
    n = len(tet)
    faces = tet[:, IDIR].reshape(n * 4, 3)
    p = vert[faces]
    onp = (np.abs(p[:, :, 2] - zplane) < 1e-9).all(axis=1) & \
          (p[:, :, 0] <= xmax + 1e-9).all(axis=1)
    tri = faces[onp]
    # dedup the two slots of each interior face
    key = np.sort(tri, axis=1)
    _, first = np.unique(key, axis=0, return_index=True)
    return tri[np.sort(first)]


def _staged(opnbdy, hsiz=0.3):
    vert, tet = cube_mesh(4)
    tris = _peninsula_tris(vert, tet)
    assert len(tris) > 4
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet), nt=len(tris))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.set_triangles(tris + 1, refs=np.full(len(tris), 9))
    pm.info.niter = 1
    pm.info.imprim = -1
    pm.info.hsiz = hsiz
    pm.info.opnbdy = opnbdy
    return pm, len(tris)


def _opn_faces(mesh):
    ft = np.asarray(mesh.ftag)
    tm = np.asarray(mesh.tmask)
    return np.where((ft & C.MG_OPNBDY) != 0, tm[:, None], False)


def test_opnbdy_ingested_and_preserved():
    pm, ntri0 = _staged(True)
    assert pm.run() == C.PMMG_SUCCESS
    m = pm._out
    opn = _opn_faces(m)
    assert opn.any(), "open-boundary faces lost during adaptation"
    # geometric preservation: every opnbdy face vertex stays on the
    # sheet plane, inside the peninsula footprint
    tet = np.asarray(m.tet)
    vert = np.asarray(m.vert)
    t_ids, f_ids = np.where(opn)
    tri = tet[t_ids][np.arange(len(t_ids))[:, None], IDIR[f_ids]]
    p = vert[np.unique(tri.reshape(-1))]
    assert np.abs(p[:, 2] - 0.5).max() < 1e-5
    assert p[:, 0].max() <= 0.5 + 1e-5
    # refined: the sheet carries more faces than the input (both slots
    # of each geometric face are tagged -> compare at 2x input)
    assert opn.sum() > 2 * ntri0
    # rim must be non-manifold-frozen: vertices at the free edge x=0.5
    vtag = np.asarray(m.vtag)[np.asarray(m.vmask)]
    vv = vert[np.asarray(m.vmask)]
    rim = (np.abs(vv[:, 0] - 0.5) < 1e-6) & (np.abs(vv[:, 2] - 0.5) < 1e-6)
    assert rim.any()
    assert ((vtag[rim] & C.MG_NOM) != 0).all()
    # volume conserved
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all() and np.isclose(vols.sum(), 1.0, rtol=1e-4)


def test_without_flag_interior_tris_stay_decorative():
    pm, _ = _staged(False)
    assert pm.run() == C.PMMG_SUCCESS
    assert not _opn_faces(pm._out).any()
