"""Option-matrix tests, part C — split from test_options.py (second split:
the XLA:CPU long-process segfault moved to the 6th test as this session
added compiled programs per process; same mitigation as _b).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.api import ParMesh, IParam, DParam
from parmmg_tpu.core import constants as C
from parmmg_tpu.core.mesh import tet_volumes
from parmmg_tpu.utils.fixtures import cube_mesh

# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
pytestmark = pytest.mark.slow


def _staged(n=3, **info_kw):
    vert, tet = cube_mesh(n)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.info.niter = 1
    pm.info.imprim = -1
    for k, v in info_kw.items():
        setattr(pm.info, k, v)
    return pm


def _run_ok(pm):
    assert pm.run() == C.PMMG_SUCCESS
    vols = np.asarray(tet_volumes(pm._out))[np.asarray(pm._out.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    return pm


def test_hsiz_drives_target_size():
    pm = _run_ok(_staged(hsiz=0.18))
    _, ne_out, *_ = pm.get_mesh_size()
    assert ne_out > len(cube_mesh(3)[1])       # refined vs 0.33 spacing
