"""Curved feature lines: ridge tangents + tangent-circle midpoint lift.

Reference contract: Mmg keeps a line tangent (and two per-side normals)
at ridge points, maintained across ranks by PMMG_hashNorver
(analys_pmmg.c:199-1171); new points on a curved ridge land on the
feature curve, not on its chord — without this the torus-equator /
cylinder-rim class stays piecewise-linear at any metric resolution.
"""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.analysis import analyze_mesh, ridge_vertex_tangents
from parmmg_tpu.ops.split import split_wave
from parmmg_tpu.utils.fixtures import cylinder_mesh

R = 0.5


def _cyl(n=6):
    vert, tet = cylinder_mesh(n, r=R)
    m = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    m = analyze_mesh(m).mesh
    return m


def test_rim_is_ridge_and_tangents_follow_circle():
    m = _cyl()
    vm = np.asarray(m.vmask)
    vt = np.asarray(m.vtag)
    v = np.asarray(m.vert)
    rho = np.hypot(v[:, 0], v[:, 1])
    rim = vm & (np.abs(v[:, 2] - 1.0) < 1e-9) & (np.abs(rho - R) < 1e-6)
    assert rim.sum() >= 8
    assert ((vt[rim] & (C.MG_GEO | C.MG_CRN)) != 0).all()
    tan = np.asarray(ridge_vertex_tangents(m))
    t = tan[rim & ((vt & C.MG_GEO) != 0) & ((vt & C.MG_CRN) == 0)]
    assert len(t) > 0
    # tangent of the rim circle: no z component, orthogonal-ish to the
    # radial direction (chordal discretization allows some slack)
    pts = v[rim & ((vt & C.MG_GEO) != 0) & ((vt & C.MG_CRN) == 0)]
    radial = pts[:, :2] / np.linalg.norm(pts[:, :2], axis=1,
                                         keepdims=True)
    assert np.abs(t[:, 2]).max() < 0.2
    along_r = np.abs(np.einsum("ij,ij->i", t[:, :2], radial))
    assert along_r.max() < 0.35


def _rim_metric(m):
    """Small target size near the cap rim only, so rim edges dominate
    the split wave's priority budget."""
    v = np.asarray(m.vert)
    rho = np.hypot(v[:, 0], v[:, 1])
    near = (np.abs(v[:, 2] - 1.0) < 0.2) & (np.abs(rho - R) < 0.15)
    met = np.where(near, 0.05, 0.5)
    return jnp.asarray(met, jnp.asarray(m.vert).dtype)


def test_split_lifts_rim_midpoints_onto_circle():
    m = _cyl()
    np0 = int(np.asarray(m.npoin))
    met = _rim_metric(m)
    m2, nsp = m, 0
    for _ in range(6):          # waves: rim edges win once their
        res = split_wave(m2, met, hausd=0.05)   # neighbors shorten
        m2, met = res.mesh, res.met
        nsp += int(res.nsplit)
    assert nsp > 0
    vm2 = np.asarray(m2.vmask)
    vt2 = np.asarray(m2.vtag)
    v2 = np.asarray(m2.vert)
    new = np.zeros(m2.capP, bool)
    new[np0:] = vm2[np0:]
    rho2 = np.hypot(v2[:, 0], v2[:, 1])
    new_rim = new & ((vt2 & C.MG_GEO) != 0) & \
        (np.abs(v2[:, 2] - 1.0) < 1e-6) & (rho2 > 0.5 * R)
    if not new_rim.any():
        import pytest
        pytest.skip("no rim edge split in this wave")
    # chordal sag of the unlifted midpoint for the coarsest rim edge
    # (24-gon at n=6): r (1 - cos(pi/24)); the tangent-circle lift must
    # recover most of it
    sag_linear = R * (1 - np.cos(np.pi / 24))
    dev = np.abs(rho2[new_rim] - R)
    assert dev.max() < 0.35 * sag_linear, (
        f"rim midpoints not lifted: dev {dev.max():.3e} vs linear sag "
        f"{sag_linear:.3e}")


def test_without_hausd_midpoints_stay_on_chord():
    m = _cyl()
    np0 = int(np.asarray(m.npoin))
    met = _rim_metric(m)
    m2 = m
    for _ in range(6):
        res = split_wave(m2, met)       # no hausd: linear midpoints
        m2, met = res.mesh, res.met
    vm2 = np.asarray(m2.vmask)
    vt2 = np.asarray(m2.vtag)
    v2 = np.asarray(m2.vert)
    new = np.zeros(m2.capP, bool)
    new[np0:] = vm2[np0:]
    rho2 = np.hypot(v2[:, 0], v2[:, 1])
    new_rim = new & ((vt2 & C.MG_GEO) != 0) & \
        (np.abs(v2[:, 2] - 1.0) < 1e-6) & (rho2 > 0.5 * R)
    if not new_rim.any():
        import pytest
        pytest.skip("no rim edge split in this wave")
    sag_linear = R * (1 - np.cos(np.pi / 24))
    dev = np.abs(rho2[new_rim] - R)
    assert dev.max() > 0.5 * sag_linear   # chord midpoints sag inward
