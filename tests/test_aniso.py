"""Anisotropic-metric adaptation tests (the reference's aniso CI cases:
planar-shock tensor metrics, cmake/testing/pmmg_tests.cmake sphere-aniso).
"""
import numpy as np
import jax.numpy as jnp

from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import build_adjacency, check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.adapt import adapt_mesh
from parmmg_tpu.ops.quality import edge_length_ani, iso_to_tensor
from parmmg_tpu.ops.edges import unique_edges, edge_lengths
from parmmg_tpu.utils.fixtures import cube_mesh
import pytest


def _cube(n=2, capmul=6):
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=capmul * len(vert), capT=capmul * len(tet))
    return analyze_mesh(m).mesh


def test_edge_length_ani_matches_iso_for_isotropic_tensor():
    p0 = jnp.asarray(np.array([[0.0, 0, 0]]))
    p1 = jnp.asarray(np.array([[1.0, 0, 0]]))
    h = jnp.asarray(np.array([0.5]))
    t = iso_to_tensor(h)
    from parmmg_tpu.ops.quality import edge_length_iso
    li = edge_length_iso(p0, p1, h, h)
    la = edge_length_ani(p0, p1, t, t)
    assert np.allclose(np.asarray(li), np.asarray(la), rtol=1e-5)


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_aniso_adapt_directional_refinement():
    m = _cube(2)
    # metric: tight spacing (0.15) along x, loose (0.6) along y/z
    hx, hyz = 0.15, 0.6
    t = np.tile(np.array([1 / hx**2, 0, 0, 1 / hyz**2, 0, 1 / hyz**2]),
                (m.capP, 1))
    met = jnp.asarray(t)
    m2, met2, st = adapt_mesh(m, met, max_cycles=25)
    assert st.nsplit > 0
    m2 = build_adjacency(m2)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(m2))[np.asarray(m2.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0, rtol=1e-4)
    # directional check: mean edge extent along x much shorter than y/z
    et = unique_edges(m2)
    em = np.asarray(et.emask)
    ev = np.asarray(et.ev)[em]
    vv = np.asarray(m2.vert)
    d = np.abs(vv[ev[:, 0]] - vv[ev[:, 1]])
    assert d[:, 0].mean() < 0.6 * max(d[:, 1].mean(), d[:, 2].mean())
    # all metric lengths below the split threshold
    lens = np.asarray(edge_lengths(m2, et, met2))[em]
    assert lens.max() < C.LLONG + 0.2


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_aniso_api_roundtrip():
    from parmmg_tpu.api import ParMesh, IParam
    vert, tet = cube_mesh(2)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.set_met_size(3, len(vert))
    t = np.tile(np.array([1 / 0.2**2, 0, 0, 1 / 0.5**2, 0, 1 / 0.5**2]),
                (len(vert), 1))
    pm.set_tensor_mets(t)
    pm.set_iparameter(IParam.niter, 1)
    assert pm.run() == C.PMMG_SUCCESS
    v, _ = pm.get_vertices()
    assert len(v) > len(vert)
    met = pm.get_metric()
    assert met.shape[1] == 6
