"""Grouped (G>1) device analysis + compacted grouped halo exchange.

The groups x shards path (parallel/dist.py G, grpsplit_pmmg.c:1551 role)
must pay the same zero-host-pull bill as G=1: the grouped analysis
program (analysis_dev.dist_analysis_grouped) must match the host
refresh bit-for-bit, and the per-device-pair packed exchange
(comms.halo_exchange_grouped_packed) must match the dense [S,G,G,I]
block — including same-device neighbor pairs and pad rows — while
shipping strictly fewer bytes per all_to_all.

Tier split: the packed-layout policy/parity tests are tier-1 (small
programs); the full grouped-analysis parity and the G=2 driver run
carry the usual multi-minute CPU compile and ride the slow tier
(scripts/run_tests.sh), like the rest of the dist matrix.
"""
import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import make_mesh, mesh_to_host
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.parallel.analysis_par import extend_numbering
from parmmg_tpu.parallel.comms import (
    build_interface_comms, halo_exchange_grouped,
    halo_exchange_grouped_packed, packed_halo_rows)
from parmmg_tpu.parallel.dist import (
    make_device_mesh, refresh_shard_analysis,
    refresh_shard_analysis_device, shard_stacked)
from parmmg_tpu.parallel.distribute import split_to_shards
from parmmg_tpu.parallel.partition import morton_partition, fix_contiguity
from parmmg_tpu.utils.fixtures import cube_mesh


# ---------------------------------------------------------------------------
# packed-layout policy + wire size (tier-1: host-side numpy only)
# ---------------------------------------------------------------------------
def test_packed_rows_policy():
    # 4 logical shards in a chain 0-1-2-3, G=2: at most 2 entries per
    # (device, dest device) -> packed with the bucketed budget 2 (< G^2)
    chain = np.array([[1, -1], [0, 2], [1, 3], [2, -1]], np.int32)
    assert packed_halo_rows(chain, 2) == 2
    # fully-connected 4 logical shards: 4 entries per device pair = the
    # dense G^2 tile; occupancy threshold keeps the dense path
    clique = np.array([[1, 2, 3], [0, 2, 3], [0, 1, 3], [0, 1, 2]],
                      np.int32)
    assert packed_halo_rows(clique, 2) is None
    # G=1 has no grouped exchange at all
    assert packed_halo_rows(chain, 1) is None
    # empty table: nothing to pack
    assert packed_halo_rows(np.full((4, 2), -1, np.int32), 2) is None
    # the knob: occupancy 1.0 accepts the clique only if the BUCKETED
    # budget still beats G^2 rows — it does not (4 >= 4), dense stays
    assert packed_halo_rows(clique, 2, occupancy=1.0) is None


def test_packed_send_buffer_bytes_drop():
    """Acceptance gate: on a G=2, S=2 interface-sized fixture the bytes
    the packed all_to_all moves (payload + headers) are strictly below
    the dense [S, G, G, I] block — asserted on the send buffer shapes.
    Host-side only: the comm tables are numpy-built."""
    vert, tet = cube_mesh(4)
    cent = vert[tet].mean(axis=1)
    part = np.clip((cent[:, 0] * 4).astype(np.int32), 0, 3)  # x-slab chain
    l2g = [np.unique(tet[part == s_]) for s_ in range(4)]
    g2l = []
    for s_ in range(4):
        mm = np.full(len(vert), -1, np.int64)
        mm[l2g[s_]] = np.arange(len(l2g[s_]))
        g2l.append(mm)
    comms = build_interface_comms(tet.astype(np.int64), part, 4, l2g,
                                  g2l)
    G, S = 2, 2
    M = packed_halo_rows(comms.nbr, G)
    assert M is not None and M < G * G
    I = comms.node_idx.shape[2]
    tail_bytes = 4 * 4                     # analysis payload: 4 x f32
    dense_bytes = S * G * G * I * tail_bytes
    packed_bytes = S * M * (I * tail_bytes + 2 * 4)   # + int32 headers
    assert packed_bytes < dense_bytes


# ---------------------------------------------------------------------------
# packed vs dense exchange parity (tiny hand-built tables)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_packed_exchange_matches_dense():
    """Hand-built 4-logical-shard table on 2 devices with a same-device
    pair per device, one cross-device pair, pad items and an idle
    neighbor slot: the packed exchange must reproduce the dense recv
    exactly (zeros on pads included).  slow: two shard_map compiles —
    the tier-1 wall budget is full (ROADMAP note); the grouped-analysis
    parity tests above re-prove the same equality end-to-end."""
    from jax.sharding import PartitionSpec as P
    from parmmg_tpu.utils.jaxcompat import shard_map

    G, S, K, I, Pv = 2, 2, 2, 4, 8
    # logical pairs: (0,1) same-device, (1,2) cross-device, (2,3)
    # same-device; slot 1 of shards 0 and 3 is an idle (-1) neighbor
    nbr = np.array([[1, -1], [0, 2], [3, 1], [2, -1]], np.int32)
    rng = np.random.default_rng(7)
    send_idx = rng.integers(0, Pv, size=(S * G, K, I)).astype(np.int32)
    send_idx[0, 1] = -1                    # idle neighbor slot
    send_idx[3, 1] = -1
    send_idx[1, 0, 2:] = -1                # pad items inside a pair
    send_idx[0, 0, 2:] = -1
    vals = rng.normal(size=(S * G, Pv, 3)).astype(np.float32)

    M = packed_halo_rows(nbr, G)
    assert M is not None and M < G * G
    dmesh = make_device_mesh(S)
    spec = P("shard")

    def run(fn):
        def local(v, ni, nb):
            return fn(v, ni, nb)
        prog = jax.jit(shard_map(local, mesh=dmesh,
                                 in_specs=(spec, spec, spec),
                                 out_specs=spec, check_vma=False))
        return np.asarray(prog(
            shard_stacked(jnp.asarray(vals), dmesh),
            shard_stacked(jnp.asarray(send_idx), dmesh),
            shard_stacked(jnp.asarray(nbr), dmesh)))

    dense = run(lambda v, ni, nb: halo_exchange_grouped(v, ni, nb, G))
    packed = run(lambda v, ni, nb: halo_exchange_grouped_packed(
        v, ni, nb, G, M))
    assert dense.shape == packed.shape == (S * G, K, I, 3)
    assert np.array_equal(dense, packed)
    # pads stay zero; real same-device + cross-device rows carry data
    assert np.all(packed[0, 1] == 0) and np.all(packed[3, 1] == 0)
    assert np.any(packed[0, 0] != 0)       # same-device pair (0,1)
    assert np.any(packed[1, 1] != 0)       # cross-device pair (1,2)


# ---------------------------------------------------------------------------
# grouped analysis parity + the G=2 driver run (slow tier)
# ---------------------------------------------------------------------------
def _setup(part_fn, n=4, nparts=4):
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=2 * len(vert), capT=2 * len(tet))
    # two material refs -> MG_REF edges where the surface refs differ
    tref = 1 + (vert[tet].mean(axis=1)[:, 0] > 0.5).astype(np.int32)
    trf = np.zeros(m.capT, np.int32)
    trf[: len(tet)] = tref
    m = dataclasses.replace(m, tref=jnp.asarray(trf))
    m = analyze_mesh(m).mesh
    is_b = (np.asarray(m.ftag) & C.MG_BDY) != 0
    frf = np.where(is_b, trf[:, None], np.asarray(m.fref))
    m = dataclasses.replace(m, fref=jnp.asarray(frf.astype(np.int32)))
    met = jnp.full(m.capP, 0.4, m.vert.dtype)
    vert_h, tet_h, _, _, _ = mesh_to_host(m)
    part = part_fn(vert_h, tet_h, nparts)
    s, ms, l2g = split_to_shards(m, met, part, nparts, return_l2g=True)
    g2l = []
    for s_ in range(nparts):
        mm = np.full(len(vert_h), -1, np.int64)
        mm[l2g[s_]] = np.arange(len(l2g[s_]))
        g2l.append(mm)
    comms = build_interface_comms(tet_h, part, nparts, l2g, g2l)
    return s, comms, nparts


def _part_morton(vert_h, tet_h, nparts):
    cent = vert_h[tet_h].mean(axis=1)
    return fix_contiguity(tet_h, morton_partition(cent, nparts))


def _part_slabs(vert_h, tet_h, nparts):
    cent = vert_h[tet_h].mean(axis=1)
    return np.clip((cent[:, 0] * nparts).astype(np.int32), 0,
                   nparts - 1)


def _assert_parity(stacked, comms, S, dmesh):
    capP = stacked.vert.shape[1]
    glo = extend_numbering(comms, [capP] * S)
    host_out = refresh_shard_analysis(stacked, comms, S, C.ANGEDG,
                                      glo=[g.copy() for g in glo])
    dev_out = refresh_shard_analysis_device(stacked, comms, S, C.ANGEDG,
                                            glo, dmesh)
    assert dev_out is not None, "grouped device path overflowed"
    vm = np.asarray(stacked.vmask)
    tm = np.asarray(stacked.tmask)
    vt_h, vt_d = np.asarray(host_out.vtag), np.asarray(dev_out.vtag)
    et_h, et_d = np.asarray(host_out.etag), np.asarray(dev_out.etag)
    for sh in range(S):
        bad_v = np.where(vm[sh] & (vt_h[sh] != vt_d[sh]))[0]
        assert len(bad_v) == 0, (
            f"shard {sh}: {len(bad_v)} vtag mismatches, first "
            f"{bad_v[:5]}: host {vt_h[sh][bad_v[:5]]} "
            f"dev {vt_d[sh][bad_v[:5]]}")
        bad_e = np.where((et_h[sh] != et_d[sh]) & tm[sh][:, None])
        assert len(bad_e[0]) == 0, (
            f"shard {sh}: {len(bad_e[0])} etag mismatches")


@pytest.mark.slow
def test_grouped_analysis_matches_host_dense():
    """G=2 on 2 devices, morton partition (fully-connected neighbors ->
    dense grouped exchange): bit-for-bit host parity."""
    s, comms, S = _setup(_part_morton)
    assert packed_halo_rows(comms.nbr, 2) is None    # dense route
    dmesh = make_device_mesh(2)
    _assert_parity(shard_stacked(s, dmesh), comms, S, dmesh)


@pytest.mark.slow
def test_grouped_analysis_matches_host_packed():
    """G=2 on 2 devices, x-slab chain partition (sparse neighbors ->
    the packed grouped exchange is selected): bit-for-bit host parity
    through the compacted wire layout."""
    s, comms, S = _setup(_part_slabs)
    assert packed_halo_rows(comms.nbr, 2) is not None   # packed route
    dmesh = make_device_mesh(2)
    _assert_parity(shard_stacked(s, dmesh), comms, S, dmesh)


@pytest.mark.slow
def test_grouped_refresh_taken_on_g2_driver_run():
    """Acceptance gate: a G=2 driver run performs the analysis refresh
    ON DEVICE — the host path (refresh_shard_analysis) is unreachable
    unless the KS budget overflows, which this fixture cannot trigger.
    The host refresh is replaced with a tripwire for the whole run."""
    from parmmg_tpu.parallel import dist as dist_mod
    from parmmg_tpu.utils.compilecache import ledger_snapshot

    vert, tet = cube_mesh(2)
    m = make_mesh(vert, tet, capP=6 * len(vert), capT=6 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.4, m.vert.dtype)

    orig = dist_mod.refresh_shard_analysis

    def tripwire(*a, **k):
        raise AssertionError(
            "host analysis refresh reached on a G>1 run without a "
            "KS-budget overflow")

    dist_mod.refresh_shard_analysis = tripwire
    try:
        out, met_m, part = dist_mod.distributed_adapt_multi(
            m, met, 4, niter=2, cycles=2, n_devices=2)
    finally:
        dist_mod.refresh_shard_analysis = orig
    assert int(np.asarray(out.tmask).sum()) > 0
    led = ledger_snapshot()
    assert led.get("dist.analysis_grouped", {}).get("calls", 0) >= 1
    # conformity of the merged result (numpy-side)
    vert_h, tet_h, _, _, _ = mesh_to_host(out)
    p = vert_h[tet_h]
    vol = np.einsum("ij,ij->i", p[:, 1] - p[:, 0],
                    np.cross(p[:, 2] - p[:, 0], p[:, 3] - p[:, 0]))
    assert (vol > 0).all()
