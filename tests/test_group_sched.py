"""Quiet-group scheduler tests (parallel/sched.py + groups.py wiring).

The scheduler skips chunked group-block dispatches for groups that a
swap-inclusive block proved quiet — exact because frozen MG_PARBDY
seams + deterministic waves make a zero-op group state a fixed point
(sched module docstring).  PR 12 pushes the same proof into the
compiled programs as a device-resident active mask (lax.cond group
bodies, PARMMG_DEVICE_MASK): fast tests pin the mask plumbing
(block_mask levels, pad_mask, cond_skipped accounting, the measured
chunk-overhead calibration) host-side; the slow tests pin the
end-to-end contracts: bit-for-bit parity vs always-dispatch AND vs
mask-off on the unchunked layout, the quiet fixed point, and the
strictly-fewer-dispatches acceptance gate.

The packed-halo hysteresis satellite (comms.packed_halo_rows ``state``)
is pinned here too: the dense/packed layout decision must be sticky
within the margin so borderline occupancy cannot flip-flop compiled
exchange layouts across comm-table rebuilds.
"""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.parallel.sched import (
    LEVEL_FULL, LEVEL_PRE, QuietGroupScheduler, chunk_plans)


# ---------------------------------------------------------------------------
# host-side state machine (tier-1: no compiles)
# ---------------------------------------------------------------------------
def _counts(n_act, nblk=1, at=None):
    """Zero count block [n_act, nblk, 8]; at={(g, cycle, col): v}."""
    c = np.zeros((n_act, nblk, 8), np.int32)
    for (g, i, col), v in (at or {}).items():
        c[g, i, col] = v
    return c


def test_sched_marks_skips_and_compacts():
    s = QuietGroupScheduler(ngroups=4, g_exec=6, chunk=2, enabled=True)
    # pad groups (4, 5) are born quiet: 2 chunks instead of 3
    act, plans = s.plan_block(pres_all_on=True)
    assert list(act) == [0, 1, 2, 3]
    assert [(list(i), n) for i, n in plans] == [([0, 1], 2), ([2, 3], 2)]
    assert s.dispatches == 2 and s.saved_dispatches == 1
    # swap-inclusive prescreen-on block: groups 1 and 3 all-zero
    s.record_block(act, _counts(4, 2, {(0, 0, 0): 5, (2, 1, 2): 1}),
                   swap_inclusive=True, pres_all_on=True)
    assert list(s.level[:4]) == [0, LEVEL_PRE, 0, LEVEL_PRE]
    # prescreen-on block skips PRE groups; compaction stays dense
    act2, plans2 = s.plan_block(pres_all_on=True)
    assert list(act2) == [0, 2]
    assert [(list(i), n) for i, n in plans2] == [([0, 2], 2)]
    # a prescreen-OFF block re-dispatches PRE groups (the exact split
    # veto can produce ops the approximate prescreen vetoed)
    act3, _ = s.plan_block(pres_all_on=False)
    assert list(act3) == [0, 1, 2, 3]
    # all-zero on the pres-off swap block: everyone LEVEL_FULL
    s.record_block(act3, _counts(4), True, False)
    act4, plans4 = s.plan_block(pres_all_on=False)
    assert len(act4) == 0 and plans4 == []
    assert s.active_per_block == [4, 2, 4, 0]
    assert s.saved_dispatches == 1 + 2 + 1 + 3
    # skipped-group accounting counts REAL groups only (dead pads are
    # not scheduler wins): 0 + 2 + 0 + 4 across the four blocks
    assert s.skipped_group_blocks == 6


def test_sched_needs_swap_and_clean_overflow():
    s = QuietGroupScheduler(2, 2, 1, enabled=True)
    act, _ = s.plan_block(True)
    # zero counts on a NON-swap block prove nothing (a later swap cycle
    # could still post ops)
    s.record_block(act, _counts(2), swap_inclusive=False,
                   pres_all_on=True)
    assert (s.level[:2] == 0).all()
    # overflow (col 4) vetoes quietness: a truncated winner set is not
    # a convergence witness
    s.record_block(act, _counts(2, at={(0, 0, 4): 1}), True, True)
    assert s.level[0] == 0 and s.level[1] == LEVEL_PRE
    # moves (col 3) veto quietness too: smoothing is part of the fixed
    # point
    s.record_block(act, _counts(2, at={(0, 0, 3): 7}), True, True)
    assert s.level[0] == 0


def test_sched_regrow_reactivates_full_set():
    """Satellite (c): a capacity regrow invalidates every quiet proof —
    the top-K wave budgets scale with capT, so budget-truncated winners
    must rerun.  Pad groups stay dead."""
    s = QuietGroupScheduler(3, 4, 2, enabled=True)
    act, _ = s.plan_block(False)
    s.record_block(act, _counts(3), True, False)
    assert (s.level[:3] == LEVEL_FULL).all()
    s.on_regrow()
    act2, _ = s.plan_block(False)
    assert list(act2) == [0, 1, 2]          # pad group 3 stays quiet
    assert s.level[3] == LEVEL_FULL


def test_sched_disabled_always_dispatches():
    s = QuietGroupScheduler(3, 4, 2, enabled=False)
    act, plans = s.plan_block(False)
    s.record_block(act, _counts(4), True, False)
    act2, plans2 = s.plan_block(False)
    assert list(act2) == [0, 1, 2, 3]       # pads included, like legacy
    assert len(plans2) == 2 and s.saved_dispatches == 0
    assert s.skipped_group_blocks == 0      # disabled: nothing skipped
    # the trajectory still shows the would-be-active real groups
    assert s.active_per_block == [3, 0]


def test_chunk_plans_pads_tail_with_repeat():
    p = chunk_plans(np.array([1, 4, 6]), 2)
    assert [(list(i), n) for i, n in p] == [([1, 4], 2), ([6, 6], 1)]
    p1 = chunk_plans(np.array([2]), 4)
    assert [(list(i), n) for i, n in p1] == [([2, 2, 2, 2], 1)]


# ---------------------------------------------------------------------------
# device-resident quiet masks (tier-1: host-side plumbing only)
# ---------------------------------------------------------------------------
def test_pad_mask_masks_padded_tail_rows(monkeypatch):
    from parmmg_tpu.parallel.sched import pad_mask
    assert list(pad_mask(4, 2)) == [True, True, False, False]
    assert list(pad_mask(3, 3)) == [True, True, True]
    # PARMMG_DEVICE_MASK=0: all-true — the disabled path computes
    # exactly what the pre-mask code did (pad rows discarded later)
    monkeypatch.setenv("PARMMG_DEVICE_MASK", "0")
    assert list(pad_mask(4, 1)) == [True] * 4
    # PARMMG_GROUP_SCHED=0 is the FULL legacy escape hatch: it forces
    # all-true masks too, even with the mask knob on
    monkeypatch.delenv("PARMMG_DEVICE_MASK")
    monkeypatch.setenv("PARMMG_GROUP_SCHED", "0")
    assert list(pad_mask(4, 1)) == [True] * 4


def test_block_mask_levels_and_knob(monkeypatch):
    """Unchunked dispatches: the mask is the only skip mechanism —
    level >= LEVEL_PRE slots masked under prescreen-ON blocks, only
    LEVEL_FULL slots under prescreen-OFF blocks; pads born masked;
    cond_skipped accounts every masked slot."""
    s = QuietGroupScheduler(ngroups=3, g_exec=4, chunk=0, enabled=True)
    s.level[1] = LEVEL_PRE
    s.level[2] = LEVEL_FULL
    m_pre = s.block_mask(pres_all_on=True)
    assert list(m_pre) == [True, False, False, False]   # pad 3 masked
    m_full = s.block_mask(pres_all_on=False)
    # a pres-OFF block re-runs LEVEL_PRE groups (exact split veto)
    assert list(m_full) == [True, True, False, False]
    assert s.cond_skipped == 3 + 2
    # scheduler disabled: masks all-true, nothing accounted
    s2 = QuietGroupScheduler(3, 4, 0, enabled=False)
    s2.level[1] = LEVEL_FULL
    assert list(s2.block_mask(True)) == [True] * 4
    assert s2.cond_skipped == 0
    # PARMMG_DEVICE_MASK=0 forces all-true even with the scheduler on
    monkeypatch.setenv("PARMMG_DEVICE_MASK", "0")
    s3 = QuietGroupScheduler(3, 4, 0, enabled=True)
    s3.level[1] = LEVEL_FULL
    assert list(s3.block_mask(True)) == [True] * 4
    assert s3.cond_skipped == 0


def test_note_plan_pads_accounts_masked_tail(monkeypatch):
    s = QuietGroupScheduler(5, 6, 2, enabled=True)
    plans = chunk_plans(np.array([0, 2, 4]), 2)   # tail padded 1 row
    s.note_plan_pads(plans)
    assert s.cond_skipped == 1
    monkeypatch.setenv("PARMMG_DEVICE_MASK", "0")
    s.note_plan_pads(plans)                        # disabled: no-op
    assert s.cond_skipped == 1


def test_calibrate_dispatch_overhead():
    """ROADMAP 1b host-side validation: the cost model's overhead
    constant is derived from the measured pipeline segments — per-
    dispatch (upload+download+writeback) over per-GROUP compute."""
    from parmmg_tpu.parallel.sched import calibrate_dispatch_overhead
    acc = {"upload": 2.0, "download": 1.0, "writeback": 1.0,
           "compute": 8.0}
    cnt = {"upload": 4, "compute": 4, "download": 4, "writeback": 4}
    # per dispatch: overhead (2+1+1)/4 = 1.0 s; compute 8/4/chunk=2
    # = 1.0 s/group -> 1.0 group-units
    assert calibrate_dispatch_overhead(acc, cnt, 2) == 1.0
    # bigger chunk -> cheaper per-group compute -> higher overhead
    assert calibrate_dispatch_overhead(acc, cnt, 4) == 2.0
    # no signal cases keep the hand-set default (None)
    assert calibrate_dispatch_overhead({}, {}, 2) is None
    assert calibrate_dispatch_overhead(acc, cnt, 0) is None
    assert calibrate_dispatch_overhead(
        {"compute": 0.0, "upload": 1.0}, {"compute": 3}, 2) is None
    # the calibration feeds recommend_group_chunk directly
    from parmmg_tpu.parallel.sched import recommend_group_chunk
    assert recommend_group_chunk([8, 8], 8, dispatch_overhead=2.0) in \
        (2, 4, 8, 0)


# ---------------------------------------------------------------------------
# packed-halo hysteresis (comms satellite; tier-1: host numpy)
# ---------------------------------------------------------------------------
def _nbr_table(n_entries, G=4):
    """[2*G, G] logical neighbor table: device 0 carries ``n_entries``
    (group, slot) entries pointing at device 1; device 1 silent."""
    nbr = np.full((2 * G, G), -1, np.int32)
    for i in range(n_entries):
        nbr[i // G, i % G] = G + (i % G)
    return nbr


def test_packed_halo_hysteresis_sticky_layout(monkeypatch):
    from parmmg_tpu.parallel.comms import packed_halo_rows
    G = 4                      # occupancy ratio r = entries / 16
    st = {}
    # below threshold: packed, state recorded
    assert packed_halo_rows(_nbr_table(7), G, occupancy=0.5,
                            state=st) is not None
    assert st["layout"] == "packed"
    # AT the threshold (r = 0.5): a stateless call flips on the exact
    # boundary; the sticky decision keeps packed within the margin
    assert packed_halo_rows(_nbr_table(8), G, occupancy=0.5,
                            state=st) is not None
    # past threshold + margin (r = 0.5625 > 0.55): flips to dense
    assert packed_halo_rows(_nbr_table(9), G, occupancy=0.5,
                            state=st) is None
    assert st["layout"] == "dense"
    # back to r = 0.5 <= occupancy but NOT below occupancy - margin:
    # stays dense — this is the flip-flop the hysteresis kills
    assert packed_halo_rows(_nbr_table(8), G, occupancy=0.5,
                            state=st) is None
    # clearly below the lower margin (r = 0.4375 <= 0.45): re-packs
    assert packed_halo_rows(_nbr_table(7), G, occupancy=0.5,
                            state=st) is not None
    assert st["layout"] == "packed"
    # widened margin knob: r = 0.5625 <= 0.5 + 0.2 now stays packed
    monkeypatch.setenv("PARMMG_HALO_PACK_HYST", "0.2")
    assert packed_halo_rows(_nbr_table(9), G, occupancy=0.5,
                            state=st) is not None
    # stateless calls keep the legacy decide-per-call behavior
    assert packed_halo_rows(_nbr_table(8), G, occupancy=0.5) is not None
    assert packed_halo_rows(_nbr_table(9), G, occupancy=0.5) is None
    # no-traffic tables decide nothing and leave the state alone
    before = dict(st)
    assert packed_halo_rows(np.full((2 * G, G), -1, np.int32), G,
                            occupancy=0.5, state=st) is None
    assert st == before


# ---------------------------------------------------------------------------
# end-to-end contracts (slow tier: group-block XLA compiles)
# ---------------------------------------------------------------------------
def _shock_setup(n=3, h=0.6):
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import analytic_iso_metric, cube_mesh
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    hh = analytic_iso_metric(vert, "shock", h=h)
    met = jnp.zeros(m.capP, m.vert.dtype).at[: len(hh)].set(
        jnp.asarray(hh, m.vert.dtype)).at[len(hh):].set(1.0)
    return m, met


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_sched_parity_bit_for_bit(monkeypatch):
    """Satellite (a): merged mesh + met byte-identical with the
    scheduler forced on vs off on a multi-group chunked fixture,
    polish included (at chunk granularity 1 the wave-major polish
    retirement is exactly the legacy per-chunk break)."""
    from parmmg_tpu.core.mesh import MESH_FIELDS
    from parmmg_tpu.ops.adapt import AdaptStats
    from parmmg_tpu.parallel.groups import grouped_adapt_pass

    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "1")

    def run(sched):
        monkeypatch.setenv("PARMMG_GROUP_SCHED", sched)
        m, met = _shock_setup()
        st = AdaptStats()
        out, met2, part = grouped_adapt_pass(m, met, 3, cycles=3,
                                             stats=st, polish=True)
        return out, np.asarray(met2), np.asarray(part), st

    ref, kref, pref, st0 = run("0")
    chk, kchk, pchk, st1 = run("1")
    for f in MESH_FIELDS:
        a = np.asarray(getattr(ref, f))
        b = np.asarray(getattr(chk, f))
        assert (a == b).all(), f"merged field {f} differs on/off"
    assert (kref == kchk).all(), "merged metric differs on/off"
    assert (pref == pchk).all()
    # always-dispatch accounting sanity
    assert st0.group_dispatches_saved == 0
    assert st1.group_dispatches + st1.group_dispatches_saved >= \
        st0.group_dispatches


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_device_mask_parity_unchunked(monkeypatch):
    """Device-mask bit-for-bit parity (PR 12): UNCHUNKED dispatches
    (PARMMG_GROUP_CHUNK=0) are where the lax.cond mask is the ONLY skip
    mechanism — host compaction cannot change the dispatch shape.
    Mask-on (scheduler levels -> cond identity for quiet slots) must
    merge byte-identical to sched-off (every slot computes), polish on
    (the unchunked polish loop is shared, so the cycle loop is the
    masked path under test).  The x-slab calm fixture guarantees quiet
    groups arise BEFORE convergence, so the mask demonstrably engages
    (cond_skipped > 0) rather than passing vacuously."""
    from parmmg_tpu.core.mesh import MESH_FIELDS, make_mesh
    from parmmg_tpu.ops.adapt import AdaptStats
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.utils.fixtures import cube_mesh

    n = 3
    vert, tet = cube_mesh(n)
    cent = vert[tet].mean(axis=1)
    part = np.minimum((cent[:, 0] * n).astype(np.int64), n - 1)
    h = np.where(vert[:, 0] < 1e-9, 0.15, 1.3 / n)
    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "0")

    def run(sched, mask):
        monkeypatch.setenv("PARMMG_GROUP_SCHED", sched)
        monkeypatch.setenv("PARMMG_DEVICE_MASK", mask)
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.zeros(m.capP, m.vert.dtype).at[: len(h)].set(
            jnp.asarray(h, m.vert.dtype)).at[len(h):].set(1.0)
        st = AdaptStats()
        out, met2, p = grouped_adapt_pass(
            m, met, n, cycles=5, part=part, stats=st, nomove=True,
            noswap=True, polish=True)
        return out, np.asarray(met2), np.asarray(p), st

    ref, kref, pref, st0 = run("0", "0")
    chk, kchk, pchk, st1 = run("1", "1")
    for f in MESH_FIELDS:
        a = np.asarray(getattr(ref, f))
        b = np.asarray(getattr(chk, f))
        assert (a == b).all(), f"merged field {f} differs mask on/off"
    assert (kref == kchk).all(), "merged metric differs mask on/off"
    assert (pref == pchk).all()
    # the mask demonstrably skipped group-slot executions on device
    assert st1.sched_extra.get("cond_skipped_rows", 0) > 0
    assert st0.sched_extra.get("cond_skipped_rows", 0) == 0


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_sched_saves_dispatches_and_quiet_fixed_point(monkeypatch):
    """Acceptance gate: on a run where some groups converge early the
    scheduler executes strictly fewer group-block dispatches than
    cycles x ceil(G/chunk); and (satellite b) a quiet group's state is
    a fixed point — re-running the block is byte-identity.

    Fixture: x-slab partition with the refinement confined to the x=0
    boundary column, calm-region metric inside the (LSHRT, LLONG)
    band for every Kuhn edge class (h = 1.3 * spacing), -nomove/-noswap
    so groups 1 and 2 post zero everything from cycle 0 while group 0
    splits for several cycles."""
    from parmmg_tpu.core.mesh import MESH_FIELDS, make_mesh
    from parmmg_tpu.ops.adapt import AdaptStats
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.parallel.groups import _group_block, grouped_adapt_pass
    from parmmg_tpu.parallel.distribute import split_to_shards
    from parmmg_tpu.utils.fixtures import cube_mesh

    n = 3
    vert, tet = cube_mesh(n)
    cent = vert[tet].mean(axis=1)
    part = np.minimum((cent[:, 0] * n).astype(np.int64), n - 1)
    h = np.where(vert[:, 0] < 1e-9, 0.15, 1.3 / n)

    def setup():
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.zeros(m.capP, m.vert.dtype).at[: len(h)].set(
            jnp.asarray(h, m.vert.dtype)).at[len(h):].set(1.0)
        return m, met

    monkeypatch.setenv("PARMMG_GROUP_CHUNK", "1")
    monkeypatch.setenv("PARMMG_GROUP_SCHED", "1")
    cycles = 5
    m, met = setup()
    st = AdaptStats()
    out, _, _ = grouped_adapt_pass(m, met, n, cycles=cycles, part=part,
                                   stats=st, nomove=True, noswap=True)
    assert int(np.asarray(out.tmask).sum()) > 0
    # strictly fewer dispatches than the always-dispatch ceiling
    assert st.group_dispatches < cycles * n, \
        (st.group_dispatches, cycles * n)
    assert st.group_dispatches_saved > 0
    assert st.groups_skipped > 0
    traj = st.sched_extra["active_groups_per_block"]
    assert traj[0] == n and min(traj) < n, traj

    # quiet fixed point: a calm group's split state re-runs to
    # byte-identical arrays under the same compiled block (the program
    # the scheduler skipped; wave index is a traced no-op on it)
    import jax
    m2, met2 = setup()
    stacked, met_s = split_to_shards(m2, met2, part, n, cap_mult=3.0)
    calm = jax.tree.map(lambda a: a[1:2], stacked)
    kcalm = met_s[1:2]
    step = _group_block((True,), (False,), True, False, None)
    on = jnp.ones(1, bool)
    cad = jnp.asarray(True)
    m1, k1, c1 = step(calm, kcalm, jnp.asarray(0, jnp.int32), on, cad)
    assert int(np.asarray(c1)[..., :5].sum()) == 0, np.asarray(c1)
    m2_, k2, c2 = step(m1, k1, jnp.asarray(1, jnp.int32), on, cad)
    assert int(np.asarray(c2)[..., :5].sum()) == 0
    for f in MESH_FIELDS:
        a, b = np.asarray(getattr(m1, f)), np.asarray(getattr(m2_, f))
        assert (a == b).all(), f"quiet group field {f} not a fixed point"
    assert (np.asarray(k1) == np.asarray(k2)).all()
