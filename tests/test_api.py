"""API-layer tests: the manual cube walkthrough of the reference examples.

Mirrors libexamples/adaptation_example0 (sequential_IO/manual cube: build a
mesh by hand through the Set_* API, adapt, read results back through
Get_*) — the reference runs these as CI tests (pmmg_tests.cmake:324-591).
"""
import numpy as np
import pytest

from parmmg_tpu.api import ParMesh, IParam, DParam
from parmmg_tpu.core import constants as C
from parmmg_tpu.utils.fixtures import cube_mesh


def _staged_cube(n=2, **ipar):
    vert, tet = cube_mesh(n)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)                      # API is 1-based
    for k, v in ipar.items():
        pm.set_iparameter(getattr(IParam, k), v)
    return pm, vert, tet


def test_manual_cube_roundtrip_no_adapt():
    pm, vert, tet = _staged_cube(2, niter=1, noinsert=1, noswap=1, nomove=1)
    pm.set_dparameter(DParam.hsiz, 0.5)             # current size: no-op
    ret = pm.run()
    assert ret == C.PMMG_SUCCESS
    npo, ne, *_ = pm.get_mesh_size()
    assert npo > 0 and ne > 0
    v, vr = pm.get_vertices()
    t, tr = pm.get_tetrahedra()
    assert t.min() >= 1 and t.max() <= len(v)
    # volume conserved
    p = v[t - 1]
    vol = np.einsum("ti,ti->t", p[:, 1] - p[:, 0],
                    np.cross(p[:, 2] - p[:, 0], p[:, 3] - p[:, 0])) / 6
    assert np.isclose(vol.sum(), 1.0, rtol=1e-4)


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_manual_cube_refine():
    pm, vert, tet = _staged_cube(2, niter=1)
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.26))
    ret = pm.run()
    assert ret == C.PMMG_SUCCESS
    v, _ = pm.get_vertices()
    assert len(v) > len(vert)                        # refined
    assert pm.stats.nsplit > 0
    tris, refs = pm.get_triangles()
    assert len(tris) > 0


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_scalar_met_setters_individual():
    pm, vert, tet = _staged_cube(1, niter=1)
    pm.set_met_size(1, len(vert))
    for i in range(len(vert)):
        pm.set_scalar_met(0.9, i + 1)
    assert pm.run() == C.PMMG_SUCCESS


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_required_vertex_survives():
    pm, vert, tet = _staged_cube(2, niter=1)
    # mark an interior vertex required: it must survive coarsening
    interior = np.where(~(((vert == 0) | (vert == 1)).any(axis=1)))[0]
    vid = int(interior[0])
    pm.set_required_vertex(vid + 1)
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 2.0))      # coarsen hard
    assert pm.run() == C.PMMG_SUCCESS
    v, _ = pm.get_vertices()
    d = np.abs(v - vert[vid]).sum(axis=1).min()
    assert d < 1e-6


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_fields_interpolated():
    pm, vert, tet = _staged_cube(2, niter=1)
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.3))
    pm.set_sols_at_vertices_size(1, [1])
    coef = np.array([2.0, -1.0, 0.5])
    pm.set_ith_sol_in_sols_at_vertices(1, vert @ coef)
    assert pm.run() == C.PMMG_SUCCESS
    v, _ = pm.get_vertices()
    f = pm.get_ith_sol_in_sols_at_vertices(1)
    assert len(f) == len(v)
    # linear field must be reproduced (P1 interpolation is exact)
    assert np.allclose(f, v @ coef, atol=5e-3)


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_user_triangle_refs_preserved():
    vert, tet = cube_mesh(2)
    # user declares the z=0 face triangles with ref 7
    pm = ParMesh()
    faces = []
    # brute-force boundary triangles of z=0 plane from the tets
    from parmmg_tpu.core.constants import IDIR
    for t in tet:
        for f in range(4):
            tri = t[IDIR[f]]
            if (vert[tri][:, 2] == 0).all():
                faces.append(tri + 1)
    faces = np.array(faces)
    pm.set_mesh_size(np_=len(vert), ne=len(tet), nt=len(faces))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.set_triangles(faces, refs=np.full(len(faces), 7))
    pm.set_iparameter(IParam.niter, 1)
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.35))
    assert pm.run() == C.PMMG_SUCCESS
    tris, refs = pm.get_triangles()
    v, _ = pm.get_vertices()
    on_z0 = np.isclose(v[tris - 1][:, :, 2], 0).all(axis=1)
    assert on_z0.any()
    # every z=0 output triangle carries ref 7
    assert (refs[on_z0] == 7).all()
    assert (refs[~on_z0] != 7).all()


def test_iparam_dparam_surface():
    pm = ParMesh()
    pm.set_iparameter(IParam.verbose, 5)
    pm.set_iparameter(IParam.niter, 2)
    pm.set_iparameter(IParam.APImode, C.APIDISTRIB_NODES)
    pm.set_dparameter(DParam.hmin, 0.01)
    pm.set_dparameter(DParam.hmax, 1.0)
    pm.set_dparameter(DParam.hgrad, 1.2)
    assert pm.info.imprim == 5
    assert pm.info.niter == 2
    assert pm.info.api_mode == C.APIDISTRIB_NODES
    assert pm.info.hmin == 0.01
    # lagrangian / level-set are settable but refused at run() time with a
    # strong failure, like the reference's PMMG_check_inputData
    # (libparmmg.c:69-81)
    pm.set_iparameter(IParam.lag, 1)
    assert pm.info.lag == 1


def test_unavailable_inputs_rejected_at_run():
    import numpy as np
    pm = ParMesh()
    vert = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, 1],
                     [1, 1, 1.]])
    tets = np.array([[1, 2, 3, 4], [2, 3, 4, 5]])
    pm.set_mesh_size(np_=len(vert), ne=len(tets))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tets)
    pm.info.niter = 1
    pm.set_iparameter(IParam.lag, 0)
    assert pm.run() == C.PMMG_STRONGFAILURE
    pm.set_iparameter(IParam.lag, -1)
    pm.set_iparameter(IParam.iso, 1)
    assert pm.run() == C.PMMG_STRONGFAILURE


def test_node_communicator_api_roundtrip():
    pm = ParMesh(nprocs=2, myrank=0)
    pm.set_mesh_size(np_=8, ne=6)
    pm.set_number_of_node_communicators(1)
    pm.set_ith_node_communicator_size(0, color_out=1, nitem=4)
    pm.set_ith_node_communicator_nodes(
        0, [3, 1, 4, 2], [30, 10, 40, 20], is_not_ordered=True)
    col, n = pm.get_ith_node_communicator_size(0)
    assert (col, n) == (1, 4)
    # sorted by global id per the ordering contract
    assert pm.get_ith_node_communicator_nodes(0).tolist() == [1, 2, 3, 4]
    assert pm.check_set_node_communicators()


def test_face_communicator_api_and_owners():
    pm = ParMesh(nprocs=3, myrank=1)
    pm.set_mesh_size(np_=8, ne=6, nt=4)
    pm.set_number_of_face_communicators(2)
    pm.set_ith_face_communicator_size(0, color_out=0, nitem=2)
    pm.set_ith_face_communicator_faces(0, [2, 1], [20, 10],
                                       is_not_ordered=True)
    pm.set_ith_face_communicator_size(1, color_out=2, nitem=1)
    pm.set_ith_face_communicator_faces(1, [3], [30], is_not_ordered=False)
    assert pm.get_number_of_face_communicators() == 2
    assert pm.get_ith_face_communicator_faces(0).tolist() == [1, 2]
    assert pm.check_set_face_communicators()
    owners, globs, nuniq, ntot = pm.get_face_communicator_owners()
    # owner = max rank of the sharing pair (libparmmg.c:962-973 rule)
    assert owners[0].tolist() == [1, 1]      # pair (1,0) -> 1
    assert owners[1].tolist() == [2]         # pair (1,2) -> 2
    assert (nuniq, ntot) == (3, 3)
    # out-of-range local id must fail the check
    pm.set_ith_face_communicator_faces(1, [99], [30], is_not_ordered=False)
    assert not pm.check_set_face_communicators()


def test_node_communicator_owners():
    pm = ParMesh(nprocs=2, myrank=0)
    pm.set_mesh_size(np_=8, ne=6)
    pm.set_number_of_node_communicators(1)
    pm.set_ith_node_communicator_size(0, color_out=1, nitem=4)
    pm.set_ith_node_communicator_nodes(
        0, [3, 1, 4, 2], [30, 10, 40, 20], is_not_ordered=True)
    owners, globs, nuniq, ntot = pm.get_node_communicator_owners()
    assert owners[0].tolist() == [1, 1, 1, 1]
    assert globs[0].tolist() == [10, 20, 30, 40]
    assert (nuniq, ntot) == (4, 4)
