"""Active-scoped narrow adaptation (ops/active.py) — the worklist path.

The reference's remesher is worklist-driven (``MMG5_mmg3d1_delone``
cascades over affected entities, libparmmg1.c:737); ops/active.py is the
batched equivalent: cycles self-select between full-width waves and an
[A]-row compacted sub-mesh over the dirty regions.  These tests pin the
invariants that make the narrow branch exact:

- untouched regions are bit-identical across a narrow cycle;
- the mesh stays conforming (adjacency oracle) and volume-preserving;
- the auto path converges to the same quality class as the full path;
- the worklist state machine (okflag/defer) actually engages narrow.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import make_mesh, tet_volumes
from parmmg_tpu.ops.active import (adapt_cycles_auto, closure_active,
                                   dirty_from_diff, narrow_rows)
from parmmg_tpu.ops.adapt import adapt_cycles_fused, adapt_mesh
from parmmg_tpu.ops.adjacency import check_adjacency
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric


def _setup(n=5, capmul=6):
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=capmul * len(vert),
                     capT=capmul * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP).at[: len(h)].set(
        jnp.asarray(h)).at[len(h):].set(1.0)
    return mesh, met


def _run_auto(mesh, met, blocks=5, nper=3):
    dirty = jnp.zeros(mesh.capP, bool)
    ok = jnp.asarray(False)
    rows = []
    for b in range(blocks):
        flags = tuple((nper * b + c) % 3 == 2 for c in range(nper))
        mesh, met, dirty, ok, counts = adapt_cycles_auto(
            mesh, met, dirty, ok, jnp.asarray(nper * b, jnp.int32),
            swap_flags=flags)
        rows.extend(np.asarray(counts))
    return mesh, met, dirty, ok, np.asarray(rows)


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_auto_engages_narrow_and_stays_conforming():
    mesh, met = _setup()
    vol0 = float(np.asarray(tet_volumes(mesh))[np.asarray(mesh.tmask)]
                 .sum())
    mesh, met, dirty, ok, rows = _run_auto(mesh, met, blocks=5)
    # the worklist must engage (narrow marker, column 7) after the
    # seeding full cycles
    assert rows[:, 7].sum() >= 3, rows
    assert check_adjacency(mesh) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(mesh))[np.asarray(mesh.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), vol0, rtol=1e-5)


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_narrow_leaves_untouched_regions_bit_identical():
    mesh, met = _setup()
    # seed the worklist with full cycles
    dirty = jnp.zeros(mesh.capP, bool)
    ok = jnp.asarray(False)
    mesh, met, dirty, ok, counts = adapt_cycles_auto(
        mesh, met, dirty, ok, jnp.asarray(0, jnp.int32),
        swap_flags=(False, False, False))
    pre = jax.tree.map(jnp.copy, mesh)
    pre_dirty = jnp.copy(dirty)
    mesh2, met2, dirty2, ok2, counts2 = adapt_cycles_auto(
        mesh, met, dirty, ok, jnp.asarray(3, jnp.int32),
        swap_flags=(False,), full_flags=(False,), final_rebuild=False)
    assert int(np.asarray(counts2)[0][7]) == 1   # ran narrow
    # rows outside the active set must be untouched
    d2, active = jax.jit(closure_active)(pre, pre_dirty)
    act = np.asarray(active)
    inact = ~act & np.asarray(pre.tmask)
    for name in ("tet", "tref", "ftag", "fref", "etag"):
        a = np.asarray(getattr(pre, name))[inact]
        b = np.asarray(getattr(mesh2, name))[inact]
        assert (a == b).all(), name
    assert np.asarray(pre.tmask)[inact].all()
    assert np.asarray(mesh2.tmask)[inact].all()
    # vertices not in the closure keep position/tags
    d2n = np.asarray(d2)
    far = ~d2n & np.asarray(pre.vmask)
    assert (np.asarray(pre.vert)[far] == np.asarray(mesh2.vert)[far]).all()
    assert (np.asarray(pre.vtag)[far] == np.asarray(mesh2.vtag)[far]).all()


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_auto_matches_full_quality():
    mesh, met = _setup(n=4)
    mesh_f, met_f = jax.tree.map(jnp.copy, mesh), jnp.copy(met)
    # auto path
    mesh_a, met_a, _, _, rows = _run_auto(mesh, met, blocks=6)
    # full-only path, same cadence
    for b in range(6):
        flags = tuple((3 * b + c) % 3 == 2 for c in range(3))
        mesh_f, met_f, _ = adapt_cycles_fused(
            mesh_f, met_f, jnp.asarray(3 * b, jnp.int32),
            swap_flags=flags)
    # compare POST-POLISH quality — the user-visible contract (the
    # production driver always runs the polish tail after the sizing
    # loop).  The RAW mins legitimately differ: narrow cycles stop
    # smoothing regions whose worklist went quiet (that is the point of
    # a worklist — Mmg's cascade behaves the same), while the full path
    # re-smooths everywhere every cycle, so its pre-polish min is
    # better whenever a sliver's neighborhood quiets early.
    from parmmg_tpu.ops.adapt import sliver_polish

    def _polish(m, k):
        for w in range(4):
            m, cnt = sliver_polish(m, k, jnp.asarray(1000 + w, jnp.int32))
            c = np.asarray(cnt)
            if int(c[0]) == 0 and int(c[1]) == 0:
                break
        return m

    mesh_a = _polish(mesh_a, met_a)
    mesh_f = _polish(mesh_f, met_f)
    qa = np.asarray(tet_quality(mesh_a))[np.asarray(mesh_a.tmask)]
    qf = np.asarray(tet_quality(mesh_f))[np.asarray(mesh_f.tmask)]
    # same quality class (the independent sets differ in tie-breaks, so
    # bit-equality is not expected)
    assert qa.min() > 0.5 * qf.min() - 1e-3
    assert abs(qa.mean() - qf.mean()) < 0.1
    na, nf = len(qa), len(qf)
    assert abs(na - nf) < 0.2 * max(na, nf)


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_adapt_mesh_auto_converges():
    # the host driver path: auto blocks + quiet/wide-check machinery +
    # polish; must converge to the standard quality gates
    mesh, met = _setup(n=4)
    m2, k2, st = adapt_mesh(mesh, met, max_cycles=40, cycle_block=3)
    assert check_adjacency(m2) == {"asymmetric": 0, "face_mismatch": 0}
    q = np.asarray(tet_quality(m2))[np.asarray(m2.tmask)]
    assert q.min() > 0.05
    assert st.nsplit > 0


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_narrow_discard_on_tight_capacity():
    # a mesh with nearly no free tet slots: the narrow branch must
    # either run full (okflag seeding) or discard cleanly — never
    # corrupt.  capmul=2 leaves little allocation room at refinement.
    mesh, met = _setup(n=3, capmul=2)
    vol0 = float(np.asarray(tet_volumes(mesh))[np.asarray(mesh.tmask)]
                 .sum())
    mesh, met, dirty, ok, rows = _run_auto(mesh, met, blocks=4)
    assert check_adjacency(mesh) == {"asymmetric": 0, "face_mismatch": 0}
    vols = np.asarray(tet_volumes(mesh))[np.asarray(mesh.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), vol0, rtol=1e-5)


def test_dirty_from_diff_detects_each_field():
    mesh, met = _setup(n=2)
    base = jax.tree.map(jnp.copy, mesh)
    # a vertex move dirties exactly that vertex (plus nothing else)
    moved = base.vert.at[5, 0].add(1e-3)
    import dataclasses
    m2 = dataclasses.replace(base, vert=moved)
    d = np.asarray(jax.jit(dirty_from_diff)(base, m2))
    assert d[5] and d.sum() == 1
    # a tet rewrite dirties its old and new vertices
    t0 = np.asarray(base.tet[0])
    newrow = jnp.asarray([t0[0], t0[1], t0[2], int(t0[3]) + 1])
    m3 = dataclasses.replace(base, tet=base.tet.at[0].set(newrow))
    d3 = np.asarray(jax.jit(dirty_from_diff)(base, m3))
    assert d3[t0].all() and d3[int(t0[3]) + 1]
