"""API surface tests, part B — split from test_api.py: the
XLA:CPU backend intermittently segfaults late in a long test process
(cumulative compiled-program state; same mitigation as
test_options/test_options_b and test_curved/test_curved_dist).
"""
import numpy as np
import pytest

from parmmg_tpu.api import ParMesh, IParam, DParam
from parmmg_tpu.core import constants as C
from parmmg_tpu.utils.fixtures import cube_mesh


def _staged_cube(n=2, **ipar):
    vert, tet = cube_mesh(n)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet))
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)                      # API is 1-based
    for k, v in ipar.items():
        pm.set_iparameter(getattr(IParam, k), v)
    return pm, vert, tet


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_entity_getters_after_adapt():
    """Single-entity + edge/normal/met getters (PMMG_Get_vertex/
    tetrahedron/triangle/edge/normalAtVertex, API_functions_pmmg.c)."""
    pm, vert, tet = _staged_cube(2, niter=1)
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.4))
    assert pm.run() == C.PMMG_SUCCESS

    npo, ne, nprism, nt, nquad, na = pm.get_mesh_size()
    x, y, z, ref, crn, req = pm.get_vertex(1)
    assert all(np.isfinite([x, y, z]))
    v = pm.get_tetrahedron(1)
    assert len(v) == 6 and all(1 <= q <= npo for q in v[:4])
    t = pm.get_triangle(1)
    assert len(t) == 5 and all(1 <= q <= npo for q in t[:3])

    # the unit cube has 12 sharp ridges -> feature edges must exist and
    # their endpoints must lie on the surface
    edges, erefs, eridge, ereq = pm.get_edges()
    assert len(edges) > 0 and eridge.any()
    assert edges.min() >= 1 and edges.max() <= npo
    e0 = pm.get_edge(1)
    assert len(e0) == 5
    # cube corners are detected as corner vertices
    verts, _ = pm.get_vertices()
    crns = [i + 1 for i in range(npo) if pm.get_vertex(i + 1)[4]]
    assert len(crns) >= 8

    # normals: unit length on smooth boundary points, zero inside
    vn = pm.get_normals()
    ln = np.linalg.norm(vn, axis=1)
    assert ((np.isclose(ln, 1, atol=1e-4)) | (ln < 1e-6)).all()
    nx, ny, nz = pm.get_normal_at_vertex(1)

    # metric getters
    assert pm.get_scalar_met(1) > 0
    assert len(pm.get_scalar_mets()) == npo

    # triangle global numbering (single-process identity)
    tg = pm.get_triangles_glonum()
    assert len(tg) == nt and tg[0] == 1 == pm.get_triangle_glonum(1)


def test_prisms_quads_passthrough():
    pm, vert, tet = _staged_cube(1, niter=1, noinsert=1, noswap=1, nomove=1)
    pm.set_mesh_size(np_=len(vert), ne=len(tet), nprism=1, nquad=1)
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.set_prism([1, 2, 3, 4, 5, 6], 7, 1)
    pm.set_quadrilateral([1, 2, 3, 4], 9, 1)
    prisms, prefs = pm.get_prisms()
    quads, qrefs = pm.get_quadrilaterals()
    assert prisms.tolist() == [[1, 2, 3, 4, 5, 6]]
    assert quads.tolist() == [[1, 2, 3, 4]]


def test_print_communicator(tmp_path):
    pm = ParMesh(nprocs=2, myrank=0)
    pm.set_mesh_size(np_=8, ne=6)
    pm.set_number_of_node_communicators(1)
    pm.set_ith_node_communicator_size(0, color_out=1, nitem=2)
    pm.set_ith_node_communicator_nodes(0, [1, 2], [10, 20])
    out = tmp_path / "comm.txt"
    pm.print_communicator(str(out))
    txt = out.read_text()
    assert "node communicators: 1" in txt and "color_out 1" in txt


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_required_tetrahedron_frozen():
    """set_required_tetrahedron freezes the tet through adaptation
    (PMMG/Mmg required-tet contract) and get_tetrahedron reports it."""
    pm, vert, tet = _staged_cube(2, niter=1)
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.3))
    req = 5                                      # arbitrary interior tet
    pm.set_required_tetrahedron(req)
    orig = np.sort(vert[tet[req - 1]], axis=0)
    assert pm.run() == C.PMMG_SUCCESS
    v, _ = pm.get_vertices()
    t, _ = pm.get_tetrahedra()
    # the required tet's 4 vertices survive at identical coordinates and
    # some output tet connects exactly those 4 vertices
    found = False
    for row in t:
        pts = np.sort(v[row - 1], axis=0)
        if pts.shape == orig.shape and np.allclose(pts, orig, atol=1e-6):
            found = True
            break
    assert found
    # and at least one output tet reads back as required
    npo, ne, *_ = pm.get_mesh_size()
    assert any(pm.get_tetrahedron(i + 1)[5] for i in range(ne))


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_prism_vertices_frozen_and_remapped():
    pm, vert, tet = _staged_cube(2, niter=1)
    pm.set_mesh_size(np_=len(vert), ne=len(tet), nprism=1)
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.3))
    pv = [1, 2, 3, 5, 6, 7]
    pm.set_prism(pv, 4, 1)
    before = vert[np.array(pv) - 1]
    assert pm.run() == C.PMMG_SUCCESS
    prisms, prefs = pm.get_prisms()
    assert prefs[0] == 4
    v, _ = pm.get_vertices()
    after = v[prisms[0] - 1]
    assert np.allclose(before, after, atol=1e-6)
