"""Telemetry spine (parmmg_tpu/obs): trace, metrics, artifacts.

All host-only — no jitted programs, so tier-1 pays zero compile time
for this file.  The compile-family and replay-parity end-to-end gates
live in scripts/obs_check.py (run_tests.sh --obs); here the host
semantics: span nesting + run-context propagation, the Timers bridge
(emission parity, external-segment tagging), histogram bucket edges,
Prometheus exposition round-trip, tenant namespacing riding the
AdaptStats isolation contract, and artifact schema validation on the
checked-in BENCH/SCALE/SERVE round artifacts.
"""
import json
import os

import pytest

from parmmg_tpu.obs import artifact as oart
from parmmg_tpu.obs import trace as otrace
from parmmg_tpu.obs.metrics import (DEFAULT_BUCKETS, MetricsRegistry,
                                    parse_prometheus, publish_stats)
from parmmg_tpu.ops.adapt import AdaptStats
from parmmg_tpu.utils.timers import Timers

ROOT = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture()
def fresh_tracer():
    """Route the global tracer (Timers emits into it) at a clean ring,
    no file sink; restore the env-driven default afterwards."""
    otrace.TRACER.configure(path=None)
    otrace.TRACER.reset()
    yield otrace.TRACER
    otrace.TRACER.configure(path=None)
    otrace.TRACER.reset()


def spans(tracer, **match):
    out = []
    for r in list(tracer.ring):
        if r.get("kind") != "span":
            continue
        if all(r.get(k) == v for k, v in match.items()):
            out.append(r)
    return out


# ---------------------------------------------------------------------------
# trace: spans, context, log
# ---------------------------------------------------------------------------
def test_span_nesting_and_context_propagation(fresh_tracer):
    rid = otrace.new_run(backend="cpu")
    with otrace.context(**{"pass": 2, "tenant": "t0"}):
        with otrace.span("outer"):
            with otrace.context(block=3):
                with otrace.span("inner"):
                    pass
    recs = spans(fresh_tracer)
    names = [r["name"] for r in recs]
    # inner completes (and therefore emits) before outer
    assert names == ["inner", "outer"]
    inner, outer = recs
    # run context folded into every record; scoped overlay only inside
    for r in (inner, outer):
        assert r["run"] == rid and r["backend"] == "cpu"
        assert r["pass"] == 2 and r["tenant"] == "t0"
    assert inner["block"] == 3 and "block" not in outer
    # leaving the scopes clears the overlay
    otrace.event("after")
    after = [r for r in fresh_tracer.ring if r.get("name") == "after"][0]
    assert "pass" not in after and "block" not in after
    otrace.new_run()  # don't leak tenant/backend into other tests


def test_timers_emit_and_replay_exactly(fresh_tracer):
    tim = Timers()
    with tim("a"):
        with tim("b"):
            pass
    with tim("a"):
        pass
    tim.add("c", 0.5, count=3)          # root-level absorb
    tot, cnt = otrace.replay_totals(list(fresh_tracer.ring),
                                    tim=tim.trace_id)
    assert set(tot) == set(tim.acc) == {"a", "a/b", "c"}
    for k in tim.acc:
        assert tot[k] == pytest.approx(tim.acc[k], rel=1e-12)
        assert cnt[k] == tim.count[k]
    # a second instance's spans don't bleed into the replay
    other = Timers()
    with other("a"):
        pass
    tot2, _ = otrace.replay_totals(list(fresh_tracer.ring),
                                   tim=tim.trace_id)
    assert tot2["a"] == pytest.approx(tim.acc["a"], rel=1e-12)


def test_timers_add_external_tagging(fresh_tracer):
    tim = Timers()
    with tim("phase"):
        tim.add("seg", 0.25)            # inside a scope: a sub-segment
    tim.add("orphan", 1.0)              # outside any scope: external
    assert "phase/seg" in tim.acc and "phase/seg" not in tim.external
    assert "orphan" in tim.external
    rep = tim.report()
    orphan_line = [ln for ln in rep.splitlines() if "orphan" in ln][0]
    seg_line = [ln for ln in rep.splitlines() if "seg" in ln][0]
    assert "[absorbed]" in orphan_line
    assert "[absorbed]" not in seg_line
    ext = spans(fresh_tracer, name="orphan")[0]
    assert ext.get("ext") is True
    assert not spans(fresh_tracer, name="phase/seg")[0].get("ext")


def test_log_gates_but_always_traces(fresh_tracer, capsys):
    assert otrace.log(2, "visible", verbose=3) is True
    assert otrace.log(3, "hidden", verbose=2) is False
    out = capsys.readouterr().out
    assert "visible" in out and "hidden" not in out
    logs = [r for r in fresh_tracer.ring if r.get("kind") == "log"]
    assert [r["msg"] for r in logs] == ["visible", "hidden"]
    assert logs[0]["shown"] is True and logs[1]["shown"] is False


def test_jsonl_sink_and_file_replay(tmp_path, fresh_tracer):
    path = str(tmp_path / "trace.jsonl")
    otrace.TRACER.configure(path=path)
    tim = Timers()
    with tim("x"):
        with tim("y"):
            pass
    otrace.event("marker", foo=1)
    otrace.TRACER.configure(path=None)
    recs = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert all("ts" in r for r in recs)
    assert any(r.get("name") == "marker" and r.get("foo") == 1
               for r in recs)
    tot, cnt = otrace.replay_totals(path, tim=tim.trace_id)
    assert set(tot) == {"x", "x/y"}
    assert tot["x"] == pytest.approx(tim.acc["x"], rel=1e-12)
    assert cnt["x/y"] == 1


def test_tracer_ring_bound_and_summary():
    t = otrace.Tracer(ring=4, path=None)
    for i in range(10):
        t.emit({"kind": "span", "name": f"s{i % 2}", "dur": 0.1})
    s = t.summary()
    assert s["events"] == 10 and s["ring"] == 4 and s["dropped"] == 6
    assert set(s["top_spans_s"]) <= {"s0", "s1"}


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
def test_histogram_bucket_edges():
    reg = MetricsRegistry()
    h = reg.histogram("lat", bounds=(0.1, 1.0, 10.0))
    # le bounds are INCLUSIVE upper edges (Prometheus convention)
    h.observe(0.1)        # == first bound -> first bucket
    h.observe(0.100001)   # just past    -> second bucket
    h.observe(1.0)        # == second    -> second bucket
    h.observe(10.0)       # == last      -> third bucket
    h.observe(11.0)       # past all     -> +Inf bucket
    assert h.counts == [1, 2, 1, 1]
    cum = dict(h.cumulative())
    assert cum[0.1] == 1 and cum[1.0] == 3 and cum[10.0] == 4
    assert cum[float("inf")] == 5
    assert h.n == 5 and h.sum == pytest.approx(22.200001)
    # default ladder is fixed, increasing, log-spaced
    assert all(b2 / b1 == 2.0
               for b1, b2 in zip(DEFAULT_BUCKETS, DEFAULT_BUCKETS[1:]))


def test_metrics_registry_types_and_snapshot():
    reg = MetricsRegistry()
    reg.counter("a.b").inc(2)
    reg.counter("a.b").inc(0.5)         # same series accumulates
    reg.gauge("g").set(7)
    reg.histogram("h").observe(0.01)
    with pytest.raises(TypeError):
        reg.gauge("a.b")                # kind collision
    with pytest.raises(ValueError):
        reg.counter("a.b").inc(-1)      # counters are monotone
    snap = reg.snapshot()
    assert snap["counters"]["a.b"] == 2.5
    assert snap["gauges"]["g"] == 7.0
    assert snap["histograms"]["h"]["count"] == 1
    json.dumps(snap)                    # JSON-serializable


def test_prometheus_exposition_roundtrip():
    reg = MetricsRegistry()
    reg.counter("serve.admit_ok").inc(3)
    reg.counter("adapt.nsplit", tenant="t-1").inc(41)
    reg.gauge("serve.queue_depth").set(2)
    h = reg.histogram("serve.latency_s", bounds=(0.5, 2.0))
    h.observe(0.4)
    h.observe(1.7)
    h.observe(9.0)
    text = reg.to_prometheus()
    parsed = parse_prometheus(text)
    assert parsed[("parmmg_serve_admit_ok_total", frozenset())] == 3
    assert parsed[("parmmg_adapt_nsplit_total",
                   frozenset({("tenant", "t-1")}))] == 41
    assert parsed[("parmmg_serve_queue_depth", frozenset())] == 2
    assert parsed[("parmmg_serve_latency_s_bucket",
                   frozenset({("le", "0.5")}))] == 1
    assert parsed[("parmmg_serve_latency_s_bucket",
                   frozenset({("le", "2")}))] == 2
    assert parsed[("parmmg_serve_latency_s_bucket",
                   frozenset({("le", "+Inf")}))] == 3
    assert parsed[("parmmg_serve_latency_s_count", frozenset())] == 3
    assert parsed[("parmmg_serve_latency_s_sum",
                   frozenset())] == pytest.approx(11.1)


def test_tenant_namespacing_rides_adaptstats_isolation():
    # cross-tenant AdaptStats merge STILL raises (the isolation
    # contract the metrics bridge relies on)
    a = AdaptStats(tenant="a", nsplit=1)
    b = AdaptStats(tenant="b", nsplit=2)
    with pytest.raises(ValueError):
        a += b
    reg = MetricsRegistry()
    publish_stats(a, reg)
    publish_stats(b, reg)
    agg = AdaptStats()
    agg += AdaptStats(tenant="c", nsplit=5,
                      sched_extra={"grp_upload_s": 0.5})
    publish_stats(agg, reg)
    snap = reg.snapshot()["counters"]
    # the AdaptStats tenant:<id>/ namespacing convention, per series
    assert snap["tenant:a/adapt.nsplit"] == 1
    assert snap["tenant:b/adapt.nsplit"] == 2
    assert snap["adapt.nsplit"] == 5          # untagged aggregate
    # the aggregate's absorbed per-tenant keys keep their namespacing
    # instead of double-tagging (they are already tenant:<id>/-scoped)
    assert "sched.tenant:c/grp_upload_s" not in snap
    # exposition separates the tenants as labels
    parsed = parse_prometheus(reg.to_prometheus())
    assert parsed[("parmmg_adapt_nsplit_total",
                   frozenset({("tenant", "a")}))] == 1
    assert parsed[("parmmg_adapt_nsplit_total",
                   frozenset({("tenant", "b")}))] == 2


# ---------------------------------------------------------------------------
# artifacts
# ---------------------------------------------------------------------------
def test_make_artifact_is_canonical_and_valid(fresh_tracer):
    reg = MetricsRegistry()
    reg.counter("x").inc()
    doc = oart.make_artifact("BENCH", metric="m", value=1.5, unit="u",
                             extra={"qmin": 0.3}, vs_baseline=2.0,
                             registry=reg)
    assert oart.validate_artifact(doc) == []
    assert doc["schema_version"] == oart.SCHEMA_VERSION
    assert doc["metrics"]["counters"]["x"] == 1.0
    assert "compile_ledger" in doc["extra"]
    assert "backend" in doc["env"]
    json.dumps(doc)
    # the upgrade path is a no-op on canonical docs
    assert oart.upgrade_artifact(doc) is doc
    with pytest.raises(ValueError):
        oart.make_artifact("NOPE", metric="m", value=0, unit="")


@pytest.mark.parametrize("fname", ["BENCH_r03.json", "SCALE_r03.json",
                                   "SERVE_r01.json"])
def test_checked_in_artifacts_upgrade_and_validate(fname):
    with open(os.path.join(ROOT, fname)) as f:
        doc = json.load(f)
    up = oart.upgrade_artifact(doc)
    assert oart.validate_artifact(up) == [], fname
    kind = fname.split("_")[0]
    assert up["kind"] == kind
    assert up["value"] > 0
    json.dumps(up)


def test_validate_rejects_malformed():
    assert oart.validate_artifact([]) != []
    doc = oart.make_artifact("SCALE", metric="m", value=1.0, unit="u")
    bad = dict(doc)
    bad.pop("metrics")
    assert any("metrics" in p for p in oart.validate_artifact(bad))
    bad2 = dict(doc, kind="WHAT")
    assert any("kind" in p for p in oart.validate_artifact(bad2))
    bad3 = dict(doc, value="fast")
    assert any("value" in p for p in oart.validate_artifact(bad3))


def test_artifact_diff_ledger_value_and_metrics():
    def mk(variants, value, qmin, counters):
        return {"schema_version": 1, "kind": "BENCH", "metric": "thr",
                "value": value, "unit": "u", "env": {"backend": "cpu"},
                "metrics": {"counters": counters, "gauges": {},
                            "histograms": {}},
                "trace": {"events": 0},
                "extra": {"qmin": qmin, "compile_ledger": {
                    "groups.adapt_block": {"variants": variants}}}}

    old = mk(1, 1.0, 0.30, {"groups.dispatches": 5})
    # ledger growth + throughput drop + qmin drop + vanished counter
    new = mk(3, 0.5, 0.10, {})
    d = oart.artifact_diff(old, new)
    assert any("groups.adapt_block" in v for v in d["ledger"])
    assert any("thr" in v for v in d["value"])
    assert any("qmin" in v for v in d["quality"])
    assert any("groups.dispatches" in v for v in d["notes"])
    # improvement directions stay quiet
    better = mk(1, 2.0, 0.35, {"groups.dispatches": 9})
    d2 = oart.artifact_diff(old, better)
    assert d2["ledger"] == [] and d2["value"] == [] \
        and d2["quality"] == [] and d2["notes"] == []


def test_artifact_diff_direction_for_seconds_metrics():
    # seconds-valued headline (MULTIHOST wall time): regression is UP
    def mh(seconds):
        return {"schema_version": 1, "kind": "MULTIHOST",
                "metric": "multihost_adapt", "value": seconds,
                "unit": "s", "env": {"backend": "cpu"},
                "metrics": {"counters": {}, "gauges": {},
                            "histograms": {}},
                "trace": {"events": 0},
                "extra": {"compile_ledger": {}}}

    faster = oart.artifact_diff(mh(100.0), mh(80.0))
    assert faster["value"] == []          # 20% faster is NOT a regression
    slower = oart.artifact_diff(mh(100.0), mh(200.0))
    assert any("multihost_adapt" in v for v in slower["value"])


def test_artifact_diff_on_checked_in_rounds():
    # the real r01 -> r03 bench history must not flag ledger
    # regressions (r01 predates the ledger: compares clean)
    with open(os.path.join(ROOT, "BENCH_r01.json")) as f:
        old = json.load(f)
    with open(os.path.join(ROOT, "BENCH_r03.json")) as f:
        new = json.load(f)
    d = oart.artifact_diff(old, new)
    assert d["ledger"] == []
    assert d["value"] == []       # throughput went UP across rounds


def test_profiler_unarmed_is_inert(monkeypatch, fresh_tracer):
    monkeypatch.delenv("PARMMG_PROFILE_DIR", raising=False)
    assert otrace.profile_pass_begin(0) is False
    assert otrace.profile_pass_end(0) is False
    assert otrace.profiling_active() is False
    # annotate/scope degrade to free nullcontexts when inert
    with otrace.annotate("x"):
        with otrace.scope("y"):
            pass
