"""Serve-daemon subsystem tests (serve/admission + autoscale + daemon).

Tier-1 pins the host-side machinery only — the autoscale controller as
a pure function of a metrics snapshot, admission backpressure, the
REAL SlotPool.step streaming worklist (mid-step slot re-rent, dispatch
stubbed), bucket resizing, and the daemon's full HTTP lifecycle over
localhost with a stub pool — no XLA compiles, no jax programs.  The
slow test pins the streaming-admission exactness contract: bit-for-bit
per-tenant parity against the between-steps admission path.  (The
daemon-vs-standalone compile/parity side is gated by
``run_tests.sh --ledger`` serving_gate and ``--serve`` /
scripts/serve_check.py.)
"""
import numpy as np
import pytest

from parmmg_tpu.serve.autoscale import (AutoscaleController, decide,
                                        latency_quantile, read_inputs)
from parmmg_tpu.serve.client import (BackpressureDeferred, ServeClient,
                                     ServeDaemonError)
from parmmg_tpu.serve.daemon import PoolDaemon
from parmmg_tpu.serve.driver import ServeDriver
from parmmg_tpu.serve.pool import SlotPool


# ---------------------------------------------------------------------------
# host-only stubs: real admission/bookkeeping, no XLA
# ---------------------------------------------------------------------------
class HostPool(SlotPool):
    """Real SlotPool admission + slot bookkeeping; load/merge stash the
    payload on the slot instead of splitting/merging (no jax)."""

    def load(self, tenant, mesh, met):
        key, i = self._where[tenant]
        s = self.buckets[key].slots[i]
        s.loaded = True
        s.payload = (mesh, met)

    def merge(self, tenant):
        return self.slot_of(tenant).payload


class InstantPool(HostPool):
    """Serves each loaded tenant after ``steps_to_converge`` advances,
    honoring the streaming ``on_retire`` contract — no dispatches."""

    def __init__(self, steps_to_converge=1, **kw):
        super().__init__(**kw)
        self.steps_to_converge = steps_to_converge

    def step(self, verbose=0, on_retire=None):
        self.steps += 1
        done = []
        while True:
            progressed = False
            for key in sorted(self.buckets):
                for s in self.buckets[key].slots:
                    if s.tenant and s.loaded and not s.converged \
                            and not s.failed \
                            and getattr(s, "stepped", 0) < self.steps:
                        s.stepped = self.steps   # once per step
                        s.c += 1
                        progressed = True
                        if s.c >= self.steps_to_converge:
                            s.converged = True
                            done.append(s.tenant)
                            if on_retire is not None:
                                on_retire([s.tenant])
            if on_retire is None or not progressed:
                break
        return done


class StubDriver(ServeDriver):
    """Host-only driver: quality + RPC staging stubbed (dict meshes)."""

    def _quality(self, mesh, met):
        tet = mesh["tet"] if isinstance(mesh, dict) else mesh.tet
        return {"qmin": 1.0, "qmean": 1.0, "nbad": 0,
                "ntets": int(len(np.asarray(tet)))}

    def stage_payload(self, arrays):
        met = arrays.get("met")
        return {"vert": arrays["vert"], "tet": arrays["tet"]}, met


def _stub_mesh():
    vert = np.arange(12, dtype=np.float64).reshape(4, 3)
    tet = np.array([[0, 1, 2, 3], [1, 2, 3, 0]], np.int32)
    return vert, tet, np.ones(4)


def _daemon(steps=1, **drv_kw):
    pool = InstantPool(steps_to_converge=steps, slots_per_bucket=2)
    drv = StubDriver(pool=pool, autoscale=False, **drv_kw)
    return PoolDaemon(driver=drv, port=0, start_paused=True,
                      idle_sleep_s=0.005).start()


# ---------------------------------------------------------------------------
# autoscale: the pure controller (no jax, no sockets)
# ---------------------------------------------------------------------------
def _inputs(**kw):
    base = {"queue_depth": 0, "occupancy": {}, "slots": {},
            "blocked": {}, "p99_s": 0.0, "slo_violations": 0,
            "deferring": False}
    base.update(kw)
    return base


def test_autoscale_decide_grows_blocked_full_bucket():
    d = decide(_inputs(queue_depth=3, occupancy={"64x192": 2},
                       slots={"64x192": 2}, blocked={"64x192": 3}),
               max_slots=4)
    assert d.grow == {"64x192": 3} and not d.shrink and not d.defer
    # growth ceiling: a bucket at max_slots never grows past it
    d = decide(_inputs(queue_depth=1, occupancy={"64x192": 4},
                       slots={"64x192": 4}, blocked={"64x192": 1}),
               max_slots=4)
    assert d.grow == {}
    # blocked but not full (slots free for other reasons): no grow
    d = decide(_inputs(queue_depth=1, occupancy={"64x192": 1},
                       slots={"64x192": 2}, blocked={"64x192": 1}),
               max_slots=4)
    assert d.grow == {}


def test_autoscale_decide_shrink_is_debounced():
    idle = _inputs(occupancy={"64x192": 0}, slots={"64x192": 3})
    assert decide(idle, idle_evals={"64x192": 2}, shrink_after=3).shrink \
        == {}
    assert decide(idle, idle_evals={"64x192": 3}, shrink_after=3).shrink \
        == {"64x192": 2}
    # never below min_slots; never while work is queued
    floor = _inputs(occupancy={"64x192": 0}, slots={"64x192": 1})
    assert decide(floor, idle_evals={"64x192": 9}).shrink == {}
    busy = _inputs(queue_depth=1, occupancy={"64x192": 0},
                   slots={"64x192": 3})
    assert decide(busy, idle_evals={"64x192": 9}).shrink == {}


def test_autoscale_defer_hysteresis():
    # latch on at the queue bound...
    d = decide(_inputs(queue_depth=4), max_queue=4)
    assert d.defer is True
    # ...stays latched above half the bound...
    d = decide(_inputs(queue_depth=3, deferring=True), max_queue=4)
    assert d.defer is True
    # ...releases at half
    d = decide(_inputs(queue_depth=2, deferring=True), max_queue=4)
    assert d.defer is False
    # p99 SLO breach with work queued also latches
    d = decide(_inputs(queue_depth=1, p99_s=2.0), target_p99_s=1.0)
    assert d.defer is True and "p99" in " ".join(d.reasons)
    # ...and a still-breached p99 holds the latch even with a small
    # queue — the latch must not flap while the condition persists
    d = decide(_inputs(queue_depth=1, p99_s=2.0, deferring=True),
               max_queue=4, target_p99_s=1.0)
    assert d.defer is True
    # p99 breach with an EMPTY queue does not (nothing to shed)
    d = decide(_inputs(queue_depth=0, p99_s=2.0), target_p99_s=1.0)
    assert d.defer is False


def test_autoscale_p99_is_windowed_per_evaluation():
    """The controller must judge RECENT latencies: cold-start compile
    latencies in the lifetime-cumulative histogram must not pin the
    p99 signal (and with it the defer latch) above target forever."""
    ctl = AutoscaleController(max_slots=4, max_queue=0,
                              target_p99_s=1.0)
    hist = {"buckets": {"256.0": 5, "inf": 5}, "count": 5}
    snap = {"gauges": {"serve.queue_depth": 1.0}, "counters": {},
            "histograms": {"serve.latency_s": hist}}
    assert ctl.evaluate(snap).defer is True     # cold window breaches
    ctl.deferring = True                        # (tick would latch it)
    # same cumulative histogram, no NEW observations: recent p99 == 0,
    # nothing hot -> the latch releases as the queue drains
    snap2 = {"gauges": {"serve.queue_depth": 0.0}, "counters": {},
             "histograms": {"serve.latency_s": dict(hist)}}
    d = ctl.evaluate(snap2)
    assert d.defer is False
    ctl.deferring = d.defer          # (tick would commit the release)
    # a fresh burst of fast latencies in the window stays un-hot
    hist3 = {"buckets": {"0.25": 3, "256.0": 8, "inf": 8}, "count": 8}
    snap3 = {"gauges": {"serve.queue_depth": 1.0}, "counters": {},
             "histograms": {"serve.latency_s": hist3}}
    assert ctl.evaluate(snap3).defer is False


def test_latency_quantile_and_read_inputs():
    hist = {"buckets": {"0.25": 4, "0.5": 9, "1.0": 10, "inf": 10},
            "count": 10}
    assert latency_quantile(hist, 0.4) == 0.25   # cum 4 covers 4.0
    assert latency_quantile(hist, 0.5) == 0.5    # cum 4 < 5 -> next edge
    assert latency_quantile(hist, 0.99) == 1.0
    assert latency_quantile({"buckets": {}, "count": 0}, 0.99) == 0.0
    snap = {"gauges": {"serve.queue_depth": 2.0,
                       "serve.occupancy.64x192": 1.0,
                       "serve.slots.64x192": 2.0,
                       "serve.admit_blocked.64x192": 1.0},
            "counters": {"tenant:a/serve.slo_violation": 2.0,
                         "serve.slo_violation": 1.0},
            "histograms": {"serve.latency_s": hist}}
    got = read_inputs(snap, deferring=True)
    assert got == {"queue_depth": 2, "occupancy": {"64x192": 1},
                   "slots": {"64x192": 2}, "blocked": {"64x192": 1},
                   "p99_s": 1.0, "slo_violations": 3.0,
                   "deferring": True}


def test_autoscale_tick_actuates_on_a_real_pool():
    from parmmg_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    pool = SlotPool(slots_per_bucket=1)
    pool.admit("a", 27, 48)
    key = pool._where["a"][0]
    label = pool.bucket_label(key)
    reg.gauge("serve.queue_depth").set(1)
    reg.gauge(f"serve.occupancy.{label}").set(1)
    reg.gauge(f"serve.slots.{label}").set(1)
    reg.gauge(f"serve.admit_blocked.{label}").set(1)
    ctl = AutoscaleController(max_slots=4, max_queue=0, target_p99_s=0,
                              shrink_after=2)
    d = ctl.tick(pool, registry=reg)
    assert d.grow == {label: 2}
    assert pool.buckets[key].nslots == 2 and ctl.grows == 1
    assert reg.snapshot()["counters"]["serve.autoscale.grow"] == 1
    # idle long enough -> shrink back (debounced over 2 evaluations)
    pool.release("a")
    reg.gauge("serve.queue_depth").set(0)
    reg.gauge(f"serve.occupancy.{label}").set(0)
    reg.gauge(f"serve.slots.{label}").set(2)
    reg.gauge(f"serve.admit_blocked.{label}").set(0)
    assert ctl.tick(pool, registry=reg).shrink == {}       # streak 1
    assert ctl.tick(pool, registry=reg).shrink == {}       # streak 2
    d = ctl.tick(pool, registry=reg)                       # acts
    assert d.shrink == {label: 1}
    assert pool.buckets[key].nslots == 1 and ctl.shrinks == 1


def test_autoscale_idle_shrink_reaches_floor_without_step():
    """tick() refreshes occupancy/slots gauges from the POOL: an idle
    pool (step never runs, so step's gauge publishing never fires)
    still shrinks rung by rung to the 1-slot floor — frozen gauges
    must not pin nslots at (last-gauged - 1) forever."""
    from parmmg_tpu.obs.metrics import MetricsRegistry
    reg = MetricsRegistry()
    pool = SlotPool(slots_per_bucket=1)
    pool.admit("a", 27, 48)
    key = pool._where["a"][0]
    pool.release("a")
    pool.resize_bucket(key, 4)
    ctl = AutoscaleController(max_slots=8, shrink_after=1)
    for _ in range(8):
        ctl.tick(pool, registry=reg)
    assert pool.buckets[key].nslots == 1
    assert ctl.shrinks == 3


# ---------------------------------------------------------------------------
# pool resize + admission backpressure (host bookkeeping)
# ---------------------------------------------------------------------------
def test_resize_bucket_grow_and_trailing_free_shrink():
    pool = SlotPool(slots_per_bucket=2)
    pool.admit("a", 27, 48)
    key = pool._where["a"][0]
    assert pool.resize_bucket(key, 4) == 4
    assert pool.buckets[key].free_slot() == 1
    # tenant in slot 0: shrink keeps it, drops only trailing free slots
    assert pool.resize_bucket(key, 1) == 1
    assert pool.slot_of("a").tenant == "a"
    # a mid-array tenant blocks shrink below its own slot index + 1
    assert pool.resize_bucket(key, 3) == 3
    pool.admit("b", 27, 48)
    pool.admit("c", 27, 48)
    pool.release("b")                  # slot 1 free, slot 2 rented
    assert pool.resize_bucket(key, 1) == 3
    assert pool.labels() == {pool.bucket_label(key): key}


def test_try_submit_backpressure_and_latch():
    drv = StubDriver(pool=InstantPool(slots_per_bucket=1),
                     autoscale=False, max_queue=1, stream=True)
    vert, tet, met = _stub_mesh()
    tid, reason = drv.try_submit(mesh={"vert": vert, "tet": tet},
                                 met=met, tenant="q1")
    assert tid == "q1" and reason is None
    tid, reason = drv.try_submit(mesh={"vert": vert, "tet": tet},
                                 met=met, tenant="q2")
    assert tid is None and "queue full" in reason
    assert drv.admission.deferred == 1 and "q2" not in drv.requests
    # the autoscale defer latch blocks even an empty queue
    drv.queue = []
    drv.admission.deferring = True
    tid, reason = drv.try_submit(mesh={"vert": vert, "tet": tet},
                                 met=met, tenant="q3")
    assert tid is None and "autoscale" in reason


def test_stream_midstep_rerent(monkeypatch):
    """The REAL SlotPool.step streaming worklist: a slot freed by a
    cohort's retirement is re-rented to a queued tenant and dispatched
    at its own cycle 0 within the SAME pool step (3 tenants through 1
    slot in ONE step; the cohort dispatch itself is stubbed so the
    test stays host-only)."""
    calls = []

    def fake_dispatch(self, b, fn, wave, ids, done):
        calls.append([b.slots[i].tenant for i in ids])
        self.dispatches += len(ids)
        return [(i, np.zeros((1, 8), np.int64)) for i in ids]

    monkeypatch.setattr(SlotPool, "_dispatch_cohort", fake_dispatch)
    vert, tet, met = _stub_mesh()
    drv = StubDriver(pool=HostPool(slots_per_bucket=1, cycles=1),
                     autoscale=False, stream=True)
    for t in ("ra", "rb", "rc"):
        drv.submit(mesh={"vert": vert, "tet": tet}, met=met, tenant=t)
    rep = drv.run()
    assert rep["served"] == 3 and rep["failed"] == 0
    # ONE pool step served all three through the one slot: each
    # retirement re-rented the slot mid-step (the zero-count block is a
    # converged fixed point, so every tenant retires on its dispatch)
    assert drv.pool.steps == 1
    assert calls == [["ra"], ["rb"], ["rc"]]
    assert rep["admission"]["stream_admissions"] == 2
    # between-steps mode on the same workload pays one step per tenant
    monkeypatch.setattr(SlotPool, "_dispatch_cohort", fake_dispatch)
    drv2 = StubDriver(pool=HostPool(slots_per_bucket=1, cycles=1),
                      autoscale=False, stream=False)
    for t in ("sa", "sb", "sc"):
        drv2.submit(mesh={"vert": vert, "tet": tet}, met=met, tenant=t)
    rep2 = drv2.run()
    assert rep2["served"] == 3 and drv2.pool.steps == 3
    assert rep2["admission"]["stream_admissions"] == 0


# ---------------------------------------------------------------------------
# daemon lifecycle over localhost (stub pool, host-only)
# ---------------------------------------------------------------------------
def test_daemon_lifecycle_roundtrip_stub_pool():
    d = _daemon()
    try:
        cl = ServeClient(port=d.port, timeout_s=10)
        h = cl.health()
        assert h["ok"] is True and h["paused"] is True
        vert, tet, met = _stub_mesh()
        tid = cl.submit(vert=vert, tet=tet, met=met, tenant="stub-a")
        assert tid == "stub-a"
        assert cl.poll(tid)["state"] == "queued"
        assert cl.step()["state"] == "active"   # manual loop iteration
        got = cl.wait(tid, timeout_s=5)
        assert got["state"] == "done" and got["quality"]["qmin"] == 1.0
        arrays = cl.fetch(tid)                  # bit-exact npz roundtrip
        assert (arrays["vert"] == vert).all()
        assert (arrays["tet"] == tet).all()
        assert (arrays["met"] == met).all()
        # a second tenant rides the LIVE loop after /resume
        cl.resume()
        tid2 = cl.submit(vert=vert, tet=tet, met=met)
        assert cl.wait(tid2, timeout_s=5)["state"] == "done"
        cl.pause()
        from parmmg_tpu.obs.metrics import parse_prometheus
        series = parse_prometheus(cl.metrics_text())
        assert any(n == "parmmg_serve_admit_ok_total"
                   for n, _ in series)
        with pytest.raises(ServeDaemonError) as ei:
            cl.poll("no-such-request")
        assert ei.value.status == 404
        rep = cl.report()
        assert rep["served"] == 2 and rep["failed"] == 0
    finally:
        d.shutdown()
    assert not d.alive()


def test_daemon_rpc_fault_quarantines_midflight(monkeypatch):
    """The serve.daemon_rpc faultpoint: an RPC fault on a RUNNING
    tenant's request quarantines THAT tenant (retired FAILED, slot
    recycled) while the daemon and its cohort-mates keep going — the
    tier-1 mirror of the --chaos daemon scenario."""
    from parmmg_tpu.resilience.faults import FAULTS
    d = _daemon(steps=2)
    try:
        cl = ServeClient(port=d.port, timeout_s=10)
        vert, tet, met = _stub_mesh()
        for t in ("fa", "fb"):
            cl.submit(vert=vert, tet=tet, met=met, tenant=t)
        cl.step()                       # both advance 1 of 2 steps
        assert cl.poll("fa")["state"] == "running"
        monkeypatch.setenv("PARMMG_FAULT", "serve.daemon_rpc:key=fa")
        FAULTS.reset()
        with pytest.raises(ServeDaemonError) as ei:
            cl.poll("fa")
        assert ei.value.status == 500
        assert ei.value.body["quarantined"] is True
        monkeypatch.delenv("PARMMG_FAULT")
        FAULTS.reset()
        assert cl.health()["ok"] is True            # daemon survives
        assert cl.poll("fa")["state"] == "failed"
        cl.step()
        assert cl.wait("fb", timeout_s=5)["state"] == "done"
        rep = cl.report()
        assert "fa" in rep["pool"]["quarantined"]
        assert "daemon rpc fault" in rep["tenants"]["fa"]["reason"]
        # the quarantined tenant's slot is back on the free list
        occ = rep["pool"]["buckets"]
        assert all(used == 0 for used, _ in occ.values())
    finally:
        monkeypatch.delenv("PARMMG_FAULT", raising=False)
        FAULTS.reset()
        d.shutdown()


def test_terminal_request_eviction_bounds_the_table():
    """A persistent service must not retain every finished request's
    merged mesh forever: beyond ``retain_done``, the oldest terminal
    requests are evicted (in-flight ones never are)."""
    vert, tet, met = _stub_mesh()
    drv = StubDriver(pool=InstantPool(slots_per_bucket=2),
                     autoscale=False, retain_done=2)
    for t in ("e0", "e1", "e2", "e3"):
        drv.submit(mesh={"vert": vert, "tet": tet}, met=met, tenant=t)
    rep = drv.run()
    # the two OLDEST finished requests were evicted; the table (and the
    # report, which covers retained requests only — the bounded-history
    # contract) holds exactly retain_done entries, all slots recycled
    assert len(drv.requests) == 2
    assert set(drv.requests) == {"e2", "e3"}
    assert rep["served"] == 2
    assert all(used == 0 for used, _ in drv.pool.occupancy().values())
    assert drv.fetch("e3") is not None
    with pytest.raises(KeyError):
        drv.poll("e0")


class FlakyDriver(StubDriver):
    """service_once raises a few times before recovering — the daemon
    loop-guard fixture."""

    boom = 2

    def service_once(self):
        if self.boom and self.queue:
            self.boom -= 1
            raise RuntimeError("injected loop iteration failure")
        return super().service_once()


def test_daemon_loop_survives_iteration_errors():
    """An exception escaping one serving-loop iteration must not kill
    the loop thread: the daemon accounts it, keeps looping, and still
    serves — and /healthz reports loop liveness honestly."""
    from parmmg_tpu.obs.metrics import REGISTRY
    pool = InstantPool(steps_to_converge=1, slots_per_bucket=2)
    drv = FlakyDriver(pool=pool, autoscale=False)
    d = PoolDaemon(driver=drv, port=0, idle_sleep_s=0.005).start()
    try:
        c0 = REGISTRY.counter("serve.loop_errors").value
        cl = ServeClient(port=d.port, timeout_s=10)
        vert, tet, met = _stub_mesh()
        tid = cl.submit(vert=vert, tet=tet, met=met, tenant="flaky")
        assert cl.wait(tid, timeout_s=5)["state"] == "done"
        assert REGISTRY.counter("serve.loop_errors").value - c0 == 2
        h = cl.health()
        assert h["ok"] is True and h["loop_alive"] is True
    finally:
        d.shutdown()


def test_daemon_malformed_submit_is_500_not_404():
    """A submit payload missing required arrays is a server-side 500
    (counted in serve.rpc_errors), never a 404 'unknown request'."""
    import base64
    import io
    d = _daemon()
    try:
        cl = ServeClient(port=d.port, timeout_s=10)
        buf = io.BytesIO()
        np.savez_compressed(buf, tet=np.zeros((1, 4), np.int32))
        with pytest.raises(ServeDaemonError) as ei:
            cl._rpc("POST", "/submit", {
                "npz_b64": base64.b64encode(buf.getvalue())
                .decode("ascii")})
        assert ei.value.status == 500
        assert "vert" in str(ei.value.body)
    finally:
        d.shutdown()


def test_daemon_backpressure_429():
    d = _daemon(max_queue=1)
    try:
        cl = ServeClient(port=d.port, timeout_s=10)
        vert, tet, met = _stub_mesh()
        cl.submit(vert=vert, tet=tet, met=met, tenant="bp1")
        with pytest.raises(BackpressureDeferred) as ei:
            cl.submit(vert=vert, tet=tet, met=met, tenant="bp2")
        assert ei.value.status == 429
        assert ei.value.body["deferred"] is True
        with pytest.raises(ServeDaemonError):   # bp2 never enqueued
            cl.poll("bp2")
        # the queued tenant drains -> the SAME submit now lands
        cl.step()
        assert cl.wait("bp1", timeout_s=5)["state"] == "done"
        assert cl.submit(vert=vert, tet=tet, met=met,
                         tenant="bp2") == "bp2"
    finally:
        d.shutdown()


# ---------------------------------------------------------------------------
# streaming-admission exactness (slow tier: group-block XLA compiles)
# ---------------------------------------------------------------------------
def _tenant(n=2, h=0.55):
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import cube_mesh
    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, h, m.vert.dtype)
    return m, met


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_streaming_admission_bit_parity():
    """Streaming (mid-step re-rent) vs between-steps admission: every
    tenant retires bit-for-bit identical — admission TIMING never
    changes a tenant's bytes.  3 tenants of distinct metrics through
    ONE home slot, so the streaming run genuinely re-rents mid-step."""
    from parmmg_tpu.core.mesh import MESH_FIELDS
    cases = {"pa": 0.55, "pb": 0.42, "pc": 0.5}
    outs = {}
    for stream in (False, True):
        drv = ServeDriver(slots_per_bucket=1, chunk=1, cycles=3,
                          stream=stream, autoscale=False)
        for tid, h in cases.items():
            m, met = _tenant(2, h)
            drv.submit(mesh=m, met=met, tenant=tid)
        rep = drv.run()
        assert rep["served"] == 3 and rep["failed"] == 0
        outs[stream] = {
            tid: tuple(np.asarray(getattr(drv.fetch(tid)[0], f))
                       .tobytes() for f in MESH_FIELDS)
            + (np.asarray(drv.fetch(tid)[1]).tobytes(),)
            for tid in cases}
        if stream:
            assert rep["admission"]["stream_admissions"] >= 1
    assert outs[False] == outs[True]
