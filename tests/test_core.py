"""M0 tests: mesh core, adjacency, quality, Medit I/O round-trips."""
import numpy as np
import jax.numpy as jnp
import pytest

from parmmg_tpu.core.mesh import (
    Mesh, make_mesh, mesh_to_host, compact, tet_volumes, with_capacity)
from parmmg_tpu.core import constants as C
from parmmg_tpu.ops.adjacency import (
    build_adjacency, check_adjacency, boundary_edge_tags)
from parmmg_tpu.ops.quality import (
    tet_quality, tet_edge_lengths, quality_histogram, length_histogram,
    iso_to_tensor, edge_length_iso)
from parmmg_tpu.utils.fixtures import cube_mesh, sphere_mesh
from parmmg_tpu.io import medit


def test_cube_fixture_conforming():
    vert, tet = cube_mesh(3)
    assert vert.shape == (64, 3)
    assert tet.shape == (6 * 27, 4)
    # positive volumes
    m = make_mesh(vert, tet)
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.isclose(vols.sum(), 1.0)


def test_adjacency_cube():
    vert, tet = cube_mesh(3)
    m = build_adjacency(make_mesh(vert, tet))
    chk = check_adjacency(m)
    assert chk == {"asymmetric": 0, "face_mismatch": 0}
    # Euler sanity: boundary faces of the cube = 2 tris * 6 faces * n^2
    nbdy = int(np.sum((np.asarray(m.ftag) & C.MG_BDY) != 0))
    assert nbdy == 2 * 6 * 9


def test_boundary_tags_propagate():
    vert, tet = cube_mesh(2)
    m = boundary_edge_tags(build_adjacency(make_mesh(vert, tet)))
    vtag = np.asarray(m.vtag)[np.asarray(m.vmask)]
    on_bdy = ((vert == 0) | (vert == 1)).any(axis=1)
    assert ((vtag & C.MG_BDY) != 0).tolist() == on_bdy.tolist()


def test_quality_equilateral_is_one():
    # regular tetrahedron
    vert = np.array([[1, 1, 1], [1, -1, -1], [-1, 1, -1], [-1, -1, 1]],
                    dtype=np.float64)
    tet = np.array([[0, 2, 1, 3]], np.int32)
    m = make_mesh(vert, tet)
    q = np.asarray(tet_quality(m))[0]
    assert abs(q - 1.0) < 1e-5
    # aniso path with identity-ish metric gives same
    met = iso_to_tensor(jnp.full(m.capP, 1.0))
    q2 = np.asarray(tet_quality(m, met))[0]
    assert abs(q2 - q) < 1e-5


def test_quality_inverted_negative():
    vert = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, 1]], float)
    tet = np.array([[0, 2, 1, 3]], np.int32)  # negative orientation
    m = make_mesh(vert, tet)
    assert float(tet_quality(m)[0]) < 0


def test_edge_lengths_iso():
    # edge of euclidean length 1 with h=0.5 at both ends -> metric length 2
    p0 = jnp.array([0.0, 0, 0])
    p1 = jnp.array([1.0, 0, 0])
    assert abs(float(edge_length_iso(p0, p1, 0.5, 0.5)) - 2.0) < 1e-6
    # log-mean: h0=1, h1=2 -> l = (r1-r0)/ln(r1/r0) ... = 1*(1-.5)/ln2
    l = float(edge_length_iso(p0, p1, 1.0, 2.0))
    assert abs(l - (0.5 / np.log(2.0))) < 1e-5


def test_histograms():
    vert, tet = cube_mesh(3)
    m = make_mesh(vert, tet)
    met = jnp.full(m.capP, 1.0 / 3.0)   # grid spacing = ideal size
    q = tet_quality(m)
    counts, qmin, qmean, nbad = quality_histogram(q, m.tmask)
    assert int(nbad) == 0
    assert int(counts.sum()) == 6 * 27
    lc, lmin, lmax, lmean = length_histogram(m, met)
    # grid edges: axis 1.0, face diag sqrt2, body diag sqrt3 (in metric units)
    assert 0.99 < float(lmin) < 1.01
    assert 1.7 < float(lmax) < 1.74
    # unique edge count for kuhn cube n=3
    assert int(lc.sum()) > 0


def test_compact_and_grow():
    vert, tet = cube_mesh(2)
    m = build_adjacency(make_mesh(vert, tet))
    # invalidate a few tets, compact, adjacency still symmetric
    tmask = np.asarray(m.tmask).copy()
    kill = [0, 5, 17]
    tmask[kill] = False
    import dataclasses
    adja = np.asarray(m.adja).copy()
    # detach killed tets from their neighbors
    for t in kill:
        for f in range(4):
            a = adja[t, f]
            if a >= 0:
                adja[a >> 2, a & 3] = -1
            adja[t, f] = -1
    m2 = dataclasses.replace(m, tmask=jnp.asarray(tmask),
                             adja=jnp.asarray(adja))
    m3 = compact(m2)
    assert m3.np_counts()[1] == 6 * 8 - 3
    assert check_adjacency(m3) == {"asymmetric": 0, "face_mismatch": 0}
    m4 = with_capacity(m3, 2 * m3.capP, 2 * m3.capT)
    assert m4.np_counts() == m3.np_counts()
    assert check_adjacency(m4) == {"asymmetric": 0, "face_mismatch": 0}


def test_mesh_to_host_roundtrip():
    vert, tet = cube_mesh(2)
    m = make_mesh(vert, tet)
    v2, t2, vr, tr, vt = mesh_to_host(m)
    assert np.allclose(v2, vert)
    assert (t2 == tet).all()


@pytest.mark.parametrize("suffix", [".mesh", ".meshb"])
def test_medit_roundtrip(tmp_path, suffix):
    vert, tet = cube_mesh(2)
    mm = medit.MeditMesh()
    mm.vert = vert
    mm.vref = np.zeros(len(vert), np.int32)
    mm.tetra = tet
    mm.tref = np.ones(len(tet), np.int32)
    mm.tria = np.array([[0, 1, 2]], np.int32)
    mm.triaref = np.array([7], np.int32)
    mm.corners = np.array([0], np.int32)
    mm.required_vert = np.array([3], np.int32)
    p = tmp_path / ("m" + suffix)
    medit.write_mesh(p, mm)
    m2 = medit.read_mesh(p)
    assert np.allclose(m2.vert, vert)
    assert (m2.tetra == tet).all()
    assert (m2.tref == 1).all()
    assert (m2.tria == [[0, 1, 2]]).all()
    assert m2.triaref[0] == 7
    assert m2.corners.tolist() == [0]
    assert m2.required_vert.tolist() == [3]


@pytest.mark.parametrize("suffix", [".sol", ".solb"])
def test_sol_roundtrip(tmp_path, suffix):
    vals = np.random.default_rng(0).random((10, 1))
    p = tmp_path / ("m" + suffix)
    medit.write_sol(p, vals, [medit.SOL_SCALAR])
    v2, types = medit.read_sol(p)
    assert types == [1]
    assert np.allclose(v2, vals)
    # tensor sol
    vals6 = np.random.default_rng(1).random((5, 6))
    p2 = tmp_path / ("t" + suffix)
    medit.write_sol(p2, vals6, [medit.SOL_TENSOR])
    v3, types3 = medit.read_sol(p2)
    assert types3 == [3]
    assert np.allclose(v3, vals6)


def test_sphere_fixture():
    vert, tet = sphere_mesh(4)
    m = make_mesh(vert, tet)
    vols = np.asarray(tet_volumes(m))[np.asarray(m.tmask)]
    assert (vols > 0).all()
    assert np.linalg.norm(vert, axis=1).max() <= 1.0 + 1e-9
