"""Parity of the Pallas radix-sort/segment engine vs jnp argsort/lexsort.

Runs the kernels with ``interpret=True`` on the CPU test backend; on
real TPU the production dispatch (ops/edges.py / ops/adjacency.py /
ops/topo_incr.py through ``pallas_kernels.sort_perm`` under
PARMMG_PALLAS_SORT) routes through the compiled versions of exactly
these kernels.  Everything here asserts BIT equality — the sort engine's
contract is "stable LSD radix == stable comparator sort", not "close".
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from parmmg_tpu.ops import pallas_kernels as pk

I32_MAX = 2147483647
# deliberately awkward lengths: 1, sub-lane, lane-1/lane/lane+1, odd,
# crossing the (8,128) block boundary, multi-block prime
SIZES = (1, 2, 127, 128, 129, 777, 1025, 4099)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(11)


@pytest.mark.parametrize("n", SIZES)
def test_radix_single_word_vs_argsort(rng, n):
    # duplicate-heavy keys: ties everywhere, stability is load-bearing
    k = jnp.asarray(rng.integers(0, max(2, n // 8), n), jnp.int32)
    ref = jnp.argsort(k)
    got = pk.radix_sort_pallas((k,), interpret=True)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("n", SIZES)
def test_radix_two_word_vs_lexsort(rng, n):
    a = jnp.asarray(rng.integers(0, 7, n), jnp.int32)
    b = jnp.asarray(rng.integers(0, 5, n), jnp.int32)
    ref = jnp.lexsort((b, a))
    got = pk.radix_sort_pallas((a, b), interpret=True)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_radix_three_word_vs_lexsort(rng):
    n = 999
    cols = [jnp.asarray(rng.integers(0, 4, n), jnp.int32)
            for _ in range(3)]
    ref = jnp.lexsort((cols[2], cols[1], cols[0]))
    got = pk.radix_sort_pallas(tuple(cols), interpret=True)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("n", (130, 1025))
def test_radix_int32max_tombstones(rng, n):
    # the sites key dead slots INT32_MAX: must sort last, stably
    k = jnp.asarray(rng.integers(0, 9, n), jnp.int32)
    k = jnp.where(jnp.asarray(rng.random(n) < 0.4), jnp.int32(I32_MAX), k)
    ref = jnp.argsort(k)
    got = pk.radix_sort_pallas((k,), interpret=True)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_radix_all_equal_keys():
    # all-equal: stability means the identity permutation
    n = 515
    e = jnp.zeros(n, jnp.int32)
    got = pk.radix_sort_pallas((e,), interpret=True)
    assert np.array_equal(np.asarray(got), np.arange(n))


def test_radix_nbits16_tombstone_remap(rng):
    # the face-sort shape: major word < capP <= 46340 < 2^16 with
    # INT32_MAX tombstones, declared nbits=16 (2 digit passes) — the
    # in-kernel remap to 0xFFFF must preserve the order exactly
    n = 1337
    s = jnp.asarray(rng.integers(0, 46340, n), jnp.int32)
    s = jnp.where(jnp.asarray(rng.random(n) < 0.3), jnp.int32(I32_MAX), s)
    w = jnp.asarray(rng.integers(0, 1 << 20, n), jnp.int32)
    ref = jnp.lexsort((w, s))
    got = pk.radix_sort_pallas((s, w), nbits=(16, 32), interpret=True)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


def test_f32_sort_u32_matches_jax_total_order(rng):
    # the uint32 image must mirror jax's stable comparator sort exactly:
    # -0.0 == +0.0 (tie by position), all NaNs equal and after +inf
    n = 521
    x = rng.normal(size=n).astype(np.float32)
    x[rng.random(n) < 0.15] = 0.0
    x[rng.random(n) < 0.15] = -0.0
    x[rng.random(n) < 0.1] = np.inf
    x[rng.random(n) < 0.1] = -np.inf
    x[rng.random(n) < 0.1] = np.nan
    xs = jnp.asarray(x)
    u = pk.f32_sort_u32(xs).astype(jnp.int32)
    ref = jnp.argsort(xs)
    got = pk.radix_sort_pallas((u,), interpret=True)
    assert np.array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("n", SIZES)
def test_segment_flags_single_word(rng, n):
    k = jnp.sort(jnp.asarray(rng.integers(0, max(2, n // 4), n),
                             jnp.int32))
    ref = np.concatenate([[True], np.asarray(k[1:] != k[:-1])])
    got = np.asarray(pk.segment_flags_pallas((k,), interpret=True))
    assert np.array_equal(ref, got)


def test_segment_flags_multi_word(rng):
    n = 2051                       # crosses the 1024-element block seam
    a = jnp.asarray(rng.integers(0, 6, n), jnp.int32)
    b = jnp.asarray(rng.integers(0, 6, n), jnp.int32)
    o = jnp.lexsort((b, a))
    aa, bb = a[o], b[o]
    ref = np.concatenate(
        [[True], np.asarray((aa[1:] != aa[:-1]) | (bb[1:] != bb[:-1]))])
    got = np.asarray(pk.segment_flags_pallas((aa, bb), interpret=True))
    assert np.array_equal(ref, got)


def test_segment_flags_all_equal():
    n = 1100
    e = jnp.full(n, 3, jnp.int32)
    got = np.asarray(pk.segment_flags_pallas((e,), interpret=True))
    ref = np.zeros(n, bool)
    ref[0] = True
    assert np.array_equal(ref, got)


# ---- forced-interpret site-level dispatch parity ---------------------------

def _forced(monkeypatch, on: bool):
    if on:
        monkeypatch.setenv("PARMMG_TPU_PALLAS", "1")
        monkeypatch.setenv("PARMMG_PALLAS_SORT", "1")
    else:
        monkeypatch.delenv("PARMMG_TPU_PALLAS", raising=False)
        monkeypatch.setenv("PARMMG_PALLAS_SORT", "0")


def test_sort_pairs_forced_parity(rng, monkeypatch):
    from parmmg_tpu.ops.edges import PACK_LIMIT, sort_pairs
    n = 700
    a = jnp.asarray(rng.integers(0, 40, n), jnp.int32)
    b = jnp.asarray(rng.integers(0, 40, n), jnp.int32)
    valid = jnp.asarray(rng.random(n) < 0.8)
    outs = []
    for on in (False, True):
        _forced(monkeypatch, on)
        # packed branch AND the unpacked 2-column fallback
        outs.append([np.asarray(x)
                     for cap in (40, PACK_LIMIT + 1)
                     for x in sort_pairs(a, b, valid, cap)])
    for x, y in zip(*outs):
        assert np.array_equal(x, y)


def test_unique_priority_forced_parity(rng, monkeypatch):
    from parmmg_tpu.ops.edges import unique_priority
    n = 600
    # heavy score ties: the argsort-rank tie-break must survive
    score = jnp.asarray(np.round(rng.random(n) * 8) / 8, jnp.float32)
    mask = jnp.asarray(rng.random(n) < 0.7)
    outs = []
    for on in (False, True):
        _forced(monkeypatch, on)
        outs.append(np.asarray(unique_priority(score, mask)))
    assert np.array_equal(outs[0], outs[1])


def test_face_sort_forced_parity(monkeypatch):
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops import adjacency as adj
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import cube_mesh
    vert, tet = cube_mesh(2)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    outs = []
    for on in (False, True):
        _forced(monkeypatch, on)
        outs.append([np.asarray(x) for x in adj.face_sort(m)])
    for x, y in zip(*outs):
        assert np.array_equal(x, y)


def test_band_order_forced_parity(rng, monkeypatch):
    from parmmg_tpu.ops.topo_incr import band_order
    m = 300
    bk = jnp.asarray(rng.integers(0, 50, m), jnp.int32)
    bk = jnp.where(jnp.asarray(rng.random(m) < 0.3),
                   jnp.int32(I32_MAX), bk)
    bs = jnp.asarray(rng.permutation(m), jnp.int32)
    outs = []
    for on in (False, True):
        _forced(monkeypatch, on)
        outs.append(np.asarray(band_order((bk,), bs)))
    assert np.array_equal(outs[0], outs[1])


def test_pallas_sort_sites_static(monkeypatch):
    # off-TPU without forcing, the dispatcher lowers only the reference
    # and the bench site list is empty; forcing interpret mode lists
    # every site
    monkeypatch.setenv("PARMMG_PALLAS_SORT", "1")
    monkeypatch.delenv("PARMMG_TPU_PALLAS", raising=False)
    if jax.default_backend() != "tpu":
        assert pk.pallas_sort_sites() == []
    monkeypatch.setenv("PARMMG_TPU_PALLAS", "1")
    assert set(pk.pallas_sort_sites()) == {
        "unique_edges_sort", "unique_edges_segment", "priority_sort",
        "face_sort", "band_sort"}
    monkeypatch.setenv("PARMMG_PALLAS_SORT", "0")
    assert pk.pallas_sort_sites() == []
