"""CLI + distributed I/O + VTK tests.

Mirror of the reference CI matrix style (cmake/testing/pmmg_tests.cmake):
end-to-end executable runs on generated fixtures, pass criterion = exit
code PLUS quality/conformity assertions (stronger than the reference's
exit-code-only gates, per SURVEY §4 implication).
"""
import json
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from parmmg_tpu.cli import main as cli_main
from parmmg_tpu.io import medit
from parmmg_tpu.io.distributed import (
    ShardComm, save_distributed_mesh, load_distributed_mesh,
    insert_rank_index, probe_distributed)
from parmmg_tpu.io.vtk import write_vtu, write_pvtu
from parmmg_tpu.utils.fixtures import cube_mesh


def _write_cube(tmp, n=2, with_sol=None):
    vert, tet = cube_mesh(n)
    m = medit.MeditMesh()
    m.vert = vert
    m.vref = np.zeros(len(vert), np.int32)
    m.tetra = tet
    m.tref = np.zeros(len(tet), np.int32)
    p = tmp / "cube.mesh"
    medit.write_mesh(p, m)
    if with_sol is not None:
        medit.write_sol(tmp / "cube.sol", np.full(len(vert), with_sol),
                        [medit.SOL_SCALAR])
    return p, vert, tet


def test_cli_noop_run(tmp_path):
    p, vert, tet = _write_cube(tmp_path)
    rc = cli_main(["-in", str(p), "-niter", "1", "-noinsert", "-noswap",
                   "-nomove", "-v", "0"])
    assert rc == 0
    out = medit.read_mesh(tmp_path / "cube.o.mesh")
    assert len(out.tetra) > 0
    assert len(out.tria) > 0            # boundary written


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_cli_adapt_with_sol(tmp_path):
    p, vert, tet = _write_cube(tmp_path, with_sol=0.3)
    rc = cli_main(["-in", str(p), "-sol", str(tmp_path / "cube.sol"),
                   "-niter", "1", "-v", "0"])
    assert rc == 0
    out = medit.read_mesh(tmp_path / "cube.o.mesh")
    assert len(out.vert) > len(vert)    # refined against h=0.3
    # output metric written next to the mesh
    vals, types = medit.read_sol(tmp_path / "cube.o.sol")
    assert len(vals) == len(out.vert)


def test_cli_default_values(capsys):
    rc = cli_main(["-val"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "niter" in out and "hgrad" in out


def test_cli_missing_input(tmp_path):
    rc = cli_main(["-in", str(tmp_path / "nope.mesh"), "-v", "0"])
    assert rc != 0


def test_distributed_roundtrip(tmp_path):
    vert, tet = cube_mesh(2)
    m = medit.MeditMesh()
    m.vert, m.vref = vert, np.zeros(len(vert), np.int32)
    m.tetra, m.tref = tet, np.zeros(len(tet), np.int32)
    fc = [ShardComm(1, np.array([1, 2, 3]), np.array([10, 20, 30]))]
    nc = [ShardComm(1, np.array([5, 6]), np.array([50, 60]))]
    out = save_distributed_mesh(tmp_path / "w.mesh", 0, m, fc, nc)
    assert out.name == "w.0.mesh"
    assert probe_distributed(tmp_path / "w.mesh", 0)
    m2, fc2, nc2 = load_distributed_mesh(tmp_path / "w.mesh", 0)
    assert np.allclose(m2.vert, m.vert)
    assert (m2.tetra == m.tetra).all()
    assert len(fc2) == 1 and fc2[0].color_out == 1
    assert fc2[0].local.tolist() == [1, 2, 3]
    assert fc2[0].global_.tolist() == [10, 20, 30]
    assert nc2[0].global_.tolist() == [50, 60]


def test_stacked_writer_roundtrip(tmp_path):
    """io.distributed.stacked_to_distributed_files: per-rank files
    written DIRECTLY from the stacked shard state (no merge), vertex
    communicators renumbered into the compacted file numbering — and
    the compaction program is the cached governed jit (writer_tables),
    so repeat checkpoints reuse one compiled variant.  The two-shard
    stacked state is hand-built (two tets sharing a face across the
    interface, dead pad slots interleaved) so the test compiles only
    the tiny writer program, not the split pipeline."""
    import dataclasses
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.io.distributed import (stacked_to_distributed_files,
                                           writer_tables)
    from parmmg_tpu.parallel.comms import pad_comm_tables
    from parmmg_tpu.utils.compilecache import ledger_snapshot

    # shard 0: tet (0,1,2,3); shard 1: tet (0,2,1,4) — the shared face
    # (0,1,2) is the interface, written with a dead pad row per shard
    v0 = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, 1]], float)
    v1 = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, -1]], float)
    import jax
    sh = []
    for vv, tt in ((v0, [[0, 1, 2, 3]]), (v1, [[0, 2, 1, 3]])):
        sh.append(make_mesh(vv, np.asarray(tt, np.int32), capP=6,
                            capT=2))
    stacked = jax.tree.map(lambda a, b: jnp.stack([a, b]), sh[0], sh[1])
    # node comms: the 3 interface vertices, same order both sides
    node_lists = [[[], [0, 1, 2]], [[0, 1, 2], []]]
    face_lists = [[[], []], [[], []]]
    owner = [np.array([1, 1, 1, 0], np.int32),
             np.array([1, 1, 1, 1], np.int32)]
    comms = pad_comm_tables(node_lists, face_lists, owner, 2)
    glo = [np.array([0, 1, 2, 3, -1, -1], np.int64),
           np.array([0, 1, 2, 4, -1, -1], np.int64)]
    outs = stacked_to_distributed_files(tmp_path / "ck.mesh", stacked,
                                        comms, glo, 2)
    assert [o.name for o in outs] == ["ck.0.mesh", "ck.1.mesh"]
    assert writer_tables() is writer_tables()      # one cached program
    assert ledger_snapshot()["io.writer_tables"]["calls"] >= 1
    for r in range(2):
        mr, fc, nc = load_distributed_mesh(tmp_path / "ck.mesh", r)
        vm = np.asarray(stacked.vmask[r])
        assert np.allclose(mr.vert, np.asarray(stacked.vert[r])[vm])
        assert len(mr.tetra) == int(np.asarray(stacked.tmask[r]).sum())
        # connectivity references the compacted numbering
        assert mr.tetra.min() >= 0 and mr.tetra.max() < len(mr.vert)
        # mirror-side agreement: the communicator carries the session
        # global ids, identical on both sides of the pair
        assert len(nc) == 1 and nc[0].color_out == 1 - r
    m0 = load_distributed_mesh(tmp_path / "ck.mesh", 0)[2][0]
    m1 = load_distributed_mesh(tmp_path / "ck.mesh", 1)[2][0]
    assert m0.global_.tolist() == m1.global_.tolist() == [1, 2, 3]


def test_stacked_writer_multi_tenant_roundtrip(tmp_path):
    """Serving satellite: two TENANTS sharing one stacked tree write to
    separate per-tenant file sets (``shards`` slot subset, no
    communicators) and read back bit-identical.  The hand-built state
    reuses the exact stacked shapes of test_stacked_writer_roundtrip so
    tier-1 pays zero fresh writer_tables compiles (host-side numpy
    otherwise)."""
    import jax
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.io.distributed import stacked_to_distributed_files

    # two independent tenant meshes as slots of ONE stacked tree —
    # same [2, 6]/[2, 2] capacities as the checkpoint test above
    vA = np.array([[0, 0, 0], [2, 0, 0], [0, 2, 0], [0, 0, 2]], float)
    vB = np.array([[0, 0, 0], [1, 0, 0], [0, 1, 0], [0, 0, -3],
                   [1, 1, 1]], float)
    mA = make_mesh(vA, np.asarray([[0, 1, 2, 3]], np.int32),
                   vref=np.asarray([1, 2, 3, 4], np.int32),
                   capP=6, capT=2)
    mB = make_mesh(vB, np.asarray([[0, 2, 1, 3], [0, 1, 2, 4]],
                                  np.int32),
                   tref=np.asarray([7, 8], np.int32), capP=6, capT=2)
    stacked = jax.tree.map(lambda a, b: jnp.stack([a, b]), mA, mB)

    outs = {}
    for tid, slot in (("tenantA", 0), ("tenantB", 1)):
        got = stacked_to_distributed_files(
            tmp_path / f"{tid}.mesh", stacked, None, None, 2,
            shards=[slot])
        assert [o.name for o in got] == [f"{tid}.0.mesh"]
        outs[tid] = got[0]
    for tid, src in (("tenantA", mA), ("tenantB", mB)):
        mr, fc, nc = load_distributed_mesh(tmp_path / f"{tid}.mesh", 0)
        assert fc == [] and nc == []       # comms=None: no sections
        vm = np.asarray(src.vmask)
        tm = np.asarray(src.tmask)
        assert (mr.vert == np.asarray(src.vert, np.float64)[vm]).all()
        assert (mr.vref == np.asarray(src.vref)[vm]).all()
        assert (mr.tref == np.asarray(src.tref)[tm]).all()
        # live connectivity survives the compact renumber bit-for-bit
        # (the compacted numbering IS the live prefix here)
        assert (mr.tetra == np.asarray(src.tet)[tm]).all()


def _write_split_cube(tmp_path, n=2):
    """Two-shard distributed fixture: centroid-split cube halves written
    as name.<rank>.mesh files; returns (vert, tet, part)."""
    vert, tet = cube_mesh(n)
    cent = vert[tet].mean(axis=1)
    part = (cent[:, 0] > 0.5).astype(int)
    for r in range(2):
        sel = tet[part == r]
        used = np.unique(sel)
        g2l = np.full(len(vert), -1)
        g2l[used] = np.arange(len(used))
        m = medit.MeditMesh()
        m.vert = vert[used]
        m.vref = np.zeros(len(used), np.int32)
        m.tetra = g2l[sel].astype(np.int32)
        m.tref = np.zeros(len(sel), np.int32)
        save_distributed_mesh(tmp_path / "d.mesh", r, m)
    return vert, tet, part


def test_cli_reads_distributed_input(tmp_path):
    vert, tet, part = _write_split_cube(tmp_path)
    rc = cli_main(["-in", str(tmp_path / "d.mesh"), "-niter", "1",
                   "-noinsert", "-noswap", "-nomove", "-v", "0"])
    assert rc == 0
    out = medit.read_mesh(tmp_path / "d.o.mesh")
    # reassembled: all tets, deduplicated interface vertices
    assert len(out.tetra) == len(tet)
    assert len(out.vert) == len(vert)


def test_vtu_pvtu_output(tmp_path):
    vert, tet = cube_mesh(1)
    f = write_vtu(tmp_path / "m.vtu", vert, tet,
                  point_data={"h": np.ones(len(vert))})
    txt = f.read_text()
    assert "UnstructuredGrid" in txt and "connectivity" in txt
    pf = write_pvtu(tmp_path / "m.pvtu", [f], point_data={"h": 1})
    assert "PUnstructuredGrid" in pf.read_text()
    assert "m.vtu" in pf.read_text()


def test_cli_vtu_output(tmp_path):
    p, vert, tet = _write_cube(tmp_path)
    rc = cli_main(["-in", str(p), "-out", str(tmp_path / "out.pvtu"),
                   "-niter", "1", "-noinsert", "-noswap", "-nomove",
                   "-v", "0"])
    assert rc == 0
    assert (tmp_path / "out.pvtu").exists()
    assert (tmp_path / "out.vtu").exists()


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_cli_bench_json(tmp_path, capsys):
    p, vert, tet = _write_cube(tmp_path, with_sol=0.4)
    rc = cli_main(["-in", str(p), "-sol", str(tmp_path / "cube.sol"),
                   "-niter", "1", "-v", "0", "-noout", "-bench-json"])
    assert rc == 0
    line = [l for l in capsys.readouterr().out.splitlines()
            if l.startswith("{")][0]
    rec = json.loads(line)
    assert rec["ntets"] > 0 and rec["qmin"] > 0


def test_cli_distributed_output_multishard_roundtrip(tmp_path):
    """-ndev 2 -distributed-output writes per-rank files with
    communicator sections; re-reading them centralized reproduces the
    mesh (the reference's distributed<->centralized round-trip CI tests,
    pmmg_tests.cmake:173-208)."""
    vert, tet = cube_mesh(2)
    m = medit.MeditMesh()
    m.vert, m.vref = vert, np.zeros(len(vert), np.int32)
    m.tetra, m.tref = tet, np.zeros(len(tet), np.int32)
    medit.write_mesh(tmp_path / "c.mesh", m)
    rc = cli_main(["-in", str(tmp_path / "c.mesh"),
                   "-out", str(tmp_path / "d.mesh"),
                   "-ndev", "2", "-niter", "1",
                   "-noinsert", "-noswap", "-nomove",
                   "-distributed-output", "-v", "0"])
    assert rc == 0
    assert (tmp_path / "d.0.mesh").exists()
    assert (tmp_path / "d.1.mesh").exists()
    from parmmg_tpu.io.distributed import load_distributed_mesh
    m0, fc0, nc0 = load_distributed_mesh(tmp_path / "d.mesh", 0)
    m1, fc1, nc1 = load_distributed_mesh(tmp_path / "d.mesh", 1)
    # both shards have comms toward each other with matched sizes/order
    assert fc0 and nc0 and fc1 and nc1
    assert fc0[0].color_out == 1 and fc1[0].color_out == 0
    assert len(fc0[0].local) == len(fc1[0].local)
    assert fc0[0].global_.tolist() == fc1[0].global_.tolist()
    assert nc0[0].global_.tolist() == nc1[0].global_.tolist()
    # interface triangles listed in each shard's Triangles section
    assert len(m0.tria) >= len(fc0[0].local)
    # reassembly: total tets conserved, interface verts deduplicated
    ntet_total = len(m0.tetra) + len(m1.tetra)
    nshared = len(nc0[0].local)
    assert ntet_total == len(tet)
    assert len(m0.vert) + len(m1.vert) - nshared == len(vert)
    # re-read distributed input through the CLI
    rc = cli_main(["-in", str(tmp_path / "d.mesh"),
                   "-out", str(tmp_path / "back.mesh"), "-niter", "1",
                   "-noinsert", "-noswap", "-nomove", "-v", "0"])
    assert rc == 0
    back = medit.read_mesh(tmp_path / "back.mesh")
    assert len(back.tetra) == len(tet)
    assert len(back.vert) == len(vert)


def test_distributed_input_adopts_partition(tmp_path):
    """Distributed input stays distributed (libparmmg.c:206-329
    semantics): the run must ADOPT the caller's decomposition as the
    initial partition instead of re-partitioning — verified by spying on
    distributed_adapt_multi's `part` argument."""
    vert, tet, part = _write_split_cube(tmp_path)

    from parmmg_tpu.parallel import dist as dist_mod
    seen = {}
    orig = dist_mod.distributed_adapt_multi

    def spy(mesh, met, n_shards, **kw):
        seen["part"] = None if kw.get("part") is None \
            else np.array(kw["part"])
        return orig(mesh, met, n_shards, **kw)

    dist_mod.distributed_adapt_multi = spy
    try:
        rc = cli_main(["-in", str(tmp_path / "d.mesh"), "-niter", "1",
                       "-noinsert", "-noswap", "-nomove", "-ndev", "2",
                       "-v", "0"])
    finally:
        dist_mod.distributed_adapt_multi = orig
    assert rc == 0
    # adopted VERBATIM: the concatenated files list shard 0's tets then
    # shard 1's, so the adopted labels must be exactly that sequence —
    # no sort on the spy side (a flipped or scrambled adoption fails)
    assert seen["part"] is not None
    n0 = int((part == 0).sum())
    n1 = int((part == 1).sum())
    assert np.array_equal(seen["part"],
                          np.repeat([0, 1], [n0, n1]))


def test_vtu_reader_roundtrip(tmp_path):
    """write_vtu -> read_vtu_medit round-trips geometry + metric
    (PMMG_loadVtuMesh_centralized role, inoutcpp_pmmg.cpp:44)."""
    from parmmg_tpu.io.vtk import read_vtu_medit
    vert, tet = cube_mesh(2)
    met = np.linspace(0.2, 0.5, len(vert))
    p = write_vtu(tmp_path / "in.vtu", vert, tet,
                  point_data={"metric": met},
                  cell_data={"ref": np.arange(len(tet), dtype=float)})
    m, met_r, fields = read_vtu_medit(p)
    assert np.allclose(m.vert, vert)
    assert (m.tetra == tet).all()
    assert np.allclose(met_r, met)
    assert (m.tref == np.arange(len(tet))).all()
    assert fields == {}


# slow: multi-minute XLA compile on the tier-1 CPU box (tier-2 covers it)
@pytest.mark.slow
def test_cli_vtu_input(tmp_path):
    """End-to-end: -in cube.vtu (metric in point data) adapts and writes
    the medit output."""
    from parmmg_tpu.io.vtk import write_vtu
    vert, tet = cube_mesh(2)
    p = write_vtu(tmp_path / "cube.vtu", vert, tet,
                  point_data={"metric": np.full(len(vert), 0.4)})
    out = tmp_path / "out.mesh"
    rc = cli_main(["-in", str(p), "-out", str(out), "-niter", "1", "-v",
                   "-1"])
    assert rc == 0
    mo = medit.read_mesh(out)
    assert len(mo.tetra) > 0


def test_parsop_edge_locals(tmp_path):
    """A parsop file with an Edges entry clamps sizes on the user edge's
    vertices (MMG3D_parsop edge-kind locals)."""
    from parmmg_tpu.api import ParMesh
    vert, tet = cube_mesh(2)
    pm = ParMesh()
    pm.set_mesh_size(np_=len(vert), ne=len(tet), na=1)
    pm.set_vertices(vert)
    pm.set_tetrahedra(tet + 1)
    # one user edge along the bottom x-axis, ref 7
    i0 = int(np.where((vert == [0, 0, 0]).all(1))[0][0])
    i1 = int(np.where((np.isclose(vert[:, 0], 0.5))
                      & (vert[:, 1] == 0) & (vert[:, 2] == 0))[0][0])
    pm.set_edges(np.array([[i0 + 1, i1 + 1]]), np.array([7]))
    pm.set_met_size(1, len(vert))
    pm.set_scalar_mets(np.full(len(vert), 0.45))
    pm.set_local_parameter(3, 7, 0.05, 0.12, 0.01)
    from parmmg_tpu.driver import build_metric
    mesh, met0 = pm._build_core_mesh()
    met = np.asarray(build_metric(mesh, met0, pm.info))
    assert met[i0] <= 0.12 + 1e-9 and met[i1] <= 0.12 + 1e-9
    others = np.setdiff1d(np.arange(len(vert)), [i0, i1])
    assert (met[others] > 0.12).any()
