"""Test config: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference CI matrix over MPI rank counts {1,2,4,6,8}
(cmake/testing/pmmg_tests.cmake:30-63) — here rank = virtual CPU device.
JAX_PLATFORMS is force-overridden: the environment presets the real TPU
(axon), but unit tests must not serialize on the single chip.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# NO persistent compile cache for the CPU test matrix: on this image the
# XLA:CPU AOT cache is unreliable — serialize() intermittently SIGABRTs
# inside put_executable_and_time, and reloading entries warns about
# machine-feature mismatches (+prefer-no-scatter) that "could lead to
# SIGILL" (cpu_aot_loader.cc).  Set JAX_COMPILATION_CACHE_DIR explicitly
# to opt back in; the TPU bench path keeps its own cache (bench.py).
if os.environ.get("PARMMG_TEST_CACHE", "") == "1":
    _CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_DIR)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# The environment may pre-register a real-TPU tunnel backend ("axon") via
# sitecustomize at interpreter startup; its lazy client creation blocks for
# minutes when the chip is busy.  Tests run on the virtual CPU mesh only, so
# drop that backend factory before any jax backend is initialized.
import jax  # noqa: E402

try:  # pragma: no cover - environment-specific
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

# Free compiled executables between test modules: the XLA:CPU runtime on
# this image becomes unstable after many hundred compilations in one
# process (intermittent segfaults in backend_compile_and_load / aborts in
# executable.serialize, always late in a long run; every test passes in a
# fresh process).  Dropping the executable caches per module keeps the
# process young.  scripts/run_tests.sh (one process per file) is the
# belt-and-braces runner.
import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 (ROADMAP verify) runs `-m 'not slow'` on a small CPU box
    # where XLA compiles dominate: tests whose adapt/SPMD programs take
    # minutes to compile are marked slow and covered by the per-file
    # tier-2 runner (scripts/run_tests.sh) instead
    config.addinivalue_line(
        "markers", "slow: heavy XLA compile; excluded from the tier-1 gate")


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    yield
    jax.clear_caches()


if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")))
