"""Benchmark: Mtets remeshed/sec/chip on the real device.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: structured cube with a planar-shock isotropic size map (the
aniso-torus CI analogue of the reference matrix,
cmake/testing/pmmg_tests.cmake:25-38), adapted by repeated jitted cycles
(split/collapse/swap/smooth waves).  Throughput = live tets examined per
wall-second, after one warm-up cycle (compile excluded).

``vs_baseline``: the reference publishes no numbers (BASELINE.md), and a
measured in-image baseline is IMPOSSIBLE: ParMmg hard-requires MPI and
METIS and builds Mmg via cmake download — none of mpicc/mpi.h/metis.h
exist in this image and egress is zero (verified 2026-07-30; see
BASELINE.md "calibration basis").  The 0.4 Mtets/s figure is therefore a
documented calibration, not a guess: sequential Mmg3d-class remeshers
process ~40-60k tets/s/core for quality-driven isotropic adaptation on
~3 GHz x86 (the rate class reported across the Mmg/tet-remeshing
literature and consistent with Mmg CI runtimes), and the ParMmg
companion paper (Cirrottola & Froehly, inria hal-02386837 — cited from
README.md:97-99) reports near-linear strong scaling at 8 ranks for the
remesh phase; 8 ranks x 50k tets/s x ~0.85-0.9 efficiency ~= 0.34-0.45
-> 0.4 chosen as the round midpoint, deliberately on the high side so
``vs_baseline`` never flatters us.  North star (BASELINE.json): >=5x
that at equal min quality.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# calibrated 8-rank CPU ParMmg estimate — see module docstring + BASELINE.md
BASELINE_MTETS_PER_SEC = 0.4


def _ensure_reachable_backend(probe_timeout_s: int = 240) -> None:
    """The axon TPU-tunnel backend can block indefinitely in client
    creation when the chip is unreachable.  Probe it in a subprocess with
    a timeout; on failure fall back to the CPU backend so the benchmark
    always reports a number (device recorded in the JSON extras)."""
    import subprocess
    import sys
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return
    try:
        subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=probe_timeout_s, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        return                      # accelerator reachable
    except Exception as e:
        print(f"bench: accelerator probe failed ({e!r}); "
              "falling back to CPU", file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    # persistent compile cache: the adapt-cycle graph takes minutes to
    # compile cold; cached executables make repeated bench runs start
    # fast.  Shared wiring with the CLI and the scale drivers
    # (utils/compilecache) — env set AFTER backend selection so the
    # CPU-fallback path stays uncached (set_cache_env declines on
    # JAX_PLATFORMS=cpu: the XLA:CPU AOT cache is unreliable on this
    # image), config pushed after jax import.
    from parmmg_tpu.utils.compilecache import (enable_persistent_cache,
                                               ledger_snapshot)
    _ensure_reachable_backend()
    import jax
    import jax.numpy as jnp
    enable_persistent_cache()

    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.active import adapt_cycles_auto
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.ops.quality import tet_quality
    from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric

    n = int(os.environ.get("BENCH_N", "16"))          # 6*n^3 tets
    cycles = int(os.environ.get("BENCH_CYCLES", "9"))
    block = int(os.environ.get("BENCH_BLOCK", "9"))   # fused cycles/dispatch
    bdiv = int(os.environ.get("BENCH_BUDGET_DIV", "8"))  # wave top-K div
    cap = int(os.environ.get("BENCH_CAP", "8"))       # capacity factor

    vert, tet = cube_mesh(n)
    # capacity: midpoint bisection against LLONG=sqrt(2)/LSHRT=1/sqrt(2)
    # equilibrates with edges at ~0.7-1.0 of target, i.e. ~2-2.5x the
    # ideal-tet count — ~6.3x the initial tets on this fixture.  A
    # capacity-saturated mesh capacity-drops residual split winners
    # every cycle (overflow flag permanently set), which both truncates
    # the workload and vetoes the worklist fast path
    mesh = make_mesh(vert, tet, capP=cap * len(vert), capT=cap * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)

    # block schedule: global cycle indices keep the swap cadence identical
    # to the unfused host driver (swap every 3rd global cycle)
    warm_cycles = 2 * block
    sched = []
    b = 0
    while b < cycles:
        nc = min(block, cycles - b)
        sched.append((b, nc, (warm_cycles + b) % 3))
        b += nc

    # warm-up: TWO blocks.  The first compiles for the host-staged input
    # layout; its outputs are device arrays with a different layout, so
    # the very next call triggers a SECOND compile — running it here (not
    # in the timed loop) is what kills the consistent ~170s first-block
    # artifact.  Then warm every other distinct flavor by EXECUTING it on
    # a copy of the state (AOT .lower().compile() would not populate the
    # jit dispatch cache).  The auto block (ops/active.py) carries the
    # worklist state (dirty, okflag); each cycle inside runs
    # active-scoped when the worklist is valid and fits — the same
    # program the production driver dispatches.
    def _flags(nc, off):
        return tuple((c + off) % 3 == 2 for c in range(nc))

    dirty = jnp.zeros(mesh.capP, bool)
    okflag = jnp.asarray(False)
    m1, k1, dirty, okflag, wcnt = adapt_cycles_auto(
        mesh, met, dirty, okflag, jnp.asarray(0, jnp.int32),
        swap_flags=_flags(block, 0), budget_div=bdiv)
    jax.block_until_ready(wcnt)
    m1, k1, dirty, okflag, wcnt = adapt_cycles_auto(
        m1, k1, dirty, okflag, jnp.asarray(block, jnp.int32),
        swap_flags=_flags(block, block % 3), budget_div=bdiv)
    jax.block_until_ready(wcnt)
    for nc, off in sorted({(nc, off) for _, nc, off in sched}
                          - {(block, 0)}):
        mc = jax.tree.map(jnp.copy, m1)
        kc = jnp.copy(k1)
        dc = jnp.copy(dirty)
        _, _, _, _, c = adapt_cycles_auto(
            mc, kc, dc, okflag, jnp.asarray(0, jnp.int32),
            swap_flags=_flags(nc, off), budget_div=bdiv)
        jax.block_until_ready(c)

    # timed loop: cycles run in fused blocks of `block` (one dispatch +
    # ONE counter pull per block — on the tunneled chip every dispatch
    # pays a transport round trip).  Blocks stalling > 3x the median
    # (transient transport contention) are dropped from the throughput.
    ntet0 = int(np.asarray(wcnt)[-1][5])          # live tets after warm-up
    m, k = m1, k1
    live, times = [], []
    prev_live = ntet0
    narrow_cycles = 0
    for b, nc, off in sched:
        t0 = time.perf_counter()
        m, k, dirty, okflag, counts = adapt_cycles_auto(
            m, k, dirty, okflag,
            jnp.asarray(warm_cycles + b, jnp.int32),
            swap_flags=_flags(nc, off), budget_div=bdiv)
        cs = np.asarray(counts)                   # blocks on this block
        times.append(time.perf_counter() - t0)
        narrow_cycles += int(cs[:, 7].sum())
        if os.environ.get("BENCH_DEBUG", "") == "1":
            for r in cs:
                nact = int(r[8]) if len(r) > 8 else -1
                oki = int(r[9]) if len(r) > 9 else -1
                print(f"bench:   cycle counts split={int(r[0]):6d} "
                      f"col={int(r[1]):6d} swap={int(r[2]):6d} "
                      f"move={int(r[3]):6d} ovf={int(r[4])} "
                      f"live={int(r[5]):6d} "
                      f"defer={int(r[6])} narrow={int(r[7])} "
                      f"nact={nact} ok={oki}", file=sys.stderr)
        # tets examined this block = sum over cycles of live-at-entry
        entries = [prev_live] + [int(r[5]) for r in cs[:-1]]
        live.append(int(np.sum(entries)))
        prev_live = int(cs[-1][5])
    # The tunneled chip intermittently stalls a dispatch for tens of
    # seconds on external contention, which would corrupt a sum-based
    # number arbitrarily badly.  Steady-state throughput is therefore the
    # MEDIAN per-block rate — robust to a stalled block without the
    # upward bias of a max; the sum-based rate is reported alongside for
    # transparency.
    rates = [lv / t for lv, t in zip(live, times)]
    mtets_per_sec = float(np.median(rates)) / 1e6
    mtets_sum = float(np.sum(live)) / float(np.sum(times)) / 1e6
    if min(times) * 3 < max(times):
        print(f"bench: block times {['%.2f' % t for t in times]}s spread "
              ">3x (transport stalls); reporting median block rate",
              file=sys.stderr)

    # bad-element polish + sequential tail repair before the quality
    # report — the SAME untimed quality tail the production driver runs
    # after the sizing loop (adapt_mesh polish + driver._finish_run
    # repair); throughput is measured on the steady-state sizing cycles
    # only, quality is reported for the full pipeline's output
    from parmmg_tpu.ops.adapt import sliver_polish
    from parmmg_tpu.ops.repair import repair_mesh

    def _quality_tail(mm, kk, wave0, use_met=False):
        for w in range(6):
            mm, pc = sliver_polish(mm, kk,
                                   jnp.asarray(wave0 + w, jnp.int32))
            pcn = np.asarray(pc)
            if int(pcn[0]) == 0 and int(pcn[1]) == 0:
                break
        mm, _ = repair_mesh(mm, kk)
        # iso reports Euclidean quality (the rounds-1..3 protocol, the
        # MMG5_caltet_iso convention); ANISO reports METRIC quality —
        # in an anisotropic metric the flattened elements are the
        # target shape and their Euclidean quality is meaningless
        qq = np.asarray(tet_quality(mm, kk) if use_met
                        else tet_quality(mm))
        tmm = np.asarray(mm.tmask)
        return (mm, int(tmm.sum()),
                float(qq[tmm].min()) if tmm.any() else 0.0,
                float(qq[tmm].mean()) if tmm.any() else 0.0)

    m, ntets_final, qmin, qmean = _quality_tail(m, k, 100)

    # ---- aniso datapoint (reference CI's torus-aniso analogue) ----------
    # a smaller planar-shock TENSOR-metric workload, same protocol in
    # miniature: warm one block, time the next ones.  Off by default
    # only via BENCH_ANISO=0.
    aniso = None
    if os.environ.get("BENCH_ANISO", "1") == "1":
        from parmmg_tpu.utils.fixtures import analytic_ani_metric
        n_a = int(os.environ.get("BENCH_ANISO_N", "12"))
        vert_a, tet_a = cube_mesh(n_a)
        mesh_a = make_mesh(vert_a, tet_a, capP=3 * len(vert_a),
                           capT=3 * len(tet_a))
        mesh_a = analyze_mesh(mesh_a).mesh
        ha = analytic_ani_metric(vert_a, "shock", h=1.5 / n_a)
        met_a = jnp.zeros((mesh_a.capP, 6), mesh_a.vert.dtype)
        met_a = met_a.at[: len(ha)].set(jnp.asarray(ha))
        met_a = met_a.at[len(ha):, 0].set(1.0).at[len(ha):, 3].set(
            1.0).at[len(ha):, 5].set(1.0)
        da = jnp.zeros(mesh_a.capP, bool)
        oka = jnp.asarray(False)
        ma, ka_ = mesh_a, met_a
        ma, ka_, da, oka, ca = adapt_cycles_auto(
            ma, ka_, da, oka, jnp.asarray(0, jnp.int32),
            swap_flags=_flags(block, 0), budget_div=bdiv)
        jax.block_until_ready(ca)
        prev_a = int(np.asarray(ca)[-1][5])
        lv_a, tm_a = 0, 0.0
        for b in range(2):
            t0 = time.perf_counter()
            ma, ka_, da, oka, ca = adapt_cycles_auto(
                ma, ka_, da, oka,
                jnp.asarray(block * (1 + b), jnp.int32),
                swap_flags=_flags(block, (block * (1 + b)) % 3),
                budget_div=bdiv)
            cs_a = np.asarray(ca)
            tm_a += time.perf_counter() - t0
            lv_a += prev_a + int(np.sum(cs_a[:-1, 5]))
            prev_a = int(cs_a[-1, 5])
        ma, nta, qmin_a, qmean_a = _quality_tail(ma, ka_, 200,
                                                 use_met=True)
        aniso = {"mtets_per_sec": round(lv_a / tm_a / 1e6, 4),
                 "ntets_final": nta,
                 "qmin": round(qmin_a, 4),
                 "qmean": round(qmean_a, 4)}

    # ---- grouped-analysis extraction probe (ROADMAP 4a, closed) ---------
    # dist_analysis_grouped now extracts the [12*capT] record table ONCE
    # per group per refresh (the PR-12 fusion: phase 1 carries the
    # verdict bits across the map, the tail re-derives only cheap
    # endpoint gathers).  extract1x_s = measured seconds of ONE
    # extraction at the bench mesh's shape — i.e. the per-group
    # per-refresh cost the fusion REMOVED (before = 2x this per group,
    # after = 1x).  Replaces the retired extract2x_s decision input.
    extract1x_s = None
    if os.environ.get("BENCH_EXTRACT2X", "1") == "1":   # knob name kept
        try:
            from parmmg_tpu.parallel.analysis_dev import \
                extract_probe_seconds
            glo_p = jnp.arange(m.vert.shape[0], dtype=jnp.int32)
            extract1x_s = round(extract_probe_seconds(m, glo_p), 5)
        except Exception as e:          # probe must never kill the bench
            print(f"bench: extract1x probe failed ({e!r})",
                  file=sys.stderr)

    # ---- quiet-group scheduler datapoint (opt-in: BENCH_GROUPED=1) ------
    # the device-resident quiet-mask before/after (PR 12): the SAME
    # grouped shock pass runs UNCHUNKED twice in one process — mask off
    # (every lax.map slot computes, the pre-PR-12 steady state: at
    # chunk 0 host compaction cannot skip anything) then mask on
    # (lax.cond identity for quiet slots) — through the same compiled
    # program, and the artifact records both steady-state seconds/cycle
    # plus a byte-compare of the merged outputs (extra.parity_ok).
    # Opt-in because the group block is a fresh compile family on a
    # cold cache; scripts/scale_big.py carries the same counters on the
    # real grouped workload.
    group_sched = None
    parity_ok = None
    incr_topo = None
    pallas_sort = None
    if os.environ.get("BENCH_GROUPED", "0") == "1":
        from parmmg_tpu.core.mesh import MESH_FIELDS
        from parmmg_tpu.ops.adapt import AdaptStats
        from parmmg_tpu.parallel.groups import grouped_adapt_pass
        n_g = int(os.environ.get("BENCH_GROUPED_N", "6"))
        ngr = 3
        cycles_g = int(os.environ.get("BENCH_GROUPED_CYCLES", "12"))
        prev_env = {k: os.environ.get(k)
                    for k in ("PARMMG_GROUP_CHUNK", "PARMMG_DEVICE_MASK",
                              "PARMMG_INCR_TOPO", "PARMMG_PALLAS_SORT")}
        os.environ["PARMMG_GROUP_CHUNK"] = "0"
        # x-slab groups on the shock metric, with the far field CLAMPED
        # into the metric dead band (h <= 1.3/n: edges stay inside
        # (LSHRT, LLONG), no far-field coarsening) — the CFD-style
        # shock-capture scenario: refine the front into an
        # already-adequate background mesh.  The refinement band
        # (x=0.5) lives in the middle slab, so the outer slabs hit
        # their fixed point within the first swap-inclusive block —
        # the quiet-group population whose wave math the device mask
        # elides.  (The unclamped bench metric coarsens the far field
        # ~2-3x, a collapse trickle that keeps every group active to
        # the last cycle; a morton split additionally puts the shock
        # in every group — neither layout ever shows the steady state
        # the scheduler exists for.)
        vg, tg = cube_mesh(n_g)
        cent_g = vg[tg].mean(axis=1)
        part_g = np.minimum((cent_g[:, 0] * ngr).astype(np.int64),
                            ngr - 1)

        def run_grouped(mask: str, reps: int = 1):
            # the pass is deterministic from its input: repeat runs
            # produce identical bytes, so min-of-reps is a pure timing
            # de-noiser (the 1-core host shows ~10% run-to-run spread)
            os.environ["PARMMG_DEVICE_MASK"] = mask
            best = None
            for _ in range(max(1, reps)):
                mg = make_mesh(vg, tg, capP=4 * len(vg),
                               capT=4 * len(tg))
                mg = analyze_mesh(mg).mesh
                hg = np.minimum(
                    analytic_iso_metric(vg, "shock", h=1.5 / n_g),
                    1.3 / n_g)
                kg = jnp.zeros(mg.capP, mg.vert.dtype).at[
                    : len(hg)].set(jnp.asarray(hg, mg.vert.dtype)).at[
                    len(hg):].set(1.0)
                st_g = AdaptStats()
                t0 = time.perf_counter()
                out_g, met_g, _ = grouped_adapt_pass(mg, kg, ngr,
                                                     cycles=cycles_g,
                                                     part=part_g,
                                                     stats=st_g)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return out_g, met_g, st_g, best
        try:
            run_grouped("0")                      # compile warm-up
            ref_g, kref_g, st0, t_off = run_grouped("0", reps=3)
            chk_g, kchk_g, st1, t_on = run_grouped("1", reps=3)
            parity_ok = bool(
                all((np.asarray(getattr(ref_g, f))
                     == np.asarray(getattr(chk_g, f))).all()
                    for f in MESH_FIELDS)
                and (np.asarray(kref_g) == np.asarray(kchk_g)).all())
            # one CHUNKED mask-on run: the double-buffered pipeline's
            # measured segment timings feed the chunk auto-tune's
            # overhead calibration (sched.calibrate_dispatch_overhead,
            # ROADMAP 1b) — recorded so the artifact carries a real
            # calibrated value, not just the wiring
            os.environ["PARMMG_GROUP_CHUNK"] = "2"
            _, _, st2, _ = run_grouped("1")
            os.environ["PARMMG_GROUP_CHUNK"] = "0"
            # incremental-topology A/B (PARMMG_INCR_TOPO, ops/topo_incr):
            # the SAME mask-on pass re-runs with the knob on — a traced
            # scalar, so it rides the compiled programs already warmed
            # above (ledger_check.py --diff shows zero groups.* growth).
            # The knob-off arm IS the mask-on run (t_on); outputs AND op
            # counters must be bit-identical (exactness by construction:
            # the dirty band re-keys exactly the slots whose keys could
            # have changed, overflow falls back to the full rebuild)
            os.environ["PARMMG_INCR_TOPO"] = "1"
            inc_g, kinc_g, st3, t_inc = run_grouped("1", reps=3)
            os.environ.pop("PARMMG_INCR_TOPO", None)
            incr_parity = bool(
                all((np.asarray(getattr(chk_g, f))
                     == np.asarray(getattr(inc_g, f))).all()
                    for f in MESH_FIELDS)
                and (np.asarray(kchk_g) == np.asarray(kinc_g)).all()
                and (st3.nsplit, st3.ncollapse, st3.nswap, st3.nmoved)
                == (st1.nsplit, st1.ncollapse, st1.nswap, st1.nmoved))
            incr_topo = {
                "off_s_per_cycle": round(t_on / max(st1.cycles, 1), 4),
                "on_s_per_cycle": round(t_inc / max(st3.cycles, 1), 4),
                "speedup": round(t_on / t_inc, 3),
                "parity_ok": incr_parity,
                # per-cycle dirty-tet counts (band occupancy the merge
                # absorbed; > band width = full-rebuild fallback cycles)
                "dirty_per_cycle":
                    st3.sched_extra.get("incr_dirty_per_cycle", []),
            }
            # Pallas sort-engine A/B (PARMMG_PALLAS_SORT, ISSUE 20): the
            # SAME mask-on pass re-runs with the knob forced on.  On a
            # CPU backend the dispatcher still lowers only the jnp
            # reference (platform_dependent picks at trace time), so the
            # numbers document the reference path honestly and
            # sites_pallas says which sites WOULD dispatch the kernels;
            # the TPU claim rides the next chip session.  Outputs and op
            # counters must stay bit-identical either way.
            from parmmg_tpu.ops.pallas_kernels import pallas_sort_sites
            os.environ["PARMMG_PALLAS_SORT"] = "1"
            srt_g, ksrt_g, st4, t_srt = run_grouped("1", reps=3)
            sort_sites = pallas_sort_sites()
            os.environ.pop("PARMMG_PALLAS_SORT", None)
            sort_parity = bool(
                all((np.asarray(getattr(chk_g, f))
                     == np.asarray(getattr(srt_g, f))).all()
                    for f in MESH_FIELDS)
                and (np.asarray(kchk_g) == np.asarray(ksrt_g)).all()
                and (st4.nsplit, st4.ncollapse, st4.nswap, st4.nmoved)
                == (st1.nsplit, st1.ncollapse, st1.nswap, st1.nmoved))
            pallas_sort = {
                "off_s_per_cycle": round(t_on / max(st1.cycles, 1), 4),
                "on_s_per_cycle": round(t_srt / max(st4.cycles, 1), 4),
                "speedup": round(t_on / t_srt, 3),
                "parity_ok": sort_parity,
                # sort sites that dispatched the Pallas kernels on THIS
                # backend (empty off-TPU: the knob-on arm lowered the
                # bit-identical jnp reference)
                "sites_pallas": sort_sites,
            }
            group_sched = {
                "ngroups": ngr,
                "cycles": st1.cycles,
                "mask_off_adapt_s": round(t_off, 3),
                "mask_on_adapt_s": round(t_on, 3),
                "mask_off_s_per_cycle":
                    round(t_off / max(st0.cycles, 1), 4),
                "mask_on_s_per_cycle":
                    round(t_on / max(st1.cycles, 1), 4),
                "cond_skipped_rows":
                    st1.sched_extra.get("cond_skipped_rows", 0),
                "dispatches": st1.group_dispatches,
                "saved_dispatches": st1.group_dispatches_saved,
                "active_groups_per_block":
                    st1.sched_extra.get("active_groups_per_block", []),
                # measured on the chunked (chunk=2) pipeline run
                "chunk_overhead_units":
                    st2.sched_extra.get("chunk_overhead_units", []),
                "chunked_saved_dispatches": st2.group_dispatches_saved,
                "chunked_cond_skipped":
                    st2.sched_extra.get("cond_skipped_rows", 0),
                "parity_ok": parity_ok,
            }
        finally:
            for k, v in prev_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    # one-pass phase-timing capture (scripts/profile_adapt.py --json):
    # committed into the artifact so the next chip session can diff the
    # SAME phase names on a real device timeline
    profile_phases = None
    pp = os.environ.get("BENCH_PROFILE_JSON", "")
    if pp and os.path.exists(pp):
        with open(pp) as f:
            profile_phases = json.load(f)

    # ledger regression check against the previous round's artifact:
    # any entry point whose compiled-variant count GREW since the last
    # BENCH_r*.json is flagged in the JSON and on stderr (the bench-side
    # teeth of the compile governor; scripts/ledger_check.py --diff is
    # the standalone form of the same comparison)
    ledger = ledger_snapshot()
    regressions = _ledger_regressions_vs_previous(ledger)
    if regressions:
        print("bench: COMPILE-LEDGER VARIANT REGRESSIONS vs previous "
              "artifact:", file=sys.stderr)
        for r in regressions:
            print(f"bench:   {r}", file=sys.stderr)

    # canonical schema-versioned artifact (obs/artifact.py): the legacy
    # top-level keys stay put, the env/metrics/trace blocks ride along
    from parmmg_tpu.obs.artifact import make_artifact
    print(json.dumps(make_artifact(
        "BENCH",
        metric="adapt_cycle_throughput",
        value=round(mtets_per_sec, 4),
        unit="Mtets/sec/chip",
        vs_baseline=round(mtets_per_sec / BASELINE_MTETS_PER_SEC, 3),
        extra={"ntets_final": ntets_final, "qmin": round(qmin, 4),
               "qmean": round(qmean, 4), "cycles": cycles,
               "sum_rate": round(mtets_sum, 4),
               "narrow_cycles": narrow_cycles,
               "aniso": aniso,
               # single [12*capT] extraction cost (= the per-group
               # per-refresh saving of the PR-12 grouped-analysis
               # fusion; replaces the retired extract2x_s) + the
               # device-mask before/after datapoint (BENCH_GROUPED=1)
               "extract1x_s": extract1x_s,
               "group_sched": group_sched,
               "parity_ok": parity_ok,
               # incremental-topology A/B (BENCH_GROUPED=1): same-machine
               # s/cycle with PARMMG_INCR_TOPO off vs on + dirty-band
               # trajectory; outputs bit-identical (parity_ok)
               "incr_topo": incr_topo,
               # Pallas radix-sort engine A/B (BENCH_GROUPED=1):
               # same-machine s/cycle with PARMMG_PALLAS_SORT off vs on;
               # off-TPU both arms lower the same jnp reference program
               # (sites_pallas records where the kernels would land)
               "pallas_sort": pallas_sort,
               "profile_phases": profile_phases,
               "device": str(jax.devices()[0].platform),
               "fallback": os.environ.get(
                   "PARMMG_BENCH_FALLBACK", "") == "1",
               # compile-churn accounting (utils/compilecache): per
               # governed entry point {calls, variants, compiles,
               # compile_s} — a regression shows up as variants or
               # compiles growing with the cycle count
               "compile_ledger": ledger,
               "ledger_regressions": regressions,
               # free-form round context (BENCH_NOTES env) — e.g. a
               # runner-image change that shifts absolute times, with
               # the same-machine seed re-measurement for comparison
               "notes": os.environ.get("BENCH_NOTES") or None})))


def _ledger_regressions_vs_previous(ledger: dict) -> list[str]:
    """Compare this run's compile ledger against the NEWEST BENCH_r*.json
    next to this script (shared logic:
    utils.compilecache.regressions_vs_latest_artifact)."""
    from parmmg_tpu.utils.compilecache import regressions_vs_latest_artifact
    here = os.path.dirname(os.path.abspath(__file__))
    return regressions_vs_latest_artifact(here, "BENCH_r*.json", ledger)


_TRANSPORT_MARKERS = (
    # axon tunnel / RPC plumbing failures observed on this image; a
    # deterministic device-side failure (OOM, kernel assert, compile
    # error) matches none of these and must surface, not be retried or
    # silently re-run on CPU.
    # "remote_compile" does match a deterministic compile failure that
    # names the tunnel's compile RPC — accepted tradeoff: the endpoint's
    # known failure mode is dropping responses mid-read, and a CPU
    # fallback run is loudly tagged fallback=true in the JSON either way.
    "remote_compile", "tunnel", "connection", "socket", "unavailable",
    "deadline_exceeded", "deadline exceeded", "broken pipe",
    "reset by peer", "eof", "transport", "version mismatch",
    "failed_precondition: libtpu",
)


def _is_transport_error(e: Exception) -> bool:
    """Tunnel/device transport failures only — a deterministic code bug
    must surface, not be retried or silently re-run on CPU."""
    try:
        from jax.errors import JaxRuntimeError
    except Exception:  # pragma: no cover
        return False
    if not isinstance(e, JaxRuntimeError):
        return False
    msg = str(e).lower()
    return any(m in msg for m in _TRANSPORT_MARKERS)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:
        # the tunnel's remote_compile endpoint intermittently drops the
        # response mid-read; one in-process retry usually succeeds.  If
        # the device stays broken, re-exec on CPU so the benchmark still
        # reports a number (tagged device=cpu, fallback=true) instead of
        # crashing the round.
        if os.environ.get("JAX_PLATFORMS", "") == "cpu" \
                or not _is_transport_error(e):
            raise
        print(f"bench: device attempt failed ({type(e).__name__}: {e}); "
              "retrying once", file=sys.stderr)
        try:
            main()
        except Exception as e2:
            if not _is_transport_error(e2):
                raise
            print(f"bench: retry failed ({type(e2).__name__}); "
                  "re-executing on CPU backend", file=sys.stderr)
            env = dict(os.environ, JAX_PLATFORMS="cpu",
                       PARMMG_BENCH_FALLBACK="1")
            os.execvpe(sys.executable,
                       [sys.executable, os.path.abspath(__file__)], env)
