"""Does batching (vmap over groups) reduce scatter/gather/scan cost?

Measures the bench-critical primitives flat at [n] vs vmapped at
[G, n/G]: if TPU scatter cost is per-index (linear), the grouped form
changes nothing; if there is a big per-op serial component that batch
dims vectorize away, the S*G logical-shard composition is THE
throughput lever.  Also re-checks the suspicious 4us sort number at
several widths with a sum-dependency (argsort result fed through a
gather so DCE cannot drop the comparator work).

Run: python scripts/scatter_scaling.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp

K = int(os.environ.get("SS_REPS", "30"))
N = int(os.environ.get("SS_N", str(6 * 73728)))     # bench capE
NP_ = N // 6                                         # pool size


def timed(name, fn, *args):
    f = jax.jit(fn)
    r = f(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    r = f(*args)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / K * 1e3
    print(f"{name:34s} {dt:9.3f} ms/op")
    return dt


def loop(body):
    def fn(x):
        return jax.lax.fori_loop(0, K, body, x)
    return fn


def main():
    print(f"backend={jax.default_backend()} N={N} reps={K}")
    key = jax.random.PRNGKey(0)
    idx = jax.random.randint(key, (N,), 0, NP_, jnp.int32)
    vals = jax.random.uniform(key, (N,))

    # flat scatter-max (dup indices), the claim primitive
    timed("scatter_max flat", loop(
        lambda i, x: jnp.zeros(NP_, x.dtype).at[idx].max(x)[idx] + x), vals)

    for G in (8, 32):
        n_g = N // G
        np_g = NP_ // G
        idx_g = (idx[: G * n_g].reshape(G, n_g) % np_g).astype(jnp.int32)
        vals_g = vals[: G * n_g].reshape(G, n_g)

        def body_g(i, x, idx_g=idx_g, np_g=np_g):
            out = jax.vmap(
                lambda ix, xv: jnp.zeros(np_g, xv.dtype).at[ix].max(xv))(
                idx_g, x)
            return jnp.take_along_axis(out, idx_g, 1) + x
        timed(f"scatter_max vmap G={G}", loop(body_g), vals_g)

    # gather
    timed("gather flat", loop(
        lambda i, x: x[idx] + 0.5), vals)
    for G in (8,):
        n_g = N // G
        idx_g = (idx[: G * n_g].reshape(G, n_g) % n_g).astype(jnp.int32)
        vals_g = vals[: G * n_g].reshape(G, n_g)
        timed(f"gather vmap G={G}", loop(
            lambda i, x, ig=idx_g: jnp.take_along_axis(x, ig, 1) + 0.5),
            vals_g)

    # associative scan
    timed("assoc_scan flat", loop(
        lambda i, x: jax.lax.associative_scan(jnp.maximum, x) * 0.999),
        vals)
    timed("assoc_scan vmap G=8", loop(
        lambda i, x: jax.lax.associative_scan(
            jnp.maximum, x, axis=1) * 0.999),
        vals.reshape(8, N // 8))
    # cumsum (used for offsets)
    timed("cumsum flat", loop(
        lambda i, x: jnp.cumsum(x) * 0.999), vals)

    # sort with un-DCE-able dependency: gather by the returned permutation
    for n in (N, N // 8):
        v = vals[:n]
        timed(f"argsort+gather n={n}", loop(
            lambda i, x: x[jnp.argsort(x)][::-1]), v)
    timed("argsort+gather vmap 8x", loop(
        lambda i, x: jnp.take_along_axis(x, jnp.argsort(x, axis=1), 1)
        [:, ::-1]), vals.reshape(8, N // 8))

    # top_k at bench budget
    timed("top_k K=N/48 flat", loop(
        lambda i, x: x.at[jax.lax.top_k(x, N // 48)[1]].add(1e-7)), vals)


if __name__ == "__main__":
    main()
