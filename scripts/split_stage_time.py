"""Cumulative per-stage timing inside split_wave / collapse_wave.

Each timed program replays the wave's pipeline UP TO stage k and returns
a value data-dependent on everything computed so far (so XLA cannot DCE
earlier stages); differencing consecutive timings attributes cost to
each stage.  Mirrors the ops/split.py + ops/collapse.py structure as of
round 3 — a diagnostic, not a contract.

Run: python scripts/split_stage_time.py [N]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp
import numpy as np

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.core.constants import (IARE, LLONG, MG_REQ, MG_PARBDY,
                                       QUAL_FLOOR, EPSD, LSHRT)
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.edges import (unique_edges, edge_lengths,
                                  claim_channels, scatter_argmax2,
                                  wave_budget, NEG_INF, PRI_MIN)
from parmmg_tpu.ops.quality import quality_from_points
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric

K = int(os.environ.get("ST_REPS", "10"))
_IARE_J = jnp.asarray(IARE)


def timed(name, fn, *args):
    f = jax.jit(fn)
    r = f(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(K):
        r = f(*args)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / K * 1e3
    print(f"  {name:30s} {dt:9.2f} ms cumulative")
    return dt


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    capT, capP = mesh.capT, mesh.capP
    print(f"N={n} capT={capT} device={jax.default_backend()}")

    # ---- split stages ----------------------------------------------------
    def s_table(mesh, met):
        et = unique_edges(mesh)
        return et.edge_id.sum() + et.nshell.sum() + et.etag.sum().astype(
            jnp.int32) + et.shell_rank.sum() + et.shell3.sum()

    def s_lens(mesh, met):
        et = unique_edges(mesh)
        lens = edge_lengths(mesh, et, met)
        return s_table(mesh, met) + lens.sum().astype(jnp.int32)

    def _prep(mesh, met):
        et = unique_edges(mesh)
        lens = edge_lengths(mesh, et, met)
        va = jnp.clip(et.ev[:, 0], 0, capP - 1)
        vb = jnp.clip(et.ev[:, 1], 0, capP - 1)
        frozen = (et.etag & (MG_REQ | MG_PARBDY)) != 0
        cand = et.emask & (lens > LLONG) & ~frozen
        return et, lens, va, vb, cand

    def s_nom(mesh, met):
        et, lens, va, vb, cand = _prep(mesh, met)
        s, t = claim_channels(lens, cand)
        tes = jnp.where(mesh.tmask[:, None], s[et.edge_id], NEG_INF)
        best_s = jnp.max(tes, axis=1)
        at_best = (tes == best_s[:, None]) & jnp.isfinite(best_s)[:, None]
        tet_t = jnp.where(at_best, t[et.edge_id], PRI_MIN)
        best_t = jnp.max(tet_t, axis=1)
        nominate = at_best & (tet_t == best_t[:, None])
        return s_lens(mesh, met) + nominate.sum().astype(jnp.int32)

    def _nom(mesh, met):
        et, lens, va, vb, cand = _prep(mesh, met)
        s, t = claim_channels(lens, cand)
        tes = jnp.where(mesh.tmask[:, None], s[et.edge_id], NEG_INF)
        best_s = jnp.max(tes, axis=1)
        at_best = (tes == best_s[:, None]) & jnp.isfinite(best_s)[:, None]
        tet_t = jnp.where(at_best, t[et.edge_id], PRI_MIN)
        best_t = jnp.max(tet_t, axis=1)
        nominate = at_best & (tet_t == best_t[:, None])
        return et, lens, va, vb, cand, nominate

    def s_veto(mesh, met):
        et, lens, va, vb, cand, nominate = _nom(mesh, met)
        ar0 = jnp.arange(capT)
        loc_n = jnp.argmax(nominate, axis=1)
        e_n = et.edge_id[ar0, loc_n]
        i_n = _IARE_J[loc_n, 0]
        j_n = _IARE_J[loc_n, 1]
        mid_n = 0.5 * (mesh.vert[va[e_n]] + mesh.vert[vb[e_n]])
        pts = mesh.vert[mesh.tet]
        q1 = quality_from_points(pts.at[ar0, j_n].set(mid_n))
        q2 = quality_from_points(pts.at[ar0, i_n].set(mid_n))
        nominate = nominate & ((q1 > QUAL_FLOOR) &
                               (q2 > QUAL_FLOOR))[:, None]
        return s_nom(mesh, met) + nominate.sum().astype(jnp.int32)

    def _win(mesh, met):
        et, lens, va, vb, cand, nominate = _nom(mesh, met)
        ar0 = jnp.arange(capT)
        loc_n = jnp.argmax(nominate, axis=1)
        e_n = et.edge_id[ar0, loc_n]
        i_n = _IARE_J[loc_n, 0]
        j_n = _IARE_J[loc_n, 1]
        mid_n = 0.5 * (mesh.vert[va[e_n]] + mesh.vert[vb[e_n]])
        pts = mesh.vert[mesh.tet]
        q1 = quality_from_points(pts.at[ar0, j_n].set(mid_n))
        q2 = quality_from_points(pts.at[ar0, i_n].set(mid_n))
        nominate = nominate & ((q1 > QUAL_FLOOR) &
                               (q2 > QUAL_FLOOR))[:, None]
        capE = et.ev.shape[0]
        nom_count = jnp.zeros(capE, jnp.int32).at[
            et.edge_id.reshape(-1)].add(
            nominate.reshape(-1).astype(jnp.int32))
        win = cand & (nom_count == et.nshell) & (et.nshell > 0)
        return et, lens, win

    def s_win(mesh, met):
        _, _, win = _win(mesh, met)
        return s_veto(mesh, met) + win.sum().astype(jnp.int32)

    def s_budget(mesh, met):
        et, lens, win = _win(mesh, met)
        capE = et.ev.shape[0]
        win_i = win.astype(jnp.int32)
        new_off = jnp.cumsum(win_i) - win_i
        nwin = jnp.sum(win_i)
        fits_p = new_off < (capP - mesh.npoin)
        shell_add = jnp.where(win & fits_p, et.nshell, 0)
        tet_off = jnp.cumsum(shell_add) - shell_add
        fits_t = (tet_off + shell_add) <= (capT - mesh.nelem)
        win_cap = win & fits_p & fits_t
        KW = min(wave_budget(capT, 8), capE)
        KH = min(2 * wave_budget(capT, 8), capT)
        bord = jnp.argsort(jnp.where(win_cap, -lens, jnp.inf))
        win_srt = win_cap[bord]
        off_srt = jnp.cumsum(win_srt.astype(jnp.int32)) - win_srt
        sh_srt = jnp.where(win_srt & (off_srt < KW), et.nshell[bord], 0)
        toff_srt = jnp.cumsum(sh_srt) - sh_srt
        ok_srt = win_srt & (off_srt < KW) & ((toff_srt + sh_srt) <= KH)
        win2 = jnp.zeros_like(win_cap).at[bord].set(ok_srt,
                                                    unique_indices=True)
        win_i2 = win2.astype(jnp.int32)
        new_off2 = jnp.cumsum(win_i2) - win_i2
        shell_add2 = jnp.where(win2, et.nshell, 0)
        tet_off2 = jnp.cumsum(shell_add2) - shell_add2
        return (s_win(mesh, met) + new_off2.sum() + tet_off2.sum()
                + win2.sum().astype(jnp.int32))

    print("split_wave stages:")
    timed("table", s_table, mesh, met)
    timed("+lengths", s_lens, mesh, met)
    timed("+nomination", s_nom, mesh, met)
    timed("+degeneracy veto", s_veto, mesh, met)
    timed("+whole-shell win", s_win, mesh, met)
    timed("+budget/offsets", s_budget, mesh, met)
    from parmmg_tpu.ops.split import split_wave
    timed("full split_wave", lambda m, k: split_wave(m, k).mesh.tet.sum(),
          mesh, met)

    # ---- collapse stages -------------------------------------------------
    def c_prep(mesh, met):
        et = unique_edges(mesh)
        lens = edge_lengths(mesh, et, met)
        va_f = jnp.clip(et.ev[:, 0], 0, capP - 1)
        vb_f = jnp.clip(et.ev[:, 1], 0, capP - 1)
        frozen = (et.etag & (MG_REQ | MG_PARBDY)) != 0
        short = et.emask & (lens < LSHRT) & ~frozen
        from parmmg_tpu.ops.collapse import _removable
        ta_f, tb_f = mesh.vtag[va_f], mesh.vtag[vb_f]
        rem_b = _removable(tb_f, ta_f, et.etag)
        rem_a = _removable(ta_f, tb_f, et.etag)
        pre = short & (rem_a | rem_b)
        return et, lens, va_f, vb_f, pre, rem_b

    def c_sel(mesh, met):
        et, lens, va_f, vb_f, pre, rem_b = c_prep(mesh, met)
        Kb = min(et.ev.shape[0], wave_budget(capT, 8))
        sel = jnp.argsort(jnp.where(pre, lens, jnp.inf))[:Kb]
        return (sel.sum() + pre.sum().astype(jnp.int32))

    def _c_top(mesh, met):
        et, lens, va_f, vb_f, pre, rem_b = c_prep(mesh, met)
        Kb = min(et.ev.shape[0], wave_budget(capT, 8))
        sel = jnp.argsort(jnp.where(pre, lens, jnp.inf))[:Kb]
        lens_c = lens[sel]
        va = va_f[sel]
        vb = vb_f[sel]
        cand = pre[sel]
        del_b = rem_b[sel]
        rm = jnp.where(del_b, vb, va)
        kp = jnp.where(del_b, va, vb)
        s, t = claim_channels(-lens_c, cand)
        is_top, v_s, v_t = scatter_argmax2(rm, s, t, cand, capP)
        kept_of = jnp.zeros(capP, jnp.int32).at[
            jnp.where(is_top, rm, capP)].set(kp, mode="drop",
                                             unique_indices=True)
        return v_s, v_t, kept_of, is_top

    def c_top(mesh, met):
        v_s, v_t, kept_of, is_top = _c_top(mesh, met)
        return (c_sel(mesh, met) + kept_of.sum()
                + is_top.sum().astype(jnp.int32))

    def c_valid(mesh, met):
        v_s, v_t, kept_of, is_top = _c_top(mesh, met)
        tv = mesh.tet
        vpos = mesh.vert[tv]
        vs_c = v_s[tv]
        has_c = jnp.isfinite(vs_c)
        kept = kept_of[tv]
        kept_pos = mesh.vert[kept]
        contains_kept = jnp.zeros((capT, 4), bool)
        for k in range(4):
            hit = jnp.zeros((capT,), bool)
            for j in range(4):
                hit = hit | ((tv[:, j] == kept[:, k]) & (j != k))
            contains_kept = contains_kept.at[:, k].set(hit)
        from parmmg_tpu.core.constants import IDIR
        from parmmg_tpu.ops.quality import edge_length_iso
        idx_act = []
        bad_all = []
        for k in range(4):
            active = has_c[:, k] & mesh.tmask & ~contains_kept[:, k]
            p = vpos.at[:, k].set(kept_pos[:, k])
            d1 = p[:, 1] - p[:, 0]
            d2 = p[:, 2] - p[:, 0]
            d3 = p[:, 3] - p[:, 0]
            vol = jnp.einsum("ti,ti->t", d1, jnp.cross(d2, d3)) / 6.0
            bad = vol <= EPSD
            for f in range(4):
                if k == f:
                    continue
                idx = IDIR[f]
                n_old = jnp.cross(vpos[:, idx[1]] - vpos[:, idx[0]],
                                  vpos[:, idx[2]] - vpos[:, idx[0]])
                n_new = jnp.cross(p[:, idx[1]] - p[:, idx[0]],
                                  p[:, idx[2]] - p[:, idx[0]])
                isb = (mesh.ftag[:, f] & 2) != 0
                flip = jnp.sum(n_old * n_new, -1) <= 0
                bad = bad | (isb & flip)
            for j in range(4):
                if j == k:
                    continue
                lnew = edge_length_iso(kept_pos[:, k], p[:, j],
                                       met[kept[:, k]], met[tv[:, j]])
                bad = bad | (lnew > LLONG)
            idx_act.append(jnp.where(active, tv[:, k], capP))
            bad_all.append(bad)
        idx_act = jnp.concatenate(idx_act)
        geombad = jnp.zeros(capP + 1, bool).at[idx_act].max(
            jnp.concatenate(bad_all), mode="drop")[:capP]
        return c_top(mesh, met) + geombad.sum().astype(jnp.int32)

    def c_ballq(mesh, met):
        v_s, v_t, kept_of, is_top = _c_top(mesh, met)
        tv = mesh.tet
        vpos = mesh.vert[tv]
        kept = kept_of[tv]
        kept_pos = mesh.vert[kept]
        has_c = jnp.isfinite(v_s[tv])
        q_ball = quality_from_points(vpos)
        idx4c = jnp.concatenate(
            [jnp.where(mesh.tmask, tv[:, k], capP) for k in range(4)])
        ballq_old = jnp.full(capP + 1, jnp.inf).at[idx4c].min(
            jnp.tile(jnp.where(mesh.tmask, q_ball, jnp.inf), 4),
            mode="drop")
        variants = jnp.concatenate(
            [vpos.at[:, k].set(kept_pos[:, k]) for k in range(4)])
        qv = quality_from_points(variants)
        act4 = jnp.concatenate([has_c[:, k] & mesh.tmask
                                for k in range(4)])
        idx_act = jnp.concatenate(
            [jnp.where(has_c[:, k] & mesh.tmask, tv[:, k], capP)
             for k in range(4)])
        ballq_new = jnp.full(capP + 1, jnp.inf).at[idx_act].min(
            jnp.where(act4, qv, jnp.inf), mode="drop")
        return (c_valid(mesh, met) +
                (ballq_new[:capP] > 0.3 * ballq_old[:capP]).sum()
                .astype(jnp.int32))

    print("collapse_wave stages:")
    timed("prep+candidacy", lambda m, k: c_prep(m, k)[4].sum()
          .astype(jnp.int32), mesh, met)
    timed("+topK sel", c_sel, mesh, met)
    timed("+top-remover claims", c_top, mesh, met)
    timed("+tet validity", c_valid, mesh, met)
    timed("+ball quality", c_ballq, mesh, met)
    from parmmg_tpu.ops.collapse import collapse_wave
    timed("full collapse_wave",
          lambda m, k: collapse_wave(m, k).mesh.tet.sum(), mesh, met)


if __name__ == "__main__":
    main()
