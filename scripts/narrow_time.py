"""Steady-state timing of the self-width-selecting auto blocks (bench proxy).

Reproduces bench.py's protocol (N=16 shock cube, capacity factor 8, 9-cycle
fused auto blocks) but times MORE blocks and prints per-block wall ms +
per-cycle narrow/full flags, so the cost of the full-refresh cadence and the
narrow row budget can be measured separately without a 19-minute bench run.

Knobs: NT_N, NT_CAP, NT_BLOCKS, NT_BLOCK (cycles/block), NT_FULL_EVERY
(full-refresh on the last cycle of every k-th block; 0 = never),
PARMMG_NARROW_DIV (ops/active.py row budget).
Run: python scripts/narrow_time.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp
import numpy as np

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.ops.active import adapt_cycles_auto
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric


def main():
    n = int(os.environ.get("NT_N", "16"))
    cap = int(os.environ.get("NT_CAP", "8"))
    nblocks = int(os.environ.get("NT_BLOCKS", "6"))
    block = int(os.environ.get("NT_BLOCK", "9"))
    full_every = int(os.environ.get("NT_FULL_EVERY", "1"))

    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=cap * len(vert), capT=cap * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    from parmmg_tpu.ops.active import narrow_rows
    print(f"N={n} capT={mesh.capT} A={narrow_rows(mesh.capT)} "
          f"block={block} full_every={full_every} "
          f"device={jax.default_backend()}", flush=True)

    def _flags(off):
        return tuple((c + off) % 3 == 2 for c in range(block))

    def _full(bi):
        if full_every == 0:
            return tuple(False for _ in range(block))
        return tuple(c == block - 1 and (bi % full_every == full_every - 1)
                     for c in range(block))

    dirty = jnp.zeros(mesh.capP, bool)
    okflag = jnp.asarray(False)
    m, k = mesh, met
    # warm-up: 2 blocks (second compile for device-layout inputs), plus one
    # of each distinct (swap_flags, full_flags) variant on state copies
    t0 = time.perf_counter()
    m, k, dirty, okflag, c0 = adapt_cycles_auto(
        m, k, dirty, okflag, jnp.asarray(0, jnp.int32),
        swap_flags=_flags(0), full_flags=_full(0))
    jax.block_until_ready(c0)
    print(f"warm block 0: {time.perf_counter()-t0:.1f}s", flush=True)
    t0 = time.perf_counter()
    m, k, dirty, okflag, c0 = adapt_cycles_auto(
        m, k, dirty, okflag, jnp.asarray(block, jnp.int32),
        swap_flags=_flags(block % 3), full_flags=_full(1))
    jax.block_until_ready(c0)
    print(f"warm block 1: {time.perf_counter()-t0:.1f}s", flush=True)
    variants = {(_flags((2 + bi) * block % 3), _full(2 + bi))
                for bi in range(nblocks)}
    variants -= {(_flags(0), _full(0)), (_flags(block % 3), _full(1))}
    for sf, ff in sorted(variants):
        mc = jax.tree.map(jnp.copy, m)
        t0 = time.perf_counter()
        _, _, _, _, cc = adapt_cycles_auto(
            mc, jnp.copy(k), jnp.copy(dirty), okflag,
            jnp.asarray(0, jnp.int32), swap_flags=sf, full_flags=ff)
        jax.block_until_ready(cc)
        print(f"warm variant: {time.perf_counter()-t0:.1f}s", flush=True)

    prev_live = int(np.asarray(c0)[-1][5])
    for bi in range(nblocks):
        off = (2 + bi) * block
        t0 = time.perf_counter()
        m, k, dirty, okflag, counts = adapt_cycles_auto(
            m, k, dirty, okflag, jnp.asarray(off, jnp.int32),
            swap_flags=_flags(off % 3), full_flags=_full(2 + bi))
        cs = np.asarray(counts)
        dt = time.perf_counter() - t0
        entries = [prev_live] + [int(r[5]) for r in cs[:-1]]
        rate = sum(entries) / dt / 1e6
        narrow = "".join("n" if r[7] else "F" for r in cs)
        ops = int(cs[:, 0].sum() + cs[:, 1].sum() + cs[:, 2].sum())
        print(f"block {bi}: {dt*1e3:7.1f} ms  {rate:6.3f} Mtets/s  "
              f"[{narrow}] live={int(cs[-1][5])} topo_ops={ops} "
              f"nact={[int(r[8]) for r in cs]}", flush=True)
        prev_live = int(cs[-1][5])


if __name__ == "__main__":
    main()
