"""Serving bench: N mixed-size tenants through one warm pool.

The first artifact family whose throughput metric is **meshes/sec**,
not Mtets/sec (ROADMAP open item 3): a pool of bucketed group slots
serves independent tenant meshes through the SAME compiled group
programs the batch grouped path uses, so after a per-bucket warmup
every request runs compile-free.

Phases:

1. **warmup** — per tenant size class, one standalone
   ``grouped_adapt_pass(ngroups=1)`` run (the batch path: exactly what
   a non-serving user pays) + the quality pull.  This compiles every
   ``groups.*`` family serving will touch AND doubles as the parity
   reference;
2. **serve** — submit all tenants to one ServeDriver, run to
   completion, measure meshes/sec + per-tenant latency percentiles +
   slot occupancy;
3. **gates** — ``extra.ledger_regressions`` lists any ``groups.*``
   entry whose compiled-variant count grew between (1) and (2) (MUST
   be empty: serving adds zero compile families after warmup), and
   ``extra.parity_ok`` asserts one representative tenant per class is
   bit-for-bit identical (mesh fields + metric) to its standalone run.

Prints ONE JSON line (bench.py shape) and writes it to SERVE_r<NN>.json
(next free round number; SERVE_OUT overrides).  Knobs: SERVE_TENANTS
(default 8), SERVE_CYCLES (default 3), SERVE_SLOTS (slots/bucket,
default 2 so slot recycling is exercised), SERVE_CHUNK (default 1).
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# CPU backend, axon factory dropped (ledger_check.py sequence): the
# serving datapoint is a CPU-backend artifact until a chip session
# validates the tunnel path
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)   # cold = honest warmup

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _tenant(n: int, h: float):
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import analytic_iso_metric, cube_mesh

    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    hh = analytic_iso_metric(vert, "shock", h=h)
    met = jnp.zeros(m.capP, m.vert.dtype).at[: len(hh)].set(
        jnp.asarray(hh, m.vert.dtype)).at[len(hh):].set(1.0)
    return m, met


def main() -> int:
    from parmmg_tpu.core.mesh import MESH_FIELDS
    from parmmg_tpu.ops.quality import quality_histogram, tet_quality
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.serve.driver import ServeDriver
    from parmmg_tpu.utils.compilecache import (
        ledger_snapshot, regressions_vs_latest_artifact,
        variants_by_prefix)

    ntenants = int(os.environ.get("SERVE_TENANTS", "8"))
    cycles = int(os.environ.get("SERVE_CYCLES", "3"))
    slots = int(os.environ.get("SERVE_SLOTS", "2"))
    chunk = int(os.environ.get("SERVE_CHUNK", "1"))

    # three size classes -> three distinct capacity-ladder buckets
    classes = [("small", 2, 0.55), ("medium", 3, 0.45),
               ("large", 4, 0.60)]

    # ---- phase 1: batch warmup (+ parity reference) ----------------------
    warm = {}
    warm_s = {}
    for name, n, h in classes:
        m, met = _tenant(n, h)
        t0 = time.perf_counter()
        out, met_m, _ = grouped_adapt_pass(m, met, 1, cycles=cycles)
        jax.block_until_ready(out.vert)
        warm_s[name] = round(time.perf_counter() - t0, 2)
        q = np.asarray(tet_quality(out, met_m))[np.asarray(out.tmask)]
        warm[name] = (out, met_m, float(q.min()), float(q.mean()))
        print(f"serve_bench: warmup {name} (cube {n}, h={h}): "
              f"{warm_s[name]}s batch", file=sys.stderr)

    def grp_variants():
        return variants_by_prefix("groups.")

    v_batch = grp_variants()

    # ---- phase 2: serve N tenants through one warm pool ------------------
    drv = ServeDriver(slots_per_bucket=slots, chunk=chunk, cycles=cycles,
                      verbose=1)
    tenants = []
    for i in range(ntenants):
        name, n, h = classes[i % len(classes)]
        m, met = _tenant(n, h)
        tid = drv.submit(mesh=m, met=met, tenant=f"{name}{i:02d}")
        tenants.append((tid, name))
    t0 = time.perf_counter()
    rep = drv.run()
    serve_s = time.perf_counter() - t0

    v_serve = grp_variants()
    regressions = [f"{k}: {v_batch.get(k, 0)} -> {v}"
                   for k, v in sorted(v_serve.items())
                   if v > v_batch.get(k, 0)]

    # ---- phase 3: parity — one tenant per class vs its standalone run ----
    parity_ok = True
    seen = set()
    for tid, name in tenants:
        if name in seen:
            continue
        seen.add(name)
        mesh, met_m = drv.fetch(tid)
        ref, kref = warm[name][0], warm[name][1]
        for f in MESH_FIELDS:
            if not (np.asarray(getattr(mesh, f))
                    == np.asarray(getattr(ref, f))).all():
                parity_ok = False
                print(f"serve_bench: PARITY MISMATCH {tid} field {f}",
                      file=sys.stderr)
        if not (np.asarray(met_m) == np.asarray(kref)).all():
            parity_ok = False
            print(f"serve_bench: PARITY MISMATCH {tid} metric",
                  file=sys.stderr)

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    ledger = ledger_snapshot()
    cross = regressions_vs_latest_artifact(root, "SERVE_r*.json", ledger)

    per_tenant = {
        tid: {
            "class": name,
            "state": rep["tenants"][tid]["state"],
            "latency_s": rep["tenants"][tid]["latency_s"],
            "qmin": (rep["tenants"][tid]["quality"] or {}).get("qmin"),
            "qmean": (rep["tenants"][tid]["quality"] or {}).get("qmean"),
            "ntets": (rep["tenants"][tid]["quality"] or {}).get("ntets"),
            "ops": rep["tenants"][tid]["ops"],
            "slo": rep["tenants"][tid].get("slo"),
        } for tid, name in tenants}

    # canonical schema-versioned artifact (obs/artifact.py)
    from parmmg_tpu.obs.artifact import make_artifact
    doc = make_artifact(
        "SERVE",
        metric="serve_throughput",
        value=round(rep["served"] / max(serve_s, 1e-9), 3),
        unit="meshes/sec (warm pool, CPU backend)",
        extra={
            "tenants": ntenants,
            "served": rep["served"],
            "rejected": rep["rejected"],
            "failed": rep["failed"],
            "bucket_sizes": sorted({f"{k[0]}x{k[1]}" for k in
                                    drv.pool.buckets}),
            "cycles": cycles,
            "chunk": chunk,
            "slots_per_bucket": slots,
            "serve_wall_s": round(serve_s, 3),
            "warmup_batch_s": warm_s,
            "latency_p50_s": rep["latency_p50_s"],
            "latency_p90_s": rep["latency_p90_s"],
            "latency_max_s": rep["latency_max_s"],
            "per_tenant": per_tenant,
            "slot_occupancy": rep["occupancy_traj"],
            "active_per_step": rep["pool"]["active_per_step"],
            "dispatches": rep["pool"]["dispatches"],
            "chunk_recommendation": rep["pool"]["chunk_recommendation"],
            "pipeline_s": rep["pool"]["pipeline_s"],
            "parity_ok": parity_ok,
            "groups_variants_batch": v_batch,
            "groups_variants_serve": v_serve,
            "ledger_regressions": regressions,
            "ledger_regressions_vs_artifact": cross,
            "compile_ledger": ledger,
            "device": jax.default_backend(),
        })
    line = json.dumps(doc)
    print(line)

    out = os.environ.get("SERVE_OUT")
    if not out:
        nums = [int(m.group(1)) for p in glob.glob(
            os.path.join(root, "SERVE_r*.json"))
            if (m := re.search(r"r(\d+)\.json$", p))]
        out = os.path.join(root, f"SERVE_r{max(nums, default=0) + 1:02d}"
                                 ".json")
    with open(out, "w") as f:
        f.write(line + "\n")
    print(f"serve_bench: wrote {out}", file=sys.stderr)
    if regressions or not parity_ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
