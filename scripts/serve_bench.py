"""Serving bench: N mixed-size tenants through one warm pool.

The first artifact family whose throughput metric is **meshes/sec**,
not Mtets/sec (ROADMAP open item 3): a pool of bucketed group slots
serves independent tenant meshes through the SAME compiled group
programs the batch grouped path uses, so after a per-bucket warmup
every request runs compile-free.

Phases:

1. **warmup** — per tenant size class, one standalone
   ``grouped_adapt_pass(ngroups=1)`` run (the batch path: exactly what
   a non-serving user pays) + the quality pull.  This compiles every
   ``groups.*`` family serving will touch AND doubles as the parity
   reference;
2. **serve** — submit all tenants to one ServeDriver, run to
   completion, measure meshes/sec + per-tenant latency percentiles +
   slot occupancy;
3. **gates** — ``extra.ledger_regressions`` lists any ``groups.*``
   entry whose compiled-variant count grew between (1) and (2) (MUST
   be empty: serving adds zero compile families after warmup), and
   ``extra.parity_ok`` asserts one representative tenant per class is
   bit-for-bit identical (mesh fields + metric) to its standalone run.

``--stream`` (the SERVE_r02+ mode): instead of submitting everything
up front to an in-process driver, tenants arrive as a sustained
OPEN-LOOP stream (PARMMG_SERVE_STREAM_RATE tenants/sec) through a pool
DAEMON over localhost HTTP (ephemeral port, in-process so the compile
ledger stays shared): streaming mid-step admission, the autoscale /
backpressure controller (HTTP 429 deferrals are retried, counted),
p50/p99 latency and queue-depth/occupancy trajectories — the serving
stack exercised end-to-end as a service, with the same parity + ledger
gates as the batch-queue mode.

Prints ONE JSON line (bench.py shape) and writes it to SERVE_r<NN>.json
(next free round number; SERVE_OUT overrides).  Knobs: SERVE_TENANTS
(default 8), SERVE_CYCLES (default 3), SERVE_SLOTS (slots/bucket,
default 2 so slot recycling is exercised), SERVE_CHUNK (default 1),
PARMMG_SERVE_STREAM_RATE (default 2/sec, --stream only).
"""
from __future__ import annotations

import glob
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# CPU backend, axon factory dropped (ledger_check.py sequence): the
# serving datapoint is a CPU-backend artifact until a chip session
# validates the tunnel path
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)   # cold = honest warmup

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _tenant(n: int, h: float):
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import analytic_iso_metric, cube_mesh

    vert, tet = cube_mesh(n)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    hh = analytic_iso_metric(vert, "shock", h=h)
    met = jnp.zeros(m.capP, m.vert.dtype).at[: len(hh)].set(
        jnp.asarray(hh, m.vert.dtype)).at[len(hh):].set(1.0)
    return m, met


def _tenant_raw(n: int, h: float):
    """Raw (vert, tet, met) for the daemon path: the daemon's
    stage_arrays applied to these reproduces _tenant() bit-for-bit
    (same 4x caps, same full-capP metric with unit pads)."""
    from parmmg_tpu.utils.fixtures import analytic_iso_metric, cube_mesh

    vert, tet = cube_mesh(n)
    hh = np.asarray(analytic_iso_metric(vert, "shock", h=h))
    met = np.ones(4 * len(vert), np.float64)
    met[: len(hh)] = hh
    return vert, tet, met


def main() -> int:
    from parmmg_tpu.core.mesh import MESH_FIELDS
    from parmmg_tpu.ops.quality import quality_histogram, tet_quality
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.serve.driver import ServeDriver
    from parmmg_tpu.utils.compilecache import (
        ledger_snapshot, regressions_vs_latest_artifact,
        variants_by_prefix)

    stream = "--stream" in sys.argv[1:]
    ntenants = int(os.environ.get("SERVE_TENANTS", "8"))
    cycles = int(os.environ.get("SERVE_CYCLES", "3"))
    slots = int(os.environ.get("SERVE_SLOTS", "2"))
    chunk = int(os.environ.get("SERVE_CHUNK", "1"))

    # three size classes -> three distinct capacity-ladder buckets
    classes = [("small", 2, 0.55), ("medium", 3, 0.45),
               ("large", 4, 0.60)]

    # ---- phase 1: batch warmup (+ parity reference) ----------------------
    warm = {}
    warm_s = {}
    for name, n, h in classes:
        m, met = _tenant(n, h)
        t0 = time.perf_counter()
        out, met_m, _ = grouped_adapt_pass(m, met, 1, cycles=cycles)
        jax.block_until_ready(out.vert)
        warm_s[name] = round(time.perf_counter() - t0, 2)
        q = np.asarray(tet_quality(out, met_m))[np.asarray(out.tmask)]
        warm[name] = (out, met_m, float(q.min()), float(q.mean()))
        print(f"serve_bench: warmup {name} (cube {n}, h={h}): "
              f"{warm_s[name]}s batch", file=sys.stderr)

    def grp_variants():
        return variants_by_prefix("groups.")

    v_batch = grp_variants()

    # ---- phase 2: serve N tenants through one warm pool ------------------
    daemon = cl = None
    stream_extra = None
    tenants = []
    if stream:
        # SERVE_r02 mode: open-loop arrivals through the pool DAEMON
        # over localhost HTTP (in-process ephemeral port — the ledger
        # diff below still sees every compile the daemon pays)
        from parmmg_tpu.serve.client import (BackpressureDeferred,
                                             ServeClient)
        from parmmg_tpu.serve.daemon import PoolDaemon
        rate = float(os.environ.get("PARMMG_SERVE_STREAM_RATE", "")
                     or 2.0)
        daemon = PoolDaemon(port=0, slots_per_bucket=slots, chunk=chunk,
                            cycles=cycles, verbose=1)
        daemon.start()
        drv = daemon.driver
        cl = ServeClient(port=daemon.port)
        arrivals = []
        for i in range(ntenants):
            name, n, h = classes[i % len(classes)]
            tid = f"{name}{i:02d}"
            arrivals.append([i / rate, tid] + list(_tenant_raw(n, h)))
            tenants.append((tid, name))
        submitted: set = set()
        terminal: set = set()
        deferred = 0
        traj = []
        t0 = time.perf_counter()
        while len(terminal) < ntenants:
            now = time.perf_counter() - t0
            while arrivals and arrivals[0][0] <= now:
                _due, tid, vert, tet, met = arrivals[0]
                try:
                    cl.submit(vert=vert, tet=tet, met=met, tenant=tid)
                    submitted.add(tid)
                    arrivals.pop(0)
                except BackpressureDeferred:
                    deferred += 1       # open-loop: retry shortly
                    arrivals[0][0] = now + 0.1
                    break
            for tid in sorted(submitted - terminal):
                if cl.poll(tid)["state"] not in ("queued", "running"):
                    terminal.add(tid)
            with daemon._lock:
                traj.append({
                    "t": round(now, 3),
                    "queue_depth": len(drv.queue),
                    "active": len(drv.pool.active_tenants()),
                    "occupancy": {k: list(v) for k, v in
                                  drv.pool.occupancy().items()}})
            time.sleep(0.05)
        serve_s = time.perf_counter() - t0
        with daemon._lock:
            rep = drv.report(list(drv._occupancy_traj))
        stream_extra = {
            "rate_per_s": rate,
            "deferred_submits": deferred,
            "stream_admissions": rep["admission"]["stream_admissions"],
            "autoscale": rep["autoscale"],
            "port": daemon.port,
            "traj": traj[:: max(1, len(traj) // 200)],
        }
    else:
        drv = ServeDriver(slots_per_bucket=slots, chunk=chunk,
                          cycles=cycles, verbose=1)
        for i in range(ntenants):
            name, n, h = classes[i % len(classes)]
            m, met = _tenant(n, h)
            tid = drv.submit(mesh=m, met=met, tenant=f"{name}{i:02d}")
            tenants.append((tid, name))
        t0 = time.perf_counter()
        rep = drv.run()
        serve_s = time.perf_counter() - t0

    v_serve = grp_variants()
    regressions = [f"{k}: {v_batch.get(k, 0)} -> {v}"
                   for k, v in sorted(v_serve.items())
                   if v > v_batch.get(k, 0)]

    # ---- phase 3: parity — one tenant per class vs its standalone run ----
    def fetch_arrays(tid):
        if stream:
            return cl.fetch(tid)
        mesh, met_m = drv.fetch(tid)
        out = {f: np.asarray(getattr(mesh, f)) for f in MESH_FIELDS}
        out["met"] = np.asarray(met_m)
        return out

    parity_ok = True
    seen = set()
    for tid, name in tenants:
        if name in seen:
            continue
        seen.add(name)
        try:
            arrays = fetch_arrays(tid)
        except Exception as e:
            parity_ok = False
            print(f"serve_bench: PARITY FETCH FAILED {tid}: {e!r}",
                  file=sys.stderr)
            continue
        ref, kref = warm[name][0], warm[name][1]
        for f in MESH_FIELDS:
            if not (arrays[f] == np.asarray(getattr(ref, f))).all():
                parity_ok = False
                print(f"serve_bench: PARITY MISMATCH {tid} field {f}",
                      file=sys.stderr)
        if not (arrays["met"] == np.asarray(kref)).all():
            parity_ok = False
            print(f"serve_bench: PARITY MISMATCH {tid} metric",
                  file=sys.stderr)
    if daemon is not None:
        daemon.shutdown()

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    ledger = ledger_snapshot()
    cross = regressions_vs_latest_artifact(root, "SERVE_r*.json", ledger)

    per_tenant = {
        tid: {
            "class": name,
            "state": rep["tenants"][tid]["state"],
            "latency_s": rep["tenants"][tid]["latency_s"],
            "qmin": (rep["tenants"][tid]["quality"] or {}).get("qmin"),
            "qmean": (rep["tenants"][tid]["quality"] or {}).get("qmean"),
            "ntets": (rep["tenants"][tid]["quality"] or {}).get("ntets"),
            "ops": rep["tenants"][tid]["ops"],
            "slo": rep["tenants"][tid].get("slo"),
        } for tid, name in tenants}

    # canonical schema-versioned artifact (obs/artifact.py)
    from parmmg_tpu.obs.artifact import make_artifact
    doc = make_artifact(
        "SERVE",
        metric="serve_throughput",
        value=round(rep["served"] / max(serve_s, 1e-9), 3),
        unit="meshes/sec (warm pool, CPU backend)",
        extra={
            "mode": "stream-daemon" if stream else "batch-queue",
            "tenants": ntenants,
            "served": rep["served"],
            "rejected": rep["rejected"],
            "failed": rep["failed"],
            "bucket_sizes": sorted({f"{k[0]}x{k[1]}" for k in
                                    drv.pool.buckets}),
            "cycles": cycles,
            "chunk": chunk,
            "slots_per_bucket": slots,
            "serve_wall_s": round(serve_s, 3),
            "warmup_batch_s": warm_s,
            "latency_p50_s": rep["latency_p50_s"],
            "latency_p90_s": rep["latency_p90_s"],
            "latency_p99_s": rep["latency_p99_s"],
            "latency_max_s": rep["latency_max_s"],
            "admission": rep["admission"],
            "stream": stream_extra,
            "per_tenant": per_tenant,
            "slot_occupancy": rep["occupancy_traj"],
            "active_per_step": rep["pool"]["active_per_step"],
            "dispatches": rep["pool"]["dispatches"],
            "chunk_recommendation": rep["pool"]["chunk_recommendation"],
            "pipeline_s": rep["pool"]["pipeline_s"],
            "parity_ok": parity_ok,
            "groups_variants_batch": v_batch,
            "groups_variants_serve": v_serve,
            "ledger_regressions": regressions,
            "ledger_regressions_vs_artifact": cross,
            "compile_ledger": ledger,
            "device": jax.default_backend(),
        })
    line = json.dumps(doc)
    print(line)

    out = os.environ.get("SERVE_OUT")
    if not out:
        nums = [int(m.group(1)) for p in glob.glob(
            os.path.join(root, "SERVE_r*.json"))
            if (m := re.search(r"r(\d+)\.json$", p))]
        out = os.path.join(root, f"SERVE_r{max(nums, default=0) + 1:02d}"
                                 ".json")
    with open(out, "w") as f:
        f.write(line + "\n")
    print(f"serve_bench: wrote {out}", file=sys.stderr)
    if regressions or not parity_ok:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
