"""File-based front-end for the serving subsystem (parmmg_tpu/serve).

Submit a batch of tenant mesh files to one warm pool and write each
tenant's adapted mesh back out as a merge-free distributed checkpoint:

    python scripts/serve_run.py --out OUTDIR a.mesh b.mesh c.vtu ...

Each input may carry a sidecar metric ``<stem>.sol`` (auto-detected;
VTK inputs may embed a "metric"/"sol" point field instead); without
one the -optim default metric is synthesized.  Prints ONE JSON report:
per-tenant state / latency / qmin / qmean / output files plus the pool
aggregates (occupancy, dispatches, chunk recommendation).

Knobs ride the PARMMG_SERVE_* env surface (see serve/driver.py):
SLOTS, CHUNK, CYCLES (SERVE_CYCLES here), MAX_INFLIGHT, TIMEOUT_S,
MAX_CAPP/MAX_CAPT.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# same defensive backend sequence as scripts/scale_big.py: the serving
# orchestrator is host-side; a real accelerator is only worth engaging
# through the pool's dispatch path, and on this image the axon factory
# must be dropped explicitly when pinning CPU
if os.environ.get("SERVE_DEVICE", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("meshes", nargs="+", help=".mesh/.meshb/.vtu inputs")
    ap.add_argument("--out", default="serve_out",
                    help="output directory for per-tenant checkpoints")
    ap.add_argument("--cycles", type=int,
                    default=int(os.environ.get("SERVE_CYCLES", "6")))
    ap.add_argument("-v", "--verbose", action="count", default=0)
    args = ap.parse_args()

    from parmmg_tpu.serve.driver import ServeDriver

    os.makedirs(args.out, exist_ok=True)
    drv = ServeDriver(out_dir=args.out, cycles=args.cycles,
                      verbose=args.verbose)
    for p in args.meshes:
        stem = os.path.splitext(p)[0]
        sol = stem + ".sol"
        drv.submit(path=p, sol=sol if os.path.exists(sol) else None,
                   tenant=os.path.basename(stem))
    rep = drv.run()
    rep.pop("occupancy_traj", None)
    print(json.dumps(rep, default=str))
    return 0 if rep["failed"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
