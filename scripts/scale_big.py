"""Million-tet single-chip datapoint via the two-level group machinery.

The 10M-tet configuration (BASELINE.md planned configs) is reachable on
one chip only through sub-device groups: chunked ``lax.map`` over group
slots keeps the working set (and the O(n log^2 n) wave sorts) at GROUP
size while HOST RAM holds the whole mesh (parallel/groups.py, the
grpsplit_pmmg.c:1551 role).  This script runs grouped adaptation passes
on a >=1M-tet shock cube and reports per-phase timings + the grouped
throughput as ONE JSON line (same shape as bench.py).

Process layout: each grouped PASS runs in its own subprocess with a
FRESH tunnel client (SCALE_WORKER=1 re-entry), with the merged mesh
handed over via .npz.  Reproduced failure mode this avoids: the axon
TPU worker reliably dies on the next BIG remote compile late in a
session that already ran a full grouped pass (pass-2 regrow-shape
compiles crashed 3/3 attempts on 2026-08-02, while identical programs
compile fine in a fresh client).  The orchestrator itself pins
JAX_PLATFORMS=cpu — only pass workers (and the nested polish worker,
parallel/_polish_worker.py) touch the chip.

Run (real chip): cd /root/repo && python scripts/scale_big.py
Knobs: SCALE_N (default 56 -> 6*56^3 = 1,053,696 tets),
       SCALE_TARGET (group size target, default 24576),
       SCALE_CYCLES (default 6), SCALE_NITER (passes, default 2),
       SCALE_DEVICE=cpu to keep even the workers off the chip.

Resume (``--resume`` / SCALE_RESUME=1): the per-pass ``state<k>.npz``
hand-over files under SCALE_TMP double as pass checkpoints — each gets
a ``.ok`` marker only once it is a COMPLETE pass input (state0 after
staging, state<k> after the displacement rewrite), so a kill mid-pass
or mid-write can never leave a marked-but-corrupt state.  A resumed
run restarts from the newest marked state and, passes being
deterministic functions of their input state, finishes bit-identical
to an uninterrupted run (the resilience/checkpoint.py contract; the
in-process half is chaos-gated by scripts/chaos_check.py).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from parmmg_tpu.core.mesh import MESH_FIELDS
from parmmg_tpu.utils.compilecache import ledger_snapshot, set_cache_env

# persistent compile cache shared with the CLI/bench (compile governor):
# env-only here so the pass workers and the nested polish worker inherit
# it — that is what stops every fresh-client subprocess recompiling the
# grouped programs from scratch
set_cache_env()


def _save_state(path, mesh, met, part, extra=None):
    np.savez(path, met=np.asarray(met), part=np.asarray(part),
             **{f: np.asarray(getattr(mesh, f)) for f in MESH_FIELDS},
             **(extra or {}))


def _load_state(path):
    from parmmg_tpu.core.mesh import Mesh
    z = np.load(path)
    mesh = Mesh(**{f: z[f] for f in MESH_FIELDS})
    return z, mesh, z["met"], z["part"]


def _mark_ready(path: str) -> None:
    """Completion marker: ``path`` is a complete pass-input state."""
    with open(path + ".ok", "w") as f:
        f.write("ok\n")


def _find_resume(tmp: str, niter: int) -> int | None:
    """Newest pass index k whose state<k>.npz is marked complete."""
    best = None
    for k in range(niter + 1):
        p = f"{tmp}/state{k}.npz"
        if os.path.exists(p) and os.path.exists(p + ".ok"):
            best = k
    return best


def worker() -> None:
    """One grouped pass on the accelerator (fresh process)."""
    import jax
    from parmmg_tpu.utils.compilecache import drop_cache_on_cpu_fallback
    # chip unreachable -> this worker silently lands on XLA:CPU; drop
    # the inherited persistent cache there (unreliable AOT cache)
    drop_cache_on_cpu_fallback()
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.ops.adapt import AdaptStats

    inp, outp = os.environ["SCALE_IN"], os.environ["SCALE_OUT"]
    cycles = int(os.environ.get("SCALE_CYCLES", "6"))
    polish = os.environ.get("SCALE_POLISH", "0") == "1"
    vb = 3 if os.environ.get("SCALE_VERBOSE") else 0
    z, mesh, met, part = _load_state(inp)
    ngroups = int(part.max()) + 1
    stats = AdaptStats()
    t0 = time.perf_counter()
    # cap_mult stays at the API default: the prediction-weighted
    # partition (main) bounds every group's FINAL size by its weight
    # share, so the standard multiplier already covers the growth and
    # the group program keeps the proven-compilable shape (a 10x cap
    # made the per-group program big enough that the tunnel's compile
    # helper was OOM-killed, and a regrow's fresh compile kills the
    # worker — see module docstring).
    mesh2, met2, part_m = grouped_adapt_pass(
        mesh, met, ngroups, cycles=cycles, part=part, stats=stats,
        verbose=vb, polish=polish,
        cap_mult=float(os.environ.get("SCALE_CAPM", "3.0")))
    adapt_s = time.perf_counter() - t0
    # quiet-group scheduler instrumentation (parallel/sched.py): the
    # active-group trajectory, saved-dispatch counters and the chunk
    # pipeline's upload/compute/download/writeback split ride back to
    # the orchestrator so the SCALE artifact shows WHERE the grouped
    # wall time goes and what the scheduler saved
    sched_timers = {k: round(v, 3) for k, v in stats.sched_extra.items()
                    if k.endswith("_s")}
    _save_state(outp, mesh2, met2, part_m, extra={
        "adapt_s": adapt_s, "cycles_run": stats.cycles,
        "ops": np.asarray([stats.nsplit, stats.ncollapse, stats.nswap,
                           stats.nmoved], np.int64),
        "active_groups": np.asarray(
            stats.sched_extra.get("active_groups_per_block", []),
            np.int64),
        # chunk auto-tune (sched.recommend_group_chunk, logged by the
        # grouped pass): adopted only under PARMMG_GROUP_CHUNK=auto;
        # the overhead constant of its cost model is CALIBRATED from
        # this pass's measured pipeline segment timings (ROADMAP 1b)
        "chunk_recommendation": np.asarray(
            stats.sched_extra.get("chunk_recommendation", [0])[-1],
            np.int64),
        # NaN = this pass produced no calibration signal (unchunked or
        # empty segments) — distinct from a measured zero overhead
        "chunk_overhead": np.asarray(
            stats.sched_extra.get("chunk_overhead_units", [np.nan])[-1],
            np.float64),
        "group_dispatches": np.asarray(stats.group_dispatches, np.int64),
        "saved_dispatches": np.asarray(stats.group_dispatches_saved,
                                       np.int64),
        # group-slot executions the device-resident quiet mask
        # lax.cond-skipped (parallel/sched.py, PR 12)
        "cond_skipped": np.asarray(
            stats.sched_extra.get("cond_skipped_rows", 0), np.int64),
        "sched_timers": np.asarray(json.dumps(sched_timers)),
        "device": np.asarray(jax.default_backend()),
        # this worker's compile ledger rides back to the orchestrator
        # so the BENCH artifact shows per-pass compile churn
        "ledger": np.asarray(json.dumps(ledger_snapshot()))})


def main():
    # orchestrator stays off the chip: host staging, displacement and
    # the final whole-mesh tails are all CPU work.  Setting
    # JAX_PLATFORMS=cpu is NOT enough on this image — the axon
    # sitecustomize re-registers the TPU plugin regardless, and a
    # second tunnel client wedges against the pass workers (the tunnel
    # is single-client).  Drop the factory explicitly, the same
    # defensive sequence as tests/conftest.py / __graft_entry__.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    # NOTE: the orchestrator itself runs WITHOUT the persistent cache —
    # it is pinned to CPU and the XLA:CPU AOT cache is unreliable on
    # this image (tests/conftest.py rationale).  The module-level
    # set_cache_env() above only exports the env var so the TPU pass
    # workers inherit it.

    from parmmg_tpu.core.mesh import make_mesh, mesh_to_host
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.ops.quality import tet_quality
    from parmmg_tpu.parallel.groups import how_many_groups
    from parmmg_tpu.parallel.partition import (morton_partition,
                                               move_interfaces)
    from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric

    n = int(os.environ.get("SCALE_N", "56"))
    target = int(os.environ.get("SCALE_TARGET", "24576"))
    niter = max(1, int(os.environ.get("SCALE_NITER", "2")))

    tmp = os.environ.get("SCALE_TMP", "/tmp/parmmg_scale")
    os.makedirs(tmp, exist_ok=True)
    # --resume / SCALE_RESUME=1: restart from the newest COMPLETE pass
    # state (``.ok``-marked — see module docstring) instead of from
    # scratch; the skipped staging metadata rides in meta.json
    resume = "--resume" in sys.argv[1:] or \
        os.environ.get("SCALE_RESUME", "") == "1"
    it0 = 0
    phases = {}
    state = f"{tmp}/state0.npz"
    # run-identity knobs: stored in meta.json and required to match at
    # resume — a reused SCALE_TMP must never silently resume a run with
    # different SCALE_* knobs (a final-pass state in particular carries
    # an UN-displaced partition, so extending niter on it would break
    # the bit-identical contract)
    knobs = {"n": n, "target": target, "niter": niter,
             "cycles": int(os.environ.get("SCALE_CYCLES", "6"))}
    if resume:
        k = _find_resume(tmp, niter)
        meta_p = f"{tmp}/meta.json"
        if k is None or not os.path.exists(meta_p):
            print(f"scale: --resume requested but no complete state "
                  f"under {tmp}; starting fresh", file=sys.stderr)
            resume = False
        else:
            with open(meta_p) as f:
                meta = json.load(f)
            stored = {kk: meta.get(kk) for kk in knobs}
            if stored != knobs:
                print("scale: --resume refused: SCALE knobs differ "
                      f"from the checkpointed run ({stored} vs "
                      f"{knobs}); starting fresh", file=sys.stderr)
                resume = False
            elif k >= niter:
                # every pass already complete: the original run emitted
                # its artifact; re-emitting one with zero adapt seconds
                # would read as a throughput regression in the artifact
                # differ — nothing to resume, say so and stop
                print(f"scale: --resume: all {niter} passes already "
                      f"complete under {tmp}; nothing to resume",
                      file=sys.stderr)
                return
            else:
                it0 = k
                ntet0, ngroups = int(meta["ntet0"]), int(meta["ngroups"])
                state = f"{tmp}/state{k}.npz"
                print(f"scale: resuming from {state} "
                      f"(outer pass {k}/{niter})", file=sys.stderr)
    if not resume:
        # fresh start: drop stale pass states + markers so a LATER
        # resume can never mix runs
        import glob as _glob
        for f in _glob.glob(f"{tmp}/state*.npz*"):
            os.remove(f)
        it0 = 0
        t0 = time.perf_counter()
        vert, tet = cube_mesh(n)
        ntet0 = len(tet)
        phases["host_build"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        # host partition: morton only — fix_contiguity's python BFS is
        # an O(mesh) host stage this datapoint deliberately excludes
        # (group seams freeze identically either way).  The curve is
        # split by PREDICTED-final-density weights, not initial counts:
        # the shock slab grows ~6x while coarse regions shrink, so
        # equal-initial groups overflow their static caps exactly where
        # the work is (the regrow then forces a fresh remote compile,
        # which is what kills the tunnel worker — see the module
        # docstring).  A tet of volume V in a region with target size h
        # ends as ~V/(h^3/(6 sqrt 2)) unit tets; the bisection
        # equilibrium overshoots the ideal count ~2.2x (measured, bench
        # fixture class).  weight = 1 + predicted bounds BOTH the
        # initial and the final group size by the group's weight share,
        # so one static cap fits all groups end to end.
        h = analytic_iso_metric(vert, "shock", h=1.5 / n)
        cent = vert[tet].mean(axis=1)
        p = vert[tet]
        vol = np.abs(np.einsum(
            "ij,ij->i", p[:, 1] - p[:, 0],
            np.cross(p[:, 2] - p[:, 0], p[:, 3] - p[:, 0]))) / 6.0
        h_tet = np.asarray(h)[tet].mean(axis=1)
        pred = 2.2 * vol / (0.1178 * np.maximum(h_tet, 1e-9) ** 3)
        w = 1.0 + pred
        ngroups = how_many_groups(int(w.sum()), int(1.5 * target))
        part = morton_partition(cent, ngroups, weights=w)
        phases["host_partition"] = time.perf_counter() - t0

        t0 = time.perf_counter()
        mesh = make_mesh(vert, tet,
                         capP=2 * len(vert), capT=2 * len(tet))
        mesh = analyze_mesh(mesh).mesh
        met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
            jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
        jax.block_until_ready(mesh.vert)
        phases["stage_analyze"] = time.perf_counter() - t0

        # ---- grouped passes, one fresh-client subprocess each ----------
        t0 = time.perf_counter()
        _save_state(state, mesh, met, part)
        _mark_ready(state)
        with open(f"{tmp}/meta.json", "w") as f:
            json.dump({"ntet0": int(ntet0), "ngroups": int(ngroups),
                       **knobs}, f)
        phases["state_io"] = time.perf_counter() - t0
        del mesh, met

    cycles_run = 0
    ops = np.zeros(4, np.int64)
    dev = "?"
    ledgers = {}
    active_traj = {}
    sched_timers = {}
    group_disp = 0
    saved_disp = 0
    cond_skipped = 0
    chunk_rec = 0
    chunk_overhead = {}
    for it in range(it0, niter):
        nxt = f"{tmp}/state{it + 1}.npz"
        env = dict(os.environ)
        env.update(SCALE_IN=state, SCALE_OUT=nxt, SCALE_WORKER="1",
                   SCALE_POLISH="1" if it == niter - 1 else "0")
        # chunked dispatch even on CPU workers (SCALE_GROUP_CHUNK,
        # default 8): chunking is what the quiet-group scheduler
        # compacts — on the chip it also bounds the per-dispatch HBM
        # (group_chunk docstring), on CPU the host staging is cheap and
        # skipping quiet groups is a straight win on this workload
        # (SCALE_r03: op counts collapse across cycles)
        env.setdefault("PARMMG_GROUP_CHUNK",
                       os.environ.get("SCALE_GROUP_CHUNK", "8"))
        # the worker decides its own backend: default = real chip
        # (inherit the axon site), SCALE_DEVICE=cpu forces CPU
        if os.environ.get("SCALE_DEVICE", "") == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            # forced-CPU workers must not inherit the persistent cache
            # (unreliable XLA:CPU AOT cache — see set_cache_env)
            env.pop("JAX_COMPILATION_CACHE_DIR", None)
        else:
            env.pop("JAX_PLATFORMS", None)
        t0 = time.perf_counter()
        # the pass is idempotent from its input state: on a tunnel
        # worker crash (the UNAVAILABLE failure mode), retry in a fresh
        # process through the shared resilience wrapper — same
        # PARMMG_RETRY_* knobs, backoff, ladder events and counters as
        # the in-process recovery paths
        from parmmg_tpu.resilience.recover import (RetryBudgetExhausted,
                                                   WorkerExitError,
                                                   retry_call)

        def _invoke_pass():
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env)
            if r.returncode != 0:
                raise WorkerExitError("scale.worker", r.returncode)
            return r

        try:
            retry_call(_invoke_pass, site="scale.worker")
        except RetryBudgetExhausted as e:
            raise RuntimeError(
                f"pass {it} worker failed after retries "
                f"({e.__cause__ or e})") from e
        phases[f"pass{it}_total"] = time.perf_counter() - t0
        z, mesh2, met2, part_m = _load_state(nxt)
        phases[f"pass{it}_adapt"] = float(z["adapt_s"])
        cycles_run += int(z["cycles_run"])
        ops += z["ops"]
        dev = str(z["device"])
        if "ledger" in z.files:
            ledgers[f"pass{it}"] = json.loads(str(z["ledger"]))
        if "active_groups" in z.files:
            active_traj[f"pass{it}"] = [int(v)
                                        for v in z["active_groups"]]
            group_disp += int(z["group_dispatches"])
            saved_disp += int(z["saved_dispatches"])
            sched_timers[f"pass{it}"] = json.loads(str(z["sched_timers"]))
        if "cond_skipped" in z.files:
            cond_skipped += int(z["cond_skipped"])
        if "chunk_overhead" in z.files and \
                np.isfinite(float(z["chunk_overhead"])):
            chunk_overhead[f"pass{it}"] = round(
                float(z["chunk_overhead"]), 4)
        if "chunk_recommendation" in z.files:
            chunk_rec = int(z["chunk_recommendation"])
            print(f"scale: pass {it} recommends PARMMG_GROUP_CHUNK="
                  f"{chunk_rec or 'unchunked'} (auto-tune; set "
                  "PARMMG_GROUP_CHUNK=auto to adopt)", file=sys.stderr)
        state = nxt
        if it + 1 < niter:
            t0 = time.perf_counter()
            _, tet_h, _, _, _ = mesh_to_host(mesh2)
            part2 = move_interfaces(tet_h, np.asarray(part_m),
                                    int(np.asarray(part_m).max()) + 1,
                                    nlayers=2)
            phases["ifc_displacement"] = \
                phases.get("ifc_displacement", 0.0) + \
                (time.perf_counter() - t0)
            # rewrite the state with the displaced partition, THEN mark
            # complete: a kill mid-rewrite resumes from the previous
            # marked state (re-running one pass, never corrupting one)
            _save_state(state, mesh2, met2, part2)
            _mark_ready(state)
        # the FINAL state is marked only after the artifact is emitted
        # (end of main): a kill during the post-adapt tail must leave
        # the last pass resumable, or the artifact could never be
        # produced without a full rerun

    # post-merge whole-mesh polish on the CPU backend: the grouped
    # polish cannot touch the FINAL seams (frozen in their own pass);
    # this full-width pass can (SCALE_MERGED_POLISH=0 skips it).
    from parmmg_tpu.ops.adapt import sliver_polish
    from parmmg_tpu.ops.repair import repair_mesh
    t0 = time.perf_counter()
    met2 = jnp.asarray(met2)
    mesh2 = jax.tree.map(jnp.asarray, mesh2)
    if os.environ.get("SCALE_MERGED_POLISH", "1") == "1":
        for w in range(3):
            mesh2, pc = sliver_polish(
                mesh2, met2, jnp.asarray(3000 + w, jnp.int32))
            pcn = np.asarray(pc)
            if int(pcn[0]) == 0 and int(pcn[1]) == 0:
                break
    phases["merged_polish"] = time.perf_counter() - t0

    # sequential tail repair (host, O(bad tets)) — the production
    # driver's _finish_run role
    t0 = time.perf_counter()
    mesh2, _nrep = repair_mesh(mesh2, met2)
    phases["repair_tail"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    tm = np.asarray(mesh2.tmask)
    q = np.asarray(tet_quality(mesh2, met2))[tm]
    phases["quality_pull"] = time.perf_counter() - t0

    # throughput accounting mirrors bench.py: live tets examined per
    # cycle / adapt wall seconds.  Worker numbers INCLUDE the one-time
    # compiles (reported separately in phases_s as passN_adapt vs
    # passN_total = adapt + state IO + process start).
    adapt_s = sum(v for k, v in phases.items() if k.endswith("_adapt"))
    examined = cycles_run * ntet0          # lower bound (mesh only grows)
    rate = examined / max(adapt_s, 1e-9) / 1e6
    # bench-side ledger regression check (compile governor teeth): any
    # entry point whose compiled-variant count grew since the newest
    # SCALE_r*.json artifact is flagged in the JSON and on stderr
    # (scripts/ledger_check.py --diff is the standalone comparison)
    ledger = {**ledgers, "host": ledger_snapshot()}
    regressions = _ledger_regressions_vs_previous(ledger)
    if regressions:
        print("scale: COMPILE-LEDGER VARIANT REGRESSIONS vs previous "
              "artifact:", file=sys.stderr)
        for r in regressions:
            print(f"scale:   {r}", file=sys.stderr)

    # canonical schema-versioned artifact (obs/artifact.py)
    from parmmg_tpu.obs.artifact import make_artifact
    print(json.dumps(make_artifact(
        "SCALE",
        metric="grouped_scale_throughput",
        value=round(rate, 4),
        unit="Mtets/sec/chip (incl. one-time compile)",
        extra={
            "niter": niter,
            **({"resumed_from_pass": it0} if it0 else {}),
            "ntets_initial": int(ntet0),
            "ntets_final": int(tm.sum()),
            "ngroups": int(ngroups),
            "cycles": int(cycles_run),
            "ops": [int(v) for v in ops],
            "qmin": round(float(q.min()), 4) if tm.any() else 0.0,
            "qmean": round(float(q.mean()), 4) if tm.any() else 0.0,
            "phases_s": {k: round(v, 2) for k, v in phases.items()},
            "device": dev,
            # quiet-group scheduler (parallel/sched.py): per-pass
            # active-group trajectory, total/saved chunk dispatches and
            # the pipeline's upload/compute/download/writeback split —
            # the win and the transfer/compute balance in one artifact
            "active_groups_per_block": active_traj,
            "group_dispatches": group_disp,
            "saved_dispatches": saved_disp,
            # device-resident quiet mask (PR 12): lax.cond-skipped
            # group-slot executions + the measured per-dispatch
            # overhead calibration feeding the chunk auto-tune
            "cond_skipped": cond_skipped,
            "chunk_recommendation": chunk_rec,
            "chunk_overhead_calibration": chunk_overhead,
            "sched_pipeline_s": sched_timers,
            # per-pass worker compile ledgers + the orchestrator's own
            # (compile governor): steady-state passes should show ~zero
            # fresh compiles once the persistent cache is warm
            "compile_ledger": ledger,
            "ledger_regressions": regressions,
        })))
    # only now is the run truly complete: mark the final state so a
    # later --resume knows there is nothing left to produce
    _mark_ready(state)


def _ledger_regressions_vs_previous(ledger: dict) -> list[str]:
    """Diff this run's (nested per-worker) ledger against the newest
    SCALE_r*.json in the repo root (shared logic:
    utils.compilecache.regressions_vs_latest_artifact)."""
    from parmmg_tpu.utils.compilecache import regressions_vs_latest_artifact
    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    return regressions_vs_latest_artifact(root, "SCALE_r*.json", ledger)


if __name__ == "__main__":
    if os.environ.get("SCALE_WORKER") == "1":
        worker()
    else:
        main()
