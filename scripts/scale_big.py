"""Million-tet single-chip datapoint via the two-level group machinery.

The 10M-tet configuration (BASELINE.md planned configs) is reachable on
one chip only through sub-device groups: lax.map over group slots keeps
the working set (and the O(n log^2 n) wave sorts) at GROUP size while
the stacked state holds the whole mesh (parallel/groups.py, the
grpsplit_pmmg.c:1551 role).  This script runs one grouped adaptation
pass on a >=1M-tet shock cube and reports per-phase timings + the
grouped throughput as ONE JSON line (same shape as bench.py).

Run (real chip): cd /root/repo && python scripts/scale_big.py
Knobs: SCALE_N (default 56 -> 6*56^3 = 1,053,696 tets),
       SCALE_TARGET (group size target, default 24576),
       SCALE_CYCLES (default 6), JAX_PLATFORMS=cpu for a CPU run.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    jax.config.update("jax_compilation_cache_dir",
                      os.environ["JAX_COMPILATION_CACHE_DIR"])

    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.ops.quality import tet_quality
    from parmmg_tpu.parallel.groups import grouped_adapt_pass, \
        how_many_groups
    from parmmg_tpu.parallel.partition import morton_partition
    from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric
    from parmmg_tpu.ops.adapt import AdaptStats

    n = int(os.environ.get("SCALE_N", "56"))
    target = int(os.environ.get("SCALE_TARGET", "24576"))
    cycles = int(os.environ.get("SCALE_CYCLES", "6"))

    phases = {}
    t0 = time.perf_counter()
    vert, tet = cube_mesh(n)
    ntet0 = len(tet)
    phases["host_build"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    # host partition: morton only — fix_contiguity's python BFS is an
    # O(mesh) host stage this datapoint deliberately excludes (group
    # seams freeze identically either way)
    cent = vert[tet].mean(axis=1)
    ngroups = how_many_groups(ntet0, target)
    part = morton_partition(cent, ngroups)
    phases["host_partition"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    # stage + analyze the FULL mesh on the CPU backend: the whole-mesh
    # analysis program at 1M-tet width does not compile through the
    # tunnel in reasonable time (the round-2 BENCH_N=32 blocker) and
    # runs once — the groups are what the chip executes
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        mesh = make_mesh(vert, tet, capP=2 * len(vert),
                         capT=2 * len(tet))
        mesh = analyze_mesh(mesh).mesh
        h = analytic_iso_metric(vert, "shock", h=1.5 / n)
        met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
            jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
        jax.block_until_ready(mesh.vert)
    phases["stage_analyze"] = time.perf_counter() - t0

    stats = AdaptStats()
    niter = int(os.environ.get("SCALE_NITER", "2"))
    vb = 3 if os.environ.get("SCALE_VERBOSE") else 0
    t0 = time.perf_counter()
    mesh2, met2 = mesh, met
    part2 = part
    for it in range(max(1, niter)):
        # the last pass runs the grouped bad-element polish so the
        # reported min quality is POST-TAIL (group seams frozen during
        # a pass are displaced between passes, so the final polish sees
        # previously-frozen seams as interior)
        mesh2, met2, part_m = grouped_adapt_pass(
            mesh2, met2, ngroups, cycles=cycles, part=part2,
            stats=stats, verbose=vb, polish=(it == max(1, niter) - 1))
        if it + 1 < max(1, niter):
            from parmmg_tpu.parallel.partition import move_interfaces
            from parmmg_tpu.core.mesh import mesh_to_host
            t1 = time.perf_counter()
            _, tet_h, _, _, _ = mesh_to_host(mesh2)
            part2 = move_interfaces(tet_h, part_m, ngroups, nlayers=2)
            phases["ifc_displacement"] = \
                phases.get("ifc_displacement", 0.0) + \
                (time.perf_counter() - t1)
    jax.block_until_ready(mesh2.vert)
    phases["grouped_adapt"] = time.perf_counter() - t0

    # post-merge whole-mesh polish on the CPU backend: the grouped
    # polish cannot touch the FINAL seams (frozen in their own pass);
    # this full-width pass can.  Whole-mesh width does not compile
    # through the TPU tunnel — the CPU backend is the right home for
    # this untimed tail (SCALE_MERGED_POLISH=0 skips it).
    from parmmg_tpu.ops.adapt import sliver_polish
    from parmmg_tpu.ops.repair import repair_mesh
    t0 = time.perf_counter()
    with jax.default_device(cpu):
        mesh2 = jax.device_put(mesh2, cpu)
        met2 = jax.device_put(met2, cpu)
        if os.environ.get("SCALE_MERGED_POLISH", "1") == "1":
            for w in range(3):
                mesh2, pc = sliver_polish(
                    mesh2, met2, jnp.asarray(3000 + w, jnp.int32))
                pcn = np.asarray(pc)
                if int(pcn[0]) == 0 and int(pcn[1]) == 0:
                    break
    phases["merged_polish"] = time.perf_counter() - t0

    # sequential tail repair (host, O(bad tets)) — the production
    # driver's _finish_run role; runs on CPU views
    t0 = time.perf_counter()
    with jax.default_device(cpu):
        mesh2, _nrep = repair_mesh(mesh2, met2)
    phases["repair_tail"] = time.perf_counter() - t0

    t0 = time.perf_counter()
    tm = np.asarray(mesh2.tmask)
    with jax.default_device(cpu):       # full-width program: CPU compile
        mesh2c = jax.device_put(mesh2, cpu)
        q = np.asarray(tet_quality(mesh2c, jax.device_put(met2, cpu)))[tm]
    phases["quality_pull"] = time.perf_counter() - t0

    # throughput accounting mirrors bench.py: live tets examined per
    # cycle / adapt wall seconds.  The first-pass number INCLUDES the
    # one-time compile of the group program (reported separately as the
    # steady rate can't be isolated without a second pass at this size).
    examined = stats.cycles * ntet0        # lower bound (mesh only grows)
    rate = examined / max(phases["grouped_adapt"], 1e-9) / 1e6
    print(json.dumps({
        "metric": "grouped_scale_throughput",
        "value": round(rate, 4),
        "unit": "Mtets/sec/chip (incl. one-time compile)",
        "extra": {
            "niter": int(os.environ.get("SCALE_NITER", "2")),
            "ntets_initial": int(ntet0),
            "ntets_final": int(tm.sum()),
            "ngroups": int(ngroups),
            "cycles": int(stats.cycles),
            "ops": [stats.nsplit, stats.ncollapse, stats.nswap,
                    stats.nmoved],
            "qmin": round(float(q.min()), 4) if tm.any() else 0.0,
            "qmean": round(float(q.mean()), 4) if tm.any() else 0.0,
            "phases_s": {k: round(v, 2) for k, v in phases.items()},
            "device": str(jax.devices()[0].platform),
        },
    }))


if __name__ == "__main__":
    main()
