"""Per-wave phase timing on the tunneled TPU (or CPU).

The cycle-level numbers (block_time.py) say ~600 ms/cycle at bench shapes
but the known primitives (adjacency 42 ms, edge table 14 ms, scatters
~9 ms) sum to a fraction of that — this script closes the attribution gap
by timing each WAVE KERNEL separately, K reps fused in one jitted
fori_loop with the mesh chained through the carry (same transport-
amortization trick as tpu_microbench.py).

Because every wave is shape-static, its cost is a function of the
capacities, not of how many ops actually apply — chaining reps is
representative even when later reps find nothing to do.

Run: python scripts/wave_time.py [N] (default 16 = bench shape)
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp
import numpy as np

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.adjacency import build_adjacency, boundary_edge_tags
from parmmg_tpu.ops.split import split_wave
from parmmg_tpu.ops.collapse import collapse_wave
from parmmg_tpu.ops.swap import swap_edges_wave, swap23_wave
from parmmg_tpu.ops.smooth import smooth_wave
from parmmg_tpu.ops.edges import unique_edges, edge_lengths
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric

K = int(os.environ.get("WT_REPS", "10"))


def timed(name, body, mesh, met):
    def loop(mesh, met):
        def it(_, mk):
            m, k = mk
            return body(m, k)
        return jax.lax.fori_loop(0, K, it, (mesh, met))

    f = jax.jit(loop, donate_argnums=())
    r = f(mesh, met)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    r = f(mesh, met)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / K
    print(f"{name:22s} {dt * 1e3:9.2f} ms/wave   ({K} reps fused)")
    return dt


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    print(f"N={n} capP={mesh.capP} capT={mesh.capT} "
          f"device={jax.default_backend()}")

    total = {}
    # edge table / lengths return no Mesh: chain a zero-valued data
    # dependency through the metric so the loop carry stays (Mesh, met)
    total["edge_table"] = timed(
        "edge_table", lambda m, k: (
            m, k + 0.0 * unique_edges(m).nshell[0]), mesh, met)
    total["edge_tab+len"] = timed(
        "edge_table+lengths", lambda m, k: (
            m, k + 0.0 * edge_lengths(m, unique_edges(m), k)[0]),
        mesh, met)
    total["adjacency"] = timed(
        "adjacency", lambda m, k: (build_adjacency(m), k), mesh, met)
    total["bdy_edge_tags"] = timed(
        "boundary_edge_tags", lambda m, k: (boundary_edge_tags(m), k),
        mesh, met)
    total["split"] = timed(
        "split_wave", lambda m, k: (lambda r: (r.mesh, r.met))(
            split_wave(m, k)), mesh, met)
    total["collapse"] = timed(
        "collapse_wave", lambda m, k: (collapse_wave(m, k).mesh, k),
        mesh, met)
    total["swap_edges"] = timed(
        "swap_edges(3-2,2-2)", lambda m, k: (swap_edges_wave(m, k).mesh, k),
        mesh, met)
    total["swap23"] = timed(
        "swap23(needs adja)", lambda m, k: (
            swap23_wave(build_adjacency(m), k).mesh, k), mesh, met)
    total["smooth"] = timed(
        "smooth_wave", lambda m, k: (
            smooth_wave(m, k, wave=jnp.asarray(0, jnp.int32)).mesh, k),
        mesh, met)

    # reference composition: one light cycle = split + bdy_tags + collapse
    # + 2x smooth; one full cycle adds swaps + adjacency
    light = (total["split"] + total["collapse"] + total["bdy_edge_tags"]
             + 2 * total["smooth"])
    full = light + total["swap_edges"] + total["swap23"]
    print(f"\ncomposed light cycle ~ {light * 1e3:.1f} ms, "
          f"full cycle ~ {full * 1e3:.1f} ms")


if __name__ == "__main__":
    main()
