"""Serving-daemon gate (scripts/run_tests.sh --serve).

End-to-end over localhost HTTP, in one process (so the compile ledger
is shared and the zero-new-family assertion has teeth):

1. stage 2 small tenants (the warm-pool fixture shapes: cube 2 + cube
   3, the same ladder buckets every other gate compiles) and run each
   standalone ``grouped_adapt_pass(ngroups=1)`` — the parity reference
   AND the warmup that compiles every ``groups.*`` family serving may
   touch;
2. start a PoolDaemon on an ephemeral port, submit both tenants as raw
   arrays through ServeClient (base64 npz), wait, fetch;
3. assert: both served; each fetched result BIT-IDENTICAL to its
   standalone run (mesh fields + metric — the staging rule is shared,
   so parity is by construction testable); daemon serving added ZERO
   ``groups.*`` compile families after the standalone warmup; /healthz
   live; /metrics parses as Prometheus exposition; clean shutdown
   (threads joined).

Exit 0 green / 1 red.  CPU backend, axon factory dropped
(ledger_check.py sequence).
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
os.environ.pop("PARMMG_FAULT", None)

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

FAILS: list[str] = []


def check(ok: bool, msg: str) -> None:
    tag = "ok" if ok else "SERVE GATE FAIL"
    print(f"  {tag}: {msg}", file=sys.stdout if ok else sys.stderr)
    if not ok:
        FAILS.append(msg)


def main() -> int:
    from parmmg_tpu.core.mesh import MESH_FIELDS
    from parmmg_tpu.obs.metrics import parse_prometheus
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.serve.admission import stage_arrays
    from parmmg_tpu.serve.client import ServeClient
    from parmmg_tpu.serve.daemon import PoolDaemon
    from parmmg_tpu.utils.compilecache import (reset_ledger,
                                               variants_by_prefix)
    from parmmg_tpu.utils.fixtures import cube_mesh

    cycles = 2
    classes = ((2, 0.55), (3, 0.5))

    # ---- 1. standalone warmup + parity references -----------------------
    print("--- serve gate: standalone warmup (parity references)")
    reset_ledger()
    raw = {}
    refs = {}
    for n, h in classes:
        vert, tet = cube_mesh(n)
        met = np.full(4 * len(vert), h)     # full-capP metric, h pads
        raw[n] = (vert, tet, met)
        mesh, met_s = stage_arrays(vert, tet, met=met)
        out, met_m, _ = grouped_adapt_pass(mesh, met_s, 1, cycles=cycles)
        jax.block_until_ready(out.vert)
        refs[n] = (out, np.asarray(met_m))
    v0 = variants_by_prefix("groups.")
    check(v0.get("groups.adapt_block", 0) >= 1,
          "warmup exercises groups.adapt_block")

    # ---- 2. daemon serving over localhost HTTP --------------------------
    print("--- serve gate: daemon round-trip (2 tenants over HTTP)")
    daemon = PoolDaemon(port=0, slots_per_bucket=2, chunk=1,
                        cycles=cycles)
    daemon.start()
    try:
        cl = ServeClient(port=daemon.port)
        check(cl.health().get("ok") is True, "daemon /healthz live")
        tids = {}
        for n, h in classes:
            vert, tet, met = raw[n]
            tids[n] = cl.submit(vert=vert, tet=tet, met=met,
                                tenant=f"n{n}")
        for n in tids:
            got = cl.wait(tids[n], timeout_s=600)
            check(got["state"] == "done",
                  f"tenant n{n} served ({got['state']}: "
                  f"{got.get('reason', '')})")

        # ---- 3. bit-for-bit parity vs the standalone runs ---------------
        for n, _h in classes:
            arrays = cl.fetch(tids[n])
            ref, kref = refs[n]
            ok = all(
                (arrays[f] == np.asarray(getattr(ref, f))).all()
                for f in MESH_FIELDS) and (arrays["met"] == kref).all()
            check(ok, f"tenant n{n} fetched result bit-identical to "
                      "its standalone grouped run")

        v1 = variants_by_prefix("groups.")
        check(v1 == v0, f"daemon serving added zero groups.* compile "
                        f"families ({v0} -> {v1})")
        series = parse_prometheus(cl.metrics_text())
        check(any(name == "parmmg_serve_dispatches_total"
                  for name, _ in series),
              "/metrics exposes serve counters in Prometheus text")
        rep = cl.report()
        check(rep["served"] == len(classes) and rep["failed"] == 0,
              f"daemon report: {rep['served']} served, "
              f"{rep['failed']} failed")
    finally:
        daemon.shutdown()
    check(not daemon.alive(), "daemon threads joined on shutdown")

    if FAILS:
        print(f"\nserve gate FAILED ({len(FAILS)} checks):",
              file=sys.stderr)
        for f in FAILS:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("\nserve OK: daemon served both tenants bit-identical to "
          "standalone with zero new compile families, clean shutdown")
    return 0


if __name__ == "__main__":
    sys.exit(main())
