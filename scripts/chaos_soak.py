"""Seeded chaos-soak harness (run_tests.sh --chaos rides a smoke of it).

``chaos_check.py`` proves each fault site lands on its documented
ladder rung ONCE, in a hand-picked order.  Production failure is not
hand-picked: faults arrive in random sites, random order, crash and
hang shapes mixed.  This harness drives N seeded runs, each with a
fault schedule drawn from the FULL ``faults.SITES`` registry
(including the PR 15 ``hang=S`` action, watchdog-deadline armed), and
asserts the bounded-time graded-failure contract per run:

- the run ends (no hang escapes the watchdog/timeout net) in either
  full success or a clean ``PMMG_LOWFAILURE`` with a conforming mesh
  (positive volumes summing to the cube);
- BIT-PARITY with the fault-free oracle whenever the schedule's
  expectation is a bit-identical rung (transient retries,
  mh_allgather, halo_dense, merged_polish-vs-polish-less, host
  analysis) — degraded never means drifted;
- no leaked ``parmmg_*`` staging in the temp dir;
- ZERO new ``groups.*`` compile families after the fault-free warmup
  — injected faults must never key fresh programs.

The schedule is a PURE function of (seed, runs): ``build_schedule``
is stdlib-only and importable without jax (tier-1 determinism test),
so any soak failure replays exactly from its seed.

Usage: python scripts/chaos_soak.py [--runs N] [--seed S] [--out PATH]
Knobs: PARMMG_SOAK_RUNS / PARMMG_SOAK_SEED (CLI defaults).
Prints ONE canonical SOAK artifact JSON line; exit 1 on any failure.
"""
from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
from contextlib import contextmanager

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

TARGET = 16          # cube_mesh(2) = 48 tets -> 3 groups
CYCLES = 2
NITER = 2

# ---------------------------------------------------------------------------
# the pure schedule builder (stdlib-only: no jax, no numpy)
# ---------------------------------------------------------------------------
# expectation vocabulary:
#   parity      — bit-identical to the runner's fault-free oracle
#   nopolish    — bit-identical to the polish-LESS pass oracle
#   lowfailure  — driver returns PMMG_LOWFAILURE with a conforming mesh
#   quarantine  — tenant t1 retired FAILED; cohort-mates bit-identical
_MENU: tuple[dict, ...] = (
    {"runner": "grouped", "site": "dispatch.chunk",
     "fault": "dispatch.chunk:nth-{n}",
     "env": {"PARMMG_RETRY_MAX": "2"}, "expect": "parity"},
    {"runner": "grouped", "site": "dispatch.chunk",
     "fault": "dispatch.chunk:every-{n1}",
     "env": {"PARMMG_RETRY_MAX": "2"}, "expect": "parity"},
    {"runner": "grouped", "site": "dispatch.chunk",
     "fault": "dispatch.chunk:hang=2;nth-1",
     "env": {"PARMMG_RETRY_MAX": "2",
             "PARMMG_DEADLINE_DISPATCH_S": "0.5",
             "PARMMG_DEADLINE_GRACE_S": "0"}, "expect": "parity"},
    {"runner": "driver", "site": "dispatch.chunk",
     "fault": "dispatch.chunk",
     "env": {"PARMMG_RETRY_MAX": "1"}, "expect": "lowfailure"},
    {"runner": "grouped_ckpt", "site": "io.checkpoint",
     "fault": "io.checkpoint",
     "env": {"PARMMG_RETRY_MAX": "2"}, "expect": "parity"},
    {"runner": "dist", "site": "multihost.exchange",
     "fault": "multihost.exchange:nth-{n}",
     "env": {"PARMMG_RETRY_MAX": "2"}, "expect": "parity"},
    {"runner": "dist", "site": "multihost.exchange",
     "fault": "multihost.exchange",
     "env": {"PARMMG_RETRY_MAX": "0"}, "expect": "parity"},
    {"runner": "dist", "site": "multihost.exchange",
     "fault": "multihost.exchange:hang=2;nth-1",
     "env": {"PARMMG_RETRY_MAX": "2",
             "PARMMG_DEADLINE_EXCHANGE_S": "0.5",
             "PARMMG_DEADLINE_GRACE_S": "0"}, "expect": "parity"},
    {"runner": "dist", "site": "analysis.ks_overflow",
     "fault": "analysis.ks_overflow:nth-{n}",
     "env": {}, "expect": "parity"},
    {"runner": "dist", "site": "halo.exchange",
     "fault": "halo.exchange:nth-1",
     "env": {"PARMMG_RETRY_MAX": "2"}, "expect": "parity"},
    {"runner": "polish", "site": "polish.worker",
     "fault": "polish.worker",
     "env": {"PARMMG_RETRY_MAX": "1", "PARMMG_POLISH_SUBPROC": "1"},
     "expect": "nopolish"},
    {"runner": "polish", "site": "polish.worker",
     "fault": "polish.worker:hang=30",
     "env": {"PARMMG_RETRY_MAX": "1", "PARMMG_POLISH_SUBPROC": "1",
             "PARMMG_POLISH_TIMEOUT_S": "2"}, "expect": "nopolish"},
    {"runner": "serve", "site": "serve.slot_step",
     "fault": "serve.slot_step:key=t1;nth-1",
     "env": {"PARMMG_SERVE_MAX_RETRIES": "2"}, "expect": "parity"},
    {"runner": "serve", "site": "serve.slot_step",
     "fault": "serve.slot_step:key=t1",
     "env": {"PARMMG_SERVE_MAX_RETRIES": "2"}, "expect": "quarantine"},
    {"runner": "daemon", "site": "serve.daemon_rpc",
     "fault": "serve.daemon_rpc:key=t1",
     "env": {}, "expect": "quarantine"},
)


def sites_in_menu() -> tuple[str, ...]:
    return tuple(sorted({m["site"] for m in _MENU}))


def build_schedule(seed: int, runs: int) -> list[dict]:
    """The campaign plan: a pure function of (seed, runs).  Every
    iteration consumes exactly two rng draws, so schedules are stable
    under menu-order-preserving edits and trivially replayable."""
    rng = random.Random(int(seed))
    sched = []
    for i in range(int(runs)):
        t = rng.choice(_MENU)
        n = rng.randint(1, 3)
        sched.append({
            "run": i,
            "runner": t["runner"],
            "site": t["site"],
            "fault": t["fault"].format(n=n, n1=n + 1),
            "env": dict(t["env"]),
            "expect": t["expect"],
        })
    return sched


# ---------------------------------------------------------------------------
# campaign execution (jax from here on)
# ---------------------------------------------------------------------------
def setup_env() -> None:
    """Process env for an in-process campaign (idempotent; matches the
    chaos gate's setup so the soak smoke can ride its warm programs)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=2").strip()
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
    for k in ("PARMMG_FAULT", "PARMMG_CKPT_DIR", "PARMMG_TRACE"):
        os.environ.pop(k, None)
    os.environ["PARMMG_GROUP_CHUNK"] = "2"
    os.environ.setdefault("PARMMG_RETRY_BASE_S", "0")


@contextmanager
def _env(**kv):
    """Scoped env knobs + fault-registry reset on entry AND exit."""
    from parmmg_tpu.resilience.faults import FAULTS
    old = {k: os.environ.get(k) for k in kv}
    for k, v in kv.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    FAULTS.reset()
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        FAULTS.reset()


def run_campaign(seed: int, runs: int, say=print) -> dict:
    """Execute the seeded campaign; returns the SOAK artifact doc with
    ``extra.failures`` (empty == soak clean)."""
    setup_env()
    import numpy as np
    import jax.numpy as jnp

    from parmmg_tpu.api.parmesh import ParMesh
    from parmmg_tpu.core import constants as C
    from parmmg_tpu.core.mesh import MESH_FIELDS, make_mesh, tet_volumes
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.parallel.dist import distributed_adapt_multi
    from parmmg_tpu.parallel.groups import grouped_adapt, \
        grouped_adapt_pass
    from parmmg_tpu.serve.driver import ServeDriver
    from parmmg_tpu.utils.compilecache import variants_by_prefix
    from parmmg_tpu.utils.fixtures import cube_mesh

    def fresh_case():
        vert, tet = cube_mesh(2)
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.full(m.capP, 0.35, m.vert.dtype)
        return m, met

    def state_bytes(mesh, met):
        return tuple(np.asarray(getattr(mesh, f)).tobytes()
                     for f in MESH_FIELDS) + (np.asarray(met).tobytes(),)

    def run_grouped(**kw):
        m, met = fresh_case()
        out, met_m = grouped_adapt(m, met, TARGET, niter=NITER,
                                   cycles=CYCLES, **kw)
        return state_bytes(out, met_m)

    def run_dist():
        m, met = fresh_case()
        out, met_m, _ = distributed_adapt_multi(m, met, 2, niter=NITER,
                                                cycles=CYCLES)
        return state_bytes(out, met_m)

    def run_pass(polish):
        m, met = fresh_case()
        out, met_m, _ = grouped_adapt_pass(m, met, 3, cycles=CYCLES,
                                           polish=polish)
        return state_bytes(out, met_m)

    def staged_pm():
        vert, tet = cube_mesh(2)
        pm = ParMesh()
        pm.set_mesh_size(len(vert), len(tet))
        pm.set_vertices(vert, np.zeros(len(vert), np.int32))
        pm.set_tetrahedra(tet + 1, np.ones(len(tet), np.int32))
        pm.info.hsiz = 0.35
        pm.info.niter = 1
        pm.info.imprim = -1
        pm.info.target_mesh_size = TARGET
        pm.info.noinsert = pm.info.noswap = pm.info.nomove = True
        return pm

    def conforming(mesh) -> bool:
        tm = np.asarray(mesh.tmask)
        vols = np.asarray(tet_volumes(mesh))[tm]
        return bool(tm.sum() > 0 and (vols > 0).all()
                    and np.isclose(vols.sum(), 1.0, rtol=1e-5))

    def run_pool():
        drv = ServeDriver(slots_per_bucket=3, chunk=2, cycles=CYCLES)
        for t in ("t0", "t1", "t2"):
            m, met = fresh_case()
            drv.submit(mesh=m, met=met, tenant=t)
        rep = drv.run()
        outs = {}
        for t in ("t0", "t1", "t2"):
            if rep["tenants"][t]["state"] == "done":
                outs[t] = state_bytes(*drv.fetch(t))
        return rep, outs

    def run_daemon(fault_spec):
        # the serve.daemon_rpc shape needs the HTTP edge: pause the
        # loop, admit 3 tenants, arm the fault around a mid-flight
        # poll of t1 (mirrors the chaos gate's scenario) — the daemon
        # must survive, t1 alone quarantined
        from parmmg_tpu.serve.client import ServeClient, ServeDaemonError
        from parmmg_tpu.serve.daemon import PoolDaemon
        vert, tet = cube_mesh(2)
        met_full = np.full(4 * len(vert), 0.35)
        d = PoolDaemon(port=0, slots_per_bucket=3, chunk=2,
                       cycles=CYCLES, start_paused=True)
        d.start()
        outs = {}
        probs = []
        try:
            cl = ServeClient(port=d.port)
            for t in ("t0", "t1", "t2"):
                cl.submit(vert=vert, tet=tet, met=met_full, tenant=t)
            cl.step()
            with _env(PARMMG_FAULT=fault_spec):
                try:
                    cl.poll("t1")
                    probs.append("armed daemon_rpc fault did not fire")
                except ServeDaemonError as e:
                    if not (e.status == 500
                            and e.body.get("quarantined") is True):
                        probs.append(f"rpc fault shape wrong: {e}")
            if cl.health().get("ok") is not True:
                probs.append("daemon died with the faulted request")
            cl.resume()
            for t in ("t0", "t2"):
                got = cl.wait(t, timeout_s=600)
                if got["state"] != "done":
                    probs.append(f"cohort tenant {t}: {got['state']}")
                    continue
                arrays = cl.fetch(t)
                outs[t] = tuple(arrays[f].tobytes()
                                for f in MESH_FIELDS) \
                    + (arrays["met"].tobytes(),)
            rep = cl.report()
            if rep["tenants"]["t1"]["state"] != "failed":
                probs.append("t1 not retired FAILED")
        finally:
            d.shutdown()
        return probs, outs

    # ---- fault-free warmup: every runner's oracle + compile baseline ---
    say(f"soak: warmup (oracles for {len(_MENU)} menu entries)")
    base_g = run_grouped()
    base_d = run_dist()
    ref_nopol = run_pass(False)
    pm0 = staged_pm()
    rc0 = pm0.run()
    assert rc0 == C.PMMG_SUCCESS, f"warmup driver run rc={rc0}"
    rep_a, outs_a = run_pool()
    assert rep_a["served"] == 3, "warmup pool must serve 3"
    def live_groups():
        # drop zero-variant keys: a runner REGISTERING a governed
        # family it never compiled (the killed polish worker leaves
        # groups.polish_block at 0) is bookkeeping, not compile growth
        return {k: v for k, v in variants_by_prefix("groups.").items()
                if v}

    v0 = live_groups()
    tmp0 = {e for e in os.listdir(tempfile.gettempdir())
            if e.startswith("parmmg_")}
    oracles = {"grouped": base_g, "grouped_ckpt": base_g,
               "dist": base_d}

    sched = build_schedule(seed, runs)
    failures: list[str] = []
    records: list[dict] = []
    for spec in sched:
        tag = (f"run {spec['run']} [{spec['runner']}] "
               f"{spec['fault']} -> {spec['expect']}")
        say(f"soak: {tag}")
        probs: list[str] = []
        kv = dict(spec["env"])
        kv["PARMMG_FAULT"] = spec["fault"]
        try:
            if spec["runner"] in ("grouped", "dist"):
                with _env(**kv):
                    got = run_grouped() if spec["runner"] == "grouped" \
                        else run_dist()
                if got != oracles[spec["runner"]]:
                    probs.append("bit-parity with fault-free oracle")
            elif spec["runner"] == "grouped_ckpt":
                with tempfile.TemporaryDirectory() as td, \
                        _env(PARMMG_CKPT_DIR=td, **kv):
                    got = run_grouped(ckpt_tag=f"soak{spec['run']}")
                    left = [f for f in os.listdir(td)
                            if f.endswith(".npz")]
                if got != oracles["grouped_ckpt"]:
                    probs.append("bit-parity under checkpoint IO fault")
                if spec["site"] == "io.checkpoint" and left:
                    probs.append(f"partial checkpoint survived: {left}")
            elif spec["runner"] == "driver":
                with _env(**kv):
                    pm = staged_pm()
                    ret = pm.run()
                if ret != C.PMMG_LOWFAILURE:
                    probs.append(f"expected PMMG_LOWFAILURE, rc={ret}")
                elif not conforming(pm._out):
                    probs.append("LOWFAILURE output not conforming")
            elif spec["runner"] == "polish":
                with _env(**kv):
                    got = run_pass(True)
                if got != ref_nopol:
                    probs.append("degrade != polish-less pass bits")
            elif spec["runner"] == "serve":
                with _env(**kv):
                    rep, outs = run_pool()
                if spec["expect"] == "parity":
                    if not (rep["served"] == 3 and outs == outs_a):
                        probs.append("transient serve fault parity")
                else:
                    if rep["tenants"]["t1"]["state"] != "failed":
                        probs.append("t1 not quarantined")
                    if not (outs.get("t0") == outs_a["t0"]
                            and outs.get("t2") == outs_a["t2"]):
                        probs.append("cohort parity after quarantine")
            elif spec["runner"] == "daemon":
                probs, outs = run_daemon(spec["fault"])
                if not (outs.get("t0") == outs_a["t0"]
                        and outs.get("t2") == outs_a["t2"]):
                    probs.append("daemon cohort parity")
            else:
                probs.append(f"unknown runner {spec['runner']!r}")
        except Exception as e:                    # noqa: BLE001
            probs.append(f"escaped exception {e!r:.300}")
        # per-run hygiene: staging leaks + compile-family neutrality
        leaks = [e for e in os.listdir(tempfile.gettempdir())
                 if e.startswith("parmmg_") and e not in tmp0]
        if leaks:
            probs.append(f"tmp leak {leaks}")
        v1 = live_groups()
        if v1 != v0:
            probs.append(f"new groups.* compile families {v0} -> {v1}")
            v0 = v1          # report each regression once
        records.append({**spec, "ok": not probs, "problems": probs})
        for p in probs:
            failures.append(f"{tag}: {p}")
            say(f"soak FAIL: {tag}: {p}")

    from parmmg_tpu.obs.artifact import make_artifact
    doc = make_artifact(
        "SOAK", metric="soak_runs", value=float(len(sched)),
        unit="runs",
        extra={
            "seed": int(seed),
            "runs": int(runs),
            "sites_covered": list(sites_in_menu()),
            "failed": len(failures),
            "failures": failures,
            "schedule": records,
        })
    return doc


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", type=int, default=int(
        os.environ.get("PARMMG_SOAK_RUNS", "8") or 8))
    ap.add_argument("--seed", type=int, default=int(
        os.environ.get("PARMMG_SOAK_SEED", "20260804") or 20260804))
    ap.add_argument("--out", default="")
    args = ap.parse_args()

    def say(msg):
        print(msg, file=sys.stderr, flush=True)

    doc = run_campaign(args.seed, args.runs, say=say)
    payload = json.dumps(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    sys.stdout.write(payload + "\n")
    nfail = doc["extra"]["failed"]
    if nfail:
        say(f"soak FAILED: {nfail} problems over "
            f"{doc['extra']['runs']} runs (seed {doc['extra']['seed']})")
        return 1
    say(f"soak OK: {doc['extra']['runs']} seeded runs, "
        f"{len(doc['extra']['sites_covered'])} fault sites, zero "
        "escapes")
    return 0


if __name__ == "__main__":
    sys.exit(main())
