"""Multi-host pod runner (multi-host step 2: the pod runtime).

Spawns NP jax.distributed processes on this host (virtual CPU devices,
``xla_force_host_platform_device_count``; cross-process collectives via
gloo, knob PARMMG_MH_COLLECTIVES), each running the IDENTICAL
``distributed_adapt_multi`` driver on the same input — the SPMD host
idiom of the reference's MPI program.  Band-table replication rides the
pod runtime's compiled exchange (``pod.gather_band``); the hot loop is
asserted allgather-free (``mh.hot_allgather_bytes == 0``).

Phase structure (the parent process):

1. ``--parity``: a single-process REFERENCE run of the same scenario in
   its own subprocess — the bit-parity oracle for ``extra.parity_ok``
   (and the 1-process seconds datapoint).
2. warm: unless the shared compile cache (PARMMG_MH_CACHE_DIR, default
   ``<repo>/.jax_cache_mh``) already holds this scenario's programs
   (marker file), run the NP-process scenario once to populate it —
   the one concurrent-compile cost (the whole MULTIHOST2P_r04 656 s
   story), paid once per scenario per cache.
3. timed run: NP processes over the warm cache.  Process 0 emits the
   canonical MULTIHOST artifact (obs/artifact.py) with per-phase trace
   spans; EVERY worker reports seconds / result hash / backend-compile
   seconds / ``mh.*`` counters through a JSON sidecar the parent merges
   into ``extra.workers`` — the "worker N+1 pays ~zero compiles"
   evidence.

Worker crash is the EXPECTED failure mode at pod scale: on a non-zero
worker exit the parent kills the survivors (a dead rank stalls the
collectives) and, when ``--ckpt`` is set, relaunches the run with
``resume=True`` — it re-enters at the pass after the newest per-pass
checkpoint and must finish bit-identical (`scripts/multihost_check.py`
asserts it).

A wedged worker is the same failure without the exit code: under
``--lease S`` each worker beats a per-rank heartbeat file (inside
``multihost.hot_path`` / ``pod.gather_band``; knob PARMMG_HEARTBEAT_S)
and the parent holds a lease per worker — a rank that has beaten once
and then goes silent for S seconds gets the whole pack killed (rc 9)
and the same checkpoint/resume relaunch.  A rank that never beat is
never stale: startup + cold compile are covered by ``--timeout``.

Usage: python scripts/multihost_run.py [--np 2] [--devices 4] [--n 4]
           [--niter 2] [--cycles 4] [--parity] [--no-warm]
           [--cache DIR] [--ckpt DIR] [--lease S]
           [--fault PID:SPEC] [--out PATH]
Prints ONE canonical artifact JSON line (stdout) from the parent.

Kept out of the default test matrix: ``run_tests.sh --multihost``
(scripts/multihost_check.py) runs the gated small scenario.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# worker
# ---------------------------------------------------------------------------
def worker() -> None:
    import numpy as np
    import jax

    pid = int(os.environ.get("JAX_PROCESS_ID", "0"))
    np_proc = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    n = int(os.environ["MH_N"])
    ndev = int(os.environ["MH_DEVICES"])
    niter = int(os.environ.get("MH_NITER", "2"))
    cycles = int(os.environ.get("MH_CYCLES", "4"))
    resume = os.environ.get("MH_RESUME", "") == "1"
    log = open(f"/tmp/parmmg_mh_{pid}.log", "w")

    def say(msg):
        print(msg, file=log, flush=True)
        if pid == 0:
            print(msg, file=sys.stderr, flush=True)

    t0 = time.time()
    from parmmg_tpu.parallel.multihost import init_multihost
    inited = init_multihost()
    if np_proc > 1:
        assert inited, "jax.distributed must initialize"
    say(f"[p{pid}] initialized: {jax.process_count()} processes, "
        f"{jax.device_count()} global / {jax.local_device_count()} "
        f"local devices ({time.time() - t0:.1f}s)")
    assert jax.process_count() == np_proc

    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import MESH_FIELDS, make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.ops.quality import tet_quality
    from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric
    from parmmg_tpu.parallel.dist import distributed_adapt_multi

    # identical input on every process (the deterministic-host contract)
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.8 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    say(f"[p{pid}] input: {len(tet)} tets -> {ndev} shards on "
        f"{np_proc} processes")

    t1 = time.time()
    out, met_m, part = distributed_adapt_multi(
        mesh, met, ndev, niter=niter, cycles=cycles, verbose=2,
        ckpt_tag=("mh" if os.environ.get("PARMMG_CKPT_DIR") else None),
        resume=resume)
    dt = time.time() - t1
    tm = np.asarray(out.tmask)
    q = np.asarray(tet_quality(out, met_m))[tm]
    hsh = hashlib.blake2b(digest_size=16)
    for f in MESH_FIELDS:
        hsh.update(np.ascontiguousarray(np.asarray(getattr(out, f)))
                   .tobytes())
    hsh.update(np.ascontiguousarray(np.asarray(met_m)).tobytes())
    digest = hsh.hexdigest()

    from parmmg_tpu.obs.metrics import REGISTRY
    from parmmg_tpu.utils.compilecache import LEDGER
    snap = LEDGER.snapshot()
    counters = REGISTRY.snapshot()["counters"]
    wrk = {
        "pid": pid,
        "seconds": round(dt, 1),
        "hash": digest,
        "compiles": int(sum(r["compiles"] for r in snap.values())),
        "compile_s": round(sum(r["compile_s"] for r in snap.values()),
                           2),
        "hot_allgather_bytes": counters.get("mh.hot_allgather_bytes",
                                            0),
        "allgather_bytes": counters.get("mh.allgather_bytes", 0),
        "band_exchange_bytes": counters.get("mh.band_exchange_bytes",
                                            0),
    }
    side = os.environ.get("MH_SIDECAR", "")
    if side:
        with open(side, "w") as f:
            json.dump(wrk, f)
    res = {
        "processes": np_proc,
        "devices": ndev,
        "ntets_in": int(len(tet)),
        "ntets_out": int(tm.sum()),
        "qmin": round(float(q.min()), 4),
        "qmean": round(float(q.mean()), 4),
        "niter": niter,
        "seconds": round(dt, 1),
        "hash": digest,
        "resumed": bool(resume),
        "pipeline": "split->adapt->band-exchange-migrate->weld->merge",
    }
    say(f"[p{pid}] done: {json.dumps(res)}")
    if pid == 0:
        # canonical schema-versioned artifact (obs/artifact.py) — the
        # legacy result dict rides in extra; per-phase spans ride the
        # trace digest (dist.adapt/refresh/migrate/merge)
        from parmmg_tpu.obs.artifact import make_artifact
        print(json.dumps(make_artifact(
            "MULTIHOST", metric="multihost_adapt",
            value=res["seconds"], unit="s", extra=res)))
    log.close()


# ---------------------------------------------------------------------------
# parent
# ---------------------------------------------------------------------------
def launch(args, np_proc: int, tmpdir: str, resume: bool = False,
           fault: tuple[int, str] | None = None,
           tag: str = "run") -> tuple[int, bytes, list, dict]:
    """One phase: spawn np_proc workers, kill the pack on the first
    non-zero exit (a dead rank stalls the survivors' collectives) OR
    on an expired heartbeat lease (--lease: a WEDGED rank stalls them
    just the same, without the courtesy of exiting), return (rc,
    proc-0 stdout, worker sidecars, supervision info)."""
    # stdlib-only module (resilience/watchdog.py): safe in this parent
    # process, which must never import jax
    from parmmg_tpu.resilience.watchdog import stale_ranks
    port = free_port()
    procs = []
    sidecars = []
    info: dict = {}
    lease = float(getattr(args, "lease", 0) or 0)
    hb_dir = os.path.join(tmpdir, f"hb.{tag}")
    for pid in range(np_proc):
        side = os.path.join(tmpdir, f"{tag}.w{pid}.json")
        sidecars.append(side)
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count="
                          f"{args.devices // np_proc}").strip(),
            "MH_WORKER": "1",
            "MH_N": str(args.n),
            "MH_DEVICES": str(args.devices),
            "MH_NITER": str(args.niter),
            "MH_CYCLES": str(args.cycles),
            "MH_SIDECAR": side,
            "PARMMG_MH_CACHE_DIR": args.cache,
            # drop any sitecustomize TPU-tunnel backend: compiles must
            # stay process-local on the CPU backend
            "PYTHONPATH": _repo_root(),
        })
        if np_proc > 1:
            env.update({
                "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
                "JAX_NUM_PROCESSES": str(np_proc),
                "JAX_PROCESS_ID": str(pid),
            })
        else:
            env["JAX_NUM_PROCESSES"] = "1"
            env.pop("JAX_COORDINATOR_ADDRESS", None)
        if args.ckpt:
            env["PARMMG_CKPT_DIR"] = args.ckpt
        if resume:
            env["MH_RESUME"] = "1"
        if lease > 0:
            # arm the per-rank heartbeat files this supervisor's lease
            # reads (workers beat inside hot_path / gather_band)
            env["PARMMG_MH_HEARTBEAT_DIR"] = hb_dir
        if fault is not None and fault[0] == pid:
            env["PARMMG_FAULT"] = fault[1]
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE if pid == 0 else subprocess.DEVNULL,
            stderr=sys.stderr if (pid == 0 and args.verbose)
            else subprocess.DEVNULL))
    rc = 0
    deadline = time.time() + args.timeout
    live = set(range(np_proc))
    failed = False
    while live and time.time() < deadline:
        for pid in sorted(live):
            r = procs[pid].poll()
            if r is None:
                continue
            live.discard(pid)
            if r != 0:
                rc = rc or r
                failed = True
        if not failed and lease > 0 and live:
            stale = stale_ranks(hb_dir, lease, sorted(live))
            if stale:
                # a WEDGED rank is a crashed rank that forgot to exit:
                # its lease expired (no beat for --lease seconds after
                # its FIRST beat), so treat it exactly like a non-zero
                # exit — kill the pack, let the checkpoint/resume
                # ladder recover
                print(f"multihost_run: heartbeat lease expired for "
                      f"rank(s) {stale} ({tag}); killing the pack",
                      file=sys.stderr)
                info["stale_heartbeat"] = stale
                rc = rc or 9
                failed = True
        if failed and live:
            # a dead rank stalls the survivors' collectives: kill the
            # pack (the checkpoint/resume ladder is the recovery, not
            # waiting out a gloo timeout)
            time.sleep(2)
            for pid in sorted(live):
                procs[pid].kill()
        time.sleep(0.2)
    if live:
        for pid in sorted(live):
            procs[pid].kill()
        print(f"multihost_run: TIMEOUT ({tag})", file=sys.stderr)
        rc = rc or 2
    out0 = b""
    if procs[0].stdout is not None:
        out0 = procs[0].stdout.read() or b""
        procs[0].stdout.close()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
    return (rc, out0,
            [json.load(open(s)) if os.path.exists(s) else None
             for s in sidecars], info)


def warm_marker(args) -> str:
    return os.path.join(
        args.cache,
        f"warm.np{args.np}.d{args.devices}.n{args.n}"
        f".i{args.niter}.c{args.cycles}.ok")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=2)
    ap.add_argument("--devices", type=int, default=4)
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--niter", type=int, default=2)
    ap.add_argument("--cycles", type=int, default=4)
    ap.add_argument("--timeout", type=int, default=3600)
    ap.add_argument("--cache", default=os.environ.get(
        "PARMMG_MH_CACHE_DIR",
        os.path.join(_repo_root(), ".jax_cache_mh")))
    ap.add_argument("--ckpt", default="",
                    help="per-pass checkpoint dir (arms resume-on-"
                         "crash)")
    ap.add_argument("--lease", type=float,
                    default=float(os.environ.get(
                        "PARMMG_HEARTBEAT_LEASE_S", "0") or 0),
                    help="heartbeat lease seconds: kill the pack when "
                         "a worker that already beat stops beating "
                         "this long (0 = off)")
    ap.add_argument("--parity", action="store_true",
                    help="run the 1-process reference for parity_ok")
    ap.add_argument("--no-warm", action="store_true")
    ap.add_argument("--fault", default="",
                    help="PID:SPEC — arm PARMMG_FAULT=SPEC in that "
                         "worker only (crash drill)")
    ap.add_argument("--out", default="")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()
    os.makedirs(args.cache, exist_ok=True)
    tmpdir = tempfile.mkdtemp(prefix="parmmg_mh_")
    fault = None
    if args.fault:
        fpid, _, spec = args.fault.partition(":")
        fault = (int(fpid), spec)
    extra_parent: dict = {"cache_dir": args.cache}

    # ---- phase 1: 1-process parity reference ---------------------------
    ref_hash = None
    if args.parity:
        t0 = time.time()
        rc, out0, sides, _info = launch(args, 1, tmpdir, tag="ref")
        if rc != 0:
            print("multihost_run: reference run failed", file=sys.stderr)
            sys.exit(rc)
        ref = json.loads(out0.decode().strip().splitlines()[-1])
        ref_hash = ref["extra"]["hash"]
        extra_parent["ref_seconds"] = ref["extra"]["seconds"]
        extra_parent["ref_wall_s"] = round(time.time() - t0, 1)

    # ---- phase 2: warm the shared compile cache ------------------------
    marker = warm_marker(args)
    if not args.no_warm and not os.path.exists(marker):
        t0 = time.time()
        rc, _out, _s, _info = launch(args, args.np, tmpdir, tag="warm")
        if rc != 0:
            print("multihost_run: warm run failed", file=sys.stderr)
            sys.exit(rc)
        extra_parent["warm_s"] = round(time.time() - t0, 1)
        with open(marker, "w") as f:
            f.write("ok\n")

    # ---- phase 3: the timed pod run ------------------------------------
    t0 = time.time()
    rc, out0, sides, info = launch(args, args.np, tmpdir, fault=fault,
                                   tag="timed")
    if info.get("stale_heartbeat"):
        extra_parent["stale_heartbeat"] = info["stale_heartbeat"]
    if rc != 0 and args.ckpt:
        # worker crash drill: the EXPECTED pod failure mode — relaunch
        # from the newest per-pass checkpoint (fault disarmed: the
        # crash — or the lease-expiry pack kill — consumed it)
        extra_parent["crashed_rc"] = rc
        rc, out0, sides, _info = launch(args, args.np, tmpdir,
                                        resume=True, tag="resumed")
    if rc != 0:
        print("multihost_run: FAILED", file=sys.stderr)
        sys.exit(rc)
    doc = json.loads(out0.decode().strip().splitlines()[-1])
    doc["extra"]["wall_s"] = round(time.time() - t0, 1)
    doc["extra"]["workers"] = [s for s in sides if s]
    doc["extra"].update(extra_parent)
    if ref_hash is not None:
        doc["extra"]["parity_ok"] = bool(
            doc["extra"]["hash"] == ref_hash)
    # cross-artifact regression diff vs the newest MULTIHOST round of
    # the SAME scenario (a gate-sized run must not diff its ledger
    # against the big-toy artifact — different scenarios legitimately
    # compile different variant counts)
    import glob
    import re

    def rnum(p: str) -> int:
        m = re.search(r"r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    regs: list = []
    doc["extra"]["ledger_diff_vs"] = None
    arts = sorted(glob.glob(os.path.join(_repo_root(),
                                         "MULTIHOST2P_r*.json")),
                  key=rnum, reverse=True)
    for prev_path in arts:
        try:
            with open(prev_path) as f:
                prev = json.load(f)
        except Exception:
            continue
        pex = prev.get("extra", prev)
        if any(pex.get(k) != doc["extra"].get(k)
               for k in ("processes", "devices", "ntets_in", "niter")):
            continue
        from parmmg_tpu.utils.compilecache import (
            extract_artifact_ledger, ledger_diff)
        regs = ledger_diff(extract_artifact_ledger(prev),
                           doc["extra"].get("compile_ledger", {}))
        doc["extra"]["ledger_diff_vs"] = os.path.basename(prev_path)
        break
    doc["extra"]["ledger_regressions"] = regs
    payload = json.dumps(doc)
    if args.out:
        with open(args.out, "w") as f:
            f.write(payload + "\n")
    sys.stdout.write(payload + "\n")


if __name__ == "__main__":
    if os.environ.get("MH_WORKER") == "1":
        worker()
    else:
        main()
