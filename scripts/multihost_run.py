"""Two-process distributed adaptation demo (multi-host step 1).

Spawns NP jax.distributed processes on this host (virtual CPU devices,
``xla_force_host_platform_device_count``), each running the IDENTICAL
``distributed_adapt_multi`` driver on the same input — the SPMD host
idiom of the reference's MPI program (every rank executes libparmmg1.c's
loop; host decisions agree through collectives).  Device arrays are
global ('shard'-sharded across the processes), band-table host pulls
replicate through ``multihost.pull_host`` (DCN allgather), and the run
exercises the full split -> adapt -> band-migrate -> weld -> merge
pipeline with the single-process guards removed.

Usage:  python scripts/multihost_run.py [--np 2] [--devices 4] [--n 4]
Writes a per-process log to /tmp/parmmg_mh_<pid>.log and prints ONE
JSON summary line from process 0 (recorded as MULTIHOST2P_r04.json by
the round driver or by hand).

Kept out of the default test matrix: on a 1-core CI image two processes
compile the SPMD graph concurrently and starve each other (documented
in ROUND_NOTES round 3); run it manually or from a beefier driver.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def worker() -> None:
    import numpy as np
    import jax

    pid = int(os.environ["JAX_PROCESS_ID"])
    np_proc = int(os.environ["JAX_NUM_PROCESSES"])
    n = int(os.environ["MH_N"])
    ndev = int(os.environ["MH_DEVICES"])
    log = open(f"/tmp/parmmg_mh_{pid}.log", "w")

    def say(msg):
        print(msg, file=log, flush=True)
        if pid == 0:
            print(msg, file=sys.stderr, flush=True)

    t0 = time.time()
    from parmmg_tpu.parallel.multihost import init_multihost
    assert init_multihost(), "jax.distributed must initialize"
    say(f"[p{pid}] initialized: {jax.process_count()} processes, "
        f"{jax.device_count()} global / {jax.local_device_count()} "
        f"local devices ({time.time() - t0:.1f}s)")
    assert jax.process_count() == np_proc

    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.ops.quality import tet_quality
    from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric
    from parmmg_tpu.parallel.dist import distributed_adapt_multi

    # identical input on every process (the deterministic-host contract)
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.8 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    say(f"[p{pid}] input: {len(tet)} tets -> {ndev} shards on "
        f"{np_proc} processes")

    t1 = time.time()
    out, met_m, part = distributed_adapt_multi(
        mesh, met, ndev, niter=2, cycles=4, verbose=2)
    dt = time.time() - t1
    tm = np.asarray(out.tmask)
    q = np.asarray(tet_quality(out, met_m))[tm]
    res = {
        "processes": np_proc,
        "devices": ndev,
        "ntets_in": int(len(tet)),
        "ntets_out": int(tm.sum()),
        "qmin": round(float(q.min()), 4),
        "qmean": round(float(q.mean()), 4),
        "niter": 2,
        "seconds": round(dt, 1),
        "pipeline": "split->adapt->band-migrate->weld->merge",
    }
    say(f"[p{pid}] done: {json.dumps(res)}")
    if pid == 0:
        # canonical schema-versioned artifact (obs/artifact.py) — the
        # legacy result dict rides in extra
        from parmmg_tpu.obs.artifact import make_artifact
        print(json.dumps(make_artifact(
            "MULTIHOST", metric="multihost_adapt",
            value=res["seconds"], unit="s", extra=res)))
    log.close()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--np", type=int, default=2)
    ap.add_argument("--devices", type=int, default=4)
    ap.add_argument("--n", type=int, default=4)
    ap.add_argument("--timeout", type=int, default=3600)
    args = ap.parse_args()

    port = free_port()
    procs = []
    for pid in range(args.np):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": (env.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count="
                          f"{args.devices // args.np}").strip(),
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": str(args.np),
            "JAX_PROCESS_ID": str(pid),
            "MH_WORKER": "1",
            "MH_N": str(args.n),
            "MH_DEVICES": str(args.devices),
            # drop any sitecustomize TPU-tunnel backend: compiles must
            # stay process-local on the CPU backend
            "PYTHONPATH": os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        })
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)], env=env,
            stdout=subprocess.PIPE if pid == 0 else subprocess.DEVNULL,
            stderr=sys.stderr if pid == 0 else subprocess.DEVNULL))
    rc = 0
    out0 = b""
    deadline = time.time() + args.timeout
    try:
        for pid, p in enumerate(procs):
            rem = max(1, deadline - time.time())
            o, _ = p.communicate(timeout=rem)
            if pid == 0:
                out0 = o or b""
            rc = rc or p.returncode
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        print("multihost_run: TIMEOUT", file=sys.stderr)
        sys.exit(2)
    sys.stdout.write(out0.decode())
    sys.exit(rc)


if __name__ == "__main__":
    if os.environ.get("MH_WORKER") == "1":
        worker()
    else:
        main()
