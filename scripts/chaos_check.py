"""Fault-injection gate (scripts/run_tests.sh --chaos).

Runs a small fault matrix IN-PROCESS on the CPU backend and FAILS
(exit 1) unless every injected fault lands on its documented
escalation-ladder step (resilience/recover.py):

1. **zero-fault neutrality**: a grouped run with the resilience wiring
   active (checkpointing armed, retry budget set) is BIT-IDENTICAL to
   the plain run and adds ZERO new ``groups.*`` compile-ledger
   families — resilience is host bookkeeping, never a new program;
2. **transient dispatch fault** (``dispatch.chunk:nth-1``): the chunk
   retries and the run recovers bit-for-bit (ladder step ``retry``);
   also armed UNDER ``PARMMG_INCR_TOPO=1`` — retained TopoState rows
   mutate only at drain writeback, so the faulted chunk replays from
   the retained sorted tables bit-for-bit;
3. **retry-budget exhaustion** (``dispatch.chunk`` every hit,
   ``PARMMG_RETRY_MAX=1``): the driver degrades to ``PMMG_LOWFAILURE``
   and the staged output is still a conforming mesh (ladder terminal
   ``lowfailure`` — the failed_handling contract);
4. **polish-worker death** (``polish.worker`` every invocation, the
   real non-zero-exit shape): grouped polish is skipped after retries
   (ladder step ``merged_polish``), the result equals a polish-less
   pass bit-for-bit and the worker's temp staging does not leak;
5. **checkpoint/resume**: a run resumed from the last completed pass
   checkpoint finishes bit-identical to the uninterrupted run; an
   injected ``io.checkpoint`` OSError is absorbed (counter, no crash,
   bit-neutral);
6. **serve-pool quarantine** (``serve.slot_step;key=<tenant>``): a
   persistently faulting tenant is retired FAILED/quarantined while
   its cohort-mates retire bit-identical to a fault-free pool; a
   transient tenant fault recovers in-step with full parity;
7. **daemon RPC fault** (``serve.daemon_rpc:key=<tenant>``): an RPC
   handled for a mid-flight tenant dies; the DAEMON survives, that
   tenant alone is quarantined (retired FAILED, slot scrubbed +
   recycled) and cohort-mates retire bit-identical to the fault-free
   daemon and to the in-process pool.

CPU backend, axon factory dropped (ledger_check.py sequence).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
from contextlib import contextmanager

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
# 2 virtual devices: scenario 8 runs the 2-shard distributed path
# (multihost.exchange faultpoint); the grouped scenarios are
# single-device and unaffected by the extra virtual device
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
for _k in ("PARMMG_FAULT", "PARMMG_CKPT_DIR", "PARMMG_TRACE"):
    os.environ.pop(_k, None)

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

# chunked dispatch everywhere: _pipeline_chunks (the dispatch.chunk
# site + retry path) only runs in chunk mode
os.environ["PARMMG_GROUP_CHUNK"] = "2"
os.environ.setdefault("PARMMG_RETRY_BASE_S", "0")

TARGET = 16          # cube_mesh(2) = 48 tets -> 3 groups
CYCLES = 2
NITER = 2


@contextmanager
def env(**kv):
    """Scoped env knobs + fault-registry reset on entry AND exit."""
    from parmmg_tpu.resilience.faults import FAULTS
    old = {k: os.environ.get(k) for k in kv}
    for k, v in kv.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = str(v)
    FAULTS.reset()
    try:
        yield
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        FAULTS.reset()


def fresh_case():
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.utils.fixtures import cube_mesh
    vert, tet = cube_mesh(2)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.35, m.vert.dtype)
    return m, met


def state_bytes(mesh, met):
    from parmmg_tpu.core.mesh import MESH_FIELDS
    return tuple(np.asarray(getattr(mesh, f)).tobytes()
                 for f in MESH_FIELDS) + (np.asarray(met).tobytes(),)


def run_grouped(**kw):
    from parmmg_tpu.parallel.groups import grouped_adapt
    m, met = fresh_case()
    out, met_m = grouped_adapt(m, met, TARGET, niter=NITER,
                               cycles=CYCLES, **kw)
    return state_bytes(out, met_m)


def counters():
    from parmmg_tpu.obs.metrics import REGISTRY
    return dict(REGISTRY.snapshot()["counters"])


def delta(before, name):
    return counters().get(name, 0) - before.get(name, 0)


def ladder_steps_since(mark):
    from parmmg_tpu.obs.trace import TRACER
    return [r.get("step") for r in list(TRACER.ring)[mark:]
            if r.get("kind") == "event"
            and r.get("name") == "resilience.ladder"]


def ring_mark():
    from parmmg_tpu.obs.trace import TRACER
    return len(TRACER.ring)


FAILS: list[str] = []


def check(ok: bool, msg: str) -> None:
    tag = "ok" if ok else "CHAOS FAIL"
    print(f"  {tag}: {msg}" if ok else f"{tag}: {msg}",
          file=sys.stdout if ok else sys.stderr)
    if not ok:
        FAILS.append(msg)


def main() -> int:
    from parmmg_tpu.utils.compilecache import (reset_ledger,
                                               variants_by_prefix)

    # ---- 0. spec grammar sanity (host only) ----------------------------
    from parmmg_tpu.resilience.faults import parse_fault_spec
    print("--- chaos gate: fault spec grammar")
    r = parse_fault_spec("dispatch.chunk:nth-2,serve.slot_step:"
                         "key=t1;every-3")
    check(r["dispatch.chunk"].nth == 2
          and r["serve.slot_step"].key == "t1"
          and r["serve.slot_step"].every == 3, "spec grammar parses")
    for bad in ("no.such.site", "dispatch.chunk:wat-3"):
        try:
            parse_fault_spec(bad)
            check(False, f"spec {bad!r} should have been rejected")
        except ValueError:
            check(True, f"spec {bad!r} rejected")

    # ---- 1. baseline + zero-fault neutrality ---------------------------
    print("--- chaos gate: zero-fault neutrality")
    reset_ledger()
    base = run_grouped()
    v0 = variants_by_prefix("groups.")
    check(v0.get("groups.adapt_block", 0) >= 1,
          "scenario exercises groups.adapt_block")
    with tempfile.TemporaryDirectory() as td, \
            env(PARMMG_CKPT_DIR=td, PARMMG_RETRY_MAX="2"):
        wired = run_grouped(ckpt_tag="neutral")
        ckpts = [f for f in os.listdir(td) if f.endswith(".npz")]
    v1 = variants_by_prefix("groups.")
    check(wired == base, "resilience wiring (ckpt+retry armed, zero "
                         "faults) is bit-neutral")
    check(v1 == v0, f"zero new groups.* compile families ({v0} -> {v1})")
    # every pass checkpoints, INCLUDING the final one (a kill during
    # the post-adapt tail must not restart the adaptation)
    check(len(ckpts) == NITER,
          f"pass checkpoints written ({ckpts})")

    # ---- 2. transient dispatch fault recovers bit-for-bit --------------
    print("--- chaos gate: dispatch.chunk transient fault")
    c0 = counters()
    mark = ring_mark()
    with env(PARMMG_FAULT="dispatch.chunk:nth-1", PARMMG_RETRY_MAX="2"):
        got = run_grouped()
    check(got == base, "nth-1 dispatch fault recovered bit-for-bit")
    check(delta(c0, "resilience.faults_injected") >= 1,
          "fault actually injected")
    check(delta(c0, "resilience.retry") >= 1, "retry rung recorded")
    check("retry" in ladder_steps_since(mark), "ladder event emitted")

    # ---- 2b. incremental topology under chunk faults -------------------
    # PARMMG_INCR_TOPO threads retained sorted tables (TopoState rows)
    # through the chunked dispatches; rows mutate ONLY at drain
    # writeback (the idempotent-writeback contract), so a faulted
    # dispatch must replay from the retained table bit-for-bit
    print("--- chaos gate: incremental topology (PARMMG_INCR_TOPO)")
    with env(PARMMG_INCR_TOPO="1"):
        inc = run_grouped()
    check(inc == base,
          "incremental-topology run bit-identical to knob-off baseline")
    c0 = counters()
    with env(PARMMG_INCR_TOPO="1",
             PARMMG_FAULT="dispatch.chunk:nth-1", PARMMG_RETRY_MAX="2"):
        got = run_grouped()
    check(got == base, "faulted chunk under the incremental path "
                       "replayed from the retained tables bit-for-bit")
    check(delta(c0, "resilience.faults_injected") >= 1,
          "incr-path fault actually injected")

    # ---- 3. retry exhaustion -> LOWFAILURE + conforming mesh -----------
    print("--- chaos gate: dispatch.chunk retry exhaustion")
    from parmmg_tpu.api.parmesh import ParMesh
    from parmmg_tpu.core import constants as C
    from parmmg_tpu.core.mesh import tet_volumes
    from parmmg_tpu.utils.fixtures import cube_mesh

    def staged_pm():
        vert, tet = cube_mesh(2)
        pm = ParMesh()
        pm.set_mesh_size(len(vert), len(tet))
        pm.set_vertices(vert, np.zeros(len(vert), np.int32))
        pm.set_tetrahedra(tet + 1, np.ones(len(tet), np.int32))
        pm.info.hsiz = 0.35
        pm.info.niter = 1
        pm.info.imprim = -1
        pm.info.target_mesh_size = TARGET
        # no-op remesh switches: the fault fires before any cycle runs,
        # and the switches keep the degrade tail (repair/fem) off so
        # the gate stays cheap
        pm.info.noinsert = pm.info.noswap = pm.info.nomove = True
        return pm

    c0 = counters()
    with env(PARMMG_FAULT="dispatch.chunk", PARMMG_RETRY_MAX="1"):
        pm = staged_pm()
        ret = pm.run()
    check(ret == C.PMMG_LOWFAILURE,
          f"exhausted retries degrade to PMMG_LOWFAILURE (got {ret})")
    check(delta(c0, "resilience.retry_exhausted") >= 1,
          "retry budget exhaustion recorded")
    check(delta(c0, "resilience.lowfailure") >= 1,
          "lowfailure ladder terminal recorded")
    tm = np.asarray(pm._out.tmask)
    vols = np.asarray(tet_volumes(pm._out))[tm]
    check(tm.sum() > 0 and (vols > 0).all()
          and np.isclose(vols.sum(), 1.0, rtol=1e-5),
          "LOWFAILURE output is a conforming mesh (positive volumes "
          "summing to the cube)")

    # ---- 4. polish worker death -> merged_polish degrade ---------------
    print("--- chaos gate: polish.worker death")
    from parmmg_tpu.parallel.groups import grouped_adapt_pass

    def run_pass(polish):
        m, met = fresh_case()
        out, met_m, _ = grouped_adapt_pass(m, met, 3, cycles=CYCLES,
                                           polish=polish)
        return state_bytes(out, met_m)

    ref = run_pass(False)
    c0 = counters()
    mark = ring_mark()
    pre_leaks = {d for d in os.listdir(tempfile.gettempdir())
                 if d.startswith("parmmg_polish_")}
    with env(PARMMG_FAULT="polish.worker", PARMMG_RETRY_MAX="1",
             PARMMG_POLISH_SUBPROC="1"):
        got = run_pass(True)
    check(got == ref, "dead polish worker degrades to the polish-less "
                      "pass bit-for-bit")
    check(delta(c0, "resilience.polish_worker_failures") >= 1,
          "polish_worker_failures counter bumped")
    check("merged_polish" in ladder_steps_since(mark),
          "merged_polish ladder step recorded")
    leaks = [d for d in os.listdir(tempfile.gettempdir())
             if d.startswith("parmmg_polish_") and d not in pre_leaks]
    check(not leaks, f"no leaked polish temp dirs ({leaks})")

    # ---- 5. checkpoint/resume bit-identity -----------------------------
    print("--- chaos gate: checkpoint/resume")
    with tempfile.TemporaryDirectory() as td, env(PARMMG_CKPT_DIR=td):
        full = run_grouped(ckpt_tag="ck")
        shard_files = [f for f in os.listdir(td)
                       if f.startswith("ck.pass0") and f.endswith(".mesh")]
        check(len(shard_files) == 3,
              f"stacked_to_distributed_files snapshot written "
              f"({shard_files})")
        # "killed after pass 0": drop the final-pass checkpoint (the
        # kill happened before it), resume from pass 0's, re-run the
        # remaining pass — must land bit-identical to the full run
        os.unlink(os.path.join(td, f"ck.pass{NITER - 1}.npz"))
        c0 = counters()
        resumed = run_grouped(ckpt_tag="ck", resume=True)
        check(resumed == full, "resumed run is bit-identical to the "
                               "uninterrupted run")
        check(delta(c0, "resilience.resumes") == 1, "resume recorded")
    c0 = counters()
    with tempfile.TemporaryDirectory() as td, \
            env(PARMMG_CKPT_DIR=td, PARMMG_FAULT="io.checkpoint"):
        got = run_grouped(ckpt_tag="ckf")
        left = [f for f in os.listdir(td) if f.endswith(".npz")]
    check(got == base, "checkpoint IO fault is bit-neutral to the run")
    check(delta(c0, "resilience.checkpoint_failures") >= 1,
          "checkpoint_failures counter bumped")
    check(not left, f"no partial checkpoint survives the fault ({left})")

    # ---- 6. serve-pool quarantine + cohort parity ----------------------
    print("--- chaos gate: serve quarantine")
    from parmmg_tpu.serve.driver import ServeDriver

    def run_pool():
        drv = ServeDriver(slots_per_bucket=3, chunk=2, cycles=CYCLES)
        for t in ("t0", "t1", "t2"):
            m, met = fresh_case()
            drv.submit(mesh=m, met=met, tenant=t)
        rep = drv.run()
        outs = {}
        for t in ("t0", "t1", "t2"):
            if rep["tenants"][t]["state"] == "done":
                outs[t] = state_bytes(*drv.fetch(t))
        return rep, outs

    rep_a, outs_a = run_pool()
    check(rep_a["served"] == 3, f"fault-free pool serves 3 ({rep_a['served']})")
    c0 = counters()
    with env(PARMMG_FAULT="serve.slot_step:key=t1",
             PARMMG_SERVE_MAX_RETRIES="2"):
        rep_b, outs_b = run_pool()
    check(rep_b["tenants"]["t1"]["state"] == "failed"
          and "quarantined" in rep_b["tenants"]["t1"]["reason"],
          f"poisoned tenant quarantined "
          f"({rep_b['tenants']['t1']['reason']!r})")
    check(rep_b["pool"]["quarantined"] == ["t1"],
          "quarantine visible in the pool report")
    check(delta(c0, "serve.quarantined") == 1,
          "serve.quarantined counter bumped")
    check(outs_b.get("t0") == outs_a["t0"]
          and outs_b.get("t2") == outs_a["t2"],
          "cohort-mates retire bit-identical to the fault-free pool")
    # transient tenant fault: in-step per-slot recovery, full parity
    with env(PARMMG_FAULT="serve.slot_step:key=t1;nth-1",
             PARMMG_SERVE_MAX_RETRIES="2"):
        rep_c, outs_c = run_pool()
    check(rep_c["served"] == 3 and outs_c == outs_a,
          "transient tenant fault recovers in-step with full parity")

    # ---- 7. daemon RPC fault -> mid-flight kill + quarantine -----------
    print("--- chaos gate: serve.daemon_rpc mid-flight kill")
    from parmmg_tpu.core.mesh import MESH_FIELDS
    from parmmg_tpu.serve.client import ServeClient, ServeDaemonError
    from parmmg_tpu.serve.daemon import PoolDaemon
    from parmmg_tpu.utils.fixtures import cube_mesh

    vert, tet = cube_mesh(2)
    met_full = np.full(4 * len(vert), 0.35)   # == fresh_case() staging

    def arrays_bytes(arrays):
        return tuple(arrays[f].tobytes() for f in MESH_FIELDS) \
            + (arrays["met"].tobytes(),)

    def run_daemon_pool(kill_t1: bool):
        d = PoolDaemon(port=0, slots_per_bucket=3, chunk=2,
                       cycles=CYCLES, start_paused=True)
        d.start()
        outs = {}
        rep = None
        try:
            cl = ServeClient(port=d.port)
            for t in ("t0", "t1", "t2"):
                cl.submit(vert=vert, tet=tet, met=met_full, tenant=t)
            cl.step()         # admits all 3 + advances one block each
            if kill_t1:
                check(cl.poll("t1")["state"] == "running",
                      "t1 is mid-flight (RUNNING) after one step")
                with env(PARMMG_FAULT="serve.daemon_rpc:key=t1"):
                    try:
                        cl.poll("t1")
                        check(False, "armed serve.daemon_rpc fault did "
                                     "not fire")
                    except ServeDaemonError as e:
                        check(e.status == 500
                              and e.body.get("quarantined") is True,
                              "RPC fault killed the in-flight request "
                              f"(HTTP {e.status}, tenant quarantined)")
                check(cl.health().get("ok") is True,
                      "daemon survives the RPC fault")
            cl.resume()
            for t in ("t0", "t2") + (() if kill_t1 else ("t1",)):
                got = cl.wait(t, timeout_s=600)
                check(got["state"] == "done",
                      f"daemon tenant {t} served ({got['state']})")
                outs[t] = arrays_bytes(cl.fetch(t))
            rep = cl.report()
        finally:
            d.shutdown()
        return rep, outs

    rep_d0, outs_d0 = run_daemon_pool(kill_t1=False)
    check(rep_d0["served"] == 3,
          f"fault-free daemon serves 3 ({rep_d0['served']})")
    check(all(outs_d0.get(t) == outs_a[t] for t in ("t0", "t1", "t2")),
          "daemon-served tenants bit-identical to the in-process pool")
    c0 = counters()
    rep_d1, outs_d1 = run_daemon_pool(kill_t1=True)
    check(rep_d1["tenants"]["t1"]["state"] == "failed"
          and "daemon rpc fault" in rep_d1["tenants"]["t1"]["reason"],
          "killed request retired FAILED "
          f"({rep_d1['tenants']['t1']['reason']!r})")
    check("t1" in rep_d1["pool"]["quarantined"],
          "RPC-edge quarantine visible in the pool report")
    check(delta(c0, "serve.quarantined") >= 1,
          "serve.quarantined counter bumped")
    check(delta(c0, "serve.rpc_faults") >= 1,
          "serve.rpc_faults counter bumped")
    check(outs_d1.get("t0") == outs_a["t0"]
          and outs_d1.get("t2") == outs_a["t2"],
          "cohort-mates of the killed request retire bit-identical")

    # ---- 8. multihost.exchange: band-exchange fault ladder -------------
    # (single-process arm of the pod failure semantics: transient ->
    # retry rung; exhausted -> mh_allgather escape hatch, both
    # bit-identical.  The cross-process arm — worker death -> resume
    # from the per-pass checkpoint — is run_tests.sh --multihost.)
    print("--- chaos gate: multihost.exchange band-exchange fault")
    from parmmg_tpu.parallel.dist import distributed_adapt_multi

    def run_dist():
        m, met = fresh_case()
        out, met_m, _ = distributed_adapt_multi(m, met, 2, niter=2,
                                                cycles=CYCLES)
        return state_bytes(out, met_m)

    base_d = run_dist()
    c0 = counters()
    mark = ring_mark()
    with env(PARMMG_FAULT="multihost.exchange:nth-1",
             PARMMG_RETRY_MAX="2"):
        got = run_dist()
    check(got == base_d,
          "nth-1 exchange fault recovered bit-for-bit (retry rung)")
    check(delta(c0, "resilience.faults_injected") >= 1,
          "exchange fault actually injected")
    check("retry" in ladder_steps_since(mark),
          "retry ladder event emitted")
    c0 = counters()
    mark = ring_mark()
    with env(PARMMG_FAULT="multihost.exchange", PARMMG_RETRY_MAX="0"):
        got2 = run_dist()
    check(got2 == base_d,
          "exhausted exchange degrades to the metered allgather "
          "bit-for-bit")
    check("mh_allgather" in ladder_steps_since(mark),
          "mh_allgather ladder step recorded")
    check(delta(c0, "resilience.mh_allgather") >= 1,
          "resilience.mh_allgather counter bumped")

    # ---- 9. hang drills: deadline watchdogs convert wedges to retries --
    # (PR 15: the hang=S fault action sleeps inside the site instead of
    # raising; only an armed PARMMG_DEADLINE_* watchdog can turn that
    # into the WatchdogTimeout the existing ladder already handles)
    print("--- chaos gate: hang=S -> deadline watchdog -> ladder")
    c0 = counters()
    mark = ring_mark()
    with env(PARMMG_FAULT="dispatch.chunk:hang=3;nth-1",
             PARMMG_RETRY_MAX="2", PARMMG_DEADLINE_DISPATCH_S="0.5",
             PARMMG_DEADLINE_GRACE_S="0"):
        got = run_grouped()
    check(got == base,
          "wedged chunk dispatch recovered bit-for-bit (watchdog -> "
          "retry rung)")
    check(delta(c0, "resilience.watchdog_timeouts") >= 1,
          "watchdog_timeouts counter bumped")
    check("retry" in ladder_steps_since(mark),
          "watchdog expiry entered the retry ladder")
    # wedged polish WORKER: the parent's subprocess timeout must kill
    # it (PARMMG_POLISH_TIMEOUT_S), unlink the partial output and ride
    # the same merged_polish degrade as a crashed worker
    c0 = counters()
    mark = ring_mark()
    pre_leaks = {d for d in os.listdir(tempfile.gettempdir())
                 if d.startswith("parmmg_polish_")}
    with env(PARMMG_FAULT="polish.worker:hang=30",
             PARMMG_RETRY_MAX="1", PARMMG_POLISH_TIMEOUT_S="2",
             PARMMG_POLISH_SUBPROC="1"):
        got = run_pass(True)
    check(got == ref,
          "wedged polish worker killed + degraded to the polish-less "
          "pass bit-for-bit")
    check(delta(c0, "resilience.watchdog_timeouts") >= 1,
          "polish timeout recorded as watchdog expiry")
    check("merged_polish" in ladder_steps_since(mark),
          "merged_polish ladder step after the killed worker")
    leaks = [d for d in os.listdir(tempfile.gettempdir())
             if d.startswith("parmmg_polish_") and d not in pre_leaks]
    check(not leaks, f"no leaked polish staging after the kill ({leaks})")
    # wedged single-process band exchange -> watchdog -> retry rung
    c0 = counters()
    with env(PARMMG_FAULT="multihost.exchange:hang=3;nth-1",
             PARMMG_RETRY_MAX="2", PARMMG_DEADLINE_EXCHANGE_S="0.5",
             PARMMG_DEADLINE_GRACE_S="0"):
        got = run_dist()
    check(got == base_d,
          "wedged band exchange recovered bit-for-bit (watchdog -> "
          "retry rung)")
    check(delta(c0, "resilience.watchdog_timeouts") >= 1,
          "exchange watchdog expiry recorded")

    # ---- 10. seeded soak smoke (scripts/chaos_soak.py, in-process) -----
    # fixed seed, 3 runs: proves the harness end-to-end on the warm
    # programs this gate already compiled; the full campaign is the
    # standalone `python scripts/chaos_soak.py`
    print("--- chaos gate: seeded soak smoke (3 runs)")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "chaos_soak", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "chaos_soak.py"))
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)
    sched = soak.build_schedule(11, 3)
    check(sched == soak.build_schedule(11, 3)
          and sched != soak.build_schedule(12, 3),
          "soak schedule is a pure function of the seed")
    doc = soak.run_campaign(11, 3, say=lambda m: print(f"  {m}"))
    check(doc["extra"]["failed"] == 0,
          f"soak smoke clean ({doc['extra']['failures']})")
    check(doc["kind"] == "SOAK" and doc["extra"]["runs"] == 3,
          "soak artifact well-formed")

    # ---- verdict -------------------------------------------------------
    if FAILS:
        print(f"\nchaos gate FAILED ({len(FAILS)} checks):",
              file=sys.stderr)
        for f in FAILS:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("\nchaos OK: every injected fault recovered bit-for-bit or "
          "degraded to its documented ladder step; fault-free "
          "resilience wiring is bit-neutral with zero new compile "
          "families")
    return 0


if __name__ == "__main__":
    sys.exit(main())
