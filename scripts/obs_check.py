"""Observability gate (scripts/run_tests.sh --obs).

Runs a tiny chunked grouped pass twice in one process — trace sink OFF,
then ON — and FAILS (exit 1) unless:

1. **replay parity**: the JSONL trace replays to the same per-phase
   totals the run's ``Timers`` registry reports (±1%) — the spans ARE
   the timer measurements (utils/timers.py emits them), so any drift
   means the spine forked the numbers;
2. **zero compile cost**: the trace-on run adds ZERO ``groups.*``
   compile-ledger families versus the trace-off run (same process, jit
   caches warm) — tracing is host bookkeeping, never a new program;
3. the metrics spine registered the pass (``groups.dispatches`` > 0)
   and the Prometheus exposition round-trips through the parser.

CPU backend, axon factory dropped (ledger_check.py sequence).
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
os.environ.pop("PARMMG_TRACE", None)       # the sink is armed explicitly

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def run_pass(tim):
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.adapt import AdaptStats
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.utils.fixtures import cube_mesh

    vert, tet = cube_mesh(2)
    m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
    m = analyze_mesh(m).mesh
    met = jnp.full(m.capP, 0.35, m.vert.dtype)
    st = AdaptStats()
    with tim("adaptation"):
        out, _, _ = grouped_adapt_pass(m, met, 3, cycles=2, stats=st,
                                       timers=tim)
    assert int(np.asarray(out.tmask).sum()) > 0
    return st


def main() -> int:
    from parmmg_tpu.obs import trace as otrace
    from parmmg_tpu.obs.metrics import REGISTRY, parse_prometheus
    from parmmg_tpu.utils.compilecache import (reset_ledger,
                                               variants_by_prefix)
    from parmmg_tpu.utils.timers import Timers

    # chunked dispatch so the pipeline segments (upload/compute/
    # download/writeback) exercise Timers.add absorption too
    prev = os.environ.get("PARMMG_GROUP_CHUNK")
    os.environ["PARMMG_GROUP_CHUNK"] = "1"
    rc = 0
    try:
        reset_ledger()
        # ---- run 1: trace sink OFF (ring only) -------------------------
        otrace.TRACER.configure(path=None)
        run_pass(Timers())
        v0 = variants_by_prefix("groups.")
        assert v0.get("groups.adapt_block", 0) >= 1, \
            "obs scenario no longer exercises groups.adapt_block"

        # ---- run 2: trace sink ON --------------------------------------
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "trace.jsonl")
            otrace.TRACER.configure(path=path)
            tim = Timers()
            st = run_pass(tim)
            otrace.TRACER.configure(path=None)
            v1 = variants_by_prefix("groups.")

            print("--- obs gate (trace-on grouped pass)")
            if v1 != v0:
                print("OBS COMPILE-FAMILY REGRESSIONS (trace on added "
                      f"variants): {v0} -> {v1}", file=sys.stderr)
                rc = 1

            # every line must parse; replay filtered to THIS Timers
            nlines = sum(1 for line in open(path) if line.strip()
                         and json.loads(line))
            tot, cnt = otrace.replay_totals(path, tim=tim.trace_id)
            if not tot:
                print("OBS: trace replay found no spans for the run's "
                      "Timers", file=sys.stderr)
                rc = 1
            for k, v in tim.acc.items():
                r = tot.get(k)
                if r is None or abs(r - v) > 0.01 * max(v, 1e-9):
                    print(f"OBS REPLAY MISMATCH: phase {k!r} timers="
                          f"{v:.6f}s trace={r}", file=sys.stderr)
                    rc = 1
                if cnt.get(k) != tim.count[k]:
                    print(f"OBS REPLAY MISMATCH: phase {k!r} count "
                          f"{tim.count[k]} != {cnt.get(k)}",
                          file=sys.stderr)
                    rc = 1
            extra = set(tot) - set(tim.acc)
            if extra:
                print(f"OBS REPLAY MISMATCH: trace has phases the "
                      f"Timers never recorded: {sorted(extra)}",
                      file=sys.stderr)
                rc = 1
            if rc == 0:
                print(f"obs replay OK: {len(tot)} phases match the "
                      f"Timers report exactly ({nlines} trace lines)")

        # ---- metrics spine ---------------------------------------------
        snap = REGISTRY.snapshot()
        if not snap["counters"].get("groups.dispatches"):
            print("OBS: groups.dispatches counter missing/zero after a "
                  "grouped pass", file=sys.stderr)
            rc = 1
        if st.group_dispatches <= 0:
            print("OBS: AdaptStats recorded no group dispatches",
                  file=sys.stderr)
            rc = 1
        parsed = parse_prometheus(REGISTRY.to_prometheus())
        if not any(name == "parmmg_groups_dispatches_total"
                   for name, _ in parsed):
            print("OBS: Prometheus exposition lost groups.dispatches",
                  file=sys.stderr)
            rc = 1
        if rc == 0:
            print(f"obs metrics OK: {len(snap['counters'])} counters, "
                  f"exposition round-trips ({len(parsed)} series)")
            print("\nobs gate OK: trace replay parity + zero new "
                  f"compile families ({v1})")
    finally:
        if prev is None:
            os.environ.pop("PARMMG_GROUP_CHUNK", None)
        else:
            os.environ["PARMMG_GROUP_CHUNK"] = prev
    return rc


if __name__ == "__main__":
    sys.exit(main())
