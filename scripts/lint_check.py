#!/usr/bin/env python
"""Static invariant lint gate (``run_tests.sh --lint``).

Runs the R1-R6 AST rules over the tree in a few seconds — no jax
import, no compiles — and fails on any violation that is neither
suppressed in source (``# lint: ok(<rule>) — reason``) nor grandfathered
in ``lint_baseline.json``.  R4 (knob registry) ignores the baseline:
it must hold exactly, from day one.

Usage:
    python scripts/lint_check.py                 # the gate
    python scripts/lint_check.py -v              # + per-rule listings
    python scripts/lint_check.py --rules R3,R4   # subset
    python scripts/lint_check.py --baseline-update
        rewrite lint_baseline.json to the current violation set (an
        intentional rotation: do this only in the PR that argues why)
"""
from __future__ import annotations

import argparse
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BASELINE = os.path.join(ROOT, "lint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite lint_baseline.json from the current "
                         "violations (R4 stays unbaselined)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    from parmmg_tpu import lint

    rules = tuple(r.strip() for r in args.rules.split(",")
                  if r.strip()) or None
    try:
        report = lint.run_lint(ROOT, rules=rules)
    except ValueError as e:
        # a typo'd --rules must not read like a lint failure
        print(f"lint: {e}", file=sys.stderr)
        return 2

    if args.baseline_update:
        import json
        payload = lint.baseline_payload(report)
        # R4 is never grandfathered — drop its keys so the registry
        # contract stays exact
        payload["grandfathered"] = {
            k: v for k, v in payload["grandfathered"].items()
            if not k.startswith("R4:")}
        with open(BASELINE, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"lint: baseline rewritten with "
              f"{len(payload['grandfathered'])} grandfathered keys "
              f"-> {BASELINE}")
        return 0

    baseline = lint.load_baseline(BASELINE)
    result = lint.gate(report, baseline)
    print(lint.format_report(report, result))

    if args.verbose:
        print("\n-- suppressed (reasoned, in-source) --")
        for v, s in report.suppressed:
            print(f"{v.rule} {v.path}:{v.line} [{v.scope}] {v.detail}"
                  f"  # {s.reason}")

    dt = time.perf_counter() - t0
    print(f"\nlint: {len(result.new)} new, "
          f"{sum(b['current'] for b in result.burndown.values())} "
          f"baselined ("
          f"{sum(b['retired'] for b in result.burndown.values())} "
          f"retired), {len(report.suppressed)} suppressed, "
          f"{len(result.bad)} suppression problems  [{dt:.2f}s]")

    # the linter's own contract: static means static — jax must never
    # have been imported by running it
    if "jax" in sys.modules:
        print("lint: INTERNAL ERROR — the linter imported jax",
              file=sys.stderr)
        return 2
    if not result.ok:
        print("lint: FAIL (fix, suppress with a reason, or argue a "
              "baseline rotation)", file=sys.stderr)
        return 1
    print("lint: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
