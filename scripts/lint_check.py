#!/usr/bin/env python
"""Static invariant lint gate (``run_tests.sh --lint``).

Runs the R1-R10 AST rules over the tree in a few seconds — no jax
import, no compiles — and fails on any violation that is neither
suppressed in source (``# lint: ok(<rule>) — reason``) nor grandfathered
in ``lint_baseline.json``.  R4 (knob registry) ignores the baseline:
it must hold exactly, from day one.

Usage:
    python scripts/lint_check.py                 # the gate
    python scripts/lint_check.py -v              # + per-rule listings
    python scripts/lint_check.py --rules R3,R4   # subset
    python scripts/lint_check.py --sarif out.sarif
        also write a SARIF 2.1.0 log: one result per violation (new =
        error, baselined = warning, suppressed results carry their
        in-source justification) for CI annotation surfaces
    python scripts/lint_check.py --changed-only
        analyze the WHOLE tree (the interprocedural summaries need it)
        but report and gate only findings in git-dirty files — the
        inner-loop mode: your edit either introduced the finding or
        touched the file that holds it
    python scripts/lint_check.py --baseline-update
        rewrite lint_baseline.json to the current violation set (an
        intentional rotation: do this only in the PR that argues why)
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

BASELINE = os.path.join(ROOT, "lint_baseline.json")


def _changed_files() -> set:
    """Repo-relative paths of git-dirty files (staged, unstaged and
    untracked) — the --changed-only report filter."""
    try:
        out = subprocess.run(
            ["git", "status", "--porcelain"], cwd=ROOT,
            capture_output=True, text=True, timeout=30, check=True,
        ).stdout
    except Exception as e:
        print(f"lint: --changed-only needs git ({e})", file=sys.stderr)
        return set()
    rels = set()
    for ln in out.splitlines():
        if len(ln) < 4:
            continue
        path = ln[3:]
        # renames show as "old -> new": the NEW path holds the code
        if " -> " in path:
            path = path.split(" -> ", 1)[1]
        rels.add(path.strip().strip('"'))
    return rels


def _sarif_payload(report, result, titles) -> dict:
    """SARIF 2.1.0: one result per violation.  New violations are
    errors, baselined ones warnings (with the gate state in
    properties), suppressed ones carry their in-source reason as a
    SARIF suppression; SUPP problems (reasonless/unknown-rule
    comments, parse errors) are errors under the pseudo-rule SUPP."""
    new_ids = {id(v) for v in result.new}

    def loc(v):
        region = {"startLine": max(int(v.line), 1)}
        return [{"physicalLocation": {
            "artifactLocation": {"uri": v.path,
                                 "uriBaseId": "SRCROOT"},
            "region": region}}]

    def res(v, level, state, suppression=None):
        r = {"ruleId": v.rule,
             "level": level,
             "message": {"text": v.message},
             "locations": loc(v),
             "properties": {"state": state,
                            "scope": v.scope,
                            "detail": v.detail,
                            "key": v.key}}
        if suppression is not None:
            r["suppressions"] = [{
                "kind": "inSource",
                "justification": suppression.reason,
                "properties": {
                    "commentLine": suppression.comment_line}}]
        return r

    results = []
    for v in result.bad:
        results.append(res(v, "error", "suppression-problem"))
    for v in report.violations:
        if id(v) in new_ids:
            results.append(res(v, "error", "new"))
        else:
            results.append(res(v, "warning", "baselined"))
    for v, s in report.suppressed:
        results.append(res(v, "note", "suppressed", suppression=s))

    rules = [{"id": rid,
              "shortDescription": {"text": titles.get(rid, rid)}}
             for rid in sorted(titles)]
    rules.append({"id": "SUPP", "shortDescription": {
        "text": "suppression hygiene (reason mandatory, rule ids must "
                "exist, files must parse)"}})
    return {
        "$schema": "https://docs.oasis-open.org/sarif/sarif/v2.1.0/"
                   "errata01/os/schemas/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "parmmg-lint",
                "informationUri":
                    "parmmg_tpu/lint/__init__.py",
                "rules": rules}},
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite lint_baseline.json from the current "
                         "violations (R4 stays unbaselined)")
    ap.add_argument("--sarif", metavar="PATH", default="",
                    help="write a SARIF 2.1.0 log of every violation "
                         "(new/baselined/suppressed) to PATH")
    ap.add_argument("--changed-only", action="store_true",
                    help="report and gate only findings in git-dirty "
                         "files (analysis still covers the whole tree)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    from parmmg_tpu import lint

    rules = tuple(r.strip() for r in args.rules.split(",")
                  if r.strip()) or None
    try:
        report = lint.run_lint(ROOT, rules=rules)
    except ValueError as e:
        # a typo'd --rules must not read like a lint failure
        print(f"lint: {e}", file=sys.stderr)
        return 2

    if args.baseline_update:
        import json
        payload = lint.baseline_payload(report)
        # R4 is never grandfathered — drop its keys so the registry
        # contract stays exact
        payload["grandfathered"] = {
            k: v for k, v in payload["grandfathered"].items()
            if not k.startswith("R4:")}
        with open(BASELINE, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"lint: baseline rewritten with "
              f"{len(payload['grandfathered'])} grandfathered keys "
              f"-> {BASELINE}")
        return 0

    baseline = lint.load_baseline(BASELINE)
    result = lint.gate(report, baseline)

    if args.changed_only:
        changed = _changed_files()
        # the SUMMARIES were computed over the full tree (an edit in
        # a callee changes facts at untouched call sites — those still
        # surface in the next full run / CI); the REPORT narrows to
        # what the working copy actually touches
        report = lint.LintReport(
            [v for v in report.violations if v.path in changed],
            [(v, s) for v, s in report.suppressed
             if v.path in changed],
            [v for v in report.bad if v.path in changed])
        result = lint.GateResult(
            [v for v in result.new if v.path in changed],
            [v for v in result.bad if v.path in changed],
            result.burndown)
        print(f"lint: --changed-only over {len(changed)} dirty "
              "file(s)")

    print(lint.format_report(report, result))

    if args.sarif:
        import json
        doc = _sarif_payload(report, result, lint.RULE_TITLES)
        with open(args.sarif, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        n = len(doc["runs"][0]["results"])
        print(f"lint: SARIF log with {n} result(s) -> {args.sarif}")

    if args.verbose:
        print("\n-- suppressed (reasoned, in-source) --")
        for v, s in report.suppressed:
            print(f"{v.rule} {v.path}:{v.line} [{v.scope}] {v.detail}"
                  f"  # {s.reason}")

    dt = time.perf_counter() - t0
    print(f"\nlint: {len(result.new)} new, "
          f"{sum(b['current'] for b in result.burndown.values())} "
          f"baselined ("
          f"{sum(b['retired'] for b in result.burndown.values())} "
          f"retired), {len(report.suppressed)} suppressed, "
          f"{len(result.bad)} suppression problems  [{dt:.2f}s]")

    # the linter's own contract: static means static — jax must never
    # have been imported by running it
    if "jax" in sys.modules:
        print("lint: INTERNAL ERROR — the linter imported jax",
              file=sys.stderr)
        return 2
    if not result.ok:
        print("lint: FAIL (fix, suppress with a reason, or argue a "
              "baseline rotation)", file=sys.stderr)
        return 1
    print("lint: OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
