#!/usr/bin/env bash
# Per-file test runner: one pytest process per test file.
#
# Why not one big `pytest tests/`: on this image the XLA:CPU compiler
# intermittently segfaults (and its AOT serializer aborts) late in a
# long-lived process after many hundred compilations — the same test
# passes in a fresh process.  Per-file processes bound the blast radius
# and mirror the reference CI, which runs each case as its own
# executable under ctest (cmake/testing/pmmg_tests.cmake).
set -u
cd "$(dirname "$0")/.."

# --lint: static invariant gate (scripts/lint_check.py) — R1-R10 AST
# rules over the whole tree in seconds, no jax import, no compiles:
# jit-hygiene, hot-path host-sync, obs print routing, PARMMG_* knob
# registry, jaxcompat shim discipline, static telemetry names, plus
# the flow-sensitive provers (R8 SPMD collective alignment, R9 lock
# discipline, R10 shape-ladder escapes).  Zero unsuppressed
# non-baselined violations allowed (lint_baseline.json is the
# grandfathered burn-down list; R4 runs with no baseline at all).
# Extra args pass through: `run_tests.sh --lint --sarif out.sarif`,
# `run_tests.sh --lint --changed-only`, `--rules R8,R9`, `-v`.
if [ "${1:-}" = "--lint" ]; then
    shift
    exec python scripts/lint_check.py "$@"
fi

# The compile-heavy gates below pay minutes of XLA:CPU compile — run
# the seconds-cheap static lint first so hygiene violations fail fast.
if [ "${1:-}" = "--ledger" ] || [ "${1:-}" = "--obs" ] \
        || [ "${1:-}" = "--chaos" ] || [ "${1:-}" = "--serve" ] \
        || [ "${1:-}" = "--multihost" ]; then
    python scripts/lint_check.py || exit 1
fi

# --ledger: compile-governor budget gate only — run the steady-state
# migration scenario (G=1 AND the grouped G=2 layout, so the grouped
# analysis/exchange entry points are budget-asserted too), the chunked
# grouped-pass scenario asserting the quiet-group scheduler introduces
# ZERO new compile families vs always-dispatch, and the serving_gate
# (a warm multi-tenant pool serving 2 tenants of different bucket
# sizes adds zero groups.* families vs the batch grouped path in the
# same process, bit-for-bit parity included); fail if any registered
# entry point exceeded its compiled-variant budget
# (scripts/ledger_check.py; its --diff mode compares two BENCH/SCALE
# artifacts for variant-count regressions).
if [ "${1:-}" = "--ledger" ]; then
    exec env JAX_PLATFORMS=cpu python scripts/ledger_check.py
fi

# --obs: observability gate (scripts/obs_check.py) — a tiny grouped
# pass with PARMMG_TRACE armed must replay to the same per-phase totals
# Timers.report prints (the spans ARE the timer measurements), and
# trace-on vs trace-off must add ZERO groups.* compile families
# (telemetry is host bookkeeping, never a new program).
if [ "${1:-}" = "--obs" ]; then
    exec env JAX_PLATFORMS=cpu python scripts/obs_check.py
fi

# --chaos: fault-injection gate (scripts/chaos_check.py) — every
# PARMMG_FAULT site provokes its REAL failure path in-process and must
# land on its documented escalation-ladder step: recovered bit-for-bit
# (transient dispatch fault, checkpoint/resume) or degraded with a
# conforming mesh (retry exhaustion -> LOWFAILURE, worker death ->
# merged polish, serve quarantine with cohort parity).  Hang drills
# (hang=S fault action): a wedged chunk dispatch / band exchange is
# converted by its PARMMG_DEADLINE_* watchdog into the same retry
# ladder, and a wedged polish worker is killed by
# PARMMG_POLISH_TIMEOUT_S into the merged_polish degrade — all
# bit-for-bit.  Ends with a 3-run fixed-seed smoke of the seeded
# chaos-soak harness (scripts/chaos_soak.py; the full campaign is
# standalone).  The zero-fault run with the resilience wiring active
# must be bit-neutral and add ZERO new groups.* compile families.
if [ "${1:-}" = "--chaos" ]; then
    exec env JAX_PLATFORMS=cpu python scripts/chaos_check.py
fi

# --serve: serving-daemon gate (scripts/serve_check.py) — start the
# pool daemon on an ephemeral port, submit 2 small tenants over
# localhost HTTP, fetch, assert bit-for-bit parity with their
# standalone grouped runs and ZERO new groups.* compile families after
# the standalone warmup, then a clean shutdown (threads joined).
if [ "${1:-}" = "--serve" ]; then
    exec env JAX_PLATFORMS=cpu python scripts/serve_check.py
fi

# --multihost: pod-runtime gate (scripts/multihost_check.py) — a
# 2-process localhost run must be bit-identical to the 1-process dist
# path, every worker must pay ~zero compiles through the shared warm
# cache, the hot path must perform ZERO process_allgather bytes
# (mh.hot_allgather_bytes), and a worker killed mid-run must resume
# from its per-pass checkpoint bit-identically — as must a worker
# WEDGED mid-run (hang=600 fault action): its heartbeat lease
# (--lease) expires, the supervisor kills the pack and the resumed
# run lands on the same bits.  First invocation warms the repo-local
# .jax_cache_mh; repeats run warm.
if [ "${1:-}" = "--multihost" ]; then
    exec env JAX_PLATFORMS=cpu python scripts/multihost_check.py
fi

fail=0
# static lint first: costs seconds, fails before any compile is paid
echo "=== lint (static invariants R1-R10)"
python scripts/lint_check.py || fail=1
for f in tests/test_*.py; do
    echo "=== $f"
    timeout 2000 python -m pytest "$f" -q --no-header 2>&1 | tail -2
    rc=${PIPESTATUS[0]}
    if [ "$rc" -ne 0 ]; then
        echo "!!! $f exited $rc"
        fail=1
    fi
done
exit $fail
