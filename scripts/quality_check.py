"""Deterministic CPU quality check of the bench workload (small N).

Runs the same shock-metric cube adaptation as bench.py at a reduced size
on the CPU backend and prints final qmin/qmean/ntets — used to compare
wave-scheduling changes (claim orders, swap cadence) for quality impact.
Run: python scripts/quality_check.py [N] [cycles]
"""
from __future__ import annotations

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jc_cpu")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.ops.adapt import adapt_cycles_fused
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import tet_quality
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    cycles = int(sys.argv[2]) if len(sys.argv) > 2 else 9
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)

    m, k = mesh, met
    for b in range(0, cycles, 3):
        nc = min(3, cycles - b)
        m, k, counts = adapt_cycles_fused(m, k, jnp.asarray(b, jnp.int32),
                                          n_cycles=nc, swap_every=3)
        cs = np.asarray(counts)
        for r in cs:
            print(f"  cycle: split {r[0]:6d} collapse {r[1]:6d} "
                  f"swap {r[2]:6d} move {r[3]:6d} live {r[5]:6d}")
    q = np.asarray(tet_quality(m, k))
    tm = np.asarray(m.tmask)
    qs = np.sort(q[tm])
    print(f"N={n} cycles={cycles}: ntets={tm.sum()} "
          f"qmin={qs[0]:.6f} q1%={qs[len(qs)//100]:.4f} "
          f"qmean={qs.mean():.4f}")


if __name__ == "__main__":
    main()
