"""Compile-ledger budget gate (scripts/run_tests.sh --ledger).

Runs the steady-state migration scenario (4 outer iterations with
drifting interface sizes, CPU backend) — once at G=1 and once on the
grouped G=2 (groups x shards) layout — and FAILS (exit 1) when any
registered entry point exceeded its compiled-variant budget — the CI
teeth behind the compile governor (utils/compilecache): a change that
reintroduces per-iteration recompiles (exact static shapes, a fresh
jit object per call, an unbucketed budget) trips this gate without
anyone having to eyeball BENCH artifacts.

``--diff old.json new.json`` instead runs the cross-artifact regression
differ (obs/artifact.py ``artifact_diff``): both sides are upgraded to
the canonical schema, then compile-ledger variant growth (the historical
hard-fail class), headline-metric drops, qmin/qmean drops, scheduler
saved-dispatch shrinkage and disappearing metric counters are reported.
Exit 1 on ledger regressions; ``--strict`` also fails on metric/quality
regressions.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def diff_main(old_path: str, new_path: str, strict: bool = False) -> int:
    from parmmg_tpu.obs.artifact import artifact_diff
    with open(old_path) as f:
        old = json.load(f)
    with open(new_path) as f:
        new = json.load(f)
    d = artifact_diff(old, new)
    for label, rows in (("LEDGER VARIANT REGRESSIONS", d["ledger"]),
                        ("METRIC REGRESSIONS", d["value"]),
                        ("QUALITY REGRESSIONS", d["quality"]),
                        ("notes", d["notes"])):
        if rows:
            print(f"{label}:", file=sys.stderr)
            for v in rows:
                print(f"  {v}", file=sys.stderr)
    bad = list(d["ledger"])
    if strict:
        bad += d["value"] + d["quality"]
    if bad:
        return 1
    print(f"artifact diff OK: no ledger"
          + ("" if not strict else "/metric/quality")
          + f" regressions ({old_path} -> {new_path})")
    return 0


if len(sys.argv) >= 2 and sys.argv[1] == "--diff":
    args = [a for a in sys.argv[2:] if a != "--strict"]
    if len(args) != 2:
        print("usage: ledger_check.py --diff [--strict] OLD.json "
              "NEW.json", file=sys.stderr)
        sys.exit(2)
    sys.exit(diff_main(args[0], args[1],
                       strict="--strict" in sys.argv[2:]))

os.environ["JAX_PLATFORMS"] = "cpu"
# the virtual multi-device CPU mesh (same setup as tests/conftest.py):
# the scenario shards over 2 devices
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# no persistent cache: a warm cache would hide fresh-variant compiles
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def grouped_sched_gate() -> int:
    """Quiet-group scheduler compile-family gate: a chunked grouped
    pass with the scheduler ON must introduce ZERO new compile families
    versus the always-dispatch path — compaction gathers group slices
    for the SAME compiled [chunk, ...] program, so the later runs below
    (same process, jit caches warm from the scheduler-off run) may not
    compile anything new under any ``groups.*`` entry point.  The same
    contract covers the device-resident quiet mask (PARMMG_DEVICE_MASK,
    parallel/sched.py): the mask is ALWAYS an argument of the compiled
    block programs, so a mask-on run vs a mask-off run in one process
    must also add zero ``groups.*`` families — the ``lax.cond`` wrapper
    may not mint new variants."""
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.utils.compilecache import (ledger_violations,
                                               reset_ledger,
                                               variants_by_prefix)
    from parmmg_tpu.utils.fixtures import cube_mesh

    def run(sched: str, mask: str = "1"):
        os.environ["PARMMG_GROUP_SCHED"] = sched
        os.environ["PARMMG_DEVICE_MASK"] = mask
        vert, tet = cube_mesh(2)
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.full(m.capP, 0.35, m.vert.dtype)
        out, _, _ = grouped_adapt_pass(m, met, 3, cycles=2)
        assert int(np.asarray(out.tmask).sum()) > 0

    def grp_variants():
        return variants_by_prefix("groups.")

    # save/restore the operator's knob values (bench.py does the same)
    prev = {k: os.environ.get(k)
            for k in ("PARMMG_GROUP_CHUNK", "PARMMG_GROUP_SCHED",
                      "PARMMG_DEVICE_MASK")}
    os.environ["PARMMG_GROUP_CHUNK"] = "1"
    try:
        reset_ledger()
        run("0", mask="0")            # legacy always-dispatch, no mask
        v0 = grp_variants()
        run("1", mask="0")            # compaction on, device mask off
        v1 = grp_variants()
        run("1", mask="1")            # compaction + device mask
        v2 = grp_variants()
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert v0.get("groups.adapt_block", 0) >= 1, \
        "grouped scenario no longer exercises groups.adapt_block"
    print("--- grouped quiet-scheduler scenario")
    if v1 != v0:
        print("SCHEDULER COMPILE-FAMILY REGRESSIONS (scheduler on "
              f"added variants): {v0} -> {v1}", file=sys.stderr)
        return 1
    if v2 != v1:
        print("DEVICE-MASK COMPILE-FAMILY REGRESSIONS (mask-on run "
              f"added variants vs mask-off): {v1} -> {v2}",
              file=sys.stderr)
        return 1
    bad = ledger_violations()
    if bad:
        print("\nLEDGER BUDGET VIOLATIONS (grouped scheduler):",
              file=sys.stderr)
        for v in bad:
            print(f"  {v}", file=sys.stderr)
        return 1
    print(f"grouped scheduler OK: zero new compile families ({v2}; "
          "scheduler AND device mask)")
    return 0


def hotloop_knob_gate() -> int:
    """Hot-loop knob compile-family gate (the cycle-cost demolition
    attacks, README "Hot-loop cycle costs"): flipping the smoothing
    cadence, the facesort swap pairing, the donor-band collapse apply,
    the Pallas scoring prep or the Pallas sort engine may not mint a
    single new ``groups.*``
    compile family in a warm process.  Two distinct mechanisms back
    this: PARMMG_SMOOTH_CADENCE and PARMMG_INCR_TOPO are TRACED device
    scalars of the compiled block (like the quiet mask — toggling
    changes an input value, never the program; the incremental path's
    band/table shapes are capT-static ladder rungs, so the knob-on arm
    adds no shape families either), while the facesort / band / score /
    sort knobs are trace-time reads whose both settings produce
    bit-identical
    results, so the warm ``_GROUP_BLOCK_CACHE`` program from the first
    run legitimately serves the flipped runs (a stale entry is only a
    perf choice, never a correctness one)."""
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.utils.compilecache import (ledger_violations,
                                               reset_ledger,
                                               variants_by_prefix)
    from parmmg_tpu.utils.fixtures import cube_mesh

    KNOBS = ("PARMMG_SMOOTH_CADENCE", "PARMMG_SWAP_FACESORT",
             "PARMMG_COLLAPSE_BAND", "PARMMG_PALLAS_SCORE",
             "PARMMG_INCR_TOPO", "PARMMG_PALLAS_SORT")

    def run(setting: str):
        for k in KNOBS:
            os.environ[k] = setting
        # cube(4): a capacity rung no earlier gate in this process has
        # compiled, so the knobs-off run below really compiles the
        # family (variants only count at compile time — a warm-cache
        # run would leave v0 empty and make the comparison vacuous)
        vert, tet = cube_mesh(4)
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.full(m.capP, 0.35, m.vert.dtype)
        out, _, _ = grouped_adapt_pass(m, met, 3, cycles=2)
        assert int(np.asarray(out.tmask).sum()) > 0

    prev = {k: os.environ.get(k)
            for k in KNOBS + ("PARMMG_GROUP_CHUNK",)}
    os.environ["PARMMG_GROUP_CHUNK"] = "1"
    try:
        reset_ledger()
        run("0")                      # all attacks off (legacy paths)
        v0 = variants_by_prefix("groups.")
        run("1")                      # all attacks on
        v1 = variants_by_prefix("groups.")
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert v0.get("groups.adapt_block", 0) >= 1, \
        "hot-loop knob scenario no longer exercises groups.adapt_block"
    print("--- hot-loop knob scenario "
          "(cadence/facesort/band/score/sort)")
    if v1 != v0:
        print("HOT-LOOP KNOB COMPILE-FAMILY REGRESSIONS (knobs-on run "
              f"added variants vs knobs-off): {v0} -> {v1}",
              file=sys.stderr)
        return 1
    bad = ledger_violations()
    if bad:
        print("\nLEDGER BUDGET VIOLATIONS (hot-loop knobs):",
              file=sys.stderr)
        for v in bad:
            print(f"  {v}", file=sys.stderr)
        return 1
    print(f"hot-loop knobs OK: zero new compile families ({v1}; "
          "cadence, facesort, collapse band, pallas score, incr topo, "
          "pallas sort)")
    return 0


def serving_gate() -> int:
    """Serving compile-family gate: a warm pool serving tenants of two
    DIFFERENT bucket sizes must add ZERO ``groups.*`` compile-ledger
    families versus the batch grouped path run in the same process —
    the pool's slots are shape-identical to the standalone
    ``grouped_adapt_pass(ngroups=1)`` layout (same capacity-ladder
    rungs, same cached ``_group_block`` programs), so serving is
    compile-free after the per-bucket warmup any batch user pays.
    Doubles as a bit-for-bit parity check: each tenant's merged output
    must equal its standalone run (mesh fields + metric)."""
    import jax.numpy as jnp
    from parmmg_tpu.core.mesh import MESH_FIELDS, make_mesh
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.parallel.groups import grouped_adapt_pass
    from parmmg_tpu.serve.driver import ServeDriver
    from parmmg_tpu.utils.compilecache import (ledger_violations,
                                               reset_ledger,
                                               variants_by_prefix)
    from parmmg_tpu.utils.fixtures import cube_mesh

    cycles = 2

    def tenant(n, h):
        vert, tet = cube_mesh(n)
        m = make_mesh(vert, tet, capP=4 * len(vert), capT=4 * len(tet))
        m = analyze_mesh(m).mesh
        met = jnp.full(m.capP, h, m.vert.dtype)
        return m, met

    def grp_variants():
        return variants_by_prefix("groups.")

    reset_ledger()
    classes = ((2, 0.55), (3, 0.5))
    # batch warmup: the standalone grouped path per bucket size — this
    # is the only phase allowed to compile groups.* programs
    refs = {}
    for n, h in classes:
        m, met = tenant(n, h)
        out, met_m, _ = grouped_adapt_pass(m, met, 1, cycles=cycles)
        refs[n] = (out, met_m)
    v0 = grp_variants()
    assert v0.get("groups.adapt_block", 0) >= 1, \
        "serving warmup no longer exercises groups.adapt_block"
    drv = ServeDriver(slots_per_bucket=2, chunk=1, cycles=cycles)
    for n, h in classes:
        m, met = tenant(n, h)
        drv.submit(mesh=m, met=met, tenant=f"n{n}")
    rep = drv.run()
    v1 = grp_variants()
    print("--- serving scenario (2 tenants, 2 buckets, warm pool)")
    if rep["served"] != 2:
        print(f"SERVING GATE: expected 2 served tenants, got {rep}",
              file=sys.stderr)
        return 1
    if v1 != v0:
        print("SERVING COMPILE-FAMILY REGRESSIONS (warm pool added "
              f"variants): {v0} -> {v1}", file=sys.stderr)
        return 1
    for n, _h in classes:
        mesh, met_m = drv.fetch(f"n{n}")
        ref, kref = refs[n]
        for f in MESH_FIELDS:
            if not (np.asarray(getattr(mesh, f))
                    == np.asarray(getattr(ref, f))).all():
                print(f"SERVING PARITY: tenant n{n} field {f} differs "
                      "from the standalone grouped run", file=sys.stderr)
                return 1
        if not (np.asarray(met_m) == np.asarray(kref)).all():
            print(f"SERVING PARITY: tenant n{n} metric differs",
                  file=sys.stderr)
            return 1
    # daemon mode: the SAME tenants through the pool daemon's HTTP RPC
    # surface (in-process ephemeral-port daemon, so the compile ledger
    # is shared) must add ZERO groups.* families vs the in-process pool
    # above, and every fetched result must stay bit-identical to the
    # standalone run — the daemon is a transport, never a new program
    from parmmg_tpu.serve.client import ServeClient
    from parmmg_tpu.serve.daemon import PoolDaemon
    from parmmg_tpu.utils.fixtures import cube_mesh
    print("--- serving scenario (daemon mode, 2 tenants over HTTP)")
    daemon = PoolDaemon(port=0, slots_per_bucket=2, chunk=1,
                        cycles=cycles)
    daemon.start()
    try:
        cl = ServeClient(port=daemon.port)
        tids = {}
        for n, h in classes:
            vert, tet = cube_mesh(n)
            # full-capP metric: identical staging to tenant() above
            tids[n] = cl.submit(vert=vert, tet=tet,
                                met=np.full(4 * len(vert), h),
                                tenant=f"d{n}")
        for n, _h in classes:
            got = cl.wait(tids[n], timeout_s=600)
            if got["state"] != "done":
                print(f"SERVING GATE (daemon): tenant d{n} ended "
                      f"{got['state']}: {got.get('reason', '')}",
                      file=sys.stderr)
                return 1
            arrays = cl.fetch(tids[n])
            ref, kref = refs[n]
            for f in MESH_FIELDS:
                if not (arrays[f] == np.asarray(getattr(ref, f))).all():
                    print(f"SERVING PARITY (daemon): tenant d{n} field "
                          f"{f} differs from the standalone run",
                          file=sys.stderr)
                    return 1
            if not (arrays["met"] == np.asarray(kref)).all():
                print(f"SERVING PARITY (daemon): tenant d{n} metric "
                      "differs", file=sys.stderr)
                return 1
    finally:
        daemon.shutdown()
    v2 = grp_variants()
    if v2 != v1:
        print("SERVING COMPILE-FAMILY REGRESSIONS (daemon mode added "
              f"variants vs the in-process pool): {v1} -> {v2}",
              file=sys.stderr)
        return 1
    bad = ledger_violations()
    if bad:
        print("\nLEDGER BUDGET VIOLATIONS (serving):", file=sys.stderr)
        for v in bad:
            print(f"  {v}", file=sys.stderr)
        return 1
    print(f"serving OK: zero new compile families ({v2}), bit-for-bit "
          "parity with the batch grouped path (in-process AND daemon)")
    return 0


def main() -> int:
    from parmmg_tpu.utils.compilecache import (format_ledger,
                                               ledger_snapshot,
                                               ledger_violations,
                                               reset_ledger)
    from parmmg_tpu.utils.fixtures import steady_state_migration_scenario

    rc = 0
    # budgets are PER steady-state family: one compiled-shape family per
    # (fixture caps, G) — the ledger is reset between the two scenario
    # runs so the G=1 and grouped gates stay individually tight instead
    # of sharing a doubled allowance
    for label, kwargs, must_call in (
            ("G=1", dict(niter=4, cycles=2, n_shards=2),
             ("migrate_dev.device_migrate", "dist.interface_check")),
            ("G=2 grouped", dict(niter=3, cycles=2, n_shards=4,
                                 n_devices=2),
             ("dist.analysis_grouped", "dist.interface_check"))):
        reset_ledger()
        out = steady_state_migration_scenario(**kwargs)
        assert int(np.asarray(out.tmask).sum()) > 0
        led = ledger_snapshot()
        for entry in must_call:
            assert led.get(entry, {}).get("calls", 0) >= 1, \
                f"{label} scenario no longer exercises {entry}"
        print(f"--- {label} steady-state scenario")
        print(format_ledger())
        bad = ledger_violations()
        if bad:
            print(f"\nLEDGER BUDGET VIOLATIONS ({label}):",
                  file=sys.stderr)
            for v in bad:
                print(f"  {v}", file=sys.stderr)
            rc = 1
    # quiet-group scheduler gate: compaction must reuse the compiled
    # [chunk, ...] group program — zero new families with it enabled
    rc = max(rc, grouped_sched_gate())
    # hot-loop knob gate: cadence/facesort/band/score toggles add zero
    # groups.* families in a warm process (traced-scalar + warm-cache
    # contracts — see hotloop_knob_gate)
    rc = max(rc, hotloop_knob_gate())
    # serving gate: a warm multi-tenant pool adds zero groups.*
    # families vs the batch grouped path (and matches it bit-for-bit)
    rc = max(rc, serving_gate())
    if rc == 0:
        print("\nledger OK: all entry points within variant budgets")
    return rc


if __name__ == "__main__":
    sys.exit(main())
