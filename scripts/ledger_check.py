"""Compile-ledger budget gate (scripts/run_tests.sh --ledger).

Runs the steady-state migration scenario (4 outer iterations with
drifting interface sizes, CPU backend) and FAILS (exit 1) when any
registered entry point exceeded its compiled-variant budget — the CI
teeth behind the compile governor (utils/compilecache): a change that
reintroduces per-iteration recompiles (exact static shapes, a fresh
jit object per call, an unbucketed budget) trips this gate without
anyone having to eyeball BENCH artifacts.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ["JAX_PLATFORMS"] = "cpu"
# the virtual multi-device CPU mesh (same setup as tests/conftest.py):
# the scenario shards over 2 devices
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
# no persistent cache: a warm cache would hide fresh-variant compiles
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

import jax  # noqa: E402

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def main() -> int:
    from parmmg_tpu.utils.compilecache import (format_ledger,
                                               ledger_violations,
                                               reset_ledger)
    from parmmg_tpu.utils.fixtures import steady_state_migration_scenario

    reset_ledger()
    out = steady_state_migration_scenario(niter=4, cycles=2, n_shards=2)
    assert int(np.asarray(out.tmask).sum()) > 0

    print(format_ledger())
    bad = ledger_violations()
    if bad:
        print("\nLEDGER BUDGET VIOLATIONS:", file=sys.stderr)
        for v in bad:
            print(f"  {v}", file=sys.stderr)
        return 1
    print("\nledger OK: all entry points within variant budgets")
    return 0


if __name__ == "__main__":
    sys.exit(main())
