"""Run the serving pool daemon (parmmg_tpu/serve/daemon.py).

The persistent-pool service of ROADMAP item 3a: one long-lived process
owns the warm compiled group programs AND the persistent compile cache
for its lifetime, fronting ``ServeDriver.submit/poll/fetch`` over a
stdlib HTTP/JSON RPC surface so clients churn while slots stay hot:

    python scripts/serve_daemon.py --port 8077 --cycles 6 &
    python - <<'EOF'
    from parmmg_tpu.serve.client import ServeClient
    cl = ServeClient(port=8077)
    tid = cl.submit(path="/abs/path/job.mesh", tenant="job-1")
    cl.wait(tid); print(cl.poll(tid))
    EOF

Endpoints: POST /submit (429 under backpressure), GET /poll /fetch
/healthz /metrics /report, POST /pause /resume /step /shutdown.
Foregrounds until SIGINT or a /shutdown RPC.

Knobs ride the PARMMG_SERVE_* env surface (see api/knobs.py): PORT,
SLOTS, CHUNK, MAX_QUEUE, STREAM, AUTOSCALE, MAX_SLOTS, TARGET_P99_S,
TIMEOUT_S, MAX_INFLIGHT, MAX_CAPP/MAX_CAPT, SLO_QMIN.  The cache knobs
follow the CLI policy: ``--cache-dir`` (or a pre-set
JAX_COMPILATION_CACHE_DIR) opts the pinned-CPU daemon into the
persistent cache; accelerator backends get it by default.
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# same defensive backend sequence as scripts/serve_run.py: pin CPU
# unless the operator asked for an accelerator via SERVE_DEVICE
if os.environ.get("SERVE_DEVICE", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None,
                    help="bind port (default PARMMG_SERVE_PORT, 8077; "
                         "0 = ephemeral)")
    ap.add_argument("--cycles", type=int,
                    default=int(os.environ.get("SERVE_CYCLES", "6")))
    ap.add_argument("--out", default=None,
                    help="optional merge-free checkpoint directory")
    ap.add_argument("--cache-dir", default=None,
                    help="persistent compile cache directory the "
                         "daemon owns for its lifetime")
    ap.add_argument("--paused", action="store_true",
                    help="start with the serving loop paused")
    ap.add_argument("-v", "--verbose", action="count", default=0)
    args = ap.parse_args()

    # the daemon owns the persistent compile cache: export env BEFORE
    # jax resolves a backend, then drop it again if the backend fell
    # back to unpinned XLA:CPU (the CLI policy, compilecache.py)
    from parmmg_tpu.utils.compilecache import set_cache_env
    set_cache_env(args.cache_dir)

    import jax
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        try:
            from jax._src import xla_bridge as _xb
            _xb._backend_factories.pop("axon", None)
        except Exception:
            pass
        jax.config.update("jax_platforms", "cpu")
    from parmmg_tpu.utils.compilecache import (drop_cache_on_cpu_fallback,
                                               enable_persistent_cache)
    drop_cache_on_cpu_fallback()
    enable_persistent_cache(args.cache_dir)

    from parmmg_tpu.obs import trace as otrace
    from parmmg_tpu.serve.daemon import PoolDaemon

    if args.out:
        os.makedirs(args.out, exist_ok=True)
    daemon = PoolDaemon(host=args.host, port=args.port,
                        start_paused=args.paused, out_dir=args.out,
                        cycles=args.cycles, verbose=args.verbose)
    daemon.start()
    otrace.log(0, f"serve daemon: pid {os.getpid()} on "
                  f"http://{daemon.host}:{daemon.port} "
                  f"(backend {jax.default_backend()})", err=True)
    try:
        while daemon.alive():
            time.sleep(0.5)
    except KeyboardInterrupt:
        otrace.log(0, "serve daemon: SIGINT, shutting down", err=True)
        daemon.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
