"""Phase-level wall-clock profile of one adapt cycle on the live device.

Times each sub-operator (edge table, lengths, split, adjacency, collapse,
swaps, smooth) with block_until_ready, after a compile warm-up, to show
where an adapt cycle's time goes.  Run: python scripts/profile_adapt.py [N]

**Device-timeline capture** (ROADMAP item 1d / 4 prerequisite — the
one-pass profile recipe, TPU-ready, runnable today on the CPU backend):

    PARMMG_PROFILE_DIR=/tmp/prof python scripts/profile_adapt.py 16

arms ``jax.profiler.start_trace`` over the timed section via the obs
capture-window machinery (obs/trace.py) — every ``timeit`` label lands
on the profiler timeline as a ``TraceAnnotation``, and the grouped
paths' ``named_scope`` phase names annotate the XLA ops, so the
TensorBoard/xprof view carries the SAME phase vocabulary as the host
trace JSONL.  The same env knob arms a capture around outer pass
``PARMMG_PROFILE_PASS=start[:stop]`` of any grouped/distributed run
(driver, bench, scale_big workers) — this script is just the smallest
recipe that produces a timeline.

``--json PATH`` additionally writes the captured phase->milliseconds
map to PATH; ``bench.py`` embeds it into the artifact under
``extra.profile_phases`` when ``BENCH_PROFILE_JSON`` points at it, so
a checked-in BENCH round carries the one-pass phase profile and the
next chip session can diff the SAME phase names on a real timeline.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from parmmg_tpu.utils.compilecache import set_cache_env  # noqa: E402

set_cache_env()

import jax
import jax.numpy as jnp
import numpy as np

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.obs import trace as otrace
from parmmg_tpu.ops import adjacency as adj
from parmmg_tpu.ops.adapt import adapt_cycle
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.collapse import collapse_wave
from parmmg_tpu.ops.edges import unique_edges, edge_lengths, unique_priority
from parmmg_tpu.ops.smooth import smooth_wave
from parmmg_tpu.ops.split import split_wave
from parmmg_tpu.ops.swap import swap23_wave, swap32_wave
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric


PHASES_MS: dict[str, float] = {}    # label -> min ms (the --json payload)
INCR: dict = {}                     # incremental-topology occupancy facts


def timeit(label, fn, *args, reps=3, **kw):
    jfn = jax.jit(fn, **kw)
    out = jfn(*args)
    jax.block_until_ready(out)          # compile + warm
    ts = []
    for _ in range(reps):
        # annotate: the label shows on the profiler's device timeline
        # when a capture is armed (free nullcontext otherwise)
        with otrace.annotate(label):
            t0 = time.perf_counter()
            out = jfn(*args)
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
    PHASES_MS[label] = round(min(ts) * 1e3, 3)
    print(f"  {label:28s} {min(ts)*1e3:9.2f} ms")
    return out


def main():
    argv = sys.argv[1:]
    json_out = None
    if "--json" in argv:
        i = argv.index("--json")
        if i + 1 >= len(argv):
            sys.exit("usage: profile_adapt.py [n] [--json PATH]")
        json_out = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    n = int(argv[0]) if argv else 16
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    print(f"N={n}: {len(tet)} tets, capT={mesh.capT}, capP={mesh.capP}, "
          f"device={jax.devices()[0].platform}")

    # capture window: with PARMMG_PROFILE_DIR set this arms the
    # profiler over the timed section below (treated as "pass 0" — the
    # default PARMMG_PROFILE_PASS window); warm-up compiles above this
    # line stay OUT of the capture so the timeline shows steady state
    otrace.profile_pass_begin(0)

    # NOTE: every prep value is produced by a jitted call — eager array
    # code on the tunneled backend pays a transport round trip PER OP
    et = timeit("unique_edges", unique_edges, mesh)
    lens = timeit("edge_lengths", edge_lengths, mesh, et, met)
    timeit("unique_priority", unique_priority, lens, et.emask)
    # Pallas sort-engine sub-phases (PARMMG_PALLAS_SORT): STABLE names —
    # BENCH rounds diff exactly these sort/segment legs on CPU and chip.
    # unique_edges_sort/segment split unique_edges' packed sort from its
    # unique-head selection; priority_sort is unique_priority's argsort
    # leg; face_sort the packed face lexsort (same pass swap_face_pairs
    # times below, under the sort engine's stable name); band_sort the
    # incremental band's local sort.
    from parmmg_tpu.core.mesh import tet_edge_vertices
    from parmmg_tpu.ops import pallas_kernels as pk
    from parmmg_tpu.ops.edges import sort_pairs, priority_order

    def _edge_cols(m):
        ev = tet_edge_vertices(m.tet).reshape(m.capT * 6, 2)
        return (jnp.minimum(ev[:, 0], ev[:, 1]),
                jnp.maximum(ev[:, 0], ev[:, 1]),
                jnp.repeat(m.tmask, 6))
    a6, b6, v6 = jax.jit(_edge_cols)(mesh)
    capP = mesh.capP
    timeit("unique_edges_sort",
           lambda a, b, v: sort_pairs(a, b, v, capP)[0], a6, b6, v6)
    ks6 = jax.jit(lambda a, b, v: jnp.sort(jnp.where(
        v, a * capP + b, jnp.iinfo(jnp.int32).max)))(a6, b6, v6)
    timeit("unique_edges_segment",
           lambda k: pk.segment_first((k,)), ks6)
    neg = jax.jit(lambda le, em: jnp.where(em, -le, jnp.inf))(
        lens, et.emask)
    timeit("priority_sort", priority_order, neg)
    timeit("face_sort", adj.face_sort, mesh)
    timeit("split_wave", lambda m, k: split_wave(m, k), mesh, met)
    timeit("build_adjacency", adj.build_adjacency, mesh)
    timeit("collapse_wave", lambda m, k: collapse_wave(m, k), mesh, met)
    timeit("boundary_edge_tags", adj.boundary_edge_tags, mesh)
    timeit("swap32_wave", lambda m, k: swap32_wave(m, k), mesh, met)
    timeit("swap23_wave", lambda m, k: swap23_wave(m, k), mesh, met)
    # hot-loop attack segments (README "Cycle-cost demolition"): STABLE
    # phase names — BENCH rounds diff these across sessions, keep them.
    # swap_face_pairs: the face-sort records swap23 pairs off when
    # PARMMG_SWAP_FACESORT is on (vs build_adjacency + swap23_wave)
    timeit("swap_face_pairs", adj.face_sort, mesh)
    timeit("swap23_facesort",
           lambda m, k: swap23_wave(m, k, facesort=True), mesh, met)
    # collapse_wave_fullwidth: the PARMMG_COLLAPSE_BAND=0 arm — the
    # donor-band saving is (collapse_wave_fullwidth - collapse_wave)
    os.environ["PARMMG_COLLAPSE_BAND"] = "0"
    try:
        timeit("collapse_wave_fullwidth",
               lambda m, k: collapse_wave(m, k), mesh, met)
    finally:
        del os.environ["PARMMG_COLLAPSE_BAND"]
    timeit("smooth_wave", lambda m, k: smooth_wave(m, k), mesh, met)

    # incremental-topology segments (PARMMG_INCR_TOPO, ops/topo_incr):
    # STABLE phase names — band_extract / band_merge / band_adjacency
    # vs the full-rebuild names above (unique_edges, build_adjacency,
    # boundary_edge_tags).  Timed at a half-full band, the decay-regime
    # shape the knob targets.
    from parmmg_tpu.ops.adapt import adapt_cycle_impl
    from parmmg_tpu.ops.topo_incr import (
        edge_band_records, incr_band_width, incr_build_adjacency,
        incr_unique_edges, topo_init)
    bw = incr_band_width(mesh.capT)
    on = jnp.ones((), bool)

    def _seed(m, t):
        _, t = incr_unique_edges(m, t, on)
        _, t = incr_build_adjacency(m, t, on)
        return t
    topo1 = jax.jit(_seed)(mesh, topo_init(mesh.capT))
    live = np.flatnonzero(np.asarray(mesh.tmask))[:max(1, bw // 2)]
    dirty = np.zeros(mesh.capT, bool)
    dirty[live] = True
    topo_d = topo1._replace(edirty=jnp.asarray(dirty),
                            fdirty=jnp.asarray(dirty))
    dt = jnp.asarray(np.concatenate(
        [live, np.full(bw - len(live), mesh.capT)]).astype(np.int32))
    from parmmg_tpu.ops.topo_incr import band_order
    bkey6, bslot6 = timeit("band_extract", edge_band_records, mesh, dt)
    timeit("band_sort",
           lambda bk, bs: band_order((bk,), bs), bkey6, bslot6)
    timeit("band_merge",
           lambda m, t: incr_unique_edges(m, t, on), mesh, topo_d)
    timeit("band_adjacency",
           lambda m, t: incr_build_adjacency(m, t, on), mesh, topo_d)
    # per-cycle dirty-band occupancy: thread TopoState through real
    # cycles and read counts[8] (dirty tets at cycle start) — the
    # occupancy the band (width bw) must absorb to stay incremental
    step = jax.jit(lambda m, k, w, t: adapt_cycle_impl(
        m, k, w, topo=t, incr=on))
    mi, ki, ti = mesh, met, topo1
    occ = []
    for cyc in range(4):
        mi, ki, cnt, ti = step(mi, ki, jnp.asarray(cyc, jnp.int32), ti)
        occ.append(int(np.asarray(cnt)[8]))
    INCR.update(band_width=bw, band_dirty=int(dirty.sum()),
                dirty_per_cycle=occ)
    print(f"  {'dirty band':28s} width {bw}, per-cycle occupancy {occ}")

    # full cycles, as bench runs them.  adapt_cycle DONATES its inputs, so
    # deep-copy the state before each flavor (and time the second call —
    # the first may absorb a compile or a transport stall)
    m1, k1, c = adapt_cycle(mesh, met, jnp.asarray(0, jnp.int32))
    jax.block_until_ready(c)
    for do_swap in (True, False):
        for rep in range(2):
            m = jax.tree.map(jnp.copy, m1)
            k = jnp.copy(k1)
            jax.block_until_ready(k)
            with otrace.annotate(f"adapt_cycle_swap{int(do_swap)}"):
                t0 = time.perf_counter()
                m, k, c = adapt_cycle(m, k, jnp.asarray(1, jnp.int32),
                                      do_swap=do_swap)
                np.asarray(c)
                dt = time.perf_counter() - t0
        print(f"  adapt_cycle(do_swap={do_swap!s:5}) "
              f"{dt*1e3:9.2f} ms  counts={np.asarray(c)[:5]}")
        PHASES_MS[f"adapt_cycle_swap{int(do_swap)}"] = round(dt * 1e3, 3)

    otrace.profile_pass_end(0)

    if json_out:
        with open(json_out, "w") as f:
            json.dump({"n": n, "ntets": len(tet),
                       "device": jax.devices()[0].platform,
                       "phases_ms": PHASES_MS, "incr": INCR}, f,
                      indent=1)
        print(f"profile: phase timings written to {json_out}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
