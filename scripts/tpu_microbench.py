"""Trustworthy per-primitive timing on the tunneled TPU.

The axon transport adds a 70-110 ms dispatch floor and random stalls, so
single-op timings lie.  Here each primitive runs K reps inside ONE jitted
fori_loop with a data dependency chained through the carry, so wall/K
approximates the true on-device op time with the transport amortized away.

Primitives measured at bench-like shapes (capT=73728, capE=6*capT):
  sort_i32      : argsort of 6*capT int32 keys (the edge-table sort)
  scatter_max   : .at[idx].max into capP pool, duplicate indices (claims)
  scatter_add   : .at[idx].add into capP pool (smooth accumulators)
  gather_rows   : tet row gather [capT,4] -> [capT,4,3] coords
  seg_scan      : associative_scan max over 6*capT (segment heads)
  cross_qual    : quality_from_points on [capT,4,3]
  adjacency     : full build_adjacency on the bench mesh
  edge_table    : full unique_edges on the bench mesh

Run ON TPU (no JAX_PLATFORMS override):  python scripts/tpu_microbench.py
Run on CPU for comparison:               JAX_PLATFORMS=cpu python ...
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp
import numpy as np

K = int(os.environ.get("MB_REPS", "30"))
N_TET = int(os.environ.get("MB_CAPT", "73728"))
N_P = N_TET // 4
N_E = 6 * N_TET


def timed(name, fn, *args):
    f = jax.jit(fn)
    r = f(*args)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    r = f(*args)
    jax.block_until_ready(r)
    dt = (time.perf_counter() - t0) / K
    print(f"{name:14s} {dt * 1e3:9.3f} ms/op   ({K} reps fused)")
    return dt


def loop(body):
    """K-rep fori_loop with carry dependency."""
    def fn(x):
        return jax.lax.fori_loop(0, K, body, x)
    return fn


def main():
    print(f"backend={jax.default_backend()} capT={N_TET} reps={K}")
    key = jax.random.PRNGKey(0)
    keys = jax.random.randint(key, (N_E,), 0, N_P * 197, jnp.int32)
    idx = jax.random.randint(key, (N_E,), 0, N_P, jnp.int32)
    vals = jax.random.uniform(key, (N_E,))
    tets = jax.random.randint(key, (N_TET, 4), 0, N_P, jnp.int32)
    verts = jax.random.uniform(key, (N_P, 3))

    timed("sort_i32", loop(
        lambda i, x: jnp.argsort(x ^ i).astype(jnp.int32)), keys)
    timed("scatter_max", loop(
        lambda i, x: jnp.zeros(N_P, x.dtype).at[idx].max(x) [idx] + x),
        vals)
    timed("scatter_add", loop(
        lambda i, x: jnp.zeros(N_P, x.dtype).at[idx].add(x)[idx] + 0.0 * x),
        vals)
    timed("scatter_uniq", loop(
        lambda i, x: jnp.zeros(N_E, x.dtype).at[
            jnp.arange(N_E)].set(x, unique_indices=True) + 1.0), vals)
    timed("gather_rows", loop(
        lambda i, t: (verts[t].sum((1, 2)) > 0).astype(jnp.int32)[:, None]
        + t), tets)
    timed("seg_scan", loop(
        lambda i, x: jax.lax.associative_scan(jnp.maximum, x ^ i)), keys)

    from parmmg_tpu.ops.quality import quality_from_points

    def qual_body(i, t):
        q = quality_from_points(verts[t])
        return t + (q.sum() > 0).astype(jnp.int32)

    timed("cross_qual", loop(qual_body), tets)

    from parmmg_tpu.core.mesh import make_mesh
    from parmmg_tpu.ops.adjacency import build_adjacency
    from parmmg_tpu.ops.analysis import analyze_mesh
    from parmmg_tpu.ops.edges import unique_edges
    from parmmg_tpu.utils.fixtures import cube_mesh

    vert, tet = cube_mesh(16)
    mesh = make_mesh(vert, tet, capP=N_P, capT=N_TET)
    mesh = analyze_mesh(mesh).mesh

    def adj_body(i, m):
        import dataclasses
        m2 = build_adjacency(m)
        return dataclasses.replace(
            m2, tet=m2.tet + (m2.adja.sum() == -i).astype(jnp.int32))

    timed("adjacency", loop(adj_body), mesh)

    def et_body(i, m):
        import dataclasses
        et = unique_edges(m)
        return dataclasses.replace(
            m, tet=m.tet + (et.nshell.sum() == -i).astype(jnp.int32))

    timed("edge_table", loop(et_body), mesh)


if __name__ == "__main__":
    main()


def payload_scaling():
    """Does scatter cost scale with payload width?  If ~flat, narrow
    scatters should be BATCHED (one wide scatter replaces N narrow)."""
    print(f"\npayload-width scaling (backend={jax.default_backend()})")
    key = jax.random.PRNGKey(1)
    idx = jax.random.randint(key, (N_E,), 0, N_P, jnp.int32)
    for w in (1, 2, 4, 8, 16):
        vals = jax.random.uniform(key, (N_E, w))

        def body(i, x):
            out = jnp.zeros((N_P, w), x.dtype).at[idx].add(x)
            return x + out[idx] * 0.0 + i * 0.0

        timed(f"scat_add_w{w}", loop(body), vals)
    for w in (1, 4, 8):
        vals = jax.random.uniform(key, (N_E, w))

        def body(i, x):
            out = jnp.zeros((N_P, w), x.dtype).at[idx].max(x)
            return x + out[idx] * 0.0 + i * 0.0

        timed(f"scat_max_w{w}", loop(body), vals)
    # gather width scaling
    for w in (1, 8):
        tbl = jax.random.uniform(key, (N_P, w))

        def body(i, x):
            return x + tbl[idx.astype(jnp.int32) + i * 0].sum(-1) * 0.0

        timed(f"gather_w{w}", loop(body),
              jax.random.uniform(key, (N_E,)))
