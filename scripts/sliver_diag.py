"""Diagnose the surviving worst tets after adaptation + polish (CPU).

Prints, for the N worst tets: quality, how many vertices/faces/edges are
boundary/required, and which polish op could in principle apply — to see
why sliver_polish leaves them behind.
Run: python scripts/sliver_diag.py [N] [cycles]
"""
from __future__ import annotations

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

try:
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from parmmg_tpu.core import constants as C
from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.ops.adapt import adapt_cycles_fused, sliver_polish
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.ops.quality import quality_from_points
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 12
    cycles = int(sys.argv[2]) if len(sys.argv) > 2 else 9
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)

    m, k = mesh, met
    for b in range(0, cycles, 3):
        nc = min(3, cycles - b)
        m, k, _ = adapt_cycles_fused(m, k, jnp.asarray(b, jnp.int32),
                                     n_cycles=nc, swap_every=3)
    for w in range(4):
        m, pc = sliver_polish(m, k, jnp.asarray(100 + w, jnp.int32))
        pcs = np.asarray(pc)
        print(f"polish {w}: collapse {pcs[0]} swap {pcs[1]} move {pcs[2]}")
        if pcs[0] == 0 and pcs[1] == 0:
            break

    q = np.asarray(quality_from_points(m.vert[m.tet]))
    tm = np.asarray(m.tmask)
    q = np.where(tm, q, np.inf)
    worst = np.argsort(q)[:12]
    tv = np.asarray(m.tet)
    vtag = np.asarray(m.vtag)
    ftag = np.asarray(m.ftag)
    etag = np.asarray(m.etag)
    vh = np.asarray(m.vert)
    for t in worst:
        vids = tv[t]
        nb = sum(1 for v in vids if vtag[v] & C.MG_BDY)
        nreq = sum(1 for v in vids if vtag[v] & C.MG_REQ)
        nbf = sum(1 for f in range(4) if ftag[t, f] & C.MG_BDY)
        nte = sum(1 for e in range(6) if etag[t, e] & (C.MG_BDY | C.MG_GEO
                                                       | C.MG_REQ))
        print(f"tet {t}: q={q[t]:.6f} bdyV={nb}/4 reqV={nreq} "
              f"bdyF={nbf} taggedE={nte} verts={[tuple(np.round(vh[v],3)) for v in vids]}")


if __name__ == "__main__":
    main()
