"""Attribute adapt-cycle cost by timing flag variants on the live device.

full - light = swap cost; light - nosmooth = smooth cost; nosmooth =
split+collapse+2 adjacency builds.  Each variant is one jit graph; timing
is min of 3 reps from a fresh copy of the same state (adapt_cycle donates
its inputs).  Run: python scripts/cycle_variants.py [N]
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
_cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "..", ".jax_cache")
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _cache)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax
import jax.numpy as jnp
import numpy as np

from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.ops.adapt import adapt_cycle
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[: len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    print(f"N={n}: {len(tet)} tets, capT={mesh.capT}, "
          f"device={jax.devices()[0].platform}")

    # advance one cycle so the timed state has mixed work
    m1, k1, c = adapt_cycle(mesh, met, jnp.asarray(0, jnp.int32))
    jax.block_until_ready(c)

    variants = [
        ("full  (split+col+swap+smooth)", dict()),
        ("light (split+col+smooth)", dict(do_swap=False)),
        ("bare  (split+col)", dict(do_swap=False, do_smooth=False)),
        ("smooth2 (light, 2 waves)", dict(do_swap=False, smooth_waves=2)),
    ]
    for label, kw in variants:
        best = None
        for rep in range(3):
            m = jax.tree.map(jnp.copy, m1)
            k = jnp.copy(k1)
            jax.block_until_ready(k)
            t0 = time.perf_counter()
            m, k, c = adapt_cycle(m, k, jnp.asarray(1, jnp.int32), **kw)
            np.asarray(c)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        print(f"  {label:34s} {best*1e3:9.2f} ms")


if __name__ == "__main__":
    main()
