"""Multi-host pod gate (scripts/run_tests.sh --multihost).

Runs the SMALL 2-process pod scenario on localhost (virtual CPU
devices, gloo collectives) through scripts/multihost_run.py and FAILS
(exit 1) unless the pod runtime's three contracts hold:

1. **bit-for-bit parity**: the 2-process run's merged mesh+metric hash
   equals the single-process dist run of the same scenario
   (``extra.parity_ok``) — the every-rank-agrees SPMD contract;
2. **shared compile cache**: after the warm phase, EVERY worker of the
   timed run pays ~zero backend-compile seconds (the warmed persistent
   cache is the mechanism that attacks the compile-dominated
   MULTIHOST2P_r04 wall clock);
3. **allgather-free hot path**: ``mh.hot_allgather_bytes == 0`` on
   every worker — band tables replicated through ``pod.gather_band``
   collectives only, the metered ``pull_host`` escape hatch untouched
   (runtime mirror of lint rule R7);

plus two pod failure-mode drills: a worker killed mid-run by an armed
``multihost.exchange`` fault (pass 1, after the pass-0 checkpoint) is
the EXPECTED failure mode — the parent relaunches with resume and the
finished mesh must be bit-identical to the uninterrupted run; and the
same drill with the worker WEDGED instead of killed (``hang=600``
fault action) — the heartbeat lease (``--lease``) must detect the
stalled rank, kill the pack and drive the identical resume path to
the identical bits.

First invocation pays the scenario's compiles into the repo-local
``.jax_cache_mh`` (warm phase + the 1-process reference); repeat
invocations run warm end to end.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(ROOT, "scripts", "multihost_run.py")

# small scenario: 2 processes x 1 device, 48-tet cube, 2 passes — the
# cheapest run that exercises split -> adapt -> band-exchange-migrate
# -> weld -> merge across processes
SCEN = ["--np", "2", "--devices", "2", "--n", "2",
        "--niter", "2", "--cycles", "2", "--timeout", "1500"]

FAILS: list[str] = []


def check(ok: bool, msg: str) -> None:
    tag = "ok" if ok else "MULTIHOST FAIL"
    print(f"  {tag}: {msg}", file=sys.stdout if ok else sys.stderr)
    if not ok:
        FAILS.append(msg)


def run(extra_args, env_over=None) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("PARMMG_RETRY_BASE_S", "0")
    env.update(env_over or {})
    out = subprocess.run(
        [sys.executable, RUNNER] + SCEN + extra_args,
        env=env, capture_output=True, timeout=1800)
    if out.returncode != 0:
        sys.stderr.write(out.stderr.decode()[-2000:])
        raise RuntimeError(f"runner exited {out.returncode}")
    return json.loads(out.stdout.decode().strip().splitlines()[-1])


def main() -> int:
    td = tempfile.mkdtemp(prefix="parmmg_mh_gate_")

    # ---- 1-3. parity + warm cache + allgather-free hot path ------------
    print("--- multihost gate: 2-process pod run (parity + cache + "
          "hot-path meter)")
    doc = run(["--parity"])
    ex = doc["extra"]
    check(ex.get("parity_ok") is True,
          f"2-process merged mesh bit-identical to the 1-process dist "
          f"run (hash {ex.get('hash', '?')[:12]})")
    workers = ex.get("workers", [])
    check(len(workers) == 2, f"both workers reported ({len(workers)})")
    for w in workers:
        check(w["hot_allgather_bytes"] == 0,
              f"worker {w['pid']}: mh.hot_allgather_bytes == 0 "
              f"(got {w['hot_allgather_bytes']})")
        check(w["compile_s"] < 30.0,
              f"worker {w['pid']} pays ~zero compiles via the shared "
              f"warm cache ({w['compile_s']}s backend compile)")
        check(w["band_exchange_bytes"] > 0,
              f"worker {w['pid']} exchanged band tables through the "
              f"pod collective ({w['band_exchange_bytes']:.0f} B)")
    check(ex.get("ledger_regressions") == [],
          f"zero compile-ledger growth "
          f"({ex.get('ledger_regressions')})")
    base_hash = ex.get("hash")

    # ---- 4. worker-crash drill: checkpoint + resume --------------------
    print("--- multihost gate: worker crash -> resume drill")
    ck = os.path.join(td, "ckpt")
    os.makedirs(ck, exist_ok=True)
    # worker 1 dies at its pass-1 extend exchange (nth-2 of the
    # key-matched site — AFTER the pass-0 checkpoint); retries off so
    # the fault is fatal, the parent relaunches with resume
    doc2 = run(["--no-warm", "--ckpt", ck,
                "--fault", "1:multihost.exchange:key=extend;nth-2"],
               env_over={"PARMMG_RETRY_MAX": "0"})
    ex2 = doc2["extra"]
    check("crashed_rc" in ex2,
          f"armed exchange fault killed worker 1 "
          f"(rc {ex2.get('crashed_rc')})")
    check(ex2.get("resumed") is True, "run resumed from the pass-0 "
                                      "checkpoint")
    check(ex2.get("hash") == base_hash,
          "resumed run finished bit-identical to the uninterrupted "
          "run")

    # ---- 5. wedged-worker drill: heartbeat lease -> kill -> resume -----
    print("--- multihost gate: wedged worker -> lease expiry -> resume "
          "drill")
    ck2 = os.path.join(td, "ckpt_hang")
    os.makedirs(ck2, exist_ok=True)
    # worker 1 HANGS (hang=600: sleeps, never raises, never exits) at
    # its pass-1 extend exchange — after the pass-0 checkpoint and
    # after both ranks' first heartbeat.  Only the lease can end this
    # run inside the gate budget: the parent must see the stale
    # heartbeat, kill the pack (rc 9) and relaunch with resume.
    # Lease sizing: it must exceed the pack's longest LEGITIMATE
    # beat-free window — on a single shared core the whole pack stops
    # beating while any rank recompiles a residual program (the peers
    # block in the next collective), ~25-30s here; 60s is 2x margin
    # and still far under the 600s wedge (gloo happily waits minutes
    # inside a collective, measured — the blocked healthy rank does
    # not time out first).
    doc3 = run(["--no-warm", "--ckpt", ck2, "--lease", "60",
                "--fault",
                "1:multihost.exchange:key=extend;nth-2;hang=600"],
               env_over={"PARMMG_HEARTBEAT_S": "0.5"})
    ex3 = doc3["extra"]
    check(bool(ex3.get("stale_heartbeat")),
          f"heartbeat lease expired for the wedged pack "
          f"(stale ranks {ex3.get('stale_heartbeat')})")
    check(ex3.get("crashed_rc") == 9,
          f"lease expiry killed the pack with the stale-lease rc "
          f"(rc {ex3.get('crashed_rc')})")
    check(ex3.get("resumed") is True,
          "wedged run resumed from the pass-0 checkpoint")
    check(ex3.get("hash") == base_hash,
          "post-hang resumed run finished bit-identical to the "
          "uninterrupted run")

    if FAILS:
        print(f"\nmultihost gate FAILED ({len(FAILS)} checks):",
              file=sys.stderr)
        for f in FAILS:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("\nmultihost OK: 2-process parity, warm-cache ~zero worker "
          "compiles, allgather-free hot path, crash->resume and "
          "wedge->lease->resume bit-identity")
    return 0


if __name__ == "__main__":
    sys.exit(main())
