"""Steady-state fused-block timing (bench proxy, ~2 min vs 9 min bench).
Times the SECOND and THIRD 3-cycle fused block after warm-up.
Run: python scripts/block_time.py [N]"""
from __future__ import annotations
import os, sys, time
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
import jax, jax.numpy as jnp, numpy as np
from parmmg_tpu.core.mesh import make_mesh
from parmmg_tpu.ops.adapt import adapt_cycles_fused
from parmmg_tpu.ops.analysis import analyze_mesh
from parmmg_tpu.utils.fixtures import cube_mesh, analytic_iso_metric

def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16
    vert, tet = cube_mesh(n)
    mesh = make_mesh(vert, tet, capP=3 * len(vert), capT=3 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    h = analytic_iso_metric(vert, "shock", h=1.5 / n)
    met = jnp.zeros(mesh.capP, mesh.vert.dtype).at[:len(h)].set(
        jnp.asarray(h, mesh.vert.dtype)).at[len(h):].set(1.0)
    print(f"N={n} capT={mesh.capT} device={jax.default_backend()}")
    m, k = mesh, met
    times = []
    for b in range(5):
        t0 = time.perf_counter()
        m, k, counts = adapt_cycles_fused(m, k, jnp.asarray(3 * b, jnp.int32),
                                          n_cycles=3, swap_every=3)
        c = np.asarray(counts)
        dt = time.perf_counter() - t0
        times.append(dt)
        print(f"block {b}: {dt*1e3:8.1f} ms  live={c[-1][5]}")
    print(f"steady median: {np.median(times[1:])*1e3:.1f} ms/block")

if __name__ == "__main__":
    main()
