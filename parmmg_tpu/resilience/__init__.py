"""Fault injection + self-healing recovery for the adapt/serve stack.

The reference's graded-failure contract (``failed_handling``,
libparmmg1.c:974-1011) is that the library never dies holding user
data: it degrades to ``PMMG_LOWFAILURE`` and hands back a conforming
mesh.  This package turns the reproduction's scattered implicit
degrade paths (driver OOM catches, the polish-worker skip, the serve
timeout expiry) into one explicit, injectable, gated subsystem:

- :mod:`~parmmg_tpu.resilience.faults` — a named-faultpoint registry
  armed via ``PARMMG_FAULT=site[:trigger]``.  Each site raises its
  REAL failure shape (``XlaRuntimeError`` for device dispatches, a
  non-zero subprocess exit for the polish worker, ``OSError`` for
  checkpoint IO) so the recovery code below is exercised, never
  simulated;
- :mod:`~parmmg_tpu.resilience.recover` — the deadline + retry +
  exponential-backoff wrapper (``PARMMG_RETRY_MAX`` /
  ``PARMMG_RETRY_BASE_S`` / ``PARMMG_RETRY_DEADLINE_S``) and the
  ordered escalation ladder the degrade paths report through
  (``LADDER``: retry -> packed->dense halo -> device->host analysis ->
  grouped->merged polish -> LOWFAILURE), each step an obs trace event
  plus a ``resilience.*`` metrics counter;
- :mod:`~parmmg_tpu.resilience.checkpoint` — pass-level
  checkpoint/resume (``PARMMG_CKPT_DIR`` / ``PARMMG_CKPT_EVERY``): the
  grouped outer loop snapshots (mesh, met, displaced part) after each
  completed pass, plus the merge-free ``stacked_to_distributed_files``
  shard snapshot of the pre-merge stacked state — the reference's
  ``-distributed-output`` checkpoint role.  ``cli.py -resume`` and
  ``scripts/scale_big.py --resume`` restart a killed run from the last
  completed pass, bit-identical to an uninterrupted run.  The
  crash-loop breaker (``crash_loop``, ``PARMMG_RESUME_MAX``) bounds
  the resume ladder itself: a pass that deterministically kills its
  worker is escalated past instead of resumed forever;
- :mod:`~parmmg_tpu.resilience.watchdog` — the HANG mirror of the
  fault registry: deadline watchdogs (``Deadline`` /
  ``run_with_deadline``, knobs ``PARMMG_DEADLINE_*``) convert a
  wedged dispatch/exchange/subprocess/serve-step into a
  ``WatchdogTimeout`` that enters ``retry_call`` like any injected
  fault, and per-rank heartbeat leases (``beat`` / ``stale_ranks``,
  ``PARMMG_HEARTBEAT_*``) let the pod supervisor treat a stalled
  worker like a crashed one (kill the pack, relaunch with resume).
  Provoked on demand via the ``hang=S`` fault action; soaked by
  ``scripts/chaos_soak.py``.

Everything here is host-side bookkeeping: no jax import at module
scope, zero new compile families on the fault-free path (gated by
``scripts/run_tests.sh --chaos``).
"""
from .faults import FAULTS, fault_trigger, faultpoint        # noqa: F401
from .recover import (LADDER, RetryBudgetExhausted,          # noqa: F401
                      ladder_step, retry_call)
from .watchdog import (Deadline, WatchdogTimeout,            # noqa: F401
                       run_with_deadline)
