"""Retry/backoff wrapper + the ordered escalation ladder.

The degrade behavior of this stack predates this module — the driver
caught OOM, the grouped path skipped a crashed polish worker, the dist
path fell back from device to host analysis — but each path was its
own ad-hoc ``except`` with its own (or no) reporting.  This module is
the shared spine:

- :func:`retry_call` — bounded retries with exponential backoff and an
  optional wall-clock deadline, knobs ``PARMMG_RETRY_MAX`` (default
  2 retries after the first failure), ``PARMMG_RETRY_BASE_S`` (default
  0.05 s, doubled per attempt) and ``PARMMG_RETRY_DEADLINE_S`` (0 =
  off).  Exhaustion raises :class:`RetryBudgetExhausted` (the original
  failure chained as ``__cause__``) — the signal the driver converts
  into a ``PMMG_LOWFAILURE`` conforming save;
- :data:`LADDER` + :func:`ladder_step` — the documented escalation
  order every degrade path reports through.  Each step taken emits an
  obs trace event (``resilience.ladder``) and bumps a
  ``resilience.<step>`` counter, so a run's failure story is readable
  from its trace/metrics instead of scattered stderr lines.

Ladder order (least to most degraded; each step preserves the
conforming-mesh invariant):

    retry          re-run the failed unit (chunk dispatch / worker /
                   band exchange)
    mh_allgather   pod band-exchange collective failed -> metered
                   pull_host allgather (bit-identical values, counted
                   bytes — parallel/pod.py escape hatch)
    halo_dense     packed halo exchange failed -> dense layout retry
    host_analysis  device analysis refresh failed/overflowed -> host
    merged_polish  grouped polish worker gone -> skip, the caller's
                   merged-mesh polish + repair tail covers quality
    lowfailure     restore the last conforming state, return
                   PMMG_LOWFAILURE (failed_handling,
                   libparmmg1.c:974-1011)
"""
from __future__ import annotations

import os
import time

__all__ = [
    "LADDER", "RetryBudgetExhausted", "WorkerExitError", "ladder_step",
    "retry_call", "retry_env",
]

LADDER = ("retry", "mh_allgather", "halo_dense", "host_analysis",
          "merged_polish", "lowfailure")

# deterministic capacity signals must not be retried: re-running the
# identical program reproduces the identical overflow
NEVER_RETRY = (MemoryError,)


class RetryBudgetExhausted(RuntimeError):
    """All retries for ``site`` failed; ``__cause__`` is the last
    failure.  Callers translate this into the next ladder step
    (typically ``lowfailure`` at the driver)."""

    def __init__(self, site: str, attempts: int):
        super().__init__(
            f"retry budget exhausted at {site} after {attempts} "
            "attempt(s)")
        self.site = site
        self.attempts = attempts


class WorkerExitError(RuntimeError):
    """A subprocess worker exited non-zero (the real tunnel-crash
    failure shape the polish path recovers from)."""

    def __init__(self, site: str, returncode: int, stderr: str = ""):
        tail = stderr[-2000:] if stderr else ""
        super().__init__(f"{site} worker exited rc={returncode}"
                         + (f"\n{tail}" if tail else ""))
        self.site = site
        self.returncode = returncode
        self.stderr = stderr


def retry_env() -> tuple[int, float, float]:
    """(max_retries, backoff base seconds, deadline seconds)."""
    mx = int(os.environ.get("PARMMG_RETRY_MAX", "2") or 2)
    base = float(os.environ.get("PARMMG_RETRY_BASE_S", "0.05") or 0.05)
    dl = float(os.environ.get("PARMMG_RETRY_DEADLINE_S", "0") or 0)
    return max(0, mx), max(0.0, base), max(0.0, dl)


def ladder_step(step: str, site: str = "", detail: str = "") -> None:
    """Record one escalation-ladder step: trace event + counter + an
    imprim-gated warning line (the one print path, obs/trace.py)."""
    from ..obs import trace as otrace
    from ..obs.metrics import REGISTRY
    if step not in LADDER:
        raise ValueError(f"unknown ladder step {step!r} "
                         f"(ladder: {LADDER})")
    REGISTRY.counter(f"resilience.{step}").inc()
    otrace.event("resilience.ladder", step=step, site=site,
                 detail=detail[:500])
    otrace.log(1, f"  ## resilience: {step}"
                  + (f" at {site}" if site else "")
                  + (f" ({detail[:200]})" if detail else ""), err=True)


def retry_call(fn, site: str, max_retries: int | None = None,
               base_s: float | None = None,
               deadline_s: float | None = None,
               initial_failure: BaseException | None = None):
    """Call ``fn()`` with up to ``max_retries`` re-attempts after a
    failure, exponential backoff between attempts, and an optional
    wall-clock deadline that stops retrying early.

    ``initial_failure``: the caller already made (and lost) attempt 0
    inline — e.g. the pipelined chunk dispatch, whose first attempt
    rides the fast path — so only the RETRY budget remains.  With
    ``PARMMG_RETRY_MAX=0`` that exhausts immediately: fail-fast mode.

    ``NEVER_RETRY`` failures (deterministic capacity signals) pass
    straight through."""
    env_mx, env_base, env_dl = retry_env()
    mx = env_mx if max_retries is None else max(0, int(max_retries))
    base = env_base if base_s is None else max(0.0, float(base_s))
    dl = env_dl if deadline_s is None else max(0.0, float(deadline_s))
    t0 = time.monotonic()
    last: BaseException | None = initial_failure
    attempts = 1 if initial_failure is not None else 0
    retries_left = mx
    while True:
        if last is not None:
            if isinstance(last, NEVER_RETRY):
                raise last
            if retries_left <= 0 or (dl and time.monotonic() - t0 >= dl):
                from ..obs.metrics import REGISTRY
                REGISTRY.counter("resilience.retry_exhausted").inc()
                raise RetryBudgetExhausted(site, attempts) from last
            # backoff then re-attempt (attempt k sleeps base * 2^(k-1))
            ladder_step("retry", site=site, detail=repr(last))
            if base > 0:
                time.sleep(min(base * (2 ** (attempts - 1)), 30.0))
            retries_left -= 1
        try:
            return fn()
        except NEVER_RETRY:
            raise
        except Exception as e:
            last = e
            attempts += 1
