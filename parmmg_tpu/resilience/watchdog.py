"""Deadline watchdogs + heartbeat leases: make the WEDGE a ladder rung.

PR 9's fault registry made every failure that *raises* recoverable,
but ParMmg's production failure mode on clusters is the hang: a
collective that never returns, a polish subprocess that sleeps
forever, a serving step stuck mid-compile.  The LOWFAILURE contract
promises a usable mesh in *bounded time* (failed_handling,
libparmmg1.c:974-1011) — a hang breaks the "bounded" half without
tripping a single ``except``.  This module converts hangs into the
exception shape the existing ladder already handles:

- :class:`Deadline` — a nestable, polled deadline context for code
  that can check cooperatively (``dl.check()`` raises
  :class:`WatchdogTimeout` once ANY enclosing deadline of the calling
  thread expired; the earliest-armed expired deadline wins);
- :func:`run_with_deadline` — the monitor-thread form for code that
  CANNOT poll (a blocked collective, ``jax.block_until_ready``, a
  wedged RPC): the guarded call runs in a worker thread and the
  caller raises ``WatchdogTimeout`` when it overruns.  SIGALRM-free
  by design: signals do not interrupt jax runtime waits and are
  main-thread-only anyway.  The abandoned worker thread is daemonic
  and harmless by construction at every guarded site — writebacks are
  idempotent and deterministic, so a late commit writes the same
  bytes the retry writes (see the per-site notes at the call sites);
- **first-use grace** (``PARMMG_DEADLINE_GRACE_S``): a site's FIRST
  guarded call gets extra seconds before its deadline fires, so a
  cold XLA compile (minutes, legitimate) is distinguished from a
  wedged warm step (seconds, pathological) without per-site tuning;
- **heartbeat leases** (:func:`beat` / :func:`stale_ranks`): pod
  workers touch a per-rank file inside ``multihost.hot_path``
  sections; the ``scripts/multihost_run.py`` supervisor holds a
  lease per worker and treats a stale lease exactly like a non-zero
  exit — kill the pack, relaunch with ``resume=True``.  A lease only
  becomes revocable AFTER the first beat (a missing file is never
  stale): startup/compile time is covered by the phase timeout, not
  the lease.

An expired deadline raises :class:`WatchdogTimeout`, a plain
``RuntimeError`` subclass, so it enters ``recover.retry_call`` exactly
like an injected fault and the existing ladder (retry -> degrade ->
checkpoint-resume -> LOWFAILURE) handles it unchanged.  Every expiry
bumps ``resilience.watchdog_timeouts`` and emits a
``watchdog.timeout`` trace event.

All deadlines default OFF (knobs ``PARMMG_DEADLINE_*`` = 0): the
zero-config run is bit-neutral and thread-free, and the chaos gate
arms them scenario by scenario.  Host-side stdlib only — no jax
import, no new compile families.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = [
    "Deadline", "WatchdogTimeout", "beat", "deadline_knob",
    "first_use_grace", "heartbeat_config", "record_timeout",
    "run_with_deadline", "stale_ranks",
]


class WatchdogTimeout(RuntimeError):
    """A watchdog deadline expired at ``site`` after ``seconds``.
    Deliberately a plain ``RuntimeError``: ``retry_call`` treats it
    like any transient failure (retry, then the site's degrade rung),
    and ``NEVER_RETRY`` does not match it."""

    def __init__(self, site: str, seconds: float):
        super().__init__(f"watchdog deadline expired at {site} after "
                         f"{seconds:g}s")
        self.site = site
        self.seconds = float(seconds)


def record_timeout(site: str, seconds: float) -> None:
    """Account one watchdog expiry (counter + trace event + log line).
    ``Deadline.check`` / ``run_with_deadline`` call it on their own
    expiries; external enforcers that kill by other means (the polish
    ``subprocess.run(timeout=)`` path) call it before raising
    :class:`WatchdogTimeout` so every expiry is visible in ONE
    place regardless of the killing mechanism."""
    from ..obs import trace as otrace
    from ..obs.metrics import REGISTRY
    REGISTRY.counter("resilience.watchdog_timeouts").inc()
    otrace.event("watchdog.timeout", site=site, seconds=float(seconds))
    otrace.log(1, f"  ## resilience: watchdog deadline expired at "
                  f"{site} after {seconds:g}s", err=True)


def deadline_knob(name: str) -> float:
    """Read a ``PARMMG_DEADLINE_*`` / timeout knob in seconds;
    unset/empty/0 means the watchdog is OFF (the default posture:
    deadlines are armed per scenario, never ambient)."""
    try:
        return max(0.0, float(os.environ.get(name, "0") or 0))
    except ValueError:
        return 0.0


# ---------------------------------------------------------------------------
# polled deadlines (cooperative form)
# ---------------------------------------------------------------------------
_LOCAL = threading.local()


def _stack() -> list:
    st = getattr(_LOCAL, "stack", None)
    if st is None:
        st = _LOCAL.stack = []
    return st


class Deadline:
    """Nestable polled deadline for the calling thread.

    ``check()`` raises :class:`WatchdogTimeout` when ANY deadline on
    the thread's enter-ordered stack has expired — the outermost
    (earliest-armed) expired one wins, so a tight inner deadline can
    never mask an exhausted outer budget.  ``seconds <= 0`` disarms
    this level (it still nests)."""

    def __init__(self, seconds: float, site: str = "deadline"):
        self.seconds = float(seconds)
        self.site = site
        self._expires_at: float | None = None

    def __enter__(self) -> "Deadline":
        self._expires_at = (time.monotonic() + self.seconds
                            if self.seconds > 0 else None)
        _stack().append(self)
        return self

    def __exit__(self, *exc) -> bool:
        st = _stack()
        if self in st:
            st.remove(self)
        return False

    @property
    def expired(self) -> bool:
        return (self._expires_at is not None
                and time.monotonic() >= self._expires_at)

    def remaining(self) -> float | None:
        """Seconds left on THIS level (None when disarmed)."""
        if self._expires_at is None:
            return None
        return max(0.0, self._expires_at - time.monotonic())

    def check(self) -> None:
        """Raise for the first expired deadline enclosing this thread
        (enter order — the outer budget outranks the inner one)."""
        for d in _stack():
            if d.expired:
                record_timeout(d.site, d.seconds)
                raise WatchdogTimeout(d.site, d.seconds)


# ---------------------------------------------------------------------------
# monitor-thread deadlines (for calls that cannot poll)
# ---------------------------------------------------------------------------
# sites that completed at least one guarded call: their first-use
# compile grace is consumed (a FAILED first call consumes it too — the
# programs it compiled are cached either way)
_FIRST_DONE: set[str] = set()
_FIRST_LOCK = threading.Lock()


def first_use_grace(site: str) -> float:
    """Extra seconds granted to ``site``'s FIRST guarded call: a stuck
    cold compile and a wedged warm step are different diagnoses, and
    only the knob owner knows the compile budget
    (``PARMMG_DEADLINE_GRACE_S``, default 300)."""
    with _FIRST_LOCK:
        if site in _FIRST_DONE:
            return 0.0
    try:
        return max(0.0, float(
            os.environ.get("PARMMG_DEADLINE_GRACE_S", "300") or 300))
    except ValueError:
        return 300.0


def run_with_deadline(fn, seconds: float, site: str):
    """Run ``fn()`` bounded by a wall-clock deadline.

    ``seconds <= 0`` calls inline (watchdog off — the ambient
    default).  Otherwise ``fn`` runs in a daemon worker thread and the
    caller waits ``seconds + first_use_grace(site)``; overrun raises
    :class:`WatchdogTimeout` here while the worker is ABANDONED (its
    late result is discarded).  Guarded sites must therefore be
    idempotent-on-retry — every wired site already is, because the
    retry ladder re-runs them from intact inputs.  The abandoned
    thread rides on the raised exception as ``.thread`` so a caller
    serializing on shared state (the serve daemon's driver lock) can
    wait it out before dispatching again."""
    s = float(seconds)
    if s <= 0:
        return fn()
    box: dict = {}
    done = threading.Event()

    def _target():
        try:
            box["value"] = fn()
        except BaseException as e:            # noqa: BLE001 — relayed
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_target, daemon=True,
                         name=f"parmmg-watchdog-{site}")
    eff = s + first_use_grace(site)
    t.start()
    if not done.wait(eff):
        record_timeout(site, eff)
        exc = WatchdogTimeout(site, eff)
        exc.thread = t
        raise exc
    with _FIRST_LOCK:
        _FIRST_DONE.add(site)
    if "error" in box:
        raise box["error"]
    return box.get("value")


# ---------------------------------------------------------------------------
# heartbeat leases (worker side: beat; supervisor side: stale_ranks)
# ---------------------------------------------------------------------------
_HB = {"last": 0.0}


def heartbeat_config() -> tuple[str, float]:
    """(heartbeat dir, beat interval seconds).  Dir == "" disables —
    ``PARMMG_MH_HEARTBEAT_DIR`` is set by the pod supervisor, never by
    hand."""
    d = os.environ.get("PARMMG_MH_HEARTBEAT_DIR", "")
    try:
        iv = float(os.environ.get("PARMMG_HEARTBEAT_S", "2") or 2)
    except ValueError:
        iv = 2.0
    return d, max(0.05, iv)


def _hb_path(d: str, rank: int) -> str:
    return os.path.join(d, f"hb.{rank}")


def beat(rank: int | None = None) -> str | None:
    """Touch this process's per-rank heartbeat file, throttled to the
    beat interval.  No-op (one env read) unless the supervisor armed
    ``PARMMG_MH_HEARTBEAT_DIR``.  Heartbeats are advisory: an IO
    failure here must never kill the work it is reporting on."""
    d, iv = heartbeat_config()
    if not d:
        return None
    now = time.monotonic()
    if now - _HB["last"] < iv:
        return None
    if rank is None:
        rank = int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
    path = _hb_path(d, rank)
    try:
        os.makedirs(d, exist_ok=True)
        with open(path, "a"):
            pass
        os.utime(path, None)
    except OSError:
        return None
    _HB["last"] = now
    from ..obs.metrics import REGISTRY
    REGISTRY.counter("resilience.heartbeats").inc()
    return path


def stale_ranks(d: str, lease_s: float, ranks,
                now: float | None = None) -> list[int]:
    """Supervisor-side staleness rule (pure, host-only): ranks whose
    lease expired.  A lease is revocable only AFTER the first beat —
    the heartbeat file must EXIST and be older than ``lease_s``.  A
    rank that never beat is never stale (startup + cold compile run
    before the first ``hot_path`` beat; the phase timeout covers a
    worker that dies there).  ``lease_s <= 0`` disables."""
    out: list[int] = []
    if lease_s <= 0:
        return out
    t = time.time() if now is None else now
    for r in ranks:
        try:
            m = os.stat(_hb_path(d, int(r))).st_mtime
        except OSError:
            continue
        if t - m > lease_s:
            out.append(int(r))
    return out
