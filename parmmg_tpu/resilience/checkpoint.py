"""Pass-level checkpoint/resume for the grouped outer loop.

A killed 1M-tet grouped run used to restart from scratch: every pass
is minutes of wall time, and the tunnel worker's favorite failure mode
is dying mid-pass.  This module makes the outer pass the unit of
durability:

- after each completed outer pass the loop saves the merged state
  (mesh fields + metric + the DISPLACED partition + the pass index)
  as one ``.npz`` under ``PARMMG_CKPT_DIR`` — the exact-resume payload
  (npz round-trips float64 bit-for-bit, which the Medit ASCII writer's
  ``%.15g`` does not);
- the pre-merge STACKED state of a checkpointed pass is additionally
  snapshotted through ``io.distributed.stacked_to_distributed_files``
  (merge-free per-group ``name.<rank>.mesh`` shard files — the
  reference's ``-distributed-output`` checkpoint contract), so a
  checkpoint is also inspectable/loadable by any Medit consumer;
- ``PARMMG_CKPT_EVERY`` (default 1) thins the cadence;
- resume (``cli.py -resume`` / ``scale_big.py --resume`` /
  ``grouped_adapt(resume=True)``) loads the NEWEST complete pass
  checkpoint and re-enters the loop at the next pass.  Passes are
  deterministic functions of their input state (the quiet-group
  fixed-point argument, parallel/sched.py), so a resumed run finishes
  bit-identical to an uninterrupted one — asserted by
  ``scripts/chaos_check.py``.

Checkpoint IO must never kill the run it is protecting: every write is
atomic (tmp + ``os.replace``) and every failure — including the
injected ``io.checkpoint`` OSError — is swallowed into a
``resilience.checkpoint_failures`` counter + trace event; the run
continues unprotected rather than dying.
"""
from __future__ import annotations

import json
import os
import re

import numpy as np

from .faults import faultpoint

__all__ = [
    "ckpt_config", "ckpt_due", "crash_loop", "latest_dist_checkpoint",
    "latest_pass_checkpoint", "load_dist_checkpoint",
    "load_pass_checkpoint", "resume_max", "save_dist_checkpoint",
    "save_pass_checkpoint", "snapshot_stacked",
]

_CKPT_RE = re.compile(r"\.pass(\d+)\.npz$")
_DCKPT_RE = re.compile(r"\.dpass(\d+)\.npz$")


def ckpt_config() -> tuple[str, int]:
    """(checkpoint dir, pass cadence); dir == "" disables."""
    d = os.environ.get("PARMMG_CKPT_DIR", "")
    every = int(os.environ.get("PARMMG_CKPT_EVERY", "1") or 1)
    return d, max(1, every)


def ckpt_due(it: int) -> bool:
    """Whether outer pass ``it`` (0-based) should checkpoint."""
    d, every = ckpt_config()
    return bool(d) and (it + 1) % every == 0


def _ckpt_path(d: str, tag: str, it: int) -> str:
    return os.path.join(d, f"{tag}.pass{it}.npz")


# ---------------------------------------------------------------------------
# crash-loop breaker
# ---------------------------------------------------------------------------
def resume_max() -> int:
    """Resume attempts into the SAME (fingerprint, pass) before the
    breaker escalates past the failing rung (PARMMG_RESUME_MAX)."""
    try:
        return max(1, int(os.environ.get("PARMMG_RESUME_MAX", "3")
                          or 3))
    except ValueError:
        return 3


def crash_loop(tag: str, fingerprint: str | None, it: int,
               write: bool = True) -> tuple[int, bool]:
    """The crash-loop breaker decision, taken at resume time.

    Checkpoint/resume made a crash survivable; it also made a
    DETERMINISTIC crash eternal — a pass that reliably kills its
    worker resumes into the identical state and kills it again, and
    the supervisor relaunch loop never terminates (the unbounded-time
    failure the LOWFAILURE contract forbids).  This records a
    per-(fingerprint, pass) resume-attempt count in a small JSON file
    next to the checkpoints and returns ``(attempts, escalate)``:
    ``escalate`` turns True on the attempt AFTER ``resume_max()`` is
    reached, the caller's signal to skip past the failing pass (the
    last conforming checkpointed state IS the bounded-time answer —
    the driver's merged-polish/LOWFAILURE tail still runs on it).

    Escalation is emitted as a ``resilience.crash_loop`` event + a
    ``resilience.crash_loops`` counter.  ``write=False`` computes the
    decision without persisting the bump (non-zero pod ranks: only
    rank 0 writes to the shared checkpoint dir, and the ranks agree
    on the final decision collectively — parallel/dist.py).  Like all
    checkpoint bookkeeping, IO failure here is absorbed, never
    raised."""
    d, _ = ckpt_config()
    key = f"{fingerprint or ''}:{int(it)}"
    counts: dict = {}
    path = os.path.join(d, f"{tag}.resume.json") if d else ""
    if path:
        try:
            with open(path) as fh:
                counts = dict(json.load(fh))
        except Exception:
            counts = {}
    n = int(counts.get(key, 0)) + 1
    if path and write:
        counts[key] = n
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(counts, fh)
            os.replace(tmp, path)
        except OSError:
            pass
    mx = resume_max()
    esc = n > mx
    if esc:
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        REGISTRY.counter("resilience.crash_loops").inc()
        otrace.event("resilience.crash_loop", tag=tag, it=int(it),
                     attempts=n, max=mx)
        otrace.log(1, f"  ## resilience: crash loop — pass {it} "
                      f"resumed {n}x (PARMMG_RESUME_MAX={mx}); "
                      "escalating past the failing pass: the last "
                      "conforming checkpointed state is the "
                      "bounded-time answer.", err=True)
    return n, esc


def run_fingerprint(mesh, met, *knobs) -> str:
    """Run-identity digest of a loop's ORIGINAL input (mesh bytes +
    metric + the loop knobs).  Stored in every pass checkpoint and
    required to match at resume: a checkpoint dir is often reused
    across runs, and silently resuming a stale checkpoint from a
    DIFFERENT input would deliver the wrong mesh."""
    import hashlib
    h = hashlib.blake2b(digest_size=16)
    for a in (mesh.vert, mesh.tet, mesh.tmask, met):
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    h.update(repr(knobs).encode())
    return h.hexdigest()


def save_pass_checkpoint(tag: str, it: int, mesh, met, part,
                         fingerprint: str | None = None) -> str | None:
    """Atomically write pass ``it``'s resume payload.  Returns the path,
    or None when disabled / not due / the write failed (failure is
    counted + traced, never raised — see module docstring)."""
    from ..core.mesh import MESH_FIELDS
    from ..obs import trace as otrace
    from ..obs.metrics import REGISTRY
    if not ckpt_due(it):
        return None
    d, _ = ckpt_config()
    path = _ckpt_path(d, tag, it)
    try:
        faultpoint("io.checkpoint")
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        # file handle, not a path: np.savez would append ".npz" to the
        # tmp name and break the atomic-replace pairing
        with open(tmp, "wb") as fh:
            np.savez(fh, it=np.asarray(it, np.int64),
                     fp=np.asarray(fingerprint or ""),
                     met=np.asarray(met),
                     part=np.asarray(part if part is not None else []),
                     **{f: np.asarray(getattr(mesh, f))
                        for f in MESH_FIELDS})
        os.replace(tmp, path)
    except Exception as e:
        # drop the partial .tmp: on the disk-full failure mode every
        # pass would otherwise leave another mesh-sized partial behind
        try:
            os.unlink(path + ".tmp")
        except OSError:
            pass
        REGISTRY.counter("resilience.checkpoint_failures").inc()
        otrace.event("ckpt.failed", tag=tag, it=it, detail=repr(e)[:300])
        otrace.log(1, f"  ## Warning: pass checkpoint failed ({e!r}); "
                      "run continues unprotected.", err=True)
        return None
    REGISTRY.counter("resilience.checkpoints").inc()
    otrace.event("ckpt.saved", tag=tag, it=it, path=path)
    return path


def snapshot_stacked(tag: str, it: int, stacked, n_groups: int) -> list:
    """Merge-free shard snapshot of a checkpointed pass's stacked state
    (``stacked_to_distributed_files``, no communicator sections: group
    seams are frozen, not parallel interfaces).  Best-effort like the
    npz write: failures are counted, never raised."""
    from ..obs import trace as otrace
    from ..obs.metrics import REGISTRY
    if not ckpt_due(it):
        return []
    d, _ = ckpt_config()
    try:
        faultpoint("io.checkpoint")
        from ..io.distributed import stacked_to_distributed_files
        os.makedirs(d, exist_ok=True)
        outs = stacked_to_distributed_files(
            os.path.join(d, f"{tag}.pass{it}.mesh"), stacked, None,
            None, n_groups, shards=range(n_groups))
    except Exception as e:
        REGISTRY.counter("resilience.checkpoint_failures").inc()
        otrace.event("ckpt.snapshot_failed", tag=tag, it=it,
                     detail=repr(e)[:300])
        return []
    REGISTRY.counter("resilience.checkpoint_shards").inc(len(outs))
    return outs


def save_dist_checkpoint(tag: str, it: int, stacked_host: dict,
                         met_s, glo: list, top: int, comms,
                         shared_prev, regrow: int,
                         fingerprint: str | None = None,
                         write: bool = True) -> str | None:
    """Per-pass durability for the SHARD-RESIDENT distributed loop
    (``distributed_adapt_multi``) — the pod runtime's restart unit:
    worker crash/stall at pod scale is the EXPECTED failure mode, and
    the survivors re-launch from here instead of re-paying the whole
    adaptation (parallel/pod.py module docstring).

    ``stacked_host``: {field: [S, ...] host array} of the stacked mesh
    (the caller replicates via pull_host under ``multihost.cold_io`` —
    every process participates in the collective, only process 0
    passes ``write=True``).  The payload carries the full loop state:
    stacked fields + metric, the host numbering mirror + session
    counter, the comm tables (incl. per-shard owner rows) and the
    shared-gid / regrow scalars.  Atomic + fault-absorbed exactly like
    :func:`save_pass_checkpoint`."""
    from ..obs import trace as otrace
    from ..obs.metrics import REGISTRY
    if not ckpt_due(it):
        return None
    d, _ = ckpt_config()
    path = os.path.join(d, f"{tag}.dpass{it}.npz")
    if not write:
        return path
    try:
        faultpoint("io.checkpoint")
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        payload = {f"m_{k}": np.asarray(v)
                   for k, v in stacked_host.items()}
        payload.update(
            it=np.asarray(it, np.int64),
            fp=np.asarray(fingerprint or ""),
            met=np.asarray(met_s),
            glo=np.stack([np.asarray(g) for g in glo]),
            top=np.asarray(int(top), np.int64),
            nbr=comms.nbr, node_idx=comms.node_idx,
            node_cnt=comms.node_cnt, face_idx=comms.face_idx,
            face_cnt=comms.face_cnt,
            shared_prev=np.asarray(shared_prev),
            regrow=np.asarray(int(regrow), np.int64))
        for s, ow in enumerate(comms.owner):
            payload[f"owner_{s}"] = np.asarray(ow)
        with open(tmp, "wb") as fh:
            np.savez(fh, **payload)
        os.replace(tmp, path)
    except Exception as e:
        try:
            os.unlink(path + ".tmp")
        except OSError:
            pass
        REGISTRY.counter("resilience.checkpoint_failures").inc()
        otrace.event("ckpt.failed", tag=tag, it=it, detail=repr(e)[:300])
        otrace.log(1, f"  ## Warning: dist pass checkpoint failed "
                      f"({e!r}); run continues unprotected.", err=True)
        return None
    REGISTRY.counter("resilience.checkpoints").inc()
    otrace.event("ckpt.saved", tag=tag, it=it, path=path)
    return path


def latest_dist_checkpoint(tag: str, fingerprint: str | None = None
                           ) -> tuple[str, int] | None:
    """Newest complete dist-loop (path, pass index) for ``tag``; same
    staleness/partial-file rules as :func:`latest_pass_checkpoint`."""
    from ..obs import trace as otrace
    d, _ = ckpt_config()
    if not d or not os.path.isdir(d):
        return None
    found = []
    for name in os.listdir(d):
        if not name.startswith(tag + ".dpass"):
            continue
        m = _DCKPT_RE.search(name)
        if m:
            found.append((int(m.group(1)), os.path.join(d, name)))
    for it, path in sorted(found, reverse=True):
        try:
            with np.load(path) as z:
                if "m_vert" not in z.files or int(z["it"]) != it:
                    continue
                if fingerprint is not None:
                    stored = str(z["fp"]) if "fp" in z.files else ""
                    if stored != fingerprint:
                        otrace.log(1, f"  ## Warning: checkpoint "
                                      f"{path} belongs to a different "
                                      "run (input fingerprint "
                                      "mismatch); skipped.", err=True)
                        continue
                return path, it
        except Exception:
            continue
    return None


def load_dist_checkpoint(path: str) -> dict:
    """Dist checkpoint -> {stacked: {field: array}, met, glo (list),
    top, comms: InterfaceComms, shared_prev, regrow, it}."""
    from ..parallel.comms import InterfaceComms
    z = np.load(path)
    stacked = {k[2:]: z[k] for k in z.files if k.startswith("m_")}
    S = z["glo"].shape[0]
    owner = [z[f"owner_{s}"] for s in range(S)]
    comms = InterfaceComms(z["nbr"], z["node_idx"], z["node_cnt"],
                           z["face_idx"], z["face_cnt"], owner)
    return dict(stacked=stacked, met=z["met"],
                glo=[g.copy() for g in z["glo"]], top=int(z["top"]),
                comms=comms, shared_prev=z["shared_prev"],
                regrow=int(z["regrow"]), it=int(z["it"]))


def latest_pass_checkpoint(tag: str, fingerprint: str | None = None
                           ) -> tuple[str, int] | None:
    """Newest complete (path, pass index) for ``tag`` under the ckpt
    dir, or None.  ``.tmp`` partials from a kill mid-write are ignored
    (the atomic-replace contract), unloadable files are skipped.
    With ``fingerprint`` set, checkpoints whose stored run identity
    differs (a STALE checkpoint from a previous run on different
    input) are skipped with a warning instead of silently resumed."""
    from ..obs import trace as otrace
    d, _ = ckpt_config()
    if not d or not os.path.isdir(d):
        return None
    found = []
    for name in os.listdir(d):
        if not name.startswith(tag + ".pass"):
            continue
        m = _CKPT_RE.search(name)
        if m:
            found.append((int(m.group(1)), os.path.join(d, name)))
    for it, path in sorted(found, reverse=True):
        try:
            with np.load(path) as z:
                if "vert" not in z.files or int(z["it"]) != it:
                    continue
                if fingerprint is not None:
                    stored = str(z["fp"]) if "fp" in z.files else ""
                    if stored != fingerprint:
                        otrace.log(1, f"  ## Warning: checkpoint "
                                      f"{path} belongs to a different "
                                      "run (input fingerprint "
                                      "mismatch); skipped.", err=True)
                        continue
                return path, it
        except Exception:
            continue
    return None


def load_pass_checkpoint(path: str):
    """Checkpoint -> (Mesh of host arrays, met, part, pass index)."""
    from ..core.mesh import MESH_FIELDS, Mesh
    z = np.load(path)
    mesh = Mesh(**{f: z[f] for f in MESH_FIELDS})
    part = z["part"]
    return mesh, z["met"], (part if part.size else None), int(z["it"])
