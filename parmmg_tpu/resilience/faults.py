"""Named-faultpoint registry: provoke the REAL failure paths on demand.

Every recovery path in this codebase was born from an actual incident
(the tunnel worker dying mid-polish, device dispatches kernel-faulting
late in a session, checkpoint disks filling up) — but none of them
could be *provoked* without waiting for the hardware to oblige.  This
module arms named fault sites through one env knob so the degrade
ladder is exercised by CI (``scripts/chaos_check.py``), not simulated
with mocks:

    PARMMG_FAULT=site[:trigger][,site[:trigger]...]

``site`` is one of :data:`SITES`.  ``trigger`` is ``;``-separated
rules (all must pass for the site to fire):

- *(none)*      — fire on every hit;
- ``nth-N``     — fire on the Nth hit only (1-based; ``N`` alone works);
- ``every-K``   — fire on every Kth hit;
- ``p=0.x``     — fire with probability x per hit (``seed=N`` makes the
  sequence reproducible; default seed 0);
- ``key=S``     — fire only on hits whose ``key`` argument equals S
  (e.g. a specific serve tenant); non-matching hits do not advance the
  site's hit counter;
- ``hang=S``    — ACTION modifier: when the rule fires, the site
  sleeps S seconds and then RETURNS instead of raising — the testable
  stand-in for a wedged collective/worker (the failure mode deadline
  watchdogs and heartbeat leases exist for, resilience/watchdog.py).
  Composes with the triggers above; for the subprocess site the
  worker sleeps pre-jax instead of exiting.

Exception fidelity: :func:`faultpoint` raises the site's REAL failure
shape — ``XlaRuntimeError`` for device-dispatch sites, ``OSError`` for
IO sites — so ``except`` clauses in the recovery code are hit exactly
as they would be by the hardware.  Sites whose real failure is a flag,
not an exception (the analysis KS-overflow fallback), use
:func:`fault_trigger` and return a bool.  The polish worker's real
failure is a non-zero subprocess exit: the PARENT decides the firing
(:func:`subprocess_fault_env`, so nth/every counting lives in one
process) and the worker exits 3 before touching jax when it finds
``PARMMG_FAULT_FORCE`` naming it.
"""
from __future__ import annotations

import dataclasses
import os
import random
import threading
import time

__all__ = [
    "FAULTS", "FaultRegistry", "FaultRule", "SITES", "fault_trigger",
    "faultpoint", "parse_fault_spec", "subprocess_fault_env",
]

# the injectable sites and the exception shape each raises
# (xla = device dispatch failure, os = IO failure, flag = non-exception
# trigger consumed by the caller, exit = non-zero subprocess exit
# forced via PARMMG_FAULT_FORCE)
SITES = {
    "polish.worker": "exit",
    "dispatch.chunk": "xla",
    "halo.exchange": "xla",
    "multihost.exchange": "xla",
    "analysis.ks_overflow": "flag",
    "serve.slot_step": "xla",
    "serve.daemon_rpc": "os",
    "io.checkpoint": "os",
}

FORCE_ENV = "PARMMG_FAULT_FORCE"


@dataclasses.dataclass
class FaultRule:
    """One armed site's trigger: all set conditions must pass."""
    site: str
    nth: int | None = None       # fire on the Nth matching hit only
    every: int | None = None     # fire on every Kth matching hit
    p: float | None = None       # fire with probability p per hit
    seed: int = 0
    key: str | None = None       # fire only when the hit key matches
    hang: float | None = None    # ACTION: sleep S then return, no raise

    def __post_init__(self):
        self._rng = random.Random(self.seed)
        self._hits = 0

    def fires(self, key: str | None) -> bool:
        if self.key is not None and key != self.key:
            return False
        self._hits += 1
        # ALL set conditions must pass (the documented ';' semantics).
        # The probability draw happens on every matching hit so the
        # seeded sequence is independent of the other conditions.
        ok = True
        if self.p is not None:
            ok = self._rng.random() < self.p
        if self.nth is not None:
            ok = ok and self._hits == self.nth
        if self.every is not None:
            ok = ok and self._hits % self.every == 0
        return ok


def parse_fault_spec(spec: str) -> dict:
    """``PARMMG_FAULT`` grammar -> {site: FaultRule}.  Raises
    ValueError on unknown sites or malformed triggers (a typo'd chaos
    knob must fail loudly, not silently inject nothing)."""
    rules: dict[str, FaultRule] = {}
    for part in filter(None, (p.strip() for p in spec.split(","))):
        site, _, trig = part.partition(":")
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: {sorted(SITES)})")
        kw: dict = {}
        for tok in filter(None, (t.strip() for t in trig.split(";"))):
            if tok.startswith("nth-"):
                kw["nth"] = int(tok[4:])
            elif tok.isdigit():
                kw["nth"] = int(tok)
            elif tok.startswith("every-"):
                kw["every"] = int(tok[6:])
            elif tok.startswith("p="):
                kw["p"] = float(tok[2:])
            elif tok.startswith("seed="):
                kw["seed"] = int(tok[5:])
            elif tok.startswith("key="):
                kw["key"] = tok[4:]
            elif tok.startswith("hang="):
                kw["hang"] = float(tok[5:])
            else:
                raise ValueError(
                    f"unparseable fault trigger {tok!r} in {part!r}")
        for f in ("nth", "every"):
            if kw.get(f) is not None and kw[f] < 1:
                raise ValueError(f"{f} must be >= 1 in {part!r}")
        if kw.get("hang") is not None and kw["hang"] <= 0:
            raise ValueError(f"hang must be > 0 seconds in {part!r}")
        rules[site] = FaultRule(site=site, **kw)
    return rules


class FaultRegistry:
    """Lazy env-armed registry; hit counters persist for the lifetime
    of one parsed spec (re-parsed when PARMMG_FAULT changes)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._raw: str | None = None
        self._rules: dict[str, FaultRule] = {}

    def reset(self) -> None:
        """Drop the parsed spec + counters (re-reads env on next hit).
        Tests and the chaos gate call this between scenarios."""
        with self._lock:
            self._raw = None
            self._rules = {}

    def _resolve(self) -> dict:
        raw = os.environ.get("PARMMG_FAULT", "")
        if raw != self._raw:
            self._raw = raw
            self._rules = parse_fault_spec(raw) if raw else {}
        return self._rules

    def armed(self) -> bool:
        with self._lock:
            return bool(self._resolve())

    def fired_rule(self, site: str,
                   key: str | None = None) -> FaultRule | None:
        """The armed rule for ``site`` when it fires on this hit, else
        None.  Callers needing the ACTION (raise vs ``hang``) use this;
        :meth:`should_fire` stays the boolean form."""
        with self._lock:
            rule = self._resolve().get(site)
            if rule is None:
                return None
            if rule.fires(None if key is None else str(key)):
                return rule
            return None

    def should_fire(self, site: str, key: str | None = None) -> bool:
        return self.fired_rule(site, key) is not None


FAULTS = FaultRegistry()


def _site_exception(site: str, key: str | None):
    kind = SITES.get(site, "xla")
    msg = (f"INTERNAL: injected fault at {site}"
           + (f" (key={key})" if key is not None else "")
           + " [PARMMG_FAULT]")
    if kind == "os":
        return OSError(msg)
    # the device-dispatch failure shape: the exact class jax raises on
    # a crashed/overflowed device program (falls back to RuntimeError
    # when jaxlib is absent — host-only test environments)
    try:
        from jax._src.lib import xla_client
        return xla_client.XlaRuntimeError(msg)
    except Exception:
        return RuntimeError(msg)


def _record(site: str, key: str | None,
            hang: float | None = None) -> None:
    from ..obs import trace as otrace
    from ..obs.metrics import REGISTRY
    REGISTRY.counter("resilience.faults_injected").inc()
    otrace.event("fault.injected", site=site,
                 **({} if key is None else {"key": str(key)}),
                 **({} if hang is None else {"hang_s": float(hang)}))


def faultpoint(site: str, key: str | None = None) -> None:
    """Raise the site's real exception type when armed and firing.
    Free when PARMMG_FAULT is unset (one dict lookup).  A firing rule
    with ``hang=S`` sleeps S seconds and returns instead — the wedge,
    not the crash: nothing raises, and only a deadline watchdog or
    heartbeat lease (resilience/watchdog.py) can notice."""
    rule = FAULTS.fired_rule(site, key)
    if rule is None:
        return
    if rule.hang is not None:
        _record(site, key, hang=rule.hang)
        time.sleep(rule.hang)
        return
    _record(site, key)
    raise _site_exception(site, key)


def fault_trigger(site: str, key: str | None = None) -> bool:
    """Flag-style sites (the real failure is a condition, not an
    exception — e.g. the analysis KS-overflow fallback): True when the
    armed fault fires, so the caller takes its real degraded branch.
    A ``hang=S`` rule sleeps and returns False — a wedge delays the
    site, it does not flip its condition."""
    rule = FAULTS.fired_rule(site, key)
    if rule is None:
        return False
    if rule.hang is not None:
        _record(site, key, hang=rule.hang)
        time.sleep(rule.hang)
        return False
    _record(site, key)
    return True


def subprocess_fault_env(site: str) -> dict:
    """Firing decision for subprocess sites, evaluated IN THE PARENT
    (so nth/every counting survives across worker invocations): returns
    the env overlay to merge into the worker's environment — the worker
    exits non-zero when it sees ``PARMMG_FAULT_FORCE`` naming it, or
    sleeps pre-jax on the ``site:hang=S`` form (the wedged-worker
    drill: the parent's subprocess timeout is what must catch it)."""
    rule = FAULTS.fired_rule(site)
    if rule is None:
        return {}
    _record(site, None, hang=rule.hang)
    if rule.hang is not None:
        return {FORCE_ENV: f"{site}:hang={rule.hang:g}"}
    return {FORCE_ENV: site}
