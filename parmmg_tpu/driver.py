"""The adaptation loop driver — PMMG_parmmglib1 analogue.

Reference flow (/root/reference/src/libparmmg1.c:550-1011): split into
groups, then per iteration: snapshot background groups, run the sequential
remesher per group with frozen interfaces, interpolate metric+fields from
the background, load-balance (split/migrate/regroup).  Here:

- single device: the whole mesh is one batched remesh operator
  (ops/adapt.py), no groups needed — the degenerate nprocs=1/ngrp=1 path
  of the reference collapses to one call;
- multi device: partition -> freeze interfaces -> SPMD waves under
  ``shard_map`` -> merge, re-partitioned every outer iteration so frozen
  interfaces land in shard interiors next time (the role of the
  ifc-displacement / graph repartitioning of loadbalancing_pmmg.c:44-161);
- fields/metric are interpolated from the ORIGINAL mesh once at the end
  (background-mesh localization, interpmesh_pmmg.c semantics) — chaining
  per-iteration interpolations only accumulates error when the background
  never changes identity.
"""
from __future__ import annotations

import numpy as np

from .core import constants as C
from .core.mesh import Mesh, mesh_to_host
from .ops.adapt import adapt_mesh, AdaptStats
from .ops.metric import metric_hsiz, metric_optim, clamp_metric, gradation


def _auto_hmin_hmax(vert: np.ndarray, info) -> tuple[float, float]:
    """Default size bounds from the bounding box (Mmg scaleMesh
    semantics: hmin/hmax resolved against the mesh scale when unset)."""
    lo, hi = vert.min(axis=0), vert.max(axis=0)
    diag = float(np.linalg.norm(hi - lo))
    hmin = info.hmin if info.hmin > 0 else 1e-3 * diag
    hmax = info.hmax if info.hmax > 0 else 2.0 * diag
    return hmin, hmax


def build_metric(mesh: Mesh, met, info):
    """Metric synthesis path: -hsiz / -optim / user metric / default."""
    import jax.numpy as jnp

    vert = np.asarray(mesh.vert)[np.asarray(mesh.vmask)]
    hmin, hmax = _auto_hmin_hmax(vert, info)
    if info.hsiz > 0:
        met = metric_hsiz(mesh, info.hsiz)
    elif met is None or info.optim or info.optimLES:
        met = metric_optim(mesh)
    met = clamp_metric(met, hmin, hmax)
    # surface-approximation size bound (Mmg defsiz -hausd route): chord
    # deviation under hausd needs h <= sqrt(8*hausd/kappa) on curved
    # boundary regions.  Requires ridge detection: without MG_GEO tags a
    # sharp edge is indistinguishable from smooth curvature and the
    # curvature estimate blows up at corners
    if info.hausd > 0 and info.angle_detection:
        from .ops.metric import hausd_metric_bound
        met = hausd_metric_bound(mesh, met, info.hausd, hmin)
    # local bounds BEFORE gradation (Mmg defsiz-then-gradsiz order) so the
    # size jump at a ref-patch boundary is smoothed by -hgrad; re-applied
    # after, since gradation only propagates smaller sizes and may pull a
    # patch below its local hmin
    if info.local_params:
        met = apply_local_params(mesh, met, info)
    if info.hgrad > 0 and met.ndim == 1:
        met = gradation(mesh, met, hgrad=info.hgrad)
        # gradation only propagates smaller sizes and may pull a patch
        # below its local hmin: re-apply the clamp (iso path only — the
        # second pass is pointless when nothing changed met)
        if info.local_params:
            met = apply_local_params(mesh, met, info)
    return met


def apply_local_params(mesh: Mesh, met, info):
    """Per-reference size bounds (MMG3D_Set_localParameter / parsop file,
    forwarded by the reference per group): vertices of the entities
    carrying reference ``ref`` get their size clamped to the local
    [hmin, hmax].  Entity kinds: 1 = triangles (surface ref patch),
    2 = tetrahedra (volume sub-domain), 3 = edges (user edge list,
    staged in ``info._user_edges`` by the API build), 0 = vertices (by
    point ref).  Per-entity hausd applies conservatively as the global
    minimum (parmmg_run); local hausd relaxation above the global value
    is not honored (documented divergence).  Iso: direct clamp; aniso:
    eigenvalue clamp of the tensor (h = 1/sqrt(lambda))."""
    import jax.numpy as jnp
    from .core.constants import IDIR, MG_BDY

    ftag = np.asarray(mesh.ftag)
    fref = np.asarray(mesh.fref)
    tet = np.asarray(mesh.tet)
    tmask = np.asarray(mesh.tmask)
    tref = np.asarray(mesh.tref)
    meth = np.array(np.asarray(met), copy=True)
    for typ, ref, lhmin, lhmax, _hausd in info.local_params:
        if typ == 1:          # triangle locals: surface reference patch
            sel_f = ((ftag & MG_BDY) != 0) & (fref == ref) & tmask[:, None]
            vids = np.unique(np.concatenate(
                [tet[sel_f[:, f]][:, IDIR[f]].reshape(-1)
                 for f in range(4)]
            )) if sel_f.any() else np.zeros(0, np.int64)
        elif typ == 2:        # tetrahedron locals: volume sub-domain
            sel_t = tmask & (tref == ref)
            vids = np.unique(tet[sel_t].reshape(-1)) if sel_t.any() \
                else np.zeros(0, np.int64)
        elif typ == 3:        # edge locals: user edges with this ref
            ue, uref = getattr(info, "_user_edges", (None, None))
            if ue is None:
                continue
            sel_e = uref == ref
            vids = np.unique(ue[sel_e].reshape(-1)) if sel_e.any() \
                else np.zeros(0, np.int64)
        elif typ == 0:        # vertex locals: points with this ref
            vm = np.asarray(mesh.vmask)
            vrf = np.asarray(mesh.vref)
            vids = np.where(vm & (vrf == ref))[0]
        else:
            continue
        if not len(vids):
            continue
        if meth.ndim == 1:
            meth[vids] = np.clip(meth[vids], lhmin, lhmax)
        else:
            from .ops.quality import unpack_sym
            m = np.asarray(unpack_sym(jnp.asarray(meth[vids])))
            w, v = np.linalg.eigh(m)
            w = np.clip(w, 1.0 / lhmax ** 2, 1.0 / lhmin ** 2)
            full = np.einsum("nij,nj,nkj->nik", v, w, v)
            meth[vids] = full[:, [0, 0, 0, 1, 1, 2], [0, 1, 2, 1, 2, 2]]
    return jnp.asarray(meth)


def parmmg_run(pm) -> tuple[Mesh, object, AdaptStats]:
    """Run the full adaptation per the staged ParMesh. Returns
    (adapted core Mesh, metric, stats)."""
    from .utils.timers import Timers
    from .api.params import check_input_data
    from .obs import trace as otrace
    from .resilience.recover import RetryBudgetExhausted, ladder_step
    info = pm.info
    check_input_data(info, met_is_aniso=(
        pm.met is not None and getattr(pm.met, "ndim", 1) == 2))
    # telemetry spine: fresh run context (run id + backend tag on every
    # trace record) and the process verbosity = the reference's imprim
    otrace.new_run()
    otrace.set_verbosity(info.imprim)
    tim = Timers()
    with tim("analysis"):
        mesh, met = pm._build_core_mesh()
    if info.nosurf:
        # -nosurf: no surface modification — freeze every boundary entity
        # with MG_REQ (exactly how the reference freezes parallel faces,
        # and how Mmg interprets nosurf: required boundary)
        import jax.numpy as jnp
        import dataclasses
        bdy_f = (mesh.ftag & C.MG_BDY) != 0
        bdy_e = (mesh.etag & C.MG_BDY) != 0
        bdy_v = (mesh.vtag & C.MG_BDY) != 0
        mesh = dataclasses.replace(
            mesh,
            ftag=jnp.where(bdy_f, mesh.ftag | C.MG_REQ, mesh.ftag),
            etag=jnp.where(bdy_e, mesh.etag | C.MG_REQ, mesh.etag),
            vtag=jnp.where(bdy_v, mesh.vtag | C.MG_REQ, mesh.vtag))
    with tim("metric"):
        met = build_metric(mesh, met, info)

    # background snapshot for field interpolation (PMMG_create_oldGrp
    # analogue, grpsplit_pmmg.c:207).  Deep copy: adapt_cycle donates its
    # input buffers, which would invalidate the background otherwise.
    bg_fields = [np.array(f, copy=True) for f in pm.fields]
    if bg_fields:
        import jax
        import jax.numpy as jnp
        bg_mesh = jax.tree.map(jnp.copy, mesh)
    else:
        bg_mesh = None

    stats = AdaptStats()
    angedg = info.angedg()
    # surface-approximation tolerance: global -hausd, tightened by any
    # local-parameter hausd (per-reference hausd applies conservatively
    # as the global minimum until per-entity hausd fields land)
    hausd = info.hausd
    for _typ, _ref, _hm, _hx, _hd in info.local_params:
        if _hd and _hd > 0:
            hausd = min(hausd, _hd)
    if not info.angle_detection:
        # -nr: no ridge tags -> the Bezier lift cannot tell a sharp
        # feature from smooth curvature; fall back to piecewise-linear
        # boundary placement (conservative; Mmg with -nr instead rounds
        # features — tracked as a semantic divergence)
        hausd = None
    if info.n_devices <= 1:
        import jax
        import jax.numpy as jnp
        from .api.params import resolve_target_mesh_size
        from .parallel.groups import how_many_groups, grouped_adapt
        niter = max(1, info.niter)
        ne0 = int(np.asarray(mesh.tmask).sum())
        target = resolve_target_mesh_size(info, ne0, 1)
        if how_many_groups(ne0, target) >= 2:
            # two-level decomposition (-mesh-size below the mesh size):
            # sub-device groups traversed with lax.map so peak HBM is one
            # group's working set (grpsplit_pmmg.c:1551 role; see
            # parallel/groups.py).  Interface seams are displaced between
            # iterations like rank interfaces.
            backup = (jax.tree.map(jnp.copy, mesh), jnp.copy(met))
            degraded = False
            try:
                with tim("adaptation"):
                    mesh, met = grouped_adapt(
                        mesh, met, target, niter=niter,
                        verbose=3 if info.imprim >= C.PMMG_VERB_ITWAVES
                        else 0, stats=stats,
                        noinsert=info.noinsert, noswap=info.noswap,
                        nomove=info.nomove, hausd=hausd,
                        ifc_layers=info.ifc_layers, timers=tim,
                        resume=getattr(info, "resume", False))
            except MemoryError:
                mesh, met = backup
                stats.status = C.PMMG_LOWFAILURE
                degraded = True
                ladder_step("lowfailure", site="groups.capacity")
            except RetryBudgetExhausted as e:
                # the retry rung of the ladder is spent (chunk dispatch
                # or polish worker kept failing): restore the conforming
                # backup and degrade — never die holding user data
                mesh, met = backup
                stats.status = C.PMMG_LOWFAILURE
                degraded = True
                ladder_step("lowfailure", site=e.site,
                            detail=str(e.__cause__ or e))
            except Exception as e:  # device OOM = XlaRuntimeError
                if "RESOURCE_EXHAUSTED" not in str(e) and \
                        "Out of memory" not in str(e):
                    raise
                mesh, met = backup
                stats.status = C.PMMG_LOWFAILURE
                degraded = True
                ladder_step("lowfailure", site="device.oom",
                            detail=str(e)[:200])
            # bad-element polish on the merged mesh (the same contract as
            # the other two paths — group seams breed slivers)
            if not degraded and not (info.noinsert and info.noswap
                                     and info.nomove):
                from .ops.adapt import sliver_polish
                with tim("bad-element polish"):
                    for w in range(8):
                        mesh, counts = sliver_polish(
                            mesh, met, jnp.asarray(1000 + w, jnp.int32),
                            do_collapse=not info.noinsert,
                            do_swap=not info.noswap,
                            do_smooth=not info.nomove, hausd=hausd)
                        pc = np.asarray(counts)
                        stats.ncollapse += int(pc[0])
                        stats.nswap += int(pc[1])
                        stats.nmoved += int(pc[2])
                        if int(pc[0]) == 0 and int(pc[1]) == 0:
                            break
            return _finish_run(pm, mesh, met, stats, info, tim,
                               bg_mesh, bg_fields, hausd)
        for it in range(niter):
            # the jitted cycles DONATE their input buffers, so the
            # pre-iteration binding would be dead after a failure; keep a
            # device-side copy for the degrade path (HBM-to-HBM, cheap)
            backup = (jax.tree.map(jnp.copy, mesh), jnp.copy(met))
            try:
                with tim(f"adaptation"):
                    mesh, met, st = adapt_mesh(
                        mesh, met,
                        verbose=3 if info.imprim >= C.PMMG_VERB_ITWAVES
                        else 0,
                        noinsert=info.noinsert, noswap=info.noswap,
                        nomove=info.nomove, angedg=angedg, hausd=hausd)
            except MemoryError:
                # capacity exhausted mid-iteration: restore the backup
                # (conforming) and degrade, don't die (failed_handling,
                # libparmmg1.c:974-1011)
                mesh, met = backup
                stats.status = C.PMMG_LOWFAILURE
                ladder_step("lowfailure", site="adapt.capacity")
                break
            except Exception as e:  # device OOM comes as XlaRuntimeError
                if "RESOURCE_EXHAUSTED" not in str(e) and \
                        "Out of memory" not in str(e):
                    raise
                mesh, met = backup
                stats.status = C.PMMG_LOWFAILURE
                ladder_step("lowfailure", site="device.oom",
                            detail=str(e)[:200])
                break
            stats += st
    else:
        from .parallel.dist import (distributed_adapt_multi,
                                    ShardOverflowError)
        part = None
        niter = max(1, info.niter)
        vrb = 3 if info.imprim >= C.PMMG_VERB_ITWAVES else 0
        # Both repartitioning modes run the shard-RESIDENT outer loop —
        # one split, niter adapt passes, ONE merge at final output
        # (the reference's migrate-only-moving-groups design,
        # loadbalancing_pmmg.c + distributegrps_pmmg.c).  The modes
        # differ only in the between-iteration labels: advancing-front
        # interface displacement (default, device flood) vs group-graph
        # repartitioning (morton clusters + weighted KL/FM — the
        # metis_pmmg.c:845-1550 gather-only-the-graph role).
        mode = "ifc" if info.repartitioning == C.REPART_IFC_DISPLACEMENT \
            else "graph"
        # distributed input stays distributed: adopt the caller's
        # partition when it matches the device count (the reference
        # preserves the input decomposition and only rebuilds comms,
        # libparmmg.c:206-329); the dedup at load time kept tet order
        in_part = getattr(pm, "_in_part", None)
        n_t0 = int(np.asarray(mesh.tmask).sum())
        # the shard COUNT must equal the device count: fewer shards
        # would leave devices permanently empty (the flood never
        # populates a shard that shares no interface)
        if in_part is not None and (
                len(in_part) != n_t0
                or int(in_part.max()) + 1 != info.n_devices):
            in_part = None
        try:
            with tim("adaptation"):
                mesh, met, part = distributed_adapt_multi(
                    mesh, met, info.n_devices, niter=niter,
                    verbose=vrb, stats=stats,
                    noinsert=info.noinsert, noswap=info.noswap,
                    nomove=info.nomove, angedg=angedg, hausd=hausd,
                    ifc_layers=info.ifc_layers,
                    nobalancing=info.nobalancing, part=in_part,
                    mode=mode)
        except ShardOverflowError as e:
            # degrade to LOWFAILURE with the conforming merged state
            # (failed_handling, libparmmg1.c:974-1011)
            mesh, met, part = e.mesh, e.met, e.part
            stats.status = C.PMMG_LOWFAILURE
            ladder_step("lowfailure", site="shard.overflow")
            from .obs.trace import log as _olog
            _olog(C.PMMG_VERB_VERSION,
                  "  ## Warning: shard capacity exhausted; saving the "
                  "last conforming mesh (LOWFAILURE).",
                  verbose=info.imprim, err=True)
        # bad-element optimization on the merged mesh (same contract as
        # the single-device path: sliver_polish after the sizing loop)
        if not (info.noinsert and info.noswap and info.nomove):
            from .ops.adapt import sliver_polish
            import jax.numpy as jnp
            with tim("bad-element polish"):
                for w in range(8):
                    mesh, counts = sliver_polish(
                        mesh, met, jnp.asarray(1000 + w, jnp.int32),
                        do_collapse=not info.noinsert,
                        do_swap=not info.noswap,
                        do_smooth=not info.nomove, hausd=hausd)
                    pc = np.asarray(counts)
                    stats.ncollapse += int(pc[0])
                    stats.nswap += int(pc[1])
                    stats.nmoved += int(pc[2])
                    if int(pc[0]) + int(pc[1]) > 0:
                        part = None   # tet set changed: labels are stale
                    if int(pc[0]) == 0 and int(pc[1]) == 0:
                        break
        pm._out_part = part          # reused by distributed output

    return _finish_run(pm, mesh, met, stats, info, tim, bg_mesh,
                       bg_fields, hausd)


def _finish_run(pm, mesh, met, stats, info, tim, bg_mesh, bg_fields,
                hausd):
    """Common run tail: sequential sliver repair, FEM-topology
    conformity, user-field interpolation, reports.  Shared by the
    whole-mesh, grouped and distributed paths."""
    from .obs.trace import log as _olog
    # sequential last-resort repair: tangled sliver clusters (stacked
    # near-flat tets, typically born at former frozen interfaces) veto
    # every BATCHED fix — each parallel op inverts a neighbor — while the
    # reference's sequential remesher resolves them one op at a time;
    # ops/repair.py reproduces that freedom for the (tiny) tail only
    if not (info.noinsert and info.noswap and info.nomove):
        from .ops.repair import repair_mesh
        with tim("sequential repair"):
            mesh, nrep = repair_mesh(
                mesh, met, allow_collapse=not info.noinsert,
                allow_swap=not info.noswap, allow_move=not info.nomove)
            if nrep:
                _olog(C.PMMG_VERB_STEPS,
                      f"  sequential repair: {nrep} cluster ops",
                      verbose=info.imprim)

    # FEM-mode topology fix (default ON like the reference,
    # API_functions_pmmg.c:413; disabled by -nofem): split interior edges
    # connecting two boundary points so no element touches the boundary
    # with two faces / all four vertices (ops.split.split_wave fem_only).
    # AFTER the repair pass — a repair collapse could otherwise resurrect
    # a bdy-bdy interior edge the fem pass just removed.
    if info.fem and not info.noinsert:
        from .ops.adapt import fem_pass, grow_mesh_met
        with tim("fem conformity"):
            nf = 0
            for _w in range(8):
                mesh, met, fc = fem_pass(mesh, met)
                nf, ovf = (int(v) for v in np.asarray(fc))
                stats.nsplit += nf
                if ovf:
                    mesh, met = grow_mesh_met(mesh, met, 2 * mesh.capP,
                                              2 * mesh.capT)
                    stats.regrows += 1
                    continue
                if nf == 0:
                    break
            if nf:
                _olog(C.PMMG_VERB_VERSION,
                      "  ## Warning: fem conformity pass did not "
                      f"converge ({nf} edges remain); output may "
                      "contain elements with two boundary faces.",
                      verbose=info.imprim, err=True)

    # interpolate user fields old mesh -> new mesh
    if bg_fields:
        with tim("metric and fields interpolation"):
            pm.fields = interpolate_fields(bg_mesh, bg_fields, mesh)

    # metrics spine: every run's counters land in the process registry
    # (tenant-tagged stats stay namespaced), snapshotted by the
    # artifact layer (obs/artifact.py)
    stats.publish()
    # quality report stays gated on BOTH compute and print: generating
    # it runs whole-mesh device programs, which the telemetry spine
    # must never add to a quiet run (its absence from the trace means
    # "not computed", not "suppressed" — README Observability)
    if info.imprim >= C.PMMG_VERB_QUAL:
        print_quality_report(mesh, met, info)
    # the report lines below are cheap host strings: _olog gates the
    # PRINT on imprim but always emits the trace record, so the JSONL
    # stream carries them (shown=false) even on quiet runs
    # quiet-group scheduler accounting (parallel/sched.py): the active
    # g/G trajectory + the dispatches the compaction saved on the
    # grouped path's chunked dispatch loop
    if stats.group_dispatches or stats.group_dispatches_saved:
        traj = stats.sched_extra.get("active_groups_per_block", [])
        line = (f"  -- QUIET-GROUP SCHEDULER  "
                f"{stats.group_dispatches} group-block dispatches, "
                f"{stats.group_dispatches_saved} saved "
                f"({stats.groups_skipped} group-blocks skipped)")
        if traj:
            line += "; active g/block " + \
                ",".join(str(a) for a in traj)
        _olog(C.PMMG_VERB_STEPS, line, verbose=info.imprim)
    _olog(C.PMMG_VERB_STEPS, tim.report(), verbose=info.imprim)
    # compile-churn accounting (utils/compilecache): a steady state
    # whose ledger keeps growing is recompiling, not computing
    from .utils.timers import format_ledger, ledger_snapshot
    # registration alone (import-time @governed) leaves all-zero
    # rows; only report once something was actually called/compiled
    if any(r["calls"] or r["compiles"]
           for r in ledger_snapshot().values()):
        _olog(C.PMMG_VERB_STEPS,
              "  -- COMPILE LEDGER (XLA backend compiles)\n"
              + format_ledger(), verbose=info.imprim)
    return mesh, met, stats


def print_quality_report(mesh: Mesh, met, info) -> None:
    """Quality + edge-length histograms (PMMG_qualhisto OUTQUA +
    PMMG_prilen, quality_pmmg.c:156,591 — the custom MPI_Op reductions
    become plain array reductions on the merged mesh / psums on shards)."""
    import jax.numpy as jnp
    from .obs.metrics import REGISTRY
    from .obs.trace import log as _olog
    from .ops.quality import tet_quality, quality_histogram, \
        length_histogram

    q = tet_quality(mesh, met)
    counts, qmin, qmean, nbad = quality_histogram(q, mesh.tmask)
    # quality gauges only exist when the quality report ran (imprim >=
    # VERB_QUAL at the callsite): computing them is a whole-mesh device
    # program, and the telemetry spine must never ADD device compute to
    # a quiet run — absent quality.* gauges in an artifact mean the run
    # skipped the report, not that quality regressed
    REGISTRY.gauge("quality.qmin").set(float(qmin))
    REGISTRY.gauge("quality.qmean").set(float(qmean))
    REGISTRY.gauge("quality.nbad").set(float(nbad))
    lines = [f"  -- MESH QUALITY   {int(jnp.sum(mesh.tmask))} tets ; "
             f"worst {float(qmin):.6f} ; mean {float(qmean):.6f} ; "
             f"bad {int(nbad)}"]
    c = np.asarray(counts)
    for i, n in enumerate(c):
        lo, hi = i / len(c), (i + 1) / len(c)
        lines.append(f"     {lo:.1f} < Q < {hi:.1f}   {int(n)}")
    if met is not None:
        lc, lmin, lmax, lmean = length_histogram(mesh, met)
        lines.append(f"  -- EDGE LENGTHS   min {float(lmin):.4f} ; "
                     f"max {float(lmax):.4f} ; mean {float(lmean):.4f}")
    _olog(C.PMMG_VERB_QUAL, "\n".join(lines), verbose=info.imprim)


def interpolate_fields(bg: Mesh, fields: list[np.ndarray], new: Mesh)\
        -> list[np.ndarray]:
    """Background P1 interpolation of user fields onto the new vertices
    (PMMG_interpMetricsAndFields semantics, interpmesh_pmmg.c:663).

    Boundary vertices interpolate from the background SURFACE (triangle
    walk, ops.interp.locate_points_bdy — the PMMG_locatePointBdy split of
    interpmesh_pmmg.c:535-620): a volume walk puts a curved-boundary
    point inside some tet whose P1 restriction misrepresents the surface
    field."""
    import jax.numpy as jnp
    from .core.constants import MG_BDY
    from .ops.interp import (locate_points, locate_points_bdy, interp_p1,
                             interp_p1_tri)

    vm = np.asarray(new.vmask)
    pts = np.asarray(new.vert)[vm]
    on_bdy = (np.asarray(new.vtag)[vm] & MG_BDY) != 0
    loc = locate_points(bg, jnp.asarray(pts, new.vert.dtype),
                        # lint: ok(R10) — one-shot solution-transfer
                        # boundary: the query count IS the compile
                        # family here, and locate_points retraces per
                        # point count regardless (host mesh ingest,
                        # outside the governed adapt loop)
                        jnp.zeros(len(pts), jnp.int32))
    # the surface walk runs on the boundary SUBSET only (the volume pass
    # would feed interior points through the closest-triangle machinery
    # for nothing — and its intermediates scale with the query count)
    sloc = locate_points_bdy(
        bg, jnp.asarray(pts[on_bdy], new.vert.dtype)) \
        if on_bdy.any() else None
    out = []
    for f in fields:
        full = np.zeros((bg.capP,) + f.shape[1:], f.dtype)
        full[: len(f)] = f
        vals = np.asarray(interp_p1(jnp.asarray(full), bg.tet, loc))
        if sloc is not None:
            vals = np.array(vals, copy=True)
            vals[on_bdy] = np.asarray(
                interp_p1_tri(jnp.asarray(full), bg, sloc))
        out.append(vals)
    return out
