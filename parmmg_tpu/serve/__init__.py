"""Remeshing-as-a-service: multi-tenant mesh serving on the group axis.

The groups x shards machinery treats G logical meshes per device
uniformly — nothing requires them to be slices of ONE mesh.  This
package is the persistent serving mode built on that observation
(ROADMAP open item 3): N independent tenant meshes ride the bucketed
``[G, ...]`` capacity ladders through the SAME compiled group programs
the batch path runs, so a warm pool serves every request with ZERO
fresh XLA compiles.

- :mod:`pool` — slot pool + admission: bucketed group slots (capacity
  ladders from ``utils.compilecache.bucket``), smallest-fitting-bucket
  admission, chunk-compacted dispatch through
  ``parallel.groups._group_block``, per-tenant convergence and slot
  recycling;
- :mod:`driver` — request lifecycle: a submit/poll/fetch API over a
  work queue (medit/VTK in, merge-free distributed checkpoints out),
  per-request AdaptStats + qmin/qmean quality SLO, admission /
  rejection / timeout / max-in-flight knobs (``PARMMG_SERVE_*``).

Front-ends: ``scripts/serve_run.py`` (file-based CLI) and
``scripts/serve_bench.py`` (the SERVE_r* artifact: meshes/sec,
latency percentiles, occupancy, ledger diff vs the batch path).
"""
from .pool import SlotPool                         # noqa: F401
from .driver import ServeDriver, ServeRequest      # noqa: F401
