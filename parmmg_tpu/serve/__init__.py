"""Remeshing-as-a-service: multi-tenant mesh serving on the group axis.

The groups x shards machinery treats G logical meshes per device
uniformly — nothing requires them to be slices of ONE mesh.  This
package is the persistent serving mode built on that observation
(ROADMAP open item 3): N independent tenant meshes ride the bucketed
``[G, ...]`` capacity ladders through the SAME compiled group programs
the batch path runs, so a warm pool serves every request with ZERO
fresh XLA compiles.

- :mod:`pool` — slot pool + admission: bucketed group slots (capacity
  ladders from ``utils.compilecache.bucket``), smallest-fitting-bucket
  admission, chunk-compacted dispatch through
  ``parallel.groups._group_block``, per-tenant convergence and slot
  recycling;
- :mod:`driver` — request lifecycle: a submit/poll/fetch API over a
  work queue (medit/VTK in, merge-free distributed checkpoints out),
  per-request AdaptStats + qmin/qmean quality SLO, admission /
  rejection / timeout / max-in-flight knobs (``PARMMG_SERVE_*``);
- :mod:`admission` — staging + queue pump + backpressure (429-style
  deferral) + STREAMING mid-step slot re-rent
  (``PARMMG_SERVE_STREAM``);
- :mod:`autoscale` — the SLO-driven controller: bucket-ladder resizing
  and admission deferral as a pure function of the obs metrics
  snapshot (``PARMMG_SERVE_AUTOSCALE``);
- :mod:`daemon` / :mod:`client` — the persistent pool SERVICE: a
  daemon process owning the warm compiled programs for its lifetime
  behind a stdlib HTTP/JSON RPC layer, and the jax-free client.

Front-ends: ``scripts/serve_daemon.py`` (the service),
``scripts/serve_run.py`` (file-based CLI) and
``scripts/serve_bench.py`` (the SERVE_r* artifact: meshes/sec,
latency percentiles, occupancy/queue trajectories, ledger diff vs the
batch path; ``--stream`` = open-loop arrivals through the daemon).
"""
from .pool import SlotPool                         # noqa: F401
from .driver import ServeDriver, ServeRequest      # noqa: F401
from .daemon import PoolDaemon                     # noqa: F401
from .client import ServeClient                    # noqa: F401
