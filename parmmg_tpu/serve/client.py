"""HTTP client for the pool daemon (serve/daemon.py).

Deliberately light — stdlib + numpy only, NO jax: a serving client must
run anywhere (a solver loop, a CI gate, a laptop) while the daemon owns
the heavy runtime.  Mesh arrays ride base64 npz both ways, so a fetched
result is bit-identical to what the daemon's slot computed — the parity
gates (ledger serving_gate, serve_check, chaos) compare client-fetched
bytes directly against standalone runs.

    from parmmg_tpu.serve.client import ServeClient
    cl = ServeClient(port=8077)
    tid = cl.submit(vert=vert, tet=tet, met=met, tenant="job-42")
    cl.wait(tid)
    arrays = cl.fetch(tid)          # {mesh field: np.ndarray, "met": ...}

``submit`` raises :class:`BackpressureDeferred` on HTTP 429 (the
admission controller is deferring — retry later); every other non-2xx
raises :class:`ServeDaemonError` with the status and decoded body.
"""
from __future__ import annotations

import base64
import io
import json
import os
import time

import numpy as np

__all__ = ["BackpressureDeferred", "ServeClient", "ServeDaemonError"]


class ServeDaemonError(RuntimeError):
    """Non-2xx daemon response (status + decoded body attached)."""

    def __init__(self, status: int, body):
        self.status = int(status)
        self.body = body
        super().__init__(f"daemon RPC failed ({status}): {body}")


class BackpressureDeferred(ServeDaemonError):
    """HTTP 429: admission deferred (queue full / autoscale latch) —
    the request was NOT enqueued; retry later."""


class ServeClient:
    """Thin submit/poll/fetch client over the daemon's RPC surface."""

    def __init__(self, host: str = "127.0.0.1", port: int | None = None,
                 timeout_s: float = 60.0):
        self.host = host
        self.port = int(port) if port is not None \
            else int(os.environ.get("PARMMG_SERVE_PORT", "8077") or 8077)
        self.timeout_s = float(timeout_s)

    # ---- transport --------------------------------------------------------
    def _rpc(self, method: str, path: str, payload: dict | None = None):
        import http.client
        conn = http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)
        try:
            body = None if payload is None else json.dumps(payload)
            headers = {"Content-Type": "application/json"} if body \
                else {}
            conn.request(method, path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            ctype = resp.getheader("Content-Type") or ""
            out = {}
            if data:
                out = json.loads(data) if "json" in ctype \
                    else data.decode("utf-8", "replace")
            if resp.status == 429:
                raise BackpressureDeferred(resp.status, out)
            if resp.status >= 400:
                raise ServeDaemonError(resp.status, out)
            return out
        finally:
            conn.close()

    @staticmethod
    def _tid_qs(tid: str) -> str:
        from urllib.parse import quote
        return quote(str(tid), safe="")

    # ---- request lifecycle ------------------------------------------------
    def submit(self, vert=None, tet=None, met=None, vref=None,
               tref=None, tenant: str | None = None,
               path: str | None = None, sol: str | None = None) -> str:
        """Submit a tenant mesh: raw arrays (vert/tet[/met][/refs],
        shipped bit-exact as npz and staged daemon-side) or a
        daemon-visible file ``path`` (+ optional ``sol``).  Returns the
        request/tenant id."""
        payload: dict = {}
        if tenant is not None:
            payload["tenant"] = str(tenant)
        if path is not None:
            payload["path"] = str(path)
            if sol is not None:
                payload["sol"] = str(sol)
        else:
            arrays = {"vert": np.asarray(vert), "tet": np.asarray(tet)}
            for k, v in (("met", met), ("vref", vref), ("tref", tref)):
                if v is not None:
                    arrays[k] = np.asarray(v)
            buf = io.BytesIO()
            np.savez_compressed(buf, **arrays)
            payload["npz_b64"] = base64.b64encode(
                buf.getvalue()).decode("ascii")
        return self._rpc("POST", "/submit", payload)["tid"]

    def poll(self, tid: str) -> dict:
        return self._rpc("GET", f"/poll?tid={self._tid_qs(tid)}")

    def wait(self, tid: str, timeout_s: float = 600.0,
             interval_s: float = 0.05) -> dict:
        """Poll until the request reaches a terminal state; returns the
        final poll payload.  Raises TimeoutError past ``timeout_s``."""
        t0 = time.monotonic()
        while True:
            got = self.poll(tid)
            if got["state"] not in ("queued", "running"):
                return got
            if time.monotonic() - t0 > timeout_s:
                raise TimeoutError(
                    f"request {tid} still {got['state']} after "
                    f"{timeout_s}s")
            time.sleep(interval_s)

    def fetch(self, tid: str) -> dict:
        """Merged result of a DONE request as
        {mesh field: np.ndarray, "met": np.ndarray} — bit-identical to
        the daemon-side merge."""
        got = self._rpc("GET", f"/fetch?tid={self._tid_qs(tid)}")
        raw = base64.b64decode(got["npz_b64"].encode("ascii"))
        with np.load(io.BytesIO(raw), allow_pickle=False) as z:
            return {k: z[k] for k in z.files}

    # ---- ops surface ------------------------------------------------------
    def health(self) -> dict:
        return self._rpc("GET", "/healthz")

    def metrics_text(self) -> str:
        return self._rpc("GET", "/metrics")

    def report(self) -> dict:
        return self._rpc("GET", "/report")

    def pause(self) -> dict:
        return self._rpc("POST", "/pause")

    def resume(self) -> dict:
        return self._rpc("POST", "/resume")

    def step(self) -> dict:
        """Run exactly one serving-loop iteration (deterministic tests
        against a paused daemon)."""
        return self._rpc("POST", "/step")

    def shutdown(self) -> dict:
        return self._rpc("POST", "/shutdown")
