"""Pool daemon: the persistent serving process (ROADMAP item 3a).

``SlotPool``/``ServeDriver`` made multi-tenant serving a LIBRARY: the
warm compiled ``_group_block`` programs — and with them the whole
zero-compile serving story — died with the one submitting process.
This module makes it a SERVICE: a daemon owns one ``ServeDriver`` (and
thereby the warm compiled programs, the compile ledger, and the
persistent compile cache configured at startup) for its lifetime, and
fronts ``submit/poll/fetch`` with a thin stdlib HTTP/JSON RPC layer so
clients churn while slots stay hot.

Transport (stdlib only, localhost-class): JSON bodies; mesh arrays ride
base64 npz (bit-exact in both directions).  Endpoints:

    POST /submit    {tenant?, npz_b64?, path?, sol?} -> {tid}
                    (HTTP 429 {error, deferred:true} under admission
                    backpressure — retry later)
    GET  /poll?tid= -> request state machine position
    GET  /fetch?tid=-> {npz_b64}: merged mesh fields + met (409 until
                    the request is done)
    GET  /healthz   -> liveness + loop counters
    GET  /metrics   -> Prometheus text exposition (obs registry)
    GET  /report    -> the full ServeDriver report
    POST /pause /resume /step /shutdown  (ops + deterministic tests;
                    /step runs exactly one serving-loop iteration)

Threads: one HTTP server (per-request handler threads) + one serving
loop; a single re-entrant lock serializes driver access, so RPC
handlers observe consistent state between steps.

Failure semantics: the RPC dispatch is a named faultpoint
(``serve.daemon_rpc``, armed via PARMMG_FAULT) — an injected or real
fault while handling a tenant's request kills THAT request mid-flight:
the tenant is quarantined (``ServeDriver.quarantine``: retired FAILED,
slot scrubbed + recycled) while cohort-mates keep their bit-identical
results and the daemon keeps serving (gated by run_tests.sh --chaos).
The serving loop composes with the PR 9 ladder unchanged (slot
retries, slot-fault quarantine).

Hang semantics: each serving-loop step runs under an optional
``PARMMG_DEADLINE_SERVE_S`` watchdog (resilience/watchdog.py).  The
first-use grace (``PARMMG_DEADLINE_GRACE_S``) distinguishes the
legitimate cold-compile first step from a wedged loop; on expiry the
daemon flips ``/healthz`` to not-ok with ``wedged: true`` and waits
the stuck step out instead of piling new steps behind the held lock.
"""
from __future__ import annotations

import base64
import io
import json
import threading
from http.server import BaseHTTPRequestHandler

import numpy as np

from .driver import ServeDriver
from .pool import _env_int

__all__ = ["PoolDaemon", "decode_npz", "encode_npz", "mesh_arrays"]


# ---------------------------------------------------------------------------
# bit-exact array transport (base64 npz)
# ---------------------------------------------------------------------------
def mesh_arrays(mesh, met=None) -> dict:
    """Merged (mesh, met) -> {field: np.ndarray} payload.  Accepts a
    core Mesh (MESH_FIELDS) or a plain dict of arrays (the host-only
    stub pools of the tier-1 tests)."""
    if isinstance(mesh, dict):
        out = {k: np.asarray(v) for k, v in mesh.items()}
    else:
        from ..core.mesh import MESH_FIELDS
        out = {f: np.asarray(getattr(mesh, f)) for f in MESH_FIELDS}
    if met is not None:
        out["met"] = np.asarray(met)
    return out


def encode_npz(arrays: dict) -> str:
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    return base64.b64encode(buf.getvalue()).decode("ascii")


def decode_npz(b64: str) -> dict:
    raw = base64.b64decode(b64.encode("ascii"))
    with np.load(io.BytesIO(raw), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


# ---------------------------------------------------------------------------
# the daemon
# ---------------------------------------------------------------------------
class PoolDaemon:
    """Persistent pool service: HTTP front-end + serving-loop thread
    around one :class:`ServeDriver`.

    ``port`` defaults to PARMMG_SERVE_PORT (8077); ``port=0`` binds an
    ephemeral port (tests/gates), readable from :attr:`port` after
    :meth:`start`.  ``start_paused`` starts with the loop idle (ops can
    /pause-/resume-/step- the loop deterministically)."""

    def __init__(self, driver: ServeDriver | None = None,
                 host: str = "127.0.0.1", port: int | None = None,
                 idle_sleep_s: float = 0.02, start_paused: bool = False,
                 **driver_kwargs):
        self.driver = driver if driver is not None \
            else ServeDriver(**driver_kwargs)
        self.host = host
        self.port = port if port is not None \
            else _env_int("PARMMG_SERVE_PORT", 8077)
        self.idle_sleep_s = float(idle_sleep_s)
        self.paused = bool(start_paused)
        self._wedged = False
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._httpd = None
        self._threads: list[threading.Thread] = []

    # ---- lifecycle --------------------------------------------------------
    def start(self) -> "PoolDaemon":
        from http.server import ThreadingHTTPServer

        from ..obs import trace as otrace
        if self._httpd is not None:
            raise RuntimeError("daemon already started")
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _Handler)
        self._httpd.daemon_threads = True
        self._httpd.pool_daemon = self
        self.port = int(self._httpd.server_address[1])
        self._threads = [
            threading.Thread(target=self._httpd.serve_forever,
                             name="parmmg-serve-http", daemon=True),
            threading.Thread(target=self._loop,
                             name="parmmg-serve-loop", daemon=True),
        ]
        for t in self._threads:
            t.start()
        otrace.event("serve.daemon_start", port=self.port)
        otrace.log(1, f"serve daemon: listening on "
                      f"http://{self.host}:{self.port}", err=True)
        return self

    def _loop(self) -> None:
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        from ..resilience.watchdog import (WatchdogTimeout,
                                           deadline_knob,
                                           run_with_deadline)

        def step():
            # the lock is taken INSIDE the guarded fn so a wedged step
            # is observable: the watchdog thread owns the RLock for the
            # step's whole (possibly unbounded) duration, /healthz
            # stays lock-free by design
            with self._lock:
                # lint: ok(R9) — the hold IS the design: serving and
                # RPCs serialize on the RLock, and this exact hold is
                # what run_with_deadline(PARMMG_DEADLINE_SERVE_S)
                # bounds; the subprocess legs inside carry their own
                # watchdogs (PARMMG_POLISH_TIMEOUT_S; the native-ext
                # build is one-time and memoized)
                return self.driver.service_once()

        while not self._stop.is_set():
            if self.paused:
                self._stop.wait(self.idle_sleep_s)
                continue
            # re-read each iteration: ops can arm/disarm the step
            # deadline on a live daemon.  run_with_deadline's first-use
            # grace (PARMMG_DEADLINE_GRACE_S) absorbs the legitimate
            # cold-compile first step; after that, a step exceeding the
            # budget is a WEDGED loop, not a slow one.
            dl = deadline_knob("PARMMG_DEADLINE_SERVE_S")
            try:
                st = run_with_deadline(step, dl, "serve.slot_step")
            except WatchdogTimeout as e:
                # the abandoned step thread still holds the RLock:
                # spawning more steps would just pile up behind it.
                # Mark the daemon wedged (healthz flips not-ok so a
                # supervisor can restart it) and wait the thread out —
                # if it ever finishes, serving resumes.
                REGISTRY.counter("serve.step_timeouts").inc()
                otrace.event("serve.step_timeout",
                             seconds=float(e.seconds))
                otrace.log(0, f"serve daemon: serving step exceeded "
                              f"{e.seconds:g}s deadline — wedged "
                              "(healthz not-ok) until it returns",
                           err=True)
                # lint: ok(R9) — GIL-atomic bool store: only this loop
                # thread ever writes _wedged; /healthz reads it
                # lock-free BY DESIGN (a liveness probe must answer
                # while the abandoned step still owns the RLock —
                # taking the lock here would recreate the wedge)
                self._wedged = True
                th = getattr(e, "thread", None)
                while th is not None and th.is_alive() \
                        and not self._stop.is_set():
                    self._stop.wait(max(self.idle_sleep_s, 0.1))
                # lint: ok(R9) — same GIL-atomic probe flag as above
                self._wedged = False
                continue
            except Exception as e:
                # the loop is the service: an escaped iteration error
                # (a degenerate merge, an actuation failure) must not
                # silently kill serving while /healthz stays green —
                # account it, back off, keep looping (per-tenant fault
                # containment already happened below this level)
                REGISTRY.counter("serve.loop_errors").inc()
                otrace.event("serve.loop_error", detail=repr(e)[:300])
                otrace.log(0, f"serve daemon: serving-loop iteration "
                              f"failed ({e!r}); continuing", err=True)
                self._stop.wait(max(self.idle_sleep_s, 0.1))
                continue
            if st != "active":
                # idle, or stalled on capacity: a daemon WAITS (new
                # submissions / autoscale / timeouts resolve it) rather
                # than mass-rejecting like the batch run() loop
                self._stop.wait(self.idle_sleep_s)

    def shutdown(self) -> None:
        from ..obs import trace as otrace
        self._stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=10)
        otrace.event("serve.daemon_stop", port=self.port)
        otrace.log(1, "serve daemon: stopped", err=True)

    def alive(self) -> bool:
        return any(t.is_alive() for t in self._threads)

    def __enter__(self) -> "PoolDaemon":
        return self if self._httpd is not None else self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ---- RPC dispatch -----------------------------------------------------
    def handle_rpc(self, method: str, op: str, qs: dict, payload: dict):
        """One RPC -> (status, body, content_type).  The dispatch runs
        behind the ``serve.daemon_rpc`` faultpoint: a fault here kills
        THIS request — its tenant is quarantined, the daemon and every
        other tenant keep going."""
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        from ..resilience.faults import faultpoint
        tid = payload.get("tenant") or (qs.get("tid") or [None])[0]
        otrace.log(2, f"serve daemon: {method} /{op}"
                      + (f" tid={tid}" if tid else ""), err=True)
        otrace.event("serve.rpc", op=op,
                     **({"tenant": tid} if tid else {}))
        try:
            faultpoint("serve.daemon_rpc", key=tid if tid else op)
        except Exception as e:
            # the request dies mid-flight: quarantine ITS tenant, keep
            # serving everyone else (PR 9 isolation, RPC-edge form)
            q = False
            if tid:
                with self._lock:
                    # lint: ok(R9) — quarantine must retire the tenant
                    # atomically with pool state (PR 9 isolation); the
                    # only subprocess on its retire->merge path is the
                    # one-time memoized native-extension build
                    q = self.driver.quarantine(
                        tid, f"daemon rpc fault: {e!r:.200}")
            REGISTRY.counter("serve.rpc_faults").inc()
            otrace.event("serve.rpc_fault", op=op,
                         **({"tenant": tid} if tid else {}))
            otrace.log(1, f"serve daemon: RPC fault on /{op}"
                          + (f" — tenant {tid} quarantined" if q else ""),
                       err=True)
            return 500, {"error": repr(e), "quarantined": q}, None
        try:
            return self._dispatch(method, op, qs, payload, tid)
        except Exception as e:
            REGISTRY.counter("serve.rpc_errors").inc()
            otrace.log(1, f"serve daemon: /{op} failed ({e!r})",
                       err=True)
            return 500, {"error": repr(e)}, None

    def _dispatch(self, method: str, op: str, qs: dict, payload: dict,
                  tid):
        d = self.driver
        if op == "submit" and method == "POST":
            b64 = payload.get("npz_b64")
            with self._lock:
                if b64:
                    mesh, met = d.stage_payload(decode_npz(b64))
                    got, reason = d.try_submit(
                        mesh=mesh, met=met, tenant=payload.get("tenant"))
                else:
                    got, reason = d.try_submit(
                        path=payload.get("path"),
                        sol=payload.get("sol"),
                        tenant=payload.get("tenant"))
            if got is None:
                return 429, {"error": reason, "deferred": True}, None
            return 200, {"tid": got}, None
        if op == "poll":
            with self._lock:
                if tid is None or tid not in d.requests:
                    return 404, {"error": f"unknown request {tid!r}"}, \
                        None
                return 200, d.poll(tid), None
        if op == "fetch":
            with self._lock:
                if tid is None or tid not in d.requests:
                    return 404, {"error": f"unknown request {tid!r}"}, \
                        None
                try:
                    mesh, met = d.fetch(tid)
                except RuntimeError as e:
                    return 409, {"error": str(e)}, None
                arrays = mesh_arrays(mesh, met)
            return 200, {"tid": tid, "npz_b64": encode_npz(arrays)}, None
        if op == "healthz":
            # deliberately LOCK-FREE: a liveness probe must answer even
            # while the loop thread holds the driver lock through a
            # cold-compile step; the counters below are single reads of
            # host ints/lists (snapshot-racy, probe-accurate).  ok ==
            # the serving loop can make progress (paused counts: that
            # is an operator choice, not a death)
            loop_alive = bool(len(self._threads) > 1
                              and self._threads[1].is_alive())
            out = {"ok": bool((self.paused or loop_alive)
                              and not self._wedged),
                   "paused": self.paused,
                   "loop_alive": loop_alive,
                   "wedged": self._wedged,
                   "steps": d.pool.steps,
                   "active": len(d.pool.active_tenants()),
                   "queue": len(d.queue),
                   "requests": len(d.requests),
                   "quarantined": list(d.pool.quarantined)}
            return 200, out, None
        if op == "metrics":
            from ..obs.metrics import REGISTRY
            return (200, REGISTRY.to_prometheus(),
                    "text/plain; version=0.0.4")
        if op == "report":
            with self._lock:
                rep = d.report(list(d._occupancy_traj))
            return 200, rep, None
        if op == "pause" and method == "POST":
            # lint: ok(R9) — GIL-atomic bool store: pause/resume are
            # the handler thread's only writes, the loop re-reads each
            # iteration and /healthz reads lock-free by design; a
            # one-iteration race just delays the pause by one step
            self.paused = True
            return 200, {"paused": True}, None
        if op == "resume" and method == "POST":
            # lint: ok(R9) — same GIL-atomic operator flag as pause
            self.paused = False
            return 200, {"paused": False}, None
        if op == "step" and method == "POST":
            with self._lock:
                # lint: ok(R9) — the ops 'step' RPC deliberately runs
                # one synchronous serving step under the RLock (same
                # work the loop bounds with PARMMG_DEADLINE_SERVE_S);
                # its subprocess legs carry PARMMG_POLISH_TIMEOUT_S
                # and the one-time native build
                st = d.service_once()
            return 200, {"state": st}, None
        if op == "shutdown" and method == "POST":
            # respond first, stop from a fresh thread (shutdown joins
            # the HTTP thread — never from inside a handler)
            threading.Thread(target=self.shutdown,
                             name="parmmg-serve-shutdown",
                             daemon=True).start()
            return 200, {"ok": True}, None
        return 404, {"error": f"unknown op {op!r} ({method})"}, None


# ---------------------------------------------------------------------------
# stdlib HTTP plumbing
# ---------------------------------------------------------------------------
class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):        # route through obs (R3)
        from ..obs import trace as otrace
        otrace.log(3, "serve daemon http: " + fmt % args, err=True)

    def _route(self, method: str) -> None:
        from urllib.parse import parse_qs, urlsplit
        u = urlsplit(self.path)
        op = u.path.strip("/") or "healthz"
        qs = parse_qs(u.query)
        payload: dict = {}
        n = int(self.headers.get("Content-Length") or 0)
        if n:
            try:
                payload = json.loads(self.rfile.read(n).decode("utf-8"))
            except ValueError:
                payload = {}
        code, body, ctype = self.server.pool_daemon.handle_rpc(
            method, op, qs, payload)
        data = body.encode("utf-8") if isinstance(body, str) \
            else json.dumps(body, default=str).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype or "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")
