"""Request lifecycle + queue driver for the serving pool.

A submit/poll/fetch front-end over :class:`serve.pool.SlotPool` — the
queue/driver layer of ROADMAP open item 3, now composed from three
parts:

- this driver: the request state machine (queued / running / done /
  rejected / failed / timeout), retirement (per-request tenant-tagged
  ``AdaptStats`` + qmin/qmean quality SLO, slot recycling, merge-free
  ``write_distributed`` checkpoints) and the serving loop
  (:meth:`ServeDriver.service_once` — one admit+step+retire+autoscale
  iteration, shared by the batch :meth:`run` loop, the streaming bench
  and the pool daemon's loop thread);
- :mod:`serve.admission` — staging + queue pump + backpressure +
  the STREAMING mid-step slot re-rent (``PARMMG_SERVE_STREAM``);
- :mod:`serve.autoscale` — the SLO-driven bucket-ladder resizing and
  admission-deferral controller (``PARMMG_SERVE_AUTOSCALE``).

``submit`` enqueues unconditionally (library callers own their queue);
``try_submit`` is the backpressure-aware edge the daemon maps to
HTTP 429.  ``quarantine`` is the RPC-edge isolation hook (the
``serve.daemon_rpc`` faultpoint): a request killed mid-flight retires
FAILED with its slot scrubbed + recycled while cohort-mates keep their
bit-identical results.

Knobs (env, constructor args win): PARMMG_SERVE_MAX_INFLIGHT (0 =
unbounded), PARMMG_SERVE_TIMEOUT_S (wall-clock per request, 0 = off),
PARMMG_SERVE_MAX_QUEUE / _STREAM / _AUTOSCALE and the pool's
PARMMG_SERVE_SLOTS / _CHUNK / _MAX_CAPP / _MAX_CAPT.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque

import numpy as np

from .admission import (DONE, FAILED, QUEUED, REJECTED, RUNNING,  # noqa: F401
                        TERMINAL, TIMEOUT, AdmissionController,
                        stage_file)
from .pool import SlotPool, _env_int

# legacy import surface: _stage_file lived here before serve/admission
_stage_file = stage_file


@dataclasses.dataclass
class ServeRequest:
    """One tenant request riding the pool."""
    tid: str
    mesh: object = None          # staged core Mesh (host/device)
    met: object = None
    path: str | None = None      # input file (medit/.vtu), lazy-staged
    sol: str | None = None
    state: str = QUEUED
    reason: str = ""
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_done: float = 0.0
    quality: dict | None = None  # {"qmin", "qmean", "ntets"} SLO fields
    slo: dict | None = None      # {"qmin_floor", "ok"} verdict
    stats: object = None         # tenant-tagged AdaptStats
    out_files: list = dataclasses.field(default_factory=list)

    @property
    def latency_s(self) -> float:
        return max(0.0, self.t_done - self.t_submit)


class ServeDriver:
    """FIFO queue + admission + retirement around a SlotPool."""

    def __init__(self, pool: SlotPool | None = None,
                 out_dir: str | None = None,
                 max_inflight: int | None = None,
                 timeout_s: float | None = None,
                 verbose: int = 0,
                 stream: bool | None = None,
                 max_queue: int | None = None,
                 autoscale=None, retain_done: int | None = None,
                 **pool_kwargs):
        self.pool = pool if pool is not None else SlotPool(**pool_kwargs)
        self.out_dir = out_dir
        self.max_inflight = max_inflight if max_inflight is not None \
            else _env_int("PARMMG_SERVE_MAX_INFLIGHT", 0)
        if timeout_s is None:
            import os
            timeout_s = float(os.environ.get("PARMMG_SERVE_TIMEOUT_S",
                                             "0") or 0)
        self.timeout_s = float(timeout_s)
        self.verbose = verbose
        self.requests: dict[str, ServeRequest] = {}
        self.queue: list[str] = []
        self._seq = 0
        self.admission = AdmissionController(self, max_queue=max_queue,
                                             stream=stream)
        # autoscale: None = knob default (PARMMG_SERVE_AUTOSCALE, on),
        # False = off, or a ready AutoscaleController instance
        if autoscale is None:
            from .autoscale import AutoscaleController, autoscale_enabled
            autoscale = AutoscaleController() if autoscale_enabled() \
                else False
        self.autoscale = autoscale or None
        # bounded occupancy trajectory (a daemon serves indefinitely)
        self._occupancy_traj: deque = deque(maxlen=4096)
        # terminal-request retention: a daemon retains at most this
        # many finished requests (each holds its merged mesh + metric
        # until fetched/evicted) — oldest-terminal eviction keeps the
        # request table, and every O(requests) scan, bounded
        self.retain_done = retain_done if retain_done is not None \
            else 4096

    # ---- API --------------------------------------------------------------
    def submit(self, mesh=None, met=None, path=None, sol=None,
               tenant: str | None = None) -> str:
        """Enqueue a request; returns the request/tenant id."""
        if tenant is None:
            tenant = f"t{self._seq:04d}"
        self._seq += 1
        if tenant in self.requests:
            raise ValueError(f"duplicate tenant id {tenant!r}")
        req = ServeRequest(tid=tenant, mesh=mesh, met=met, path=path,
                           sol=sol, t_submit=time.perf_counter())
        self.requests[tenant] = req
        self.queue.append(tenant)
        return tenant

    def try_submit(self, mesh=None, met=None, path=None, sol=None,
                   tenant: str | None = None):
        """Backpressure-aware submit: returns ``(tid, None)`` when
        accepted, ``(None, reason)`` when deferred (the daemon's
        HTTP 429; the streaming bench retries the arrival)."""
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        reason = self.admission.backpressure()
        if reason:
            self.admission.deferred += 1
            REGISTRY.counter("serve.deferred").inc()
            otrace.event("serve.deferred",
                         **({"tenant": tenant} if tenant else {}))
            otrace.log(2, f"serve: deferred submit ({reason})",
                       verbose=self.verbose, err=True)
            return None, reason
        return self.submit(mesh=mesh, met=met, path=path, sol=sol,
                           tenant=tenant), None

    def poll(self, tid: str) -> dict:
        r = self.requests[tid]
        out = {"tid": tid, "state": r.state, "reason": r.reason}
        if r.state == DONE:
            out["latency_s"] = round(r.latency_s, 3)
            out["quality"] = r.quality
        return out

    def fetch(self, tid: str):
        """Merged (Mesh, met) of a DONE request (merge-free file output
        goes through write_distributed / out_dir instead)."""
        r = self.requests[tid]
        if r.state != DONE:
            raise RuntimeError(f"request {tid} is {r.state}, not done")
        return r.mesh, r.met

    def write_distributed(self, tid: str, path) -> list:
        """Merge-free checkpoint of a tenant's slot straight from the
        pool's stacked state (the reference's -distributed-output never
        centralizes either)."""
        from ..io.distributed import stacked_to_distributed_files
        b, i = self.pool.slot_state(tid)
        return stacked_to_distributed_files(
            path, b.stacked, None, None, b.nslots, shards=[i])

    def stage_payload(self, arrays: dict):
        """npz-style array payload -> staged (mesh, met) — the daemon's
        RPC staging edge (one rule with admission.stage_arrays so
        daemon-served results are bit-identical to standalone runs).
        Overridable by the host-only stub drivers in tier-1 tests."""
        from .admission import stage_arrays
        return stage_arrays(
            arrays["vert"], arrays["tet"],
            vref=arrays.get("vref"), tref=arrays.get("tref"),
            met=arrays.get("met"))

    def quarantine(self, tid: str, reason: str) -> bool:
        """RPC-edge quarantine (the ``serve.daemon_rpc`` faultpoint's
        isolation contract): a request killed mid-flight retires FAILED
        — a RUNNING tenant's slot is scrubbed + recycled through the
        normal retirement path, a QUEUED one is dropped from the queue
        — and cohort-mates are untouched (slot isolation).  Returns
        False for unknown or already-terminal requests (no-op)."""
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        r = self.requests.get(tid)
        if r is None or r.state in TERMINAL:
            return False
        self.pool.quarantined.append(tid)
        REGISTRY.counter("serve.quarantined").inc()
        otrace.event("serve.quarantine", tenant=tid, detail=reason[:300])
        if r.state == RUNNING:
            self.pool.slot_of(tid).failed = reason
            self._retire(tid)
        else:
            self.queue = [t for t in self.queue if t != tid]
            REGISTRY.gauge("serve.queue_depth").set(len(self.queue))
            r.state = FAILED
            r.reason = reason
            r.t_done = time.perf_counter()
        otrace.log(1, f"serve: QUARANTINED {tid} at the RPC edge "
                      f"({reason})", verbose=self.verbose, err=True)
        return True

    # ---- retirement -------------------------------------------------------
    def _quality(self, mesh, met) -> dict:
        """Merged tenant state -> the quality/SLO fields (overridable
        by the host-only stub drivers in tier-1 tests)."""
        from ..ops.quality import quality_histogram, tet_quality
        q = tet_quality(mesh, met)
        _, qmin, qmean, nbad = quality_histogram(q, mesh.tmask)
        return {"qmin": round(float(qmin), 6),
                "qmean": round(float(qmean), 6),
                "nbad": int(nbad),
                "ntets": int(np.asarray(mesh.tmask).sum())}

    def _retire(self, tid: str) -> None:
        from ..obs.metrics import REGISTRY
        from ..obs.trace import log as _olog
        r = self.requests[tid]
        slot = self.pool.slot_of(tid)
        r.stats = slot.stats
        if slot.failed:
            r.state = FAILED
            r.reason = slot.failed
        else:
            if self.out_dir is not None:
                from pathlib import Path
                out = Path(self.out_dir) / f"{tid}.mesh"
                r.out_files = [str(p) for p in
                               self.write_distributed(tid, out)]
            mesh, met = self.pool.merge(tid)
            r.mesh, r.met = mesh, met
            r.quality = self._quality(mesh, met)
            r.state = DONE
            # per-tenant SLO verdict (machine-readable, tenant-tagged):
            # quality floor from PARMMG_SERVE_SLO_QMIN (0 = quality SLO
            # off, verdict rides on completion alone)
            import os
            floor = float(os.environ.get("PARMMG_SERVE_SLO_QMIN", "0")
                          or 0)
            ok = r.quality["qmin"] >= floor
            r.slo = {"qmin_floor": floor, "ok": ok}
            REGISTRY.counter(
                "serve.slo_ok" if ok else "serve.slo_violation",
                tenant=tid).inc()
        r.t_done = time.perf_counter()
        if r.state == DONE:
            REGISTRY.histogram("serve.latency_s").observe(r.latency_s)
        # per-tenant counters land tenant-namespaced in the registry
        if r.stats is not None:
            r.stats.publish()
        self.pool.release(tid)
        _olog(1, f"serve: retired {tid} ({r.state}"
                 + (f", qmin {r.quality['qmin']}" if r.quality else "")
                 + f", {r.latency_s:.2f}s)",
              verbose=self.verbose, err=True)

    def _expire_timeouts(self) -> None:
        """Expire requests past PARMMG_SERVE_TIMEOUT_S.  Reclamation
        contract for a RUNNING tenant (regression-tested,
        tests/test_serve.py): ``pool.release`` must scrub the slot row
        back to the dead-mesh state AND return the slot to the bucket's
        free list, so the next queued tenant can rent it — a timed-out
        tenant must never strand capacity."""
        if not self.timeout_s:
            return
        now = time.perf_counter()
        for tid, r in self.requests.items():
            if r.state == RUNNING and now - r.t_submit > self.timeout_s:
                slot = self.pool.slot_of(tid)
                r.stats = slot.stats
                r.state = TIMEOUT
                r.reason = f"exceeded {self.timeout_s}s"
                r.t_done = now
                self.pool.release(tid)
            elif r.state == QUEUED and now - r.t_submit > self.timeout_s:
                r.state = TIMEOUT
                r.reason = f"queued past {self.timeout_s}s"
                r.t_done = now
                self.queue = [t for t in self.queue if t != tid]

    # ---- the serving loop --------------------------------------------------
    def service_once(self) -> str:
        """One serving-loop iteration: expire timeouts, pump the
        admission queue, run the autoscale controller, advance the pool
        one step (with streaming mid-step re-rent when enabled) and
        retire finished tenants.  Returns the loop state:

        - ``"active"`` — tenants advanced (call again immediately);
        - ``"idle"``   — nothing queued, nothing running;
        - ``"stalled"``— queued work the pool could not admit with
          every slot free (capacity deadlock; :meth:`run` rejects it,
          a daemon keeps waiting — timeouts still apply)."""
        self._expire_timeouts()
        admitted = self.admission.pump()
        if self.autoscale is not None:
            d = self.autoscale.tick(self.pool, self.admission)
            if d.grow and self.queue:
                # a grown bucket can admit immediately — don't make the
                # blocked tenant wait one extra loop iteration
                admitted += self.admission.pump()
        if not self.pool.active_tenants():
            if self.queue and not admitted:
                return "stalled"
            if not self.queue and not admitted:
                return "idle"
            return "active"
        self._occupancy_traj.append(self.pool.occupancy())
        on_retire = self.admission.mid_step if self.admission.stream \
            else None
        for tid in self.pool.step(verbose=self.verbose,
                                  on_retire=on_retire):
            # streaming mode already retired mid-step; retire the rest
            if self.requests[tid].state == RUNNING:
                self._retire(tid)
        self._evict_terminal()
        return "active"

    def _evict_terminal(self) -> None:
        """Bound the request table for indefinite serving: beyond
        ``retain_done`` requests, evict the OLDEST terminal ones (each
        DONE request pins its merged mesh + metric until fetched).  An
        evicted id polls/fetches as unknown, and :meth:`report` covers
        retained requests only — the bounded-history contract of a
        persistent service (batch ``run()`` callers stay whole below
        the default 4096 bound)."""
        excess = len(self.requests) - self.retain_done
        if excess <= 0:
            return
        terminal = sorted(
            (r.t_done, tid) for tid, r in self.requests.items()
            if r.state in TERMINAL)
        for _t, tid in terminal[:excess]:
            del self.requests[tid]

    def _reject_stalled(self) -> None:
        """Terminal handling of a capacity deadlock (e.g. max_inflight
        with 0 slots): reject everything still queued rather than
        spin."""
        for tid in self.queue:
            r = self.requests[tid]
            r.state = REJECTED
            r.reason = "pool cannot admit (no slot ever)"
            r.t_done = time.perf_counter()
        self.queue = []

    def run(self, max_steps: int = 10000) -> dict:
        """Drive the loop until every request reaches a terminal state.
        Returns the serving report (per-tenant + pool aggregates)."""
        self._occupancy_traj.clear()
        for _ in range(max_steps):
            st = self.service_once()
            if st == "idle":
                break
            if st == "stalled":
                self._reject_stalled()
                break
        return self.report(list(self._occupancy_traj))

    # ---- reporting ----------------------------------------------------------
    def report(self, occupancy_traj=None) -> dict:
        from ..ops.adapt import AdaptStats
        agg = AdaptStats()
        tenants = {}
        for tid, r in sorted(self.requests.items()):
            if r.stats is not None:
                agg += r.stats          # namespaced per tenant
            tenants[tid] = {
                "state": r.state,
                "reason": r.reason,
                "latency_s": round(r.latency_s, 3),
                "quality": r.quality,
                "slo": r.slo,
                "cycles": r.stats.cycles if r.stats else 0,
                "ops": ([r.stats.nsplit, r.stats.ncollapse,
                         r.stats.nswap, r.stats.nmoved]
                        if r.stats else [0, 0, 0, 0]),
                "out_files": r.out_files,
            }
        lat = sorted(t["latency_s"] for t in tenants.values()
                     if t["state"] == DONE)

        def pct(p):
            # nearest-rank percentile, integer ceil: rank(p) =
            # ceil(p*n) (int(p*n) would hand p90-of-10 the maximum;
            # float ceil mis-rounds 0.9*10)
            if not lat:
                return 0.0
            rank = (int(p * 100) * len(lat) + 99) // 100
            return round(lat[min(len(lat), max(rank, 1)) - 1], 3)

        return {
            "tenants": tenants,
            "served": sum(1 for t in tenants.values()
                          if t["state"] == DONE),
            "rejected": sum(1 for t in tenants.values()
                            if t["state"] == REJECTED),
            "failed": sum(1 for t in tenants.values()
                          if t["state"] in (FAILED, TIMEOUT)),
            "latency_p50_s": pct(0.50),
            "latency_p90_s": pct(0.90),
            "latency_p99_s": pct(0.99),
            "latency_max_s": lat[-1] if lat else 0.0,
            "admission": self.admission.summary(),
            "autoscale": (self.autoscale.summary()
                          if self.autoscale is not None else None),
            "pool": {
                "steps": self.pool.steps,
                "dispatches": self.pool.dispatches,
                "chunk": self.pool.chunk,
                "slots_per_bucket": self.pool.slots_per_bucket,
                # fault-isolation state (resilience ladder, serving
                # form): tenants retired FAILED after
                # PARMMG_SERVE_MAX_RETRIES slot faults
                "quarantined": list(self.pool.quarantined),
                "max_slot_retries": self.pool.max_slot_retries,
                "buckets": self.pool.occupancy(),
                "active_per_step": list(self.pool.active_per_step),
                "chunk_recommendation": self.pool.chunk_recommendation(),
                "pipeline_s": {k: round(v, 3)
                               for k, v in self.pool.timers.acc.items()},
            },
            "occupancy_traj": occupancy_traj or [],
            "agg_sched_extra": {k: v for k, v in agg.sched_extra.items()},
        }
