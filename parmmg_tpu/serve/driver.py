"""Request lifecycle + queue driver for the serving pool.

A minimal submit/poll/fetch front-end over :class:`serve.pool.SlotPool`
— the "simple queue/driver front-end" of ROADMAP open item 3:

- **submit** a tenant mesh (in-memory arrays, or a medit ``.mesh[b]`` /
  VTK ``.vtu`` file streamed through io.medit / io.vtk, with an
  optional ``.sol`` metric) -> request id;
- the **run loop** admits queued requests into the smallest fitting
  bucket (FIFO, bounded by PARMMG_SERVE_MAX_INFLIGHT), steps the pool,
  and retires converged tenants: per-request ``AdaptStats``
  (tenant-tagged — ops.adapt.AdaptStats refuses cross-tenant merges)
  and the qmin/qmean quality SLO are computed on retirement, the slot
  is recycled for the next queued request;
- **poll** returns the request state machine position
  (queued / running / done / rejected / failed / timeout);
- **fetch** returns the merged (Mesh, met); ``write_distributed``
  emits the merge-free per-tenant checkpoint straight from the slot
  state (io.distributed.stacked_to_distributed_files with a slot
  subset — the -distributed-output contract, no centralization).

Knobs (env, constructor args win): PARMMG_SERVE_MAX_INFLIGHT (0 =
unbounded), PARMMG_SERVE_TIMEOUT_S (wall-clock per request, 0 = off),
plus the pool's PARMMG_SERVE_SLOTS / _CHUNK / _MAX_CAPP / _MAX_CAPT.
"""
from __future__ import annotations

import dataclasses
import time

import numpy as np

from .pool import SlotPool, _env_int

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
REJECTED = "rejected"
FAILED = "failed"
TIMEOUT = "timeout"


@dataclasses.dataclass
class ServeRequest:
    """One tenant request riding the pool."""
    tid: str
    mesh: object = None          # staged core Mesh (host/device)
    met: object = None
    path: str | None = None      # input file (medit/.vtu), lazy-staged
    sol: str | None = None
    state: str = QUEUED
    reason: str = ""
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_done: float = 0.0
    quality: dict | None = None  # {"qmin", "qmean", "ntets"} SLO fields
    slo: dict | None = None      # {"qmin_floor", "ok"} verdict
    stats: object = None         # tenant-tagged AdaptStats
    out_files: list = dataclasses.field(default_factory=list)

    @property
    def latency_s(self) -> float:
        return max(0.0, self.t_done - self.t_submit)


def _stage_file(path: str, sol: str | None):
    """File -> (core Mesh, met): medit or VTK in, analysis tags on,
    metric from the .sol (scalar/tensor) or the -optim default."""
    import jax.numpy as jnp
    from ..core.mesh import make_mesh
    from ..io.medit import read_mesh, read_sol
    from ..ops.analysis import analyze_mesh
    from ..ops.metric import metric_optim

    vtu_met = None
    if str(path).endswith(".vtu"):
        from ..io.vtk import read_vtu_medit
        mm, vtu_met, _fields = read_vtu_medit(path)
    else:
        mm = read_mesh(path)
    mesh = make_mesh(mm.vert, mm.tetra, vref=mm.vref, tref=mm.tref)
    mesh = analyze_mesh(mesh).mesh
    vals = None
    if sol:
        vals, _types = read_sol(sol)
    elif vtu_met is not None:
        vals = np.asarray(vtu_met)
    if vals is not None:
        vals = np.asarray(vals)
        met = np.ones((mesh.capP,) + vals.shape[1:], np.float64)
        met[: len(vals)] = vals
        if met.ndim == 2 and met.shape[1] == 1:
            met = met[:, 0]
        met = jnp.asarray(met, mesh.vert.dtype)
    else:
        met = metric_optim(mesh)
    return mesh, met


class ServeDriver:
    """FIFO queue + admission + retirement around a SlotPool."""

    def __init__(self, pool: SlotPool | None = None,
                 out_dir: str | None = None,
                 max_inflight: int | None = None,
                 timeout_s: float | None = None,
                 verbose: int = 0, **pool_kwargs):
        self.pool = pool if pool is not None else SlotPool(**pool_kwargs)
        self.out_dir = out_dir
        self.max_inflight = max_inflight if max_inflight is not None \
            else _env_int("PARMMG_SERVE_MAX_INFLIGHT", 0)
        if timeout_s is None:
            import os
            timeout_s = float(os.environ.get("PARMMG_SERVE_TIMEOUT_S",
                                             "0") or 0)
        self.timeout_s = float(timeout_s)
        self.verbose = verbose
        self.requests: dict[str, ServeRequest] = {}
        self.queue: list[str] = []
        self._seq = 0

    # ---- API --------------------------------------------------------------
    def submit(self, mesh=None, met=None, path=None, sol=None,
               tenant: str | None = None) -> str:
        """Enqueue a request; returns the request/tenant id."""
        if tenant is None:
            tenant = f"t{self._seq:04d}"
        self._seq += 1
        if tenant in self.requests:
            raise ValueError(f"duplicate tenant id {tenant!r}")
        req = ServeRequest(tid=tenant, mesh=mesh, met=met, path=path,
                           sol=sol, t_submit=time.perf_counter())
        self.requests[tenant] = req
        self.queue.append(tenant)
        return tenant

    def poll(self, tid: str) -> dict:
        r = self.requests[tid]
        out = {"tid": tid, "state": r.state, "reason": r.reason}
        if r.state == DONE:
            out["latency_s"] = round(r.latency_s, 3)
            out["quality"] = r.quality
        return out

    def fetch(self, tid: str):
        """Merged (Mesh, met) of a DONE request (merge-free file output
        goes through write_distributed / out_dir instead)."""
        r = self.requests[tid]
        if r.state != DONE:
            raise RuntimeError(f"request {tid} is {r.state}, not done")
        return r.mesh, r.met

    def write_distributed(self, tid: str, path) -> list:
        """Merge-free checkpoint of a tenant's slot straight from the
        pool's stacked state (the reference's -distributed-output never
        centralizes either)."""
        from ..io.distributed import stacked_to_distributed_files
        b, i = self.pool.slot_state(tid)
        return stacked_to_distributed_files(
            path, b.stacked, None, None, b.nslots, shards=[i])

    # ---- the serving loop --------------------------------------------------
    def _admit_from_queue(self) -> None:
        inflight = len(self.pool.active_tenants())
        remaining = []
        for tid in self.queue:
            r = self.requests[tid]
            if self.max_inflight and inflight >= self.max_inflight:
                remaining.append(tid)
                continue
            try:
                if r.mesh is None and r.path is not None:
                    r.mesh, r.met = _stage_file(r.path, r.sol)
                # needP counts TET-REFERENCED vertices, exactly what
                # split_to_shards sizes capP from — an orphan vertex
                # must not inflate the admission bucket past the rung
                # the split will actually produce
                tm = np.asarray(r.mesh.tmask)
                nt = int(tm.sum())
                nv = len(np.unique(np.asarray(r.mesh.tet)[tm]))
                mw = 0 if np.asarray(r.met).ndim == 1 \
                    else int(np.asarray(r.met).shape[-1])
            except Exception as e:
                # per-request fault isolation: a corrupt input must not
                # take down the loop or the other tenants
                r.state = FAILED
                r.reason = f"staging failed: {e}"
                r.t_done = time.perf_counter()
                continue
            got = self.pool.admit(tid, nv, nt, met_width=mw)
            if got[0] == "oversize":
                r.state = REJECTED
                r.reason = (f"needs caps {got[1][0]}x{got[1][1]} > pool "
                            f"max {self.pool.max_capP}x"
                            f"{self.pool.max_capT}")
                r.t_done = time.perf_counter()
                continue
            if got[0] == "full":
                remaining.append(tid)       # waits for a recycled slot
                continue
            try:
                self.pool.load(tid, r.mesh, r.met)
            except Exception as e:
                self.pool.release(tid)      # fault isolation (as above)
                r.state = FAILED
                r.reason = f"load failed: {e}"
                r.t_done = time.perf_counter()
                continue
            r.state = RUNNING
            r.t_admit = time.perf_counter()
            inflight += 1
            # stderr: stdout belongs to the front-ends' JSON report
            from ..obs.trace import log as _olog
            _olog(1, f"serve: admitted {tid} -> bucket "
                     f"{got[1][0]}x{got[1][1]} slot {got[2]}",
                  verbose=self.verbose, err=True)
        self.queue = remaining
        from ..obs.metrics import REGISTRY
        REGISTRY.gauge("serve.queue_depth").set(len(self.queue))

    def _retire(self, tid: str) -> None:
        from ..obs.metrics import REGISTRY
        from ..obs.trace import log as _olog
        from ..ops.quality import quality_histogram, tet_quality
        r = self.requests[tid]
        slot = self.pool.slot_of(tid)
        r.stats = slot.stats
        if slot.failed:
            r.state = FAILED
            r.reason = slot.failed
        else:
            if self.out_dir is not None:
                from pathlib import Path
                out = Path(self.out_dir) / f"{tid}.mesh"
                r.out_files = [str(p) for p in
                               self.write_distributed(tid, out)]
            mesh, met = self.pool.merge(tid)
            r.mesh, r.met = mesh, met
            q = tet_quality(mesh, met)
            _, qmin, qmean, nbad = quality_histogram(q, mesh.tmask)
            r.quality = {"qmin": round(float(qmin), 6),
                         "qmean": round(float(qmean), 6),
                         "nbad": int(nbad),
                         "ntets": int(np.asarray(mesh.tmask).sum())}
            r.state = DONE
            # per-tenant SLO verdict (machine-readable, tenant-tagged):
            # quality floor from PARMMG_SERVE_SLO_QMIN (0 = quality SLO
            # off, verdict rides on completion alone)
            import os
            floor = float(os.environ.get("PARMMG_SERVE_SLO_QMIN", "0")
                          or 0)
            ok = r.quality["qmin"] >= floor
            r.slo = {"qmin_floor": floor, "ok": ok}
            REGISTRY.counter(
                "serve.slo_ok" if ok else "serve.slo_violation",
                tenant=tid).inc()
        r.t_done = time.perf_counter()
        if r.state == DONE:
            REGISTRY.histogram("serve.latency_s").observe(r.latency_s)
        # per-tenant counters land tenant-namespaced in the registry
        if r.stats is not None:
            r.stats.publish()
        self.pool.release(tid)
        _olog(1, f"serve: retired {tid} ({r.state}"
                 + (f", qmin {r.quality['qmin']}" if r.quality else "")
                 + f", {r.latency_s:.2f}s)",
              verbose=self.verbose, err=True)

    def _expire_timeouts(self) -> None:
        """Expire requests past PARMMG_SERVE_TIMEOUT_S.  Reclamation
        contract for a RUNNING tenant (regression-tested,
        tests/test_serve.py): ``pool.release`` must scrub the slot row
        back to the dead-mesh state AND return the slot to the bucket's
        free list, so the next queued tenant can rent it — a timed-out
        tenant must never strand capacity."""
        if not self.timeout_s:
            return
        now = time.perf_counter()
        for tid, r in self.requests.items():
            if r.state == RUNNING and now - r.t_submit > self.timeout_s:
                slot = self.pool.slot_of(tid)
                r.stats = slot.stats
                r.state = TIMEOUT
                r.reason = f"exceeded {self.timeout_s}s"
                r.t_done = now
                self.pool.release(tid)
            elif r.state == QUEUED and now - r.t_submit > self.timeout_s:
                r.state = TIMEOUT
                r.reason = f"queued past {self.timeout_s}s"
                r.t_done = now
                self.queue = [t for t in self.queue if t != tid]

    def run(self, max_steps: int = 10000) -> dict:
        """Drive the loop until every request reaches a terminal state.
        Returns the serving report (per-tenant + pool aggregates)."""
        occupancy_traj = []
        for _ in range(max_steps):
            self._expire_timeouts()
            self._admit_from_queue()
            if not self.pool.active_tenants():
                if self.queue:
                    # queued work but nothing admitted: deadlocked on
                    # capacity (e.g. max_inflight 0 slots) — bail out
                    # rather than spin
                    for tid in self.queue:
                        r = self.requests[tid]
                        r.state = REJECTED
                        r.reason = "pool cannot admit (no slot ever)"
                        r.t_done = time.perf_counter()
                    self.queue = []
                break
            occupancy_traj.append(self.pool.occupancy())
            for tid in self.pool.step(verbose=self.verbose):
                self._retire(tid)
        return self.report(occupancy_traj)

    # ---- reporting ----------------------------------------------------------
    def report(self, occupancy_traj=None) -> dict:
        from ..ops.adapt import AdaptStats
        agg = AdaptStats()
        tenants = {}
        for tid, r in sorted(self.requests.items()):
            if r.stats is not None:
                agg += r.stats          # namespaced per tenant
            tenants[tid] = {
                "state": r.state,
                "reason": r.reason,
                "latency_s": round(r.latency_s, 3),
                "quality": r.quality,
                "slo": r.slo,
                "cycles": r.stats.cycles if r.stats else 0,
                "ops": ([r.stats.nsplit, r.stats.ncollapse,
                         r.stats.nswap, r.stats.nmoved]
                        if r.stats else [0, 0, 0, 0]),
                "out_files": r.out_files,
            }
        lat = sorted(t["latency_s"] for t in tenants.values()
                     if t["state"] == DONE)

        def pct(p):
            # nearest-rank percentile, integer ceil: rank(p) =
            # ceil(p*n) (int(p*n) would hand p90-of-10 the maximum;
            # float ceil mis-rounds 0.9*10)
            if not lat:
                return 0.0
            rank = (int(p * 100) * len(lat) + 99) // 100
            return round(lat[min(len(lat), max(rank, 1)) - 1], 3)

        return {
            "tenants": tenants,
            "served": sum(1 for t in tenants.values()
                          if t["state"] == DONE),
            "rejected": sum(1 for t in tenants.values()
                            if t["state"] == REJECTED),
            "failed": sum(1 for t in tenants.values()
                          if t["state"] in (FAILED, TIMEOUT)),
            "latency_p50_s": pct(0.50),
            "latency_p90_s": pct(0.90),
            "latency_max_s": lat[-1] if lat else 0.0,
            "pool": {
                "steps": self.pool.steps,
                "dispatches": self.pool.dispatches,
                "chunk": self.pool.chunk,
                "slots_per_bucket": self.pool.slots_per_bucket,
                # fault-isolation state (resilience ladder, serving
                # form): tenants retired FAILED after
                # PARMMG_SERVE_MAX_RETRIES slot faults
                "quarantined": list(self.pool.quarantined),
                "max_slot_retries": self.pool.max_slot_retries,
                "buckets": self.pool.occupancy(),
                "active_per_step": list(self.pool.active_per_step),
                "chunk_recommendation": self.pool.chunk_recommendation(),
                "pipeline_s": {k: round(v, 3)
                               for k, v in self.pool.timers.acc.items()},
            },
            "occupancy_traj": occupancy_traj or [],
            "agg_sched_extra": {k: v for k, v in agg.sched_extra.items()},
        }
