"""Admission control: staging, queue pump, backpressure, STREAMING
slot re-rent for the serving driver (ROADMAP item 3b).

PR 6's driver admitted queued tenants only BETWEEN pool steps: a slot
freed by a tenant retiring mid-cohort sat dead until the whole step
drained.  This module factors the admission path out of ``ServeDriver``
and adds the two service-grade behaviours the daemon needs:

- **streaming admission** (``PARMMG_SERVE_STREAM``, default on): the
  pool's step loop reports each cohort's retirements AS THEY COMMIT
  (``SlotPool.step(on_retire=...)``) and :meth:`AdmissionController.
  mid_step` retires them and re-rents the freed slots to queued tenants
  while the step is still in flight — the quiet-group fixed point
  already proved which cohort slots retired, so the re-rented slot
  rides the step's remaining re-scan at its own cycle 0.  Exactness:
  a tenant's block sequence is a function of its own cycle index alone
  (``groups.block_schedule``) and ``lax.map`` rows are independent, so
  admission TIMING never changes a tenant's bytes — bit-for-bit
  per-tenant parity with the between-steps path is pinned by the slow
  test in tests/test_serve_daemon.py;
- **backpressure** (``PARMMG_SERVE_MAX_QUEUE`` + the autoscale
  controller's defer latch): :meth:`backpressure` gives
  ``ServeDriver.try_submit`` a 429-style deferral reason instead of
  letting the queue grow without bound; the daemon maps it to
  HTTP 429 so clients retry instead of piling on.

Staging (file -> Mesh, raw arrays -> Mesh) lives here too: the daemon's
RPC edge and the queue pump share ONE staging rule, which is what makes
daemon-served results bit-identical to standalone runs and to the
in-process pool (gated by ledger_check.serving_gate / serve_check.py).
"""
from __future__ import annotations

import numpy as np

from .pool import _env_int

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
REJECTED = "rejected"
FAILED = "failed"
TIMEOUT = "timeout"

TERMINAL = (DONE, REJECTED, FAILED, TIMEOUT)


# ---------------------------------------------------------------------------
# staging: one rule for files, raw arrays, and the daemon RPC edge
# ---------------------------------------------------------------------------
def _pad_met(mesh, vals):
    """Metric values (scalar or tensor, any length <= capP) -> the
    staged full-capP metric with unit pads, in the mesh dtype.  THE
    one padding rule both staging paths share — bit parity between
    daemon-staged and standalone runs rides on it."""
    import jax.numpy as jnp
    vals = np.asarray(vals)
    full = np.ones((mesh.capP,) + vals.shape[1:], np.float64)
    full[: len(vals)] = vals
    if full.ndim == 2 and full.shape[1] == 1:
        full = full[:, 0]
    return jnp.asarray(full, mesh.vert.dtype)


def stage_file(path: str, sol: str | None):
    """File -> (core Mesh, met): medit or VTK in, analysis tags on,
    metric from the .sol (scalar/tensor) or the -optim default."""
    from ..core.mesh import make_mesh
    from ..io.medit import read_mesh, read_sol
    from ..ops.analysis import analyze_mesh
    from ..ops.metric import metric_optim

    vtu_met = None
    if str(path).endswith(".vtu"):
        from ..io.vtk import read_vtu_medit
        mm, vtu_met, _fields = read_vtu_medit(path)
    else:
        mm = read_mesh(path)
    mesh = make_mesh(mm.vert, mm.tetra, vref=mm.vref, tref=mm.tref)
    mesh = analyze_mesh(mesh).mesh
    vals = None
    if sol:
        vals, _types = read_sol(sol)
    elif vtu_met is not None:
        vals = np.asarray(vtu_met)
    if vals is not None:
        met = _pad_met(mesh, vals)
    else:
        met = metric_optim(mesh)
    return mesh, met


def stage_arrays(vert, tet, vref=None, tref=None, met=None):
    """Raw arrays -> staged (core Mesh, met): the daemon RPC staging
    rule, shared with the gates' standalone references so daemon-served
    parity holds by construction.  Caps use the serve-bench 4x headroom
    (``make_mesh(capP=4*nvert, capT=4*ntet)``), analysis tags are
    computed, and the metric (scalar or tensor, any length <= capP) is
    padded to capP with unit entries; an absent metric falls back to
    ``metric_optim`` like the file path."""
    from ..core.mesh import make_mesh
    from ..ops.analysis import analyze_mesh
    from ..ops.metric import metric_optim

    vert = np.asarray(vert, np.float64)
    tet = np.asarray(tet, np.int32)
    mesh = make_mesh(vert, tet, vref=vref, tref=tref,
                     capP=4 * len(vert), capT=4 * len(tet))
    mesh = analyze_mesh(mesh).mesh
    if met is None:
        return mesh, metric_optim(mesh)
    return mesh, _pad_met(mesh, met)


def mesh_size(mesh) -> tuple[int, int]:
    """(tet-referenced vertex count, live tet count) — the admission
    sizing rule (``split_to_shards`` sizes capP from TET-REFERENCED
    vertices, not vmask).  Accepts a staged core Mesh or a plain dict
    of arrays (the stub pools of the host-only tier-1 tests)."""
    if isinstance(mesh, dict):
        tet = np.asarray(mesh["tet"])
        return len(np.unique(tet)), len(tet)
    tm = np.asarray(mesh.tmask)
    nt = int(tm.sum())
    nv = len(np.unique(np.asarray(mesh.tet)[tm]))
    return nv, nt


def met_width(met) -> int:
    """Metric trailing width (0 = scalar) — the bucket-key component."""
    if met is None:
        return 0
    a = np.asarray(met)
    return 0 if a.ndim == 1 else int(a.shape[-1])


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------
class AdmissionController:
    """Queue pump + backpressure + the streaming mid-step hook.

    Owns no queue of its own — the queue and request table stay on the
    driver (report/bench compatibility); this object owns the POLICY:
    streaming on/off (``PARMMG_SERVE_STREAM``), the submit-time queue
    bound (``PARMMG_SERVE_MAX_QUEUE``), and the autoscale controller's
    defer latch (``deferring``, set by
    ``autoscale.AutoscaleController.tick``).  Everything here is pure
    host bookkeeping (tier-1 tested with stub pools, no jax)."""

    def __init__(self, driver, max_queue: int | None = None,
                 stream: bool | None = None):
        self.driver = driver
        self.max_queue = max_queue if max_queue is not None \
            else _env_int("PARMMG_SERVE_MAX_QUEUE", 0)
        if stream is None:
            import os
            stream = os.environ.get("PARMMG_SERVE_STREAM", "1") != "0"
        self.stream = bool(stream)
        self.deferring = False          # autoscale backpressure latch
        self.stream_admissions = 0
        self.deferred = 0

    # ---- backpressure (429-style deferral) -------------------------------
    def backpressure(self) -> str | None:
        """Deferral reason for a NEW submit, or None to accept.  Never
        affects already-queued requests — only the admission edge."""
        if self.deferring:
            return "autoscale backpressure (deferring admissions)"
        if self.max_queue and len(self.driver.queue) >= self.max_queue:
            return (f"queue full ({len(self.driver.queue)} >= "
                    f"PARMMG_SERVE_MAX_QUEUE {self.max_queue})")
        return None

    # ---- the queue pump ---------------------------------------------------
    def pump(self) -> list[str]:
        """Admit queued requests into free slots (between steps, or —
        via :meth:`mid_step` — while a step is in flight).  Staging
        failures and oversize requests retire per-request (fault
        isolation); "full" requests stay queued and publish the
        per-bucket blocked-admission gauge the autoscale controller
        grows on.  Returns the newly admitted tenant ids."""
        import time

        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        d = self.driver
        pool = d.pool
        admitted: list[str] = []
        inflight = len(pool.active_tenants())
        remaining: list[str] = []
        blocked: dict[str, int] = {}
        for tid in d.queue:
            r = d.requests[tid]
            if d.max_inflight and inflight >= d.max_inflight:
                remaining.append(tid)
                continue
            try:
                if r.mesh is None and r.path is not None:
                    r.mesh, r.met = stage_file(r.path, r.sol)
                nv, nt = mesh_size(r.mesh)
                mw = met_width(r.met)
            except Exception as e:
                # per-request fault isolation: a corrupt input must not
                # take down the loop or the other tenants
                r.state = FAILED
                r.reason = f"staging failed: {e}"
                r.t_done = time.perf_counter()
                continue
            got = pool.admit(tid, nv, nt, met_width=mw)
            if got[0] == "oversize":
                r.state = REJECTED
                r.reason = (f"needs caps {got[1][0]}x{got[1][1]} > pool "
                            f"max {pool.max_capP}x{pool.max_capT}")
                r.t_done = time.perf_counter()
                continue
            if got[0] == "full":
                remaining.append(tid)       # waits for a recycled slot
                label = pool.bucket_label(got[1])
                blocked[label] = blocked.get(label, 0) + 1
                continue
            try:
                pool.load(tid, r.mesh, r.met)
            except Exception as e:
                pool.release(tid)           # fault isolation (as above)
                r.state = FAILED
                r.reason = f"load failed: {e}"
                r.t_done = time.perf_counter()
                continue
            r.state = RUNNING
            r.t_admit = time.perf_counter()
            inflight += 1
            admitted.append(tid)
            # stderr: stdout belongs to the front-ends' JSON report
            otrace.log(1, f"serve: admitted {tid} -> bucket "
                          f"{got[1][0]}x{got[1][1]} slot {got[2]}",
                       verbose=d.verbose, err=True)
        d.queue = remaining
        REGISTRY.gauge("serve.queue_depth").set(len(d.queue))
        # full-bucket admission pressure: the autoscale grow signal,
        # cleared for buckets that stopped blocking this pump
        for label in pool.labels():
            # lint: ok(R6) — label ranges over the finite capacity
            # ladder (same cardinality bound as serve.occupancy.*)
            REGISTRY.gauge(f"serve.admit_blocked.{label}").set(
                blocked.get(label, 0))
        return admitted

    # ---- streaming admission (the SlotPool.step on_retire hook) ----------
    def mid_step(self, retired: list[str]) -> None:
        """Retire a cohort's finished tenants NOW and re-rent their
        freed slots from the queue, while the pool step is still in
        flight.  The pool's re-scan picks the re-rented slots up at
        their own cycle 0 within the same step."""
        d = self.driver
        for tid in retired:
            if d.requests[tid].state == RUNNING:
                d._retire(tid)
        if not d.queue:
            return
        got = self.pump()
        if got:
            from ..obs import trace as otrace
            from ..obs.metrics import REGISTRY
            self.stream_admissions += len(got)
            REGISTRY.counter("serve.stream_admissions").inc(len(got))
            otrace.event("serve.stream_admit", tenants=len(got))

    def summary(self) -> dict:
        return {"stream": self.stream, "max_queue": self.max_queue,
                "deferring": self.deferring,
                "stream_admissions": self.stream_admissions,
                "deferred": self.deferred}
