"""Slot pool + admission: bucketed group slots for multi-tenant serving.

A *slot* is one group position of a host-resident stacked mesh state —
exactly the unit ``parallel.groups.grouped_adapt_pass`` dispatches in
chunk mode — except the slots of one bucket hold INDEPENDENT tenant
meshes instead of slices of one mesh.  Buckets are rungs of the
capacity ladder ``utils.compilecache.bucket(cap_mult * n, floor=64,
scheme="geo")`` — the SAME formula ``parallel.distribute
.split_to_shards`` uses for group capacities, so a tenant admitted into
its home bucket runs at byte-identical static shapes to the standalone
``grouped_adapt_pass(ngroups=1)`` path: same cached ``_group_block``
program, same wave indices, same top-K budgets.  That is the whole
compile story of serving: after one warmup per bucket (which any batch
user pays anyway), every request is served by already-compiled
programs — zero new ``groups.*`` compile-ledger families (gated by
``scripts/run_tests.sh --ledger`` / ``ledger_check.serving_gate``).

Scheduling: per step, active (admitted, unconverged) slots of each
bucket are cohorted by cycle index — slots in the same cohort share
``(flags, pres, wave)`` and are compacted into dense ``[chunk, ...]``
dispatches with ``parallel.sched.chunk_plans``, ridden through the
double-buffered ``groups._pipeline_chunks`` pipeline.  A tenant
retires at its own fixed point (``groups.block_converged`` — the
per-tenant form of the batch loop's early exit, which at one group per
tenant is exactly the standalone rule) and frees its slot for the next
queued request: the quiet-group scheduler's skip (parallel/sched.py)
becomes slot recycling.  Free/pad slots are born quiet (all-zero dead
meshes, ``groups._pad_groups`` convention) and are never dispatched.

Capacity overflow mirrors the batch regrow: the overflowed post-run
state is promoted to a ``(2*capP, 2*capT)`` bucket and the SAME block
re-runs (the batch path's ``on_regrow`` + block-rerun semantics, at
tenant granularity).

The admission state machine (admit / full / oversize, slot recycling)
is pure host bookkeeping — tests drive it without touching XLA; array
storage is allocated lazily on the first ``load``.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..utils.compilecache import bucket

BUCKET_FLOOR = 64          # split_to_shards' geo-ladder floor


def _env_int(name: str, default: int) -> int:
    import os
    v = os.environ.get(name, "")
    return int(v) if v else default


@dataclasses.dataclass
class Slot:
    """One bucketed group slot: bookkeeping for the tenant renting it."""
    tenant: str | None = None
    c: int = 0                 # cycle index (block boundary)
    converged: bool = False
    failed: str = ""           # non-empty = terminal failure reason
    regrows: int = 0
    loaded: bool = False
    stats: object = None       # AdaptStats(tenant=...)
    faults: int = 0            # dispatch faults (quarantine ladder)


class Bucket:
    """One capacity rung: ``nslots`` group slots at (capP, capT).

    ``stacked``/``met`` are host numpy trees [nslots, ...] in the
    chunk-mode layout of grouped_adapt_pass (allocated on first load);
    free slots stay all-zero = dead meshes (born quiet)."""

    def __init__(self, capP: int, capT: int, nslots: int):
        self.capP = capP
        self.capT = capT
        self.nslots = nslots
        self.slots = [Slot() for _ in range(nslots)]
        self.stacked = None
        self.met = None
        # per-slot incremental-topology state (ops/topo_incr.TopoState,
        # host numpy [nslots, ...]); lazily allocated at first dispatch.
        # All-zero rows = ok=False = full rebuild on first derivation,
        # so slot recycling resets topo exactly like mesh state
        self.topo = None

    def free_slot(self) -> int | None:
        for i, s in enumerate(self.slots):
            if s.tenant is None:
                return i
        return None

    def occupancy(self) -> tuple[int, int]:
        return (sum(1 for s in self.slots if s.tenant is not None),
                self.nslots)


class SlotPool:
    """Bucketed slot pool: admission + chunked multi-tenant dispatch.

    Knobs (constructor arg wins over env): ``slots_per_bucket``
    (PARMMG_SERVE_SLOTS, default 4), ``chunk`` groups/dispatch
    (PARMMG_SERVE_CHUNK, default 1 — every dispatch reuses the
    standalone ``[1, ...]`` program; larger chunks pack tenants
    per dispatch at the cost of one ``[chunk, ...]`` warmup),
    ``max_capT``/``max_capP`` admission ceilings
    (PARMMG_SERVE_MAX_CAPT / _CAPP, default 1<<22 — *oversize*
    rejection), ``cap_mult`` growth headroom (the split_to_shards
    default 3.0), and the remesh parameters shared by every tenant of
    the pool (one kernel, many meshes — the paper's model)."""

    def __init__(self, slots_per_bucket: int | None = None,
                 chunk: int | None = None, cap_mult: float = 3.0,
                 max_capP: int | None = None, max_capT: int | None = None,
                 cycles: int = 6, noinsert: bool = False,
                 noswap: bool = False, nomove: bool = False,
                 hausd: float | None = None,
                 max_slot_retries: int | None = None):
        self.slots_per_bucket = slots_per_bucket if slots_per_bucket \
            else _env_int("PARMMG_SERVE_SLOTS", 4)
        # fault-isolation budget (PARMMG_SERVE_MAX_RETRIES): a tenant
        # whose slot dispatch faults this many times is quarantined —
        # retired FAILED, slot scrubbed and recycled — never aborting
        # cohort-mates sharing the chunk
        self.max_slot_retries = max(1, max_slot_retries
                                    if max_slot_retries is not None
                                    else _env_int(
                                        "PARMMG_SERVE_MAX_RETRIES", 2))
        self.quarantined: list[str] = []
        self.chunk = max(1, chunk if chunk
                         else _env_int("PARMMG_SERVE_CHUNK", 1))
        self.cap_mult = float(cap_mult)
        self.max_capP = max_capP if max_capP \
            else _env_int("PARMMG_SERVE_MAX_CAPP", 1 << 22)
        self.max_capT = max_capT if max_capT \
            else _env_int("PARMMG_SERVE_MAX_CAPT", 1 << 22)
        self.cycles = int(cycles)
        self.noinsert = noinsert
        self.noswap = noswap
        self.nomove = nomove
        self.hausd = hausd
        self.buckets: dict[tuple, Bucket] = {}
        self._where: dict[str, tuple] = {}      # tenant -> (key, slot)
        self.dispatches = 0
        self.steps = 0
        # active-slot trajectory per (step, bucket) — the serving-side
        # analogue of extra.active_groups_per_block, feeding the same
        # chunk auto-tune cost model
        self.active_per_step: list[int] = []
        # pipeline segment timers (upload/compute/download/writeback),
        # folded across every dispatch of the pool's lifetime
        from ..utils.timers import Timers
        self.timers = Timers()

    # ---- admission state machine (pure host bookkeeping) -----------------
    def home_caps(self, n_vert: int, n_tet: int) -> tuple[int, int]:
        """Smallest ladder rung fitting a tenant of ``n_tet`` live tets
        referencing ``n_vert`` vertices — the exact capacities
        split_to_shards computes for a one-part split (its maxP counts
        TET-REFERENCED vertices, not vmask: callers must pass that, or
        an orphan vertex inflates the bucket past the rung the split
        produces and load() rejects the mismatch)."""
        return (bucket(int(self.cap_mult * n_vert), floor=BUCKET_FLOOR,
                       scheme="geo"),
                bucket(int(self.cap_mult * n_tet), floor=BUCKET_FLOOR,
                       scheme="geo"))

    def admit(self, tenant: str, n_vert: int, n_tet: int,
              met_width: int = 0):
        """Try to admit a tenant: ("ok", key, slot) | ("full", key) |
        ("oversize", caps).  "full" tenants stay queued at the caller
        (the driver) until a converged tenant recycles its slot."""
        from ..obs.metrics import REGISTRY
        if tenant in self._where:
            raise ValueError(f"tenant {tenant!r} already admitted")
        capP, capT = self.home_caps(n_vert, n_tet)
        if capP > self.max_capP or capT > self.max_capT:
            REGISTRY.counter("serve.admit_oversize").inc()
            return ("oversize", (capP, capT))
        key = (capP, capT, int(met_width))
        b = self.buckets.get(key)
        if b is None:
            b = self.buckets[key] = Bucket(capP, capT,
                                           self.slots_per_bucket)
        i = b.free_slot()
        if i is None:
            REGISTRY.counter("serve.admit_full").inc()
            return ("full", key)
        from ..ops.adapt import AdaptStats
        b.slots[i] = Slot(tenant=tenant, stats=AdaptStats(tenant=tenant))
        self._where[tenant] = (key, i)
        REGISTRY.counter("serve.admit_ok").inc()
        return ("ok", key, i)

    @staticmethod
    def _zero_row(b: Bucket, i: int) -> None:
        """Reset a slot row to the dead-mesh state (all-zero — the
        _pad_groups pad-group convention: born quiet)."""
        if b.stacked is not None:
            import jax

            def z(a):
                a[i] = 0            # broadcasts over the row
                return a
            jax.tree.map(z, b.stacked)
            b.met[i] = 0
        if b.topo is not None:
            import jax
            jax.tree.map(lambda a: a.__setitem__(i, 0), b.topo)

    def release(self, tenant: str) -> None:
        """Free a tenant's slot (slot recycling): the row is zeroed
        back to a dead mesh — born quiet for the next renter."""
        key, i = self._where.pop(tenant)
        b = self.buckets[key]
        if b.slots[i].loaded:
            self._zero_row(b, i)
        b.slots[i] = Slot()

    @staticmethod
    def bucket_label(key: tuple) -> str:
        """Report/metric spelling of a bucket key: ``capPxcapT`` plus a
        metric-width suffix keeping scalar- and tensor-metric buckets
        of equal caps from colliding on one report/gauge key."""
        return f"{key[0]}x{key[1]}" + (f"m{key[2]}" if key[2] else "")

    def labels(self) -> dict:
        """{report label: bucket key} — the autoscale actuator's map
        from metric-series bucket names back to pool buckets."""
        return {self.bucket_label(k): k for k in self.buckets}

    def occupancy(self) -> dict:
        return {self.bucket_label(k): b.occupancy()
                for k, b in sorted(self.buckets.items())}

    def resize_bucket(self, key: tuple, nslots: int) -> int:
        """Autoscale actuator: grow/shrink one bucket's slot count.

        Growth appends born-quiet dead rows (all-zero, the _pad_groups
        convention) — compiled shapes are untouched because dispatches
        gather ``[chunk, ...]`` row slices, never the whole
        ``[nslots, ...]`` array, so resizing adds zero compile
        families.  Shrink drops TRAILING FREE slots only (never evicts
        or renumbers a tenant: ``_where`` holds live slot indices), so
        the result may stay larger than requested.  Returns the actual
        new slot count."""
        b = self.buckets[key]
        want = max(1, int(nslots))
        if want > b.nslots:
            add = want - b.nslots
            b.slots.extend(Slot() for _ in range(add))
            if b.stacked is not None:
                import jax
                b.stacked = jax.tree.map(
                    lambda a: np.concatenate(
                        [a, np.zeros((add,) + a.shape[1:], a.dtype)]),
                    b.stacked)
                b.met = np.concatenate(
                    [b.met, np.zeros((add,) + b.met.shape[1:],
                                     b.met.dtype)])
            if b.topo is not None:
                import jax
                b.topo = jax.tree.map(
                    lambda a: np.concatenate(
                        [a, np.zeros((add,) + a.shape[1:], a.dtype)]),
                    b.topo)
            b.nslots = want
        elif want < b.nslots:
            keep = b.nslots
            while keep > want and b.slots[keep - 1].tenant is None:
                keep -= 1
            if keep < b.nslots:
                b.slots = b.slots[:keep]
                if b.stacked is not None:
                    import jax
                    b.stacked = jax.tree.map(
                        lambda a: np.ascontiguousarray(a[:keep]),
                        b.stacked)
                    b.met = np.ascontiguousarray(b.met[:keep])
                if b.topo is not None:
                    import jax
                    b.topo = jax.tree.map(
                        lambda a: np.ascontiguousarray(a[:keep]),
                        b.topo)
                b.nslots = keep
        return b.nslots

    def active_tenants(self) -> list[str]:
        return [t for t, (k, i) in self._where.items()
                if self.buckets[k].slots[i].loaded
                and not self.buckets[k].slots[i].converged
                and not self.buckets[k].slots[i].failed]

    def slot_of(self, tenant: str) -> Slot:
        key, i = self._where[tenant]
        return self.buckets[key].slots[i]

    # ---- mesh attach / detach --------------------------------------------
    def load(self, tenant: str, mesh, met) -> None:
        """Split the tenant mesh into its slot (one-part
        split_to_shards, staged on the CPU backend exactly like the
        chunked grouped path) and write the row into the bucket's host
        state."""
        import jax
        from ..parallel.distribute import split_to_shards

        key, i = self._where[tenant]
        b = self.buckets[key]
        ntet = int(np.asarray(mesh.tmask).sum())
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            stacked1, met1 = split_to_shards(
                mesh, met, np.zeros(ntet, np.int32), 1,
                cap_mult=self.cap_mult)
        if stacked1.vert.shape[1] != b.capP or \
                stacked1.tet.shape[1] != b.capT:
            raise ValueError(
                f"tenant {tenant!r} split caps "
                f"{stacked1.vert.shape[1]}x{stacked1.tet.shape[1]} != "
                f"admitted bucket {b.capP}x{b.capT}")
        if b.stacked is None:
            # allocate the bucket's host state from the first tenant's
            # split as a template; free rows all-zero = dead meshes
            b.stacked = jax.tree.map(
                lambda a: np.zeros((b.nslots,) + a.shape[1:], a.dtype),
                stacked1)
            b.met = np.zeros((b.nslots,) + met1.shape[1:], met1.dtype)
        from ..core.mesh import MESH_FIELDS
        for f in MESH_FIELDS:
            getattr(b.stacked, f)[i] = np.asarray(getattr(stacked1, f)[0])
        b.met[i] = np.asarray(met1[0])
        if b.topo is not None:
            # stale retained-table state must not leak across tenants:
            # zero = ok=False = full rebuild at the first derivation
            jax.tree.map(lambda a: a.__setitem__(i, 0), b.topo)
        b.slots[i].loaded = True

    def slot_state(self, tenant: str):
        """(bucket, slot index) — the raw stacked row accessors for the
        merge-free writers (driver.write_distributed)."""
        key, i = self._where[tenant]
        return self.buckets[key], i

    def merge(self, tenant: str):
        """Merge the tenant's single-slot state back to one Mesh + met
        (the same merge_shards call grouped_adapt_pass makes, staged on
        the CPU backend)."""
        import jax
        import jax.numpy as jnp
        from ..parallel.distribute import merge_shards

        b, i = self.slot_state(tenant)
        one = jax.tree.map(lambda a: jnp.asarray(a[i:i + 1]), b.stacked)
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            return merge_shards(one, jnp.asarray(b.met[i:i + 1]))

    # ---- fault isolation (resilience ladder, serving form) ----------------
    def _note_slot_fault(self, s: Slot, exc) -> bool:
        """Account one slot-dispatch fault.  Returns True when the
        tenant just crossed ``max_slot_retries`` and is quarantined:
        terminal FAILED, slot scrubbed + recycled at retirement.
        Below the threshold the slot simply stays at its cycle index
        and is re-dispatched next step — its state is untouched
        (writeback only happens on a successful drain), so the retry
        is exact."""
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        s.faults += 1
        REGISTRY.counter("serve.slot_faults").inc()
        if s.tenant is not None:
            REGISTRY.counter("serve.slot_faults", tenant=s.tenant).inc()
        if s.faults >= self.max_slot_retries:
            s.failed = (f"quarantined after {s.faults} slot fault(s): "
                        + repr(exc)[:200])
            self.quarantined.append(s.tenant)
            REGISTRY.counter("serve.quarantined").inc()
            otrace.event("serve.quarantine", tenant=s.tenant,
                         faults=s.faults, detail=repr(exc)[:300])
            otrace.log(1, f"serve: QUARANTINED {s.tenant} after "
                          f"{s.faults} slot fault(s)", err=True)
            return True
        otrace.event("serve.slot_fault", tenant=s.tenant,
                     faults=s.faults, detail=repr(exc)[:300])
        return False

    def _dispatch_cohort(self, b: Bucket, fn, wave, ids, done) -> list:
        """Dispatch one cohort with per-tenant fault isolation.

        Fast path: one compacted multi-slot dispatch (the packed
        serving path).  If it faults (a poisoned tenant's dispatch —
        injectable via ``PARMMG_FAULT=serve.slot_step;key=<tenant>``),
        fall back to per-slot dispatches so cohort-mates are never
        aborted by the faulting tenant: a single-slot plan pads to the
        SAME compiled ``[chunk, ...]`` program (``chunk_plans``) and
        ``lax.map`` rows are independent, so the mates' results stay
        bit-identical to the packed dispatch.  Plans whose drain
        already COMMITTED during the fast path (the ``done`` contract
        of ``_pipeline_chunks``) keep their results — their slots
        advanced, and re-dispatching them would apply the cycle wave
        twice.  Returns [(slot index, counts row [nblk, >=8; 9 with
        the topo-threaded block: col 8 = dirty-tet count])] for
        slots that ran; faulting slots are accounted via
        :meth:`_note_slot_fault` (retried next step, or quarantined
        into ``done``)."""
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        import jax.numpy as jnp
        from ..parallel.groups import _pipeline_chunks
        from ..parallel.sched import cadence_enabled, chunk_plans
        from ..resilience.faults import FAULTS, faultpoint
        from ..ops.topo_incr import incr_topo_enabled, topo_init_np
        plans = chunk_plans(np.asarray(ids), self.chunk)
        # smoothing-cadence + incremental-topology enables ride along as
        # traced scalars (the hotloop_knob_gate contract): same compiled
        # programs either way
        cad = jnp.asarray(cadence_enabled())
        inc = jnp.asarray(incr_topo_enabled())
        if b.topo is None:
            b.topo = topo_init_np(b.nslots, b.capT)
        committed: dict = {}
        try:
            if FAULTS.armed():
                for i in ids:
                    faultpoint("serve.slot_step", key=b.slots[i].tenant)
            parts = _pipeline_chunks(fn, b.stacked, b.met, wave, plans,
                                     self.timers, done=committed,
                                     extra=(cad, inc), topo=b.topo)
            self.dispatches += len(plans)
            REGISTRY.counter("serve.dispatches").inc(len(plans))
            return list(zip(ids, np.concatenate(parts)))
        except Exception as e:
            REGISTRY.counter("resilience.serve_cohort_faults").inc()
            otrace.event("serve.cohort_fault", detail=repr(e)[:300])
        out = []
        for pi, (idx, nreal) in enumerate(plans):
            rows = [int(v) for v in idx[:nreal]]
            if pi in committed:
                # this plan's drain COMMITTED during the fast path (its
                # writeback advanced the slots): honor its results —
                # re-dispatching would apply the cycle wave twice
                self.dispatches += 1
                REGISTRY.counter("serve.dispatches").inc()
                out.extend(zip(rows, committed[pi]))
                continue
            for i in rows:
                s = b.slots[i]
                try:
                    faultpoint("serve.slot_step", key=s.tenant)
                    plans1 = chunk_plans(np.asarray([i]), self.chunk)
                    parts1 = _pipeline_chunks(fn, b.stacked, b.met,
                                              wave, plans1, self.timers,
                                              extra=(cad, inc),
                                              topo=b.topo)
                    self.dispatches += len(plans1)
                    REGISTRY.counter("serve.dispatches").inc(len(plans1))
                    out.append((i, np.concatenate(parts1)[0]))
                except Exception as e:
                    if self._note_slot_fault(s, e):
                        done.append(s.tenant)
        return out

    # ---- the serving step -------------------------------------------------
    def _grow_tenant(self, tenant: str) -> None:
        """Promote an overflowed tenant to the (2*capP, 2*capT) bucket
        (the batch regrow geometry: np.pad by the old capacity on the
        capacity axis, slot ids preserved) and re-rent a slot there.
        Raises MemoryError past the regrow limit — the caller marks the
        tenant failed, it does NOT kill the pool."""
        key, i = self._where[tenant]
        b = self.buckets[key]
        s = b.slots[i]
        if s.regrows >= 6:
            raise MemoryError(f"tenant {tenant!r}: slot capacity "
                              "exhausted after 6 regrows")
        capP, capT = b.capP, b.capT
        row = {f: np.asarray(getattr(b.stacked, f)[i])
               for f in ("vert", "vref", "vtag", "vmask", "tet", "tref",
                         "tmask", "adja", "ftag", "fref", "etag")}
        npoin = np.asarray(b.stacked.npoin[i])
        nelem = np.asarray(b.stacked.nelem[i])
        met_row = np.asarray(b.met[i])

        def padP(x, fill=0):
            pad = [(0, 0)] * x.ndim
            pad[0] = (0, capP)
            return np.pad(x, pad, constant_values=fill)

        def padT(x, fill=0):
            pad = [(0, 0)] * x.ndim
            pad[0] = (0, capT)
            return np.pad(x, pad, constant_values=fill)

        nkey = (2 * capP, 2 * capT, key[2])
        nb = self.buckets.get(nkey)
        if nb is None:
            nb = self.buckets[nkey] = Bucket(2 * capP, 2 * capT,
                                             self.slots_per_bucket)
        j = nb.free_slot()
        if j is None:
            # a full promotion bucket grows by one slot rather than
            # deadlocking the overflowed tenant (it already paid the
            # regrow; queueing it cannot make progress)
            self.resize_bucket(nkey, nb.nslots + 1)
            j = nb.nslots - 1
        if nb.stacked is None:
            import jax
            nb.stacked = jax.tree.map(
                lambda a: np.zeros(
                    (nb.nslots,) + ((2 * capP,) + a.shape[2:]
                                    if a.shape[1:2] == (capP,)
                                    else (2 * capT,) + a.shape[2:]
                                    if a.shape[1:2] == (capT,)
                                    else a.shape[1:]), a.dtype),
                b.stacked)
            nb.met = np.zeros((nb.nslots, 2 * capP) + b.met.shape[2:],
                              b.met.dtype)
        for f, fill in (("vert", 0), ("vref", 0), ("vtag", 0),
                        ("vmask", False)):
            getattr(nb.stacked, f)[j] = padP(row[f], fill)
        for f, fill in (("tet", 0), ("tref", 0), ("tmask", False),
                        ("adja", -1), ("ftag", 0), ("fref", 0),
                        ("etag", 0)):
            getattr(nb.stacked, f)[j] = padT(row[f], fill)
        nb.stacked.npoin[j] = npoin
        nb.stacked.nelem[j] = nelem
        nb.met[j] = padP(met_row)
        if nb.topo is not None:
            # retained tables do not transfer across capacity rungs
            # (band/table widths are capT-static): reset to full-rebuild
            import jax
            jax.tree.map(lambda a: a.__setitem__(j, 0), nb.topo)
        # hand the slot over: bookkeeping moves, old slot recycles
        nb.slots[j] = dataclasses.replace(s, regrows=s.regrows + 1)
        self._zero_row(b, i)
        b.slots[i] = Slot()
        self._where[tenant] = (nkey, j)
        if s.stats is not None:
            s.stats.regrows += 1

    def step(self, verbose: int = 0, on_retire=None) -> list[str]:
        """Advance every active tenant by one cycle block.  Returns the
        tenants that reached a terminal state (converged/failed) this
        step.

        Slots of one bucket at the same cycle index share (flags, pres,
        wave) and ride compacted [chunk, ...] dispatches of the SAME
        cached compiled programs the batch grouped path uses.

        ``on_retire`` (streaming admission, serve/admission.py): when
        given, it is called with each cohort's newly-retired tenants AS
        THEY RETIRE, while the step is still in flight.  The callback
        may release slots and admit+load queued tenants into them; the
        step then RE-SCANS for tenants it has not yet dispatched this
        step and picks the re-rented slots up at their own cycle 0 — a
        freed slot is re-rented without waiting for the cohort (or the
        step) to drain.  Each TENANT dispatches at most once per step
        (a regrown tenant re-runs its block next step, either mode), so
        existing tenants advance exactly one block either way.
        Per-tenant parity with the between-steps path is exact: a
        tenant's block sequence is a function of its own cycle index
        alone (groups.block_schedule) and ``lax.map`` rows are
        independent, so WHEN a tenant is admitted never changes WHAT it
        computes (pinned by tests/test_serve_daemon.py)."""
        import jax.numpy as jnp
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        from ..ops.adapt import default_cycle_block
        from ..parallel.groups import (_group_block, block_converged,
                                       block_schedule)

        self.steps += 1
        done: list[str] = []
        block = default_cycle_block()
        stepped: set[str] = set()       # tenants dispatched this step
        while True:
            progressed = False
            # sorted() snapshots the key list: a regrow or a streaming
            # re-rent may add buckets mid-scan (picked up on re-scan)
            for key in sorted(self.buckets):
                b = self.buckets[key]
                occ, nslots = b.occupancy()
                label = self.bucket_label(key)
                # lint: ok(R6) — label is a capacity-ladder bucket (geo
                # ladder from bucket(), capped by PARMMG_SERVE_MAX_CAP*):
                # O(log cap) distinct series, not unbounded
                REGISTRY.gauge(f"serve.occupancy.{label}").set(occ)
                # lint: ok(R6) — same capacity-ladder cardinality bound
                REGISTRY.gauge(f"serve.slots.{label}").set(nslots)
                act = [(i, s) for i, s in enumerate(b.slots)
                       if s.tenant is not None and s.loaded
                       and not s.converged and not s.failed
                       and s.tenant not in stepped]
                if not act:
                    continue
                self.active_per_step.append(len(act))
                cohorts: dict[int, list[int]] = {}
                for i, s in act:
                    cohorts.setdefault(s.c, []).append(i)
                for c in sorted(cohorts):
                    ids = cohorts[c]
                    n_done0 = len(done)
                    nblk = min(block, self.cycles - c)
                    flags, pres = block_schedule(c, nblk, self.cycles,
                                                 self.noswap)
                    fn = _group_block(flags, pres, self.nomove,
                                      self.noinsert, self.hausd)
                    stepped.update(b.slots[i].tenant for i in ids)
                    progressed = True
                    rows = self._dispatch_cohort(
                        b, fn, jnp.asarray(c, jnp.int32), ids, done)
                    for i, crow in rows:
                        s = b.slots[i]
                        cs = crow.astype(np.int64)           # [nblk, 9]
                        st = s.stats
                        for ib in range(nblk):
                            st.nsplit += int(cs[ib][0])
                            st.ncollapse += int(cs[ib][1])
                            st.nswap += int(cs[ib][2])
                            st.nmoved += int(cs[ib][3])
                            st.cycles += 1
                        st.group_dispatches += 1
                        st.sched_extra.setdefault(
                            "ops_per_block", []).append(
                            int(cs[:, :4].sum()))
                        if int(cs[:, 4].max()) != 0:
                            # batch regrow semantics: promote the
                            # post-run state, re-run the SAME block
                            # next step
                            try:
                                self._grow_tenant(s.tenant)
                            except MemoryError as e:
                                s.failed = str(e)
                                done.append(s.tenant)
                            continue
                        s.c = c + nblk
                        if block_converged(cs, flags, self.noswap) \
                                or s.c >= self.cycles:
                            s.converged = True
                            done.append(s.tenant)
                    otrace.log(2, f"  serve step {self.steps} bucket "
                                  f"{key[0]}x{key[1]} c{c}: {len(ids)} "
                                  f"tenants, {len(rows)} dispatched",
                               verbose=verbose, err=True)
                    if on_retire is not None and len(done) > n_done0:
                        # mid-step retirement hook: slots freed by this
                        # cohort may be re-rented before the step ends
                        on_retire(done[n_done0:])
            if on_retire is None or not progressed:
                break
        return done

    def run_to_completion(self, max_steps: int = 1000) -> list[str]:
        """Drive step() until no tenant is active (pool-only loop; the
        request-queue front-end lives in serve/driver.py)."""
        out = []
        for _ in range(max_steps):
            if not self.active_tenants():
                break
            out.extend(self.step())
        return out

    def chunk_recommendation(self) -> int:
        """Trajectory-derived PARMMG_GROUP_CHUNK recommendation for the
        pool's dispatch loop (satellite of ROADMAP 1b): feed the
        active-slot counts per step into the same cost model the batch
        path logs."""
        from ..parallel.sched import recommend_group_chunk
        return recommend_group_chunk(self.active_per_step,
                                     self.slots_per_bucket)
