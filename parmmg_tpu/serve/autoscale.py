"""SLO-driven autoscaling + admission backpressure (ROADMAP item 3d).

The PR 8 metrics the pool already publishes — ``serve.queue_depth``,
``serve.occupancy.<bucket>`` / ``serve.slots.<bucket>``, the
``serve.latency_s`` histogram, ``serve.slo_violation`` counters, plus
the admission pump's ``serve.admit_blocked.<bucket>`` pressure gauges —
are a complete control signal.  This module closes the loop:

- :func:`decide` is the controller as a PURE FUNCTION of a metrics
  snapshot (no jax, no sockets, no clock — tier-1 tested directly):
  it returns bucket-ladder resize targets (grow a bucket whose fullness
  is blocking admissions, shrink an idle one) and the admission
  backpressure verdict (429-style deferral when the queue passes
  ``PARMMG_SERVE_MAX_QUEUE`` or observed p99 latency passes
  ``PARMMG_SERVE_TARGET_P99_S`` with work still queued; hysteresis
  releases at half the queue bound so the latch cannot flap);
- :class:`AutoscaleController` holds the little state a pure policy
  cannot (per-bucket idle streaks for shrink debounce, the defer
  latch) and ACTUATES decisions: ``SlotPool.resize_bucket`` for the
  ladder (compiled shapes untouched — dispatches gather [chunk, ...]
  slices, so resizing is compile-free) and the admission controller's
  ``deferring`` latch for backpressure.  Every decision is a
  ``serve.autoscale`` trace event plus ``serve.autoscale.*`` counters.

Quarantine composes unchanged: a quarantined tenant's slot is scrubbed
and recycled by the pool (PR 9), which this controller simply observes
as freed occupancy.  ``PARMMG_SERVE_AUTOSCALE=0`` disables the whole
loop (the driver then never constructs a controller).
"""
from __future__ import annotations

import dataclasses

from .pool import _env_int

__all__ = ["AutoscaleController", "Decision", "autoscale_enabled",
           "decide", "latency_quantile", "read_inputs"]


def _env_float(name: str, default: float) -> float:
    import os
    v = os.environ.get(name, "")
    return float(v) if v else default


def autoscale_enabled() -> bool:
    """PARMMG_SERVE_AUTOSCALE knob (default on)."""
    import os
    return os.environ.get("PARMMG_SERVE_AUTOSCALE", "1") != "0"


@dataclasses.dataclass(frozen=True)
class Decision:
    """One controller evaluation: bucket-label -> target slot count
    maps plus the admission backpressure verdict."""
    grow: dict
    shrink: dict
    defer: bool
    reasons: tuple = ()


def latency_quantile(hist: dict, q: float) -> float:
    """Approximate quantile from a snapshot histogram block
    (``{"buckets": {repr(le): cumulative}, "count": n}`` — the
    ``MetricsRegistry.snapshot()`` shape): the smallest bucket edge
    whose cumulative count covers ``q`` (the Prometheus
    histogram_quantile upper-edge convention; conservative, never
    under-reports)."""
    n = int(hist.get("count", 0) or 0)
    if n == 0:
        return 0.0
    target = q * n
    edges = sorted((float(le), int(c))
                   for le, c in (hist.get("buckets") or {}).items())
    for le, c in edges:
        if c >= target:
            return le
    return edges[-1][0] if edges else 0.0


def read_inputs(snapshot: dict, deferring: bool = False) -> dict:
    """Metrics snapshot (``MetricsRegistry.snapshot()``) -> controller
    inputs: queue depth, per-bucket occupancy/slots/blocked-admission
    pressure, observed p99 latency, and the aggregate SLO-violation
    count (summed across tenant-namespaced series)."""
    g = snapshot.get("gauges") or {}
    c = snapshot.get("counters") or {}
    h = snapshot.get("histograms") or {}
    occ: dict = {}
    slots: dict = {}
    blocked: dict = {}
    for k, v in g.items():
        if k.startswith("serve.occupancy."):
            occ[k[len("serve.occupancy."):]] = int(v)
        elif k.startswith("serve.slots."):
            slots[k[len("serve.slots."):]] = int(v)
        elif k.startswith("serve.admit_blocked."):
            blocked[k[len("serve.admit_blocked."):]] = int(v)
    return {
        "queue_depth": int(g.get("serve.queue_depth", 0)),
        "occupancy": occ, "slots": slots, "blocked": blocked,
        "p99_s": latency_quantile(h.get("serve.latency_s", {}), 0.99),
        "slo_violations": sum(
            v for k, v in c.items()
            if k.endswith("serve.slo_violation")),
        "deferring": bool(deferring),
    }


def decide(inputs: dict, *, max_slots: int = 16, min_slots: int = 1,
           max_queue: int = 0, target_p99_s: float = 0.0,
           idle_evals: dict | None = None,
           shrink_after: int = 3) -> Decision:
    """The pure policy.  ``inputs`` is :func:`read_inputs` output;
    ``idle_evals`` the per-bucket consecutive-idle-evaluation streaks
    the stateful controller tracks (shrink debounce).

    Rules:
    - GROW a bucket by one slot (up to ``max_slots``) when its fullness
      blocked at least one admission this pump and every slot is rented
      — targeted by the actual queued demand, not a guess;
    - SHRINK an idle bucket by one slot (down to ``min_slots``) only
      when the queue is empty and the bucket sat idle for
      ``shrink_after`` consecutive evaluations;
    - DEFER new admissions when the queue passes ``max_queue`` or
      observed p99 passes ``target_p99_s`` with work still queued;
      release the latch once the queue drains to half the bound
      (hysteresis — the latch cannot flap on one retirement)."""
    grow: dict = {}
    shrink: dict = {}
    reasons: list[str] = []
    qd = int(inputs.get("queue_depth", 0))
    slots = inputs.get("slots") or {}
    occ = inputs.get("occupancy") or {}
    for label, nblk in sorted((inputs.get("blocked") or {}).items()):
        if nblk <= 0:
            continue
        n = int(slots.get(label, 0))
        used = int(occ.get(label, 0))
        if n and used >= n and n < max_slots:
            grow[label] = n + 1
            reasons.append(f"grow {label} -> {n + 1}: {nblk} blocked "
                           f"admission(s) at {used}/{n}")
    idle_evals = idle_evals or {}
    if qd == 0:
        for label, n in sorted(slots.items()):
            if label in grow:
                continue
            if int(occ.get(label, 0)) == 0 and n > min_slots \
                    and idle_evals.get(label, 0) >= shrink_after:
                shrink[label] = n - 1
                reasons.append(
                    f"shrink {label} -> {n - 1}: idle for "
                    f"{idle_evals[label]} evaluations")
    defer = bool(inputs.get("deferring"))
    p99 = float(inputs.get("p99_s", 0.0))
    hot = (max_queue and qd >= max_queue) or \
        (target_p99_s and p99 > target_p99_s and qd > 0)
    if hot and not defer:
        defer = True
        why = [f"queue_depth {qd}"]
        if target_p99_s and p99 > target_p99_s:
            why.append(f"p99 {p99:.3g}s > target {target_p99_s:g}s")
        viol = inputs.get("slo_violations", 0)
        if viol:
            # quality-SLO context on the shed decision (quarantine owns
            # per-tenant isolation; backpressure owns load)
            why.append(f"{viol:g} slo violation(s) recorded")
        reasons.append("defer admissions: " + ", ".join(why))
    elif defer and not hot and qd <= (max_queue // 2):
        # release only once NOTHING is hot (a still-breached p99 must
        # not flap the latch every evaluation) AND the queue drained
        # past half the bound
        defer = False
        reasons.append(f"resume admissions: queue_depth {qd}")
    return Decision(grow=grow, shrink=shrink, defer=defer,
                    reasons=tuple(reasons))


class AutoscaleController:
    """Stateful wrapper + actuator around :func:`decide`.

    Knobs (constructor wins over env): ``max_slots``
    (PARMMG_SERVE_MAX_SLOTS, per-bucket growth ceiling), ``max_queue``
    (PARMMG_SERVE_MAX_QUEUE, shared with admission), ``target_p99_s``
    (PARMMG_SERVE_TARGET_P99_S, 0 = latency SLO off)."""

    def __init__(self, max_slots: int | None = None, min_slots: int = 1,
                 max_queue: int | None = None,
                 target_p99_s: float | None = None,
                 shrink_after: int = 3):
        self.max_slots = max_slots if max_slots is not None \
            else _env_int("PARMMG_SERVE_MAX_SLOTS", 16)
        self.min_slots = int(min_slots)
        self.max_queue = max_queue if max_queue is not None \
            else _env_int("PARMMG_SERVE_MAX_QUEUE", 0)
        self.target_p99_s = target_p99_s if target_p99_s is not None \
            else _env_float("PARMMG_SERVE_TARGET_P99_S", 0.0)
        self.shrink_after = int(shrink_after)
        self._idle: dict = {}           # bucket label -> idle streak
        self._last_hist: dict | None = None   # p99 windowing state
        self.deferring = False
        self.grows = 0
        self.shrinks = 0
        self.defers = 0
        self.evals = 0

    def _window_hist(self, hist: dict | None) -> dict:
        """Latency histogram DELTA since the previous evaluation: the
        registry histogram is lifetime-cumulative, and a p99 computed
        over the whole lifetime would let cold-start compile latencies
        pin the backpressure signal above target forever.  Cumulative
        bucket counts subtract bucket-wise (delta of cumulative ==
        cumulative of delta); an evaluation window with no new
        observations yields count 0 -> p99 0 (no recent latency
        evidence, no latency-driven deferral)."""
        cur = {"buckets": dict((hist or {}).get("buckets") or {}),
               "count": int((hist or {}).get("count", 0) or 0)}
        prev, self._last_hist = self._last_hist, cur
        if prev is None:
            return cur
        return {"buckets": {le: c - prev["buckets"].get(le, 0)
                            for le, c in cur["buckets"].items()},
                "count": cur["count"] - prev["count"]}

    def evaluate(self, snapshot: dict) -> Decision:
        """One evaluation of a metrics snapshot (no actuation): decide
        over the windowed latency signal, then advance the idle streaks
        the NEXT decision debounces on."""
        snapshot = dict(snapshot)
        hists = dict(snapshot.get("histograms") or {})
        hists["serve.latency_s"] = self._window_hist(
            hists.get("serve.latency_s"))
        snapshot["histograms"] = hists
        inputs = read_inputs(snapshot, self.deferring)
        d = decide(inputs, max_slots=self.max_slots,
                   min_slots=self.min_slots, max_queue=self.max_queue,
                   target_p99_s=self.target_p99_s,
                   idle_evals=dict(self._idle),
                   shrink_after=self.shrink_after)
        for label, n in inputs["slots"].items():
            if inputs["occupancy"].get(label, 0) == 0 \
                    and inputs["queue_depth"] == 0:
                self._idle[label] = self._idle.get(label, 0) + 1
            else:
                self._idle[label] = 0
        for label in d.shrink:          # a shrink restarts its streak
            self._idle[label] = 0
        return d

    def tick(self, pool, admission=None, registry=None) -> Decision:
        """Evaluate the live registry and ACTUATE: resize pool buckets,
        flip the admission defer latch, account + trace everything."""
        from ..obs import trace as otrace
        from ..obs.metrics import REGISTRY
        reg = registry if registry is not None else REGISTRY
        self.evals += 1
        reg.counter("serve.autoscale.evals").inc()
        # refresh occupancy/slots gauges from the POOL (authoritative)
        # before snapshotting: step() only publishes them while tenants
        # are active, and an idle pool's frozen gauges would otherwise
        # pin shrink at (last-gauged nslots - 1) forever
        for label, (used, n) in pool.occupancy().items():
            # lint: ok(R6) — label ranges over the finite capacity
            # ladder (same cardinality bound as serve.occupancy.*)
            reg.gauge(f"serve.occupancy.{label}").set(used)
            # lint: ok(R6) — same capacity-ladder cardinality bound
            reg.gauge(f"serve.slots.{label}").set(n)
        d = self.evaluate(reg.snapshot())
        labels = pool.labels()
        for action, targets in (("grow", d.grow), ("shrink", d.shrink)):
            for label, n in sorted(targets.items()):
                key = labels.get(label)
                if key is None:
                    continue
                before = pool.buckets[key].nslots
                after = pool.resize_bucket(key, n)
                if after == before:
                    continue            # e.g. trailing slot still rented
                if action == "grow":
                    self.grows += 1
                    reg.counter("serve.autoscale.grow").inc()
                else:
                    self.shrinks += 1
                    reg.counter("serve.autoscale.shrink").inc()
                otrace.event("serve.autoscale", action=action,
                             bucket=label, nslots=after)
                otrace.log(2, f"serve autoscale: {action} {label} "
                              f"{before} -> {after} slots", err=True)
        if d.defer != self.deferring:
            self.deferring = d.defer
            if d.defer:
                self.defers += 1
                reg.counter("serve.autoscale.defer").inc()
            otrace.event("serve.autoscale",
                         action="defer" if d.defer else "resume")
            otrace.log(1, "serve autoscale: "
                          + ("DEFERRING admissions"
                             if d.defer else "resuming admissions")
                          + (" — " + "; ".join(d.reasons)
                             if d.reasons else ""), err=True)
        if admission is not None:
            admission.deferring = self.deferring
        return d

    def summary(self) -> dict:
        return {"evals": self.evals, "grows": self.grows,
                "shrinks": self.shrinks, "defers": self.defers,
                "deferring": self.deferring,
                "max_slots": self.max_slots,
                "max_queue": self.max_queue,
                "target_p99_s": self.target_p99_s}
