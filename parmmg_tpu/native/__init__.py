"""ctypes bindings for the native host runtime (meshkit.cpp).

Builds ``libmeshkit.so`` on demand with g++ (no pybind11 in the image —
plain C ABI + ctypes, per the environment contract).  Every entry point
has a pure-numpy fallback elsewhere in the package; ``available()`` gates
use.
"""
from __future__ import annotations

import ctypes
import subprocess
from pathlib import Path

import numpy as np

_DIR = Path(__file__).parent
_SO = _DIR / "libmeshkit.so"
_LIB = None


def build(force: bool = False) -> bool:
    src = _DIR / "meshkit.cpp"
    if _SO.exists() and not force \
            and _SO.stat().st_mtime >= src.stat().st_mtime:
        return True
    try:
        subprocess.run(
            ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
             str(src), "-o", str(_SO)],
            check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _lib():
    global _LIB
    if _LIB is not None:
        return _LIB
    if not build():
        return None
    lib = ctypes.CDLL(str(_SO))
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C")
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C")
    lib.build_adjacency.argtypes = [ctypes.c_int64, i32p, i32p]
    lib.greedy_partition.argtypes = [ctypes.c_int64, i32p, f64p,
                                     ctypes.c_int32, i64p, i32p]
    lib.scan_medit.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                               ctypes.c_int, i64p,
                               ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_void_p, ctypes.c_void_p]
    lib.color_components.argtypes = [ctypes.c_int64, i32p, i32p, i32p]
    lib.color_components.restype = ctypes.c_int
    _LIB = lib
    return lib


def available() -> bool:
    return _lib() is not None


def build_adjacency(tet: np.ndarray) -> np.ndarray:
    """adja[4*t+f] = 4*t'+f' or -1 (host fast path)."""
    lib = _lib()
    tet = np.ascontiguousarray(tet, np.int32)
    ne = len(tet)
    adja = np.empty(4 * ne, np.int32)
    lib.build_adjacency(ne, tet.reshape(-1), adja)
    return adja.reshape(ne, 4)


def greedy_partition(adja: np.ndarray, nparts: int,
                     seeds: np.ndarray,
                     weights: np.ndarray | None = None) -> np.ndarray:
    lib = _lib()
    ne = len(adja)
    w = np.ascontiguousarray(
        np.ones(ne) if weights is None else weights, np.float64)
    part = np.empty(ne, np.int32)
    lib.greedy_partition(ne, np.ascontiguousarray(adja, np.int32)
                         .reshape(-1), w, nparts,
                         np.ascontiguousarray(seeds, np.int64), part)
    return part


def scan_medit(path) -> dict:
    """Fast ASCII Medit scan -> dict of arrays (vert 0-based ids)."""
    lib = _lib()
    data = Path(path).read_bytes()
    counts = np.zeros(3, np.int64)
    lib.scan_medit(data, len(data), 0, counts, None, None, None, None,
                   None, None)
    np_, ne, nt = map(int, counts)
    vert = np.empty((np_, 3), np.float64)
    vref = np.empty(np_, np.int32)
    tet = np.empty((ne, 4), np.int32)
    tref = np.empty(ne, np.int32)
    tria = np.empty((max(nt, 1), 3), np.int32)
    triaref = np.empty(max(nt, 1), np.int32)
    lib.scan_medit(data, len(data), 1, counts,
                   vert.ctypes.data_as(ctypes.c_void_p),
                   vref.ctypes.data_as(ctypes.c_void_p),
                   tet.ctypes.data_as(ctypes.c_void_p),
                   tref.ctypes.data_as(ctypes.c_void_p),
                   tria.ctypes.data_as(ctypes.c_void_p),
                   triaref.ctypes.data_as(ctypes.c_void_p))
    return {"vert": vert, "vref": vref, "tet": tet - 1, "tref": tref,
            "tria": tria[:nt] - 1, "triaref": triaref[:nt]}


def color_components(adja: np.ndarray, part: np.ndarray) -> np.ndarray:
    lib = _lib()
    ne = len(adja)
    comp = np.empty(ne, np.int32)
    lib.color_components(ne, np.ascontiguousarray(adja, np.int32)
                         .reshape(-1),
                         np.ascontiguousarray(part, np.int32), comp)
    return comp
