// meshkit: native host-side runtime kernels (C++17, no deps).
//
// TPU-native framework runtime pieces that stay on the host — the
// counterparts of the reference's C runtime around the remesher:
//   - tet-tet adjacency via face hashing (MMG3D_hashTetra role,
//     used by the reference at libparmmg1.c:733) — hash map beats
//     numpy lexsort on large meshes host-side;
//   - BFS greedy graph-growing partitioner (the METIS slot,
//     metis_pmmg.c:1271 role) with element weights;
//   - Medit ASCII fast scanner (inout_pmmg.c role): single pass,
//     manual float parsing, ~10x the Python tokenizer.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in the image).
// Build: g++ -O3 -march=native -shared -fPIC meshkit.cpp -o libmeshkit.so
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <queue>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// adjacency: adja[4*t+f] = 4*t'+f' of the twin face, or -1
// ---------------------------------------------------------------------------
static inline uint64_t face_key(int64_t a, int64_t b, int64_t c) {
  // sort the triple, pack 21 bits each
  if (a > b) { int64_t t = a; a = b; b = t; }
  if (b > c) { int64_t t = b; b = c; c = t; }
  if (a > b) { int64_t t = a; a = b; b = t; }
  return (uint64_t(a) << 42) | (uint64_t(b) << 21) | uint64_t(c);
}

// faces of tet (IDIR convention: face f opposite vertex f)
static const int FDIR[4][3] = {{1, 2, 3}, {0, 3, 2}, {0, 1, 3}, {0, 2, 1}};

int build_adjacency(int64_t ne, const int32_t* tet, int32_t* adja) {
  std::unordered_map<uint64_t, int64_t> open;  // key -> 4*t+f of 1st side
  open.reserve(size_t(ne) * 2);
  for (int64_t s = 0; s < 4 * ne; ++s) adja[s] = -1;
  for (int64_t t = 0; t < ne; ++t) {
    const int32_t* v = tet + 4 * t;
    for (int f = 0; f < 4; ++f) {
      uint64_t k = face_key(v[FDIR[f][0]], v[FDIR[f][1]], v[FDIR[f][2]]);
      auto it = open.find(k);
      if (it == open.end()) {
        open.emplace(k, 4 * t + f);
      } else {
        int64_t other = it->second;
        adja[4 * t + f] = int32_t(other);
        adja[other] = int32_t(4 * t + f);
        open.erase(it);
      }
    }
  }
  return 0;
}

// ---------------------------------------------------------------------------
// greedy graph-growing partitioner over the dual graph
// ---------------------------------------------------------------------------
int greedy_partition(int64_t ne, const int32_t* adja, const double* weights,
                     int32_t nparts, const int64_t* seeds, int32_t* part) {
  std::vector<std::queue<int64_t>> q(nparts);
  std::vector<double> load(nparts, 0.0);
  double total = 0.0;
  for (int64_t t = 0; t < ne; ++t) total += weights ? weights[t] : 1.0;
  for (int64_t t = 0; t < ne; ++t) part[t] = -1;
  for (int p = 0; p < nparts; ++p) q[p].push(seeds[p]);
  int64_t remaining = ne;
  while (remaining > 0) {
    // pick the least-loaded part with a non-empty queue
    int best = -1;
    for (int p = 0; p < nparts; ++p)
      if (!q[p].empty() && (best < 0 || load[p] < load[best])) best = p;
    if (best < 0) {
      // disconnected leftovers -> least-loaded part
      int lp = 0;
      for (int p = 1; p < nparts; ++p) if (load[p] < load[lp]) lp = p;
      for (int64_t t = 0; t < ne; ++t)
        if (part[t] == -1) { part[t] = lp; load[lp] += weights ? weights[t] : 1.0; --remaining; }
      break;
    }
    bool took = false;
    while (!q[best].empty()) {
      int64_t t = q[best].front(); q[best].pop();
      if (part[t] != -1) continue;
      part[t] = best;
      load[best] += weights ? weights[t] : 1.0;
      --remaining;
      for (int f = 0; f < 4; ++f) {
        int32_t a = adja[4 * t + f];
        if (a >= 0 && part[a / 4] == -1) q[best].push(a / 4);
      }
      took = true;
      break;
    }
    (void)took;
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Medit ASCII fast scanner.
// Pass 1 (mode=0): returns counts in out_counts[0..2] = (np, ne, nt).
// Pass 2 (mode=1): fills vert[3*np], vref[np], tet[4*ne], tref[ne],
//                  tria[3*nt], triaref[nt] (tet/tria 1-based as in file).
// ---------------------------------------------------------------------------
static const char* skip_ws(const char* p, const char* end) {
  while (p < end) {
    if (*p == '#') { while (p < end && *p != '\n') ++p; }
    else if (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r') ++p;
    else break;
  }
  return p;
}

static const char* read_tok(const char* p, const char* end, const char** s,
                            int64_t* len) {
  p = skip_ws(p, end);
  *s = p;
  while (p < end && *p != ' ' && *p != '\t' && *p != '\n' && *p != '\r' &&
         *p != '#') ++p;
  *len = p - *s;
  return p;
}

int scan_medit(const char* buf, int64_t n, int mode, int64_t* out_counts,
               double* vert, int32_t* vref, int32_t* tet, int32_t* tref,
               int32_t* tria, int32_t* triaref) {
  const char* p = buf;
  const char* end = buf + n;
  int64_t np = 0, ne = 0, nt = 0;
  const char* s; int64_t L;
  while (p < end) {
    p = read_tok(p, end, &s, &L);
    if (L == 0) break;
    if (L == 3 && !strncmp(s, "End", 3)) break;
    if ((L == 20 && !strncmp(s, "MeshVersionFormatted", 20)) ||
        (L == 9 && !strncmp(s, "Dimension", 9))) {
      p = read_tok(p, end, &s, &L);
    } else if (L == 8 && !strncmp(s, "Vertices", 8)) {
      p = read_tok(p, end, &s, &L); np = strtoll(s, nullptr, 10);
      if (mode == 0) { // skip np * 4 tokens
        for (int64_t i = 0; i < np * 4; ++i) p = read_tok(p, end, &s, &L);
      } else {
        char* q;
        for (int64_t i = 0; i < np; ++i) {
          p = skip_ws(p, end);
          vert[3 * i]     = strtod(p, &q); p = q;
          vert[3 * i + 1] = strtod(p, &q); p = q;
          vert[3 * i + 2] = strtod(p, &q); p = q;
          vref[i] = int32_t(strtol(p, &q, 10)); p = q;
        }
      }
    } else if (L == 10 && !strncmp(s, "Tetrahedra", 10)) {
      p = read_tok(p, end, &s, &L); ne = strtoll(s, nullptr, 10);
      if (mode == 0) {
        for (int64_t i = 0; i < ne * 5; ++i) p = read_tok(p, end, &s, &L);
      } else {
        char* q;
        for (int64_t i = 0; i < ne; ++i) {
          p = skip_ws(p, end);
          for (int k = 0; k < 4; ++k) {
            tet[4 * i + k] = int32_t(strtol(p, &q, 10)); p = q;
          }
          tref[i] = int32_t(strtol(p, &q, 10)); p = q;
        }
      }
    } else if (L == 9 && !strncmp(s, "Triangles", 9)) {
      p = read_tok(p, end, &s, &L); nt = strtoll(s, nullptr, 10);
      if (mode == 0) {
        for (int64_t i = 0; i < nt * 4; ++i) p = read_tok(p, end, &s, &L);
      } else {
        char* q;
        for (int64_t i = 0; i < nt; ++i) {
          p = skip_ws(p, end);
          for (int k = 0; k < 3; ++k) {
            tria[3 * i + k] = int32_t(strtol(p, &q, 10)); p = q;
          }
          triaref[i] = int32_t(strtol(p, &q, 10)); p = q;
        }
      }
    } else {
      // unknown keyword: "count" then count*? tokens — cannot size; stop
      break;
    }
  }
  out_counts[0] = np; out_counts[1] = ne; out_counts[2] = nt;
  return 0;
}

// connected-component labeling over the dual graph (contiguity checks,
// PMMG_check_contiguity role, moveinterfaces_pmmg.c:309)
int color_components(int64_t ne, const int32_t* adja, const int32_t* part,
                     int32_t* comp) {
  for (int64_t t = 0; t < ne; ++t) comp[t] = -1;
  int32_t nc = 0;
  std::vector<int64_t> stack;
  for (int64_t s0 = 0; s0 < ne; ++s0) {
    if (comp[s0] != -1) continue;
    comp[s0] = nc;
    stack.push_back(s0);
    while (!stack.empty()) {
      int64_t t = stack.back(); stack.pop_back();
      for (int f = 0; f < 4; ++f) {
        int32_t a = adja[4 * t + f];
        if (a >= 0) {
          int64_t u = a / 4;
          if (comp[u] == -1 && part[u] == part[t]) {
            comp[u] = nc;
            stack.push_back(u);
          }
        }
      }
    }
    ++nc;
  }
  return nc;
}

}  // extern "C"
