"""Flat, masked, fixed-capacity mesh arrays — the TPU-native mesh structure.

Replaces the reference's pointer-rich ``MMG5_pMesh`` (linked xtetra/xpoint side
tables, realloc-on-demand, see /root/reference/src/libparmmgtypes.h:286-307 for
how groups wrap it) with a pytree of dense device arrays:

- static *capacity* (array shape) + dynamic *used prefix* + per-slot validity
  masks.  XLA needs static shapes; the Mmg pack/realloc dance
  (``MMG5_paktet``/``PMMG_fitMeshSize``, reference zaldy_pmmg.c:256-492)
  becomes mask-and-compact, with capacity growth done host-side between jitted
  phases (the analogue of the reference's memory budgeting).
- boundary data (Mmg's sparse ``xtetra``/``xpoint``) becomes dense per-face and
  per-edge tag arrays on every tet: regular layout beats sparse side tables on
  a vector machine.
- adjacency ``adja[ne,4]`` stores ``4*neighbor_tet + neighbor_face`` (same
  packing idea as Mmg) or -1 on a boundary face.

All fields are JAX arrays so a Mesh can cross jit boundaries as a pytree.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .constants import IDIR, IARE, MG_BDY, MG_CRN, MG_REQ


@partial(jax.tree_util.register_dataclass,
         data_fields=["vert", "vref", "vtag", "vmask",
                      "tet", "tref", "tmask", "adja",
                      "ftag", "fref", "etag",
                      "npoin", "nelem"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class Mesh:
    """A tetrahedral mesh in fixed-capacity device arrays.

    Invalid slots form a suffix after :func:`compact`, but code must only rely
    on the masks. Vertex ids stored in ``tet`` are row indices into ``vert``.
    """
    # -- vertices -----------------------------------------------------------
    vert: jax.Array   # [capP, 3] float coordinates
    vref: jax.Array   # [capP]    int32 reference
    vtag: jax.Array   # [capP]    uint32 MG_* tag bits
    vmask: jax.Array  # [capP]    bool validity
    # -- tetrahedra ---------------------------------------------------------
    tet: jax.Array    # [capT, 4] int32 vertex ids
    tref: jax.Array   # [capT]    int32 reference (sub-domain id)
    tmask: jax.Array  # [capT]    bool validity
    adja: jax.Array   # [capT, 4] int32: 4*neigh+face, or -1 (boundary/none)
    # -- boundary / tag side data (dense replacement for xtetra) ------------
    ftag: jax.Array   # [capT, 4] uint32 per-face MG_* tags
    fref: jax.Array   # [capT, 4] int32 per-face surface reference
    etag: jax.Array   # [capT, 6] uint32 per-edge MG_* tags
    # -- dynamic counts (used-prefix hints; authoritative = masks) ----------
    npoin: jax.Array  # scalar int32
    nelem: jax.Array  # scalar int32

    # -- static helpers -----------------------------------------------------
    @property
    def capP(self) -> int:
        return self.vert.shape[0]

    @property
    def capT(self) -> int:
        return self.tet.shape[0]

    @property
    def dtype(self):
        return self.vert.dtype

    def np_counts(self):
        """(#valid points, #valid tets) as concrete ints (host sync)."""
        return int(jnp.sum(self.vmask)), int(jnp.sum(self.tmask))


# canonical field-name tuple for (de)serializing a Mesh as flat arrays
# (npz state handoffs: scripts/scale_big.py, parallel/_polish_worker.py)
MESH_FIELDS = tuple(f.name for f in dataclasses.fields(Mesh))


def make_mesh(vert: np.ndarray, tet: np.ndarray,
              vref: np.ndarray | None = None,
              tref: np.ndarray | None = None,
              capP: int | None = None, capT: int | None = None,
              dtype=jnp.float32) -> Mesh:
    """Build a Mesh from host arrays, padding to the given capacities.

    Capacities default to a growth headroom of ~3x points / ~3x tets, the
    analogue of the reference memory-repartition budget
    (zaldy_pmmg.c:140-254) — adaptation inserts points, so headroom is the
    price of static shapes.
    """
    vert = np.asarray(vert, dtype=np.float64)
    tet = np.asarray(tet, dtype=np.int32)
    n_p, n_t = vert.shape[0], tet.shape[0]
    if capP is None:
        capP = max(64, int(3 * n_p))
    if capT is None:
        capT = max(64, int(3 * n_t))
    if capP < n_p or capT < n_t:
        raise ValueError("capacity smaller than input mesh")
    if n_t and tet.max() >= n_p:
        raise ValueError("tet references nonexistent vertex")

    def pad(a, cap, fill=0, dt=None):
        out = np.full((cap,) + a.shape[1:], fill,
                      dtype=dt if dt is not None else a.dtype)
        out[: a.shape[0]] = a
        return out

    vref = np.zeros(n_p, np.int32) if vref is None else np.asarray(vref, np.int32)
    tref = np.zeros(n_t, np.int32) if tref is None else np.asarray(tref, np.int32)
    vmask = pad(np.ones(n_p, bool), capP, False)
    tmask = pad(np.ones(n_t, bool), capT, False)
    return Mesh(
        vert=jnp.asarray(pad(vert, capP), dtype=dtype),
        vref=jnp.asarray(pad(vref, capP)),
        vtag=jnp.zeros(capP, jnp.uint32),
        vmask=jnp.asarray(vmask),
        tet=jnp.asarray(pad(tet, capT)),
        tref=jnp.asarray(pad(tref, capT)),
        tmask=jnp.asarray(tmask),
        adja=jnp.full((capT, 4), -1, jnp.int32),
        ftag=jnp.zeros((capT, 4), jnp.uint32),
        fref=jnp.zeros((capT, 4), jnp.int32),
        etag=jnp.zeros((capT, 6), jnp.uint32),
        npoin=jnp.asarray(n_p, jnp.int32),
        nelem=jnp.asarray(n_t, jnp.int32),
    )


def mesh_to_host(mesh: Mesh):
    """Extract compacted (vert, tet, vref, tref) numpy arrays.

    The inverse of :func:`make_mesh`; renumbers vertices densely.  This is the
    analogue of the final ``MMG5_paktet`` + API ``PMMG_Get_*`` readout
    (reference libparmmg1.c:156, API_functions_pmmg.c).
    """
    vmask = np.asarray(mesh.vmask)
    tmask = np.asarray(mesh.tmask)
    vert = np.asarray(mesh.vert)[vmask]
    vref = np.asarray(mesh.vref)[vmask]
    vtag = np.asarray(mesh.vtag)[vmask]
    new_id = np.cumsum(vmask) - 1          # old -> new vertex id
    tet = new_id[np.asarray(mesh.tet)[tmask]].astype(np.int32)
    tref = np.asarray(mesh.tref)[tmask]
    return vert, tet.reshape(-1, 4), vref, tref, vtag


# ---------------------------------------------------------------------------
# Derived element arrays (pure functions of the Mesh pytree)
# ---------------------------------------------------------------------------
_IDIR_J = jnp.asarray(IDIR)
_IARE_J = jnp.asarray(IARE)


def tet_face_vertices(tet: jax.Array) -> jax.Array:
    """[capT, 4, 3] vertex ids of each tet face (face f opposite vertex f)."""
    return tet[:, _IDIR_J]


def tet_edge_vertices(tet: jax.Array) -> jax.Array:
    """[capT, 6, 2] vertex ids of each tet edge."""
    return tet[:, _IARE_J]


def tet_volumes(mesh: Mesh) -> jax.Array:
    """Signed volume of every tet slot (garbage where tmask is False)."""
    p = mesh.vert[mesh.tet]                      # [capT,4,3]
    d1 = p[:, 1] - p[:, 0]
    d2 = p[:, 2] - p[:, 0]
    d3 = p[:, 3] - p[:, 0]
    det = jnp.einsum("ti,ti->t", d1, jnp.cross(d2, d3))
    return det / 6.0


def compact(mesh: Mesh) -> Mesh:
    """Host-side compaction: move valid slots to the front, renumber.

    The analogue of ``PMMG_packParMesh`` (reference libparmmg1.c:195): run
    between jitted phases when the free-slot suffix runs out.  Not jittable on
    purpose (gather with dynamic output size); capacities are preserved.
    """
    vmask = np.asarray(mesh.vmask)
    tmask = np.asarray(mesh.tmask)
    n_p, n_t = int(vmask.sum()), int(tmask.sum())
    vperm = np.argsort(~vmask, kind="stable")    # valid first, order kept
    tperm = np.argsort(~tmask, kind="stable")
    old2new = np.empty(mesh.capP, np.int32)
    old2new[vperm] = np.arange(mesh.capP, dtype=np.int32)

    tet = old2new[np.asarray(mesh.tet)[tperm]]
    # adjacency: renumber neighbor tet ids through tperm
    t_old2new = np.empty(mesh.capT, np.int32)
    t_old2new[tperm] = np.arange(mesh.capT, dtype=np.int32)
    adja = np.asarray(mesh.adja)[tperm]
    nb = adja >> 2
    valid = adja >= 0
    adja = np.where(valid, 4 * t_old2new[np.clip(nb, 0, mesh.capT - 1)]
                    + (adja & 3), -1).astype(np.int32)

    return Mesh(
        vert=jnp.asarray(np.asarray(mesh.vert)[vperm]),
        vref=jnp.asarray(np.asarray(mesh.vref)[vperm]),
        vtag=jnp.asarray(np.asarray(mesh.vtag)[vperm]),
        vmask=jnp.asarray(vmask[vperm]),
        tet=jnp.asarray(tet.astype(np.int32)),
        tref=jnp.asarray(np.asarray(mesh.tref)[tperm]),
        tmask=jnp.asarray(tmask[tperm]),
        adja=jnp.asarray(adja),
        ftag=jnp.asarray(np.asarray(mesh.ftag)[tperm]),
        fref=jnp.asarray(np.asarray(mesh.fref)[tperm]),
        etag=jnp.asarray(np.asarray(mesh.etag)[tperm]),
        npoin=jnp.asarray(n_p, jnp.int32),
        nelem=jnp.asarray(n_t, jnp.int32),
    )


def with_capacity(mesh: Mesh, capP: int, capT: int) -> Mesh:
    """Grow (never shrink below content) the capacities, host-side."""
    mesh = compact(mesh)
    n_p, n_t = mesh.np_counts()
    if capP < n_p or capT < n_t:
        raise ValueError("cannot shrink below live content")

    def grow(a, cap, fill=0):
        a = np.asarray(a)
        out = np.full((cap,) + a.shape[1:], fill, dtype=a.dtype)
        out[: a.shape[0]] = a[:min(a.shape[0], cap)]
        return jnp.asarray(out)

    return Mesh(
        vert=grow(mesh.vert, capP), vref=grow(mesh.vref, capP),
        vtag=grow(mesh.vtag, capP), vmask=grow(mesh.vmask, capP, False),
        tet=grow(mesh.tet, capT), tref=grow(mesh.tref, capT),
        tmask=grow(mesh.tmask, capT, False), adja=grow(mesh.adja, capT, -1),
        ftag=grow(mesh.ftag, capT), fref=grow(mesh.fref, capT),
        etag=grow(mesh.etag, capT),
        npoin=mesh.npoin, nelem=mesh.nelem,
    )
