"""Tag vocabulary, error codes and default parameters.

TPU-native re-design of the ParMmg constant surface:
- entity tag bits mirror the Mmg ``MG_*`` vocabulary referenced throughout the
  reference (see /root/reference/src/tag_pmmg.c:39-107 for how parallel
  interface entities are tagged ``MG_PARBDY + MG_BDY + MG_REQ + MG_NOSURF`` so
  the remesher treats them as frozen), because the freeze/ownership contract is
  behavioral API we must reproduce;
- error codes mirror PMMG_SUCCESS/LOWFAILURE/STRONGFAILURE
  (/root/reference/src/libparmmgtypes.h:45-66);
- default knobs mirror PMMG_Init_parameters
  (/root/reference/src/API_functions_pmmg.c:400-426) and parmmg.h:70,209-227.

Here the tags live in dense per-entity uint32 arrays (points, tet faces, tet
edges) instead of sparse xtetra/xpoint side structures: dense arrays are the
vectorizable representation on TPU.
"""
from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------------------
# Entity tag bits (uint32). Names follow the Mmg vocabulary for parity.
# ---------------------------------------------------------------------------
MG_NOTAG = 0
MG_REF = 1 << 0       # entity lies on a reference (multi-material) surface
MG_BDY = 1 << 1       # entity lies on the domain boundary
MG_REQ = 1 << 2       # required: the remesher must not touch it
MG_CRN = 1 << 3       # corner point (geometric singularity)
MG_GEO = 1 << 4       # ridge (sharp edge by dihedral angle)
MG_NOM = 1 << 5       # non-manifold entity
MG_NOSURF = 1 << 6    # REQ was set by us, not the user (can be relaxed)
MG_OPNBDY = 1 << 7    # open boundary face (hanging surface inside volume)
MG_PARBDY = 1 << 8    # entity on a parallel (inter-shard) interface
MG_PARBDYBDY = 1 << 9 # true domain boundary that also lies on an interface
MG_OLDPARBDY = 1 << 10  # was a parallel interface at the previous iteration

# Frozen-interface contract: everything on a parallel interface is required +
# boundary + "not a real surface" (reference tag_pmmg.c:39-124).
PARBDY_TAGS = MG_PARBDY | MG_BDY | MG_REQ | MG_NOSURF

# ---------------------------------------------------------------------------
# Return codes (libparmmgtypes.h:45-66)
# ---------------------------------------------------------------------------
PMMG_SUCCESS = 0
PMMG_LOWFAILURE = 1      # something failed but a conforming mesh can be saved
PMMG_STRONGFAILURE = 2   # unrecoverable
PMMG_FAILURE = 4

# ---------------------------------------------------------------------------
# Default parameters (API_functions_pmmg.c:400-426, parmmg.h:70,209-227)
# ---------------------------------------------------------------------------
NITER_DEFAULT = 3                 # parmmg.h:70
TARGET_MESH_SIZE_SENTINEL = -30_000_000   # parmmg.h:209 (negative => default)
REMESHER_NGRPS_MAX = 100          # parmmg.h:212
RATIO_MMG_METIS_SENTINEL = -100   # parmmg.h:215
REDISTR_NGRPS_MAX = 1000          # parmmg.h:218
REDISTR_NELEM_MIN = 6             # parmmg.h:221
GRPS_RATIO = 2.0                  # parmmg.h:224
MVIFCS_NLAYERS = 2                # parmmg.h:227 (interface displacement waves)
IFC_EDGE_WEIGHT = 1.0e6           # metis_pmmg.h:64 (keep old ifcs off cuts)
WGT_ALPHA = 28.0                  # metis_pmmg.c:280 metric-aware edge weight
PARMETIS_UBVEC = 1.05             # metis_pmmg.h:72

# Repartitioning modes (libparmmgtypes.h:173-194)
REPART_GRAPH = 0
REPART_IFC_DISPLACEMENT = 1       # reference default
# Load-balancing partitioners
LB_METIS = 0   # reference: sequential METIS on gathered group graph
LB_SPECTRAL = 1  # ours: on-device spectral partitioner

# API modes for distributed input (libparmmg.h APImode)
APIDISTRIB_FACES = 0
APIDISTRIB_NODES = 1

# ---------------------------------------------------------------------------
# Remesh thresholds (Mmg kernel constants, mmg3d.h). Edge lengths are in
# metric space where the ideal length is 1.
# ---------------------------------------------------------------------------
LLONG = 1.4142135623730951   # split edges longer than sqrt(2)
LSHRT = 0.7071067811865476   # collapse edges shorter than 1/sqrt(2)
LOPTL = 1.3                  # target long threshold used in later passes
LOPTS = 0.6                  # target short threshold used in later passes
ANGEDG_DEG = 45.0            # dihedral angle for ridge detection (Mmg default)
ANGEDG = np.cos(ANGEDG_DEG * np.pi / 180.0)
EPSD = 1e-30
# Normalisation so an equilateral tet has quality 1:
#   Q = ALPHA_TET * vol / (sum_of_squared_edge_lengths)^{3/2}
# (Mmg MMG5_caltet_iso semantics, reference quality_pmmg.c:720 calls it per
# group; 36*sqrt(12) = 124.707...)
ALPHA_TET = 36.0 * np.sqrt(12.0)

# Minimal acceptable quality for an operator to be applied (Mmg uses a
# relative criterion; we keep an absolute floor plus no-worsening rules).
QUAL_FLOOR = 1e-9

# Default Hausdorff / gradation values (Mmg defaults, forwarded per group by
# PMMG_Set_dparameter, API_functions_pmmg.c:735)
HAUSD_DEFAULT = 0.01
HGRAD_DEFAULT = 1.3
HGRADREQ_DEFAULT = 2.3

# Verbosity levels (parmmg.h:128-163)
PMMG_VERB_NO = -1
PMMG_VERB_VERSION = 0
PMMG_VERB_QUAL = 1
PMMG_VERB_STEPS = 2
PMMG_VERB_ITWAVES = 3
PMMG_VERB_DETQUAL = 4

# ---------------------------------------------------------------------------
# Local tet topology tables (canonical, same conventions as Mmg where the
# reference relies on them for face/edge encodings, libparmmg1.c:132-140).
# Face f of a tet is opposite vertex f; MMG5_idir lists its 3 vertices.
# ---------------------------------------------------------------------------
# faces: IDIR[f] = the 3 local vertex indices of face f (opposite vertex f)
IDIR = np.array([[1, 2, 3], [0, 3, 2], [0, 1, 3], [0, 2, 1]], dtype=np.int32)
# edges: IARE[e] = the 2 local vertex indices of edge e
IARE = np.array(
    [[0, 1], [0, 2], [0, 3], [1, 2], [1, 3], [2, 3]], dtype=np.int32
)
# IFAR[e] = the 2 faces NOT containing edge e ; faces containing edge e:
EDGE_FACES = np.array(
    [[2, 3], [1, 3], [1, 2], [0, 3], [0, 2], [0, 1]], dtype=np.int32
)
# For face f (vertices IDIR[f]), the local edge indices of its 3 edges
FACE_EDGES = np.array(
    [[3, 5, 4], [2, 5, 1], [0, 4, 2], [1, 3, 0]], dtype=np.int32
)
