"""Command-line interface — the ``parmmg_O3`` executable analogue.

Flag surface mirrors the reference CLI (usage list
/root/reference/src/libparmmg_tools.c:101-170; main flow parmmg.c:60-446):
load (centralized file, or per-shard ``name.<rank>.mesh`` fallback probe
like parmmg.c:161-188), adapt, save (mesh/meshb/vtu/pvtu, centralized or
distributed).  Device parallelism replaces MPI ranks: ``-ndev N`` shards
the mesh over N devices of the JAX mesh.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

from .api import ParMesh, IParam, DParam
from .core import constants as C
from .obs import trace as otrace


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="parmmg_tpu", add_help=True, prefix_chars="-",
        description="TPU-native parallel tetrahedral remesher "
                    "(ParMmg capability surface)")
    a = p.add_argument
    a("-in", dest="inp", metavar="file", help="input mesh")
    a("-out", dest="out", metavar="file", help="output mesh")
    a("-sol", "-met", dest="sol", metavar="file", help="metric file")
    a("-field", dest="field", metavar="file", help="input fields to "
      "interpolate")
    a("-noout", action="store_true", help="no output mesh")
    a("-v", dest="verbose", type=int, default=1, help="verbosity")
    a("-mmg-v", dest="mmg_verbose", type=int, default=-1,
      help="remesh-kernel verbosity")
    a("-m", dest="mem", type=int, default=-1, help="memory budget MB")
    a("-d", dest="debug", action="store_true", help="debug mode")
    a("-niter", type=int, default=C.NITER_DEFAULT,
      help="adaptation iterations")
    a("-mesh-size", dest="mesh_size", type=int,
      default=C.TARGET_MESH_SIZE_SENTINEL, help="target shard mesh size")
    a("-metis-ratio", dest="metis_ratio", type=int,
      default=C.RATIO_MMG_METIS_SENTINEL,
      help="ratio of migration groups to remesh groups")
    a("-nlayers", type=int, default=C.MVIFCS_NLAYERS,
      help="interface displacement layers")
    a("-groups-ratio", dest="groups_ratio", type=float, default=C.GRPS_RATIO,
      help="allowed group imbalance")
    a("-nobalance", action="store_true", help="no load balancing")
    a("-ndev", type=int, default=1, help="number of devices (shards)")
    a("-hmin", type=float, default=-1.0)
    a("-hmax", type=float, default=-1.0)
    a("-hsiz", type=float, default=-1.0, help="constant target size")
    a("-hausd", type=float, default=C.HAUSD_DEFAULT)
    a("-hgrad", type=float, default=C.HGRAD_DEFAULT)
    a("-hgradreq", type=float, default=C.HGRADREQ_DEFAULT)
    a("-ar", dest="angle", type=float, default=C.ANGEDG_DEG,
      help="ridge detection angle (deg)")
    a("-nr", dest="noridge", action="store_true",
      help="no ridge detection")
    a("-A", dest="aniso", action="store_true",
      help="anisotropic metric computation (reference -A flag)")
    a("-mmg-d", dest="mmg_debug", action="store_true",
      help="remesh-kernel debug mode")
    a("-optim", action="store_true", help="preserve current sizing")
    a("-optimLES", action="store_true")
    a("-noinsert", action="store_true")
    a("-noswap", action="store_true")
    a("-nomove", action="store_true")
    a("-nosurf", action="store_true")
    a("-nofem", action="store_true")
    a("-opnbdy", action="store_true", help="preserve open boundaries")
    a("-octree", type=int, default=-1, help="(accepted, unused on TPU)")
    a("-rn", type=int, default=-1, help="(renumbering: n/a on TPU)")
    a("-centralized-output", dest="cent_out", action="store_true")
    a("-distributed-output", dest="dist_out", action="store_true")
    a("-resume", action="store_true",
      help="resume a killed grouped run from the newest "
           "PARMMG_CKPT_DIR pass checkpoint (resilience/checkpoint.py)")
    a("-val", action="store_true", help="print default values and exit")
    a("-bench-json", dest="bench_json", action="store_true",
      help="print one JSON line with timing/quality stats")
    return p


def default_values() -> str:
    """PMMG_defaultValues analogue (libparmmg_tools.c:61)."""
    from .api.params import Info
    info = Info()
    lines = ["default parameter values:"]
    for f, v in sorted(vars(info).items()):
        lines.append(f"  {f:24s} {v}")
    return "\n".join(lines)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # the CLI's -v flag IS the process imprim: align obs.trace.log's
    # gate with it up front so pre-run errors/warnings follow the flag
    # (not a stray PARMMG_VERBOSE inherited from the environment);
    # fatal diagnostics are level 0, silenced only by an explicit
    # negative -v — the reference's imprim semantics
    otrace.set_verbosity(args.verbose)
    if args.val:
        print(default_values())   # lint: ok(R3) — -val stdout contract
        return 0
    if not args.inp:
        otrace.log(0, "missing -in <mesh>", err=True)
        return 1
    # persistent compile cache (compile governor): the adapt programs
    # take minutes to compile cold and are identical across runs —
    # default the cache dir (env JAX_COMPILATION_CACHE_DIR wins) so
    # repeat CLI invocations and subprocess workers start warm.
    # set_cache_env itself declines on the forced-CPU backend, and the
    # fallback guard below re-drops the cache when the accelerator is
    # absent and jax silently resolves to XLA:CPU (whose AOT cache is
    # unreliable on this image).
    from .utils.compilecache import (drop_cache_on_cpu_fallback,
                                     set_cache_env)
    set_cache_env()
    drop_cache_on_cpu_fallback()

    from .io import medit
    from .io.distributed import probe_distributed, load_distributed_mesh

    t0 = time.perf_counter()
    pm = ParMesh()
    inp = Path(args.inp)
    vtu_met = vtu_fields = None
    if inp.suffix == ".vtu":
        # centralized VTK input (PMMG_loadVtuMesh_centralized role,
        # inoutcpp_pmmg.cpp:44); point fields named metric/sol become
        # the metric unless -sol overrides
        from .io.vtk import read_vtu_medit
        if not inp.exists():
            otrace.log(0, f"cannot open {inp}", err=True)
            return 1
        m, vtu_met, vtu_fields = read_vtu_medit(inp)
        distributed_in = False
    else:
        if inp.suffix not in (".mesh", ".meshb"):
            inp = inp.with_suffix(".mesh")
        distributed_in = not inp.exists() and probe_distributed(inp, 0)
    if distributed_in:
        # reassemble shards (the centralized entry of a distributed
        # checkpoint; parmmg.c's probe order reversed but equivalent)
        parts = []
        r = 0
        while probe_distributed(inp, r):
            parts.append(load_distributed_mesh(inp, r)[0])
            r += 1
        m = _concat_shards(parts)
        # distributed input stays distributed: the run adopts the
        # caller's decomposition (libparmmg.c:206-329 semantics) when the
        # device count matches the shard count
        pm._in_part = getattr(m, "src_part", None)
    elif inp.suffix == ".vtu":
        pass                                  # loaded above
    elif inp.exists():
        m = medit.read_mesh(inp)
    else:
        otrace.log(0, f"cannot open {inp}", err=True)
        return 1

    pm.set_mesh_size(np_=len(m.vert), ne=len(m.tetra), nt=len(m.tria),
                     na=len(m.edges))
    pm.set_vertices(m.vert, m.vref)
    pm.set_tetrahedra(m.tetra + 1, m.tref)
    if len(m.tria):
        pm.set_triangles(m.tria + 1, m.triaref)
    if len(m.edges):
        pm.set_edges(m.edges + 1, m.edgeref)
    for c in m.corners:
        pm.set_corner(int(c) + 1)
    for rv in m.required_vert:
        pm.set_required_vertex(int(rv) + 1)
    for rid in m.ridges:
        pm.set_ridge(int(rid) + 1)

    if args.sol:
        vals, types = medit.read_sol(args.sol)
        typ = types[0]
        pm.set_met_size(3 if typ == medit.SOL_TENSOR else 1, len(m.vert))
        if typ == medit.SOL_TENSOR:
            pm.set_tensor_mets(vals.reshape(len(m.vert), 6))
        else:
            pm.set_scalar_mets(vals.reshape(len(m.vert)))
    elif vtu_met is not None:
        # metric carried in the VTU point data (the VTK-solution ingest
        # of the reference's loadVtu path)
        if vtu_met.ndim == 2 and vtu_met.shape[1] == 6:
            pm.set_met_size(3, len(m.vert))
            pm.set_tensor_mets(vtu_met)
        else:
            pm.set_met_size(1, len(m.vert))
            pm.set_scalar_mets(np.asarray(vtu_met).reshape(len(m.vert)))
    if args.field:
        vals, types = medit.read_sol(args.field)
        pm.set_sols_at_vertices_size(len(types), types)
        off = 0
        ncomp = {1: 1, 2: 3, 3: 6}
        vals2 = vals.reshape(len(m.vert), -1)
        for i, t in enumerate(types):
            w = ncomp[t]
            chunk = vals2[:, off:off + w]
            pm.set_ith_sol_in_sols_at_vertices(
                i + 1, chunk if w > 1 else chunk[:, 0])
            off += w
    elif vtu_fields:
        # non-metric VTU point fields ride along as solution fields
        # (the reference's loadVtu path carries them; losing them
        # silently would strand the user's data) — scalar and
        # 3-component fields map to the Medit sol types, anything else
        # is skipped with a warning
        from .io.medit import SOL_SCALAR, SOL_VECTOR, SOL_TENSOR
        carried, types = [], []
        for nm, arr in vtu_fields.items():
            a = np.asarray(arr, np.float64).reshape(len(m.vert), -1)
            if a.shape[1] == 1:
                carried.append(a[:, 0])
                types.append(SOL_SCALAR)
            elif a.shape[1] == 3:
                carried.append(a)
                types.append(SOL_VECTOR)
            elif a.shape[1] == 6:
                carried.append(a)
                types.append(SOL_TENSOR)
            else:
                otrace.log(0, f"warning: dropping VTU point field "
                              f"'{nm}' ({a.shape[1]} components)",
                           err=True)
        if carried:
            pm.set_sols_at_vertices_size(len(types), types)
            for i, chunk in enumerate(carried):
                pm.set_ith_sol_in_sols_at_vertices(i + 1, chunk)

    # parameters
    info = pm.info
    info.imprim = args.verbose
    info.mmg_imprim = args.mmg_verbose
    info.debug = args.debug
    info.niter = args.niter
    info.target_mesh_size = args.mesh_size
    info.metis_ratio = args.metis_ratio
    info.ifc_layers = args.nlayers
    info.grps_ratio = args.groups_ratio
    info.nobalancing = args.nobalance
    info.n_devices = args.ndev
    info.hmin, info.hmax = args.hmin, args.hmax
    info.hsiz = args.hsiz
    info.hausd = args.hausd
    info.hgrad = args.hgrad
    info.hgradreq = args.hgradreq
    info.angle_deg = args.angle
    info.angle_detection = not args.noridge
    info.optim = args.optim
    info.optimLES = args.optimLES
    info.anisosize = args.aniso
    info.mmg_debug = args.mmg_debug
    info.noinsert = args.noinsert
    info.noswap = args.noswap
    info.nomove = args.nomove
    info.nosurf = args.nosurf
    info.fem = not args.nofem
    info.opnbdy = args.opnbdy
    info.mem_budget_mb = args.mem
    info.centralized_output = not args.dist_out
    info.noout = args.noout
    info.resume = args.resume

    # local-parameter file (<mesh>.mmg3d, MMG3D_parsop format; the
    # reference delegates parsing to Mmg at libparmmg_tools.c:573)
    parfile = Path(args.inp).with_suffix(".mmg3d")
    if parfile.exists():
        try:
            parsed = _parse_parfile(parfile)
        except (IndexError, ValueError) as e:
            # the file is discovered implicitly by name — a stale or
            # malformed one must not abort the run
            otrace.log(0, f"  ## Warning: unable to parse {parfile} "
                          f"({e}); local parameters ignored.", err=True)
            parsed = []
        for typ, ref, hmin_l, hmax_l, hausd_l in parsed:
            pm.set_local_parameter(typ, ref, hmin_l, hmax_l, hausd_l)
        otrace.log(1, f"  %% {parfile} read: "
                      f"{len(pm.info.local_params)} local parameter(s)",
                   verbose=args.verbose)

    ret = pm.run()
    dt = time.perf_counter() - t0
    if ret == C.PMMG_LOWFAILURE:
        # a conforming mesh was produced despite the partial failure —
        # save it and exit nonzero (the reference CLI's LOWFAILURE path)
        otrace.log(0, "adaptation INCOMPLETE (low failure): saving "
                      "the last conforming mesh", err=True)
        if not args.noout:
            _save_outputs(pm, args)
        return ret
    if ret != C.PMMG_SUCCESS:
        otrace.log(0, f"adaptation FAILED ({ret})", err=True)
        return ret

    if args.verbose >= C.PMMG_VERB_QUAL or args.bench_json:
        _report(pm, dt, args.bench_json)

    if not args.noout:
        _save_outputs(pm, args)
    return 0


def _parse_parfile(path):
    """Parse an Mmg local-parameter file:

        Parameters
        <n>
        <ref> <Triangle|Vertex|...> <hmin> <hmax> <hausd>

    Returns [(typ, ref, hmin, hmax, hausd)]: typ 1 = triangles (surface
    reference patch), typ 2 = tetrahedra (volume sub-domain by tref),
    typ 3 = edges (user edge list by ref), typ 0 = vertices (by point
    ref); other entity types warn and are skipped."""
    typ_map = {"triangle": 1, "triangles": 1,
               "tetrahedron": 2, "tetrahedra": 2, "tetrahedrons": 2,
               "edge": 3, "edges": 3, "ridge": 3,
               "vertex": 0, "vertices": 0}
    out = []
    lines = [ln.strip() for ln in path.read_text().splitlines()
             if ln.strip() and not ln.strip().startswith("#")]
    i = 0
    while i < len(lines):
        if lines[i].lower().startswith("parameters"):
            n = int(lines[i + 1].split()[0])
            for j in range(n):
                tok = lines[i + 2 + j].split()
                typ = typ_map.get(tok[1].lower())
                if typ is None:
                    otrace.log(0, "  ## Warning: unsupported local-"
                                  f"parameter type '{tok[1]}' in "
                                  f"{path}; entry skipped.", err=True)
                    continue
                out.append((typ, int(tok[0]),
                            float(tok[2]), float(tok[3]), float(tok[4])))
            i += 2 + n
        else:
            i += 1
    return out


def _concat_shards(parts):
    """Reassemble distributed shard files into one mesh + the per-tet
    source-shard labels.  The labels preserve the CALLER'S partition so
    the distributed run adopts it instead of re-partitioning from
    scratch — the reference's distributed entry keeps the input
    decomposition and only rebuilds communicators (libparmmg.c:206-329).
    """
    from .io.medit import MeditMesh
    m = MeditMesh()
    off = 0
    vs, vr, ts, tr, src = [], [], [], [], []
    for k, p in enumerate(parts):
        vs.append(p.vert); vr.append(p.vref)
        ts.append(p.tetra + off); tr.append(p.tref)
        src.append(np.full(len(p.tetra), k, np.int32))
        off += len(p.vert)
    m.vert = np.concatenate(vs)
    m.vref = np.concatenate(vr)
    m.tetra = np.concatenate(ts)
    m.tref = np.concatenate(tr)
    m.src_part = np.concatenate(src)
    # duplicate interface vertices are deduplicated by the core merge on
    # exact coordinates at run() time via analysis; cheap dedup here:
    uniq, inv = np.unique(m.vert.round(12), axis=0, return_inverse=True)
    if len(uniq) < len(m.vert):
        first = np.zeros(len(uniq), np.int64)
        seen = np.full(len(uniq), -1, np.int64)
        for i, k in enumerate(inv):
            if seen[k] < 0:
                seen[k] = i
        m.tetra = seen[inv[m.tetra]].astype(np.int32)
        keep = np.zeros(len(m.vert), bool)
        keep[seen] = True
        newid = np.cumsum(keep) - 1
        m.tetra = newid[m.tetra].astype(np.int32)
        m.vert = m.vert[keep]
        m.vref = m.vref[keep]
    return m


def _save_distributed_shards(pm, m, out, ndev):
    """True distributed output: split the adapted mesh into ndev shards
    and write ``name.<rank>.mesh`` files with ParallelVertex/Triangle
    communicator sections (inout_pmmg.c:74-486 format) — the
    checkpoint/resume contract of the reference's -distributed-output."""
    from .io.medit import MeditMesh
    from .io.distributed import save_distributed_mesh, ShardComm
    from .parallel.partition import greedy_partition, fix_contiguity
    from .parallel.comms import build_interface_comms

    tet0 = np.asarray(m.tetra, np.int64)
    # reuse the partition the distributed run just produced (it indexes
    # the compacted output tets, same order as m.tetra); fall back to a
    # fresh partition for single-device runs or mismatched shapes
    part = getattr(pm, "_out_part", None)
    if part is None or len(part) != len(tet0) or part.max() >= ndev:
        cent = m.vert[tet0].mean(axis=1)
        part = fix_contiguity(tet0, greedy_partition(tet0, cent, ndev))
    l2g = [np.unique(tet0[part == s]) for s in range(ndev)]
    g2l = []
    for s in range(ndev):
        mp = np.full(len(m.vert), -1, np.int64)
        mp[l2g[s]] = np.arange(len(l2g[s]))
        g2l.append(mp)
    comms = build_interface_comms(tet0, part, ndev, l2g, g2l)

    # boundary-triangle ownership: a triangle belongs to the shard that
    # owns its adjacent tetrahedron (vertex membership alone can assign a
    # fully-on-interface surface triangle to a shard with no matching tet
    # face)
    tglob = np.asarray(m.tria, np.int64) if len(m.tria) else \
        np.zeros((0, 3), np.int64)
    tri_tet = getattr(m, "tria_tet", None)
    if tri_tet is not None and len(tri_tet) == len(tglob):
        tri_owner = part[np.asarray(tri_tet, np.int64)]
    else:
        tri_owner = np.full(len(tglob), -1, np.int64)
        for s in range(ndev):
            inside = (g2l[s][tglob] >= 0).all(axis=1) if len(tglob) else \
                np.zeros(0, bool)
            tri_owner[inside] = s

    for s in range(ndev):
        sh = MeditMesh()
        sh.vert = m.vert[l2g[s]]
        sh.vref = m.vref[l2g[s]]
        sel = part == s
        sh.tetra = g2l[s][tet0[sel]].astype(np.int32)
        sh.tref = m.tref[sel]
        # shard triangle list: interface faces (from the comm tables, in
        # table order so comm items can reference them by position),
        # then the shard's share of the true boundary triangles
        tris, trefs = [], []
        face_comms, node_comms = [], []
        from .core.constants import IDIR
        for k in range(comms.nbr.shape[1]):
            b = int(comms.nbr[s, k])
            if b < 0:
                continue
            nf = int(comms.face_cnt[s, k])
            fidx = comms.face_idx[s, k, :nf]        # 4*local_tet+face
            lt, lf = fidx // 4, fidx % 4
            fv = sh.tetra[lt][np.arange(nf)[:, None], np.asarray(IDIR)[lf]]
            first = sum(len(t) for t in tris)
            tris.append(fv)
            trefs.append(np.zeros(nf, np.int32))
            local_ids = np.arange(first + 1, first + nf + 1)
            # global face id: stable across both sides = sorted global
            # vertex triple encoded
            gfv = np.sort(l2g[s][fv], axis=1)
            gid = (gfv[:, 0] << 42) | (gfv[:, 1] << 21) | gfv[:, 2]
            face_comms.append(ShardComm(b, local_ids, gid))
            nn = int(comms.node_cnt[s, k])
            nidx = comms.node_idx[s, k, :nn]
            node_comms.append(ShardComm(
                b, nidx + 1, l2g[s][nidx] + 1))
        if len(tglob):
            # true boundary triangles owned by this shard
            mine = tri_owner == s
            tl = g2l[s][tglob[mine]].astype(np.int32)
            tris.append(tl)
            trefs.append(m.triaref[mine])
        if tris:
            sh.tria = np.concatenate(tris).astype(np.int32)
            sh.triaref = np.concatenate(trefs)
        save_distributed_mesh(out, s, sh, face_comms, node_comms)


def _report(pm, dt, as_json):
    from .ops.quality import tet_quality
    import jax.numpy as jnp
    q = np.asarray(tet_quality(pm._out, pm._out_met))
    tm = np.asarray(pm._out.tmask)
    st = pm.stats
    rec = {
        "ntets": int(tm.sum()),
        "qmin": float(q[tm].min()) if tm.any() else 0.0,
        "qmean": float(q[tm].mean()) if tm.any() else 0.0,
        "nsplit": st.nsplit if st else 0,
        "ncollapse": st.ncollapse if st else 0,
        "nswap": st.nswap if st else 0,
        "wall_s": round(dt, 3),
    }
    if as_json:
        # lint: ok(R3) — -bench-json stdout contract (machine-readable
        # record consumed by bench tooling; must not be gated)
        print(json.dumps(rec))
    else:
        otrace.log(0, f"  #tets {rec['ntets']}  quality min "
                      f"{rec['qmin']:.4f} mean {rec['qmean']:.4f}  "
                      f"ops s/c/w {rec['nsplit']}/{rec['ncollapse']}"
                      f"/{rec['nswap']}  {rec['wall_s']}s")


def _save_outputs(pm, args):
    from .io.medit import MeditMesh, write_mesh, write_sol, SOL_SCALAR, \
        SOL_TENSOR
    from .io.vtk import write_vtu, write_pvtu
    out = Path(args.out) if args.out else \
        Path(args.inp).with_name(Path(args.inp).stem + ".o.mesh")

    vert, vref = pm.get_vertices()
    tet, tref = pm.get_tetrahedra()
    tris, trefs = pm.get_triangles()

    if out.suffix in (".vtu", ".pvtu"):
        vtu = write_vtu(out.with_suffix(".vtu"), vert, tet - 1)
        if out.suffix == ".pvtu":
            write_pvtu(out, [vtu])
        return

    m = MeditMesh()
    m.vert, m.vref = vert, vref
    m.tetra, m.tref = tet - 1, tref
    m.tria, m.triaref = tris - 1, trefs
    m.tria_tet = pm._out_triangles()[3]     # adjacent-tet provenance
    # boundary entity sections (Edges/Ridges/Corners/RequiredVertices),
    # rebuilt from the adapted tags like the reference bdryBuild output
    edges, erefs, eridge, ereq = pm.get_edges()
    if len(edges):
        m.edges, m.edgeref = edges - 1, erefs
        m.ridges = np.flatnonzero(eridge).astype(np.int32)
        m.required_edges = np.flatnonzero(ereq).astype(np.int32)
    _, _, _, _, vtag = pm._out_host()
    m.corners = np.flatnonzero(vtag & C.MG_CRN).astype(np.int32)
    m.required_vert = np.flatnonzero(
        ((vtag & C.MG_REQ) != 0) & ((vtag & C.MG_PARBDY) == 0)
    ).astype(np.int32)
    if args.dist_out:
        from .io.distributed import save_distributed_mesh
        ndev = pm.info.n_devices
        if ndev > 1:
            _save_distributed_shards(pm, m, out, ndev)
        else:
            save_distributed_mesh(out, 0, m)
    else:
        write_mesh(out, m)
    met = pm.get_metric()
    if met is not None:
        write_sol(out.with_suffix(".sol"),
                  met.reshape(len(vert), -1),
                  [SOL_TENSOR if met.ndim == 2 and met.shape[1] == 6
                   else SOL_SCALAR])


if __name__ == "__main__":
    sys.exit(main())
