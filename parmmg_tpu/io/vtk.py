"""VTK XML output: .vtu per shard + .pvtu master (pure Python).

Equivalent of the reference's VTK path (inoutcpp_pmmg.cpp:44-116,
``PMMG_savePvtuMesh`` writing parallel .pvtu through Mmg's VTK templates)
without the VTK library: we emit ascii VTU XML directly.
"""
from __future__ import annotations

from pathlib import Path

import numpy as np

_VTK_TETRA = 10


def write_vtu(path: str | Path, vert: np.ndarray, tet: np.ndarray,
              point_data: dict[str, np.ndarray] | None = None,
              cell_data: dict[str, np.ndarray] | None = None) -> Path:
    path = Path(path)
    n_p, n_c = len(vert), len(tet)
    lines = []
    a = lines.append
    a('<?xml version="1.0"?>')
    a('<VTKFile type="UnstructuredGrid" version="0.1" '
      'byte_order="LittleEndian">')
    a('  <UnstructuredGrid>')
    a(f'    <Piece NumberOfPoints="{n_p}" NumberOfCells="{n_c}">')
    a('      <Points>')
    a('        <DataArray type="Float64" NumberOfComponents="3" '
      'format="ascii">')
    for p in np.asarray(vert, np.float64):
        a(f"          {p[0]:.17g} {p[1]:.17g} {p[2]:.17g}")
    a('        </DataArray>')
    a('      </Points>')
    a('      <Cells>')
    a('        <DataArray type="Int64" Name="connectivity" format="ascii">')
    for t in np.asarray(tet, np.int64):
        a("          " + " ".join(map(str, t)))
    a('        </DataArray>')
    a('        <DataArray type="Int64" Name="offsets" format="ascii">')
    a("          " + " ".join(str(4 * (i + 1)) for i in range(n_c)))
    a('        </DataArray>')
    a('        <DataArray type="UInt8" Name="types" format="ascii">')
    a("          " + " ".join([str(_VTK_TETRA)] * n_c))
    a('        </DataArray>')
    a('      </Cells>')

    def data_block(tag, data):
        if not data:
            return
        a(f'      <{tag}>')
        for name, arr in data.items():
            arr = np.asarray(arr)
            nc = 1 if arr.ndim == 1 else arr.shape[1]
            a(f'        <DataArray type="Float64" Name="{name}" '
              f'NumberOfComponents="{nc}" format="ascii">')
            for row in arr.reshape(len(arr), -1):
                a("          " + " ".join(f"{x:.17g}" for x in row))
            a('        </DataArray>')
        a(f'      </{tag}>')

    data_block("PointData", point_data)
    data_block("CellData", cell_data)
    a('    </Piece>')
    a('  </UnstructuredGrid>')
    a('</VTKFile>')
    path.write_text("\n".join(lines) + "\n")
    return path


def write_pvtu(path: str | Path, piece_files: list[str | Path],
               point_data: dict[str, int] | None = None,
               cell_data: dict[str, int] | None = None) -> Path:
    """Master file referencing per-shard .vtu pieces
    (PMMG_savePvtuMesh analogue).  ``point_data``/``cell_data`` map field
    name -> number of components."""
    path = Path(path)
    lines = []
    a = lines.append
    a('<?xml version="1.0"?>')
    a('<VTKFile type="PUnstructuredGrid" version="0.1" '
      'byte_order="LittleEndian">')
    a('  <PUnstructuredGrid GhostLevel="0">')
    a('    <PPoints>')
    a('      <PDataArray type="Float64" NumberOfComponents="3"/>')
    a('    </PPoints>')
    for tag, data in (("PPointData", point_data),
                      ("PCellData", cell_data)):
        if data:
            a(f'    <{tag}>')
            for name, nc in data.items():
                a(f'      <PDataArray type="Float64" Name="{name}" '
                  f'NumberOfComponents="{nc}"/>')
            a(f'    </{tag}>')
    for f in piece_files:
        a(f'    <Piece Source="{Path(f).name}"/>')
    a('  </PUnstructuredGrid>')
    a('</VTKFile>')
    path.write_text("\n".join(lines) + "\n")
    return path
