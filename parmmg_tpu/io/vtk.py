"""VTK XML I/O: .vtu read + .vtu/.pvtu write (pure Python).

Equivalent of the reference's VTK path (inoutcpp_pmmg.cpp:44-116:
``PMMG_loadVtuMesh_centralized`` reading a centralized .vtu through
Mmg's VTK templates, ``PMMG_savePvtuMesh`` writing parallel .pvtu)
without the VTK library: ascii VTU XML emitted/parsed directly.
"""
from __future__ import annotations

from pathlib import Path

import numpy as np

_VTK_TETRA = 10
_VTK_TRIANGLE = 5
_VTK_LINE = 3


def write_vtu(path: str | Path, vert: np.ndarray, tet: np.ndarray,
              point_data: dict[str, np.ndarray] | None = None,
              cell_data: dict[str, np.ndarray] | None = None) -> Path:
    path = Path(path)
    n_p, n_c = len(vert), len(tet)
    lines = []
    a = lines.append
    a('<?xml version="1.0"?>')
    a('<VTKFile type="UnstructuredGrid" version="0.1" '
      'byte_order="LittleEndian">')
    a('  <UnstructuredGrid>')
    a(f'    <Piece NumberOfPoints="{n_p}" NumberOfCells="{n_c}">')
    a('      <Points>')
    a('        <DataArray type="Float64" NumberOfComponents="3" '
      'format="ascii">')
    for p in np.asarray(vert, np.float64):
        a(f"          {p[0]:.17g} {p[1]:.17g} {p[2]:.17g}")
    a('        </DataArray>')
    a('      </Points>')
    a('      <Cells>')
    a('        <DataArray type="Int64" Name="connectivity" format="ascii">')
    for t in np.asarray(tet, np.int64):
        a("          " + " ".join(map(str, t)))
    a('        </DataArray>')
    a('        <DataArray type="Int64" Name="offsets" format="ascii">')
    a("          " + " ".join(str(4 * (i + 1)) for i in range(n_c)))
    a('        </DataArray>')
    a('        <DataArray type="UInt8" Name="types" format="ascii">')
    a("          " + " ".join([str(_VTK_TETRA)] * n_c))
    a('        </DataArray>')
    a('      </Cells>')

    def data_block(tag, data):
        if not data:
            return
        a(f'      <{tag}>')
        for name, arr in data.items():
            arr = np.asarray(arr)
            nc = 1 if arr.ndim == 1 else arr.shape[1]
            a(f'        <DataArray type="Float64" Name="{name}" '
              f'NumberOfComponents="{nc}" format="ascii">')
            for row in arr.reshape(len(arr), -1):
                a("          " + " ".join(f"{x:.17g}" for x in row))
            a('        </DataArray>')
        a(f'      </{tag}>')

    data_block("PointData", point_data)
    data_block("CellData", cell_data)
    a('    </Piece>')
    a('  </UnstructuredGrid>')
    a('</VTKFile>')
    path.write_text("\n".join(lines) + "\n")
    return path


def read_vtu(path: str | Path):
    """Read an ascii .vtu into (vert [n,3] f64, cells dict, point_data,
    cell_data) — the ``PMMG_loadVtuMesh_centralized`` role
    (inoutcpp_pmmg.cpp:44: load a centralized VTK mesh + metric/fields).

    ``cells`` maps VTK type -> [m, k] int64 connectivity (10 = tetra
    [m,4], 5 = triangle [m,3], 3 = line [m,2]); mixed-cell files
    partition by type.  Data arrays come back as float64; cell_data rows
    follow the FILE cell order, so per-type slices align with the cells
    dict (types are returned in first-appearance order with stable
    within-type order, and a "__order__" entry gives each type's row
    indices into the original cell sequence).

    Only ascii format is supported (the writer's own format and the
    common interchange case); binary/appended raise ValueError rather
    than mis-parse.
    """
    import xml.etree.ElementTree as ET
    root = ET.parse(str(path)).getroot()
    piece = root.find(".//Piece")
    if piece is None:
        raise ValueError(f"{path}: no <Piece> in VTU")

    def arr_of(da):
        if da.get("format", "ascii") != "ascii":
            raise ValueError(
                f"{path}: only ascii VTU supported "
                f"(format={da.get('format')!r})")
        text = da.text or ""
        dt = np.float64 if da.get("type", "").startswith("Float") \
            else np.int64
        if not text.strip():
            return np.zeros(0, dt)
        return np.array(text.split(), dtype=dt)

    pts = piece.find("Points/DataArray")
    vert = arr_of(pts).astype(np.float64).reshape(-1, 3)

    conn = offs = types = None
    for da in piece.findall("Cells/DataArray"):
        nm = da.get("Name")
        if nm == "connectivity":
            conn = arr_of(da).astype(np.int64)
        elif nm == "offsets":
            offs = arr_of(da).astype(np.int64)
        elif nm == "types":
            types = arr_of(da).astype(np.int64)
    if conn is None or offs is None or types is None:
        raise ValueError(f"{path}: incomplete <Cells> block")
    starts = np.concatenate([[0], offs[:-1]])
    cells: dict[int, np.ndarray] = {}
    order: dict[int, np.ndarray] = {}
    for t, k in ((_VTK_TETRA, 4), (_VTK_TRIANGLE, 3), (_VTK_LINE, 2)):
        rows = np.where(types == t)[0]
        if len(rows):
            if not (offs[rows] - starts[rows] == k).all():
                raise ValueError(f"{path}: cell type {t} with wrong "
                                 "vertex count")
            cells[t] = conn[starts[rows][:, None]
                            + np.arange(k)[None, :]]
            order[t] = rows
    unknown = set(np.unique(types)) - {_VTK_TETRA, _VTK_TRIANGLE,
                                       _VTK_LINE}
    if unknown:
        raise ValueError(f"{path}: unsupported VTK cell types "
                         f"{sorted(unknown)}")

    def data_of(tag, n):
        out = {}
        blk = piece.find(tag)
        if blk is not None:
            for da in blk.findall("DataArray"):
                v = arr_of(da).astype(np.float64)
                nc = int(da.get("NumberOfComponents", "1"))
                out[da.get("Name", "field")] = \
                    v.reshape(n, nc) if nc > 1 else v
        return out

    point_data = data_of("PointData", len(vert))
    cell_data = data_of("CellData", len(types))
    cell_data["__order__"] = order
    return vert, cells, point_data, cell_data


def read_vtu_medit(path: str | Path):
    """.vtu -> MeditMesh (+ metric/fields), the ingest shape the CLI and
    API load path consume.  References come from an integer-valued cell
    field named like the Medit convention when present
    ("medit:ref"/"ref"/"MaterialID"); otherwise zero."""
    from .medit import MeditMesh
    vert, cells, pdata, cdata = read_vtu(path)
    order = cdata.pop("__order__", {})
    m = MeditMesh()
    m.vert = vert
    m.vref = np.zeros(len(vert), np.int32)

    def refs_for(t, n):
        short = []
        for nm in ("medit:ref", "ref", "MaterialID", "CellEntityIds"):
            if nm in cdata and len(order.get(t, ())):
                # order[t] holds row indices into the FULL cell
                # sequence: the array must cover its MAX index, not
                # just this type's count (a per-type-length array from
                # a mixed-cell producer would otherwise fancy-index
                # out of range).  A short array is skipped in favor of
                # the next candidate name (the pre-existing fallthrough
                # contract); only if NO candidate is usable does the
                # ambiguity become a hard error instead of silently
                # zeroed refs.
                if len(cdata[nm]) <= int(np.max(order[t])):
                    short.append(nm)
                    continue
                v = np.asarray(cdata[nm])[order[t]]
                if v.ndim == 1:
                    return v.astype(np.int32)
        if short:
            raise ValueError(
                f"CellData {short} shorter than the file's cell list "
                "(per-type cell-data arrays are not supported) and no "
                "full-length ref array is present")
        return np.zeros(n, np.int32)

    if _VTK_TETRA in cells:
        m.tetra = cells[_VTK_TETRA].astype(np.int32)
        m.tref = refs_for(_VTK_TETRA, len(m.tetra))
    if _VTK_TRIANGLE in cells:
        m.tria = cells[_VTK_TRIANGLE].astype(np.int32)
        m.triaref = refs_for(_VTK_TRIANGLE, len(m.tria))
    if _VTK_LINE in cells:
        m.edges = cells[_VTK_LINE].astype(np.int32)
        m.edgeref = refs_for(_VTK_LINE, len(m.edges))
    # metric conventions: a scalar "metric"/"sol" point field, or the
    # 6-component packed tensor
    met = None
    for nm in ("metric", "sol", "met"):
        if nm in pdata:
            met = pdata[nm]
            break
    fields = {k: v for k, v in pdata.items()
              if k not in ("metric", "sol", "met")}
    return m, met, fields


def write_pvtu(path: str | Path, piece_files: list[str | Path],
               point_data: dict[str, int] | None = None,
               cell_data: dict[str, int] | None = None) -> Path:
    """Master file referencing per-shard .vtu pieces
    (PMMG_savePvtuMesh analogue).  ``point_data``/``cell_data`` map field
    name -> number of components."""
    path = Path(path)
    lines = []
    a = lines.append
    a('<?xml version="1.0"?>')
    a('<VTKFile type="PUnstructuredGrid" version="0.1" '
      'byte_order="LittleEndian">')
    a('  <PUnstructuredGrid GhostLevel="0">')
    a('    <PPoints>')
    a('      <PDataArray type="Float64" NumberOfComponents="3"/>')
    a('    </PPoints>')
    for tag, data in (("PPointData", point_data),
                      ("PCellData", cell_data)):
        if data:
            a(f'    <{tag}>')
            for name, nc in data.items():
                a(f'      <PDataArray type="Float64" Name="{name}" '
                  f'NumberOfComponents="{nc}"/>')
            a(f'    </{tag}>')
    for f in piece_files:
        a(f'    <Piece Source="{Path(f).name}"/>')
    a('  </PUnstructuredGrid>')
    a('</VTKFile>')
    path.write_text("\n".join(lines) + "\n")
    return path
