"""Medit .mesh/.meshb and .sol/.solb reader/writer (pure Python).

Covers the format surface the reference handles through Mmg's I/O plus the
ParMmg distributed extensions (/root/reference/src/inout_pmmg.c):
- ASCII ``.mesh`` with Vertices/Tetrahedra/Triangles/Edges/Corners/
  RequiredVertices/Ridges/RequiredTriangles sections;
- binary ``.meshb`` (GMF format: int code table, little/big endian);
- ``.sol``/``.solb`` metric & field files (scalar / vector / sym tensor);
- the distributed extensions ``ParallelTriangleCommunicators`` /
  ``ParallelVertexCommunicators`` and rank-decorated filenames
  ``name.<rank>.mesh`` (inout_pmmg.c:74-486) are in io/distributed.py.
"""
from __future__ import annotations

import struct
from pathlib import Path

import numpy as np

# GMF keyword codes (libmeshb v7) — subset we support
_KW = {
    "MeshVersionFormatted": 1,
    "Dimension": 3,
    "Vertices": 4,
    "Edges": 5,
    "Triangles": 6,
    "Quadrilaterals": 7,
    "Tetrahedra": 8,
    "Corners": 13,
    "RequiredVertices": 15,
    "Ridges": 14,
    "RequiredEdges": 16,
    "RequiredTriangles": 17,
    "Normals": 60,
    "SolAtVertices": 62,
    "End": 54,
}
_KW_INV = {v: k for k, v in _KW.items()}

SOL_SCALAR = 1
SOL_VECTOR = 2
SOL_TENSOR = 3
_SOL_NCOMP = {SOL_SCALAR: 1, SOL_VECTOR: 3, SOL_TENSOR: 6}


class MeditMesh:
    """Host-side container for everything a Medit file can carry."""

    def __init__(self):
        self.vert = np.zeros((0, 3), np.float64)
        self.vref = np.zeros(0, np.int32)
        self.tetra = np.zeros((0, 4), np.int32)   # 0-based
        self.tref = np.zeros(0, np.int32)
        self.tria = np.zeros((0, 3), np.int32)
        self.triaref = np.zeros(0, np.int32)
        self.edges = np.zeros((0, 2), np.int32)
        self.edgeref = np.zeros(0, np.int32)
        self.corners = np.zeros(0, np.int32)
        self.required_vert = np.zeros(0, np.int32)
        self.ridges = np.zeros(0, np.int32)       # edge indices (into edges)
        self.required_tria = np.zeros(0, np.int32)
        self.required_edges = np.zeros(0, np.int32)


def read_mesh(path: str | Path) -> MeditMesh:
    path = Path(path)
    if path.suffix == ".meshb":
        return _read_meshb(path)
    # native fast path for the common Vertices/Tetrahedra/Triangles case;
    # files with additional sections fall back to the Python tokenizer
    try:
        txt = path.read_text()
        simple = not any(
            k in txt for k in ("Edges", "Corners", "Required", "Ridges",
                               "Parallel", "Normals"))
        if simple:
            from .. import native
            if native.available():
                got = native.scan_medit(path)
                m = MeditMesh()
                m.vert, m.vref = got["vert"], got["vref"]
                m.tetra, m.tref = got["tet"], got["tref"]
                m.tria, m.triaref = got["tria"], got["triaref"]
                return m
    except Exception:
        pass
    return _read_mesh_ascii(path)


def _tokens(path: Path):
    with open(path) as f:
        for line in f:
            line = line.split("#")[0]
            yield from line.split()


def _read_mesh_ascii(path: Path) -> MeditMesh:
    m = MeditMesh()
    it = _tokens(path)
    tok = next(it, None)
    while tok is not None:
        kw = tok
        if kw == "End":
            break
        if kw in ("MeshVersionFormatted", "Dimension"):
            next(it)
        elif kw == "Vertices":
            n = int(next(it))
            dat = np.fromiter((next(it) for _ in range(4 * n)), float,
                              count=4 * n).reshape(n, 4)
            m.vert = dat[:, :3]
            m.vref = dat[:, 3].astype(np.int32)
        elif kw == "Tetrahedra":
            n = int(next(it))
            dat = np.fromiter((next(it) for _ in range(5 * n)), float,
                              count=5 * n).reshape(n, 5).astype(np.int64)
            m.tetra = (dat[:, :4] - 1).astype(np.int32)
            m.tref = dat[:, 4].astype(np.int32)
        elif kw == "Triangles":
            n = int(next(it))
            dat = np.fromiter((next(it) for _ in range(4 * n)), float,
                              count=4 * n).reshape(n, 4).astype(np.int64)
            m.tria = (dat[:, :3] - 1).astype(np.int32)
            m.triaref = dat[:, 3].astype(np.int32)
        elif kw == "Edges":
            n = int(next(it))
            dat = np.fromiter((next(it) for _ in range(3 * n)), float,
                              count=3 * n).reshape(n, 3).astype(np.int64)
            m.edges = (dat[:, :2] - 1).astype(np.int32)
            m.edgeref = dat[:, 2].astype(np.int32)
        elif kw == "Corners":
            n = int(next(it))
            m.corners = np.fromiter((next(it) for _ in range(n)), float,
                                    count=n).astype(np.int64).astype(np.int32) - 1
        elif kw == "RequiredVertices":
            n = int(next(it))
            m.required_vert = np.fromiter((next(it) for _ in range(n)), float,
                                          count=n).astype(np.int64).astype(np.int32) - 1
        elif kw == "Ridges":
            n = int(next(it))
            m.ridges = np.fromiter((next(it) for _ in range(n)), float,
                                   count=n).astype(np.int64).astype(np.int32) - 1
        elif kw == "RequiredEdges":
            n = int(next(it))
            m.required_edges = np.fromiter((next(it) for _ in range(n)), float,
                                           count=n).astype(np.int64).astype(np.int32) - 1
        elif kw == "RequiredTriangles":
            n = int(next(it))
            m.required_tria = np.fromiter((next(it) for _ in range(n)), float,
                                          count=n).astype(np.int64).astype(np.int32) - 1
        elif kw in ("ParallelTriangleCommunicators",
                    "ParallelVertexCommunicators"):
            # distributed extensions: consumed by io.distributed, which
            # re-reads the file; here skip the whole section
            ncomm = int(next(it))
            nit_tot = 0
            for _ in range(ncomm):
                next(it)                    # color
                nit_tot += int(next(it))    # nitem
            for _ in range(2 * nit_tot):
                next(it)
        else:
            # unknown section: assume "n" then n lines we cannot size — bail
            raise ValueError(f"unsupported Medit keyword: {kw}")
        tok = next(it, None)
    return m


def write_mesh(path: str | Path, m: MeditMesh) -> None:
    path = Path(path)
    if path.suffix == ".meshb":
        _write_meshb(path, m)
        return
    with open(path, "w") as f:
        f.write("MeshVersionFormatted 2\n\nDimension 3\n\n")
        f.write(f"Vertices\n{len(m.vert)}\n")
        for p, r in zip(m.vert, m.vref):
            f.write(f"{p[0]:.15g} {p[1]:.15g} {p[2]:.15g} {int(r)}\n")
        if len(m.tetra):
            f.write(f"\nTetrahedra\n{len(m.tetra)}\n")
            for t, r in zip(m.tetra + 1, m.tref):
                f.write(f"{t[0]} {t[1]} {t[2]} {t[3]} {int(r)}\n")
        if len(m.tria):
            f.write(f"\nTriangles\n{len(m.tria)}\n")
            for t, r in zip(m.tria + 1, m.triaref):
                f.write(f"{t[0]} {t[1]} {t[2]} {int(r)}\n")
        if len(m.edges):
            f.write(f"\nEdges\n{len(m.edges)}\n")
            for e, r in zip(m.edges + 1, m.edgeref):
                f.write(f"{e[0]} {e[1]} {int(r)}\n")
        for name, arr in [("Corners", m.corners),
                          ("RequiredVertices", m.required_vert),
                          ("Ridges", m.ridges),
                          ("RequiredEdges", m.required_edges),
                          ("RequiredTriangles", m.required_tria)]:
            if len(arr):
                f.write(f"\n{name}\n{len(arr)}\n")
                f.write("\n".join(str(int(i) + 1) for i in arr) + "\n")
        f.write("\nEnd\n")


# ---------------------------------------------------------------------------
# Binary GMF (.meshb) — version 2 (int32 positions) or 3 (int64), dim 3
# ---------------------------------------------------------------------------
def _read_meshb(path: Path) -> MeditMesh:
    data = path.read_bytes()
    (magic,) = struct.unpack_from("<i", data, 0)
    if magic == 1:
        en = "<"
    else:
        (magic_b,) = struct.unpack_from(">i", data, 0)
        if magic_b != 1:
            raise ValueError("not a meshb file")
        en = ">"
    (ver,) = struct.unpack_from(en + "i", data, 4)
    pos_fmt = "i" if ver < 3 else "q"
    pos_size = 4 if ver < 3 else 8
    flt = "f" if ver == 1 else "d"
    flt_size = 4 if ver == 1 else 8
    m = MeditMesh()
    off = 8

    def read_i(o):
        return struct.unpack_from(en + "i", data, o)[0], o + 4

    def read_pos(o):
        return struct.unpack_from(en + pos_fmt, data, o)[0], o + pos_size

    while off < len(data):
        kw, off = read_i(off)
        if kw == _KW["End"] or kw == 0:
            break
        nxt, off = read_pos(off)
        name = _KW_INV.get(kw)
        if name == "Dimension":
            _, off = read_i(off)
        elif name == "Vertices":
            n, off = read_i(off)
            rec = np.frombuffer(data, dtype=np.dtype(
                [("xyz", en + flt, 3), ("ref", en + "i")]), count=n,
                offset=off)
            m.vert = rec["xyz"].astype(np.float64)
            m.vref = rec["ref"].astype(np.int32)
            off += n * (3 * flt_size + 4)
        elif name in ("Tetrahedra", "Triangles", "Edges"):
            nv = {"Tetrahedra": 4, "Triangles": 3, "Edges": 2}[name]
            n, off = read_i(off)
            rec = np.frombuffer(data, dtype=np.dtype(
                [("v", en + "i", nv), ("ref", en + "i")]), count=n,
                offset=off)
            ids = rec["v"].astype(np.int32) - 1
            refs = rec["ref"].astype(np.int32)
            if name == "Tetrahedra":
                m.tetra, m.tref = ids, refs
            elif name == "Triangles":
                m.tria, m.triaref = ids, refs
            else:
                m.edges, m.edgeref = ids, refs
            off += n * (nv + 1) * 4
        elif name in ("Corners", "RequiredVertices", "Ridges",
                      "RequiredEdges", "RequiredTriangles"):
            n, off = read_i(off)
            arr = np.frombuffer(data, dtype=en + "i", count=n,
                                offset=off).astype(np.int32) - 1
            setattr(m, {"Corners": "corners",
                        "RequiredVertices": "required_vert",
                        "Ridges": "ridges",
                        "RequiredEdges": "required_edges",
                        "RequiredTriangles": "required_tria"}[name], arr)
            off += n * 4
        else:
            if nxt <= off or nxt > len(data):
                break
            off = nxt
    return m


def _write_meshb(path: Path, m: MeditMesh) -> None:
    out = bytearray()
    en = "<"

    def w(fmt, *vals):
        out.extend(struct.pack(en + fmt, *vals))

    w("ii", 1, 2)            # magic, version 2 (float64, int32 positions)
    w("ii", _KW["Dimension"], 0)
    # patch "next" later is optional (0 = unknown) — readers scan sequentially
    w("i", 3)
    w("ii", _KW["Vertices"], 0)
    w("i", len(m.vert))
    rec = np.zeros(len(m.vert), dtype=np.dtype(
        [("xyz", en + "d", 3), ("ref", en + "i")]))
    rec["xyz"] = m.vert
    rec["ref"] = m.vref
    out.extend(rec.tobytes())
    for name, ids, refs in [("Tetrahedra", m.tetra, m.tref),
                            ("Triangles", m.tria, m.triaref),
                            ("Edges", m.edges, m.edgeref)]:
        if len(ids):
            w("ii", _KW[name], 0)
            w("i", len(ids))
            nv = ids.shape[1]
            rec = np.zeros(len(ids), dtype=np.dtype(
                [("v", en + "i", nv), ("ref", en + "i")]))
            rec["v"] = ids + 1
            rec["ref"] = refs
            out.extend(rec.tobytes())
    for name, attr in [("Corners", "corners"),
                       ("RequiredVertices", "required_vert"),
                       ("Ridges", "ridges"),
                       ("RequiredEdges", "required_edges"),
                       ("RequiredTriangles", "required_tria")]:
        arr = getattr(m, attr)
        if len(arr):
            w("ii", _KW[name], 0)
            w("i", len(arr))
            out.extend((np.asarray(arr, np.int32) + 1).tobytes())
    w("ii", _KW["End"], 0)
    path.write_bytes(bytes(out))


# ---------------------------------------------------------------------------
# .sol files
# ---------------------------------------------------------------------------
def read_sol(path: str | Path):
    """Returns (values [n, ncomp_total], types list[int])."""
    path = Path(path)
    if path.suffix == ".solb":
        return _read_solb(path)
    it = _tokens(path)
    types, n = [], 0
    tok = next(it, None)
    while tok is not None:
        if tok == "End":
            break
        if tok in ("MeshVersionFormatted", "Dimension"):
            next(it)
        elif tok == "SolAtVertices":
            n = int(next(it))
            ntyp = int(next(it))
            types = [int(next(it)) for _ in range(ntyp)]
            ncomp = sum(_SOL_NCOMP[t] for t in types)
            vals = np.fromiter((next(it) for _ in range(n * ncomp)), float,
                               count=n * ncomp).reshape(n, ncomp)
            return vals, types
        else:
            raise ValueError(f"unsupported sol keyword {tok}")
        tok = next(it, None)
    raise ValueError("no SolAtVertices section")


def write_sol(path: str | Path, vals: np.ndarray, types: list[int]) -> None:
    path = Path(path)
    vals = np.atleast_2d(np.asarray(vals, np.float64))
    if vals.shape[0] == 1 and vals.shape[1] > 1 and sum(
            _SOL_NCOMP[t] for t in types) == 1:
        vals = vals.T
    if path.suffix == ".solb":
        _write_solb(path, vals, types)
        return
    with open(path, "w") as f:
        f.write("MeshVersionFormatted 2\n\nDimension 3\n\n")
        f.write(f"SolAtVertices\n{vals.shape[0]}\n")
        f.write(f"{len(types)} " + " ".join(str(t) for t in types) + "\n")
        for row in vals:
            f.write(" ".join(f"{v:.15g}" for v in row) + "\n")
        f.write("\nEnd\n")


def _read_solb(path: Path):
    data = path.read_bytes()
    (magic,) = struct.unpack_from("<i", data, 0)
    en = "<" if magic == 1 else ">"
    (ver,) = struct.unpack_from(en + "i", data, 4)
    pos_fmt, pos_size = ("i", 4) if ver < 3 else ("q", 8)
    flt = "f" if ver == 1 else "d"
    flt_size = 4 if ver == 1 else 8
    off = 8
    while off < len(data):
        (kw,) = struct.unpack_from(en + "i", data, off)
        off += 4
        if kw == _KW["End"] or kw == 0:
            break
        off += pos_size
        if kw == _KW["Dimension"]:
            off += 4
        elif kw == _KW["SolAtVertices"]:
            n, ntyp = struct.unpack_from(en + "ii", data, off)
            off += 8
            types = list(struct.unpack_from(en + f"{ntyp}i", data, off))
            off += 4 * ntyp
            ncomp = sum(_SOL_NCOMP[t] for t in types)
            vals = np.frombuffer(data, en + flt, count=n * ncomp,
                                 offset=off).reshape(n, ncomp).astype(np.float64)
            return vals, types
        else:
            raise ValueError(f"unsupported solb keyword {kw}")
    raise ValueError("no SolAtVertices section")


def _write_solb(path: Path, vals: np.ndarray, types: list[int]) -> None:
    out = bytearray()
    en = "<"
    out.extend(struct.pack(en + "ii", 1, 2))
    out.extend(struct.pack(en + "iii", _KW["Dimension"], 0, 3))
    out.extend(struct.pack(en + "ii", _KW["SolAtVertices"], 0))
    out.extend(struct.pack(en + "ii", vals.shape[0], len(types)))
    out.extend(struct.pack(en + f"{len(types)}i", *types))
    out.extend(np.asarray(vals, en + "f8").tobytes())
    out.extend(struct.pack(en + "ii", _KW["End"], 0))
    path.write_bytes(bytes(out))
