"""Distributed Medit I/O: per-shard mesh files + communicator sections.

Reproduces the reference's distributed format capability
(/root/reference/src/inout_pmmg.c): each shard writes
``name.<rank>.mesh[b]`` (filename decoration ``PMMG_insert_rankIndex``,
inout_pmmg.c:387) containing its submesh plus custom Medit sections
describing the parallel interfaces:

    ParallelTriangleCommunicators        (or ParallelVertexCommunicators)
    <ncomm>
    <color_out_0> <nitem_0>
    ...
    # then, per communicator, nitem lines of
    <local id> <global id>

(The reference stores (local, global, icomm) triples after per-comm
color/size headers, inout_pmmg.c:74-186; grouping the triples per comm is
the same information.)  This doubles as the framework's checkpoint/resume
format, exactly like the reference's ``-distributed-output`` round-trip CI
tests (SURVEY §5 checkpoint note).
"""
from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from .medit import MeditMesh, read_mesh, write_mesh


@dataclasses.dataclass
class ShardComm:
    """One external communicator of a shard (PMMG_Ext_comm analogue)."""
    color_out: int                  # neighbor shard id
    local: np.ndarray               # local entity ids (1-based, Medit-style)
    global_: np.ndarray             # global entity ids


def insert_rank_index(path: str | Path, rank: int) -> Path:
    """name.mesh -> name.<rank>.mesh (PMMG_insert_rankIndex flavor)."""
    p = Path(path)
    return p.with_name(f"{p.stem}.{rank}{p.suffix}")


def save_distributed_mesh(path: str | Path, rank: int, m: MeditMesh,
                          face_comms: list[ShardComm] | None = None,
                          node_comms: list[ShardComm] | None = None) -> Path:
    """Write one shard's mesh + communicator sections."""
    out = insert_rank_index(path, rank)
    write_mesh(out, m)
    # append communicator sections to the ASCII file / as sidecar for binary
    if out.suffix == ".meshb":
        side = out.with_suffix(".comm")
        with open(side, "w") as f:
            _write_comm_sections(f, face_comms, node_comms)
    else:
        text = out.read_text()
        text = text.replace("\nEnd\n", "\n")
        with open(out, "w") as f:
            f.write(text)
            _write_comm_sections(f, face_comms, node_comms)
            f.write("End\n")
    return out


def _write_comm_sections(f, face_comms, node_comms):
    for name, comms in (("ParallelTriangleCommunicators", face_comms),
                        ("ParallelVertexCommunicators", node_comms)):
        if not comms:
            continue
        f.write(f"\n{name}\n{len(comms)}\n")
        for c in comms:
            f.write(f"{c.color_out} {len(c.local)}\n")
        for c in comms:
            for lo, gl in zip(c.local, c.global_):
                f.write(f"{int(lo)} {int(gl)}\n")


def load_distributed_mesh(path: str | Path, rank: int):
    """Read one shard file -> (MeditMesh, face_comms, node_comms)."""
    p = insert_rank_index(path, rank)
    m = read_mesh(p)
    face_comms, node_comms = [], []
    src = p.with_suffix(".comm") if p.suffix == ".meshb" else p
    if src.exists():
        face_comms = _parse_comm_section(
            src, "ParallelTriangleCommunicators")
        node_comms = _parse_comm_section(
            src, "ParallelVertexCommunicators")
    return m, face_comms, node_comms


def _parse_comm_section(path: Path, keyword: str) -> list[ShardComm]:
    toks = []
    with open(path) as f:
        txt = f.read()
    if keyword not in txt:
        return []
    toks = txt[txt.index(keyword) + len(keyword):].split()
    ncomm = int(toks[0])
    i = 1
    heads = []
    for _ in range(ncomm):
        heads.append((int(toks[i]), int(toks[i + 1])))
        i += 2
    comms = []
    for color, nit in heads:
        lo = np.zeros(nit, np.int64)
        gl = np.zeros(nit, np.int64)
        for k in range(nit):
            lo[k] = int(toks[i]); gl[k] = int(toks[i + 1])
            i += 2
        comms.append(ShardComm(color, lo, gl))
    return comms


def probe_distributed(path: str | Path, rank: int = 0) -> bool:
    """Centralized-vs-distributed input probe (parmmg.c:161-188 flavor):
    True if the rank-decorated file exists."""
    return insert_rank_index(path, rank).exists()


# ---------------------------------------------------------------------------
# device-state writer: stacked shards -> rank files, no merge
# ---------------------------------------------------------------------------
# ONE module-level jitted compaction program + compile-ledger
# registration (the check_interface_echo caching pattern): the writers
# run once per checkpoint in the steady-state loop, and a per-call jit
# object here would recompile the renumbering for every write.
_WRITER_PROG = []


def writer_tables():
    """Cached jitted shard-compaction program for the distributed
    writers: per shard, the dense->compact vertex renumbering, the
    renumbered connectivity, and the live counts.

    Returns fn(vmask [S,capP], tmask [S,capT], tet [S,capT,4]) ->
      (new_id [S,capP] (-1 dead), tet_l [S,capT,4] (-1 dead rows),
       nvert [S], ntet [S])."""
    if not _WRITER_PROG:
        import jax
        import jax.numpy as jnp
        from ..utils.compilecache import governed

        @governed("io.writer_tables", budget=2)
        @jax.jit
        def prog(vmask, tmask, tet):
            capP = vmask.shape[1]
            new_id = jnp.where(
                vmask, jnp.cumsum(vmask, axis=1, dtype=jnp.int32) - 1, -1)
            sidx = jnp.arange(vmask.shape[0])[:, None, None]
            tet_l = jnp.where(
                tmask[..., None],
                new_id[sidx, jnp.clip(tet, 0, capP - 1)], -1)
            return (new_id, tet_l,
                    jnp.sum(vmask, axis=1, dtype=jnp.int32),
                    jnp.sum(tmask, axis=1, dtype=jnp.int32))

        _WRITER_PROG.append(prog)
    return _WRITER_PROG[0]


def stacked_to_distributed_files(path, stacked, comms, glo,
                                 n_shards: int,
                                 shards=None) -> list[Path]:
    """Write ``name.<rank>.mesh`` files DIRECTLY from the stacked shard
    state — the distributed-output/checkpoint path of the shard-resident
    loop: no ``merge_shards`` (the reference's -distributed-output never
    centralizes either, inout_pmmg.c:387).  Vertex communicators come
    from the live comm tables with local ids renumbered into each
    shard's compacted file numbering and globals from the session
    numbering ``glo``.

    ``shards`` selects a SUBSET of slots to write, re-ranked densely
    (slot ``shards[i]`` -> ``name.<i>.mesh``) — the multi-tenant
    serving output path (serve/driver.py): tenants sharing one stacked
    tree each write their own slot set to their own file set.  With
    ``comms=None`` no communicator sections are emitted (single-slot
    tenants have no parallel interfaces)."""
    new_id, tet_l, nvert, ntet = (np.asarray(x) for x in writer_tables()(
        stacked.vmask, stacked.tmask, stacked.tet))
    vert = np.asarray(stacked.vert)
    vref = np.asarray(stacked.vref)
    tref = np.asarray(stacked.tref)
    vmask = np.asarray(stacked.vmask)
    tmask = np.asarray(stacked.tmask)
    outs = []
    ranks = list(range(n_shards)) if shards is None \
        else [int(s) for s in shards]
    # subset writes are re-ranked densely, so communicator neighbor ids
    # must follow: color_out is remapped slot->dense rank, and a
    # neighbor OUTSIDE the subset is an error (the written file set
    # could never resolve it) — the subset must be comm-closed
    rankmap = {r: i for i, r in enumerate(ranks)}
    for i, r in enumerate(ranks):
        m = MeditMesh()
        m.vert = vert[r][vmask[r]].astype(np.float64)
        m.vref = vref[r][vmask[r]]
        m.tetra = tet_l[r][tmask[r]].astype(np.int32)
        m.tref = tref[r][tmask[r]]
        node_comms = []
        for k in range(comms.nbr.shape[1] if comms is not None else 0):
            b = int(comms.nbr[r, k])
            if b < 0:
                continue
            if b not in rankmap:
                raise ValueError(
                    f"shard {r} has a communicator to slot {b} outside "
                    f"the written subset {ranks}: the subset must be "
                    "closed under its communicators")
            cnt = int(comms.node_cnt[r, k])
            rows = comms.node_idx[r, k, :cnt]
            node_comms.append(ShardComm(
                rankmap[b], new_id[r][rows].astype(np.int64) + 1,
                np.asarray(glo[r])[rows].astype(np.int64) + 1))
        outs.append(save_distributed_mesh(path, i, m, None, node_comms))
    return outs


# ---------------------------------------------------------------------------
# shard <-> MeditMesh conversion with communicators
# ---------------------------------------------------------------------------
def shards_to_distributed_files(path, shards_host: list[dict]) -> list[Path]:
    """shards_host: list of dicts with keys vert,tet,vref,tref and optional
    tria/triaref plus 'face_comms'/'node_comms' (ShardComm lists)."""
    outs = []
    for r, sh in enumerate(shards_host):
        m = MeditMesh()
        m.vert = np.asarray(sh["vert"], np.float64)
        m.vref = np.asarray(sh.get("vref",
                                   np.zeros(len(m.vert), np.int32)))
        m.tetra = np.asarray(sh["tet"], np.int32)
        m.tref = np.asarray(sh.get("tref",
                                   np.zeros(len(m.tetra), np.int32)))
        if "tria" in sh:
            m.tria = np.asarray(sh["tria"], np.int32)
            m.triaref = np.asarray(sh.get("triaref",
                                          np.zeros(len(m.tria), np.int32)))
        outs.append(save_distributed_mesh(
            path, r, m, sh.get("face_comms"), sh.get("node_comms")))
    return outs
