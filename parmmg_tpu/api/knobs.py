"""Central registry of every ``PARMMG_*`` environment knob.

The env surface grew one knob at a time across the governor, scheduler,
halo, obs, resilience and serve layers; until this module the only
inventory was grep.  Every knob the tree reads MUST be declared here —
``scripts/lint_check.py`` (rule R4) cross-checks the registry against
the actual ``os.environ`` / ``getenv`` read sites AND against the
README knob tables, in both directions: an unregistered read fails the
lint, and so does a registered knob nothing reads (dead knob) or one
the README never mentions.

This module is import-light on purpose (stdlib only, no jax, no
numpy): the linter and host-only tests consume it, and the readers in
the hot layers keep their existing direct ``os.environ`` reads — the
registry is the *contract*, not a call-path rewrite.

``python -m parmmg_tpu.api.knobs`` prints the canonical markdown table
(the README "Environment knobs" section is generated from it; R4
verifies the two never drift).

NOTE for the R4 linter: ``KNOBS`` below must stay a single dict literal
of ``"NAME": Knob(type, default, doc)`` entries — the linter reads it
with ``ast`` (no import) so it can run jax-free in <10 s.
"""
from __future__ import annotations

import dataclasses
import os

__all__ = ["KNOBS", "Knob", "get", "knob_table_md", "registered"]


@dataclasses.dataclass(frozen=True)
class Knob:
    """One env knob: coarse value type ("int" | "float" | "str" |
    "flag" | "path" | "spec"), the default the reader applies when the
    variable is unset/empty (as the string the env would carry; "" =
    off/auto), and a one-line doc."""
    type: str
    default: str
    doc: str


KNOBS: dict[str, Knob] = {
    "PARMMG_BAND_PATH": Knob(
        "flag", "1",
        "device band-migration path; 0 = legacy host full-mesh migrate"),
    "PARMMG_BENCH_FALLBACK": Knob(
        "flag", "",
        "bench.py internal: marks a worker run that fell back to "
        "XLA:CPU so the artifact records fallback=true"),
    "PARMMG_CKPT_DIR": Knob(
        "path", "",
        "pass-checkpoint directory (resilience/checkpoint.py); unset "
        "= checkpointing off"),
    "PARMMG_CKPT_EVERY": Knob(
        "int", "1", "checkpoint every Nth outer pass"),
    "PARMMG_COLLAPSE_BAND": Knob(
        "flag", "1",
        "donor-scoped collapse apply: run the collapse tag/ref join "
        "scatters on a geo-bucketed donor band instead of full [capT] "
        "width, bit-identical by the band coverage proof "
        "(ops/collapse.py); 0 = always full width"),
    "PARMMG_CYCLE_BLOCK": Knob(
        "int", "",
        "override cycles per compiled adapt block (ops/adapt.py); "
        "empty = backend default"),
    "PARMMG_DEADLINE_DISPATCH_S": Knob(
        "float", "0",
        "watchdog deadline on each grouped chunk dispatch/drain "
        "(resilience/watchdog.py; 0 = off); expiry enters the retry "
        "ladder as WatchdogTimeout"),
    "PARMMG_DEADLINE_EXCHANGE_S": Knob(
        "float", "0",
        "watchdog deadline on each single-process gather_band "
        "exchange attempt (0 = off; cross-process hangs are the "
        "heartbeat lease's job)"),
    "PARMMG_DEADLINE_GRACE_S": Knob(
        "float", "300",
        "extra seconds granted to a site's FIRST guarded call so a "
        "cold XLA compile is not misread as a wedged warm step"),
    "PARMMG_DEADLINE_SERVE_S": Knob(
        "float", "0",
        "watchdog deadline on each serve daemon loop step (0 = off); "
        "expiry flips /healthz to wedged until the step returns"),
    "PARMMG_DEVICE_MASK": Knob(
        "flag", "1",
        "device-resident quiet masks: lax.cond-skip the wave math for "
        "quiet/pad group slots on the grouped and dist paths "
        "(parallel/sched.py); 0 = compute every slot"),
    "PARMMG_FAULT": Knob(
        "spec", "",
        "arm fault-injection sites: site[:trigger][,site...] "
        "(resilience/faults.py grammar)"),
    "PARMMG_FAULT_FORCE": Knob(
        "str", "",
        "internal parent->subprocess forcing of one fault site (the "
        "polish worker exits pre-jax on it); never set by hand"),
    "PARMMG_GROUP_CHUNK": Knob(
        "int", "",
        "groups per dispatch on the grouped path (0 = one lax.map; "
        "auto = adopt sched.recommend_group_chunk; empty = backend "
        "default, 8 on TPU)"),
    "PARMMG_GROUP_PIPELINE": Knob(
        "flag", "1",
        "double-buffer the chunk dispatches; 0 = serialize (one chunk "
        "in flight)"),
    "PARMMG_GROUP_SCHED": Knob(
        "flag", "1",
        "quiet-group scheduler on the grouped adapt path; 0 = legacy "
        "always-dispatch"),
    "PARMMG_HALO_PACK_HYST": Knob(
        "float", "0.05",
        "hysteresis margin around the packed-halo occupancy threshold "
        "(layout flips only past threshold +/- margin)"),
    "PARMMG_HALO_PACK_OCC": Knob(
        "float", "0.75",
        "measured-occupancy threshold under which the grouped halo "
        "uses the packed per-device-pair layout instead of dense"),
    "PARMMG_HEARTBEAT_LEASE_S": Knob(
        "float", "0",
        "pod supervisor default for scripts/multihost_run.py --lease: "
        "seconds without a worker heartbeat after which the pack is "
        "killed and relaunched with resume (0 = leases off)"),
    "PARMMG_HEARTBEAT_S": Knob(
        "float", "2",
        "worker heartbeat interval: minimum seconds between per-rank "
        "heartbeat touches inside hot_path sections"),
    "PARMMG_HOST_ANALYSIS": Knob(
        "flag", "",
        "1 = skip the device analysis-refresh path and always use the "
        "host fallback"),
    "PARMMG_INCR_BAND": Knob(
        "int", "",
        "override the incremental-topology dirty-band width in tets "
        "(ops/topo_incr.incr_band_width; tests/tuning); empty = one "
        "geo-ladder rung of capT//16, floor 1024"),
    "PARMMG_INCR_TOPO": Knob(
        "flag", "",
        "incremental topology maintenance: merge each wave's dirty-tet "
        "band into the retained sorted edge/face tables instead of "
        "re-sorting all 6*capT/4*capT slot keys per derivation "
        "(ops/topo_incr.py; overflow lax.cond-falls back to the full "
        "rebuild, bit-identical by the stable-sort merge proof); "
        "threaded as a traced scalar so toggling mints zero compile "
        "families; 0/unset = legacy full rebuilds"),
    "PARMMG_MH_CACHE_DIR": Knob(
        "path", "",
        "shared persistent compile-cache dir for multi-host pod "
        "workers (parallel/multihost.init_multihost): worker 0 warms, "
        "workers N+1 deserialize instead of recompiling"),
    "PARMMG_MH_COLLECTIVES": Knob(
        "str", "gloo",
        "cross-process CPU collectives implementation for the dev pod "
        "(gloo | mpi | none); ignored on real chip interconnects"),
    "PARMMG_MH_HANDOFF": Knob(
        "flag", "",
        "1 = host-to-host group handoff: rebalance logical shards "
        "across devices/processes between iterations (parallel/pod.py;"
        " off by default — reordering arrivals breaks bit-parity with "
        "the no-handoff run)"),
    "PARMMG_MH_HEARTBEAT_DIR": Knob(
        "path", "",
        "internal supervisor->worker heartbeat directory (per-rank "
        "hb.N files; scripts/multihost_run.py sets it under --lease); "
        "never set by hand"),
    "PARMMG_MH_IMBALANCE": Knob(
        "float", "0.25",
        "device load skew (max/mean - 1) above which the group "
        "handoff re-plans placement"),
    "PARMMG_MH_STRICT": Knob(
        "flag", "",
        "1 = raise on any hot-path process_allgather instead of only "
        "metering it (mh.hot_allgather_bytes tripwire)"),
    "PARMMG_NARROW_DIV": Knob(
        "int", "",
        "narrow-row budget divisor override (ops/active.py); empty = "
        "tuned default"),
    "PARMMG_PALLAS_SCORE": Knob(
        "flag", "1",
        "Pallas candidate-scoring kernels for the split/collapse/swap "
        "top-k budget prep (ops/pallas_kernels.py; dispatched on TPU "
        "only — CPU always uses the bit-identical jnp reference); "
        "0 = jnp reference everywhere"),
    "PARMMG_PALLAS_SORT": Knob(
        "flag", "",
        "Pallas radix-sort/segment engine for the edge/face/band sort "
        "sites (ops/pallas_kernels.py sort_perm/segment_first; stable "
        "LSD radix = bit-identical to the jnp argsort/lexsort "
        "reference); empty = platform-aware default like "
        "PARMMG_SWAP_FACESORT (on iff the backend is a TPU), 1/0 "
        "force"),
    "PARMMG_POLISH_SUBPROC": Knob(
        "flag", "",
        "grouped polish phase in a subprocess worker (the TPU-tunnel "
        "path); empty = only on the tpu backend"),
    "PARMMG_POLISH_TIMEOUT_S": Knob(
        "float", "0",
        "wall-clock timeout on the grouped polish subprocess worker "
        "(0 = off): expiry kills the worker, unlinks its partial "
        "output and degrades to merged_polish like a worker crash"),
    "PARMMG_PROFILE_DIR": Knob(
        "path", "",
        "arm a jax.profiler capture writing the xprof timeline into "
        "this directory"),
    "PARMMG_PROFILE_PASS": Knob(
        "spec", "0",
        "outer-pass capture window start[:stop] for "
        "PARMMG_PROFILE_DIR"),
    "PARMMG_RESUME_MAX": Knob(
        "int", "3",
        "crash-loop breaker: resume attempts into the SAME pass of "
        "the same run fingerprint before escalating to lowfailure "
        "instead of resuming again (resilience/checkpoint.crash_loop)"),
    "PARMMG_RETRY_BASE_S": Knob(
        "float", "0.05",
        "retry backoff base seconds, doubled per attempt"),
    "PARMMG_RETRY_DEADLINE_S": Knob(
        "float", "0",
        "wall-clock cap on retrying (0 = no deadline)"),
    "PARMMG_RETRY_MAX": Knob(
        "int", "2",
        "retries after the first failure on retry_call sites (0 = "
        "fail fast)"),
    "PARMMG_SERVE_AUTOSCALE": Knob(
        "flag", "1",
        "SLO-driven autoscale controller on the serving loop (bucket "
        "resizing + admission deferral); 0 = off"),
    "PARMMG_SERVE_CHUNK": Knob(
        "int", "1", "serve pool: tenants per packed cohort dispatch"),
    "PARMMG_SERVE_MAX_CAPP": Knob(
        "int", "4194304",
        "serve admission ceiling on the vertex capacity (oversize "
        "requests rejected)"),
    "PARMMG_SERVE_MAX_CAPT": Knob(
        "int", "4194304",
        "serve admission ceiling on the tet capacity"),
    "PARMMG_SERVE_MAX_INFLIGHT": Knob(
        "int", "0",
        "serve driver: max requests admitted concurrently (0 = "
        "unbounded)"),
    "PARMMG_SERVE_MAX_QUEUE": Knob(
        "int", "0",
        "admission backpressure: try_submit / daemon submits are "
        "deferred (HTTP 429) at this queue depth (0 = unbounded)"),
    "PARMMG_SERVE_MAX_RETRIES": Knob(
        "int", "2",
        "slot faults before a serve tenant is quarantined (retired "
        "FAILED, slot scrubbed)"),
    "PARMMG_SERVE_MAX_SLOTS": Knob(
        "int", "16",
        "autoscale growth ceiling on any bucket's slot count"),
    "PARMMG_SERVE_PORT": Knob(
        "int", "8077",
        "serve daemon: HTTP bind port (scripts/serve_daemon.py; 0 = "
        "ephemeral)"),
    "PARMMG_SERVE_SLO_QMIN": Knob(
        "float", "0",
        "per-tenant qmin SLO floor; retirement records an slo_ok / "
        "slo_violation verdict (0 = off)"),
    "PARMMG_SERVE_SLOTS": Knob(
        "int", "4", "serve pool: slots per capacity bucket"),
    "PARMMG_SERVE_STREAM": Knob(
        "flag", "1",
        "streaming admission: re-rent slots freed MID-STEP to queued "
        "tenants; 0 = admit between steps only"),
    "PARMMG_SERVE_STREAM_RATE": Knob(
        "float", "2",
        "serve_bench.py --stream open-loop arrival rate (tenants/sec)"),
    "PARMMG_SERVE_TARGET_P99_S": Knob(
        "float", "0",
        "autoscale latency SLO: defer admissions while observed p99 "
        "exceeds this with work queued (0 = off)"),
    "PARMMG_SERVE_TIMEOUT_S": Knob(
        "float", "0",
        "serve driver: per-request wall-clock timeout; the slot is "
        "reclaimed (0 = off)"),
    "PARMMG_SMOOTH_CADENCE": Knob(
        "flag", "1",
        "quality-triggered smoothing cadence: skip smooth_wave on a "
        "cycle whose topology counts are zero and whose previous "
        "smoothing moved nothing — an exact fixed point "
        "(ops/adapt.py); threaded as a traced scalar so toggling "
        "mints zero compile families; 0 = smooth every cycle"),
    "PARMMG_SOAK_RUNS": Knob(
        "int", "8",
        "scripts/chaos_soak.py default campaign length (seeded runs "
        "with randomized fault schedules)"),
    "PARMMG_SOAK_SEED": Knob(
        "int", "20260804",
        "scripts/chaos_soak.py campaign seed: the fault schedule is a "
        "pure function of (seed, runs)"),
    "PARMMG_SWAP_FACESORT": Knob(
        "flag", "",
        "pair swap23 candidates directly off the face-sort records, "
        "skipping the cycle-interior build_adjacency rebuild "
        "(ops/swap.py); bit-identical pairing by the argmin/argmax2 "
        "tie-break equivalence; unset = on for TPU, off elsewhere "
        "(the CPU sort costs more than the rebuild it replaces); "
        "1/0 force either path on any backend"),
    "PARMMG_TEST_CACHE": Knob(
        "flag", "",
        "1 = opt the test processes into the persistent compile cache "
        "(tests/conftest.py; default off — the XLA:CPU AOT cache is "
        "unreliable on this image)"),
    "PARMMG_TPU_PALLAS": Knob(
        "flag", "",
        "1 = force the Pallas TPU kernels (interpret mode off-TPU); "
        "0 = disable even on TPU"),
    "PARMMG_TRACE": Knob(
        "path", "",
        "append structured trace records (JSONL) to this file; unset "
        "= ring buffer only"),
    "PARMMG_TRACE_RING": Knob(
        "int", "4096", "trace ring-buffer capacity in records"),
    "PARMMG_VERBOSE": Knob(
        "int", "1",
        "process verbosity (the reference's imprim scale) gating "
        "obs.trace.log output"),
}


def registered() -> tuple[str, ...]:
    """All declared knob names, sorted."""
    return tuple(sorted(KNOBS))


def get(name: str, default: str | None = None) -> str:
    """Registry-checked ``os.environ.get``: raises ``KeyError`` on an
    undeclared knob so ad-hoc env surface cannot creep back in; falls
    back to the declared default when no override is given."""
    if name not in KNOBS:
        raise KeyError(f"undeclared PARMMG knob {name!r} — declare it "
                       "in parmmg_tpu/api/knobs.py")
    return os.environ.get(
        name, KNOBS[name].default if default is None else default)


def knob_table_md() -> str:
    """The canonical markdown knob table (README 'Environment knobs'
    section body; R4 verifies every registered name appears in README)."""
    rows = ["| knob | type | default | purpose |",
            "|---|---|---|---|"]
    for name in registered():
        k = KNOBS[name]
        rows.append(f"| `{name}` | {k.type} | "
                    f"{('`' + k.default + '`') if k.default else 'unset'}"
                    f" | {k.doc} |")
    return "\n".join(rows)


if __name__ == "__main__":
    # lint: ok(R3) — the table dump IS this module's stdout contract
    # (README generation channel)
    print(knob_table_md())
