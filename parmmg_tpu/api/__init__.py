from .params import IParam, DParam, Info           # noqa: F401
from .parmesh import ParMesh                        # noqa: F401
