"""Parameter system: the PMMG_Param enum surface + the Info block.

Mirrors the reference's public parameter API (``PMMG_Param`` IPARAM/DPARAM
enum, /root/reference/src/libparmmg.h:54-91) and the ``PMMG_Info`` struct
(libparmmgtypes.h:313-336) with the defaults of ``PMMG_Init_parameters``
(API_functions_pmmg.c:400-426).  Negative sentinels (target mesh size,
metis ratio) mean "use the built-in default and clamp hard", reproduced in
``resolve_target_mesh_size`` (reference grpsplit_pmmg.c:1589-1613).
"""
from __future__ import annotations

import dataclasses
import enum

from ..core import constants as C


class IParam(enum.IntEnum):
    """Integer parameters (libparmmg.h PMMG_IPARAM_*)."""
    verbose = 0
    mmgVerbose = 1
    mem = 2
    debug = 3
    mmgDebug = 4
    angle = 5
    iso = 6
    lag = 7
    optim = 8
    optimLES = 9
    noinsert = 10
    noswap = 11
    nomove = 12
    nosurf = 13
    numberOfLocalParam = 14
    anisosize = 15
    octree = 16
    meshSize = 17           # target per-group mesh size (-mesh-size)
    metisRatio = 18         # ratio distribution groups / remesh groups
    ifcLayers = 19          # interface displacement layers (-nlayers)
    APImode = 20            # faces(0) / nodes(1) distributed input
    globalNum = 21          # compute output global numbering
    niter = 22
    nobalancing = 23
    loadbalancingMode = 24
    repartitioningMode = 25
    nomoveMode = 26
    fem = 27
    opnbdy = 28


class DParam(enum.IntEnum):
    """Double parameters (libparmmg.h PMMG_DPARAM_*)."""
    angleDetection = 100
    hmin = 101
    hmax = 102
    hsiz = 103
    hausd = 104
    hgrad = 105
    hgradreq = 106
    ls = 107
    groupsRatio = 108


@dataclasses.dataclass
class Info:
    """Runtime parameter block (PMMG_Info analogue)."""
    # verbosity / debug
    imprim: int = 1
    mmg_imprim: int = -1
    debug: bool = False
    mmg_debug: bool = False
    # iteration control (defaults: API_functions_pmmg.c:400-426)
    niter: int = C.NITER_DEFAULT
    nobalancing: bool = False
    repartitioning: int = C.REPART_IFC_DISPLACEMENT
    loadbalancing: int = C.LB_METIS
    ifc_layers: int = C.MVIFCS_NLAYERS
    grps_ratio: float = C.GRPS_RATIO
    target_mesh_size: int = C.TARGET_MESH_SIZE_SENTINEL
    metis_ratio: int = C.RATIO_MMG_METIS_SENTINEL
    api_mode: int = C.APIDISTRIB_FACES
    compute_glonum: bool = False
    # remesher switches (forwarded to the wave kernels)
    optim: bool = False
    optimLES: bool = False
    noinsert: bool = False
    noswap: bool = False
    nomove: bool = False
    nosurf: bool = False
    anisosize: bool = False
    opnbdy: bool = False
    # FEM-suitable output by default (MMG5_FEM, API_functions_pmmg.c:413);
    # -nofem turns it off.  Consumed by driver._finish_run's fem pass.
    fem: bool = True
    # unsupported-feature knobs, accepted then rejected at run() like the
    # reference's PMMG_check_inputData (libparmmg.c:69-81): level-set
    # discretization and lagrangian motion are settable but refused
    iso: bool = False
    lag: int = -1
    ls_value: float = 0.0
    mem_budget_mb: int = -1
    # geometry thresholds
    angle_deg: float = C.ANGEDG_DEG
    angle_detection: bool = True
    hmin: float = -1.0      # <0: auto from bounding box
    hmax: float = -1.0
    hsiz: float = -1.0
    hausd: float = C.HAUSD_DEFAULT
    hgrad: float = C.HGRAD_DEFAULT
    hgradreq: float = C.HGRADREQ_DEFAULT
    # local (per-reference) parameters: (elt_type, ref, hmin, hmax, hausd)
    # — the MMG3D_Set_localParameter / parsop surface the reference
    # forwards per group (libparmmg_tools.c:573, API_functions 'nlocal')
    local_params: list = dataclasses.field(default_factory=list)
    # I/O
    fmtout: str = "mesh"
    centralized_output: bool = True
    noout: bool = False
    # resilience (resilience/checkpoint.py): resume the grouped outer
    # loop from the newest PARMMG_CKPT_DIR pass checkpoint (-resume)
    resume: bool = False
    # devices
    n_devices: int = 1

    def angedg(self) -> float:
        """Ridge-detection threshold as a cosine: cos(angle_deg), or the
        'never a ridge' sentinel -1.1 when detection is off (-nr).  The
        single source of truth for initial analysis and mid-adaptation
        re-analysis."""
        import math
        if not self.angle_detection:
            return -1.1
        return math.cos(math.radians(self.angle_deg))

    def set_iparameter(self, key: IParam, val: int) -> None:
        m = {
            IParam.verbose: ("imprim", int),
            IParam.mmgVerbose: ("mmg_imprim", int),
            IParam.mem: ("mem_budget_mb", int),
            IParam.debug: ("debug", bool),
            IParam.mmgDebug: ("mmg_debug", bool),
            IParam.iso: ("iso", bool),
            IParam.lag: ("lag", int),
            IParam.angle: ("angle_detection", bool),
            IParam.optim: ("optim", bool),
            IParam.optimLES: ("optimLES", bool),
            IParam.noinsert: ("noinsert", bool),
            IParam.noswap: ("noswap", bool),
            IParam.nomove: ("nomove", bool),
            IParam.nosurf: ("nosurf", bool),
            IParam.anisosize: ("anisosize", bool),
            IParam.meshSize: ("target_mesh_size", int),
            IParam.metisRatio: ("metis_ratio", int),
            IParam.ifcLayers: ("ifc_layers", int),
            IParam.APImode: ("api_mode", int),
            IParam.globalNum: ("compute_glonum", bool),
            IParam.niter: ("niter", int),
            IParam.nobalancing: ("nobalancing", bool),
            IParam.loadbalancingMode: ("loadbalancing", int),
            IParam.repartitioningMode: ("repartitioning", int),
            IParam.opnbdy: ("opnbdy", bool),
            IParam.fem: ("fem", bool),
        }
        if key not in m:
            raise KeyError(f"unsupported iparam {key}")
        name, cast = m[key]
        setattr(self, name, cast(val))

    def set_dparameter(self, key: DParam, val: float) -> None:
        m = {
            DParam.angleDetection: "angle_deg",
            DParam.hmin: "hmin",
            DParam.hmax: "hmax",
            DParam.hsiz: "hsiz",
            DParam.hausd: "hausd",
            DParam.hgrad: "hgrad",
            DParam.hgradreq: "hgradreq",
            DParam.ls: "ls_value",
            DParam.groupsRatio: "grps_ratio",
        }
        if key not in m:
            raise KeyError(f"unsupported dparam {key}")
        setattr(self, m[key], float(val))


class InputError(ValueError):
    """Unsupported input combination, refused like the reference's
    PMMG_check_inputData (libparmmg.c:55-101)."""


def check_input_data(info: Info, met_is_aniso: bool = False) -> None:
    """Graded input rejection (PMMG_check_inputData, libparmmg.c:69-101):
    lagrangian motion and level-set discretization are unavailable; an
    anisotropic metric is incompatible with -optimLES."""
    if info.lag > -1:
        raise InputError("lagrangian motion option unavailable")
    if info.iso:
        raise InputError("level-set discretization option unavailable")
    if info.optimLES and met_is_aniso:
        raise InputError("-optimLES is not compatible with an anisotropic "
                         "metric")


def resolve_target_mesh_size(info: Info, ne_global: int, n_devices: int)\
        -> int:
    """Group/shard target size with sentinel semantics
    (grpsplit_pmmg.c:1589-1613): negative => default, hard-clamped."""
    t = info.target_mesh_size
    if t < 0:
        t = abs(C.TARGET_MESH_SIZE_SENTINEL)
    return max(C.REDISTR_NELEM_MIN, min(t, max(1, ne_global // n_devices)))
