"""ParMesh: the public mesh-adaptation object (PMMG_ParMesh analogue).

Mirrors the reference's public API surface (libparmmg.h; implementation
API_functions_pmmg.c) in pythonic form: every ``PMMG_Set_*``/``PMMG_Get_*``
pair becomes a ``set_*``/``get_*`` method operating on numpy staging
arrays; the adaptation entries (``PMMG_parmmglib_centralized``
libparmmg.c:1444, ``_distributed`` :1519) become :meth:`run`.

Design note (TPU-first): the reference keeps per-rank groups of Mmg meshes
and remeshes them sequentially; here the staging arrays become ONE flat
device Mesh (core.mesh) adapted by batched waves, and the multi-device
path shards it over a ``jax.sharding.Mesh`` with frozen interfaces
(parallel/).  Groups survive only as shards — the migration quantum — so
the "two-level rank→group decomposition" (SURVEY §2.8) maps to
device→shard.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from ..core import constants as C
from .params import Info, IParam, DParam


def _grow(a: np.ndarray | None, n: int, width: int | None, dtype):
    shape = (n,) if width is None else (n, width)
    out = np.zeros(shape, dtype)
    if a is not None:
        k = min(len(a), n)
        out[:k] = a[:k]
    return out


class ParMesh:
    """Staged mesh + solutions + parameters + (optional) interface comms."""

    def __init__(self, nprocs: int = 1, myrank: int = 0):
        self.info = Info()
        self.nprocs = nprocs
        self.myrank = myrank
        self.comm = None            # plugged by parallel runs
        # mesh staging (1-based API ids are converted to 0-based rows)
        self.np_ = 0
        self.ne_ = 0
        self.nt_ = 0
        self.na_ = 0
        self.nprism_ = 0
        self.nquad_ = 0
        self.vert: np.ndarray | None = None
        self.vref: np.ndarray | None = None
        self.vreq: np.ndarray | None = None     # bool required
        self.vcrn: np.ndarray | None = None     # bool corner
        self.vnormal: np.ndarray | None = None
        self.tetra: np.ndarray | None = None
        self.tref: np.ndarray | None = None
        self.tetra_req: np.ndarray | None = None
        self.tria: np.ndarray | None = None
        self.triaref: np.ndarray | None = None
        self.tria_req: np.ndarray | None = None
        self.edge: np.ndarray | None = None
        self.edgeref: np.ndarray | None = None
        self.edge_ridge: np.ndarray | None = None
        self.edge_req: np.ndarray | None = None
        self.prism: np.ndarray | None = None
        self.quad: np.ndarray | None = None
        # metric / ls / displacement / user fields
        self.met: np.ndarray | None = None      # [np] or [np,6]
        self.met_type: int = 0                  # 0 none,1 scalar,3 tensor
        self.ls: np.ndarray | None = None
        self.disp: np.ndarray | None = None
        self.fields: list[np.ndarray] = []
        self.field_types: list[int] = []
        # distributed-API communicators (Set_ith*Communicator*)
        self.n_node_comm = 0
        self.n_face_comm = 0
        self.node_comms: list[dict] = []
        self.face_comms: list[dict] = []
        # outputs (+ caches, invalidated by run())
        self._out = None                        # core Mesh after run()
        self._out_met = None
        self._out_stats = None
        self._glonum = None
        self._out_vn = None
        self._out_host_cache = None
        self._out_edges_cache = None
        self._out_tria_cache = None

    # ------------------------------------------------------------------
    # sizes
    # ------------------------------------------------------------------
    def set_mesh_size(self, np_: int, ne: int, nprism: int = 0, nt: int = 0,
                      nquad: int = 0, na: int = 0) -> None:
        """PMMG_Set_meshSize (libparmmg.h:348)."""
        self.np_, self.ne_, self.nt_, self.na_ = np_, ne, nt, na
        self.nprism_, self.nquad_ = nprism, nquad
        self.vert = _grow(self.vert, np_, 3, np.float64)
        self.vref = _grow(self.vref, np_, None, np.int32)
        self.vreq = _grow(self.vreq, np_, None, bool)
        self.vcrn = _grow(self.vcrn, np_, None, bool)
        self.tetra = _grow(self.tetra, ne, 4, np.int64)
        self.tref = _grow(self.tref, ne, None, np.int32)
        self.tetra_req = _grow(self.tetra_req, ne, None, bool)
        self.tria = _grow(self.tria, nt, 3, np.int64)
        self.triaref = _grow(self.triaref, nt, None, np.int32)
        self.tria_req = _grow(self.tria_req, nt, None, bool)
        self.edge = _grow(self.edge, na, 2, np.int64)
        self.edgeref = _grow(self.edgeref, na, None, np.int32)
        self.edge_ridge = _grow(self.edge_ridge, na, None, bool)
        self.edge_req = _grow(self.edge_req, na, None, bool)
        self.prism = _grow(self.prism, nprism, 6, np.int64)
        self.prism_ref = _grow(getattr(self, "prism_ref", None), nprism,
                               None, np.int32)
        self.quad = _grow(self.quad, nquad, 4, np.int64)
        self.quad_ref = _grow(getattr(self, "quad_ref", None), nquad,
                              None, np.int32)

    def get_mesh_size(self):
        """PMMG_Get_meshSize: sizes of the CURRENT mesh — after run() the
        adapted output (incl. the rebuilt feature-edge count, so
        ``for i in 1..na: get_edge(i)`` walks the output edges)."""
        if self._out is not None:
            vert, tet, _, _, _ = self._out_host()
            return len(vert), len(tet), self.nprism_, self._out_ntria(), \
                self.nquad_, len(self.get_edges()[0])
        return self.np_, self.ne_, self.nprism_, self.nt_, self.nquad_, \
            self.na_

    # ------------------------------------------------------------------
    # entities (1-based ids, like the reference API)
    # ------------------------------------------------------------------
    def set_vertex(self, x, y, z, ref: int, pos: int) -> None:
        self.vert[pos - 1] = (x, y, z)
        self.vref[pos - 1] = ref

    def set_vertices(self, coords: np.ndarray, refs=None) -> None:
        coords = np.asarray(coords, np.float64).reshape(self.np_, 3)
        self.vert[:] = coords
        if refs is not None:
            self.vref[:] = np.asarray(refs, np.int32).reshape(self.np_)

    def set_tetrahedron(self, v0, v1, v2, v3, ref: int, pos: int) -> None:
        self.tetra[pos - 1] = (v0, v1, v2, v3)
        self.tref[pos - 1] = ref

    def set_tetrahedra(self, tets: np.ndarray, refs=None) -> None:
        self.tetra[:] = np.asarray(tets, np.int64).reshape(self.ne_, 4)
        if refs is not None:
            self.tref[:] = np.asarray(refs, np.int32).reshape(self.ne_)

    def set_triangle(self, v0, v1, v2, ref: int, pos: int) -> None:
        self.tria[pos - 1] = (v0, v1, v2)
        self.triaref[pos - 1] = ref

    def set_triangles(self, tris: np.ndarray, refs=None) -> None:
        self.tria[:] = np.asarray(tris, np.int64).reshape(self.nt_, 3)
        if refs is not None:
            self.triaref[:] = np.asarray(refs, np.int32).reshape(self.nt_)

    def set_edge(self, v0, v1, ref: int, pos: int) -> None:
        self.edge[pos - 1] = (v0, v1)
        self.edgeref[pos - 1] = ref

    def set_edges(self, edges: np.ndarray, refs=None) -> None:
        self.edge[:] = np.asarray(edges, np.int64).reshape(self.na_, 2)
        if refs is not None:
            self.edgeref[:] = np.asarray(refs, np.int32).reshape(self.na_)

    def set_prism(self, vs, ref: int, pos: int) -> None:
        self.prism[pos - 1] = vs
        self.prism_ref[pos - 1] = ref

    def set_quadrilateral(self, vs, ref: int, pos: int) -> None:
        self.quad[pos - 1] = vs
        self.quad_ref[pos - 1] = ref

    def set_corner(self, pos: int) -> None:
        self.vcrn[pos - 1] = True

    def set_required_vertex(self, pos: int) -> None:
        self.vreq[pos - 1] = True

    def set_required_tetrahedron(self, pos: int) -> None:
        self.tetra_req[pos - 1] = True

    def set_required_triangle(self, pos: int) -> None:
        self.tria_req[pos - 1] = True

    def set_required_edge(self, pos: int) -> None:
        self.edge_req[pos - 1] = True

    def set_ridge(self, pos: int) -> None:
        self.edge_ridge[pos - 1] = True

    def set_normal_at_vertex(self, pos: int, nx, ny, nz) -> None:
        if self.vnormal is None:
            self.vnormal = np.zeros((self.np_, 3))
        self.vnormal[pos - 1] = (nx, ny, nz)

    # ------------------------------------------------------------------
    # metric & solutions
    # ------------------------------------------------------------------
    def set_met_size(self, typ: int, np_: int) -> None:
        """typ: 1=scalar, 3=tensor (MMG5_Scalar/MMG5_Tensor)."""
        if np_ != self.np_:
            raise ValueError("metric size must match vertex count")
        self.met_type = typ
        width = None if typ == 1 else 6
        self.met = _grow(None, np_, width, np.float64)

    def set_scalar_met(self, m: float, pos: int) -> None:
        self.met[pos - 1] = m

    def set_scalar_mets(self, m: np.ndarray) -> None:
        self.met[:] = np.asarray(m, np.float64).reshape(self.np_)

    def set_tensor_met(self, m11, m12, m13, m22, m23, m33, pos: int) -> None:
        self.met[pos - 1] = (m11, m12, m13, m22, m23, m33)

    def set_tensor_mets(self, m: np.ndarray) -> None:
        self.met[:] = np.asarray(m, np.float64).reshape(self.np_, 6)

    def set_sols_at_vertices_size(self, nsols: int, types: list[int]) -> None:
        """PMMG_Set_solsAtVerticesSize: declare user fields."""
        self.fields = []
        self.field_types = list(types)
        for t in types:
            width = {1: None, 2: 3, 3: 6}[t]
            self.fields.append(_grow(None, self.np_, width, np.float64))

    def set_ith_sol_in_sols_at_vertices(self, i: int, vals: np.ndarray)\
            -> None:
        f = self.fields[i - 1]
        self.fields[i - 1] = np.asarray(vals, np.float64).reshape(f.shape)

    def get_ith_sol_in_sols_at_vertices(self, i: int) -> np.ndarray:
        return self.fields[i - 1]

    # ------------------------------------------------------------------
    # parameters
    # ------------------------------------------------------------------
    def set_local_parameter(self, typ: int, ref: int, hmin: float,
                            hmax: float, hausd: float) -> None:
        """MMG3D_Set_localParameter analogue: size bounds applying only to
        entities carrying surface reference ``ref``.  ``typ``: 1=triangle
        (the only type the reference's parameter files use for 3D)."""
        self.info.local_params.append(
            (int(typ), int(ref), float(hmin), float(hmax), float(hausd)))

    def set_iparameter(self, key: IParam, val: int) -> None:
        self.info.set_iparameter(key, val)

    def set_dparameter(self, key: DParam, val: float) -> None:
        self.info.set_dparameter(key, val)

    # ------------------------------------------------------------------
    # distributed-API communicators (libparmmg.h Set_ith*Communicator*)
    # ------------------------------------------------------------------
    def set_number_of_node_communicators(self, n: int) -> None:
        self.n_node_comm = n
        self.node_comms = [dict(color_out=-1, local=None, global_=None)
                           for _ in range(n)]

    def set_number_of_face_communicators(self, n: int) -> None:
        self.n_face_comm = n
        self.face_comms = [dict(color_out=-1, local=None, global_=None)
                           for _ in range(n)]

    def set_ith_node_communicator_size(self, i: int, color_out: int,
                                       nitem: int) -> None:
        c = self.node_comms[i]
        c["color_out"] = color_out
        c["local"] = np.zeros(nitem, np.int64)
        c["global_"] = np.zeros(nitem, np.int64)

    def set_ith_face_communicator_size(self, i: int, color_out: int,
                                       nitem: int) -> None:
        c = self.face_comms[i]
        c["color_out"] = color_out
        c["local"] = np.zeros(nitem, np.int64)
        c["global_"] = np.zeros(nitem, np.int64)

    def set_ith_node_communicator_nodes(self, i: int, local_ids, global_ids,
                                        is_not_ordered: bool = True) -> None:
        """Items must appear in the same order on both sides of a rank
        pair; with ``is_not_ordered`` they are sorted by global id (the
        ordering contract, reference API_functions_pmmg.c:1295-1330)."""
        c = self.node_comms[i]
        lo = np.asarray(local_ids, np.int64)
        gl = np.asarray(global_ids, np.int64)
        if is_not_ordered:
            o = np.argsort(gl, kind="stable")
            lo, gl = lo[o], gl[o]
        c["local"], c["global_"] = lo, gl

    def set_ith_face_communicator_faces(self, i: int, local_ids, global_ids,
                                        is_not_ordered: bool = True) -> None:
        c = self.face_comms[i]
        lo = np.asarray(local_ids, np.int64)
        gl = np.asarray(global_ids, np.int64)
        if is_not_ordered:
            o = np.argsort(gl, kind="stable")
            lo, gl = lo[o], gl[o]
        c["local"], c["global_"] = lo, gl

    def get_number_of_node_communicators(self) -> int:
        return self.n_node_comm

    def get_number_of_face_communicators(self) -> int:
        return self.n_face_comm

    def get_ith_node_communicator_size(self, i: int):
        c = self.node_comms[i]
        return c["color_out"], len(c["local"])

    def get_ith_face_communicator_size(self, i: int):
        c = self.face_comms[i]
        return c["color_out"], len(c["local"])

    def get_ith_node_communicator_nodes(self, i: int):
        return self.node_comms[i]["local"]

    def get_ith_face_communicator_faces(self, i: int):
        return self.face_comms[i]["local"]

    def check_set_node_communicators(self) -> bool:
        """Coordinate-based sanity check of the user comms
        (PMMG_Check_Set_NodeCommunicators, chkcomm oracle flavor).
        Single-process form: verify ids are in range and orderings are
        self-consistent (pairwise exchange happens in parallel/comms)."""
        for c in self.node_comms:
            if c["local"] is None:
                return False
            if (np.asarray(c["local"]) < 1).any() or \
                    (np.asarray(c["local"]) > self.np_).any():
                return False
        return True

    def check_set_face_communicators(self) -> bool:
        """Face-comm mirror of the check above
        (PMMG_Check_Set_FaceCommunicators, libparmmg.h:2279-2346 flavor):
        every item set, local triangle ids in range."""
        for c in self.face_comms:
            if c["local"] is None:
                return False
            lo = np.asarray(c["local"])
            ntri = self.nt_ if self.tria is None \
                else max(self.nt_, len(self.tria))
            if (lo < 1).any() or (lo > ntri).any():
                return False
        return True

    def get_node_communicator_owners(self):
        """Owner rank of each node-comm item + its global id
        (PMMG_Get_NodeCommunicator_owners semantics: owner = max rank
        touching the entity, libparmmg.c:962-973).  Returns
        (owners_per_comm, globals_per_comm, nunique, ntot)."""
        owners, globs = [], []
        ntot = 0
        seen = set()
        for c in self.node_comms:
            n = 0 if c["local"] is None else len(c["local"])
            own = np.full(n, max(self.myrank, int(c["color_out"])), np.int64)
            owners.append(own)
            g = (np.zeros(n, np.int64) if c["global_"] is None
                 else np.asarray(c["global_"], np.int64))
            globs.append(g)
            ntot += n
            seen.update(int(x) for x in g)
        return owners, globs, len(seen), ntot

    def get_face_communicator_owners(self):
        """Face-comm mirror of the owners query.  Interface faces are
        shared by exactly 2 ranks; owner = max of the pair."""
        owners, globs = [], []
        ntot = 0
        seen = set()
        for c in self.face_comms:
            n = 0 if c["local"] is None else len(c["local"])
            own = np.full(n, max(self.myrank, int(c["color_out"])), np.int64)
            owners.append(own)
            g = (np.zeros(n, np.int64) if c["global_"] is None
                 else np.asarray(c["global_"], np.int64))
            globs.append(g)
            ntot += n
            seen.update(int(x) for x in g)
        return owners, globs, len(seen), ntot

    # ------------------------------------------------------------------
    # run
    # ------------------------------------------------------------------
    def _build_core_mesh(self):
        """Assemble the staged arrays into a core Mesh + metric."""
        import jax.numpy as jnp
        from ..core.mesh import make_mesh
        from ..ops.analysis import analyze_mesh

        if self.np_ == 0 or self.ne_ == 0:
            raise ValueError("mesh size not set")
        tets0 = self.tetra - 1                     # 1-based -> 0-based
        from ..utils.budget import plan_capacities
        capP, capT = plan_capacities(self.np_, self.ne_,
                                     self.info.mem_budget_mb)
        mesh = make_mesh(self.vert, tets0.astype(np.int32),
                         vref=self.vref, tref=self.tref,
                         capP=capP, capT=capT)
        # geometric analysis first (ridges/corners/normals from dihedrals)
        mesh = analyze_mesh(mesh, angedg=self.info.angedg()).mesh

        # overlay user-required / corner / ridge flags
        vtag = np.array(np.asarray(mesh.vtag), copy=True)
        vtag[: self.np_][self.vreq] |= C.MG_REQ
        vtag[: self.np_][self.vcrn] |= C.MG_CRN
        mesh = dataclasses.replace(mesh, vtag=jnp.asarray(vtag))

        # prism/quadrilateral vertices are frozen (Mmg keeps hybrid
        # elements untouched; their vertices must survive adaptation so
        # the pass-through connectivity stays valid)
        hybrid = np.concatenate([
            (self.prism.reshape(-1) if self.nprism_ else
             np.zeros(0, np.int64)),
            (self.quad.reshape(-1) if self.nquad_ else
             np.zeros(0, np.int64))])
        if len(hybrid):
            hyb = np.zeros(mesh.capP, bool)
            hyb[(hybrid - 1).astype(np.int64)] = True
            vtag = np.array(np.asarray(mesh.vtag), copy=True)
            vtag[hyb] |= C.MG_REQ
            # freeze the tet<->hybrid interface at full depth: any tet
            # face/edge whose vertices are all hybrid vertices lies on a
            # pass-through element; splitting such an edge would hang a
            # midpoint on the prism/quad face (non-conforming result).
            # Same mechanism as the required-tetra freeze below.
            from ..core.constants import IDIR, IARE
            tv = np.asarray(mesh.tet)
            hv = hyb[np.clip(tv, 0, mesh.capP - 1)] \
                & np.asarray(mesh.tmask)[:, None]
            ftag = np.array(np.asarray(mesh.ftag), copy=True)
            etag = np.array(np.asarray(mesh.etag), copy=True)
            for f in range(4):
                ftag[hv[:, IDIR[f]].all(axis=1), f] |= C.MG_REQ
            for e in range(6):
                etag[hv[:, IARE[e]].all(axis=1), e] |= C.MG_REQ
            mesh = dataclasses.replace(
                mesh, vtag=jnp.asarray(vtag), ftag=jnp.asarray(ftag),
                etag=jnp.asarray(etag))

        # required tetrahedra: freeze all their entities (faces, edges,
        # vertices get MG_REQ) so no wave touches them — the contract the
        # remesh kernels honor (same mechanism as the MG_PARBDY freeze)
        if self.tetra_req is not None and self.tetra_req.any():
            req = np.flatnonzero(self.tetra_req)
            ftag = np.array(np.asarray(mesh.ftag), copy=True)
            etag = np.array(np.asarray(mesh.etag), copy=True)
            vtag = np.array(np.asarray(mesh.vtag), copy=True)
            ftag[req] |= C.MG_REQ
            etag[req] |= C.MG_REQ
            tv = np.asarray(mesh.tet)[req]
            vtag[tv.reshape(-1)] |= C.MG_REQ
            mesh = dataclasses.replace(
                mesh, ftag=jnp.asarray(ftag), etag=jnp.asarray(etag),
                vtag=jnp.asarray(vtag))

        # user triangles: push refs onto matching boundary faces
        if self.nt_:
            mesh = self._apply_user_triangles(mesh)
        if self.na_:
            mesh = self._apply_user_edges(mesh)
            # stage the refs for edge-kind local parameters (the core
            # mesh keeps edge TAGS per tet slot, not refs — parsop edge
            # locals resolve against the user list, driver.py
            # apply_local_params typ 3)
            self.info._user_edges = (
                np.asarray(self.edge[: self.na_], np.int64) - 1,
                np.asarray(self.edgeref[: self.na_], np.int32))

        # metric
        cap = mesh.capP
        if self.met is None or self.met_type == 0:
            met = None
        elif self.met_type == 1:
            met = np.zeros(cap)
            met[: self.np_] = self.met
            met[self.np_:] = 1.0
        else:
            met = np.zeros((cap, 6))
            met[: self.np_] = self.met
            met[self.np_:] = np.array([1, 0, 0, 1, 0, 1.0])
        return mesh, (jnp.asarray(met) if met is not None else None)

    def _apply_user_triangles(self, mesh):
        """Match user boundary triangles to tet faces; transfer refs and
        required tags (what Mmg does from the Triangles field).

        With ``info.opnbdy`` (the reference's -opnbdy,
        libparmmg_tools.c usage + the OpnBdy_peninsula/island CI cases,
        cmake/testing/pmmg_tests.cmake:153-165): a user triangle that
        matches an INTERIOR face pair is ingested as an *open boundary*
        surface — BOTH face slots get MG_BDY | MG_OPNBDY (+ ref / REQ),
        so the hanging sheet behaves as a boundary for every wave
        (analysis treats it one-sided, ops.analysis.analyze_mesh).
        Without the flag interior triangles keep the previous behavior
        (refs transferred, no boundary promotion) — the reference
        ignores them unless -opnbdy is given.
        """
        import jax.numpy as jnp
        from ..core.mesh import tet_face_vertices

        fv = np.sort(np.asarray(tet_face_vertices(mesh.tet)), axis=2)
        capT = mesh.capT
        keys = fv.reshape(capT * 4, 3)
        tria = np.sort(self.tria - 1, axis=1)
        # dict-free matching: concatenate + lexsort; a key segment holds
        # 1 or 2 face-slot rows (hull / interior pair) + the tria row
        allk = np.concatenate([keys, tria])
        tag = np.concatenate([np.full(capT * 4, -1),
                              np.arange(len(tria))])
        o = np.lexsort(allk.T[::-1])
        ks, ts = allk[o], tag[o]
        n = len(ks)
        same_next = np.concatenate(
            [(ks[1:] == ks[:-1]).all(axis=1), [False]])
        head = np.concatenate([[True], ~same_next[:-1]])
        seg = np.cumsum(head) - 1
        nseg = seg[-1] + 1 if n else 0
        is_face = ts < 0
        is_tria = ~is_face
        # per segment: the tria id (if any) and the face rows
        tria_of = np.full(nseg, -1, np.int64)
        np.maximum.at(tria_of, seg[is_tria], ts[is_tria])
        nfaces = np.bincount(seg[is_face], minlength=nseg)
        ftag = np.array(np.asarray(mesh.ftag), copy=True).reshape(-1)
        fref = np.array(np.asarray(mesh.fref), copy=True).reshape(-1)
        face_rows = np.where(is_face)[0]
        fseg = seg[face_rows]
        hit = tria_of[fseg] >= 0
        tids = tria_of[fseg][hit]
        slots = o[face_rows[hit]]
        fref[slots] = self.triaref[tids]
        ftag[slots] |= np.where(self.tria_req[tids],
                                np.uint32(C.MG_REQ), np.uint32(0))
        if self.info.opnbdy:
            interior = nfaces[fseg][hit] == 2
            ftag[slots[interior]] |= np.uint32(C.MG_BDY | C.MG_OPNBDY)
        return dataclasses.replace(
            mesh, ftag=jnp.asarray(ftag.reshape(capT, 4)),
            fref=jnp.asarray(fref.reshape(capT, 4)))

    def _apply_user_edges(self, mesh):
        """Transfer user edge refs/ridge/required onto tet edge slots."""
        import jax.numpy as jnp
        from ..core.mesh import tet_edge_vertices

        ev = np.asarray(tet_edge_vertices(mesh.tet))
        capT = mesh.capT
        ev2 = np.sort(ev.reshape(capT * 6, 2), axis=1)
        ue = np.sort(self.edge - 1, axis=1)
        etag = np.array(np.asarray(mesh.etag), copy=True).reshape(-1)
        add = np.where(self.edge_ridge, np.uint32(C.MG_GEO), 0) | \
            np.where(self.edge_req, np.uint32(C.MG_REQ), 0) | \
            np.where(self.edgeref != 0, np.uint32(C.MG_REF), 0)
        key = ev2[:, 0].astype(np.int64) << 32 | ev2[:, 1]
        ukey = ue[:, 0].astype(np.int64) << 32 | ue[:, 1]
        o = np.argsort(ukey)
        pos = np.searchsorted(ukey[o], key)
        pos = np.clip(pos, 0, len(ukey) - 1)
        hit = ukey[o][pos] == key
        etag[hit] |= add[o][pos[hit]].astype(np.uint32)
        return dataclasses.replace(
            mesh, etag=jnp.asarray(etag.reshape(capT, 6)))

    def run(self) -> int:
        """The adaptation entry (PMMG_parmmglib_centralized /_distributed
        depending on staged comms).  Returns PMMG_SUCCESS/…"""
        from ..driver import parmmg_run
        from .params import InputError
        try:
            out, met, stats = parmmg_run(self)
        except InputError as e:
            from ..obs import trace as otrace
            otrace.log(0, f"  ## Error: {e}.",
                       verbose=self.info.imprim, err=True)
            return C.PMMG_STRONGFAILURE
        except MemoryError:
            return C.PMMG_STRONGFAILURE
        self._out, self._out_met, self._out_stats = out, met, stats
        # invalidate all output caches
        self._glonum = None
        self._out_vn = None
        self._out_ridge_nn = None
        self._out_vtag_cache = None
        self._out_host_cache = None
        self._out_edges_cache = None
        self._out_tria_cache = None
        self._out_ftag_cache = None
        # graded failure: the staged output above IS the saveable
        # conforming mesh (failed_handling, libparmmg1.c:974-1011)
        return stats.status

    # ------------------------------------------------------------------
    # output getters
    # ------------------------------------------------------------------
    def _out_host(self):
        from ..core.mesh import mesh_to_host
        if self._out is None:
            raise RuntimeError("run() first")
        # cached: the single-entity getters (get_vertex/tetrahedron/...)
        # are naturally called in a loop over all entities; recomputing
        # the O(N) compaction per call would make that O(N^2)
        if self._out_host_cache is None:
            self._out_host_cache = mesh_to_host(self._out)
        return self._out_host_cache

    def _out_ntria(self) -> int:
        m = self._out
        ftag = np.asarray(m.ftag)
        return int((((ftag & C.MG_BDY) != 0)
                    & np.asarray(m.tmask)[:, None]).sum())

    def get_vertices(self):
        vert, tet, vref, tref, vtag = self._out_host()
        return vert, vref

    def get_tetrahedra(self):
        vert, tet, vref, tref, vtag = self._out_host()
        return tet + 1, tref                       # back to 1-based

    def get_triangles(self):
        """Boundary faces of the adapted mesh as (tria [nt,3] 1-based,
        refs)."""
        tris, refs, _, _ = self._out_triangles()
        return tris, refs

    def get_metric(self):
        if self._out_met is None:
            return None
        m = np.asarray(self._out_met)
        vm = np.asarray(self._out.vmask)
        return m[vm]

    # -- single-entity getters (PMMG_Get_vertex/tetrahedron/triangle/edge,
    #    API_functions_pmmg.c; flags decoded from the MG_* tag bits) -------
    def get_vertex(self, pos: int):
        """(x, y, z, ref, isCorner, isRequired) of output vertex `pos`."""
        vert, _, vref, _, vtag = self._out_host()
        t = int(vtag[pos - 1])
        return (*map(float, vert[pos - 1]), int(vref[pos - 1]),
                bool(t & C.MG_CRN), bool(t & C.MG_REQ))

    def get_tetrahedron(self, pos: int):
        """(v0..v3 1-based, ref, isRequired).

        isRequired is derived from the freeze marker (all 4 faces
        MG_REQ), the mechanism ``set_required_tetrahedron`` uses; a tet
        whose 4 faces were all independently marked required via user
        triangles reads back as required too (the flat mesh carries no
        separate per-tet flag)."""
        _, tet, _, tref, _ = self._out_host()
        # cache the compacted ftag: the natural usage loops over all tets
        # and a fresh device pull per call would be O(N^2)
        if getattr(self, "_out_ftag_cache", None) is None:
            m = self._out
            self._out_ftag_cache = \
                np.asarray(m.ftag)[np.asarray(m.tmask)]
        req = bool((self._out_ftag_cache[pos - 1] & C.MG_REQ).all())
        return tuple(int(v) + 1 for v in tet[pos - 1]) + \
            (int(tref[pos - 1]), req)

    def get_triangle(self, pos: int):
        """(v0..v2 1-based, ref, isRequired) of output boundary tria."""
        tris, refs, req, _ = self._out_triangles()
        return tuple(int(v) for v in tris[pos - 1]) + \
            (int(refs[pos - 1]), bool(req[pos - 1]))

    def get_edges(self):
        """Feature edges (ridge/ref/required) of the adapted mesh:
        (edges [na,2] 1-based, refs, isRidge, isRequired).  The reference
        rebuilds the edge list from xtetra tags at output
        (MMG3D bdryBuild path); here it is one masked unique over the
        per-tet edge tag array.  Edge refs: staged user refs are carried
        only for edges whose endpoints are original staged vertices
        (midpoints inserted on a refined ref-edge lose the numeric ref —
        tracked gap, the MG_REF flag itself is preserved)."""
        if self._out_edges_cache is not None:
            return self._out_edges_cache
        from ..core.mesh import tet_edge_vertices
        m = self._out
        ev = np.asarray(tet_edge_vertices(m.tet)).reshape(-1, 2)
        etag = np.asarray(m.etag).reshape(-1)
        live = np.repeat(np.asarray(m.tmask), 6)
        feat = live & ((etag & (C.MG_GEO | C.MG_REQ | C.MG_REF)) != 0)
        e = np.sort(ev[feat], axis=1)
        tags = etag[feat]
        if len(e) == 0:                     # e.g. -nr on a smooth surface
            self._out_edges_cache = (
                np.zeros((0, 2), np.int64), np.zeros(0, np.int32),
                np.zeros(0, bool), np.zeros(0, bool))
            return self._out_edges_cache
        key = e[:, 0].astype(np.int64) << 32 | e[:, 1]
        o = np.argsort(key, kind="stable")
        key, e, tags = key[o], e[o], tags[o]
        head = np.concatenate([[True], key[1:] != key[:-1]])
        seg = np.cumsum(head) - 1
        # OR tags over duplicate tet-edge slots of the same edge
        utags = np.zeros(int(head.sum()), np.uint32)
        np.bitwise_or.at(utags, seg, tags.astype(np.uint32))
        e = e[head]
        vmask = np.asarray(m.vmask)
        new_id = np.cumsum(vmask) - 1
        # recover staged user edge refs where both endpoints are original
        # staged vertices (1-based output ids of staged vertex i = its
        # compacted position; staged vertices occupy the leading rows)
        refs = np.zeros(len(e), np.int32)
        if self.na_ and len(e):
            out_e = new_id[e]                       # 0-based output ids
            orig = (e < self.np_).all(axis=1)       # original-vertex rows
            ue = np.sort(self.edge - 1, axis=1)
            ukey = ue[:, 0].astype(np.int64) << 32 | ue[:, 1]
            # e rows are already (min,max)-sorted from construction
            ekey = e[:, 0].astype(np.int64) << 32 | e[:, 1]
            o = np.argsort(ukey)
            pos = np.clip(np.searchsorted(ukey[o], ekey), 0, len(ukey) - 1)
            hit = orig & (ukey[o][pos] == ekey)
            refs[hit] = self.edgeref[o][pos[hit]]
        self._out_edges_cache = (
            new_id[e] + 1, refs,
            (utags & C.MG_GEO) != 0, (utags & C.MG_REQ) != 0)
        return self._out_edges_cache

    def get_edge(self, pos: int):
        """(v0, v1 1-based, ref, isRidge, isRequired)."""
        e, r, rid, req = self.get_edges()
        return (int(e[pos - 1, 0]), int(e[pos - 1, 1]), int(r[pos - 1]),
                bool(rid[pos - 1]), bool(req[pos - 1]))

    def _input_vertex_remap(self):
        """Output 1-based id of each staged input vertex (vertices are
        frozen only if tagged; callers use this for pass-through hybrid
        elements whose vertices ARE frozen)."""
        if self._out is None:
            return None
        vm = np.asarray(self._out.vmask)
        new_id = np.cumsum(vm) - 1
        return new_id[: self.np_] + 1

    def get_prisms(self):
        """Prisms pass through adaptation untouched (their vertices are
        frozen at run(); PMMG_Get_prisms).  Connectivity is renumbered to
        the output vertex ids."""
        if self._out is not None and self.nprism_:
            rm = self._input_vertex_remap()
            return rm[self.prism - 1], self.prism_ref
        return self.prism, self.prism_ref

    def get_quadrilaterals(self):
        if self._out is not None and self.nquad_:
            rm = self._input_vertex_remap()
            return rm[self.quad - 1], self.quad_ref
        return self.quad, self.quad_ref

    def get_normals(self):
        """Unit outward normals at output boundary vertices [np,3]
        (PMMG_Get_normalAtVertex source data; zero off-surface)."""
        if getattr(self, "_out_vn", None) is None:
            from ..ops.analysis import analyze_mesh
            res = analyze_mesh(self._out)
            self._out_vn = np.asarray(res.vnormal)[np.asarray(
                self._out.vmask)]
        return self._out_vn

    def get_normal_at_vertex(self, pos: int):
        """(nx, ny, nz) at output vertex ``pos`` (1-based).

        At RIDGE points the averaged normal is geometrically meaningless
        (the reference keeps two per-side normals in the xPoint,
        analys_pmmg.c:199-1171, and exposes n1); here likewise the
        first-side normal is returned — use
        :meth:`get_ridge_normals_at_vertex` for both sides."""
        from ..core.constants import MG_GEO, MG_REF, MG_CRN, MG_NOM
        if getattr(self, "_out_vtag_cache", None) is None:
            self._out_vtag_cache = np.asarray(self._out.vtag)[
                np.asarray(self._out.vmask)]
        t = int(self._out_vtag_cache[pos - 1])
        if (t & (MG_GEO | MG_REF)) and not (t & (MG_CRN | MG_NOM)):
            n1, _ = self.get_ridge_normals_at_vertex(pos)
            return n1
        n = self.get_normals()[pos - 1]
        return float(n[0]), float(n[1]), float(n[2])

    def get_ridge_normals_at_vertex(self, pos: int):
        """Both per-side normals (n1, n2) at a ridge vertex (the xPoint
        n1/n2 of the reference); zeros at non-ridge points."""
        if getattr(self, "_out_ridge_nn", None) is None:
            from ..ops.analysis import ridge_vertex_normals
            n1, n2 = ridge_vertex_normals(self._out)
            vm = np.asarray(self._out.vmask)
            self._out_ridge_nn = (np.asarray(n1)[vm], np.asarray(n2)[vm])
        n1, n2 = self._out_ridge_nn
        return (tuple(float(x) for x in n1[pos - 1]),
                tuple(float(x) for x in n2[pos - 1]))

    def get_scalar_met(self, pos: int) -> float:
        return float(self.get_metric()[pos - 1])

    def get_scalar_mets(self) -> np.ndarray:
        return self.get_metric()

    def get_tensor_met(self, pos: int):
        return tuple(float(x) for x in self.get_metric()[pos - 1])

    def get_tensor_mets(self) -> np.ndarray:
        return self.get_metric()

    def _out_triangles(self):
        """(tris 1-based, refs, isRequired, tet_of_tria) of output
        boundary faces; ``tet_of_tria`` is the 0-based *compacted* id of
        the tet each boundary face belongs to (used e.g. to assign
        triangles to the shard that owns the adjacent tet)."""
        if self._out_tria_cache is not None:
            return self._out_tria_cache
        from ..core.mesh import tet_face_vertices
        m = self._out
        vm = np.asarray(m.vmask)
        new_id = np.cumsum(vm) - 1
        tm = np.asarray(m.tmask)
        tet_new = np.cumsum(tm) - 1
        fv = np.asarray(tet_face_vertices(m.tet))
        ftag = np.asarray(m.ftag)
        sel = ((ftag & C.MG_BDY) != 0) & tm[:, None]
        rows = np.nonzero(sel)[0]
        self._out_tria_cache = (
            new_id[fv[sel]] + 1, np.asarray(m.fref)[sel],
            (ftag[sel] & C.MG_REQ) != 0, tet_new[rows])
        return self._out_tria_cache

    def get_vertex_glonum(self, pos: int) -> int:
        if self._glonum is None:
            self._compute_glonum()
        return int(self._glonum[pos - 1])

    def get_vertices_glonum(self) -> np.ndarray:
        if self._glonum is None:
            self._compute_glonum()
        return self._glonum

    def _compute_glonum(self):
        """Output global numbering (single-process: identity; multi-shard
        handled by parallel.comms.global_node_numbering)."""
        vert, _, _, _, _ = self._out_host()
        self._glonum = np.arange(1, len(vert) + 1, dtype=np.int64)

    def get_triangle_glonum(self, pos: int) -> int:
        """PMMG_Get_triangleGloNum: global id of an output boundary tria
        (single-process: identity; the two-phase owned/parallel numbering
        of the reference collapses, libparmmg.c:464)."""
        return pos

    def get_triangles_glonum(self) -> np.ndarray:
        return np.arange(1, self._out_ntria() + 1, dtype=np.int64)

    def print_communicator(self, path: str) -> None:
        """PMMG_printCommunicator (libparmmg.h:2554): dump the staged
        node/face communicators to a text file for debugging."""
        with open(path, "w") as f:
            f.write(f"rank {self.myrank} / {self.nprocs}\n")
            f.write(f"node communicators: {self.n_node_comm}\n")
            for i, c in enumerate(self.node_comms):
                n = 0 if c["local"] is None else len(c["local"])
                f.write(f"  comm {i}: color_out {c['color_out']} "
                        f"nitem {n}\n")
                if n:
                    for lo, gl in zip(c["local"], c["global_"]):
                        f.write(f"    {int(lo)} {int(gl)}\n")
            f.write(f"face communicators: {self.n_face_comm}\n")
            for i, c in enumerate(self.face_comms):
                n = 0 if c["local"] is None else len(c["local"])
                f.write(f"  comm {i}: color_out {c['color_out']} "
                        f"nitem {n}\n")
                if n:
                    for lo, gl in zip(c["local"], c["global_"]):
                        f.write(f"    {int(lo)} {int(gl)}\n")

    @property
    def stats(self):
        return self._out_stats
